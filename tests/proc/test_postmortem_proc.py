"""Flight recorder + t4j-postmortem over a real launcher job
(docs/observability.md "flight recorder").

An 8-rank ``--telemetry DIR`` job whose rank 3 SIGKILLs itself
MID-COLLECTIVE (a helper thread fires while the rank is blocked inside
an allreduce) must leave, from the persisted files alone:

* a crash-consistent ``rank3-<boot>.t4jflight`` file (no drained
  ``rank3.t4j.json`` — the kill skipped every exit path) whose header
  is unfinalized and whose mmap'd ring still holds the open allreduce;
* survivors' drained files carrying their link_break/link_dead view;
* a ``t4j-postmortem`` verdict naming the killed rank, its in-flight
  op and the affected links — and the launcher's own first-failure
  report must print the flight-recorder tail plus the postmortem
  summary.

The ctypes twin (plain + ASan) is tools/postmortem_smoke.py, the
ci_smoke ``postmortem`` lane.
"""

import pathlib

import pytest

try:
    import mpi4jax_tpu  # noqa: F401 -- probe only
except Exception as e:  # pragma: no cover - old-jax containers
    pytest.skip(f"mpi4jax_tpu unavailable: {e}", allow_module_level=True)

from mpi4jax_tpu.telemetry import dump, postmortem, schema

from tests.proc.test_proc_backend import run_workers

pytestmark = pytest.mark.fault

REPO = pathlib.Path(__file__).resolve().parent.parent.parent

VICTIM = 3

WORKER = f"""
import os, signal, threading, time
import numpy as np
import jax

jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp

import mpi4jax_tpu as m

comm = m.get_default_comm()
assert comm.backend == "proc", comm.backend
n, rank = comm.size, comm.rank()

tok = m.create_token()
x = jnp.arange(256 * 1024, dtype=jnp.float32) + rank  # 1 MB payload
y = x
try:
    for it in range(8):
        if rank == {VICTIM} and it == 4:
            # hard death MID-collective: the timer fires while this
            # rank is blocked inside the allreduce below — no drain,
            # no atexit, no finalize
            threading.Thread(
                target=lambda: (time.sleep(0.05),
                                os.kill(os.getpid(), signal.SIGKILL)),
                daemon=True,
            ).start()
        y, tok = m.allreduce(y, m.SUM, comm=comm, token=tok)
        np.asarray(y)
except Exception as e:
    # survivors: the dead peer surfaces as a contextual bridge error
    print("WORKER-SURVIVOR-ABORT", rank, type(e).__name__, flush=True)
    raise SystemExit(17)
print("WORKER-UNEXPECTED-COMPLETE", rank, flush=True)
"""

ENV = {
    "T4J_NO_SHM": "1",
    "T4J_RING_MIN_BYTES": "0",
    "T4J_SEG_BYTES": "65536",
    "T4J_OP_TIMEOUT": "30",
    "T4J_RETRY_MAX": "2",
    "T4J_BACKOFF_BASE": "0.05",
    "T4J_BACKOFF_MAX": "0.2",
}


def test_sigkilled_rank_named_from_persisted_files(tmp_path):
    tel_dir = tmp_path / "tel"
    proc = run_workers(
        WORKER, nprocs=8, env=ENV, timeout=300, expect_fail=True,
        launch_args=("--telemetry", str(tel_dir)),
    )
    assert "WORKER-UNEXPECTED-COMPLETE" not in proc.stdout

    # the kill skipped every cooperative exit path...
    assert not (tel_dir / dump.rank_file_name(VICTIM)).exists()
    flights = sorted(tel_dir.glob(f"rank{VICTIM}-*.t4jflight"))
    assert flights, sorted(p.name for p in tel_dir.iterdir())
    fobj = schema.read_flight_file(flights[-1])
    assert not fobj["finalized"]
    assert fobj["events"], "flight ring recovered zero events"
    assert fobj["heartbeat_count"] > 0

    # ...yet the postmortem names the rank, its op and its links from
    # the files alone (stale threshold 0: the job ended seconds ago,
    # and a launcher-reaped process cannot still be beating)
    report = postmortem.analyze_dir(tel_dir, stale_s=0.0)
    assert report["first_failing_rank"] == VICTIM
    assert report["verdicts"][str(VICTIM)] == "dead"
    vic = report["ranks"][str(VICTIM)]
    open_ops = [o["op"] for o in vic["inflight"]["ops"]]
    assert "allreduce" in open_ops, open_ops
    assert vic["affected_links"], "no affected links recovered"
    assert report["peer_views"], "no surviving peer view"
    assert any(
        any(row["kind"] in ("link_break", "link_dead") for row in rows)
        for rows in report["peer_views"].values()
    )
    for r in range(8):
        if r != VICTIM:
            assert report["verdicts"][str(r)] == "drained", (
                r, report["verdicts"])

    # the launcher's first-failure report used the flight fallback
    # (the victim had no drained file) and printed the postmortem
    assert "flight recorder" in proc.stderr, proc.stderr[-2000:]
    assert f"postmortem: first failure: rank {VICTIM}" in proc.stderr, (
        proc.stderr[-2000:])


def test_clean_job_finalizes_flight_files(tmp_path):
    tel_dir = tmp_path / "tel"
    proc = run_workers(
        """
import numpy as np
import jax

jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp

import mpi4jax_tpu as m

comm = m.get_default_comm()
y, _ = m.allreduce(jnp.ones(1024, jnp.float32), m.SUM, comm=comm)
np.asarray(y)
print("WORKER-OK", comm.rank(), flush=True)
""",
        nprocs=2, env=ENV, launch_args=("--telemetry", str(tel_dir)),
    )
    assert proc.stdout.count("WORKER-OK") == 2
    flights = sorted(tel_dir.glob(schema.FLIGHT_FILE_GLOB))
    assert len(flights) == 2, sorted(p.name for p in tel_dir.iterdir())
    for f in flights:
        fobj = schema.read_flight_file(f)
        assert fobj["finalized"], f
    # zero false deaths on a healthy job
    report = postmortem.analyze_dir(tel_dir, stale_s=0.0)
    assert report["dead_ranks"] == []
    assert report["first_failing_rank"] is None
    # the drained rank files pair themselves with their flight file
    for rank in (0, 1):
        obj = schema.load_rank_file(tel_dir / dump.rank_file_name(rank))
        assert obj["flight"].get("path", "").endswith(".t4jflight")
