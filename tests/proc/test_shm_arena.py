"""Same-host shm arena (native/src/shm.cc): the DCN bridge's intra-host
transport.  Verifies (a) the full collective battery is correct through
the arena at 8 ranks, (b) payloads larger than the slot capacity stream
piece-wise, (c) the TCP frame algorithms still work when the arena is
disabled (the cross-host fallback), and (d) both transports agree.

Reference analog: libmpi's shm BTL serves the reference's intra-host
ranks transparently (mpi_xla_bridge.pyx:149-167); `mpirun -np N` on one
machine exercises it the same way this file drives the launcher.
"""

import pytest

from tests.proc.test_proc_backend import run_workers, PREAMBLE

_BATTERY = """
import os
x = jnp.arange(24.0).reshape(4, 6) + 100 * rank

y, tok = m.allreduce(x, m.SUM, comm=comm)
want = sum(np.arange(24.0).reshape(4, 6) + 100 * r for r in range(size))
assert np.allclose(np.asarray(y), want), "allreduce"

mx, tok = m.allreduce(x, m.MAX, comm=comm, token=tok)
assert np.allclose(np.asarray(mx),
                   np.arange(24.0).reshape(4, 6) + 100 * (size - 1)), "max"

b, tok = m.bcast(x if rank == 2 else jnp.zeros_like(x), 2, comm=comm, token=tok)
assert np.allclose(np.asarray(b),
                   np.arange(24.0).reshape(4, 6) + 200), "bcast"

g, tok = m.allgather(jnp.array([float(rank)]), comm=comm, token=tok)
assert np.allclose(np.asarray(g).ravel(), np.arange(size)), "allgather"

r, tok = m.reduce(x, m.SUM, 1, comm=comm, token=tok)
if rank == 1:
    assert np.allclose(np.asarray(r), want), "reduce root"
else:
    assert np.allclose(np.asarray(r), x), "reduce off-root"

s, tok = m.scan(jnp.array([float(rank + 1)]), m.SUM, comm=comm, token=tok)
assert np.allclose(np.asarray(s), sum(range(1, rank + 2))), "scan"

a2, tok = m.alltoall(jnp.arange(float(size)) + 100 * rank, comm=comm, token=tok)
assert np.allclose(np.asarray(a2), 100 * np.arange(size) + rank), "alltoall"

if rank == 0:
    payload = jnp.arange(float(size * 3)).reshape(size, 3)
else:
    payload = jnp.zeros((3,))
sc, tok = m.scatter(payload, 0, comm=comm, token=tok)
assert np.allclose(np.asarray(sc), [3 * rank, 3 * rank + 1, 3 * rank + 2]), "scatter"

ga, tok = m.gather(jnp.full((2,), float(rank)), 0, comm=comm, token=tok)
if rank == 0:
    assert np.allclose(np.asarray(ga), np.repeat(np.arange(size), 2).reshape(size, 2)), "gather"

tok = m.barrier(comm=comm, token=tok)

# sub-communicator (own arena, distinct ctx): evens and odds
sub = comm.split(color=lambda r: r % 2, key=lambda r: r)
z, _ = m.allreduce(jnp.array([float(rank)]), m.SUM, comm=sub)
members = [r for r in range(size) if r % 2 == rank % 2]
assert np.allclose(np.asarray(z), float(sum(members))), "split allreduce"

print(f"WORKER_OK {rank}", flush=True)
"""


def _check(proc, n):
    for r in range(n):
        assert f"WORKER_OK {r}" in proc.stdout, (proc.stdout, proc.stderr)


def test_arena_battery_8_ranks():
    _check(run_workers(PREAMBLE + _BATTERY, nprocs=8), 8)


def test_arena_multi_piece_streaming():
    # payloads >> slot capacity: T4J_SHM_SLOT_MB=1 forces piece-wise
    # streaming (3 MB payload -> 3+ pieces per collective)
    proc = run_workers(
        PREAMBLE
        + """
n = 750_000  # 3 MB of f32
x = jnp.arange(float(n)) * (rank + 1)
y, tok = m.allreduce(x, m.SUM, comm=comm)
total = sum(range(1, size + 1))
assert np.allclose(np.asarray(y), np.arange(float(n)) * total), "large allreduce"
b, tok = m.bcast(x if rank == 0 else jnp.zeros(n), 0, comm=comm, token=tok)
assert np.allclose(np.asarray(b), np.arange(float(n))), "large bcast"
g, tok = m.allgather(x[:200_000], comm=comm, token=tok)
assert np.allclose(
    np.asarray(g),
    np.stack([np.arange(200_000.0) * (r + 1) for r in range(size)]),
), "large allgather"
print(f"WORKER_OK {rank}", flush=True)
""",
        nprocs=3,
        env={"T4J_SHM_SLOT_MB": "1"},
    )
    _check(proc, 3)


def test_tcp_fallback_agrees():
    # T4J_NO_SHM=1 must route through the TCP frame algorithms (the
    # cross-host path) and produce identical results
    proc = run_workers(
        PREAMBLE + _BATTERY, nprocs=4, env={"T4J_NO_SHM": "1"}
    )
    _check(proc, 4)


def test_arena_dtypes():
    # the arena folds raw bytes via the shared combine table: cover the
    # non-f32 dtypes incl. the half types that reduce via float
    proc = run_workers(
        PREAMBLE
        + """
for dt, op, want in [
    ("float64", m.SUM, float(sum(range(1, size + 1)))),
    ("int32", m.PROD, float(np.prod(np.arange(1, size + 1)))),
    ("int64", m.MAX, float(size)),
    ("bfloat16", m.SUM, float(sum(range(1, size + 1)))),
    ("float16", m.MIN, 1.0),
]:
    v = (jnp.ones((17,)) * (rank + 1)).astype(dt)
    y, _ = m.allreduce(v, op, comm=comm)
    assert np.allclose(np.asarray(y).astype("float64"), want), (dt, np.asarray(y))
b = jnp.arange(8) % 2 == 0 if rank == 0 else jnp.zeros(8, bool)
y, _ = m.allreduce(b, m.LOR, comm=comm)
assert np.array_equal(np.asarray(y), np.arange(8) % 2 == 0), "bool lor"
print(f"WORKER_OK {rank}", flush=True)
""",
        nprocs=4,
    )
    _check(proc, 4)


def test_p2p_pipes_and_tcp_fallback_agree():
    # p2p frames ride the same-host shm byte pipes (round 4); with
    # T4J_NO_SHM=1 the same traffic rides TCP loopback.  Both must
    # deliver identical matching semantics (tags, ANY_SOURCE, order).
    body = (
        PREAMBLE
        + """
tok = m.create_token()
x = jnp.full((5,), float(rank + 1))
tok = m.send(x, (rank + 1) % size, tag=7, comm=comm, token=tok)
st = m.Status()
y, tok = m.recv(x, (rank - 1) % size, tag=7, comm=comm, token=tok, status=st)
assert np.allclose(np.asarray(y), float((rank - 1) % size + 1))
assert int(np.asarray(st.source)) == (rank - 1) % size

# ordering: two sends same pair, distinct tags, wildcard recvs must
# deliver in posting order (MPI non-overtaking)
tok = m.send(x * 10, (rank + 1) % size, tag=1, comm=comm, token=tok)
tok = m.send(x * 20, (rank + 1) % size, tag=2, comm=comm, token=tok)
a, tok = m.recv(x, m.ANY_SOURCE, m.ANY_TAG, comm=comm, token=tok)
b, tok = m.recv(x, m.ANY_SOURCE, m.ANY_TAG, comm=comm, token=tok)
left = (rank - 1) % size + 1
assert np.allclose(np.asarray(a), left * 10.0), np.asarray(a)
assert np.allclose(np.asarray(b), left * 20.0), np.asarray(b)

# a 6MB frame exceeds the 4MB pipe buffer: must stream through in
# chunks (the pipe is a blocking byte FIFO, not a frame ring)
big = jnp.arange(1_500_000, dtype=jnp.float32) * (rank + 1)
tok = m.send(big, (rank + 1) % size, tag=9, comm=comm, token=tok)
z, tok = m.recv(big, (rank - 1) % size, tag=9, comm=comm, token=tok)
assert np.allclose(np.asarray(z), np.arange(1_500_000, dtype=np.float32) * ((rank - 1) % size + 1))
print(f"WORKER_OK {rank}", flush=True)
"""
    )
    _check(run_workers(body, nprocs=3), 3)
    _check(run_workers(body, nprocs=3, env={"T4J_NO_SHM": "1"}), 3)


def test_divergent_env_cannot_split_transport():
    """A rank with T4J_NO_SHM=1 while its peers have shm enabled (the
    hand-launched divergent-env case) must drop the WHOLE group to TCP
    consistently — the disabled bit rides the host fingerprint, so an
    enabled rank never classifies a disabled one as shm-eligible.
    Before that fix this scenario deadlocked: the disabled rank went
    straight to the TCP collective while peers waited in the shm
    agreement rounds."""
    proc = run_workers(
        """
import os
if os.environ["T4J_RANK"] == "1":
    os.environ["T4J_NO_SHM"] = "1"  # BEFORE the bridge initialises
"""
        + PREAMBLE
        + """
x = jnp.arange(12.0) * (rank + 1)
y, tok = m.allreduce(x, m.SUM, comm=comm)
assert np.allclose(np.asarray(y), np.arange(12.0) * sum(range(1, size + 1)))
tok = m.send(x, (rank + 1) % size, tag=3, comm=comm, token=tok)
z, tok = m.recv(x, (rank - 1) % size, tag=3, comm=comm, token=tok)
assert np.allclose(np.asarray(z), np.arange(12.0) * ((rank - 1) % size + 1))
print(f"WORKER_OK {rank}", flush=True)
""",
        nprocs=3,
        timeout=120,
    )
    _check(proc, 3)


def test_divergent_slot_size_drops_group_to_tcp():
    """Mismatched T4J_SHM_SLOT_MB across ranks makes the arena attach
    fail its cap validation; the agreement round must then drop every
    member to the TCP algorithms together (no hang, right answers)."""
    proc = run_workers(
        """
import os
if os.environ["T4J_RANK"] == "0":
    os.environ["T4J_SHM_SLOT_MB"] = "2"  # others keep the default 8
"""
        + PREAMBLE
        + """
x = jnp.arange(10.0) + 100 * rank
y, tok = m.allreduce(x, m.SUM, comm=comm)
want = sum(np.arange(10.0) + 100 * r for r in range(size))
assert np.allclose(np.asarray(y), want)
print(f"WORKER_OK {rank}", flush=True)
""",
        nprocs=3,
        timeout=120,
    )
    _check(proc, 3)
