"""The north-star parity check: the reference's own example program,
byte-for-byte unmodified, runs against this framework through the
import shims (mpi4py/mpi4jax -> mpi4jax_tpu.compat) under the process
launcher.

Skipped when the reference checkout isn't mounted (CI without it)."""

import os
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent.parent
REFERENCE_EXAMPLE = pathlib.Path("/root/reference/examples/shallow_water.py")


@pytest.mark.skipif(
    not REFERENCE_EXAMPLE.exists(),
    reason="reference checkout not available",
)
def test_unmodified_reference_example_runs():
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)
    res = subprocess.run(
        [
            sys.executable,
            "-m",
            "mpi4jax_tpu.launch",
            "--shims",
            "-np",
            "2",
            str(REFERENCE_EXAMPLE),
            "--benchmark",
        ],
        capture_output=True,
        text=True,
        env=env,
        cwd=str(REPO),
        timeout=480,
    )
    assert res.returncode == 0, (res.stdout[-2000:], res.stderr[-2000:])
    # the example prints its own wall-clock on success
    assert "Solution took" in res.stdout + res.stderr, res.stdout[-2000:]
