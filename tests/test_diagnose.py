"""Diagnosis pure core (telemetry/diagnose.py + telemetry/exporter.py
+ ops/step.py): critical-path attribution over synthetic multi-rank
traces with KNOWN stragglers, truncated dead-rank spans, step-marker
balance, caller-blocked vs engine-lane overlap, the A/B diff, the
plane audit, snapshot building/validation, and the step-marker state
machine.

All of it is import-free of jax (stdlib only), so these tests run on
every container — including old-jax ones where ``import mpi4jax_tpu``
raises at the version gate — via the same package-stub loader as
tests/test_telemetry.py.  The native half (the delay-injected 8-rank
job) is covered by tests/proc/test_diagnose_proc.py and the ci_smoke
``diagnose`` lane (tools/diagnose_smoke.py).
"""

import importlib
import importlib.util
import json
import pathlib
import sys
import types

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent


def _load_telemetry():
    try:
        import mpi4jax_tpu.telemetry as tele

        return tele
    except Exception:
        # stub the parent just long enough to import the jax-free
        # subpackage, then REMOVE it (see tests/test_telemetry.py for
        # why a lingering stub would poison later-collected modules)
        stubbed = "mpi4jax_tpu" not in sys.modules
        if stubbed:
            stub = types.ModuleType("mpi4jax_tpu")
            stub.__path__ = [str(REPO / "mpi4jax_tpu")]
            sys.modules["mpi4jax_tpu"] = stub
        try:
            return importlib.import_module("mpi4jax_tpu.telemetry")
        finally:
            if stubbed:
                sys.modules.pop("mpi4jax_tpu", None)


def _load_step_module():
    """ops/step.py is jax-free but lives under ops/ whose __init__ is
    not: load it as a standalone module under its real name."""
    name = "mpi4jax_tpu.ops.step"
    if name in sys.modules:
        return sys.modules[name]
    try:
        from mpi4jax_tpu.ops import step as step_mod

        return step_mod
    except Exception:
        spec = importlib.util.spec_from_file_location(
            name, REPO / "mpi4jax_tpu/ops/step.py"
        )
        mod = importlib.util.module_from_spec(spec)
        sys.modules[name] = mod
        spec.loader.exec_module(mod)
        return mod


tele = _load_telemetry()
schema = tele.schema
diagnose = importlib.import_module(tele.__name__ + ".diagnose")
exporter = importlib.import_module(tele.__name__ + ".exporter")
dump = importlib.import_module(tele.__name__ + ".dump")
trace = importlib.import_module(tele.__name__ + ".trace")
recorder = importlib.import_module(tele.__name__ + ".recorder")
step_mod = _load_step_module()

MS = 1_000_000  # ns per ms
ANCHOR = 5_000_000

STEP = schema.STEP_KIND
WAIT = schema.WAIT_KIND
ALLREDUCE = schema.KIND_IDS["allreduce"]
FRAME_TX = schema.KIND_IDS["frame_tx"]
OP_PROGRESS = schema.KIND_IDS["op_progress"]
OP_COMPLETE = schema.KIND_IDS["op_complete"]
B, E = schema.PHASE_BEGIN, schema.PHASE_END


def ev(t_ms, kind, phase, plane=0, comm=0, peer=-1, lane=5, nbytes=0):
    return schema.Event(ANCHOR + int(t_ms * MS), kind, phase, plane,
                        comm, peer, lane, nbytes)


def rank_obj(rank, events, world=3, py_events=None, tuning=None,
             topology=None):
    return dump.build_rank_obj(
        rank=rank, world=world, anchor_mono_ns=ANCHOR,
        anchor_unix_ns=1_700_000_000_000, mode="trace",
        events=events, py_events=py_events or [],
        link_stats={"per_peer": {}}, topology=topology or {},
        tuning=tuning or {}, job="diagjob",
    )


def compute_straggler_events(rank, steps=4, slow_rank=1,
                             slow_compute_ms=50.0, fast_compute_ms=5.0,
                             op_ms=10.0):
    """Marked steps where ``slow_rank`` sits in compute before its op:
    the known critical path is that rank's compute phase."""
    compute = slow_compute_ms if rank == slow_rank else fast_compute_ms
    out = []
    for k in range(steps):
        base = k * 100.0
        out.append(ev(base, STEP, B, nbytes=k))
        out.append(ev(base + compute, ALLREDUCE, B, plane=2,
                      nbytes=1 << 20))
        out.append(ev(base + compute + op_ms, ALLREDUCE, E, plane=2,
                      nbytes=1 << 20))
        out.append(ev(base + compute + op_ms + 0.5, STEP, E, nbytes=k))
    return out


def wire_straggler_events(rank, steps=4, slow_rank=1, stall_ms=30.0):
    """Uniform compute, but ``slow_rank`` sends its outbound frames
    ``stall_ms`` after its op began (the injected-delay / slow-NIC
    signature): the known critical phase is wire."""
    out = []
    for k in range(steps):
        base = k * 100.0
        out.append(ev(base, STEP, B, nbytes=k))
        out.append(ev(base + 5.0, ALLREDUCE, B, plane=2, nbytes=1 << 20))
        tx = 5.0 + (stall_ms if rank == slow_rank else 0.5)
        out.append(ev(base + tx, FRAME_TX, 0, peer=(rank + 1) % 3))
        out.append(ev(base + tx + 5.0, ALLREDUCE, E, plane=2,
                      nbytes=1 << 20))
        out.append(ev(base + tx + 5.5, STEP, E, nbytes=k))
    return out


class TestCriticalPath:
    def test_compute_straggler_fingered_every_step(self):
        views = [
            diagnose.rank_view_from_obj(
                rank_obj(r, compute_straggler_events(r))
            )
            for r in range(3)
        ]
        report = diagnose.diagnose(views)
        assert report["n_steps"] == 4
        for s in report["steps"]:
            assert s["critical_rank"] == 1, s
            assert s["critical_phase"] == "compute", s
        assert report["summary"]["straggler"] == 1
        assert report["summary"]["straggler_share"] == 1.0
        assert report["stragglers"] == {"1": 4}

    def test_wire_straggler_attributed_to_wire_and_link(self):
        views = [
            diagnose.rank_view_from_obj(
                rank_obj(r, wire_straggler_events(r))
            )
            for r in range(3)
        ]
        report = diagnose.diagnose(views)
        for s in report["steps"]:
            assert s["critical_rank"] == 1, s
            assert s["critical_phase"] == "wire", s
        # the pacing stall is tied to the link and the op it stalled
        links = [link for link in report["links"]
                 if link["rank"] == 1 and link["pacing_ms"] > 0]
        assert links, report["links"]
        assert links[0]["peer"] == 2
        assert links[0]["cause"] == "pacing"
        assert links[0]["stalled_ops"][0]["op"] == "allreduce"
        # no phantom stalls on the inheriting ranks: their tx follows
        # their rx immediately, so local send latency stays small
        assert not [link for link in report["links"]
                    if link["rank"] != 1 and link["pacing_ms"] > 0]

    def test_balanced_job_names_no_straggler(self):
        views = [
            diagnose.rank_view_from_obj(
                rank_obj(r, compute_straggler_events(r, slow_rank=-1))
            )
            for r in range(3)
        ]
        report = diagnose.diagnose(views)
        for s in report["steps"]:
            assert s["critical_rank"] is None, s
            assert s["critical_phase"] == "balanced"
        assert report["summary"]["straggler"] is None

    def test_entry_skew_histogram_buckets(self):
        views = [
            diagnose.rank_view_from_obj(
                rank_obj(r, compute_straggler_events(r))
            )
            for r in range(3)
        ]
        report = diagnose.diagnose(views)
        hist = report["entry_skew_hist_ms"]
        # lockstep begins: every step lands in the smallest bucket
        assert sum(hist.values()) == 4
        assert hist["<1.0"] == 4


class TestTruncatedAndMarkers:
    def test_dead_rank_step_closed_at_last_event(self):
        events = [
            ev(0.0, STEP, B, nbytes=0),
            ev(2.0, ALLREDUCE, B, plane=2, nbytes=4096),
            ev(8.0, ALLREDUCE, E, plane=2, nbytes=4096),
            # died mid-step: no step end, the op is the last thing seen
        ]
        view = diagnose.rank_view_from_obj(rank_obj(0, events, world=1))
        t0, t1, truncated = view.steps[0]
        assert truncated is True
        assert t1 == 8.0 * MS
        report = diagnose.diagnose([view])
        assert report["steps"][0]["ranks"][0]["truncated"] is True

    def test_marker_problems_surface_in_report(self):
        events = [
            ev(0.0, STEP, E, nbytes=0),   # end that never began
            ev(1.0, STEP, B, nbytes=1),
            ev(2.0, STEP, E, nbytes=1),
        ]
        report = diagnose.diagnose(
            [diagnose.rank_view_from_obj(rank_obj(0, events, world=1))]
        )
        assert report["step_marker_problems"], report
        assert "never began" in report["step_marker_problems"][0]

    def test_markerless_trace_degrades_to_one_job_step(self):
        events = [
            ev(1.0, ALLREDUCE, B, plane=2, nbytes=4096),
            ev(6.0, ALLREDUCE, E, plane=2, nbytes=4096),
        ]
        report = diagnose.diagnose(
            [diagnose.rank_view_from_obj(rank_obj(0, events, world=1))]
        )
        assert report["n_steps"] == 1
        assert report["steps"][0]["index"] == -1
        assert report["steps"][0]["name"] == "job"

    def test_step_names_ride_the_python_lane(self):
        events = compute_straggler_events(0)
        py = [[ANCHOR + k * 100 * MS, "step:train", 1, k]
              for k in range(4)]
        view = diagnose.rank_view_from_obj(
            rank_obj(0, events, world=1, py_events=py)
        )
        report = diagnose.diagnose([view])
        assert all(s["name"] == "train" for s in report["steps"])


class TestOverlap:
    """The measured overlap ratio: engine wire time NOT covered by a
    caller-side blocked bracket.  Op scopes on the ENGINE lane are
    body executions and must not count as caller-blocked — the native
    wait bracket (kind 53) and python-lane spans are what the caller
    actually sat in."""

    ENGINE_LANE = 9

    def _engine_events(self, wire_lo, wire_hi, wait_lo, wait_hi):
        dur_ns = int((wire_hi - wire_lo) * MS)
        comm = (1 << 24) | 0  # iallreduce tag (async_evt_comm)
        return [
            ev(0.0, STEP, B, nbytes=0),
            ev(wire_lo, OP_PROGRESS, 0, lane=self.ENGINE_LANE,
               comm=comm, peer=1),
            ev(wire_lo, ALLREDUCE, B, plane=2, lane=self.ENGINE_LANE,
               nbytes=1 << 20),
            ev(wire_hi, ALLREDUCE, E, plane=2, lane=self.ENGINE_LANE,
               nbytes=1 << 20),
            ev(wire_hi, OP_COMPLETE, 0, lane=self.ENGINE_LANE,
               comm=comm, peer=0, nbytes=dur_ns),
            ev(wait_lo, WAIT, B, comm=comm, nbytes=1 << 20),
            ev(wait_hi, WAIT, E, comm=comm, nbytes=1 << 20),
            ev(100.0, STEP, E, nbytes=0),
        ]

    def test_overlapped_wait_scores_high(self):
        # wire 10..60, caller waited only 50..60: 80% overlapped
        view = diagnose.rank_view_from_obj(rank_obj(
            0, self._engine_events(10.0, 60.0, 50.0, 60.0), world=1
        ))
        assert view.engine_lanes == {self.ENGINE_LANE}
        report = diagnose.diagnose([view])
        assert report["steps"][0]["overlap_pct"] == pytest.approx(
            80.0, abs=1.0
        )

    def test_blocking_wait_scores_zero(self):
        # caller sat in wait for the whole wire phase
        view = diagnose.rank_view_from_obj(rank_obj(
            0, self._engine_events(10.0, 60.0, 9.0, 61.0), world=1
        ))
        report = diagnose.diagnose([view])
        assert report["steps"][0]["overlap_pct"] == 0.0

    def test_engine_lane_scope_is_not_caller_blocked(self):
        view = diagnose.rank_view_from_obj(rank_obj(
            0, self._engine_events(10.0, 60.0, 50.0, 60.0), world=1
        ))
        # blocked = the wait bracket only; the engine-lane allreduce
        # scope contributes wire, not blocked
        assert diagnose._total(view.blocked_spans) == 10 * MS
        assert diagnose._total(view.engine_busy) == 50 * MS

    def test_caller_lane_scope_still_counts_blocked(self):
        # pre-engine caller-thread op (no engine lifecycle events):
        # its scope IS the caller sitting in the op
        events = [
            ev(0.0, STEP, B, nbytes=0),
            ev(10.0, ALLREDUCE, B, plane=2, nbytes=4096),
            ev(60.0, ALLREDUCE, E, plane=2, nbytes=4096),
            ev(100.0, STEP, E, nbytes=0),
        ]
        view = diagnose.rank_view_from_obj(rank_obj(0, events, world=1))
        assert diagnose._total(view.blocked_spans) == 50 * MS


class TestMergedTraceInput:
    def test_same_verdict_from_merged_trace(self):
        objs = [rank_obj(r, compute_straggler_events(r))
                for r in range(3)]
        merged = trace.merge_rank_objs(objs, job="diagjob")
        views = diagnose.rank_views_from_trace(merged)
        assert [v.rank for v in views] == [0, 1, 2]
        report = diagnose.diagnose(views)
        assert report["n_steps"] == 4
        assert report["summary"]["straggler"] == 1
        for s in report["steps"]:
            assert s["critical_phase"] == "compute", s

    def test_truncated_step_survives_the_merge(self):
        # rank dies inside step 1: the merger synthesizes the close
        # with the BEGIN's args, so the merged-trace input path keeps
        # both the step identity and the truncated tag
        obj = rank_obj(0, [
            ev(0.0, STEP, B, nbytes=0),
            ev(10.0, STEP, E, nbytes=0),
            ev(20.0, STEP, B, nbytes=1),
            ev(25.0, ALLREDUCE, B, plane=2, nbytes=4096),
            # no op end, no step end: died here
        ], world=1)
        views = diagnose.rank_views_from_trace(
            trace.merge_rank_objs([obj], job="j")
        )
        steps = views[0].steps
        assert set(steps) == {0, 1}
        assert steps[0][2] is False
        assert steps[1][2] is True      # truncated tag preserved
        assert steps[1][1] >= 25 * MS   # closed at the last event
        # parity with the rank-file path: the unclosed op span is not
        # fabricated from the synthesized close
        assert views[0].op_spans == []

    def test_wait_spans_survive_the_merge(self):
        obj = rank_obj(0, [
            ev(0.0, STEP, B, nbytes=0),
            ev(5.0, WAIT, B, nbytes=4096),
            ev(15.0, WAIT, E, nbytes=4096),
            ev(20.0, STEP, E, nbytes=0),
        ], world=1)
        views = diagnose.rank_views_from_trace(
            trace.merge_rank_objs([obj], job="j")
        )
        assert diagnose._total(views[0].wait_spans) == 10 * MS


class TestPlaneAudit:
    def test_tree_bytes_over_ring_min_counted(self):
        events = [
            ev(0.0, ALLREDUCE, B, plane=1, nbytes=1 << 20),
            ev(5.0, ALLREDUCE, E, plane=1, nbytes=1 << 20),  # tree, 1M
            ev(6.0, ALLREDUCE, B, plane=1, nbytes=1 << 10),
            ev(7.0, ALLREDUCE, E, plane=1, nbytes=1 << 10),  # tiny: fine
        ]
        report = diagnose.diagnose(
            [diagnose.rank_view_from_obj(rank_obj(0, events, world=1))],
            ring_min_bytes=256 << 10,
        )
        audit = report["plane_audit"]
        assert audit["tree_calls_over_ring_min"] == 1
        assert audit["tree_bytes_over_ring_min"] == 1 << 20

    def test_recorded_tuning_beats_default(self):
        events = [
            ev(0.0, ALLREDUCE, B, plane=1, nbytes=1 << 20),
            ev(5.0, ALLREDUCE, E, plane=1, nbytes=1 << 20),
        ]
        obj = rank_obj(0, events, world=1,
                       tuning={"ring_min_bytes": 4 << 20})
        report = diagnose.diagnose(
            [diagnose.rank_view_from_obj(obj)]
        )
        # the job ran with a 4M switchover: 1M on tree was correct
        assert report["plane_audit"]["tree_calls_over_ring_min"] == 0

    def test_multihost_flat_counted_against_leader_min(self):
        events = [
            ev(0.0, ALLREDUCE, B, plane=2, nbytes=4 << 20),
            ev(5.0, ALLREDUCE, E, plane=2, nbytes=4 << 20),
        ]
        obj = rank_obj(0, events, world=1,
                       topology={"n_hosts": 2, "local_size": 2})
        report = diagnose.diagnose(
            [diagnose.rank_view_from_obj(obj)],
            leader_ring_min_bytes=1 << 20,
        )
        audit = report["plane_audit"]
        assert audit["flat_calls_over_leader_min_on_multihost"] == 1


class TestCtrlStall:
    def test_repair_and_replays_attributed_per_link(self):
        LB = schema.KIND_IDS["link_break"]
        RC = schema.KIND_IDS["reconnect"]
        RP = schema.KIND_IDS["replay"]
        # comm=-1: unstriped/legacy control events (schema v2 carries
        # the stripe index in comm for these kinds)
        events = [
            ev(0.0, STEP, B, nbytes=0),
            ev(10.0, LB, 0, comm=-1, peer=1),
            ev(14.0, RP, 0, comm=-1, peer=1),
            ev(15.0, RC, 0, comm=-1, peer=1),  # peer 1: 5 ms, 1 replay
            ev(20.0, LB, 0, comm=-1, peer=2),
            ev(21.0, RP, 0, comm=-1, peer=2),
            ev(22.0, RP, 0, comm=-1, peer=2),
            ev(30.0, RC, 0, comm=-1, peer=2),  # peer 2: 10 ms, 2 replays
            ev(50.0, STEP, E, nbytes=0),
        ]
        report = diagnose.diagnose(
            [diagnose.rank_view_from_obj(rank_obj(0, events, world=1))]
        )
        links = {link["peer"]: link for link in report["links"]}
        assert links[1]["repair_ms"] == pytest.approx(5.0)
        assert links[1]["replays"] == 1
        assert links[1]["breaks"] == 1
        assert links[2]["repair_ms"] == pytest.approx(10.0)
        assert links[2]["replays"] == 2
        assert links[2]["breaks"] == 1
        assert links[2]["cause"] == "repair"
        assert links[2]["slow_stripe"] is None

    def test_striped_repair_names_the_slow_stripe(self):
        # striped link (docs/performance.md "striped links"): stripe 2
        # owns the repair window, so the wait-cause names IT — and a
        # break on stripe 2 must NOT be closed by stripe 0's reconnect
        LB = schema.KIND_IDS["link_break"]
        RC = schema.KIND_IDS["reconnect"]
        events = [
            ev(0.0, STEP, B, nbytes=0),
            ev(10.0, LB, 0, comm=2, peer=1),   # stripe 2 breaks
            ev(12.0, LB, 0, comm=0, peer=1),   # stripe 0 blips too
            ev(13.0, RC, 0, comm=0, peer=1),   # ...and repairs in 1 ms
            ev(40.0, RC, 0, comm=2, peer=1),   # stripe 2 takes 30 ms
            ev(50.0, STEP, E, nbytes=0),
        ]
        report = diagnose.diagnose(
            [diagnose.rank_view_from_obj(rank_obj(0, events, world=1))]
        )
        link = {lk["peer"]: lk for lk in report["links"]}[1]
        assert link["repair_ms"] == pytest.approx(31.0)
        assert link["slow_stripe"] == 2
        assert link["cause"] == "repair (stripe 2)"
        assert link["repair_by_stripe"][2] == pytest.approx(30.0)
        assert link["breaks"] == 2

    def test_unrecovered_break_stalls_to_step_end(self):
        LB = schema.KIND_IDS["link_break"]
        events = [
            ev(0.0, STEP, B, nbytes=0),
            ev(10.0, LB, 0, peer=3),
            ev(50.0, STEP, E, nbytes=0),
        ]
        report = diagnose.diagnose(
            [diagnose.rank_view_from_obj(rank_obj(0, events, world=1))]
        )
        link = report["links"][0]
        assert link["peer"] == 3
        assert link["repair_ms"] == pytest.approx(40.0)


class TestDiff:
    def _report(self, slow_rank, stall_ms=30.0):
        views = [
            diagnose.rank_view_from_obj(rank_obj(
                r, wire_straggler_events(r, slow_rank=slow_rank,
                                         stall_ms=stall_ms)
            ))
            for r in range(3)
        ]
        return diagnose.diagnose(views)

    def test_metric_deltas_are_sign_aware(self):
        base = self._report(1, stall_ms=60.0)
        cur = self._report(1, stall_ms=10.0)
        diff = diagnose.diff_reports(cur, base)
        med = next(m for m in diff["metrics"]
                   if m["metric"] == "step_ms_median")
        assert med["delta"] < 0          # steps got faster
        assert med["improved"] is True
        assert diff["straggler"] == {"base": 1, "cur": 1}

    def test_zero_baseline_metric_stays_valid_json(self):
        base = self._report(1)
        cur = self._report(1)
        base["summary"]["overlap_pct_median"] = 0.0
        cur["summary"]["overlap_pct_median"] = 50.0
        diff = diagnose.diff_reports(cur, base)
        ov = next(m for m in diff["metrics"]
                  if m["metric"] == "overlap_pct_median")
        assert ov["delta_pct"] is None  # no finite %, never Infinity
        json.loads(json.dumps(diff))  # strictly serializable
        assert "median overlap" in diagnose.render_diff(diff)

    def test_straggler_movement_and_link_deltas(self):
        base = self._report(1)
        cur = self._report(2)
        diff = diagnose.diff_reports(cur, base)
        assert diff["straggler"]["base"] == 1
        assert diff["straggler"]["cur"] == 2
        deltas = {(link["rank"], link["peer"]): link["delta_ms"]
                  for link in diff["links"]}
        assert deltas[(1, 2)] < 0   # r1's stall vanished
        assert deltas[(2, 0)] > 0   # r2's appeared
        assert "straggler moved" in diagnose.render_diff(diff)

    def _report_n(self, n, slow_rank=1, stall_ms=30.0):
        """Like :meth:`_report` but for an ``n``-rank world (the
        autoscaled arm of an A/B run)."""
        def events(rank):
            out = []
            for k in range(4):
                base = k * 100.0
                out.append(ev(base, STEP, B, nbytes=k))
                out.append(ev(base + 5.0, ALLREDUCE, B, plane=2,
                              nbytes=1 << 20))
                tx = 5.0 + (stall_ms if rank == slow_rank else 0.5)
                out.append(ev(base + tx, FRAME_TX, 0,
                              peer=(rank + 1) % n))
                out.append(ev(base + tx + 5.0, ALLREDUCE, E, plane=2,
                              nbytes=1 << 20))
                out.append(ev(base + tx + 5.5, STEP, E, nbytes=k))
            return out
        views = [
            diagnose.rank_view_from_obj(
                rank_obj(r, events(r), world=n)
            )
            for r in range(n)
        ]
        return diagnose.diagnose(views)

    def test_cross_world_diff_marks_membership_links(self):
        # autoscaled arm shrank to 2 ranks: links touching rank 2 did
        # not "improve", the rank left the world — they get delta None
        # + only_in instead of a phantom negative delta
        base = self._report(1)        # static 3-rank arm
        cur = self._report_n(2)       # shrunk arm
        diff = diagnose.diff_reports(cur, base)
        assert diff["world"] == {"base": 3, "cur": 2}
        gone = [lk for lk in diff["links"] if lk.get("only_in") == "base"]
        assert gone
        assert all(lk["delta_ms"] is None for lk in gone)
        assert all(max(lk["rank"], lk["peer"]) >= 2 for lk in gone)
        # links whose endpoints exist in BOTH worlds keep signed deltas
        both = [lk for lk in diff["links"] if "only_in" not in lk]
        assert both
        assert all(lk["delta_ms"] is not None for lk in both)
        json.loads(json.dumps(diff))  # None stays valid JSON
        assert "world differs" in diagnose.render_diff(diff)

    def test_cross_world_grow_links_are_membership_not_regression(self):
        base = self._report_n(2)      # small arm
        cur = self._report(1)         # grew to 3 ranks
        diff = diagnose.diff_reports(cur, base)
        new = [lk for lk in diff["links"] if lk.get("only_in") == "cur"]
        assert new
        assert all(lk["delta_ms"] is None for lk in new)
        # render must not crash ranking None-delta links
        assert "world differs" in diagnose.render_diff(diff)


class TestCLI:
    def _write_job(self, tmp_path):
        for r in range(3):
            obj = rank_obj(r, wire_straggler_events(r))
            (tmp_path / dump.rank_file_name(r)).write_text(
                json.dumps(obj)
            )

    def test_json_report_round_trips(self, tmp_path, capsys):
        self._write_job(tmp_path)
        assert diagnose.main([str(tmp_path), "--json"]) == 0
        report = json.loads(capsys.readouterr().out)
        assert report["schema"] == diagnose.DIAG_SCHEMA
        assert report["summary"]["straggler"] == 1

    def test_human_render_names_the_straggler(self, tmp_path, capsys):
        self._write_job(tmp_path)
        assert diagnose.main([str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "straggler: r1" in out
        assert "wire" in out

    def test_diff_against_saved_report(self, tmp_path, capsys):
        self._write_job(tmp_path)
        assert diagnose.main([str(tmp_path), "--json"]) == 0
        saved = tmp_path / "base.json"
        saved.write_text(capsys.readouterr().out)
        assert diagnose.main(
            [str(tmp_path), "--diff", str(saved)]
        ) == 0
        assert "straggler unchanged" in capsys.readouterr().out

    def test_missing_dir_is_a_clean_error(self, tmp_path, capsys):
        assert diagnose.main([str(tmp_path / "nope")]) == 2
        assert "t4j-diagnose" in capsys.readouterr().err

    def test_parse_bytes_suffixes(self):
        assert diagnose.parse_bytes("256K") == 256 << 10
        assert diagnose.parse_bytes("4m") == 4 << 20
        assert diagnose.parse_bytes(1024) == 1024
        with pytest.raises(ValueError, match="byte count"):
            diagnose.parse_bytes("lots")


class TestExporterSnapshot:
    def _snapshot(self, rank=0, comm_ms=1.0):
        reg = tele.MetricsRegistry()
        reg.observe(comm=0, op="allreduce", plane="ring",
                    nbytes=1 << 20, dur_ns=int(comm_ms * MS))
        return exporter.build_snapshot(
            rank=rank, world=2, mode="counters", metrics=reg,
            link_stats={"reconnects": 1, "max_reconnects": 1,
                        "worst_peer": 1, "state": 0,
                        "max_replayed_bytes": 0,
                        "per_peer": {"1": {"reconnects": 1,
                                           "replayed_frames": 0,
                                           "replayed_bytes": 0,
                                           "state": 0}}},
            last_events=[ev(1.0, ALLREDUCE, B, nbytes=64),
                         ev(2.0, ALLREDUCE, E, nbytes=64)],
            dropped=0, job="diagjob",
        )

    def test_build_validates_and_round_trips(self, tmp_path):
        snap = self._snapshot()
        exporter.validate_snapshot(snap)
        out = tmp_path / "export.json"
        assert exporter.export_file(out, obj=snap) == out
        exporter.validate_snapshot(json.loads(out.read_text()))

    def test_missing_key_rejected(self):
        snap = self._snapshot()
        del snap["ops"]
        with pytest.raises(exporter.SnapshotError, match="ops"):
            exporter.validate_snapshot(snap)

    def test_last_events_use_the_shared_formatter(self):
        snap = self._snapshot()
        # same rendering check_health prints: op + phase + age
        assert any("allreduce" in line for line in snap["last_events"])
        joined = "; ".join(snap["last_events"])
        assert joined == schema.format_recent_events(
            [ev(1.0, ALLREDUCE, B, nbytes=64),
             ev(2.0, ALLREDUCE, E, nbytes=64)]
        )

    def test_prometheus_exposition(self):
        text = exporter.render_prometheus(self._snapshot())
        assert 't4j_op_count_total{rank="0",op="allreduce"' in text
        assert "t4j_worst_link_reconnects" in text
        assert "# TYPE t4j_op_count_total counter" in text

    def test_aggregate_names_straggler_and_worst_link(self):
        # rank 1 spends the least time in comm: in a collective job
        # everyone waits on it, so it is the live straggler estimate
        snaps = [self._snapshot(rank=0, comm_ms=9.0),
                 self._snapshot(rank=1, comm_ms=1.0)]
        agg = exporter.aggregate_snapshots(snaps, job="diagjob")
        assert agg["ranks_reporting"] == 2
        assert agg["straggler"] == 1
        assert agg["worst_link"]["reconnects"] == 1
        text = exporter.render_prometheus_job(agg)
        assert "t4j_job_straggler_rank 1" in text

    def test_http_server_serves_both_views(self):
        snap = self._snapshot()
        srv = exporter.MetricsExporter(0, collect_fn=lambda: snap)
        srv.start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            obj = exporter.scrape(f"{base}/metrics.json", timeout=5)
            exporter.validate_snapshot(obj)
            from urllib.request import urlopen

            with urlopen(f"{base}/metrics", timeout=5) as resp:
                assert b"t4j_op_count_total" in resp.read()
        finally:
            srv.stop()


class TestStepMarkers:
    def setup_method(self):
        step_mod._reset()
        recorder._reset("trace")

    def teardown_method(self):
        step_mod._reset()
        recorder._reset()

    def test_indices_monotone_and_autoclose(self):
        assert step_mod.annotate_step("a") == 0
        assert step_mod.current_step() == (0, "a")
        assert step_mod.annotate_step("b") == 1  # auto-closes #0
        step_mod.end_step()
        assert step_mod.current_step() is None
        step_mod.end_step()  # idempotent
        rows = recorder.drain()
        marks = [(r[1], r[2], r[3]) for r in rows
                 if r[1].startswith("step:")]
        assert marks == [
            ("step:a", 1, 0), ("step:a", 2, 0),
            ("step:b", 1, 1), ("step:b", 2, 1),
        ]

    def test_scope_form_balances(self):
        with step_mod.step_scope("train") as idx:
            assert idx == 0
            assert step_mod.current_step() == (0, "train")
        assert step_mod.current_step() is None
        rows = [r for r in recorder.drain()
                if r[1] == "step:train"]
        assert [r[2] for r in rows] == [1, 2]

    def test_scope_tolerates_inner_annotate(self):
        with step_mod.step_scope("outer"):
            step_mod.annotate_step("inner")  # closes "outer"
        # the scope exit must not close "inner" twice or re-close outer
        assert step_mod.current_step() == (1, "inner")
        step_mod.end_step()

    def test_markers_never_raise_without_native_bridge(self):
        # no bridge loaded anywhere in this test process: the native
        # half is a no-op, the python-lane record still lands
        idx = step_mod.annotate_step("solo")
        step_mod.end_step()
        assert idx == 0


class TestResizePhase:
    """Elastic resize windows (docs/failure-semantics.md "elastic
    membership") are their OWN diagnosis phase: the membership
    agreement/rebuild time must not be misbinned as link repair, and
    repair windows overlapping a resize are clipped against it."""

    RB = schema.RESIZE_BEGIN_KIND
    RD = schema.RESIZE_DONE_KIND

    def test_resize_window_is_its_own_phase(self):
        LB = schema.KIND_IDS["link_break"]
        RC = schema.KIND_IDS["reconnect"]
        events = [
            ev(0.0, STEP, B, nbytes=0),
            # a resize spanning 10..40 ms; the link to the dead peer
            # breaks inside it and "recovers" (the rebuild) inside it
            ev(10.0, self.RB, 0, peer=-1, nbytes=1),
            ev(12.0, LB, 0, peer=3),
            ev(38.0, RC, 0, peer=3),
            ev(40.0, self.RD, 0, peer=7, nbytes=1),
            ev(80.0, STEP, E, nbytes=0),
        ]
        report = diagnose.diagnose(
            [diagnose.rank_view_from_obj(rank_obj(0, events, world=1))]
        )
        row = report["steps"][0]["ranks"][0]
        assert row["resize_ms"] == pytest.approx(30.0)
        # the 26 ms break->reconnect window lies INSIDE the resize:
        # clipped to zero repair, not double-attributed
        links = {link["peer"]: link for link in report["links"]}
        assert links[3]["repair_ms"] == pytest.approx(0.0)
        assert report["rank_summary"][0]["resize_stall_ms"] == \
            pytest.approx(30.0)

    def test_repair_outside_resize_still_counts(self):
        LB = schema.KIND_IDS["link_break"]
        RC = schema.KIND_IDS["reconnect"]
        events = [
            ev(0.0, STEP, B, nbytes=0),
            ev(5.0, LB, 0, peer=1),
            ev(9.0, RC, 0, peer=1),   # plain 4 ms repair, no resize
            ev(20.0, self.RB, 0, peer=-1, nbytes=1),
            ev(30.0, self.RD, 0, peer=2, nbytes=1),
            ev(60.0, STEP, E, nbytes=0),
        ]
        report = diagnose.diagnose(
            [diagnose.rank_view_from_obj(rank_obj(0, events, world=1))]
        )
        row = report["steps"][0]["ranks"][0]
        assert row["resize_ms"] == pytest.approx(10.0)
        links = {link["peer"]: link for link in report["links"]}
        assert links[1]["repair_ms"] == pytest.approx(4.0)

    def test_unclosed_resize_stalls_to_step_end(self):
        events = [
            ev(0.0, STEP, B, nbytes=0),
            ev(10.0, self.RB, 0, peer=-1, nbytes=1),
            ev(50.0, STEP, E, nbytes=0),
        ]
        report = diagnose.diagnose(
            [diagnose.rank_view_from_obj(rank_obj(0, events, world=1))]
        )
        row = report["steps"][0]["ranks"][0]
        assert row["resize_ms"] == pytest.approx(40.0)

    def test_step_spanning_resize_is_tagged(self):
        # an autoscale epoch committing mid-serve: the slow step is
        # attributed to the resize AND carries the spans_resize flag so
        # dashboards/t4j-diagnose name the epoch, not a phantom link
        # stall; the clean step after it stays untagged
        events = [
            ev(0.0, STEP, B, nbytes=0),
            ev(5.0, self.RB, 0, peer=-1, nbytes=1),
            ev(45.0, self.RD, 0, peer=3, nbytes=1),
            ev(50.0, STEP, E, nbytes=0),
            ev(100.0, STEP, B, nbytes=1),
            ev(110.0, STEP, E, nbytes=1),
        ]
        report = diagnose.diagnose(
            [diagnose.rank_view_from_obj(rank_obj(0, events, world=1))]
        )
        resize_step, clean_step = report["steps"][0], report["steps"][1]
        assert resize_step["spans_resize"] is True
        assert resize_step["critical_phase"] == "resize"
        assert clean_step["spans_resize"] is False
        assert clean_step["critical_phase"] != "resize"


class TestExporterMembership:
    """The membership gauges (docs/observability.md): per-rank
    t4j_world_* series, job-level aggregation, and departed-rank
    marking — dashboards must follow the resized world instead of
    flatlining."""

    def _snap(self, rank, epoch=1, alive=7, mask=0xF7, boot=8):
        return exporter.build_snapshot(
            rank=rank, world=boot, mode="counters", metrics=[],
            world_info={"epoch": epoch, "boot_size": boot,
                        "alive_count": alive, "alive_mask": mask,
                        "resizing": False},
        )

    def test_rank_prometheus_world_gauges(self):
        text = exporter.render_prometheus(self._snap(0))
        assert 't4j_world_size{rank="0"} 7' in text
        assert 't4j_world_epoch{rank="0"} 1' in text
        assert 't4j_world_resizing{rank="0"} 0' in text

    def test_snapshot_without_world_info_unchanged(self):
        snap = exporter.build_snapshot(rank=0, world=2,
                                       mode="counters", metrics=[])
        assert snap["world_info"] == {}
        assert "t4j_world_size" not in exporter.render_prometheus(snap)

    def test_job_aggregate_tracks_membership(self):
        # freshest epoch wins even when a stale-scrape rank still
        # reports the pre-resize view
        stale = self._snap(1, epoch=0, alive=8, mask=0xFF)
        agg = exporter.aggregate_snapshots(
            [self._snap(0), stale], job="j")
        assert agg["world_size"] == 7
        assert agg["world_epoch"] == 1
        assert agg["departed_ranks"] == [3]
        text = exporter.render_prometheus_job(agg)
        assert "t4j_world_size 7" in text
        assert "t4j_world_epoch 1" in text
        assert 't4j_rank_departed{rank="3"} 1' in text

    def test_job_aggregate_without_world_info(self):
        agg = exporter.aggregate_snapshots(
            [exporter.build_snapshot(rank=0, world=2, mode="counters",
                                     metrics=[])], job="j")
        assert agg["world_size"] is None
        assert "t4j_world_size" not in exporter.render_prometheus_job(agg)

    def test_job_view_full_shrink_rejoin_cycle(self):
        """The gauges through a whole elastic life cycle (epoch 0 boot
        -> epoch 1 shrink losing rank 3 -> epoch 2 rejoin back to 8):
        the job view must track each transition, mark the departure
        only while it holds, and clear it when the slot rejoins."""
        def stage(epoch, alive, mask, ranks):
            return exporter.aggregate_snapshots(
                [self._snap(r, epoch=epoch, alive=alive, mask=mask)
                 for r in ranks], job="cycle")

        boot = stage(0, 8, 0xFF, range(8))
        assert (boot["world_size"], boot["world_epoch"]) == (8, 0)
        assert boot["departed_ranks"] == []
        shrink = stage(1, 7, 0xF7, [r for r in range(8) if r != 3])
        assert (shrink["world_size"], shrink["world_epoch"]) == (7, 1)
        assert shrink["departed_ranks"] == [3]
        rejoin = stage(2, 8, 0xFF, range(8))
        assert (rejoin["world_size"], rejoin["world_epoch"]) == (8, 2)
        assert rejoin["departed_ranks"] == []
        # the Prometheus series a dashboard would scrape at each stage
        t0, t1, t2 = (exporter.render_prometheus_job(a)
                      for a in (boot, shrink, rejoin))
        assert "t4j_world_size 8" in t0 and "t4j_world_epoch 0" in t0
        assert "t4j_rank_departed" not in t0
        assert "t4j_world_size 7" in t1 and "t4j_world_epoch 1" in t1
        assert 't4j_rank_departed{rank="3"} 1' in t1
        assert "t4j_world_size 8" in t2 and "t4j_world_epoch 2" in t2
        assert "t4j_rank_departed" not in t2

    def test_job_view_mid_rejoin_scrape_prefers_freshest_epoch(self):
        """A scrape that catches survivors already at epoch 2 while a
        laggard still reports the epoch-1 shrunk view must resolve to
        the rejoined world — freshest epoch wins, so the dashboard
        never regresses to a stale membership."""
        laggard = self._snap(5, epoch=1, alive=7, mask=0xF7)
        fresh = [self._snap(r, epoch=2, alive=8, mask=0xFF)
                 for r in (0, 3)]
        agg = exporter.aggregate_snapshots([laggard] + fresh, job="j")
        assert agg["world_epoch"] == 2
        assert agg["world_size"] == 8
        assert agg["departed_ranks"] == []


class TestExporterServing:
    """The serving gauges on the launcher job view (docs/serving.md):
    the frontend's queue/shed/SLO block rides the aggregate next to
    the membership gauges, and the Prometheus job rendering carries
    queue depth, batch occupancy, shed count and p99-vs-SLO."""

    @staticmethod
    def _serving(rank, **over):
        sv = {
            "schema": "t4j-serving-v1", "admit_mode": "on",
            "slo_ms": 500.0, "max_batch": 4, "queue_depth": 2,
            "batch_occupancy": 3, "steps": 10, "submitted": 12,
            "completed": 9, "shed": 1,
            "shed_by_reason": {"predicted-miss": 1}, "slo_ok": 9,
            "slo_attainment": 0.9, "latency_p50_ms": 80.0,
            "latency_p99_ms": 420.0, "first_token_p50_ms": 20.0,
            "first_token_p99_ms": 60.0,
        }
        sv.update(over)
        return sv

    def _snap(self, rank, serving=None):
        return exporter.build_snapshot(
            rank=rank, world=4, mode="counters", metrics=[],
            serving=serving,
        )

    def test_job_view_takes_frontend_block(self):
        # rank 0 is the frontend; followers publish occupancy-only
        # blocks the aggregate must not prefer
        objs = [
            self._snap(1, self._serving(1, queue_depth=0,
                                        submitted=0)),
            self._snap(0, self._serving(0)),
            self._snap(2),
        ]
        agg = exporter.aggregate_snapshots(objs, job="serve")
        assert agg["serving"]["queue_depth"] == 2
        assert agg["serving"]["submitted"] == 12
        assert agg["serving_ranks"] == [0, 1]

    def test_job_prometheus_serving_rows(self):
        agg = exporter.aggregate_snapshots(
            [self._snap(0, self._serving(0))], job="serve"
        )
        text = exporter.render_prometheus_job(agg)
        assert "t4j_job_serving_queue_depth 2" in text
        assert "t4j_job_serving_batch_occupancy 3" in text
        assert "t4j_job_serving_shed_total 1" in text
        assert "t4j_job_serving_completed_total 9" in text
        assert "t4j_job_serving_latency_p99_ms 420.0" in text
        assert "t4j_job_serving_slo_ms 500.0" in text
        assert "t4j_job_serving_slo_attainment 0.9" in text
        assert "t4j_job_serving_ranks 1" in text

    def test_job_view_without_serving_unchanged(self):
        agg = exporter.aggregate_snapshots(
            [self._snap(0), self._snap(1)], job="j"
        )
        assert agg["serving"] == {}
        assert "t4j_job_serving" not in exporter.render_prometheus_job(
            agg
        )
