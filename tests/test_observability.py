"""Observability coverage (reference: §5.1 bridge logging, asserted with
regexes against captured stdout in tests/collective_ops/test_common.py:
118-165; env toggling of MPI4JAX_DEBUG).

Two surfaces here: XLA-profiler name scopes baked into the lowered
module (always on), and opt-in per-call debug lines in the reference's
``r{rank} | {callid} | <Op> ...`` wire format.

These are the reference-parity surfaces only.  The first-class
telemetry layer that superseded them — the native event ring, metrics
registry with p50/p99, cross-rank Perfetto timelines and ``t4j-top``
(``T4J_TELEMETRY``, ``launch.py --telemetry``) — is documented in
docs/observability.md and covered by tests/test_telemetry.py (pure
core), tests/proc/test_telemetry_proc.py (2-rank end-to-end) and the
ci_smoke ``telemetry`` lane (tools/telemetry_smoke.py).
"""

import re

import jax
import jax.numpy as jnp
import pytest

import mpi4jax_tpu as m
from mpi4jax_tpu.utils import config

from tests.helpers import spmd

SIZE = 8


def test_named_scope_in_lowered_module(comm1d):
    """Every op's profiler scope must appear in the lowered HLO, so XLA
    profiles attribute collective time to the op that issued it."""

    def fn(x):
        tok = m.create_token()
        y, tok = m.allreduce(x, m.SUM, comm=comm1d, token=tok)
        y, tok = m.sendrecv(
            y,
            y,
            source=lambda r: (r - 1) % SIZE,
            dest=lambda r: (r + 1) % SIZE,
            comm=comm1d,
            token=tok,
        )
        return y

    text = (
        jax.jit(spmd(comm1d, fn))
        .lower(jnp.arange(8.0))
        .as_text(debug_info=True)
    )
    assert "mpi4jax_tpu.allreduce" in text
    assert "mpi4jax_tpu.sendrecv" in text


def test_debug_log_wire_format(comm1d, capfd):
    """MPI4JAX_TPU_DEBUG output: the reference's begin/done line pair
    per call per device (mpi_xla_bridge.pyx:47-60 wire format)."""
    config.set_debug(True)
    try:

        def fn(x):
            y, _ = m.allreduce(x, m.SUM, comm=comm1d)
            return y

        out = jax.jit(spmd(comm1d, fn))(jnp.arange(8.0))
        jax.block_until_ready(out)
        jax.effects_barrier()
    finally:
        config.set_debug(None)

    captured = capfd.readouterr().out
    begins = [
        l for l in captured.splitlines()
        if "MPI_Allreduce with" in l
    ]
    dones = [
        l for l in captured.splitlines()
        if "MPI_Allreduce done with code 0" in l
    ]
    assert len(begins) == SIZE, captured
    assert len(dones) == SIZE, captured
    bpat = re.compile(r"^r\d+ \| \w{8} \| MPI_Allreduce with 1 items$")
    dpat = re.compile(
        r"^r\d+ \| \w{8} \| MPI_Allreduce done with code 0 "
        r"\(\d\.\d{2}e[+-]?\d+s\)$"
    )
    assert all(bpat.match(l) for l in begins), begins
    assert all(dpat.match(l) for l in dones), dones
    ranks = sorted(int(l[1 : l.index(" ")]) for l in begins)
    assert ranks == list(range(SIZE))

    def ids_by_rank(lines):
        return {l.split(" | ")[0]: l.split(" | ")[1] for l in lines}

    # each rank's begin/done pair must carry the same call id
    assert ids_by_rank(begins) == ids_by_rank(dones), (begins, dones)


def test_debug_ids_survive_concurrent_executions(comm1d, capfd):
    """Two executions of ONE jitted call site running concurrently must
    emit correctly paired begin/done ids (the id and start time are
    threaded through the computation, not kept in per-site state)."""
    import threading

    config.set_debug(True)
    try:

        def fn(x):
            y, _ = m.allreduce(x, m.SUM, comm=comm1d)
            return y

        jitted = jax.jit(spmd(comm1d, fn))
        jax.block_until_ready(jitted(jnp.arange(8.0)))  # compile outside
        capfd.readouterr()  # drop warm-up lines

        results = []

        def run():
            results.append(jax.block_until_ready(jitted(jnp.arange(8.0))))

        threads = [threading.Thread(target=run) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        jax.effects_barrier()
    finally:
        config.set_debug(None)

    captured = capfd.readouterr().out
    begins, dones = {}, {}
    for line in captured.splitlines():
        if "MPI_Allreduce" not in line:
            continue
        rank, rid, rest = line.split(" | ", 2)
        bucket = dones if "done" in rest else begins
        bucket.setdefault((rank, rid), 0)
        bucket[(rank, rid)] += 1
    # 4 runs x 8 devices, every (rank, id) pair appears exactly once on
    # each side, and the id sets match exactly — no '????????' orphans,
    # no reused or crossed ids
    assert sum(begins.values()) == 4 * SIZE, captured
    assert begins == dones, captured
    assert all(n == 1 for n in begins.values()), captured
    assert not any("????????" in line for line in captured.splitlines())


def test_debug_disabled_stages_nothing(comm1d):
    """With debug off, no host callback may appear in the lowered IR."""
    config.set_debug(False)
    try:

        def fn(x):
            y, _ = m.allreduce(x, m.SUM, comm=comm1d)
            return y

        text = jax.jit(spmd(comm1d, fn)).lower(jnp.arange(8.0)).as_text()
    finally:
        config.set_debug(None)
    assert "callback" not in text.lower()


def test_env_var_toggle(monkeypatch):
    monkeypatch.setenv("MPI4JAX_TPU_DEBUG", "1")
    assert config.debug_enabled()
    monkeypatch.setenv("MPI4JAX_TPU_DEBUG", "0")
    assert not config.debug_enabled()
    monkeypatch.setenv("MPI4JAX_TPU_DEBUG", "junk")
    with pytest.raises(ValueError):
        config.debug_enabled()
