"""Version-gate shim (reference: tests/test_jax_compat.py with
monkeypatched versions, mpi4jax/_src/jax_compat.py:59-83)."""

import warnings

import pytest

from mpi4jax_tpu.utils import jax_compat


def test_current_jax_accepted():
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        try:
            jax_compat.check_jax_version()
        except Warning:
            pass  # newer-than-pin warning is acceptable for current jax


def test_newer_jax_warns():
    with pytest.warns(UserWarning, match="newer than"):
        jax_compat.check_jax_version("99.0.0")


def test_newer_jax_warning_silenced(monkeypatch):
    monkeypatch.setenv("MPI4JAX_TPU_NO_WARN_JAX_VERSION", "1")
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        jax_compat.check_jax_version("99.0.0")


def test_older_jax_rejected():
    with pytest.raises(RuntimeError, match="requires jax>="):
        jax_compat.check_jax_version("0.4.35")


def test_dev_version_parses():
    jax_compat.check_jax_version("0.7.1.dev20250101")
