"""t4j-postmortem pure core (mpi4jax_tpu/telemetry/postmortem.py):
cross-rank death analysis over synthetic drained + flight files.

Same stub-loader pattern as tests/test_telemetry.py so the suite runs
on every container, old-jax included.  The native half (a REAL
SIGKILL'd rank recovered from its mmap'd flight file) is covered by
tools/postmortem_smoke.py (the ci_smoke ``postmortem`` lane, plain +
ASan) and tests/proc/test_postmortem_proc.py.
"""

import importlib
import json
import pathlib
import sys
import types

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent


def _load_telemetry():
    try:
        import mpi4jax_tpu.telemetry as tele

        return tele
    except Exception:
        stubbed = "mpi4jax_tpu" not in sys.modules
        if stubbed:
            stub = types.ModuleType("mpi4jax_tpu")
            stub.__path__ = [str(REPO / "mpi4jax_tpu")]
            sys.modules["mpi4jax_tpu"] = stub
        try:
            return importlib.import_module("mpi4jax_tpu.telemetry")
        finally:
            if stubbed:
                sys.modules.pop("mpi4jax_tpu", None)


tele = _load_telemetry()
schema = tele.schema
dump = importlib.import_module(tele.__name__ + ".dump")
postmortem = importlib.import_module(tele.__name__ + ".postmortem")

E = schema.Event
NOW = 10**18  # the analysis instant (unix ns)
T0 = NOW - 100 * 10**9  # job start: 100s before the analysis
ANCHOR_MONO = 1_000_000_000  # every rank's monotonic anchor


def _mono(rel_s):
    """Job-relative seconds -> the synthetic monotonic clock."""
    return ANCHOR_MONO + int(rel_s * 1e9)


def write_drained(d, rank, events, world=8):
    obj = dump.build_rank_obj(
        rank, world, ANCHOR_MONO, T0, "trace", events=events)
    with open(d / dump.rank_file_name(rank), "w") as f:
        json.dump(obj, f)


def write_flight(d, rank, events, *, boot=1, epoch=0, hb_rel_s=None,
                 finalized=False, world=8, **kw):
    events = list(events)
    hb = _mono(hb_rel_s) if hb_rel_s is not None else (
        events[-1].t_ns if events else _mono(0))
    (d / schema.flight_file_name(rank, boot)).write_bytes(
        schema.encode_flight_file(
            rank, world, events, epoch=epoch, boot_unix_ns=boot,
            anchor_mono_ns=ANCHOR_MONO, anchor_unix_ns=T0,
            heartbeat_ns=hb, heartbeat_count=max(1, len(events)),
            finalized=finalized, **kw))


def op_span(rel_s, kind=7, lane=11, dur_s=0.01, peer=-1, nbytes=4096):
    return [E(_mono(rel_s), kind, 1, 2, 0, peer, lane, nbytes),
            E(_mono(rel_s + dur_s), kind, 2, 2, 0, peer, lane, nbytes)]


def open_op(rel_s, kind=7, lane=11, peer=-1, nbytes=4096):
    return [E(_mono(rel_s), kind, 1, 2, 0, peer, lane, nbytes)]


def kill_scene(d, victim=3, world=8, kill_rel_s=50.0):
    """The canonical hard death: every survivor drains (with a
    link_break/link_dead view of the victim), the victim leaves only
    a flight file with an open allreduce and a stopped heartbeat."""
    for r in range(world):
        if r == victim:
            continue
        events = op_span(kill_rel_s - 10) + [
            E(_mono(kill_rel_s + 0.3), schema.KIND_IDS["link_break"],
              0, 5, -1, victim, 7, 0),
            E(_mono(kill_rel_s + 0.8), schema.KIND_IDS["link_dead"],
              0, 5, -1, victim, 7, 0),
        ]
        write_drained(d, r, events, world=world)
        write_flight(d, r, events, hb_rel_s=kill_rel_s + 2.0,
                     world=world)
    victim_events = (
        op_span(kill_rel_s - 10)
        + [E(_mono(kill_rel_s - 0.2), schema.STEP_KIND, 1, 5, -1, -1,
             7, 4)]
        + open_op(kill_rel_s - 0.1, peer=-1)
        + [E(_mono(kill_rel_s - 0.05), schema.KIND_IDS["frame_tx"], 0,
             2, -1, (victim + 1) % world, 7, 65536)]
    )
    write_flight(d, victim, victim_events, hb_rel_s=kill_rel_s,
                 world=world)
    return victim


class TestVerdicts:
    def test_hard_death_vs_survivors(self, tmp_path):
        victim = kill_scene(tmp_path)
        report = postmortem.analyze_dir(tmp_path, now_unix_ns=NOW)
        assert report["first_failing_rank"] == victim
        assert report["verdicts"][str(victim)] == "dead"
        assert report["dead_ranks"] == [victim]
        for r in range(8):
            if r != victim:
                assert report["verdicts"][str(r)] == "drained"

    def test_fresh_heartbeat_reads_wedged_not_dead(self, tmp_path):
        kill_scene(tmp_path, kill_rel_s=99.0)  # died 1s before "now"
        report = postmortem.analyze_dir(tmp_path, now_unix_ns=NOW)
        assert report["verdicts"]["3"] == "alive"
        assert report["wedged_ranks"] == [3]
        assert report["first_failing_rank"] == 3  # still fingered

    def test_finalized_flight_is_not_a_death(self, tmp_path):
        write_flight(tmp_path, 0, op_span(10), finalized=True,
                     hb_rel_s=20.0)
        report = postmortem.analyze_dir(tmp_path, now_unix_ns=NOW)
        assert report["verdicts"]["0"] == "finalized"
        assert report["dead_ranks"] == []
        assert report["first_failing_rank"] is None

    def test_no_evidence_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            postmortem.analyze_dir(tmp_path)


class TestFirstFailure:
    def test_earliest_death_wins_among_two(self, tmp_path):
        write_flight(tmp_path, 1, open_op(40.0), hb_rel_s=40.0)
        write_flight(tmp_path, 5, open_op(44.0), hb_rel_s=44.0)
        write_drained(tmp_path, 0, op_span(45))
        report = postmortem.analyze_dir(tmp_path, now_unix_ns=NOW)
        assert sorted(report["dead_ranks"]) == [1, 5]
        assert report["first_failing_rank"] == 1

    def test_accusations_fallback_without_victim_evidence(
            self, tmp_path):
        # flight recorder off on the dead rank: survivors' control
        # events still converge on the accused peer
        for r in (0, 1, 2):
            write_drained(tmp_path, r, [
                E(_mono(50), schema.KIND_IDS["link_dead"], 0, 5, -1, 6,
                  7, 0)])
        report = postmortem.analyze_dir(tmp_path, now_unix_ns=NOW)
        assert report["first_failing_rank"] == 6
        assert report["verdicts"]["6"] == "no-evidence"
        # summary_lines must not crash on the evidence-free victim
        lines = postmortem.summary_lines(report)
        assert any("rank 6" in ln for ln in lines)


class TestInflightAndPeers:
    def test_open_op_step_links_and_peer_views(self, tmp_path):
        victim = kill_scene(tmp_path)
        report = postmortem.analyze_dir(tmp_path, now_unix_ns=NOW)
        vic = report["ranks"][str(victim)]
        assert [o["op"] for o in vic["inflight"]["ops"]] == ["allreduce"]
        assert vic["inflight"]["step"] == 4  # died inside step #4
        assert (victim + 1) % 8 in vic["affected_links"]
        views = report["peer_views"]
        assert len(views) == 7
        kinds = {row["kind"] for rows in views.values() for row in rows}
        assert {"link_break", "link_dead"} <= kinds
        lines = postmortem.summary_lines(report)
        joined = "\n".join(lines)
        assert f"first failure: rank {victim}" in joined
        assert "allreduce" in joined
        assert "step #4" in joined

    def test_balanced_stream_has_nothing_inflight(self, tmp_path):
        write_drained(tmp_path, 0, op_span(10) + op_span(11))
        report = postmortem.analyze_dir(tmp_path, now_unix_ns=NOW)
        assert report["ranks"]["0"]["inflight"]["ops"] == []


class TestResizeOrdering:
    def _resize_events(self, begin_rel_s, epoch, members):
        return [
            E(_mono(begin_rel_s), schema.RESIZE_BEGIN_KIND, 0, 5, -1,
              -1, 7, epoch),
            E(_mono(begin_rel_s + 0.5), schema.RESIZE_DONE_KIND, 0, 5,
              -1, members, 7, epoch),
        ]

    def test_death_preceding_the_resize_that_removed_it(self, tmp_path):
        victim = 3
        for r in range(8):
            if r == victim:
                continue
            events = [
                E(_mono(50.2), schema.KIND_IDS["rank_dead"], 0, 5, -1,
                  victim, 7, 1),
            ] + self._resize_events(50.3, 1, 7)
            write_drained(tmp_path, r, events)
        write_flight(tmp_path, victim, open_op(49.9), hb_rel_s=50.0,
                     epoch=0)
        report = postmortem.analyze_dir(tmp_path, now_unix_ns=NOW)
        resize = report["resize"]
        assert resize is not None
        assert resize["victim_epoch"] == 0
        assert resize["removing_epoch"] == 1
        assert resize["death_preceded_resize"] is True
        joined = "\n".join(postmortem.summary_lines(report))
        assert "preceded resize epoch 1" in joined

    def test_death_after_surviving_an_earlier_resize(self, tmp_path):
        # victim lived through epoch 1 (its header says so) and died
        # later, with no epoch-2 resize observed
        victim = 2
        for r in (0, 1):
            write_drained(tmp_path, r, self._resize_events(30.0, 1, 7))
        write_flight(tmp_path, victim, open_op(60.0), hb_rel_s=60.0,
                     epoch=1)
        report = postmortem.analyze_dir(tmp_path, now_unix_ns=NOW)
        resize = report["resize"]
        assert resize["victim_epoch"] == 1
        assert resize["removing_epoch"] is None
        assert resize["death_followed_epoch"] == 1
        joined = "\n".join(postmortem.summary_lines(report))
        assert "followed resize epoch 1" in joined


class TestTimelineAndWindow:
    def test_window_drops_old_events(self, tmp_path):
        events = [
            E(_mono(5.0), schema.KIND_IDS["link_break"], 0, 5, -1, 1,
              7, 0),
            E(_mono(95.0), schema.KIND_IDS["link_break"], 0, 5, -1, 1,
              7, 0),
        ]
        write_drained(tmp_path, 0, events)
        wide = postmortem.analyze_dir(tmp_path, window_s=1000,
                                      now_unix_ns=NOW)
        narrow = postmortem.analyze_dir(tmp_path, window_s=10,
                                        now_unix_ns=NOW)
        assert len(wide["timeline"]) == 2
        assert len(narrow["timeline"]) == 1
        assert narrow["timeline"][0]["t_rel_s"] == pytest.approx(95.0)

    def test_timeline_is_job_relative_and_sorted(self, tmp_path):
        kill_scene(tmp_path)
        report = postmortem.analyze_dir(tmp_path, now_unix_ns=NOW)
        rels = [row["t_rel_s"] for row in report["timeline"]]
        assert rels == sorted(rels)
        assert all(r is not None and r >= 0 for r in rels)


class TestMergedEvidence:
    def test_drained_and_flight_events_dedupe(self, tmp_path):
        events = op_span(10)
        write_drained(tmp_path, 0, events)
        write_flight(tmp_path, 0, events, hb_rel_s=11.0)
        report = postmortem.analyze_dir(tmp_path, now_unix_ns=NOW)
        assert report["ranks"]["0"]["events"] == 2  # not 4
        assert report["ranks"]["0"]["sources"] == ["drained", "flight"]

    def test_newest_incarnation_wins_and_counts(self, tmp_path):
        write_flight(tmp_path, 0, open_op(10.0), boot=100,
                     hb_rel_s=10.0)
        write_flight(tmp_path, 0, open_op(60.0), boot=200,
                     hb_rel_s=60.0, epoch=2)
        report = postmortem.analyze_dir(tmp_path, now_unix_ns=NOW)
        assert report["ranks"]["0"]["incarnations"] == 2
        assert report["ranks"]["0"]["epoch"] == 2

    def test_torn_slots_surface_in_report(self, tmp_path):
        write_flight(tmp_path, 0, open_op(10.0), hb_rel_s=10.0,
                     torn_positions=(30,))
        report = postmortem.analyze_dir(tmp_path, now_unix_ns=NOW)
        assert report["ranks"]["0"]["torn_slots"] == 1

    def test_split_flight_dir_evidence_is_found(self, tmp_path):
        # an explicit T4J_FLIGHT_DIR can point away from the telemetry
        # dir: the analysis must read flight files from BOTH, or a
        # hard death in the custom dir silently degrades to
        # "no-evidence"
        tel = tmp_path / "tel"
        fdir = tmp_path / "flight"
        tel.mkdir()
        fdir.mkdir()
        write_drained(tel, 0, op_span(45) + [
            E(_mono(50), schema.KIND_IDS["link_dead"], 0, 5, -1, 3, 7,
              0)])
        write_flight(fdir, 3, open_op(49.9), hb_rel_s=50.0)
        report = postmortem.analyze_dir(tel, now_unix_ns=NOW,
                                        flight_dir=fdir)
        assert report["first_failing_rank"] == 3
        assert report["verdicts"]["3"] == "dead"
        assert report["ranks"]["3"]["sources"] == ["flight"]
        # same dir passed twice must not double-count incarnations
        write_flight(tel, 1, open_op(40.0), hb_rel_s=40.0)
        report2 = postmortem.analyze_dir(tel, now_unix_ns=NOW,
                                         flight_dir=tel)
        assert report2["ranks"]["1"]["incarnations"] == 1


class TestCLI:
    def test_render_and_json(self, tmp_path, capsys):
        kill_scene(tmp_path)
        assert postmortem.main([str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "t4j-postmortem" in out
        assert "first failure: rank 3" in out
        assert postmortem.main([str(tmp_path), "--json"]) == 0
        report = json.loads(capsys.readouterr().out)
        assert report["schema"] == "t4j-postmortem-v1"
        assert report["first_failing_rank"] == 3

    def test_missing_dir_errors(self, tmp_path, capsys):
        assert postmortem.main([str(tmp_path / "nope")]) == 2
        assert "t4j-postmortem" in capsys.readouterr().err
