"""Support-code tests: capability probes, drain/flush, versioning —
the counterparts of the reference's tests/test_has_cuda.py and
tests/test_flush.py plus a version-shape check (versioneer analog)."""

import re

import jax
import jax.numpy as jnp
import numpy as np

import mpi4jax_tpu as m


def test_capability_probes():
    # on the CPU test platform: no TPU, and CUDA is never supported here
    assert m.has_cuda_support() is False
    assert m.has_tpu_support() is False  # conftest pins jax_platforms=cpu


def test_version_shape():
    # PEP-440-ish: starts with digits, dot-separated (git-describe local
    # parts allowed after '+')
    assert re.match(r"^\d+\.\d+", m.__version__), m.__version__


def test_drain_blocks_and_returns_scalar():
    from mpi4jax_tpu.utils.runtime import drain

    x = (jnp.arange(16.0) + 1).reshape(4, 4) * 2
    out = drain(x)
    assert np.asarray(out) == 2.0  # first element (nonzero on purpose)
    s = drain(jnp.float32(7))
    assert np.asarray(s) == 7.0


def test_drain_after_collective(comm1d):
    from mpi4jax_tpu.utils.runtime import drain
    from tests.helpers import spmd_jit

    f = spmd_jit(comm1d, lambda x: m.allreduce(x, m.SUM, comm=comm1d)[0])
    out = f(jnp.arange(8.0))
    assert drain(out) == 28.0


def test_version_prerelease_tags_are_pep440():
    """v0.1.0-rc1 must become the PEP 440 pre-release 0.1.0rc1 (which
    sorts BEFORE 0.1.0), not the local version 0.1.0+rc1 (after)."""
    from mpi4jax_tpu._version import _munge_describe as munge

    assert munge("v0.1.0-rc1") == "0.1.0rc1"
    assert munge("v0.1.0-rc1-3-gabc12") == "0.1.0rc1+3.gabc12"
    assert munge("v0.2.0-alpha.2") == "0.2.0a2"
    assert munge("v0.1.0-beta2") == "0.1.0b2"
    assert munge("v0.1.0-5-gdef00") == "0.1.0+5.gdef00"
    assert munge("v0.1.0") == "0.1.0"
