"""Shared helpers: run a per-device function SPMD over a comm's mesh."""

import jax


def spmd(comm, fn):
    """shard_map ``fn`` over all of ``comm``'s axes, everything sharded
    along its leading dimension."""
    spec = jax.P(comm.axes)
    return jax.shard_map(fn, mesh=comm.mesh, in_specs=spec, out_specs=spec)


def spmd_jit(comm, fn):
    return jax.jit(spmd(comm, fn))
