"""The DP×TP×SP transformer train step vs an unsharded oracle.

Composes every parallelism family in one differentiable step (TP
Megatron f/g, SP ring attention with GQA, DP grad sync) and checks the
loss and one SGD update against identical math on one device."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as m
from mpi4jax_tpu.models import transformer as tfm

CFG = tfm.TransformerConfig(
    vocab=32, d_model=16, layers=2, heads=4, kv_heads=2, head_dim=8, d_ff=32
)
B, S = 4, 16  # global batch/sequence


@pytest.fixture(scope="module")
def mesh3d():
    return jax.make_mesh(
        (2, 2, 2),
        ("dp", "tp", "sp"),
        axis_types=(jax.sharding.AxisType.Auto,) * 3,
    )


@pytest.fixture(scope="module")
def comms(mesh3d):
    world = m.MeshComm.from_mesh(mesh3d)
    return world.sub("dp"), world.sub("tp"), world.sub("sp")


def batch(seed=0):
    kt = jax.random.PRNGKey(seed)
    tokens = jax.random.randint(kt, (B, S), 0, CFG.vocab)
    # next-token targets, shifted globally (crosses sp shard boundaries)
    targets = jnp.roll(tokens, -1, axis=1)
    return tokens, targets


def test_train_step_matches_oracle(mesh3d, comms):
    comm_dp, comm_tp, comm_sp = comms
    params = tfm.init_params(jax.random.PRNGKey(1), CFG)
    tokens, targets = batch()

    step = tfm.make_global_train_step(
        mesh3d, comm_dp, comm_tp, comm_sp, CFG, lr=1e-1
    )
    new_params, loss = step(params, (tokens, targets))

    # oracle: same math, one device, explicit grad step
    ref_loss, ref_grads = jax.value_and_grad(
        lambda p: tfm.reference_loss(p, tokens, targets, CFG)
    )(params)
    ref_new = jax.tree.map(lambda p, g: p - 1e-1 * g, params, ref_grads)

    np.testing.assert_allclose(
        float(np.asarray(loss)[0]), float(ref_loss), rtol=2e-5, atol=2e-5
    )
    flat_new = jax.tree.leaves(new_params)
    flat_ref = jax.tree.leaves(ref_new)
    names = [
        "embed", "ln1", "wq", "wk", "wv", "wo", "ln2", "w1", "w2",
        "ln_f", "head",
    ]
    for name, got, want in zip(names, flat_new, flat_ref):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4,
            err_msg=name,
        )


def test_loss_decreases_over_steps(mesh3d, comms):
    comm_dp, comm_tp, comm_sp = comms
    params = tfm.init_params(jax.random.PRNGKey(2), CFG)
    tokens, targets = batch(seed=3)
    step = tfm.make_global_train_step(
        mesh3d, comm_dp, comm_tp, comm_sp, CFG, lr=3e-1
    )
    losses = []
    for _ in range(8):
        params, loss = step(params, (tokens, targets))
        losses.append(float(np.asarray(loss)[0]))
    assert losses[-1] < losses[0] * 0.8, losses  # memorises the batch
    assert np.isfinite(losses).all()


def test_head_divisibility_required(mesh3d, comms):
    comm_dp, comm_tp, comm_sp = comms
    for bad in (CFG._replace(heads=3), CFG._replace(kv_heads=1)):
        with pytest.raises(ValueError, match="divisible by the tensor"):
            tfm.make_global_train_step(
                mesh3d, comm_dp, comm_tp, comm_sp, bad
            )


def test_ulysses_sequence_matches_oracle(mesh3d, comms):
    # same oracle as the ring: ulysses computes exact attention, only
    # the collective schedule differs (2 alltoalls vs p ppermutes).
    # kv_heads=4 so heads/tp=2 divides sp=2 (the GQA config can't).
    cfg = CFG._replace(kv_heads=4)
    comm_dp, comm_tp, comm_sp = comms
    params = tfm.init_params(jax.random.PRNGKey(5), cfg)
    tokens, targets = batch(seed=6)

    step = tfm.make_global_train_step(
        mesh3d, comm_dp, comm_tp, comm_sp, cfg, lr=1e-1, sequence="ulysses"
    )
    new_params, loss = step(params, (tokens, targets))

    ref_loss, ref_grads = jax.value_and_grad(
        lambda p: tfm.reference_loss(p, tokens, targets, cfg)
    )(params)
    ref_new = jax.tree.map(lambda p, g: p - 1e-1 * g, params, ref_grads)

    np.testing.assert_allclose(
        float(np.asarray(loss)[0]), float(ref_loss), rtol=2e-5, atol=2e-5
    )
    for got, want in zip(jax.tree.leaves(new_params), jax.tree.leaves(ref_new)):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
        )


@pytest.mark.parametrize("sequence", ["ring", "ulysses"])
def test_remat_matches_plain(mesh3d, comms, sequence):
    # jax.checkpoint on each layer: same math recomputed — the update
    # must match the non-remat step bitwise-closely (identical graph
    # values; only scheduling differs).  Covers both context-parallel
    # schemes' collectives replaying under remat.
    cfg = CFG if sequence == "ring" else CFG._replace(kv_heads=4)
    comm_dp, comm_tp, comm_sp = comms
    params = tfm.init_params(jax.random.PRNGKey(7), cfg)
    tokens, targets = batch(seed=8)
    plain = tfm.make_global_train_step(
        mesh3d, comm_dp, comm_tp, comm_sp, cfg, lr=1e-1, sequence=sequence
    )
    rstep = tfm.make_global_train_step(
        mesh3d, comm_dp, comm_tp, comm_sp, cfg, lr=1e-1, sequence=sequence,
        remat=True,
    )
    p1, l1 = plain(params, (tokens, targets))
    p2, l2 = rstep(params, (tokens, targets))
    np.testing.assert_allclose(
        float(np.asarray(l1)[0]), float(np.asarray(l2)[0]), rtol=1e-6
    )
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2), strict=True):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        )


@pytest.mark.parametrize(
    "policy",
    ["names", ("attn_out", "mlp_out"), ("qkv", "v_proj", "attn_out", "mlp_out")],
    ids=["names", "save-residuals", "save-all-tags"],
)
def test_remat_save_lists_match_plain(mesh3d, comms, policy):
    # partial-remat policies (the named sweet spot and custom
    # save-lists) recompute a subset of the layer: gradients must be
    # identical to the non-remat step up to scheduling.
    comm_dp, comm_tp, comm_sp = comms
    params = tfm.init_params(jax.random.PRNGKey(9), CFG)
    tokens, targets = batch(seed=10)
    plain = tfm.make_global_train_step(
        mesh3d, comm_dp, comm_tp, comm_sp, CFG, lr=1e-1
    )
    rstep = tfm.make_global_train_step(
        mesh3d, comm_dp, comm_tp, comm_sp, CFG, lr=1e-1, remat=policy
    )
    p1, l1 = plain(params, (tokens, targets))
    p2, l2 = rstep(params, (tokens, targets))
    np.testing.assert_allclose(
        float(np.asarray(l1)[0]), float(np.asarray(l2)[0]), rtol=1e-6
    )
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2), strict=True):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        )


def test_ce_chunked_matches_streaming(mesh3d, comms):
    # chunked CE (head matmul + logsumexp per token chunk under
    # jax.checkpoint, full logits never materialised) is the same math
    # as the streaming form: loss and updated params must agree to f32
    # reduction-order roundoff.
    comm_dp, comm_tp, comm_sp = comms
    params = tfm.init_params(jax.random.PRNGKey(11), CFG)
    tokens, targets = batch(seed=12)
    plain = tfm.make_global_train_step(
        mesh3d, comm_dp, comm_tp, comm_sp, CFG, lr=1e-1
    )
    # global S=16 over sp=2 -> local seq 8; chunk 4 gives 2 chunks per
    # rank, so the scan's cross-chunk accumulation actually runs
    chunked = tfm.make_global_train_step(
        mesh3d, comm_dp, comm_tp, comm_sp, CFG._replace(ce_chunk=4),
        lr=1e-1,
    )
    p1, l1 = plain(params, (tokens, targets))
    p2, l2 = chunked(params, (tokens, targets))
    np.testing.assert_allclose(
        float(np.asarray(l1)[0]), float(np.asarray(l2)[0]), rtol=1e-6
    )
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2), strict=True):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        )


def test_ce_chunk_indivisible_raises(mesh3d, comms):
    comm_dp, comm_tp, comm_sp = comms
    step = tfm.make_global_train_step(
        mesh3d, comm_dp, comm_tp, comm_sp, CFG._replace(ce_chunk=7)
    )
    tokens, targets = batch(seed=13)
    with pytest.raises(ValueError, match="ce_chunk"):
        step(tfm.init_params(jax.random.PRNGKey(0), CFG), (tokens, targets))


def test_remat_unknown_tag_raises(mesh3d, comms):
    comm_dp, comm_tp, comm_sp = comms
    with pytest.raises(ValueError, match="unknown checkpoint tag"):
        step = tfm.make_global_train_step(
            mesh3d, comm_dp, comm_tp, comm_sp, CFG, remat=("nope",)
        )
        step(tfm.init_params(jax.random.PRNGKey(0), CFG), batch(seed=0))


def test_ulysses_gqa_divisibility_error(mesh3d, comms):
    comm_dp, comm_tp, comm_sp = comms
    with pytest.raises(ValueError, match="ulysses"):
        tfm.make_global_train_step(
            mesh3d, comm_dp, comm_tp, comm_sp, CFG, sequence="ulysses"
        )
