"""Pallas flash-attention kernel vs the dense oracle (interpret mode on
the CPU test platform; the same kernel compiles for TPU via Mosaic)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi4jax_tpu.ops.flash import flash_attention
from mpi4jax_tpu.parallel.longseq import local_attention


def _qkv(B, T, TK, H, D, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (
        jax.random.normal(ks[0], (B, T, H, D), dtype),
        jax.random.normal(ks[1], (B, TK, H, D), dtype),
        jax.random.normal(ks[2], (B, TK, H, D), dtype),
    )


CASES = [
    # B, Tq, Tk, H, D, causal, q_offset, k_offset
    (2, 128, 128, 4, 64, False, 0, 0),
    (1, 256, 256, 2, 64, True, 0, 0),  # triangle grid (square causal)
    (2, 100, 100, 3, 64, False, 0, 0),  # sequence padding path
    (1, 96, 160, 2, 32, True, 64, 0),  # ragged q/k + block offset
    (1, 64, 64, 1, 128, True, 128, 64),
    (1, 512, 512, 1, 64, True, 0, 0),  # triangle grid, 8x8 blocks (T=36)
]


@pytest.mark.parametrize("case", CASES)
def test_flash_matches_dense(case):
    B, T, TK, H, D, causal, qo, ko = case
    q, k, v = _qkv(B, T, TK, H, D)
    ref = local_attention(
        q, k, v, causal=causal, q_offset=qo, k_offset=ko, impl="xla"
    )
    out = flash_attention(
        q, k, v, causal=causal, q_offset=qo, k_offset=ko,
        block_q=64, block_k=64, interpret=True,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_fully_masked_rows():
    # causal block with q entirely before k: every row fully masked.
    # Convention matches the dense oracle (uniform weights over the
    # masked row -> mean of V), and stays finite.
    q, k, v = _qkv(1, 64, 64, 2, 64)
    ref = local_attention(q, k, v, causal=True, q_offset=0, k_offset=512, impl="xla")
    out = flash_attention(
        q, k, v, causal=True, q_offset=0, k_offset=512,
        block_q=32, block_k=32, interpret=True,
    )
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_bf16_io():
    q, k, v = _qkv(1, 128, 128, 2, 64, dtype=jnp.bfloat16)
    ref = local_attention(q, k, v, impl="xla")
    out = flash_attention(q, k, v, block_q=64, block_k=64, interpret=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=3e-2
    )


def test_local_attention_impl_dispatch():
    # "auto" resolves to the dense path on the CPU test platform and
    # must equal the explicit oracle
    q, k, v = _qkv(1, 128, 128, 2, 64)
    np.testing.assert_array_equal(
        np.asarray(local_attention(q, k, v)),
        np.asarray(local_attention(q, k, v, impl="xla")),
    )


def test_flash_fully_masked_rows_with_padding():
    # regression: with Tk not a multiple of block_k, fully-masked rows
    # must normalise over the REAL key count, not the padded one —
    # padded keys are -inf (excluded), causally-masked real keys are
    # the finite _NEG (uniform-weights convention)
    q, k, v = _qkv(1, 64, 100, 2, 64)
    ref = local_attention(
        q, k, v, causal=True, q_offset=0, k_offset=512, impl="xla"
    )
    out = flash_attention(
        q, k, v, causal=True, q_offset=0, k_offset=512,
        block_q=64, block_k=64, interpret=True,
    )
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("case", CASES)
def test_flash_grad_matches_dense(case):
    # flash has a custom VJP (blockwise dK/dV + dQ kernels): grads must
    # match the dense path over the SAME case matrix the forward tests
    # cover — padding, ragged Tq != Tk, and block offsets all take
    # distinct paths through the backward's masking/statistics
    B, T, TK, H, D, causal, qo, ko = case
    q, k, v = _qkv(B, T, TK, H, D)

    def loss_flash(q, k, v):
        out = flash_attention(
            q, k, v, causal=causal, q_offset=qo, k_offset=ko,
            block_q=64, block_k=64, interpret=True,
        )
        return (out * out).sum()

    def loss_dense(q, k, v):
        out = local_attention(
            q, k, v, causal=causal, q_offset=qo, k_offset=ko, impl="xla"
        )
        return (out * out).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4, rtol=1e-3,
            err_msg=f"d{name} {case}",
        )


def test_flash_grad_fully_masked_rows():
    # the review-caught regression: on fully-masked causal rows the
    # softmax weights are the uniform 1/n convention, and dV must see
    # 1/n — an m+log(l) fused residual loses log(n) against the huge
    # _NEG in float32 and inflates dV by exactly n
    q, k, v = _qkv(1, 64, 32, 2, 64)

    def loss(f):
        def inner(q, k, v):
            return f(q, k, v).sum()
        return inner

    flash_fn = lambda q, k, v: flash_attention(  # noqa: E731
        q, k, v, causal=True, q_offset=0, k_offset=512,
        block_q=32, block_k=32, interpret=True,
    )
    dense_fn = lambda q, k, v: local_attention(  # noqa: E731
        q, k, v, causal=True, q_offset=0, k_offset=512, impl="xla"
    )
    gf = jax.grad(loss(flash_fn), argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss(dense_fn), argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5, err_msg=f"d{name}"
        )
