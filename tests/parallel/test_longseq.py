"""Context-parallel attention vs a dense single-device oracle.

The reference has no sequence-parallel scheme to port (SURVEY §5.7);
these tests validate the two schemes assembled from its primitive set
(ring = sendrecv steps, Ulysses = alltoall reshard) against dense
attention on the gathered sequence, including causal masking and
reverse-mode gradients through the ring.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi4jax_tpu.parallel import (
    local_attention,
    ring_attention,
    ulysses_attention,
)

SIZE = 8
B, T_LOCAL, H, D = 2, 4, 8, 16
T = SIZE * T_LOCAL


def global_qkv(seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (B, T, H, D)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


def run_sharded(comm, fn, *arrays):
    spec = jax.P(None, comm.axes[0], None, None)
    shmapped = jax.shard_map(
        fn,
        mesh=comm.mesh,
        in_specs=(spec,) * len(arrays),
        out_specs=spec,
    )
    return jax.jit(shmapped)(*arrays)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(comm1d, causal):
    q, k, v = global_qkv()

    def fn(ql, kl, vl):
        out, _ = ring_attention(ql, kl, vl, comm1d, causal=causal)
        return out

    got = run_sharded(comm1d, fn, q, k, v)
    want = local_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_dense(comm1d, causal):
    q, k, v = global_qkv(seed=1)

    def fn(ql, kl, vl):
        out, _ = ulysses_attention(ql, kl, vl, comm1d, causal=causal)
        return out

    got = run_sharded(comm1d, fn, q, k, v)
    want = local_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_ring_attention_grad(comm1d):
    """Gradients flow backwards around the ring (sendrecv transpose)."""
    q, k, v = global_qkv(seed=2)
    w = jax.random.normal(jax.random.PRNGKey(9), (B, T, H, D))

    def loss_local(ql, kl, vl, wl):
        out, _ = ring_attention(ql, kl, vl, comm1d, causal=True)
        return (out * wl).sum()

    def loss_dense(q, k, v):
        return (local_attention(q, k, v, causal=True) * w).sum()

    spec = jax.P(None, comm1d.axes[0], None, None)

    def grad_fn(ql, kl, vl, wl):
        g = jax.grad(loss_local, argnums=(0, 1, 2))(ql, kl, vl, wl)
        return g

    shmapped = jax.shard_map(
        grad_fn,
        mesh=comm1d.mesh,
        in_specs=(spec,) * 4,
        out_specs=(spec,) * 3,
    )
    got = jax.jit(shmapped)(q, k, v, w)
    want = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for g, wv, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(wv), rtol=5e-4, atol=5e-4,
            err_msg=f"grad w.r.t. {name}",
        )


def test_ulysses_attention_grad(comm1d):
    q, k, v = global_qkv(seed=3)
    w = jax.random.normal(jax.random.PRNGKey(10), (B, T, H, D))

    def loss_local(ql, kl, vl, wl):
        out, _ = ulysses_attention(ql, kl, vl, comm1d, causal=True)
        return (out * wl).sum()

    def loss_dense(q, k, v):
        return (local_attention(q, k, v, causal=True) * w).sum()

    spec = jax.P(None, comm1d.axes[0], None, None)
    shmapped = jax.shard_map(
        lambda ql, kl, vl, wl: jax.grad(loss_local, argnums=(0, 1, 2))(
            ql, kl, vl, wl
        ),
        mesh=comm1d.mesh,
        in_specs=(spec,) * 4,
        out_specs=(spec,) * 3,
    )
    got = jax.jit(shmapped)(q, k, v, w)
    want = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for g, wv, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(wv), rtol=5e-4, atol=5e-4,
            err_msg=f"grad w.r.t. {name}",
        )


def test_ring_size_one_is_dense(selfcomm):
    q, k, v = global_qkv(seed=4)
    out, _ = ring_attention(q, k, v, selfcomm, causal=True)
    want = local_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), rtol=1e-6)


def test_ulysses_head_divisibility(comm1d):
    q = jnp.zeros((1, 4, 6, 8))  # 6 heads, ring of 8

    def fn(ql):
        out, _ = ulysses_attention(ql, ql, ql, comm1d)
        return out

    spec = jax.P(None, comm1d.axes[0], None, None)
    with pytest.raises(ValueError, match="divisible"):
        jax.jit(
            jax.shard_map(
                fn, mesh=comm1d.mesh, in_specs=(spec,), out_specs=spec
            )
        )(jnp.zeros((1, 32, 6, 8)))


def test_ring_requires_1d_comm(comm2d):
    q = jnp.zeros((1, 4, 4, 8))
    with pytest.raises(ValueError, match="1-D communicator"):
        spec = jax.P(None, "y", None, None)
        jax.jit(
            jax.shard_map(
                lambda ql: ring_attention(ql, ql, ql, comm2d)[0],
                mesh=comm2d.mesh,
                in_specs=(spec,),
                out_specs=spec,
            )
        )(jnp.zeros((1, 8, 4, 8)))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_zigzag_matches_dense(comm1d, causal):
    """Zigzag (balanced-causal) layout: shard the zigzag-reordered
    sequence, run the ring, un-reorder — must equal dense attention on
    the original order."""
    from mpi4jax_tpu.parallel import zigzag_shard, zigzag_unshard

    q, k, v = global_qkv(seed=3)

    def fn(ql, kl, vl):
        out, _ = ring_attention(
            ql, kl, vl, comm1d, causal=causal, layout="zigzag"
        )
        return out

    got = run_sharded(
        comm1d,
        fn,
        zigzag_shard(q, SIZE),
        zigzag_shard(k, SIZE),
        zigzag_shard(v, SIZE),
    )
    got = zigzag_unshard(got, SIZE)
    want = local_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_ring_attention_zigzag_grads(comm1d):
    from mpi4jax_tpu.parallel import zigzag_shard, zigzag_unshard

    q, k, v = global_qkv(seed=4)

    def sharded_loss(ql, kl, vl):
        out, _ = ring_attention(
            ql, kl, vl, comm1d, causal=True, layout="zigzag"
        )
        return out

    def loss_ring(qz, kz, vz):
        spec = jax.P(None, comm1d.axes[0], None, None)
        out = jax.shard_map(
            sharded_loss, mesh=comm1d.mesh,
            in_specs=(spec,) * 3, out_specs=spec,
        )(qz, kz, vz)
        return (out * out).sum()

    def loss_dense(qq, kk, vv):
        out = local_attention(qq, kk, vv, causal=True, impl="xla")
        return (out * out).sum()

    gq_z, gk_z, gv_z = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(
        zigzag_shard(q, SIZE), zigzag_shard(k, SIZE), zigzag_shard(v, SIZE)
    )
    gq, gk, gv = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for got_z, want in ((gq_z, gq), (gk_z, gk), (gv_z, gv)):
        got = zigzag_unshard(got_z, SIZE)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=3e-4, atol=3e-4
        )


def test_zigzag_shard_roundtrip():
    from mpi4jax_tpu.parallel import zigzag_shard, zigzag_unshard, zigzag_indices

    x = jnp.arange(32.0)[None, :, None, None]
    z = zigzag_shard(x, 4)
    assert np.array_equal(np.asarray(zigzag_unshard(z, 4)), np.asarray(x))
    idx = zigzag_indices(4, 32)
    assert idx.shape == (4, 8)
    # rank 0 holds the first and last chunk
    assert list(idx[0]) == list(range(0, 4)) + list(range(28, 32))


def test_zigzag_requires_divisibility():
    from mpi4jax_tpu.parallel import zigzag_indices

    with pytest.raises(ValueError, match="divisible"):
        zigzag_indices(4, 30)
