"""ZeRO-1-style sharded-optimizer train step
(models/train.py:make_global_zero_train_step): the reduce_scatter
gradient-sharding pattern validated against the plain allreduce step and
a dense single-device momentum oracle.
"""

import jax
import jax.numpy as jnp
import numpy as np

import mpi4jax_tpu as m
from mpi4jax_tpu.models import train as tr


def _auto(n):
    return (jax.sharding.AxisType.Auto,) * n


def _setup(dp_n=2, tp_n=4, d_in=8, d_hid=32, d_out=4, batch=16):
    mesh = jax.make_mesh((dp_n, tp_n), ("dp", "tp"), axis_types=_auto(2))
    comm = m.MeshComm.from_mesh(mesh)
    dp, tp = comm.sub("dp"), comm.sub("tp")
    params = tr.init_params(jax.random.PRNGKey(0), d_in, d_hid, d_out, tp_size=tp_n)
    x = jax.random.normal(jax.random.PRNGKey(1), (batch, d_in))
    t = x @ jax.random.normal(jax.random.PRNGKey(2), (d_in, d_out))
    return mesh, dp, tp, params, (x, t)


def _dense_grads(params, batch):
    """Oracle: gradient of the global mean loss with full weights."""
    x, t = batch

    def loss(p):
        y = jax.nn.relu(x @ p.w1 + p.b1) @ p.w2 + p.b2
        return jnp.mean((y - t) ** 2)

    return jax.grad(loss)(params)


def test_zero_momentum0_equals_plain_step():
    mesh, dp, tp, params, batch = _setup()
    plain = tr.make_global_train_step(mesh, dp, tp, lr=5e-2)
    zstep, zinit = tr.make_global_zero_train_step(
        mesh, dp, tp, lr=5e-2, momentum=0.0
    )
    p_plain, _ = plain(params, batch)
    p_zero, _, _ = zstep(params, zinit(params), batch)
    for a, b in zip(p_plain, p_zero):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_zero_momentum_matches_dense_oracle():
    mesh, dp, tp, params, batch = _setup()
    mu, lr = 0.9, 5e-2
    zstep, zinit = tr.make_global_zero_train_step(
        mesh, dp, tp, lr=lr, momentum=mu
    )
    state = zinit(params)

    # dense momentum-SGD oracle, two steps
    ref = params
    v = jax.tree.map(jnp.zeros_like, ref)
    for _ in range(2):
        g = _dense_grads(ref, batch)
        v = jax.tree.map(lambda vi, gi: mu * vi + gi, v, g)
        ref = jax.tree.map(lambda pi, vi: pi - lr * vi, ref, v)

    p = params
    for _ in range(2):
        p, state, _loss = zstep(p, state, batch)

    for name, a, b in zip(ref._fields, ref, p):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, err_msg=name
        )


def test_zero_state_is_sharded():
    dp_n, tp_n = 2, 4
    mesh, dp, tp, params, batch = _setup(dp_n, tp_n)
    zstep, zinit = tr.make_global_zero_train_step(mesh, dp, tp)
    state = zinit(params)
    for p, v, local_n in zip(
        params,
        state,
        # local (per-device) parameter sizes: tp-sharded except b2
        [
            params.w1.size // tp_n,
            params.b1.size // tp_n,
            params.w2.size // tp_n,
            params.b2.size,
        ],
    ):
        chunk = -(-local_n // dp_n)
        assert v.shape == (dp_n, tp_n * chunk)
        # each device stores 1/dp of its local parameter count (+pad)
        shard = v.sharding.shard_shape(v.shape)
        assert shard == (1, chunk)

    # and it learns
    first = None
    for _ in range(40):
        params, state, loss = zstep(params, state, batch)
        if first is None:
            first = float(np.asarray(loss)[0])
    assert float(np.asarray(loss)[0]) < 0.3 * first
