"""Greedy autoregressive decoding with a TP-sharded KV cache
(models/transformer.py:make_global_decode) vs the unsharded
full-recompute oracle: generated token sequences must match exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as m
from mpi4jax_tpu.models import transformer as tfm

CFG = tfm.TransformerConfig(
    vocab=32, d_model=16, layers=2, heads=4, kv_heads=2, head_dim=8, d_ff=32
)
B, P, MAX = 4, 5, 14


@pytest.fixture(scope="module")
def mesh2d():
    # tp=2 so the GQA kv_heads=2 divide; dp=4 batches
    return jax.make_mesh(
        (4, 2), ("dp", "tp"), axis_types=(jax.sharding.AxisType.Auto,) * 2
    )


@pytest.fixture(scope="module")
def comms(mesh2d):
    world = m.MeshComm.from_mesh(mesh2d)
    return world.sub("dp"), world.sub("tp")


@pytest.mark.parametrize("prefill", ["batched", "stepwise"])
def test_decode_matches_oracle(mesh2d, comms, prefill):
    comm_dp, comm_tp = comms
    params = tfm.init_params(jax.random.PRNGKey(1), CFG)
    prompt = jax.random.randint(jax.random.PRNGKey(2), (B, P), 0, CFG.vocab)

    decode = tfm.make_global_decode(
        mesh2d, comm_dp, comm_tp, CFG, MAX, prefill=prefill
    )
    got = decode(params, prompt)

    want = tfm.reference_greedy_decode(params, prompt, CFG, MAX)
    got, want = np.asarray(got), np.asarray(want)
    # the prompt must be echoed verbatim
    np.testing.assert_array_equal(got[:, :P], np.asarray(prompt))
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("prefill", ["batched", "stepwise"])
@pytest.mark.parametrize("bucket", [4, 5, 14])
def test_decode_kv_bucket_matches_oracle(mesh2d, comms, prefill, bucket):
    # bucketed KV growth (scan carry = a cache view growing by static
    # buckets) is token-exact vs the oracle — including a bucket that
    # does not divide max_len (ragged last segment) and bucket ==
    # max_len (degenerates to the un-bucketed loop)
    comm_dp, comm_tp = comms
    params = tfm.init_params(jax.random.PRNGKey(1), CFG)
    prompt = jax.random.randint(jax.random.PRNGKey(2), (B, P), 0, CFG.vocab)
    decode = tfm.make_global_decode(
        mesh2d, comm_dp, comm_tp, CFG, MAX, prefill=prefill,
        kv_bucket=bucket,
    )
    got = decode(params, prompt)
    want = tfm.reference_greedy_decode(params, prompt, CFG, MAX)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.skipif(
    jax.default_backend() not in ("tpu", "axon"),
    reason="flash prefill needs the compiled Pallas kernel (interpret "
    "mode inside shard_map trips jax's vma checking); the on-chip "
    "equivalence was measured at prompt 256 (token-identical) and the "
    "capability point at prompt 8192 (dense prefill cannot compile) — "
    "docs/performance.md",
)
def test_decode_flash_prefill_matches_oracle(mesh2d, comms):
    # prefill_impl="flash" (the long-prompt prefill kernel) produces
    # the identical token sequence — on the real chip it decodes at
    # prompt 8192 where the dense prefill's [P, P] scores cannot even
    # compile (docs/performance.md)
    comm_dp, comm_tp = comms
    params = tfm.init_params(jax.random.PRNGKey(1), CFG)
    prompt = jax.random.randint(jax.random.PRNGKey(2), (B, P), 0, CFG.vocab)
    decode = tfm.make_global_decode(
        mesh2d, comm_dp, comm_tp, CFG, MAX, prefill_impl="flash"
    )
    got = decode(params, prompt)
    want = tfm.reference_greedy_decode(params, prompt, CFG, MAX)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.parametrize("top_k", [None, 3])
@pytest.mark.parametrize("prefill", ["batched", "stepwise"])
def test_decode_sampling_matches_oracle(mesh2d, comms, prefill, top_k):
    # categorical sampling: the per-row key folds in position and
    # GLOBAL row id, so the dp/tp-sharded sampler must match the
    # unsharded oracle bitwise given the same key — with and without
    # top-k truncation
    comm_dp, comm_tp = comms
    params = tfm.init_params(jax.random.PRNGKey(1), CFG)
    prompt = jax.random.randint(jax.random.PRNGKey(2), (B, P), 0, CFG.vocab)
    key = jax.random.PRNGKey(42)
    decode = tfm.make_global_decode(
        mesh2d, comm_dp, comm_tp, CFG, MAX, prefill=prefill,
        sampler="categorical", temperature=0.8, top_k=top_k,
    )
    got = np.asarray(decode(params, prompt, key))
    want = np.asarray(
        tfm.reference_sample_decode(
            params, prompt, CFG, MAX, key, temperature=0.8, top_k=top_k
        )
    )
    np.testing.assert_array_equal(got[:, :P], np.asarray(prompt))
    np.testing.assert_array_equal(got, want)


def test_decode_sampling_key_sensitivity(mesh2d, comms):
    # different keys must (for this config) give different sequences,
    # and the same key must reproduce bitwise
    comm_dp, comm_tp = comms
    params = tfm.init_params(jax.random.PRNGKey(1), CFG)
    prompt = jax.random.randint(jax.random.PRNGKey(2), (B, P), 0, CFG.vocab)
    decode = tfm.make_global_decode(
        mesh2d, comm_dp, comm_tp, CFG, MAX, sampler="categorical",
        temperature=2.0,
    )
    a = np.asarray(decode(params, prompt, jax.random.PRNGKey(0)))
    a2 = np.asarray(decode(params, prompt, jax.random.PRNGKey(0)))
    b = np.asarray(decode(params, prompt, jax.random.PRNGKey(7)))
    np.testing.assert_array_equal(a, a2)
    assert (a != b).any(), "distinct keys produced identical sequences"


def test_decode_sampler_validation(mesh2d, comms):
    comm_dp, comm_tp = comms
    with pytest.raises(ValueError, match="sampler"):
        tfm.make_global_decode(
            mesh2d, comm_dp, comm_tp, CFG, MAX, sampler="beam"
        )
    with pytest.raises(ValueError, match="temperature"):
        tfm.make_global_decode(
            mesh2d, comm_dp, comm_tp, CFG, MAX, sampler="categorical",
            temperature=0.0,
        )
    with pytest.raises(ValueError, match="top_k"):
        tfm.make_global_decode(
            mesh2d, comm_dp, comm_tp, CFG, MAX, sampler="categorical",
            top_k=CFG.vocab + 1,
        )


def test_decode_kv_bucket_validation(mesh2d, comms):
    comm_dp, comm_tp = comms
    with pytest.raises(ValueError, match="kv_bucket"):
        tfm.make_global_decode(
            mesh2d, comm_dp, comm_tp, CFG, MAX, kv_bucket=0
        )
    with pytest.raises(ValueError, match="kv_bucket"):
        tfm.make_global_decode(
            mesh2d, comm_dp, comm_tp, CFG, MAX, kv_bucket=MAX + 1
        )


@pytest.mark.parametrize("prefill", ["batched", "stepwise"])
def test_decode_prompt_only_roundtrip(mesh2d, comms, prefill):
    # max_len == prompt length: nothing generated, prompt returned
    comm_dp, comm_tp = comms
    params = tfm.init_params(jax.random.PRNGKey(3), CFG)
    prompt = jax.random.randint(jax.random.PRNGKey(4), (B, 6), 0, CFG.vocab)
    decode = tfm.make_global_decode(
        mesh2d, comm_dp, comm_tp, CFG, 6, prefill=prefill
    )
    out = decode(params, prompt)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(prompt))


def test_decode_single_token_prompt(mesh2d, comms):
    # p_len == 1: the batched path degrades to stepwise (a 1-token
    # prefill IS one step); both must match the oracle
    comm_dp, comm_tp = comms
    params = tfm.init_params(jax.random.PRNGKey(9), CFG)
    prompt = jax.random.randint(jax.random.PRNGKey(10), (B, 1), 0, CFG.vocab)
    decode = tfm.make_global_decode(mesh2d, comm_dp, comm_tp, CFG, 8)
    want = tfm.reference_greedy_decode(params, prompt, CFG, 8)
    np.testing.assert_array_equal(
        np.asarray(decode(params, prompt)), np.asarray(want)
    )


def test_decode_prompt_longer_than_budget_errors(mesh2d, comms):
    comm_dp, comm_tp = comms
    params = tfm.init_params(jax.random.PRNGKey(7), CFG)
    prompt = jax.random.randint(jax.random.PRNGKey(8), (B, 9), 0, CFG.vocab)
    decode = tfm.make_global_decode(mesh2d, comm_dp, comm_tp, CFG, 8)
    with pytest.raises(ValueError, match="exceeds max_len"):
        decode(params, prompt)


def test_decode_deterministic_across_meshes(comms, mesh2d):
    # tp=2 (the mesh2d fixture's tp extent) vs tp=1: same greedy
    # sequence (collective roundoff must not flip the argmax at these
    # scales/seeds)
    comm_dp, comm_tp = comms
    params = tfm.init_params(jax.random.PRNGKey(5), CFG)
    prompt = jax.random.randint(jax.random.PRNGKey(6), (B, P), 0, CFG.vocab)
    d4 = tfm.make_global_decode(mesh2d, comm_dp, comm_tp, CFG, MAX)
    mesh1 = jax.make_mesh(
        (1, 1), ("dp", "tp"), axis_types=(jax.sharding.AxisType.Auto,) * 2
    )
    w1 = m.MeshComm.from_mesh(mesh1)
    d1 = tfm.make_global_decode(mesh1, w1.sub("dp"), w1.sub("tp"), CFG, MAX)
    np.testing.assert_array_equal(
        np.asarray(d4(params, prompt)), np.asarray(d1(params, prompt))
    )
