"""Grouped-query attention (Hkv < Hq) across the attention stack: the
dense path, the flash kernel, and both context-parallel schemes, all
against a kv-head-repeated MHA oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi4jax_tpu.parallel import (
    local_attention,
    ring_attention,
    ulysses_attention,
    zigzag_shard,
    zigzag_unshard,
)

SIZE = 8
B, T, HQ, HK, D = 2, 32, 8, 2, 16


def gqa_qkv(seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, T, HQ, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, T, HK, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, T, HK, D), jnp.float32)
    return q, k, v


def oracle(q, k, v, causal):
    g = q.shape[2] // k.shape[2]
    return local_attention(
        q, jnp.repeat(k, g, axis=2), jnp.repeat(v, g, axis=2),
        causal=causal, impl="xla",
    )


from tests.parallel.test_longseq import run_sharded  # shared harness


@pytest.mark.parametrize("causal", [False, True])
def test_local_gqa_matches_repeated_mha(causal):
    q, k, v = gqa_qkv()
    got = local_attention(q, k, v, causal=causal, impl="xla")
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(oracle(q, k, v, causal)),
        rtol=2e-5, atol=2e-5,
    )


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gqa_matches_dense(causal):
    from mpi4jax_tpu.ops.flash import flash_attention

    q, k, v = gqa_qkv(seed=1)
    got = flash_attention(q, k, v, causal=causal, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(oracle(q, k, v, causal)),
        rtol=2e-5, atol=2e-5,
    )


def test_flash_gqa_grads():
    from mpi4jax_tpu.ops.flash import flash_attention

    q, k, v = gqa_qkv(seed=2)

    def loss_flash(q_, k_, v_):
        return (flash_attention(q_, k_, v_, causal=True, interpret=True) ** 2).sum()

    def loss_dense(q_, k_, v_):
        return (oracle(q_, k_, v_, True) ** 2).sum()

    got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for g_, w_ in zip(got, want):
        assert g_.shape == w_.shape  # kv grads keep the Hkv head count
        np.testing.assert_allclose(
            np.asarray(g_), np.asarray(w_), rtol=3e-4, atol=3e-4
        )


@pytest.mark.parametrize("layout", ["contiguous", "zigzag"])
@pytest.mark.parametrize("causal", [False, True])
def test_ring_gqa_matches_dense(comm1d, causal, layout):
    q, k, v = gqa_qkv(seed=3)

    def fn(ql, kl, vl):
        out, _ = ring_attention(
            ql, kl, vl, comm1d, causal=causal, layout=layout
        )
        return out

    if layout == "zigzag":
        got = run_sharded(
            comm1d, fn,
            zigzag_shard(q, SIZE), zigzag_shard(k, SIZE), zigzag_shard(v, SIZE),
        )
        got = zigzag_unshard(got, SIZE)
    else:
        got = run_sharded(comm1d, fn, q, k, v)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(oracle(q, k, v, causal)),
        rtol=2e-5, atol=2e-5,
    )


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_gqa_matches_dense(comm1d, causal):
    # HK = 8 here: kv heads must divide the ring size on ulysses
    ks = jax.random.split(jax.random.PRNGKey(5), 3)
    q = jax.random.normal(ks[0], (B, T, 16, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, T, 8, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, T, 8, D), jnp.float32)

    def fn(ql, kl, vl):
        out, _ = ulysses_attention(ql, kl, vl, comm1d, causal=causal)
        return out

    got = run_sharded(comm1d, fn, q, k, v)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(oracle(q, k, v, causal)),
        rtol=2e-5, atol=2e-5,
    )


def test_ulysses_gqa_kv_heads_guidance(comm1d):
    q, k, v = gqa_qkv()  # HK=2 < SIZE=8

    def fn(ql, kl, vl):
        out, _ = ulysses_attention(ql, kl, vl, comm1d)
        return out

    with pytest.raises(ValueError, match="repeat kv"):
        run_sharded(comm1d, fn, q, k, v)


def test_gqa_head_mismatch_raises():
    q, k, v = gqa_qkv()
    with pytest.raises(ValueError, match="multiple of kv heads"):
        local_attention(q, k[:, :, :1].repeat(3, axis=2), v, impl="xla")
