"""The MoE (expert-parallel) transformer train step vs an unsharded
oracle: dp×tp×sp mesh where sp doubles as the expert axis — local
expert-choice routing, alltoall dispatch/combine, ring attention, TP
f/g, DP sync, one SGD step against identical math on one device."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as m
from mpi4jax_tpu.models import moe_transformer as moe

CFG = moe.MoEConfig(
    vocab=32, d_model=16, layers=2, heads=4, kv_heads=2, head_dim=8,
    experts=4, d_ff=32,
)
B, S = 4, 16
DP, TP, SP = 2, 2, 2


@pytest.fixture(scope="module")
def mesh3d():
    return jax.make_mesh(
        (DP, TP, SP),
        ("dp", "tp", "sp"),
        axis_types=(jax.sharding.AxisType.Auto,) * 3,
    )


@pytest.fixture(scope="module")
def comms(mesh3d):
    world = m.MeshComm.from_mesh(mesh3d)
    return world.sub("dp"), world.sub("tp"), world.sub("sp")


def batch(seed=0):
    kt = jax.random.PRNGKey(seed)
    tokens = jax.random.randint(kt, (B, S), 0, CFG.vocab)
    targets = jnp.roll(tokens, -1, axis=1)
    return tokens, targets


@pytest.mark.parametrize("routing", ["expert_choice", "topk"])
def test_moe_train_step_matches_oracle(mesh3d, comms, routing):
    cfg = CFG._replace(routing=routing)
    comm_dp, comm_tp, comm_sp = comms
    params = moe.init_params(jax.random.PRNGKey(1), cfg)
    tokens, targets = batch()

    step = moe.make_global_train_step(
        mesh3d, comm_dp, comm_tp, comm_sp, cfg, lr=1e-1
    )
    new_params, loss = step(params, (tokens, targets))

    ref_loss, ref_grads = jax.value_and_grad(
        lambda p: moe.reference_loss(p, tokens, targets, cfg, DP, SP)
    )(params)
    ref_new = jax.tree.map(lambda p, g: p - 1e-1 * g, params, ref_grads)

    np.testing.assert_allclose(
        float(np.asarray(loss)[0]), float(ref_loss), rtol=2e-5, atol=2e-5
    )
    names = [
        "embed", "ln1", "wq", "wk", "wv", "wo", "ln2", "wr", "w1e",
        "w2e", "ln_f", "head",
    ]
    for name, got, want in zip(
        names, jax.tree.leaves(new_params), jax.tree.leaves(ref_new)
    ):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=3e-4, atol=3e-4,
            err_msg=name,
        )


def test_moe_loss_decreases(mesh3d, comms):
    comm_dp, comm_tp, comm_sp = comms
    params = moe.init_params(jax.random.PRNGKey(2), CFG)
    tokens, targets = batch(seed=3)
    step = moe.make_global_train_step(
        mesh3d, comm_dp, comm_tp, comm_sp, CFG, lr=3e-1
    )
    losses = []
    for _ in range(8):
        params, loss = step(params, (tokens, targets))
        losses.append(float(np.asarray(loss)[0]))
    assert losses[-1] < losses[0] * 0.8, losses
    assert np.isfinite(losses).all()


def test_moe_remat_matches_plain(mesh3d, comms):
    # the MoE sublayer's alltoall pair must also replay correctly under
    # jax.checkpoint
    comm_dp, comm_tp, comm_sp = comms
    params = moe.init_params(jax.random.PRNGKey(11), CFG)
    tokens, targets = batch(seed=12)
    plain = moe.make_global_train_step(
        mesh3d, comm_dp, comm_tp, comm_sp, CFG, lr=1e-1
    )
    rstep = moe.make_global_train_step(
        mesh3d, comm_dp, comm_tp, comm_sp, CFG, lr=1e-1, remat=True
    )
    p1, l1 = plain(params, (tokens, targets))
    p2, l2 = rstep(params, (tokens, targets))
    np.testing.assert_allclose(
        float(np.asarray(l1)[0]), float(np.asarray(l2)[0]), rtol=1e-6
    )
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2), strict=True):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        )


def test_moe_experts_divisibility(mesh3d, comms):
    comm_dp, comm_tp, comm_sp = comms
    with pytest.raises(ValueError, match="divisible by the expert"):
        moe.make_global_train_step(
            mesh3d, comm_dp, comm_tp, comm_sp, CFG._replace(experts=3)
        )


def test_moe_token_capacity_check(mesh3d, comms):
    # per-device token count not divisible by experts -> curated error
    comm_dp, comm_tp, comm_sp = comms
    cfg = CFG._replace(experts=SP * 3)  # 6 experts, T_local=16 not div.
    step = moe.make_global_train_step(
        mesh3d, comm_dp, comm_tp, comm_sp, cfg
    )
    with pytest.raises(ValueError, match="divisible by experts"):
        step(moe.init_params(jax.random.PRNGKey(0), cfg), batch())


def test_route_local_selects_top_capacity():
    key = jax.random.PRNGKey(5)
    xt = jax.random.normal(key, (8, 4))
    wr = jax.random.normal(jax.random.PRNGKey(6), (4, 2))
    gates, idx = moe._route_local(xt, wr, 2)
    assert gates.shape == (2, 4) and idx.shape == (2, 4)
    probs = jax.nn.softmax(xt @ wr, axis=-1)
    for e in range(2):
        # each expert's picks are its top-capacity local tokens
        want = np.argsort(-np.asarray(probs[:, e]))[:4]
        assert set(np.asarray(idx[e]).tolist()) == set(want.tolist())


def test_combine_gate_weighted_sum_and_unpicked_zero():
    # combine semantics through the real _moe_ffn dispatch path (ep=1
    # via SelfComm): a token picked by k experts receives the sum of
    # the k gate-weighted expert outputs; an unpicked token gets zero
    cfg = moe.MoEConfig(d_model=4, experts=2, d_ff=8)
    comm = m.SelfComm()
    b, s, d = 1, 8, 4
    h = jax.random.normal(jax.random.PRNGKey(7), (b, s, d))
    wr = jax.random.normal(jax.random.PRNGKey(8), (d, 2))
    w1e = jax.random.normal(jax.random.PRNGKey(9), (2, d, 8))
    w2e = jax.random.normal(jax.random.PRNGKey(10), (2, 8, d))

    out, _tok = moe._moe_ffn(h, wr, w1e, w2e, cfg, comm, None)

    # numpy loop oracle
    xt = np.asarray(h).reshape(s, d)
    probs = np.asarray(jax.nn.softmax(jnp.asarray(xt) @ wr, axis=-1))
    expected = np.zeros_like(xt)
    picked = set()
    for e in range(2):
        top = np.argsort(-probs[:, e], kind="stable")[:4]
        for t in top:
            picked.add(int(t))
            hmid = np.asarray(jax.nn.gelu(jnp.asarray(xt[t]) @ w1e[e]))
            expected[t] += probs[t, e] * (hmid @ np.asarray(w2e[e]))
    np.testing.assert_allclose(
        np.asarray(out).reshape(s, d), expected, rtol=1e-5, atol=1e-5
    )
    unpicked = [t for t in range(s) if t not in picked]
    for t in unpicked:
        np.testing.assert_array_equal(np.asarray(out).reshape(s, d)[t], 0.0)
