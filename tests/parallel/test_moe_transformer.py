"""The MoE (expert-parallel) transformer train step vs an unsharded
oracle: dp×tp×sp mesh where sp doubles as the expert axis — local
expert-choice routing, alltoall dispatch/combine, ring attention, TP
f/g, DP sync, one SGD step against identical math on one device."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as m
from mpi4jax_tpu.models import moe_transformer as moe

CFG = moe.MoEConfig(
    vocab=32, d_model=16, layers=2, heads=4, kv_heads=2, head_dim=8,
    experts=4, d_ff=32,
)
B, S = 4, 16
DP, TP, SP = 2, 2, 2


@pytest.fixture(scope="module")
def mesh3d():
    return jax.make_mesh(
        (DP, TP, SP),
        ("dp", "tp", "sp"),
        axis_types=(jax.sharding.AxisType.Auto,) * 3,
    )


@pytest.fixture(scope="module")
def comms(mesh3d):
    world = m.MeshComm.from_mesh(mesh3d)
    return world.sub("dp"), world.sub("tp"), world.sub("sp")


def batch(seed=0):
    kt = jax.random.PRNGKey(seed)
    tokens = jax.random.randint(kt, (B, S), 0, CFG.vocab)
    targets = jnp.roll(tokens, -1, axis=1)
    return tokens, targets


@pytest.mark.parametrize("routing", ["expert_choice", "topk"])
def test_moe_train_step_matches_oracle(mesh3d, comms, routing):
    cfg = CFG._replace(routing=routing)
    comm_dp, comm_tp, comm_sp = comms
    params = moe.init_params(jax.random.PRNGKey(1), cfg)
    tokens, targets = batch()

    step = moe.make_global_train_step(
        mesh3d, comm_dp, comm_tp, comm_sp, cfg, lr=1e-1
    )
    new_params, loss = step(params, (tokens, targets))

    ref_loss, ref_grads = jax.value_and_grad(
        lambda p: moe.reference_loss(p, tokens, targets, cfg, DP, SP)
    )(params)
    ref_new = jax.tree.map(lambda p, g: p - 1e-1 * g, params, ref_grads)

    np.testing.assert_allclose(
        float(np.asarray(loss)[0]), float(ref_loss), rtol=2e-5, atol=2e-5
    )
    names = [
        "embed", "ln1", "wq", "wk", "wv", "wo", "ln2", "wr", "w1e",
        "w2e", "ln_f", "head",
    ]
    for name, got, want in zip(
        names, jax.tree.leaves(new_params), jax.tree.leaves(ref_new)
    ):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=3e-4, atol=3e-4,
            err_msg=name,
        )


def test_moe_loss_decreases(mesh3d, comms):
    comm_dp, comm_tp, comm_sp = comms
    params = moe.init_params(jax.random.PRNGKey(2), CFG)
    tokens, targets = batch(seed=3)
    step = moe.make_global_train_step(
        mesh3d, comm_dp, comm_tp, comm_sp, CFG, lr=3e-1
    )
    losses = []
    for _ in range(8):
        params, loss = step(params, (tokens, targets))
        losses.append(float(np.asarray(loss)[0]))
    assert losses[-1] < losses[0] * 0.8, losses
    assert np.isfinite(losses).all()


def test_moe_remat_matches_plain(mesh3d, comms):
    # the MoE sublayer's alltoall pair must also replay correctly under
    # jax.checkpoint
    comm_dp, comm_tp, comm_sp = comms
    params = moe.init_params(jax.random.PRNGKey(11), CFG)
    tokens, targets = batch(seed=12)
    plain = moe.make_global_train_step(
        mesh3d, comm_dp, comm_tp, comm_sp, CFG, lr=1e-1
    )
    rstep = moe.make_global_train_step(
        mesh3d, comm_dp, comm_tp, comm_sp, CFG, lr=1e-1, remat=True
    )
    p1, l1 = plain(params, (tokens, targets))
    p2, l2 = rstep(params, (tokens, targets))
    np.testing.assert_allclose(
        float(np.asarray(l1)[0]), float(np.asarray(l2)[0]), rtol=1e-6
    )
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2), strict=True):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        )


def test_moe_aux_losses_match_oracle(mesh3d, comms):
    """With the Switch balance loss and router z-loss enabled, the
    sharded step's total loss (CE + mean-over-blocks aux) must still
    match the unsharded oracle — pinning the aux scaling through the
    psum/(n_data·tp) reduction."""
    cfg = CFG._replace(routing="topk", aux_weight=0.02, z_weight=1e-3)
    comm_dp, comm_tp, comm_sp = comms
    params = moe.init_params(jax.random.PRNGKey(21), cfg)
    tokens, targets = batch(seed=22)

    step = moe.make_global_train_step(
        mesh3d, comm_dp, comm_tp, comm_sp, cfg, lr=1e-1
    )
    new_params, loss = step(params, (tokens, targets))

    ref_loss, ref_grads = jax.value_and_grad(
        lambda p: moe.reference_loss(p, tokens, targets, cfg, DP, SP)
    )(params)
    np.testing.assert_allclose(
        float(np.asarray(loss)[0]), float(ref_loss), rtol=2e-5, atol=2e-5
    )
    # aux must actually contribute: the same batch without aux gives a
    # strictly different loss
    plain = moe.reference_loss(
        params, tokens, targets, cfg._replace(aux_weight=0.0, z_weight=0.0),
        DP, SP,
    )
    assert abs(float(ref_loss) - float(plain)) > 1e-6
    # and the router still receives finite, nonzero gradients
    g_wr = np.asarray(ref_grads.blocks.wr)
    assert np.isfinite(g_wr).all() and np.abs(g_wr).max() > 0
    ref_new = jax.tree.map(lambda p, g: p - 1e-1 * g, params, ref_grads)
    for got, want in zip(
        jax.tree.leaves(new_params), jax.tree.leaves(ref_new), strict=True
    ):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=3e-4, atol=3e-4
        )


def test_aux_loss_training_reduces_imbalance():
    """The VERDICT-named routing-quality property: starting from a
    router skewed hard toward expert 0, training WITH the balance loss
    must end up measurably more balanced (and dropping fewer tokens)
    than the identical run WITHOUT it.  Uses the unsharded oracle loss
    (the mesh step matches it exactly — tests above), so the comparison
    is deterministic and fast."""
    cfg0 = CFG._replace(routing="topk", router_k=2)
    params = moe.init_params(jax.random.PRNGKey(31), cfg0)
    # skew: amplified router weights saturate the softmax, giving a
    # genuinely imbalanced, token-dropping, large-logit starting point
    params = params._replace(
        blocks=params.blocks._replace(wr=params.blocks.wr * 8.0)
    )
    tokens, targets = batch(seed=32)
    report0 = moe.routing_report(params, tokens, cfg0, DP, SP)
    assert report0["balance_loss"] > 1.3  # measurably imbalanced start
    assert report0["dropped_fraction"] > 0.2
    assert report0["z_loss"] > 50.0

    def train(cfg, steps=25, lr=0.3):
        p = params
        grad = jax.jit(jax.grad(
            lambda p: moe.reference_loss(p, tokens, targets, cfg, DP, SP)
        ))
        for _ in range(steps):
            p = jax.tree.map(lambda w, g: w - lr * g, p, grad(p))
        return p

    p_aux = train(cfg0._replace(aux_weight=0.05, z_weight=1e-3))
    p_plain = train(cfg0)
    r_aux = moe.routing_report(p_aux, tokens, cfg0, DP, SP)
    r_plain = moe.routing_report(p_plain, tokens, cfg0, DP, SP)
    assert r_aux["balance_loss"] < r_plain["balance_loss"]
    assert r_aux["balance_loss"] < report0["balance_loss"]
    assert r_aux["dropped_fraction"] < r_plain["dropped_fraction"]
    assert r_aux["z_loss"] < r_plain["z_loss"]  # z-loss shrinks logits
    # load is a proper distribution either way
    np.testing.assert_allclose(np.asarray(r_aux["load"]).sum(), 1.0, rtol=1e-5)


def test_routing_report_refuses_expert_choice():
    params = moe.init_params(jax.random.PRNGKey(41), CFG)
    with pytest.raises(ValueError, match="balanced by construction"):
        moe.routing_report(params, batch()[0], CFG, DP, SP)


def test_moe_experts_divisibility(mesh3d, comms):
    comm_dp, comm_tp, comm_sp = comms
    with pytest.raises(ValueError, match="divisible by the expert"):
        moe.make_global_train_step(
            mesh3d, comm_dp, comm_tp, comm_sp, CFG._replace(experts=3)
        )


def test_moe_token_capacity_check(mesh3d, comms):
    # per-device token count not divisible by experts -> curated error
    comm_dp, comm_tp, comm_sp = comms
    cfg = CFG._replace(experts=SP * 3)  # 6 experts, T_local=16 not div.
    step = moe.make_global_train_step(
        mesh3d, comm_dp, comm_tp, comm_sp, cfg
    )
    with pytest.raises(ValueError, match="divisible by experts"):
        step(moe.init_params(jax.random.PRNGKey(0), cfg), batch())


def test_route_local_selects_top_capacity():
    key = jax.random.PRNGKey(5)
    xt = jax.random.normal(key, (8, 4))
    wr = jax.random.normal(jax.random.PRNGKey(6), (4, 2))
    gates, idx = moe._route_local(xt @ wr, 2)
    assert gates.shape == (2, 4) and idx.shape == (2, 4)
    probs = jax.nn.softmax(xt @ wr, axis=-1)
    for e in range(2):
        # each expert's picks are its top-capacity local tokens
        want = np.argsort(-np.asarray(probs[:, e]))[:4]
        assert set(np.asarray(idx[e]).tolist()) == set(want.tolist())


def test_combine_gate_weighted_sum_and_unpicked_zero():
    # combine semantics through the real _moe_ffn dispatch path (ep=1
    # via SelfComm): a token picked by k experts receives the sum of
    # the k gate-weighted expert outputs; an unpicked token gets zero
    cfg = moe.MoEConfig(d_model=4, experts=2, d_ff=8)
    comm = m.SelfComm()
    b, s, d = 1, 8, 4
    h = jax.random.normal(jax.random.PRNGKey(7), (b, s, d))
    wr = jax.random.normal(jax.random.PRNGKey(8), (d, 2))
    w1e = jax.random.normal(jax.random.PRNGKey(9), (2, d, 8))
    w2e = jax.random.normal(jax.random.PRNGKey(10), (2, 8, d))

    out, _tok = moe._moe_ffn(h, wr, w1e, w2e, cfg, comm, None)

    # numpy loop oracle
    xt = np.asarray(h).reshape(s, d)
    probs = np.asarray(jax.nn.softmax(jnp.asarray(xt) @ wr, axis=-1))
    expected = np.zeros_like(xt)
    picked = set()
    for e in range(2):
        top = np.argsort(-probs[:, e], kind="stable")[:4]
        for t in top:
            picked.add(int(t))
            hmid = np.asarray(jax.nn.gelu(jnp.asarray(xt[t]) @ w1e[e]))
            expected[t] += probs[t, e] * (hmid @ np.asarray(w2e[e]))
    np.testing.assert_allclose(
        np.asarray(out).reshape(s, d), expected, rtol=1e-5, atol=1e-5
    )
    unpicked = [t for t in range(s) if t not in picked]
    for t in unpicked:
        np.testing.assert_array_equal(np.asarray(out).reshape(s, d)[t], 0.0)
