"""Pipeline-parallel transformer train step vs the dense oracle: layers
sharded into GPipe stages over pp, microbatch scan, gradients through
the reversed handoff — one SGD step matches single-device math."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as m
from mpi4jax_tpu.models import pp_transformer as ppt

CFG = ppt.TransformerConfig(
    vocab=32, d_model=16, layers=4, heads=4, kv_heads=2, head_dim=8, d_ff=32
)
B, S = 8, 12
DP, PP = 2, 4


@pytest.fixture(scope="module")
def mesh2d():
    return jax.make_mesh(
        (DP, PP), ("dp", "pp"), axis_types=(jax.sharding.AxisType.Auto,) * 2
    )


@pytest.fixture(scope="module")
def comms(mesh2d):
    world = m.MeshComm.from_mesh(mesh2d)
    return world.sub("dp"), world.sub("pp")


def batch(seed=0):
    kt = jax.random.PRNGKey(seed)
    tokens = jax.random.randint(kt, (B, S), 0, CFG.vocab)
    return tokens, jnp.roll(tokens, -1, axis=1)


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_pp_train_step_matches_oracle(mesh2d, comms, schedule):
    comm_dp, comm_pp = comms
    params = ppt.init_params(jax.random.PRNGKey(1), CFG)
    tokens, targets = batch()

    step = ppt.make_global_train_step(
        mesh2d, comm_dp, comm_pp, CFG, n_micro=2, lr=1e-1,
        schedule=schedule,
    )
    new_params, loss = step(params, (tokens, targets))

    ref_loss, ref_grads = jax.value_and_grad(
        lambda p: ppt.reference_loss(p, tokens, targets, CFG)
    )(params)
    ref_new = jax.tree.map(lambda p, g: p - 1e-1 * g, params, ref_grads)

    np.testing.assert_allclose(
        float(np.asarray(loss)[0]), float(ref_loss), rtol=2e-5, atol=2e-5
    )
    names = [
        "embed", "ln1", "wq", "wk", "wv", "wo", "ln2", "w1", "w2",
        "ln_f", "head",
    ]
    for name, got, want in zip(
        names, jax.tree.leaves(new_params), jax.tree.leaves(ref_new)
    ):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=3e-4, atol=3e-4,
            err_msg=name,
        )


@pytest.mark.parametrize("n_micro", [1, 4])
@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_pp_microbatch_count_invariance(mesh2d, comms, schedule, n_micro):
    # the schedule (bubble pattern) must not change the math
    comm_dp, comm_pp = comms
    params = ppt.init_params(jax.random.PRNGKey(2), CFG)
    tokens, targets = batch(seed=3)
    step = ppt.make_global_train_step(
        mesh2d, comm_dp, comm_pp, CFG, n_micro=n_micro, lr=1e-1,
        schedule=schedule,
    )
    _, loss = step(params, (tokens, targets))
    ref = float(ppt.reference_loss(params, tokens, targets, CFG))
    np.testing.assert_allclose(float(np.asarray(loss)[0]), ref, rtol=2e-5)


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_pp_loss_decreases(mesh2d, comms, schedule):
    comm_dp, comm_pp = comms
    params = ppt.init_params(jax.random.PRNGKey(4), CFG)
    tokens, targets = batch(seed=5)
    step = ppt.make_global_train_step(
        mesh2d, comm_dp, comm_pp, CFG, n_micro=2, lr=3e-1,
        schedule=schedule,
    )
    losses = []
    for _ in range(8):
        params, loss = step(params, (tokens, targets))
        losses.append(float(np.asarray(loss)[0]))
    assert losses[-1] < losses[0] * 0.8, losses
    assert np.isfinite(losses).all()


def test_pp_layer_divisibility(mesh2d, comms):
    comm_dp, comm_pp = comms
    with pytest.raises(ValueError, match="divisible by the pipeline"):
        ppt.make_global_train_step(
            mesh2d, comm_dp, comm_pp, CFG._replace(layers=3), n_micro=2
        )
