"""Expert-parallel dispatch/combine (the reference's alltoall EP
building block, SURVEY §2.4) — round-trip and expert-computation
correctness against a dense local oracle, plus gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as m
from mpi4jax_tpu.parallel.moe import expert_combine, expert_dispatch

E = 8   # experts = devices
T = 16  # tokens per rank (capacity 2)
D = 4


def _mesh_comm():
    mesh = jax.make_mesh((E,), ("ep",), axis_types=(jax.sharding.AxisType.Auto,))
    return mesh, m.MeshComm.from_mesh(mesh)


def _balanced_assignment(key, rank_seed):
    # exactly T//E tokens per expert, order shuffled
    base = jnp.repeat(jnp.arange(E), T // E)
    return jax.random.permutation(jax.random.fold_in(key, rank_seed), base)


def test_dispatch_combine_roundtrip_and_expert_compute():
    mesh, comm = _mesh_comm()
    key = jax.random.PRNGKey(0)
    # per-rank tokens and assignments (global arrays sharded over ep)
    xs = jax.random.normal(key, (E, T, D))
    idx = jnp.stack([_balanced_assignment(key, r) for r in range(E)])
    scales = 1.0 + jnp.arange(E, dtype=jnp.float32)  # expert e: x * (e+1)

    def local(x, idx, scale):
        x, idx = x[0], idx[0]
        ein, order, tok = expert_dispatch(x, idx, comm)
        eout = ein * scale[0]  # this rank's expert
        out, tok = expert_combine(eout, order, comm, token=tok)
        return out[None]

    f = jax.jit(
        jax.shard_map(
            local,
            mesh=mesh,
            in_specs=(jax.P("ep"), jax.P("ep"), jax.P("ep")),
            out_specs=jax.P("ep"),
        )
    )
    out = np.asarray(f(xs, idx, scales))
    # oracle: every token scaled by (its expert + 1), order preserved
    expected = np.asarray(xs) * (np.asarray(idx)[..., None] + 1.0)
    np.testing.assert_allclose(out, expected, rtol=1e-6)


def test_dispatch_grad():
    mesh, comm = _mesh_comm()
    key = jax.random.PRNGKey(1)
    xs = jax.random.normal(key, (E, T, D))
    idx = jnp.stack([_balanced_assignment(key, r) for r in range(E)])

    def local(x, idx):
        x, idx = x[0], idx[0]
        ein, order, tok = expert_dispatch(x, idx, comm)
        out, tok = expert_combine(ein * 2.0, order, comm, token=tok)
        return out[None]

    f = jax.jit(
        jax.shard_map(
            local, mesh=mesh,
            in_specs=(jax.P("ep"), jax.P("ep")),
            out_specs=jax.P("ep"),
        )
    )

    g = jax.grad(lambda x: (f(x, idx) ** 2).sum())(xs)
    # out = 2x token-wise -> d/dx sum(out^2) = 8x
    np.testing.assert_allclose(np.asarray(g), 8 * np.asarray(xs), rtol=1e-5)


def test_non_divisible_token_count_raises():
    _, comm = _mesh_comm()
    with pytest.raises(ValueError, match="divisible"):
        from tests.helpers import spmd_jit

        spmd_jit(
            comm,
            lambda v: expert_dispatch(
                jnp.ones((E + 1, D)), jnp.zeros(E + 1, jnp.int32), comm
            )[0],
        )(jnp.arange(8.0))


def test_unbalanced_assignment_is_a_precondition():
    # a divisible-but-unbalanced assignment violates the documented
    # capacity-1 precondition: dispatch reshapes blindly, so tokens land
    # on the wrong experts (no error is possible — values are traced).
    # This pins the behaviour so the contract stays documented-honest.
    mesh, comm = _mesh_comm()
    xs = jnp.ones((E, T, D))
    idx = jnp.zeros((E, T), jnp.int32)  # everyone wants expert 0
    scales = 1.0 + jnp.arange(E, dtype=jnp.float32)

    def local(x, idx, scale):
        ein, order, tok = expert_dispatch(x[0], idx[0], comm)
        out, tok = expert_combine(ein * scale[0], order, comm, token=tok)
        return out[None]

    f = jax.jit(
        jax.shard_map(
            local, mesh=mesh,
            in_specs=(jax.P("ep"), jax.P("ep"), jax.P("ep")),
            out_specs=jax.P("ep"),
        )
    )
    out = np.asarray(f(xs, idx, scales))
    # tokens were spread across all experts despite idx==0 everywhere:
    # NOT everything is scaled by expert 0's factor
    assert not np.allclose(out, 1.0)


# ---------------- token-choice top-k routing (GShard/Switch) ----------------

from mpi4jax_tpu.parallel.moe import topk_moe, topk_route  # noqa: E402


def _np_topk_route(scores, k, capacity):
    """Loop oracle: per token pick top-k experts; per expert accept its
    top-capacity choosers by score."""
    t, e_n = scores.shape
    chose = np.full((t, e_n), -np.inf, np.float32)
    for i in range(t):
        for e in np.argsort(-scores[i], kind="stable")[:k]:
            chose[i, e] = scores[i, e]
    out = []
    for e in range(e_n):
        order = np.argsort(-chose[:, e], kind="stable")[:capacity]
        out.append([(i, chose[i, e]) for i in order])
    return out  # per expert: list of (token, score or -inf)


def test_topk_route_matches_loop_oracle():
    rng = np.random.RandomState(0)
    scores = rng.rand(12, 4).astype(np.float32)
    idx, gate, valid = topk_route(jnp.asarray(scores), k=2, capacity=3)
    want = _np_topk_route(scores, 2, 3)
    for e in range(4):
        for c, (tok_i, sc) in enumerate(want[e]):
            if np.isfinite(sc):
                assert bool(valid[e, c])
                assert int(idx[e, c]) == tok_i, (e, c)
                np.testing.assert_allclose(float(gate[e, c]), sc, rtol=1e-6)
            else:
                assert not bool(valid[e, c])
                assert float(gate[e, c]) == 0.0


def test_topk_route_overflow_drops_lowest():
    # 4 tokens all choose expert 0 (k=1), capacity 2: the two highest
    # scores win, the rest overflow
    scores = jnp.asarray(
        [[0.9, 0.1], [0.8, 0.2], [0.7, 0.3], [0.6, 0.4]], jnp.float32
    )
    idx, gate, valid = topk_route(scores, k=1, capacity=2)
    assert sorted(np.asarray(idx[0]).tolist()) == [0, 1]
    assert bool(valid[0, 0]) and bool(valid[0, 1])
    # expert 1: nobody chose it
    assert not np.asarray(valid[1]).any()


def test_topk_route_inf_masked_logits():
    # the raw-logits-with--inf-masking idiom: slot validity is derived
    # from chooser counts, not score finiteness, so a token whose k
    # picks include a masked (-inf) expert still occupies a zero-gated
    # slot instead of being misread as an unfilled expert.
    neg = -jnp.inf
    scores = jnp.asarray(
        [[1.0, neg, neg],   # token 0: only expert 0 unmasked
         [0.5, 2.0, neg],   # token 1: experts 0, 1
         [0.2, 1.5, neg]],  # token 2: experts 0, 1
        jnp.float32,
    )
    idx, gate, valid = topk_route(scores, k=2, capacity=3)
    # expert 0: chosen by all three tokens, finite gates
    assert np.asarray(valid[0]).all()
    np.testing.assert_allclose(np.sort(np.asarray(gate[0])), [0.2, 0.5, 1.0])
    # expert 1: tokens 1 and 2 chose it with finite scores; token 0's
    # forced second pick (ties break low) lands here too -> THREE valid
    # slots, the -inf one gated to exactly 0, finite ones undisplaced
    assert np.asarray(valid[1]).all()
    assert sorted(np.asarray(idx[1]).tolist()) == [0, 1, 2]
    np.testing.assert_allclose(np.sort(np.asarray(gate[1])), [0.0, 1.5, 2.0])
    # expert 2: no choosers at all -> unfilled
    assert not np.asarray(valid[2]).any()
    # nothing non-finite leaks into gates
    assert np.isfinite(np.asarray(gate)).all()


def test_topk_moe_matches_dense_oracle():
    mesh, comm = _mesh_comm()
    t_loc = 16
    key = jax.random.PRNGKey(3)
    xs = jax.random.normal(key, (E, t_loc, D))
    wr = jax.random.normal(jax.random.PRNGKey(4), (D, E))
    scales = 1.0 + jnp.arange(E, dtype=jnp.float32)

    def local(x, scale):
        x = x[0]
        scores = jax.nn.softmax(x @ wr, axis=-1)
        y, _tok = topk_moe(
            x, scores, lambda v: v * scale[0], comm, k=2
        )
        return y[None]

    f = jax.jit(
        jax.shard_map(
            local, mesh=mesh,
            in_specs=(jax.P("ep"), jax.P("ep")),
            out_specs=jax.P("ep"),
        )
    )
    out = np.asarray(f(xs, scales))

    # dense oracle per rank: token i gets sum over its surviving
    # (expert, gate) picks of gate * (x_i * (e+1))
    cap = -(-2 * t_loc // E)
    for r in range(E):
        x = np.asarray(xs[r])
        scores = np.asarray(jax.nn.softmax(jnp.asarray(x) @ wr, axis=-1))
        picks = _np_topk_route(scores, 2, cap)
        want = np.zeros_like(x)
        for e in range(E):
            for tok_i, sc in picks[e]:
                if np.isfinite(sc):
                    want[tok_i] += sc * x[tok_i] * (e + 1)
        np.testing.assert_allclose(out[r], want, rtol=1e-5, atol=1e-5)


def test_topk_moe_grads_flow_to_router():
    mesh, comm = _mesh_comm()
    t_loc = 16
    xs = jax.random.normal(jax.random.PRNGKey(5), (E, t_loc, D))
    wr0 = jax.random.normal(jax.random.PRNGKey(6), (D, E))
    scales = 1.0 + jnp.arange(E, dtype=jnp.float32)

    def local(x, wr, scale):
        x = x[0]

        def loss(w):
            scores = jax.nn.softmax(x @ w, axis=-1)
            y, _ = topk_moe(x, scores, lambda v: v * scale[0], comm, k=2)
            return (y * y).sum()

        return jax.grad(loss)(wr)[None]

    f = jax.jit(
        jax.shard_map(
            local, mesh=mesh,
            in_specs=(jax.P("ep"), jax.P(None, None), jax.P("ep")),
            out_specs=jax.P(("ep",), None, None),
        )
    )
    g = np.asarray(f(xs, wr0, scales))
    assert np.isfinite(g).all()
    assert np.abs(g).max() > 0  # router receives gradient through gates
