"""Expert-parallel dispatch/combine (the reference's alltoall EP
building block, SURVEY §2.4) — round-trip and expert-computation
correctness against a dense local oracle, plus gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as m
from mpi4jax_tpu.parallel.moe import expert_combine, expert_dispatch

E = 8   # experts = devices
T = 16  # tokens per rank (capacity 2)
D = 4


def _mesh_comm():
    mesh = jax.make_mesh((E,), ("ep",), axis_types=(jax.sharding.AxisType.Auto,))
    return mesh, m.MeshComm.from_mesh(mesh)


def _balanced_assignment(key, rank_seed):
    # exactly T//E tokens per expert, order shuffled
    base = jnp.repeat(jnp.arange(E), T // E)
    return jax.random.permutation(jax.random.fold_in(key, rank_seed), base)


def test_dispatch_combine_roundtrip_and_expert_compute():
    mesh, comm = _mesh_comm()
    key = jax.random.PRNGKey(0)
    # per-rank tokens and assignments (global arrays sharded over ep)
    xs = jax.random.normal(key, (E, T, D))
    idx = jnp.stack([_balanced_assignment(key, r) for r in range(E)])
    scales = 1.0 + jnp.arange(E, dtype=jnp.float32)  # expert e: x * (e+1)

    def local(x, idx, scale):
        x, idx = x[0], idx[0]
        ein, order, tok = expert_dispatch(x, idx, comm)
        eout = ein * scale[0]  # this rank's expert
        out, tok = expert_combine(eout, order, comm, token=tok)
        return out[None]

    f = jax.jit(
        jax.shard_map(
            local,
            mesh=mesh,
            in_specs=(jax.P("ep"), jax.P("ep"), jax.P("ep")),
            out_specs=jax.P("ep"),
        )
    )
    out = np.asarray(f(xs, idx, scales))
    # oracle: every token scaled by (its expert + 1), order preserved
    expected = np.asarray(xs) * (np.asarray(idx)[..., None] + 1.0)
    np.testing.assert_allclose(out, expected, rtol=1e-6)


def test_dispatch_grad():
    mesh, comm = _mesh_comm()
    key = jax.random.PRNGKey(1)
    xs = jax.random.normal(key, (E, T, D))
    idx = jnp.stack([_balanced_assignment(key, r) for r in range(E)])

    def local(x, idx):
        x, idx = x[0], idx[0]
        ein, order, tok = expert_dispatch(x, idx, comm)
        out, tok = expert_combine(ein * 2.0, order, comm, token=tok)
        return out[None]

    f = jax.jit(
        jax.shard_map(
            local, mesh=mesh,
            in_specs=(jax.P("ep"), jax.P("ep")),
            out_specs=jax.P("ep"),
        )
    )

    g = jax.grad(lambda x: (f(x, idx) ** 2).sum())(xs)
    # out = 2x token-wise -> d/dx sum(out^2) = 8x
    np.testing.assert_allclose(np.asarray(g), 8 * np.asarray(xs), rtol=1e-5)


def test_non_divisible_token_count_raises():
    _, comm = _mesh_comm()
    with pytest.raises(ValueError, match="divisible"):
        from tests.helpers import spmd_jit

        spmd_jit(
            comm,
            lambda v: expert_dispatch(
                jnp.ones((E + 1, D)), jnp.zeros(E + 1, jnp.int32), comm
            )[0],
        )(jnp.arange(8.0))


def test_unbalanced_assignment_is_a_precondition():
    # a divisible-but-unbalanced assignment violates the documented
    # capacity-1 precondition: dispatch reshapes blindly, so tokens land
    # on the wrong experts (no error is possible — values are traced).
    # This pins the behaviour so the contract stays documented-honest.
    mesh, comm = _mesh_comm()
    xs = jnp.ones((E, T, D))
    idx = jnp.zeros((E, T), jnp.int32)  # everyone wants expert 0
    scales = 1.0 + jnp.arange(E, dtype=jnp.float32)

    def local(x, idx, scale):
        ein, order, tok = expert_dispatch(x[0], idx[0], comm)
        out, tok = expert_combine(ein * scale[0], order, comm, token=tok)
        return out[None]

    f = jax.jit(
        jax.shard_map(
            local, mesh=mesh,
            in_specs=(jax.P("ep"), jax.P("ep"), jax.P("ep")),
            out_specs=jax.P("ep"),
        )
    )
    out = np.asarray(f(xs, idx, scales))
    # tokens were spread across all experts despite idx==0 everywhere:
    # NOT everything is scaled by expert 0's factor
    assert not np.allclose(out, 1.0)
