"""Pipeline parallelism (the reference's 'PP building block': sendrecv
ring step + microbatch lax.scan, SURVEY §2.4) — correctness against the
sequential oracle, forward and gradients."""

import jax
import jax.numpy as jnp
import numpy as np

import mpi4jax_tpu as m
from mpi4jax_tpu.models.pipeline import pipeline_apply

S = 8  # stages = devices
M = 5  # microbatches
MB = 3  # rows per microbatch
D = 4


def _setup():
    mesh = jax.make_mesh((S,), ("pp",), axis_types=(jax.sharding.AxisType.Auto,))
    comm = m.MeshComm.from_mesh(mesh)
    key = jax.random.PRNGKey(0)
    ws = jax.random.normal(key, (S, D, D)) * 0.3
    bs = jax.random.normal(jax.random.PRNGKey(1), (S, D)) * 0.1
    xs = jax.random.normal(jax.random.PRNGKey(2), (M, MB, D))
    return mesh, comm, ws, bs, xs


def _stage_fn(params, a):
    w, b = params
    return jnp.tanh(a @ w + b)


def _sequential(ws, bs, xs):
    out = xs
    for s in range(S):
        out = jnp.tanh(out @ ws[s] + bs[s])
    return out


def _run_pipeline(mesh, comm, ws, bs, xs):
    def local(w, b, xs):
        # per-device stage params arrive as (1, D, D)/(1, D) shards
        outputs, _tok = pipeline_apply(
            _stage_fn, (w[0], b[0]), xs, comm
        )
        return outputs[None]  # (1, M, MB, D) per device

    f = jax.jit(
        jax.shard_map(
            local,
            mesh=mesh,
            in_specs=(jax.P("pp"), jax.P("pp"), jax.P()),
            out_specs=jax.P("pp"),
        )
    )
    return f(ws, bs, xs)  # (S, M, MB, D); row -1 = final-stage outputs


def test_pipeline_matches_sequential():
    mesh, comm, ws, bs, xs = _setup()
    out = _run_pipeline(mesh, comm, ws, bs, xs)
    expected = _sequential(ws, bs, xs)
    np.testing.assert_allclose(
        np.asarray(out)[-1], np.asarray(expected), rtol=1e-5, atol=1e-6
    )
    # non-final stages bank nothing
    assert np.allclose(np.asarray(out)[:-1], 0.0)


def test_pipeline_grad_matches_sequential():
    mesh, comm, ws, bs, xs = _setup()

    def pipe_loss(ws, bs):
        return (_run_pipeline(mesh, comm, ws, bs, xs)[-1] ** 2).sum()

    def seq_loss(ws, bs):
        return (_sequential(ws, bs, xs) ** 2).sum()

    gp_w, gp_b = jax.grad(pipe_loss, argnums=(0, 1))(ws, bs)
    gs_w, gs_b = jax.grad(seq_loss, argnums=(0, 1))(ws, bs)
    np.testing.assert_allclose(
        np.asarray(gp_w), np.asarray(gs_w), rtol=2e-5, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(gp_b), np.asarray(gs_b), rtol=2e-5, atol=1e-5
    )
