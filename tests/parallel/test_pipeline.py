"""Pipeline parallelism (the reference's 'PP building block': sendrecv
ring step + microbatch lax.scan, SURVEY §2.4) — correctness against the
sequential oracle, forward and gradients, for both the GPipe and the
1F1B schedules."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as m
from mpi4jax_tpu.models.pipeline import pipeline_apply, pipeline_train

S = 8  # stages = devices
M = 5  # microbatches
MB = 3  # rows per microbatch
D = 4


def _setup():
    mesh = jax.make_mesh((S,), ("pp",), axis_types=(jax.sharding.AxisType.Auto,))
    comm = m.MeshComm.from_mesh(mesh)
    key = jax.random.PRNGKey(0)
    ws = jax.random.normal(key, (S, D, D)) * 0.3
    bs = jax.random.normal(jax.random.PRNGKey(1), (S, D)) * 0.1
    xs = jax.random.normal(jax.random.PRNGKey(2), (M, MB, D))
    return mesh, comm, ws, bs, xs


def _stage_fn(params, a):
    w, b = params
    return jnp.tanh(a @ w + b)


def _sequential(ws, bs, xs):
    out = xs
    for s in range(S):
        out = jnp.tanh(out @ ws[s] + bs[s])
    return out


def _run_pipeline(mesh, comm, ws, bs, xs):
    def local(w, b, xs):
        # per-device stage params arrive as (1, D, D)/(1, D) shards
        outputs, _tok = pipeline_apply(
            _stage_fn, (w[0], b[0]), xs, comm
        )
        return outputs[None]  # (1, M, MB, D) per device

    f = jax.jit(
        jax.shard_map(
            local,
            mesh=mesh,
            in_specs=(jax.P("pp"), jax.P("pp"), jax.P()),
            out_specs=jax.P("pp"),
        )
    )
    return f(ws, bs, xs)  # (S, M, MB, D); row -1 = final-stage outputs


def test_pipeline_matches_sequential():
    mesh, comm, ws, bs, xs = _setup()
    out = _run_pipeline(mesh, comm, ws, bs, xs)
    expected = _sequential(ws, bs, xs)
    np.testing.assert_allclose(
        np.asarray(out)[-1], np.asarray(expected), rtol=1e-5, atol=1e-6
    )
    # non-final stages bank nothing
    assert np.allclose(np.asarray(out)[:-1], 0.0)


def test_pipeline_grad_matches_sequential():
    mesh, comm, ws, bs, xs = _setup()

    def pipe_loss(ws, bs):
        return (_run_pipeline(mesh, comm, ws, bs, xs)[-1] ** 2).sum()

    def seq_loss(ws, bs):
        return (_sequential(ws, bs, xs) ** 2).sum()

    gp_w, gp_b = jax.grad(pipe_loss, argnums=(0, 1))(ws, bs)
    gs_w, gs_b = jax.grad(seq_loss, argnums=(0, 1))(ws, bs)
    np.testing.assert_allclose(
        np.asarray(gp_w), np.asarray(gs_w), rtol=2e-5, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(gp_b), np.asarray(gs_b), rtol=2e-5, atol=1e-5
    )


# ------------------------------ 1F1B ---------------------------------


def _head_fn(hp, a, t):
    return (((a @ hp) - t) ** 2).mean()


@pytest.mark.parametrize("n_micro", [1, 2, 5])
def test_1f1b_grads_match_sequential(n_micro):
    """The interleaved schedule's manually built backward is exact:
    loss, stage grads, head grads, and input grads all match the
    sequential AD oracle, at microbatch counts below, at, and above the
    stage count boundary cases."""
    mesh, comm, ws, bs, _ = _setup()
    xs = jax.random.normal(jax.random.PRNGKey(2), (n_micro, MB, D))
    hw = jax.random.normal(jax.random.PRNGKey(3), (D,)) * 0.5
    tg = jax.random.normal(jax.random.PRNGKey(4), (n_micro, MB))

    def local(w, b, hw, xs, tg):
        loss, (dw, db), dhw, dxs, _tok = pipeline_train(
            _stage_fn, (w[0], b[0]), _head_fn, hw, xs, tg, comm
        )
        return loss[None], dw[None], db[None], dhw[None], dxs[None]

    f = jax.jit(
        jax.shard_map(
            local,
            mesh=mesh,
            in_specs=(jax.P("pp"), jax.P("pp"), jax.P(), jax.P(), jax.P()),
            out_specs=tuple(jax.P("pp") for _ in range(5)),
        )
    )
    loss, dw, db, dhw, dxs = f(ws, bs, hw, xs, tg)

    def seq_loss(ws, bs, hw, xs):
        out = xs
        for s in range(S):
            out = jnp.tanh(out @ ws[s] + bs[s])
        return sum(_head_fn(hw, out[i], tg[i]) for i in range(n_micro))

    ref = jax.grad(seq_loss, argnums=(0, 1, 2, 3))(ws, bs, hw, xs)
    rl = seq_loss(ws, bs, hw, xs)
    # loss accumulates on the last stage; head grads live there too;
    # input grads live on stage 0 — the documented placement contract
    np.testing.assert_allclose(np.asarray(loss)[-1], float(rl), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(dw), np.asarray(ref[0]), rtol=1e-4, atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(db), np.asarray(ref[1]), rtol=1e-4, atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(dhw)[-1], np.asarray(ref[2]), rtol=1e-4, atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(dxs)[0], np.asarray(ref[3]), rtol=1e-4, atol=1e-6
    )
    # placement: off-last head grads and off-first input grads are zero
    assert np.allclose(np.asarray(dhw)[:-1], 0.0)
    assert np.allclose(np.asarray(dxs)[1:], 0.0)


def test_1f1b_bounds_activation_memory():
    """The schedule's reason to exist: in-flight activations bounded by
    the 2S-1 stash instead of GPipe's M microbatches of scan residuals.
    Verified on the compiled executables' memory analysis (M=16 >> S=4:
    the GPipe step must allocate several times the 1F1B step's temps)."""
    from mpi4jax_tpu.models import pp_transformer as ppt

    cfg = ppt.TransformerConfig(
        vocab=256, d_model=128, layers=4, heads=8, kv_heads=8,
        head_dim=16, d_ff=512,
    )
    mesh = jax.make_mesh(
        (1, 4), ("dp", "pp"),
        axis_types=(jax.sharding.AxisType.Auto,) * 2,
        devices=jax.devices()[:4],
    )
    world = m.MeshComm.from_mesh(mesh)
    comm_dp, comm_pp = world.sub("dp"), world.sub("pp")
    params = ppt.init_params(jax.random.PRNGKey(1), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (64, 128), 0, cfg.vocab)
    batch = (tokens, jnp.roll(tokens, -1, axis=1))

    temps = {}
    for sched in ("gpipe", "1f1b"):
        step = ppt.make_global_train_step(
            mesh, comm_dp, comm_pp, cfg, n_micro=16, lr=1e-2, schedule=sched
        )
        mem = step.lower(params, batch).compile().memory_analysis()
        temps[sched] = mem.temp_size_in_bytes
    # measured ~297 MB vs ~24 MB on the CPU mesh; assert a conservative
    # factor so compiler-version drift doesn't flake the test
    assert temps["1f1b"] * 3 < temps["gpipe"], temps
