"""The SPMD matching contract (docs/sharp-bits.md "The matching
contract, case by case"): every mesh-backend divergence from MPI raises
with guidance that names the escape hatch.  These tests pin the
guidance text."""

import jax
import jax.numpy as jnp
import pytest

import mpi4jax_tpu as m


def test_bare_int_dest_names_launcher(comm1d):
    with pytest.raises(ValueError) as e:
        m.send(jnp.zeros(3), dest=1, comm=comm1d)
    msg = str(e.value)
    assert "ambiguous under SPMD" in msg
    assert "mpi4jax_tpu.launch" in msg  # proc-backend escape hatch
    assert "shift_perm" in msg  # the SPMD-native alternative


def test_bare_int_source_names_launcher(comm1d):
    with pytest.raises(ValueError) as e:
        m.recv(jnp.zeros(3), source=2, comm=comm1d)
    assert "mpi4jax_tpu.launch" in str(e.value)


def test_unmatched_wildcard_recv_is_runtime_matched(comm1d, monkeypatch):
    # Contract change in round 3 (VERDICT r2 #4): a WILDCARD recv with
    # no trace-time match no longer raises at trace time — it IS the
    # runtime-matching path (host rendezvous, ops/_rendezvous.py).  A
    # lone one therefore diagnoses the deadlock at execution time with
    # the curated timeout error.
    import numpy as np

    monkeypatch.setenv("MPI4JAX_TPU_RENDEZVOUS_TIMEOUT", "1")

    def fn(x):
        y, _ = m.recv(x, comm=comm1d)
        return y

    with pytest.raises(Exception, match="timed out") as e:
        np.asarray(
            jax.shard_map(
                fn, mesh=comm1d.mesh, in_specs=jax.P("i"),
                out_specs=jax.P("i"),
            )(jnp.arange(8.0))
        )
    assert "deadlock" in str(e.value)  # the diagnosis, with guidance


def test_unmatched_static_recv_names_proc_backend(comm1d):
    # a STATIC-pattern recv with no staged send keeps the trace-time
    # error (it can never be satisfied at runtime either — the matching
    # send would have been staged in this same trace)
    def fn(x):
        y, _ = m.recv(x, source=lambda r: (r - 1) % 8, comm=comm1d)
        return y

    with pytest.raises(RuntimeError) as e:
        jax.shard_map(
            fn, mesh=comm1d.mesh, in_specs=jax.P("i"), out_specs=jax.P("i")
        )(jnp.arange(8.0))
    msg = str(e.value)
    assert "same trace" in msg
    assert "multi-process" in msg  # escape hatch


def test_ragged_split_names_proc_backend(comm1d):
    # colors 0:5 ranks / 1:3 ranks -> ragged
    with pytest.raises(ValueError) as e:
        comm1d.split(lambda r: 0 if r < 5 else 1)
    msg = str(e.value)
    assert "equal-size subgroups" in msg
    assert "multi-process" in msg  # escape hatch


def test_traced_root_hint(comm1d):
    # a tracer leaking into a static arg must point at static_argnums
    # (the reference's validation hint, validation.py:77-88 there)
    def fn(x):
        y, _ = m.bcast(x, root=jnp.int32(0), comm=comm1d)
        return y

    with pytest.raises(TypeError) as e:
        jax.jit(
            jax.shard_map(
                fn, mesh=comm1d.mesh, in_specs=jax.P("i"), out_specs=jax.P("i")
            )
        )(jnp.arange(8.0))
    assert "static" in str(e.value).lower()


def test_any_source_is_trace_time_fifo(comm1d):
    # Not an error: ANY_SOURCE on the mesh backend deterministically
    # matches the EARLIEST staged send (documented trace-time FIFO).
    ring = [(r, (r + 1) % 8) for r in range(8)]
    back = [((r + 1) % 8, r) for r in range(8)]

    def fn(x):
        tok = m.send(x, ring, tag=7, comm=comm1d)
        tok = m.send(x * 2, back, tag=9, comm=comm1d, token=tok)
        st = m.Status()
        y, tok = m.recv(x, comm=comm1d, token=tok, status=st)  # ANY/ANY
        z, tok = m.recv(x, comm=comm1d, token=tok)
        return y * 1000 + z

    out = jax.jit(
        jax.shard_map(
            fn, mesh=comm1d.mesh, in_specs=jax.P("i"), out_specs=jax.P("i")
        )
    )(jnp.arange(8.0))
    import numpy as np

    arr = np.arange(8.0)
    first = np.roll(arr, 1)  # earliest staged send: the tag-7 ring
    second = np.roll(arr * 2, -1)
    assert np.array_equal(np.asarray(out), first * 1000 + second)
