"""Token plumbing tests: chaining, coercion, pytree behaviour, and
ordering inside control flow (the reference's token discipline,
docs/sharp-bits.rst:6-34, enforced here by data dependence).
"""

import jax
import jax.numpy as jnp
import numpy as np

import mpi4jax_tpu as m

from tests.helpers import spmd_jit

SIZE = 8


def test_create_and_coerce():
    tok = m.create_token()
    assert isinstance(tok, m.Token)
    assert m.as_token(None) is not None
    assert isinstance(m.as_token(tok), m.Token)
    arr_tok = m.as_token(jnp.zeros(()))
    assert isinstance(arr_tok, m.Token)


def test_token_is_pytree():
    tok = m.create_token()
    leaves, treedef = jax.tree.flatten(tok)
    tok2 = jax.tree.unflatten(treedef, leaves)
    assert isinstance(tok2, m.Token)


def test_token_through_jit_and_scan(comm1d):
    def fn(x):
        tok = m.create_token()

        def body(carry, _):
            val, tok = carry
            val, tok = m.allreduce(val, m.SUM, comm=comm1d, token=tok)
            val = val / SIZE
            return (val, tok), val.sum()

        (val, tok), _ = jax.lax.scan(body, (x, tok), None, length=4)
        return val

    out = spmd_jit(comm1d, fn)(jnp.ones(SIZE))
    # each iteration: allreduce(1s) = 8 -> /8 = 1 (fixed point)
    assert np.array_equal(np.asarray(out), np.ones(SIZE))


def test_ordering_chain_is_data_dependent(comm1d):
    # the jaxpr must show the second op consuming the first op's stamp
    def fn(x):
        tok = m.create_token()
        a, tok = m.allreduce(x, m.SUM, comm=comm1d, token=tok)
        b, tok = m.allreduce(x * 2, m.SUM, comm=comm1d, token=tok)
        return a + b

    jaxpr = jax.make_jaxpr(
        jax.shard_map(
            fn,
            mesh=comm1d.mesh,
            in_specs=jax.P(comm1d.axes),
            out_specs=jax.P(comm1d.axes),
        )
    )(jnp.ones(SIZE))
    text = str(jaxpr)
    assert text.count("mpi4jax_tpu_allreduce") == 2


def test_token_cond(comm1d):
    # token threading through lax.cond branches
    def fn(x):
        tok = m.create_token()

        def branch_a(args):
            v, tok = args
            y, tok = m.allreduce(v, m.SUM, comm=comm1d, token=tok)
            return y, tok

        def branch_b(args):
            v, tok = args
            y, tok = m.allreduce(v * 2, m.SUM, comm=comm1d, token=tok)
            return y, tok

        # static predicate per trace is fine; use a traced one
        pred = x.sum() > 100.0  # False for our input
        y, tok = jax.lax.cond(pred, branch_a, branch_b, (x, tok))
        return y

    out = spmd_jit(comm1d, fn)(jnp.ones(SIZE))
    assert np.array_equal(np.asarray(out), np.full(SIZE, 16.0))
