"""User-defined reduction operators (Op.create — the MPI.Op.Create
analog; the reference forwards such handles straight to MPI_Allreduce,
mpi4jax/_src/utils.py:77-96 + collective_ops/allreduce.py:36-66)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as m

SIZE = 8


def _run(comm, fn, x=None):
    f = jax.jit(
        jax.shard_map(
            fn,
            mesh=comm.mesh,
            in_specs=jax.P(comm.axes),
            out_specs=jax.P(comm.axes),
        )
    )
    return f(jnp.arange(float(SIZE)) if x is None else x)


def test_create_requires_callable():
    with pytest.raises(TypeError, match="callable"):
        m.Op.create("not-a-function")


def test_custom_commutative_matches_builtin(comm1d):
    my_max = m.Op.create(jnp.maximum, name="my_max")

    def fn(x):
        y, _ = m.allreduce(x, my_max, comm=comm1d)
        return y

    out = _run(comm1d, fn)
    assert np.array_equal(np.asarray(out), np.full(SIZE, 7.0))


def test_custom_noncommutative_rank_order(comm1d):
    # MPI commute=False contract: operands combined in rank order.
    # LEFT keeps the lowest rank's operand, RIGHT the highest's.
    left = m.Op.create(lambda a, b: a, name="left", commute=False)
    right = m.Op.create(lambda a, b: b, name="right", commute=False)

    def fn(x):
        lo, tok = m.allreduce(x, left, comm=comm1d)
        hi, tok = m.allreduce(x, right, comm=comm1d, token=tok)
        return lo * 10 + hi

    out = _run(comm1d, fn)
    assert np.array_equal(np.asarray(out), np.full(SIZE, 0.0 * 10 + 7.0))


def test_custom_scan_rank_order(comm1d):
    # inclusive prefix with RIGHT-projection == each rank's own value;
    # with LEFT-projection == rank 0's value everywhere.  Exercises the
    # ladder's lower-rank-on-the-left operand order.
    left = m.Op.create(lambda a, b: a, name="left", commute=False)
    right = m.Op.create(lambda a, b: b, name="right", commute=False)

    def fn(x):
        a, tok = m.scan(x, left, comm=comm1d)
        b, tok = m.scan(x, right, comm=comm1d, token=tok)
        return a * 10 + b

    out = np.asarray(_run(comm1d, fn))
    assert np.array_equal(out, np.zeros(SIZE) * 10 + np.arange(8.0))


def test_custom_scan_associative(comm1d):
    # a genuinely mixing associative op: 2x2 matrix product flattened
    # into the last axis (affine-recurrence composition — the classic
    # non-commutative scan payload)
    def matmul2(a, b):
        a2 = a.reshape(*a.shape[:-1], 2, 2)
        b2 = b.reshape(*b.shape[:-1], 2, 2)
        return jnp.matmul(a2, b2).reshape(a.shape)

    op = m.Op.create(matmul2, name="matmul2", commute=False)
    # per-rank matrix [[1, r], [0, 1]]; prefix product = [[1, sum r], [0, 1]]
    def fn(x):
        r = x[0]
        mat = jnp.stack([1.0, r, 0.0, 1.0])[None]  # (1, 4) per rank
        y, _ = m.scan(mat, op, comm=comm1d)
        return y

    out = np.asarray(_run(comm1d, fn))  # (8, 4)
    prefix = np.cumsum(np.arange(8.0))
    expected = np.stack(
        [np.ones(8), prefix, np.zeros(8), np.ones(8)], axis=1
    )
    assert np.allclose(out, expected)


def test_custom_reduce(comm1d):
    my_sum = m.Op.create(jnp.add, name="my_sum")

    def fn(x):
        y, _ = m.reduce(x, my_sum, 0, comm=comm1d)
        return y

    out = _run(comm1d, fn)
    assert np.asarray(out)[0] == 28.0


def test_custom_op_self_backend(selfcomm):
    op = m.Op.create(jnp.minimum, name="my_min")
    y, _ = m.allreduce(jnp.float32(3.0), op, comm=selfcomm)
    assert float(y) == 3.0
    s, _ = m.scan(jnp.float32(4.0), op, comm=selfcomm)
    assert float(s) == 4.0


def test_custom_op_not_differentiable(comm1d):
    op = m.Op.create(jnp.add, name="sum")  # even named "sum"

    def fn(x):
        def loss(v):
            return m.allreduce(v, op, comm=comm1d)[0].sum()

        return jax.grad(loss)(x)

    with pytest.raises(NotImplementedError, match="op=SUM"):
        _run(comm1d, fn)


def test_custom_op_hash_identity():
    f = jnp.add
    a = m.Op.create(f, name="x")
    b = m.Op.create(f, name="x")
    c = m.Op.create(jnp.multiply, name="x")
    assert a == b  # same combine fn + name
    assert a != c  # different combine fn, despite same name
    assert hash(a) == hash(b)


def test_custom_op_rejected_on_proc_backend():
    from mpi4jax_tpu.ops._proc import _op_code

    op = m.Op.create(jnp.add, name="weird")
    with pytest.raises(NotImplementedError, match="mesh backend"):
        _op_code(op)
    assert _op_code(m.SUM) == 0


def test_op_create_mpi4py_spelling(comm1d):
    # compat path: MPI.Op.Create(fn, commute) — mpi4py's exact spelling
    from mpi4jax_tpu.compat import MPI

    op = MPI.Op.Create(jnp.minimum, commute=True)
    assert op.is_user and op.commute

    def fn(x):
        y, _ = m.allreduce(x, op, comm=comm1d)
        return y

    out = _run(comm1d, fn)
    assert np.array_equal(np.asarray(out), np.zeros(SIZE))
