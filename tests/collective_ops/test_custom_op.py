"""User-defined reduction operators (Op.create — the MPI.Op.Create
analog; the reference forwards such handles straight to MPI_Allreduce,
mpi4jax/_src/utils.py:77-96 + collective_ops/allreduce.py:36-66)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as m

SIZE = 8


def _run(comm, fn, x=None):
    f = jax.jit(
        jax.shard_map(
            fn,
            mesh=comm.mesh,
            in_specs=jax.P(comm.axes),
            out_specs=jax.P(comm.axes),
        )
    )
    return f(jnp.arange(float(SIZE)) if x is None else x)


def test_create_requires_callable():
    with pytest.raises(TypeError, match="callable"):
        m.Op.create("not-a-function")


def test_custom_commutative_matches_builtin(comm1d):
    my_max = m.Op.create(jnp.maximum, name="my_max")

    def fn(x):
        y, _ = m.allreduce(x, my_max, comm=comm1d)
        return y

    out = _run(comm1d, fn)
    assert np.array_equal(np.asarray(out), np.full(SIZE, 7.0))


def test_custom_noncommutative_rank_order(comm1d):
    # MPI commute=False contract: operands combined in rank order.
    # LEFT keeps the lowest rank's operand, RIGHT the highest's.
    left = m.Op.create(lambda a, b: a, name="left", commute=False)
    right = m.Op.create(lambda a, b: b, name="right", commute=False)

    def fn(x):
        lo, tok = m.allreduce(x, left, comm=comm1d)
        hi, tok = m.allreduce(x, right, comm=comm1d, token=tok)
        return lo * 10 + hi

    out = _run(comm1d, fn)
    assert np.array_equal(np.asarray(out), np.full(SIZE, 0.0 * 10 + 7.0))


def test_custom_scan_rank_order(comm1d):
    # inclusive prefix with RIGHT-projection == each rank's own value;
    # with LEFT-projection == rank 0's value everywhere.  Exercises the
    # ladder's lower-rank-on-the-left operand order.
    left = m.Op.create(lambda a, b: a, name="left", commute=False)
    right = m.Op.create(lambda a, b: b, name="right", commute=False)

    def fn(x):
        a, tok = m.scan(x, left, comm=comm1d)
        b, tok = m.scan(x, right, comm=comm1d, token=tok)
        return a * 10 + b

    out = np.asarray(_run(comm1d, fn))
    assert np.array_equal(out, np.zeros(SIZE) * 10 + np.arange(8.0))


def test_custom_scan_associative(comm1d):
    # a genuinely mixing associative op: 2x2 matrix product flattened
    # into the last axis (affine-recurrence composition — the classic
    # non-commutative scan payload)
    def matmul2(a, b):
        a2 = a.reshape(*a.shape[:-1], 2, 2)
        b2 = b.reshape(*b.shape[:-1], 2, 2)
        return jnp.matmul(a2, b2).reshape(a.shape)

    op = m.Op.create(matmul2, name="matmul2", commute=False)
    # per-rank matrix [[1, r], [0, 1]]; prefix product = [[1, sum r], [0, 1]]
    def fn(x):
        r = x[0]
        mat = jnp.stack([1.0, r, 0.0, 1.0])[None]  # (1, 4) per rank
        y, _ = m.scan(mat, op, comm=comm1d)
        return y

    out = np.asarray(_run(comm1d, fn))  # (8, 4)
    prefix = np.cumsum(np.arange(8.0))
    expected = np.stack(
        [np.ones(8), prefix, np.zeros(8), np.ones(8)], axis=1
    )
    assert np.allclose(out, expected)


def test_custom_reduce(comm1d):
    my_sum = m.Op.create(jnp.add, name="my_sum")

    def fn(x):
        y, _ = m.reduce(x, my_sum, 0, comm=comm1d)
        return y

    out = _run(comm1d, fn)
    assert np.asarray(out)[0] == 28.0


def test_custom_op_self_backend(selfcomm):
    op = m.Op.create(jnp.minimum, name="my_min")
    y, _ = m.allreduce(jnp.float32(3.0), op, comm=selfcomm)
    assert float(y) == 3.0
    s, _ = m.scan(jnp.float32(4.0), op, comm=selfcomm)
    assert float(s) == 4.0


def test_custom_op_not_differentiable(comm1d):
    op = m.Op.create(jnp.add, name="sum")  # even named "sum"

    def fn(x):
        def loss(v):
            return m.allreduce(v, op, comm=comm1d)[0].sum()

        return jax.grad(loss)(x)

    with pytest.raises(NotImplementedError, match="op=SUM"):
        _run(comm1d, fn)


def test_custom_op_hash_identity():
    f = jnp.add
    a = m.Op.create(f, name="x")
    b = m.Op.create(f, name="x")
    c = m.Op.create(jnp.multiply, name="x")
    assert a == b  # same combine fn + name
    assert a != c  # different combine fn, despite same name
    assert hash(a) == hash(b)


@pytest.mark.parametrize("staged", [False, True], ids=["ffi", "staged"])
def test_custom_op_proc_backend_two_ranks(staged):
    """Op.Create on the multi-process backend (VERDICT r3 missing #1):
    the reference supports arbitrary MPI.Op through every backend
    (mpi4jax/_src/collective_ops/allreduce.py:36-66, utils.py:77-96) —
    here the operands ride the native allgather/gather wire and the
    rank-ordered fold runs on-device.  2 launcher ranks, eager + jit,
    commutative and non-commutative, allreduce/reduce/scan; the staged
    leg covers the accelerator (io_callback) tier."""
    from tests.proc.test_proc_backend import run_workers, PREAMBLE

    proc = run_workers(
        PREAMBLE
        + """
x = jnp.full((4,), float(rank + 1))

# commutative user op matches the builtin
my_max = m.Op.create(jnp.maximum, name="my_max")
y, tok = m.allreduce(x, my_max, comm=comm)
assert np.allclose(np.asarray(y), float(size)), np.asarray(y)

# under jit too
yj, _ = jax.jit(lambda v: m.allreduce(v, my_max, comm=comm))(x)
assert np.allclose(np.asarray(yj), float(size))

# non-commutative rank-order contract (commute=False): LEFT keeps the
# lowest rank's operand, RIGHT the highest's
left = m.Op.create(lambda a, b: a, name="left", commute=False)
right = m.Op.create(lambda a, b: b, name="right", commute=False)
lo, tok = m.allreduce(x, left, comm=comm, token=tok)
hi, tok = m.allreduce(x, right, comm=comm, token=tok)
assert np.allclose(np.asarray(lo), 1.0), np.asarray(lo)
assert np.allclose(np.asarray(hi), float(size)), np.asarray(hi)

# reduce: fold on root, off-root passthrough (wrapper contract)
my_sum = m.Op.create(jnp.add, name="my_sum")
r, tok = m.reduce(x, my_sum, 0, comm=comm, token=tok)
if rank == 0:
    assert np.allclose(np.asarray(r), sum(range(1, size + 1)))
else:
    assert np.allclose(np.asarray(r), x)

# inclusive prefix scan, rank-ordered
s, tok = m.scan(jnp.array([float(rank + 1)]), my_sum, comm=comm, token=tok)
assert np.allclose(np.asarray(s), sum(range(1, rank + 2))), np.asarray(s)
s2, tok = m.scan(jnp.array([float(rank)]), right, comm=comm, token=tok)
assert np.allclose(np.asarray(s2), float(rank)), np.asarray(s2)

print(f"WORKER_OK {rank}", flush=True)
""",
        nprocs=2,
        env={"MPI4JAX_TPU_FORCE_STAGED": "1"} if staged else None,
    )
    for r in range(2):
        assert f"WORKER_OK {r}" in proc.stdout


def test_op_create_mpi4py_spelling(comm1d):
    # compat path: MPI.Op.Create(fn, commute) — mpi4py's exact spelling
    from mpi4jax_tpu.compat import MPI

    op = MPI.Op.Create(jnp.minimum, commute=True)
    assert op.is_user and op.commute

    def fn(x):
        y, _ = m.allreduce(x, op, comm=comm1d)
        return y

    out = _run(comm1d, fn)
    assert np.array_equal(np.asarray(out), np.zeros(SIZE))
