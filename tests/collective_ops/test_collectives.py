"""Value tests for allgather / alltoall / barrier / bcast / gather /
reduce / scan / scatter, mirroring the reference's per-op files
(tests/collective_ops/test_{allgather,alltoall,bcast,...}.py): eager,
jit, and closed-form oracles in rank/size.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as m

from tests.helpers import spmd, spmd_jit

SIZE = 8


def world_input():
    return jnp.arange(float(SIZE))


def _run(comm, fn, x=None, in_specs=None, out_specs=None, jit=True):
    in_specs = in_specs or jax.P(comm.axes)
    out_specs = out_specs or jax.P(comm.axes)
    f = jax.shard_map(fn, mesh=comm.mesh, in_specs=in_specs, out_specs=out_specs)
    if jit:
        f = jax.jit(f)
    return f(world_input() if x is None else x)


@pytest.mark.parametrize("jit", [True, False])
def test_allgather(comm1d, jit):
    def fn(x):
        g, _ = m.allgather(x[0], comm=comm1d)
        return g[None]  # (1, 8) per device

    out = _run(
        comm1d, fn, out_specs=jax.P(comm1d.axes, None), jit=jit
    )  # (8, 8) global
    expected = np.tile(np.arange(8.0), (8, 1))
    assert np.array_equal(np.asarray(out), expected)


@pytest.mark.parametrize("jit", [True, False])
def test_alltoall(comm1d, jit):
    # device r holds row r scaled: in[r] = r*8 + [0..7]; alltoall == transpose
    x = jnp.arange(64.0).reshape(8, 8)

    def fn(v):
        y, _ = m.alltoall(v, comm=comm1d)
        return y

    out = _run(
        comm1d,
        fn,
        x=x,
        in_specs=jax.P(None, comm1d.axes),
        out_specs=jax.P(None, comm1d.axes),
        jit=jit,
    )
    assert np.array_equal(np.asarray(out), np.arange(64.0).reshape(8, 8).T)


def test_alltoall_wrong_leading_dim(comm1d):
    with pytest.raises(ValueError, match=r"shape \(nproc, ...\)"):
        _run(comm1d, lambda v: m.alltoall(v, comm=comm1d)[0])


@pytest.mark.parametrize("root", [0, 3])
def test_bcast(comm1d, root):
    def fn(x):
        y, _ = m.bcast(x * 10, root, comm=comm1d)
        return y

    out = _run(comm1d, fn)
    assert np.array_equal(np.asarray(out), np.full(SIZE, 10.0 * root))


@pytest.mark.parametrize("schedule", ["tree", "psum"])
@pytest.mark.parametrize("root", [0, 3])
def test_bcast_schedules_agree(comm1d, root, schedule, monkeypatch):
    monkeypatch.setenv("MPI4JAX_TPU_BCAST", schedule)

    def fn(x):
        y, _ = m.bcast(x * 10, root, comm=comm1d)
        return y

    out = _run(comm1d, fn)
    assert np.array_equal(np.asarray(out), np.full(SIZE, 10.0 * root))


def test_bcast_bool(comm1d):
    def fn(x):
        y, _ = m.bcast(x[0] > 2, 5, comm=comm1d)
        return y[None].astype(jnp.float32)

    out = _run(comm1d, fn)
    assert np.array_equal(np.asarray(out), np.ones(SIZE))


@pytest.mark.parametrize("root", [0, 2])
def test_gather(comm1d, root):
    def fn(x):
        g, _ = m.gather(x[0], root, comm=comm1d)
        return g[None]

    out = _run(comm1d, fn, out_specs=jax.P(comm1d.axes, None))
    # root's row must hold every rank's value (off-root rows also valid here)
    assert np.array_equal(np.asarray(out)[root], np.arange(8.0))


@pytest.mark.parametrize("root", [0, 6])
def test_scatter(comm1d, root):
    def fn(x):
        # every rank passes (size,) template; only root's values matter
        payload = jnp.arange(8.0) * 100 if True else x
        payload = jnp.where(x[0] == root, payload, jnp.zeros(8))
        y, _ = m.scatter(payload, root, comm=comm1d)
        return y[None]

    out = _run(comm1d, fn)
    assert np.array_equal(np.asarray(out), np.arange(8.0) * 100)


@pytest.mark.parametrize("op,expected", [(m.SUM, 28.0), (m.MAX, 7.0)])
def test_reduce(comm1d, op, expected):
    def fn(x):
        y, _ = m.reduce(x, op, 0, comm=comm1d)
        return y

    out = _run(comm1d, fn)
    assert np.asarray(out)[0] == expected  # root's value


@pytest.mark.parametrize("op", [m.SUM, m.PROD, m.MAX, m.MIN])
def test_scan(comm1d, op):
    def fn(x):
        y, _ = m.scan(x + 1, op, comm=comm1d)
        return y

    out = np.asarray(_run(comm1d, fn))
    vals = np.arange(8.0) + 1
    expected = np.array(
        [
            {
                "sum": np.sum,
                "prod": np.prod,
                "max": np.max,
                "min": np.min,
            }[op.name](vals[: r + 1])
            for r in range(8)
        ]
    )
    assert np.array_equal(out, expected)


def test_scan_2d_comm(comm2d):
    def fn(x):
        y, _ = m.scan(x, m.SUM, comm=comm2d)
        return y

    out = np.asarray(_run(comm2d, fn))
    assert np.array_equal(out, np.cumsum(np.arange(8.0)))


def test_barrier(comm1d):
    def fn(x):
        tok = m.create_token()
        tok = m.barrier(comm=comm1d, token=tok)
        y, tok = m.allreduce(x, m.SUM, comm=comm1d, token=tok)
        tok = m.barrier(comm=comm1d, token=tok)
        return y

    out = _run(comm1d, fn)
    assert np.array_equal(np.asarray(out), np.full(SIZE, 28.0))


def test_chained_mixed_ops(comm1d):
    # one token chain through five different collectives
    def fn(x):
        tok = m.create_token()
        a, tok = m.allreduce(x, m.SUM, comm=comm1d, token=tok)
        b, tok = m.bcast(x * 2, 1, comm=comm1d, token=tok)
        g, tok = m.allgather(x[0], comm=comm1d, token=tok)
        s, tok = m.scan(x, m.SUM, comm=comm1d, token=tok)
        tok = m.barrier(comm=comm1d, token=tok)
        return a + b + s + g.sum()

    out = np.asarray(_run(comm1d, fn))
    ranks = np.arange(8.0)
    expected = 28.0 + 2.0 + np.cumsum(ranks) + 28.0
    assert np.array_equal(out, expected)


def test_allgather_grad(comm1d):
    # AD through allgather is a superset of the reference (which defines
    # no rules); verify it is at least consistent: d/dx sum(allgather(x))
    f = spmd_jit(comm1d, lambda x: m.allgather(x[0], comm=comm1d)[0][:1])

    def loss(x):
        return f(x).sum()

    g = jax.grad(loss)(world_input())
    assert g.shape == (8,)
