"""allreduce test matrix, mirroring the reference's
tests/collective_ops/test_allreduce.py: eager / jit / scalar / vmap plus
the full AD battery (grad, jvp, vjp, linear_transpose, double transpose,
chained-token grad) with closed-form oracles in rank/size.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as m

from tests.helpers import spmd, spmd_jit

SIZE = 8


def world_input():
    # per-device value = rank (per-device shape (1,))
    return jnp.arange(float(SIZE))


def test_allreduce_sum_eager(comm1d):
    out = spmd(comm1d, lambda x: m.allreduce(x, m.SUM, comm=comm1d)[0])(world_input())
    assert np.array_equal(np.asarray(out), np.full(SIZE, SIZE * (SIZE - 1) / 2))


def test_allreduce_sum_jit(comm1d):
    out = spmd_jit(comm1d, lambda x: m.allreduce(x, m.SUM, comm=comm1d)[0])(
        world_input()
    )
    assert np.array_equal(np.asarray(out), np.full(SIZE, 28.0))


def test_allreduce_scalar(comm1d):
    def fn(x):
        res, _ = m.allreduce(x[0], m.SUM, comm=comm1d)
        return res[None]

    out = spmd_jit(comm1d, fn)(world_input())
    assert np.array_equal(np.asarray(out), np.full(SIZE, 28.0))


@pytest.mark.parametrize(
    "op,expected",
    [
        (m.MAX, 7.0),
        (m.MIN, 0.0),
        (m.PROD, 0.0),
    ],
)
def test_allreduce_other_ops(comm1d, op, expected):
    out = spmd_jit(comm1d, lambda x: m.allreduce(x, op, comm=comm1d)[0])(world_input())
    assert np.array_equal(np.asarray(out), np.full(SIZE, expected))


def test_allreduce_prod_nonzero(comm1d):
    out = spmd_jit(comm1d, lambda x: m.allreduce(x + 1, m.PROD, comm=comm1d)[0])(
        world_input()
    )
    import math

    assert np.array_equal(np.asarray(out), np.full(SIZE, float(math.factorial(8))))


def test_allreduce_logical(comm1d):
    def fn(x):
        flag = x[0] > 3  # True on ranks 4..7
        a, tok = m.allreduce(flag, m.LAND, comm=comm1d)
        o, tok = m.allreduce(flag, m.LOR, comm=comm1d, token=tok)
        x_, tok = m.allreduce(flag, m.LXOR, comm=comm1d, token=tok)
        return jnp.stack([a, o, x_])[None].astype(jnp.float32)

    out = np.asarray(
        jax.jit(
            jax.shard_map(
                fn,
                mesh=comm1d.mesh,
                in_specs=jax.P(comm1d.axes),
                out_specs=jax.P(comm1d.axes, None),
            )
        )(world_input())
    )
    assert np.array_equal(out[0], [0.0, 1.0, 0.0])  # 4 Trues: and=F or=T xor=F


def test_allreduce_bitwise(comm1d):
    def fn(x):
        v = x.astype(jnp.int32)
        a, tok = m.allreduce(v, m.BOR, comm=comm1d)
        b, tok = m.allreduce(v, m.BAND, comm=comm1d, token=tok)
        c, tok = m.allreduce(v, m.BXOR, comm=comm1d, token=tok)
        return a, b, c

    f = jax.jit(
        jax.shard_map(
            fn,
            mesh=comm1d.mesh,
            in_specs=jax.P(comm1d.axes),
            out_specs=(jax.P(comm1d.axes),) * 3,
        )
    )
    a, b, c = f(world_input())
    ranks = np.arange(8)
    assert np.array_equal(np.asarray(a), np.full(8, np.bitwise_or.reduce(ranks)))
    assert np.array_equal(np.asarray(b), np.full(8, np.bitwise_and.reduce(ranks)))
    assert np.array_equal(np.asarray(c), np.full(8, np.bitwise_xor.reduce(ranks)))


def test_allreduce_vmap(comm1d):
    def fn(x):
        batched = jnp.stack([x, 2 * x, 3 * x])  # (3, 1) per device
        out = jax.vmap(lambda v: m.allreduce(v, m.SUM, comm=comm1d)[0])(batched)
        return out.sum(axis=0)

    out = spmd_jit(comm1d, fn)(world_input())
    assert np.array_equal(np.asarray(out), np.full(SIZE, 6 * 28.0))


# ---- AD battery (reference: test_allreduce.py:79-221) ----


def _allreduce_fn(comm):
    return spmd_jit(comm, lambda x: m.allreduce(x, m.SUM, comm=comm)[0])


def test_allreduce_transpose(comm1d):
    f = _allreduce_fn(comm1d)
    x = world_input()
    (res,) = jax.linear_transpose(f, x)(x)
    assert np.array_equal(np.asarray(res), np.asarray(x))


def test_allreduce_transpose2(comm1d):
    f = _allreduce_fn(comm1d)
    x = world_input()

    def lt(y):
        return jax.linear_transpose(f, x)(y)[0]

    (res,) = jax.linear_transpose(lt, x)(jnp.ones(SIZE))
    expected = f(jnp.ones(SIZE))
    assert np.array_equal(np.asarray(res), np.asarray(expected))


def test_allreduce_transpose3(comm1d):
    # triple transpose = single transpose = identity
    f = _allreduce_fn(comm1d)
    x = world_input()

    def lt(y):
        return jax.linear_transpose(f, x)(y)[0]

    def lt2(y):
        return jax.linear_transpose(lt, x)(y)[0]

    (res,) = jax.linear_transpose(lt2, x)(x)
    assert np.array_equal(np.asarray(res), np.asarray(x))


def test_allreduce_grad(comm1d):
    f = _allreduce_fn(comm1d)
    x = world_input()
    res, grad = jax.value_and_grad(lambda v: f(v).sum())(x)
    assert np.asarray(res) == pytest.approx(8 * 28.0)
    assert np.array_equal(np.asarray(grad), np.ones(SIZE))


def test_allreduce_jvp(comm1d):
    f = _allreduce_fn(comm1d)
    x = world_input()
    res, tangent = jax.jvp(f, (x,), (x,))
    assert np.array_equal(np.asarray(res), np.full(SIZE, 28.0))
    assert np.array_equal(np.asarray(tangent), np.full(SIZE, 28.0))


def test_allreduce_vjp(comm1d):
    f = _allreduce_fn(comm1d)
    x = world_input()
    res, vjp_fun = jax.vjp(f, x)
    (vjp,) = vjp_fun(x)
    assert np.array_equal(np.asarray(res), np.full(SIZE, 28.0))
    assert np.array_equal(np.asarray(vjp), np.asarray(x))


def test_allreduce_chained_grad(comm1d):
    # reference: test_allreduce_chained — d/dx of two token-chained
    # allreduces of the same scalar = 2
    def fn(x):
        tok = m.create_token()
        x1, tok = m.allreduce(x, m.SUM, comm=comm1d, token=tok)
        x2, tok = m.allreduce(x, m.SUM, comm=comm1d, token=tok)
        return (x1 + x2).sum()

    def global_fn(x):
        return (
            jax.shard_map(
                lambda v: jax.grad(fn)(v[0])[None],
                mesh=comm1d.mesh,
                in_specs=jax.P(comm1d.axes),
                out_specs=jax.P(comm1d.axes),
            )(x)
        )

    res = jax.jit(global_fn)(world_input())
    assert np.array_equal(np.asarray(res), np.full(SIZE, 2.0))


def test_allreduce_nonsum_grad_raises(comm1d):
    f = spmd_jit(comm1d, lambda x: m.allreduce(x, m.MAX, comm=comm1d)[0])
    with pytest.raises(NotImplementedError):
        jax.grad(lambda v: f(v).sum())(world_input())


def test_allreduce_2d_comm(comm2d):
    out = jax.jit(
        jax.shard_map(
            lambda x: m.allreduce(x, m.SUM, comm=comm2d)[0],
            mesh=comm2d.mesh,
            in_specs=jax.P(comm2d.axes),
            out_specs=jax.P(comm2d.axes),
        )
    )(world_input())
    assert np.array_equal(np.asarray(out), np.full(SIZE, 28.0))


def test_allreduce_subcomm(comm2d):
    # reduce only over the "x" axis: 2 independent row groups of 4
    row = comm2d.sub("x")
    out = jax.jit(
        jax.shard_map(
            lambda x: m.allreduce(x, m.SUM, comm=row)[0],
            mesh=comm2d.mesh,
            in_specs=jax.P(comm2d.axes),
            out_specs=jax.P(comm2d.axes),
        )
    )(world_input())
    # ranks 0-3 sum to 6, ranks 4-7 sum to 22
    assert np.array_equal(np.asarray(out), [6, 6, 6, 6, 22, 22, 22, 22])
