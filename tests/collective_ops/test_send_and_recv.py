"""send/recv pairing tests, mirroring the reference's
tests/collective_ops/test_send_and_recv.py (including the deadlock
regression shape at :104-117 — here deadlock-freedom holds by
construction because the matched pair lowers to one ppermute, but the
ordering and matching semantics still need coverage).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as m

from tests.helpers import spmd_jit

SIZE = 8


def world_input():
    return jnp.arange(float(SIZE))


def test_send_then_recv(comm1d):
    def fn(x):
        tok = m.create_token()
        tok = m.send(x, lambda r: (r + 1) % SIZE, comm=comm1d, token=tok)
        y, tok = m.recv(x, lambda r: (r - 1) % SIZE, comm=comm1d, token=tok)
        return y

    out = spmd_jit(comm1d, fn)(world_input())
    assert np.array_equal(np.asarray(out), np.roll(np.arange(8.0), 1))


def test_two_sends_two_recvs_fifo(comm1d):
    # same pattern + same tag: recvs must match sends in FIFO order
    def fn(x):
        tok = m.create_token()
        tok = m.send(x, lambda r: (r + 1) % SIZE, tag=0, comm=comm1d, token=tok)
        tok = m.send(10 * x, lambda r: (r + 1) % SIZE, tag=0, comm=comm1d, token=tok)
        a, tok = m.recv(x, lambda r: (r - 1) % SIZE, tag=0, comm=comm1d, token=tok)
        b, tok = m.recv(x, lambda r: (r - 1) % SIZE, tag=0, comm=comm1d, token=tok)
        return a + b  # shifted(x) + shifted(10x) = 11 * shifted(x)

    out = spmd_jit(comm1d, fn)(world_input())
    assert np.array_equal(np.asarray(out), 11 * np.roll(np.arange(8.0), 1))


def test_tag_matching(comm1d):
    # recv with tag=2 must skip the staged tag=1 send
    def fn(x):
        tok = m.create_token()
        tok = m.send(x, lambda r: (r + 1) % SIZE, tag=1, comm=comm1d, token=tok)
        tok = m.send(-x, lambda r: (r + 1) % SIZE, tag=2, comm=comm1d, token=tok)
        b, tok = m.recv(x, lambda r: (r - 1) % SIZE, tag=2, comm=comm1d, token=tok)
        a, tok = m.recv(x, lambda r: (r - 1) % SIZE, tag=1, comm=comm1d, token=tok)
        return 100 * b + a

    out = spmd_jit(comm1d, fn)(world_input())
    shifted = np.roll(np.arange(8.0), 1)
    assert np.array_equal(np.asarray(out), -100 * shifted + shifted)


def test_any_tag_any_source(comm1d):
    def fn(x):
        tok = m.create_token()
        tok = m.send(x, lambda r: (r + 3) % SIZE, tag=9, comm=comm1d, token=tok)
        y, tok = m.recv(x, m.ANY_SOURCE, m.ANY_TAG, comm=comm1d, token=tok)
        return y

    out = spmd_jit(comm1d, fn)(world_input())
    assert np.array_equal(np.asarray(out), np.roll(np.arange(8.0), 3))


def test_recv_without_send_raises(comm1d):
    with pytest.raises(RuntimeError, match="no matching in-trace send"):
        spmd_jit(
            comm1d,
            lambda x: m.recv(x, lambda r: (r - 1) % SIZE, comm=comm1d)[0],
        )(world_input())


def test_undrained_token_detectable(comm1d):
    def fn(x):
        tok = m.create_token()
        tok = m.send(x, lambda r: (r + 1) % SIZE, comm=comm1d, token=tok)
        with pytest.raises(RuntimeError, match="unmatched send"):
            tok.assert_drained()
        y, tok = m.recv(x, lambda r: (r - 1) % SIZE, comm=comm1d, token=tok)
        tok.assert_drained()
        return y

    spmd_jit(comm1d, fn)(world_input())


def test_send_recv_through_jit_boundary(comm1d):
    # a send staged inside one jit can be received after it: the pending
    # payload rides the token pytree across the boundary
    def stage(x):
        tok = m.create_token()
        return m.send(x, lambda r: (r + 1) % SIZE, comm=comm1d, token=tok)

    def consume(x, tok):
        y, tok = m.recv(x, lambda r: (r - 1) % SIZE, comm=comm1d, token=tok)
        return y

    def fn(x):
        tok = stage(x)
        return consume(x, tok)

    out = spmd_jit(comm1d, fn)(world_input())
    assert np.array_equal(np.asarray(out), np.roll(np.arange(8.0), 1))


def test_sendrecv_to_self(selfcomm):
    # reference regression: sendrecv-to-self must not hang
    # (test_common.py:91-115); here it is a local identity
    def fn(x):
        tok = m.create_token()
        tok = m.send(x, 0, comm=selfcomm, token=tok)
        y, tok = m.recv(x, 0, comm=selfcomm, token=tok)
        return y

    x = jnp.arange(4.0)
    out = jax.jit(fn)(x)
    assert np.array_equal(np.asarray(out), np.arange(4.0))


def test_recv_invalid_source_size1(selfcomm):
    tok = m.create_token()
    tok = m.send(jnp.ones(3), 0, comm=selfcomm, token=tok)
    with pytest.raises(ValueError, match="out of range"):
        m.recv(jnp.ones(3), 5, comm=selfcomm, token=tok)


def test_out_of_range_partner_callable(comm1d):
    with pytest.raises(ValueError, match="out of range"):
        spmd_jit(
            comm1d,
            lambda x: m.sendrecv(
                x, x, source=lambda r: r - 1, dest=lambda r: r + 1, comm=comm1d
            )[0],
        )(world_input())
