"""Dtype-matrix and vmap coverage across ops.

The reference supports 14 dtypes through its MPI datatype map
(mpi4jax/_src/utils.py:43-71, incl. bool and complex) and exercises
vmap/vmap+jit per op (e.g. tests/collective_ops/test_allreduce.py:55-76);
this is the equivalent battery for the mesh backend.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as m
from tests.helpers import spmd, spmd_jit

SIZE = 8

DTYPES = [
    jnp.float32,
    jnp.float16,
    jnp.bfloat16,
    jnp.int8,
    jnp.int32,
    jnp.uint8,
    jnp.uint32,
    jnp.complex64,
    jnp.bool_,
]


def _world(dtype):
    if dtype == jnp.bool_:
        return jnp.array([False] * (SIZE - 1) + [True])
    if dtype == jnp.complex64:
        return (jnp.arange(SIZE) * (1 + 1j)).astype(dtype)
    return jnp.arange(SIZE).astype(dtype)


@pytest.mark.parametrize("dtype", DTYPES, ids=lambda d: jnp.dtype(d).name)
def test_allreduce_sum_dtypes(comm1d, dtype):
    x = _world(dtype)
    out = spmd_jit(comm1d, lambda v: m.allreduce(v, m.SUM, comm=comm1d)[0])(x)
    assert out.dtype == x.dtype, (out.dtype, x.dtype)
    if dtype == jnp.bool_:
        expected = np.full(SIZE, True)
    else:
        expected = np.full(SIZE, np.asarray(x).sum(), np.asarray(x).dtype)
    assert np.array_equal(np.asarray(out), expected), out


@pytest.mark.parametrize("dtype", DTYPES, ids=lambda d: jnp.dtype(d).name)
def test_reduce_scatter_sum_dtypes(comm1d, dtype):
    # extension op: same dtype battery as allreduce; identity
    # reduce_scatter(x)[rank] == allreduce-sum of the per-rank rows
    x = _world(dtype)

    def fn(v):
        rows = jnp.broadcast_to(v, (SIZE, 1))
        y, _ = m.reduce_scatter(rows, comm=comm1d)
        return y

    out = spmd_jit(comm1d, fn)(x)
    assert out.dtype == x.dtype, (out.dtype, x.dtype)
    if dtype == jnp.bool_:
        expected = np.full(SIZE, True)  # one rank contributes True
    else:
        expected = np.full(SIZE, np.asarray(x).sum(), np.asarray(x).dtype)
    assert np.array_equal(np.asarray(out), expected), out


@pytest.mark.parametrize("dtype", DTYPES, ids=lambda d: jnp.dtype(d).name)
def test_bcast_allgather_dtypes(comm1d, dtype):
    x = _world(dtype)
    b = spmd_jit(comm1d, lambda v: m.bcast(v, 3, comm=comm1d)[0])(x)
    assert b.dtype == x.dtype
    assert np.array_equal(np.asarray(b), np.full(SIZE, np.asarray(x)[3]))
    g = spmd_jit(
        comm1d, lambda v: m.allgather(v, comm=comm1d)[0].reshape(-1)
    )(x)
    assert g.dtype == x.dtype
    assert np.array_equal(np.asarray(g), np.tile(np.asarray(x), SIZE))


@pytest.mark.parametrize("dtype", [jnp.float16, jnp.int8, jnp.complex64, jnp.bool_],
                         ids=lambda d: jnp.dtype(d).name)
def test_sendrecv_ring_dtypes(comm1d, dtype):
    x = _world(dtype)
    shift = [(r, (r + 1) % SIZE) for r in range(SIZE)]
    out = spmd_jit(
        comm1d,
        lambda v: m.sendrecv(v, v, source=shift, dest=shift, comm=comm1d)[0],
    )(x)
    assert out.dtype == x.dtype
    assert np.array_equal(np.asarray(out), np.roll(np.asarray(x), 1))


@pytest.mark.parametrize("jit", [False, True])
def test_bcast_vmap(comm1d, jit):
    # batch dim inside the per-device function; comm dim via shard_map
    x = jnp.arange(SIZE * 3.0).reshape(SIZE, 3)

    def fn(v):  # v: (1, 3) per device -> vmap over the 3 columns
        return jax.vmap(lambda c: m.bcast(c, 2, comm=comm1d)[0], in_axes=1, out_axes=1)(v)

    runner = spmd_jit(comm1d, fn) if jit else spmd(comm1d, fn)
    out = runner(x)
    assert np.allclose(np.asarray(out), np.tile(np.asarray(x)[2], (SIZE, 1)))


@pytest.mark.parametrize("jit", [False, True])
def test_allgather_vmap(comm1d, jit):
    x = jnp.arange(SIZE * 2.0).reshape(SIZE, 2)

    def fn(v):
        return jax.vmap(
            lambda c: m.allgather(c, comm=comm1d)[0], in_axes=1, out_axes=1
        )(v)

    runner = spmd_jit(comm1d, fn) if jit else spmd(comm1d, fn)
    out = runner(x)  # (SIZE, gathered=SIZE, 2)
    expected = np.broadcast_to(
        np.arange(SIZE * 2.0).reshape(SIZE, 2), (SIZE, SIZE, 2)
    )
    assert np.allclose(np.asarray(out).reshape(SIZE, SIZE, 2), expected)


@pytest.mark.parametrize("jit", [False, True])
def test_sendrecv_vmap(comm1d, jit):
    x = jnp.arange(SIZE * 4.0).reshape(SIZE, 4)
    shift = [(r, (r + 1) % SIZE) for r in range(SIZE)]

    def fn(v):
        return jax.vmap(
            lambda c: m.sendrecv(c, c, source=shift, dest=shift, comm=comm1d)[0],
            in_axes=1,
            out_axes=1,
        )(v)

    runner = spmd_jit(comm1d, fn) if jit else spmd(comm1d, fn)
    out = runner(x)
    expected = np.roll(np.arange(SIZE * 4.0).reshape(SIZE, 4), 1, axis=0)
    assert np.allclose(np.asarray(out), expected)


def test_scalar_ops(comm1d):
    # scalar (0-d) payloads through reduce/scan/gather (reference scalar
    # cases, e.g. test_allreduce.py scalar variants)
    def fn(v):
        s = v[0]
        r, tok = m.reduce(s, m.SUM, 0, comm=comm1d)
        sc, tok = m.scan(s, m.SUM, comm=comm1d, token=tok)
        g, tok = m.gather(s, 0, comm=comm1d, token=tok)
        return (r[None] if r.ndim == 0 else r[:1]), sc[None], g.reshape(-1)[:1]

    r, sc, g = spmd_jit(comm1d, fn)(jnp.arange(SIZE * 1.0))
    # rank 0 rows hold the rooted results
    assert np.asarray(r)[0] == 28.0
    assert np.allclose(np.asarray(sc).ravel(), np.cumsum(np.arange(8.0)))
