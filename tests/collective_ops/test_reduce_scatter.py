"""reduce_scatter (extension op, MPI_Reduce_scatter_block semantics):
value tests against the allreduce identity, non-SUM / user-defined /
bool operators, grouped (split) comms, and the AD battery for SUM
(composition transposes to all_gather).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as m

SIZE = 8


def _run(comm, fn, x, in_specs, out_specs, jit=True):
    f = jax.shard_map(fn, mesh=comm.mesh, in_specs=in_specs, out_specs=out_specs)
    if jit:
        f = jax.jit(f)
    return f(x)


@pytest.mark.parametrize("jit", [True, False])
def test_reduce_scatter_sum(comm1d, jit):
    # every device contributes rows scaled by its rank; row r of the sum
    # lands on rank r
    x = jnp.arange(float(SIZE))  # sharded: device r holds [r]

    def fn(v):
        # v is (1,): this device's rank value; contribution to rank r is
        # v * (r+1)
        rows = jnp.broadcast_to(v, (SIZE, 1)) * jnp.arange(1.0, SIZE + 1)[:, None]
        y, _ = m.reduce_scatter(rows, comm=comm1d)
        return y

    out = _run(
        comm1d, fn, x, in_specs=jax.P(comm1d.axes), out_specs=jax.P(comm1d.axes)
    )
    # rank r receives sum_src (src * (r+1)) = (r+1) * sum(0..7)
    expected = np.arange(1.0, SIZE + 1) * np.arange(float(SIZE)).sum()
    assert np.allclose(np.asarray(out), expected)


def test_reduce_scatter_equals_allreduce_row(comm1d):
    # identity: reduce_scatter(x)[rank] == allreduce(x)[rank]
    key = jax.random.PRNGKey(0)
    xs = jax.random.normal(key, (SIZE, SIZE, 3))  # per-device (SIZE, 3)

    def fn(v):
        rs, _ = m.reduce_scatter(v[0], comm=comm1d)
        ar, _ = m.allreduce(v[0], comm=comm1d)
        rank = comm1d.rank()
        return (rs - jax.lax.dynamic_index_in_dim(ar, rank, 0, False))[None]

    out = _run(
        comm1d, fn, xs,
        in_specs=jax.P(comm1d.axes, None, None),
        out_specs=jax.P(comm1d.axes, None),
    )
    assert np.allclose(np.asarray(out), 0.0, atol=1e-6)


@pytest.mark.parametrize("opname, expect", [
    ("max", lambda cols: cols.max(0)),
    ("min", lambda cols: cols.min(0)),
    ("prod", lambda cols: cols.prod(0)),
])
def test_reduce_scatter_named_ops(comm1d, opname, expect):
    rng = np.random.RandomState(1)
    xs = rng.randint(1, 5, size=(SIZE, SIZE)).astype(np.float32)

    def fn(v):
        y, _ = m.reduce_scatter(v[0], op=opname, comm=comm1d)
        return y[None]

    out = _run(
        comm1d, fn, jnp.asarray(xs),
        in_specs=jax.P(comm1d.axes, None),
        out_specs=jax.P(comm1d.axes),
    )
    assert np.allclose(np.asarray(out), expect(xs))


def test_reduce_scatter_user_op_rank_order(comm1d):
    # non-commutative user op: a*0.5 + b — result depends on fold order,
    # so this pins the rank-ordered (commute=False) contract
    op = m.Op.create(lambda a, b: 0.5 * a + b, name="halfsum", commute=False)
    xs = jnp.arange(float(SIZE * SIZE)).reshape(SIZE, SIZE)

    def fn(v):
        y, _ = m.reduce_scatter(v[0], op=op, comm=comm1d)
        return y[None]

    out = _run(
        comm1d, fn, xs,
        in_specs=jax.P(comm1d.axes, None),
        out_specs=jax.P(comm1d.axes),
    )
    cols = np.asarray(xs)  # contribution of src s to rank r: xs[s, r]
    expected = []
    for r in range(SIZE):
        acc = cols[0, r]
        for s in range(1, SIZE):
            acc = 0.5 * acc + cols[s, r]
        expected.append(acc)
    assert np.allclose(np.asarray(out), np.asarray(expected))


def test_reduce_scatter_bool(comm1d):
    # LOR via op="lor" on bool payloads
    xs = np.zeros((SIZE, SIZE), bool)
    xs[3, 5] = True  # only src 3 contributes, to rank 5

    def fn(v):
        y, _ = m.reduce_scatter(v[0], op="lor", comm=comm1d)
        return y[None].astype(jnp.float32)

    out = _run(
        comm1d, fn, jnp.asarray(xs),
        in_specs=jax.P(comm1d.axes, None),
        out_specs=jax.P(comm1d.axes),
    )
    expected = np.zeros(SIZE)
    expected[5] = 1.0
    assert np.array_equal(np.asarray(out), expected)


def test_reduce_scatter_wrong_leading_dim(comm1d):
    with pytest.raises(ValueError, match=r"shape \(nproc, ...\)"):
        _run(
            comm1d,
            lambda v: m.reduce_scatter(v, comm=comm1d)[0],
            jnp.arange(float(SIZE)),
            in_specs=jax.P(comm1d.axes),
            out_specs=jax.P(comm1d.axes),
        )


def test_reduce_scatter_split_groups(comm1d):
    # split into two 4-rank halves: reductions stay within each half
    sub = comm1d.split(lambda r: r // 4)
    xs = jnp.ones((SIZE, 4))

    def fn(v):
        y, _ = m.reduce_scatter(v[0], comm=sub)
        return y[None]

    out = _run(
        comm1d, fn, xs,
        in_specs=jax.P(comm1d.axes, None),
        out_specs=jax.P(comm1d.axes),
    )
    assert np.allclose(np.asarray(out), 4.0)


def test_reduce_scatter_grad(comm1d):
    # d/dx of sum(reduce_scatter(x)) — every element of every rank's
    # input contributes exactly once, so the grad is all-ones
    xs = jax.random.normal(jax.random.PRNGKey(2), (SIZE, SIZE, 2))

    def fn(v):
        def loss(u):
            y, _ = m.reduce_scatter(u, comm=comm1d)
            return (y * y).sum()

        val, g = jax.value_and_grad(loss)(v[0])
        return g[None]

    out = _run(
        comm1d, fn, xs,
        in_specs=jax.P(comm1d.axes, None, None),
        out_specs=jax.P(comm1d.axes, None, None),
    )
    # analytic: grad wrt x_src[r] = 2 * (sum over srcs of x[r]) — the
    # cotangent of psum_scatter is an all_gather of 2*y
    sums = np.asarray(xs).sum(axis=0)  # (SIZE, 2) row r = rank r's result
    expected = 2.0 * np.broadcast_to(sums[None], (SIZE, SIZE, 2))
    assert np.allclose(np.asarray(out), expected, atol=1e-5)


def test_reduce_scatter_jvp(comm1d):
    xs = jax.random.normal(jax.random.PRNGKey(3), (SIZE, SIZE))
    ts = jax.random.normal(jax.random.PRNGKey(4), (SIZE, SIZE))

    def fn(v, t):
        def f(u):
            y, _ = m.reduce_scatter(u, comm=comm1d)
            return y

        y, yt = jax.jvp(f, (v[0],), (t[0],))
        return y[None], yt[None]

    f = jax.shard_map(
        fn, mesh=comm1d.mesh,
        in_specs=(jax.P(comm1d.axes, None), jax.P(comm1d.axes, None)),
        out_specs=(jax.P(comm1d.axes), jax.P(comm1d.axes)),
    )
    y, yt = jax.jit(f)(xs, ts)
    assert np.allclose(np.asarray(y), np.asarray(xs).sum(0), atol=1e-5)
    assert np.allclose(np.asarray(yt), np.asarray(ts).sum(0), atol=1e-5)


def test_reduce_scatter_self():
    comm = m.SelfComm()
    y, _ = m.reduce_scatter(jnp.arange(3.0)[None], comm=comm)
    assert np.array_equal(np.asarray(y), np.arange(3.0))
