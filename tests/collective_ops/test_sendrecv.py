"""sendrecv tests, mirroring tests/collective_ops/test_sendrecv.py of the
reference plus the transpose rule (sendrecv.py:366-385: gradients travel
the reverse ring direction).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as m

from tests.helpers import spmd, spmd_jit

SIZE = 8


def world_input():
    return jnp.arange(float(SIZE))


def ring_fn(comm, disp=1):
    def fn(x):
        y, _ = m.sendrecv(
            x,
            x,
            source=lambda r: (r - disp) % SIZE,
            dest=lambda r: (r + disp) % SIZE,
            comm=comm,
        )
        return y

    return fn


@pytest.mark.parametrize("jit", [True, False])
def test_sendrecv_ring(comm1d, jit):
    f = spmd(comm1d, ring_fn(comm1d))
    if jit:
        f = jax.jit(f)
    out = f(world_input())
    assert np.array_equal(np.asarray(out), np.roll(np.arange(8.0), 1))


def test_sendrecv_perm_pairs(comm1d):
    # explicit (source, dest) pair list, reversed ring
    pairs = [(r, (r - 1) % SIZE) for r in range(SIZE)]

    def fn(x):
        y, _ = m.sendrecv(x, x, source=pairs, dest=pairs, comm=comm1d)
        return y

    out = spmd_jit(comm1d, fn)(world_input())
    assert np.array_equal(np.asarray(out), np.roll(np.arange(8.0), -1))


def test_sendrecv_transpose(comm1d):
    # transpose of a +1 ring shift is a -1 ring shift
    f = spmd_jit(comm1d, ring_fn(comm1d))
    x = world_input()
    (res,) = jax.linear_transpose(f, x)(x)
    assert np.array_equal(np.asarray(res), np.roll(np.arange(8.0), -1))


def test_sendrecv_grad(comm1d):
    f = spmd_jit(comm1d, ring_fn(comm1d))
    g = jax.grad(lambda v: (f(v) * jnp.arange(8.0)).sum())(world_input())
    # dL/dx_r = weight at the rank x_r was shifted to = (r+1) % 8
    assert np.array_equal(np.asarray(g), np.roll(np.arange(8.0), -1))


def test_sendrecv_jvp(comm1d):
    # forward mode works here (the reference hard-errors, sendrecv.py:128-133)
    f = spmd_jit(comm1d, ring_fn(comm1d))
    x = world_input()
    _, tangent = jax.jvp(f, (x,), (x,))
    assert np.array_equal(np.asarray(tangent), np.roll(np.arange(8.0), 1))


def test_sendrecv_nonperiodic(comm1d):
    # MPI_PROC_NULL analog: edge ranks keep their recv buffer
    def fn(x):
        recvbuf = jnp.full_like(x, -5.0)
        y, _ = m.sendrecv(
            x,
            recvbuf,
            source=lambda r: r - 1 if r > 0 else None,
            dest=lambda r: r + 1 if r < SIZE - 1 else None,
            comm=comm1d,
        )
        return y

    out = spmd_jit(comm1d, fn)(world_input())
    assert np.array_equal(np.asarray(out), [-5.0, 0, 1, 2, 3, 4, 5, 6])


def test_sendrecv_status(comm1d):
    def fn(x):
        status = m.Status()
        y, _ = m.sendrecv(
            x,
            x,
            source=lambda r: (r - 1) % SIZE,
            dest=lambda r: (r + 1) % SIZE,
            sendtag=3,
            comm=comm1d,
            status=status,
        )
        return y + status.source.astype(jnp.float32)

    out = spmd_jit(comm1d, fn)(world_input())
    expected = np.roll(np.arange(8.0), 1) + (np.arange(8) - 1) % 8
    assert np.array_equal(np.asarray(out), expected)


def test_sendrecv_mismatched_views(comm1d):
    with pytest.raises(ValueError, match="disagree"):
        spmd_jit(
            comm1d,
            lambda x: m.sendrecv(
                x,
                x,
                source=lambda r: (r + 1) % SIZE,  # wrong: same direction as dest
                dest=lambda r: (r + 1) % SIZE,
                comm=comm1d,
            )[0],
        )(world_input())


def test_sendrecv_int_dest_raises(comm1d):
    with pytest.raises(ValueError, match="permutation"):
        spmd_jit(
            comm1d,
            lambda x: m.sendrecv(x, x, source=0, dest=1, comm=comm1d)[0],
        )(world_input())


def test_sendrecv_2d_shift(comm2d):
    # shift along the x axis of a (2,4) grid via comm.shift_perm
    pairs = comm2d.shift_perm("x", 1, periodic=True)

    def fn(x):
        y, _ = m.sendrecv(x, x, source=pairs, dest=pairs, comm=comm2d)
        return y

    out = spmd_jit(comm2d, fn)(world_input())
    expected = np.concatenate([np.roll(np.arange(4.0), 1), np.roll(np.arange(4.0, 8.0), 1)])
    assert np.array_equal(np.asarray(out), expected)
