"""Randomized collective-chain property test.

Seeded random sequences of collectives run as ONE jitted shard_map
program with token threading, checked against a pure-numpy oracle of
the per-rank state.  Values stay small integers (mod 97, exact in f32)
so the oracle comparison is equality, not tolerance.  This is the
cross-op interaction net: fences, vma promotion, and AD-free dataflow
across arbitrary op interleavings — the kind of bug a per-op test
matrix cannot see.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as m

SIZE = 8
MOD = 97.0

# each entry: (name, jax_fn(x, comm, token) -> (x, token),
#              numpy oracle rows(n, n) -> rows(n, n))
# per-device state x is an (n,)-vector; oracle holds all rows


def _jx_allreduce(x, comm, tok):
    return m.allreduce(x, m.SUM, comm=comm, token=tok)


def _np_allreduce(rows):
    return np.broadcast_to(rows.sum(0), rows.shape).copy()


def _jx_allreduce_max(x, comm, tok):
    return m.allreduce(x, m.MAX, comm=comm, token=tok)


def _np_allreduce_max(rows):
    return np.broadcast_to(rows.max(0), rows.shape).copy()


def _jx_bcast(x, comm, tok):
    return m.bcast(x, 3, comm=comm, token=tok)


def _np_bcast(rows):
    return np.broadcast_to(rows[3], rows.shape).copy()


def _jx_allgather_next(x, comm, tok):
    g, tok = m.allgather(x, comm=comm, token=tok)
    r = comm.rank()
    nxt = jax.lax.dynamic_index_in_dim(g, (r + 1) % SIZE, 0, keepdims=False)
    return nxt, tok


def _np_allgather_next(rows):
    return rows[(np.arange(SIZE) + 1) % SIZE]


def _jx_alltoall(x, comm, tok):
    y, tok = m.alltoall(x[:, None], comm=comm, token=tok)
    return y[:, 0], tok


def _np_alltoall(rows):
    return rows.T.copy()


def _jx_reduce_scatter(x, comm, tok):
    s, tok = m.reduce_scatter(x, comm=comm, token=tok)
    return jnp.broadcast_to(s, x.shape), tok


def _np_reduce_scatter(rows):
    col = rows.sum(0)  # entry r -> rank r
    return np.broadcast_to(col[:, None], rows.shape).copy()


def _jx_scan(x, comm, tok):
    return m.scan(x, m.SUM, comm=comm, token=tok)


def _np_scan(rows):
    return np.cumsum(rows, axis=0)


def _jx_scatter(x, comm, tok):
    s, tok = m.scatter(x, 2, comm=comm, token=tok)
    return jnp.broadcast_to(s, x.shape), tok


def _np_scatter(rows):
    return np.broadcast_to(rows[2][:, None], rows.shape).copy()


def _jx_ring(x, comm, tok):
    ring = [(r, (r + 1) % SIZE) for r in range(SIZE)]
    return m.sendrecv(x, x, source=ring, dest=ring, comm=comm, token=tok)


def _np_ring(rows):
    return rows[(np.arange(SIZE) - 1) % SIZE]


OPS = [
    (_jx_allreduce, _np_allreduce),
    (_jx_allreduce_max, _np_allreduce_max),
    (_jx_bcast, _np_bcast),
    (_jx_allgather_next, _np_allgather_next),
    (_jx_alltoall, _np_alltoall),
    (_jx_reduce_scatter, _np_reduce_scatter),
    (_jx_scan, _np_scan),
    (_jx_scatter, _np_scatter),
    (_jx_ring, _np_ring),
]


# the adjoint-differentiable subset: every op here is a LINEAR map of
# the global state whose JAX transpose is the true adjoint, so the
# gradient of sum(chain(x)) is the transpose applied to ones —
# computable exactly in numpy from basis vectors.  ``allreduce`` is
# deliberately absent: its AD contract is the reference's
# identity-transpose convention (transpose(allreduce) = identity, NOT
# the adjoint — see ops/allreduce.py), pinned by its own test battery.
LINEAR_OPS = [
    (_jx_bcast, _np_bcast),
    (_jx_allgather_next, _np_allgather_next),
    (_jx_alltoall, _np_alltoall),
    (_jx_reduce_scatter, _np_reduce_scatter),
    (_jx_scan, _np_scan),
    (_jx_scatter, _np_scatter),
    (_jx_ring, _np_ring),
]


@pytest.mark.parametrize("seed", range(4))
def test_random_chain_grads_match_linear_oracle(comm1d, seed):
    rng = np.random.RandomState(100 + seed)
    chain = [LINEAR_OPS[i] for i in rng.randint(0, len(LINEAR_OPS), size=5)]
    init = rng.randint(0, 5, size=(SIZE, SIZE)).astype(np.float32)

    def np_chain(rows):
        for _, np_fn in chain:
            rows = np_fn(rows)
        return rows

    # gradient oracle by linearity: d sum(A x) / d x_ij = sum(A e_ij)
    expected = np.zeros((SIZE, SIZE), np.float32)
    for i in range(SIZE):
        for j in range(SIZE):
            e = np.zeros((SIZE, SIZE), np.float32)
            e[i, j] = 1.0
            expected[i, j] = np_chain(e).sum()

    def local(v):
        def loss(x):
            tok = m.create_token()
            for jx_fn, _ in chain:
                x, tok = jx_fn(x, comm1d, tok)
            return x.sum()  # global loss = sum of per-device sums

        g = jax.grad(loss)(v[0])
        return g[None]

    f = jax.jit(
        jax.shard_map(
            local, mesh=comm1d.mesh,
            in_specs=jax.P(comm1d.axes, None),
            out_specs=jax.P(comm1d.axes, None),
        )
    )
    out = f(jnp.asarray(init))
    np.testing.assert_allclose(
        np.asarray(out), expected, rtol=1e-5, atol=1e-5, err_msg=str(seed)
    )


@pytest.mark.parametrize("seed", range(8))
def test_random_chain_matches_numpy_oracle(comm1d, seed):
    rng = np.random.RandomState(seed)
    chain = [OPS[i] for i in rng.randint(0, len(OPS), size=10)]
    init = rng.randint(0, 13, size=(SIZE, SIZE)).astype(np.float32)

    # numpy oracle
    rows = init.copy()
    for _, np_fn in chain:
        rows = np.mod(np_fn(rows), MOD)

    # one jitted SPMD program running the whole chain
    def local(v):
        x = v[0]  # (SIZE,) this device's row
        tok = m.create_token()
        for jx_fn, _ in chain:
            x, tok = jx_fn(x, comm1d, tok)
            x = jnp.mod(x, MOD)
        return x[None]

    f = jax.jit(
        jax.shard_map(
            local, mesh=comm1d.mesh,
            in_specs=jax.P(comm1d.axes, None),
            out_specs=jax.P(comm1d.axes, None),
        )
    )
    out = f(jnp.asarray(init))
    np.testing.assert_array_equal(np.asarray(out), rows, err_msg=str(seed))
