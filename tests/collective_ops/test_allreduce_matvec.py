"""Distributed (tensor-parallel) matvec integration tests, mirroring the
reference's tests/collective_ops/test_allreduce_matvec.py:44-239 — a
column-sharded matvec whose partial products are allreduced, checked
against dense oracles through grad, jvp, vjp and nested
``jax.linear_transpose`` — the Megatron-style TP f/g pair on our
primitives.

MPMD→SPMD embedding note: in the reference each rank returns the *full*
(replicated) result vector and AD is per-rank.  Here the replicated
result carries an explicit leading device axis (shape ``(size, N)``
globally, one row per device), so per-device cotangents — the MPMD
semantics the reference's identity-transpose convention assumes — map
one-to-one onto rows.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as m

SIZE = 8
N = 16  # global vector length; each device owns N // SIZE columns
COLS = N // SIZE


@pytest.fixture(scope="module")
def setup():
    rng = np.random.RandomState(42)
    A = rng.randn(N, N).astype(np.float32)  # replicated matrix
    x = rng.randn(N).astype(np.float32)  # column-sharded vector
    return A, jnp.asarray(x)


def matvec_spmd(comm, A):
    """f: x (N, sharded) -> (SIZE, N): per-device full result rows."""

    def fn(x_local):
        rank = comm.rank()
        A_local = jax.lax.dynamic_slice_in_dim(
            jnp.asarray(A), rank * COLS, COLS, axis=1
        )
        partial = A_local @ x_local
        full, _ = m.allreduce(partial, m.SUM, comm=comm)
        return full[None]  # (1, N) per device -> (SIZE, N) global

    return jax.jit(
        jax.shard_map(
            fn,
            mesh=comm.mesh,
            in_specs=jax.P(comm.axes),
            out_specs=jax.P(comm.axes, None),
        )
    )


def test_matvec_forward(comm1d, setup):
    A, x = setup
    f = matvec_spmd(comm1d, A)
    out = np.asarray(f(x))
    for r in range(SIZE):
        np.testing.assert_allclose(out[r], A @ np.asarray(x), rtol=1e-4, atol=1e-5)


def test_matvec_transpose(comm1d, setup):
    # per-rank cotangent = y on every device -> global x_bar = A.T @ y
    # (reference oracle at test_allreduce_matvec.py:93-117)
    A, x = setup
    f = matvec_spmd(comm1d, A)
    y = np.asarray(f(x))[0]
    ct = jnp.asarray(np.tile(y, (SIZE, 1)))
    (xt,) = jax.linear_transpose(f, x)(ct)
    np.testing.assert_allclose(np.asarray(xt), A.T @ y, rtol=1e-3, atol=1e-5)


def test_matvec_transpose2(comm1d, setup):
    A, x = setup
    f = matvec_spmd(comm1d, A)

    def lt(ct):
        return jax.linear_transpose(f, x)(ct)[0]

    # transpose of the transpose recovers the forward matvec
    (res,) = jax.linear_transpose(lt, f(x))(x)
    expected = np.asarray(f(x))
    np.testing.assert_allclose(np.asarray(res), expected, rtol=1e-3, atol=1e-5)


def test_matvec_transpose3(comm1d, setup):
    A, x = setup
    f = matvec_spmd(comm1d, A)

    def lt(ct):
        return jax.linear_transpose(f, x)(ct)[0]

    def lt2(v):
        return jax.linear_transpose(lt, f(x))(v)[0]

    y = np.asarray(f(x))[0]
    ct = jnp.asarray(np.tile(y, (SIZE, 1)))
    # transpose(transpose(transpose(f))) = transpose(f)
    (res,) = jax.linear_transpose(lt2, x)(ct)
    np.testing.assert_allclose(np.asarray(res), A.T @ y, rtol=1e-3, atol=1e-5)


def test_matvec_grad(comm1d, setup):
    A, x = setup
    f = matvec_spmd(comm1d, A)
    g = jax.grad(lambda v: (f(v) ** 2).sum())(x)
    # per-rank loss ||y||^2 -> per-block grads 2 A_r^T y, concat = 2 A^T A x
    expected = 2 * A.T @ (A @ np.asarray(x))
    np.testing.assert_allclose(np.asarray(g), expected, rtol=1e-3, atol=1e-5)


def test_matvec_jvp(comm1d, setup):
    A, x = setup
    f = matvec_spmd(comm1d, A)
    v = jnp.ones(N, jnp.float32)
    _, tangent = jax.jvp(f, (x,), (v,))
    for r in range(SIZE):
        np.testing.assert_allclose(
            np.asarray(tangent)[r], A @ np.ones(N, np.float32), rtol=1e-3
        )


def test_matvec_vjp(comm1d, setup):
    A, x = setup
    f = matvec_spmd(comm1d, A)
    y2, vjp_fun = jax.vjp(f, x)
    (xt,) = vjp_fun(y2)
    y = np.asarray(y2)[0]
    np.testing.assert_allclose(np.asarray(xt), A.T @ y, rtol=1e-3, atol=1e-5)
