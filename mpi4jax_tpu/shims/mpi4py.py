"""Shim satisfying ``from mpi4py import MPI`` with the compat layer's
MPI namespace (operators, constants, Status, COMM_WORLD proxy).

Only meaningful under the mpi4jax_tpu launcher (or a single process);
see mpi4jax_tpu/shims/__init__.py.
"""

from mpi4jax_tpu.compat import MPI  # noqa: F401
