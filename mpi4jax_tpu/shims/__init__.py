"""Import shims: run unmodified reference user programs.

This directory, when prepended to ``sys.path``, provides top-level
modules named ``mpi4py`` and ``mpi4jax`` backed by
:mod:`mpi4jax_tpu.compat` — so a program written for the reference
stack runs without touching its imports:

    python -m mpi4jax_tpu.launch --shims -np 4 their_script.py

or manually:

    PYTHONPATH="$(python -m mpi4jax_tpu.shims)" python their_script.py

The shims are intentionally *not* importable by default: they shadow
real packages, so they must be opted into per-process.
"""

from pathlib import Path

__all__ = ["path"]


def path():
    """Directory to prepend to sys.path / PYTHONPATH."""
    return str(Path(__file__).resolve().parent)
