"""Shim package satisfying both ``from mpi4py import MPI`` and
``import mpi4py.MPI`` (both forms are common in reference user code)
with the compat layer's MPI namespace (operators, constants, Status,
COMM_WORLD proxy).

Only meaningful under the mpi4jax_tpu launcher (or a single process);
see mpi4jax_tpu/shims/__init__.py.
"""

from . import MPI  # noqa: F401  (relative: this package is imported
# both as top-level ``mpi4py`` — via the shim path — and as
# ``mpi4jax_tpu.shims.mpi4py``)

__all__ = ["MPI"]
