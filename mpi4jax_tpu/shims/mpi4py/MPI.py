"""The ``mpi4py.MPI`` shim submodule.

Mirrors every public attribute of the compat layer's MPI namespace
(operators, constants, Status, COMM_WORLD proxy, get_vendor) as module
globals, so both ``from mpi4py import MPI`` and ``import mpi4py.MPI``
resolve to one module with the full surface.
"""

import sys as _sys

from mpi4jax_tpu.compat import MPI as _ns

_mod = _sys.modules[__name__]
for _k in dir(_ns):
    if not _k.startswith("_"):
        setattr(_mod, _k, getattr(_ns, _k))
del _sys, _mod, _k
