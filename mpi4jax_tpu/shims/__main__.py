"""Print the shim directory (for PYTHONPATH wiring in shell scripts)."""

from mpi4jax_tpu.shims import path

print(path())
