"""Shim satisfying ``import mpi4jax`` with the compat layer (the twelve
ops with the reference's signatures, has_cuda_support, experimental
namespace as a real subpackage so ``from mpi4jax.experimental import
auto_tokenize`` works)."""

from mpi4jax_tpu.compat import *  # noqa: F401,F403
from mpi4jax_tpu.compat import MPI, create_token  # noqa: F401
from mpi4jax_tpu import Token, __version__  # noqa: F401
from . import experimental  # noqa: F401
