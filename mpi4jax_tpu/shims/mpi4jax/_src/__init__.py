"""Minimal internals shim: only the pieces of the reference's private
``mpi4jax._src`` namespace that user-facing programs/tests reasonably
touch. The full internal surface (Cython bridge modules, decorators) is
implementation-specific to the reference and intentionally absent."""
