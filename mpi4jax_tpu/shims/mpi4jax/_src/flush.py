"""Shim for mpi4jax._src.flush (flush.py:1-12 there: block until
pending XLA work is done)."""


def flush(platform=None):
    del platform
    import jax
    import jax.numpy as jnp

    from mpi4jax_tpu.utils.runtime import drain

    drain(jnp.zeros(()) + 0)
    del jax
