"""Shim for mpi4jax._src.xla_bridge.mpi_xla_bridge: set_logging /
get_logging (mpi_xla_bridge.pyx:35-44 there), mapped onto this
library's debug-log switch (same wire format, utils/config.py)."""

from mpi4jax_tpu.utils import config as _config


def set_logging(enable):
    _config.set_debug(bool(enable))


def get_logging():
    return bool(_config.debug_enabled())
