"""Shim for mpi4jax._src.xla_bridge: the logging toggles.

The reference seeds bridge logging from MPI4JAX_DEBUG at (re)import
(xla_bridge/__init__.py:18-22 there); mirrored here so
``importlib.reload`` re-reads the environment the same way.
"""

import os

from mpi4jax_tpu.utils import config as _config

_env = os.environ.get("MPI4JAX_DEBUG")
if _env is not None:
    _config.set_debug(_env not in ("", "0"))

from . import mpi_xla_bridge  # noqa: E402,F401

HAS_GPU_EXT = False
