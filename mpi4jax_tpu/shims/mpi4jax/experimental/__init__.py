"""Shim for the reference's experimental namespace
(mpi4jax/experimental/__init__.py:1-5 exports auto_tokenize only)."""

from mpi4jax_tpu.experimental import auto_tokenize  # noqa: F401
