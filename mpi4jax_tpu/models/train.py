"""Distributed training-step demo: data-parallel × tensor-parallel MLP.

The reference ships these as *enabled patterns*, not a trainer: the DP
gradient-allreduce headline example (README.rst:61-80) and the
tensor-parallel sharded matvec with its AD-correct transpose
(tests/collective_ops/test_allreduce_matvec.py:44-62) — SURVEY §2.4
requires both as first-class, tested capabilities.  This module composes
them into a real train step on mpi4jax_tpu primitives:

* TP (Megatron f/g pair): W1 column-sharded, W2 row-sharded over the
  ``tp`` mesh axis; the partial output is summed with ``allreduce`` (the
  "g" collective).  The identity-transpose AD convention delivers the
  correct per-shard gradients in the backward pass (the "f" side).
* DP: per-device micro-batches over the ``dp`` axis; gradients averaged
  with ``allreduce`` before the optimiser step.

The whole step — forward, backward, both allreduce families, SGD — runs
inside one ``shard_map`` under ``jit``: on a TPU slice it compiles to a
single executable whose collectives ride ICI.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp

from mpi4jax_tpu.ops import reductions
from mpi4jax_tpu.ops.allreduce import allreduce
from mpi4jax_tpu.ops._core import create_token

__all__ = [
    "MLPParams",
    "StackParams",
    "init_params",
    "init_stack_params",
    "make_train_step",
    "make_global_train_step",
    "make_global_zero_train_step",
    "make_dp_train_step",
    "run_elastic",
]


class MLPParams(NamedTuple):
    w1: jax.Array  # (d_in, d_hidden / tp) column shard
    b1: jax.Array  # (d_hidden / tp,)
    w2: jax.Array  # (d_hidden / tp, d_out) row shard
    b2: jax.Array  # (d_out,) replicated (only tp-rank 0's bias is added)


def init_params(key, d_in, d_hidden, d_out, tp_size, dtype=jnp.float32):
    """Global parameter arrays laid out for TP sharding on axis tp.

    Returns arrays shaped for a ``(dp, tp)`` mesh: the hidden dimension
    carries the tp shards.
    """
    if d_hidden % tp_size:
        raise ValueError(
            f"d_hidden={d_hidden} must be divisible by tp_size={tp_size}"
        )
    k1, k2 = jax.random.split(key)
    scale1 = (2.0 / d_in) ** 0.5
    scale2 = (2.0 / d_hidden) ** 0.5
    w1 = jax.random.normal(k1, (d_in, d_hidden), dtype) * scale1
    w2 = jax.random.normal(k2, (d_hidden, d_out), dtype) * scale2
    b1 = jnp.zeros((d_hidden,), dtype)
    b2 = jnp.zeros((d_out,), dtype)
    return MLPParams(w1, b1, w2, b2)


def _forward(params, x, comm_tp, token):
    """TP forward: local matmuls + one output allreduce (the g op)."""
    h = jax.nn.relu(x @ params.w1 + params.b1)  # (B, hid/tp) local
    y_partial = h @ params.w2  # (B, d_out) partial sum
    y, token = allreduce(y_partial, reductions.SUM, comm=comm_tp, token=token)
    # bias is replicated; add once (scaled by 1/tp it would drift — add
    # full bias after the reduce instead)
    return y + params.b2, token


def make_train_step(comm_dp, comm_tp, lr=1e-2):
    """Per-device SPMD train step; call inside shard_map over (dp, tp).

    ``batch = (x, targets)`` holds this device's micro-batch (identical
    across the tp axis, sharded across dp).
    """

    def step(params, batch):
        x, targets = batch
        token = create_token()

        def loss_fn(p):
            y, _tok = _forward(p, x, comm_tp, token)
            return jnp.mean((y - targets) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)

        # DP gradient averaging (README.rst:61-80 pattern)
        tok = create_token()
        dp = float(comm_dp.size)
        synced = []
        for g in grads:
            g_sum, tok = allreduce(g, reductions.SUM, comm=comm_dp, token=tok)
            synced.append(g_sum / dp)
        grads = MLPParams(*synced)

        # loss is averaged too, for logging parity across devices
        loss_sum, tok = allreduce(loss, reductions.SUM, comm=comm_dp, token=tok)
        loss = loss_sum / dp

        params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        return params, loss

    return step


def make_global_train_step(mesh, comm_dp, comm_tp, lr=1e-2):
    """Jitted global train step over a ("dp", "tp") mesh.

    Parameters enter with their hidden dimension sharded over tp and
    replicated over dp; the batch is sharded over dp.  The TP forward
    goes through :func:`allreduce` (and its backward through the
    identity-transpose rule); the DP gradient sync rides shard_map's
    vma-aware AD — differentiating w.r.t. a param typed *replicated*
    over dp automatically psums its cotangent over dp (the transpose of
    replication is a sum), which also leaves the updated parameters
    typed replicated as the out_specs require.
    """
    dp_ax, tp_ax = comm_dp.axes[0], comm_tp.axes[0]
    dp, tp = float(comm_dp.size), float(comm_tp.size)

    param_specs = MLPParams(
        w1=jax.P(None, tp_ax),
        b1=jax.P(tp_ax),
        w2=jax.P(tp_ax, None),
        b2=jax.P(None),
    )
    batch_specs = (jax.P(dp_ax, None), jax.P(dp_ax, None))

    def sync_grad(g, tp_sharded):
        # shard_map's AD has ALREADY psum'ed each param's cotangent over
        # every mesh axis the param is replicated on — an explicit psum
        # here would double-count the gradient (it did, until round 2:
        # 2x on the tp-sharded params, dp*tp x on b2, silently absorbed
        # into the learning rate by the convergence test).  Only the
        # local-mean → global-mean loss scaling remains:
        if tp_sharded:
            return g / dp
        # replicated params additionally got the (identical) tp copies
        # summed
        return g / (dp * tp)

    def local_step(params, batch):
        x, targets = batch
        token = create_token()

        def loss_fn(p):
            y, _tok = _forward(p, x, comm_tp, token)
            return jnp.mean((y - targets) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = MLPParams(
            w1=sync_grad(grads.w1, True),
            b1=sync_grad(grads.b1, True),
            w2=sync_grad(grads.w2, True),
            b2=sync_grad(grads.b2, False),
        )
        params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        return params, loss[None]

    return jax.jit(
        jax.shard_map(
            local_step,
            mesh=mesh,
            in_specs=(param_specs, batch_specs),
            out_specs=(param_specs, jax.P((dp_ax, tp_ax))),
        )
    )


class StackParams(NamedTuple):
    """Deep MLP stack for the data-parallel (MPMD) train step.

    Each layer is its own ``(w, b)`` pair of leaves — deliberately NOT
    stacked into one ``(layers, d, d)`` array: per-layer leaves are
    what lets :class:`~mpi4jax_tpu.BucketedGradSync` bucket gradients
    in backprop order, so layer k's bucket can hit the wire while the
    backward pass is still producing layer k-1's gradients.  The
    flattened leaf order is ``(w0, b0, w1, b1, ..., w_out)``; reversed
    it is exactly the order backprop produces gradients in.
    """

    layers: tuple  # L entries of (w: (d, d), b: (d,))
    w_out: jax.Array  # (d, d_out)


def init_stack_params(key, layers, d, d_out=None, dtype=jnp.float32):
    d_out = d_out or d
    keys = jax.random.split(key, layers + 1)
    scale = (2.0 / d) ** 0.5
    return StackParams(
        layers=tuple(
            (jax.random.normal(keys[i], (d, d), dtype) * scale,
             jnp.zeros((d,), dtype))
            for i in range(layers)
        ),
        w_out=jax.random.normal(keys[-1], (d, d_out), dtype) * scale,
    )


def make_dp_train_step(comm, lr=1e-2, overlap=True, bucket_bytes=None,
                       loss_sync=True):
    """Pure data-parallel train step for MPMD backends (the proc tier),
    with DDP-style bucketed compute/comm overlap (docs/async.md
    "gradient bucketing").

    Each rank holds the FULL parameters and its own micro-batch.  For
    :class:`StackParams` the backward pass is written out per layer, and
    as soon as a gradient bucket (~``bucket_bytes``, default
    ``T4J_BUCKET_BYTES``) fills, its ``iallreduce`` is submitted and the
    remaining backprop is FENCED to depend on the submit's stamp
    (``lax.optimization_barrier``): the data dependency forces XLA to
    issue bucket k's request before computing layer k-1's gradients, so
    the native progress engine runs the wire phase while the backward
    pass continues — relying on the scheduler to hoist an independent
    callback does NOT work (XLA's CPU schedule serialises it; measured
    in docs/async.md).  Every request is waited at the optimizer step.

    ``overlap=False`` runs the identical bucket layout and fence points
    through blocking allreduces — classic non-overlapped DDP, the
    control arm of ``benchmarks/transformer.py --overlap`` interleaved
    pairs.  Both arms are bit-identical in results (same reduction
    sizes, same order).

    Other parameter pytrees (:class:`MLPParams` included) fall back to
    ``jax.value_and_grad`` + :class:`~mpi4jax_tpu.BucketedGradSync`,
    where overlap is at the scheduler's discretion.

    Returns ``step(params, (x, targets)) -> (params, loss)`` — jit it
    yourself (``jax.jit(step)``) or call it eagerly.
    """
    from jax import lax

    from mpi4jax_tpu.ops._core import create_token
    from mpi4jax_tpu.ops.allreduce import BucketedGradSync
    from mpi4jax_tpu.ops.async_ import iallreduce, wait

    if bucket_bytes is None:
        from mpi4jax_tpu.utils import config

        bucket_bytes = config.bucket_bytes()
    bucket_bytes = max(1, int(bucket_bytes))
    n = float(comm.size)
    use_async = overlap and getattr(comm, "backend", None) != "mesh"

    def generic_step(params, batch):
        x, targets = batch
        sync = BucketedGradSync(
            comm, bucket_bytes=bucket_bytes, average=True,
            overlap=use_async,
        )

        def loss_fn(p):
            h = jax.nn.relu(x @ p.w1 + p.b1)
            y = h @ p.w2 + p.b2
            return jnp.mean((y - targets) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads, tok = sync(grads)
        params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        if loss_sync:
            loss_sum, tok = allreduce(
                loss, reductions.SUM, comm=comm, token=tok
            )
            loss = loss_sum / n
        return params, loss

    def stack_step(params, batch):
        x, targets = batch
        layers = list(params.layers)

        # forward, saving each layer's input and pre-activation (the
        # exact residuals the hand-written backward below needs)
        h = x
        saves = []
        for w, b in layers:
            pre = h @ w + b
            saves.append((h, pre))
            h = jax.nn.relu(pre)
        y = h @ params.w_out
        diff = y - targets
        loss = jnp.mean(diff ** 2)
        dy = (2.0 / diff.size) * diff

        tok = create_token()
        itemsize = jnp.dtype(y.dtype).itemsize
        pending = []   # (entries, request-or-reduced) in submit order
        bucket = []    # [(key, grad)] accumulating toward bucket_bytes
        bucket_nbytes = 0

        def flush(tok):
            nonlocal bucket, bucket_nbytes
            if not bucket:
                return tok, None
            flat = jnp.concatenate([g.reshape(-1) for _k, g in bucket])
            entries = [(k, g.shape, g.size) for k, g in bucket]
            if use_async:
                handle, tok = iallreduce(
                    flat, reductions.SUM, comm=comm, token=tok
                )
            else:
                handle, tok = allreduce(
                    flat, reductions.SUM, comm=comm, token=tok
                )
            pending.append((entries, handle))
            bucket = []
            bucket_nbytes = 0
            return tok, tok.stamp

        def push(tok, key, g):
            nonlocal bucket_nbytes
            bucket.append((key, g))
            bucket_nbytes += g.size * itemsize
            if bucket_nbytes >= bucket_bytes:
                return flush(tok)
            return tok, None

        # backward, last layer first — each flush point fences the rest
        # of the backward pass on the submit's stamp, forcing the DDP
        # schedule: bucket k on the wire while layer k-1 backprops
        tok, stamp = push(tok, ("w_out",), h.T @ dy)
        dh = dy @ params.w_out.T
        if stamp is not None:
            dh, _ = lax.optimization_barrier((dh, stamp))
        for i in reversed(range(len(layers))):
            h_in, pre = saves[i]
            w, _b = layers[i]
            dpre = jnp.where(pre > 0, dh, jnp.zeros((), dh.dtype))
            tok, stamp = push(tok, ("w", i), h_in.T @ dpre)
            tok, stamp2 = push(tok, ("b", i), dpre.sum(axis=0))
            if i > 0:
                dh = dpre @ w.T
                gate = stamp2 if stamp2 is not None else stamp
                if gate is not None:
                    dh, _ = lax.optimization_barrier((dh, gate))
        tok, _ = flush(tok)

        # wait every request at the optimizer step and apply updates
        scale = jnp.asarray(1.0 / n, y.dtype)
        synced = {}
        for entries, handle in pending:
            if use_async:
                red, tok = wait(handle, token=tok)
            else:
                red = handle
            red = red * scale
            off = 0
            for key, shape, size in entries:
                synced[key] = red[off:off + size].reshape(shape)
                off += size
        new_layers = tuple(
            (w - lr * synced[("w", i)], b - lr * synced[("b", i)])
            for i, (w, b) in enumerate(layers)
        )
        new_params = StackParams(
            layers=new_layers,
            w_out=params.w_out - lr * synced[("w_out",)],
        )
        if loss_sync:
            loss_sum, tok = allreduce(
                loss, reductions.SUM, comm=comm, token=tok
            )
            loss = loss_sum / n
        return new_params, loss

    def step(params, batch):
        if isinstance(params, StackParams):
            return stack_step(params, batch)
        if isinstance(params, MLPParams):
            return generic_step(params, batch)
        raise TypeError(
            f"make_dp_train_step knows StackParams/MLPParams, got "
            f"{type(params)}"
        )

    return step


def make_global_zero_train_step(mesh, comm_dp, comm_tp, lr=1e-2, momentum=0.9):
    """ZeRO-1-style train step: optimizer state sharded over ``dp``.

    The canonical :func:`~mpi4jax_tpu.reduce_scatter` pattern: instead of
    all-reducing gradients and keeping a full momentum buffer on every
    data-parallel rank, the loss is differentiated w.r.t. **dp-varying**
    params (so the cotangents stay per-device partial sums — no
    automatic dp-psum), each parameter's flattened partial gradient is
    **reduce-scattered** over ``dp`` — performing AD's dp-reduction and
    the ZeRO sharding in one O(payload) collective; rank ``r`` receives
    only chunk ``r`` — the momentum update runs on that 1/dp-sized
    shard, and the updated shard is rebroadcast into the replicated
    parameters.  The momentum memory per device drops by ``dp``×; the
    wire cost is unchanged (reduce_scatter + re-broadcast ≡ the
    allreduce of the plain step — the classic ZeRO identity).

    The rebroadcast is a masked ``psum`` rather than ``all_gather``
    because shard_map's value-typing can statically see a psum output is
    replicated (all_gather outputs are varying-typed, which the
    replicated param out_specs would reject).

    Returns ``(step, init_opt_state)``:

    * ``step(params, opt_state, batch) -> (params, opt_state, loss)`` —
      jitted over the global mesh;
    * ``init_opt_state(params) -> opt_state`` — jitted; momentum buffers
      of global shape ``(dp, tp * ceil(local_size / dp))`` per parameter,
      sharded over ``(dp, tp)`` (each device stores exactly its chunk).
    """
    from jax import lax

    from mpi4jax_tpu.ops._core import promote_vma
    from mpi4jax_tpu.ops.collectives import reduce_scatter

    dp_ax, tp_ax = comm_dp.axes[0], comm_tp.axes[0]
    dpn = comm_dp.size
    dp, tp = float(comm_dp.size), float(comm_tp.size)

    param_specs = MLPParams(
        w1=jax.P(None, tp_ax),
        b1=jax.P(tp_ax),
        w2=jax.P(tp_ax, None),
        b2=jax.P(None),
    )
    tp_sharded = MLPParams(w1=True, b1=True, w2=True, b2=False)
    batch_specs = (jax.P(dp_ax, None), jax.P(dp_ax, None))
    state_specs = MLPParams(*([jax.P(dp_ax, tp_ax)] * 4))

    def _chunk(n):
        return -(-n // dpn)  # ceil(local param size / dp)

    def local_init(params):
        return MLPParams(
            *(jnp.zeros((1, _chunk(p.size)), p.dtype) for p in params)
        )

    init_opt_state = jax.jit(
        jax.shard_map(
            local_init, mesh=mesh, in_specs=(param_specs,),
            out_specs=state_specs,
        )
    )

    def local_step(params, vstate, batch):
        x, targets = batch
        token = create_token()

        # Differentiate w.r.t. dp-VARYING params: the cotangent then
        # stays this device's partial batch gradient (shard_map's AD
        # only auto-psums over axes a param is replicated on), and the
        # reduce_scatter below performs the dp-reduction AND the ZeRO
        # sharding in a single collective — replacing the allreduce
        # entirely, not re-sharding an already-reduced gradient.
        p_var = jax.tree.map(
            lambda a: promote_vma(a, (dp_ax,)), params
        )

        def loss_fn(p):
            y, _tok = _forward(p, x, comm_tp, token)
            return jnp.mean((y - targets) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(p_var)

        rank = comm_dp.rank()
        tok = create_token()
        new_p, new_v = [], []
        for p, g, v, is_tp in zip(params, grads, vstate, tp_sharded):
            n, chunk = p.size, _chunk(p.size)
            pad = dpn * chunk - n
            # local-mean → global-mean scaling; b2 (tp-replicated) also
            # got its identical tp copies auto-summed
            scale = dp if is_tp else dp * tp
            gflat = jnp.pad(g.reshape(-1) / scale, (0, pad))
            # rank r receives the dp-mean of its parameter chunk
            gsh, tok = reduce_scatter(
                gflat.reshape(dpn, chunk), comm=comm_dp, token=tok
            )
            v1 = momentum * v[0] + gsh
            psh = lax.dynamic_slice(
                jnp.pad(p.reshape(-1), (0, pad)), (rank * chunk,), (chunk,)
            )
            u = psh - lr * v1
            # rebroadcast the updated shard: masked psum == all_gather
            # value-wise, but typed replicated over dp
            buf = jnp.where(
                jnp.arange(dpn)[:, None] == rank, u[None, :], jnp.zeros((), u.dtype)
            )
            if is_tp:
                pnew = lax.psum(buf, dp_ax)
            else:
                pnew = lax.psum(buf, (dp_ax, tp_ax)) / tp
            new_p.append(pnew.reshape(-1)[:n].reshape(p.shape))
            new_v.append(v1[None])
        return MLPParams(*new_p), MLPParams(*new_v), loss[None]

    step = jax.jit(
        jax.shard_map(
            local_step,
            mesh=mesh,
            in_specs=(param_specs, state_specs, batch_specs),
            out_specs=(param_specs, state_specs, jax.P((dp_ax, tp_ax))),
        )
    )
    return step, init_opt_state


# ------------------------------------------------- elastic training loop


def _resize_interrupted(exc):
    """True for an op failure caused by an elastic resize: the native
    ResizeInterrupted status (an op drained mid-resize), or a
    stale-communicator error from a CACHED jit executable — a rank
    that sat in compute through the whole resize window sees the
    latter, because check_health only runs at trace time and its
    compiled step goes straight to the (invalidated) native handle."""
    s = str(exc)
    return "ResizeInterrupted" in s or "world resize" in s or \
        "not a member of the current world" in s


def run_elastic(nsteps, checkpoint_dir, *, d=32, layers=2, batch=4,
                lr=1e-2, save_every=2, seed=0, dtype=jnp.float32,
                log=print):
    """Elastic data-parallel training loop (docs/failure-semantics.md
    "elastic membership"): the job survives rank deaths under
    ``T4J_ELASTIC=shrink`` and grows back under ``rejoin`` instead of
    restarting from scratch.

    The recovery contract this loop implements — the template for any
    elastic trainer on this stack:

    1. Every rank checkpoints its (replicated) state into its OWN
       per-rank :class:`~mpi4jax_tpu.utils.checkpoint.Manager` series
       every ``save_every`` steps.
    2. At loop entry AND after every resize, the members agree on the
       resume point with a MIN-allreduce of their latest durably saved
       steps (ranks may have died between saves, and a rejoined
       replacement inherits its predecessor's possibly-lagging
       series) and everyone restores that step — the state
       redistribution.
    3. A mid-step membership change surfaces as an op failure carrying
       the native ``ResizeInterrupted`` status (from a cached jit) or
       as :class:`~mpi4jax_tpu.WorldResized` directly (from
       ``check_health`` at the next op).  The loop waits the resize
       out, calls :func:`runtime.refresh_after_resize` (drops stale
       comm handles, re-resolves the tuning knobs for the NEW topology
       fingerprint — collective, so every member calls it), rebuilds
       the communicator and the jitted step over the surviving world,
       and resumes at the agreed step.

    Losses after a shrink are NOT bit-identical to the full-world run
    (fewer micro-batches per global step; docs/sharp-bits.md).

    Returns ``{"resizes", "final_world", "final_epoch", "last_step",
    "losses"}``.
    """
    import numpy as np

    from mpi4jax_tpu.native import runtime
    from mpi4jax_tpu.native.runtime import WorldResized
    from mpi4jax_tpu.ops.allreduce import allreduce as _allreduce
    from mpi4jax_tpu.ops import reductions as _red
    from mpi4jax_tpu.parallel.proc import world_comm_if_initialized
    from mpi4jax_tpu.utils import checkpoint

    runtime.ensure_initialized()
    comm = world_comm_if_initialized()
    if comm is None:
        raise RuntimeError(
            "run_elastic needs a multi-process world "
            "(python -m mpi4jax_tpu.launch -np N --elastic shrink ...)"
        )
    rank = runtime.world_rank()
    mgr = checkpoint.Manager(f"{checkpoint_dir}/rank{rank}",
                             max_to_keep=5)

    def template():
        return init_stack_params(jax.random.PRNGKey(seed), layers, d)

    def build(c):
        return jax.jit(make_dp_train_step(c, lr=lr, overlap=False))

    def batch_for(i, c):
        # deterministic per (member index, step): reproducible streams
        # whose partition follows the membership
        k = jax.random.fold_in(jax.random.PRNGKey(seed),
                               1009 * i + c.rank())
        x = jax.random.normal(k, (batch, d), dtype)
        t = jax.random.normal(jax.random.fold_in(k, 1), (batch, d),
                              dtype)
        return x, t

    def sync_start(c):
        """Agree on the resume point: MIN over every member's latest
        durably saved step (-1 = nothing saved)."""
        mgr.wait_until_finished()
        local = mgr.latest_step()
        local = -1 if local is None else int(local)
        agreed, _ = _allreduce(
            jnp.asarray([local], jnp.int32), op=_red.MIN, comm=c,
            token=create_token(),
        )
        agreed = int(np.asarray(agreed)[0])
        if agreed < 0:
            return template(), 0
        return mgr.restore(agreed, like=template()), agreed + 1

    # Every recovery action runs INSIDE the try via pending flags: the
    # rendezvous and rebuild are themselves collectives, so a SECOND
    # resize (e.g. the rejoin landing right after a shrink) can
    # interrupt them too — the flags make each pass idempotent and the
    # handler never does comm work where a raise would escape the loop.
    step = build(comm)
    resizes = 0
    epoch = (runtime.world_info() or {}).get("epoch", 0)
    losses = []
    params = None
    i = 0
    pending_rebuild = False
    pending_sync = True
    while pending_rebuild or pending_sync or i < nsteps:
        try:
            if pending_rebuild:
                # drop stale comm handles, re-resolve the tuning knobs
                # for the NEW topology fingerprint (collective: the
                # rejoiner pairs it with the resolution inside its own
                # ensure_initialized), rebuild the comm and the step
                runtime.refresh_after_resize()
                comm = world_comm_if_initialized()
                step = build(comm)
                pending_rebuild = False
                pending_sync = True
            if pending_sync:
                params, i = sync_start(comm)
                pending_sync = False
                continue
            params, loss = step(params, batch_for(i, comm))
            losses.append(float(loss))
            if save_every and (i % save_every == 0 or i == nsteps - 1):
                mgr.save(i, params)
            i += 1
        except WorldResized as w:
            resizes += 1
            epoch = w.epoch
            pending_rebuild = True
            log(
                f"t4j elastic: world now {len(w.new_world)} member(s) "
                f"at epoch {w.epoch} — re-resolving tuning and "
                "resuming from the last agreed checkpoint",
                flush=True,
            )
        except Exception as e:  # noqa: BLE001 — only resize marks pass
            if not _resize_interrupted(e):
                raise
            runtime.resize_wait()
            info = runtime.world_info() or {}
            if info.get("epoch", epoch) == epoch and not pending_rebuild:
                # settled with no epoch change: either the runtime's
                # own epoch tracking still owes us a WorldResized, or
                # the resize escalated to a fault — surface whichever
                try:
                    runtime.check_health()
                except WorldResized as w:
                    resizes += 1
                    epoch = w.epoch
                    pending_rebuild = True
                    continue
                raise
            resizes += 1
            epoch = info.get("epoch", epoch)
            pending_rebuild = True
            log(
                f"t4j elastic: step {i} interrupted by a resize "
                f"(epoch {epoch}) — rebuilding",
                flush=True,
            )
    mgr.close()
    info = runtime.world_info() or {}
    return {
        "resizes": resizes,
        "final_world": comm.size,
        "final_epoch": int(info.get("epoch", epoch)),
        "last_step": i - 1,
        "losses": losses,
    }
