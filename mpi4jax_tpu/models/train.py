"""Distributed training-step demo: data-parallel × tensor-parallel MLP.

The reference ships these as *enabled patterns*, not a trainer: the DP
gradient-allreduce headline example (README.rst:61-80) and the
tensor-parallel sharded matvec with its AD-correct transpose
(tests/collective_ops/test_allreduce_matvec.py:44-62) — SURVEY §2.4
requires both as first-class, tested capabilities.  This module composes
them into a real train step on mpi4jax_tpu primitives:

* TP (Megatron f/g pair): W1 column-sharded, W2 row-sharded over the
  ``tp`` mesh axis; the partial output is summed with ``allreduce`` (the
  "g" collective).  The identity-transpose AD convention delivers the
  correct per-shard gradients in the backward pass (the "f" side).
* DP: per-device micro-batches over the ``dp`` axis; gradients averaged
  with ``allreduce`` before the optimiser step.

The whole step — forward, backward, both allreduce families, SGD — runs
inside one ``shard_map`` under ``jit``: on a TPU slice it compiles to a
single executable whose collectives ride ICI.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp

from mpi4jax_tpu.ops import reductions
from mpi4jax_tpu.ops.allreduce import allreduce
from mpi4jax_tpu.ops._core import create_token

__all__ = [
    "MLPParams",
    "init_params",
    "make_train_step",
    "make_global_train_step",
]


class MLPParams(NamedTuple):
    w1: jax.Array  # (d_in, d_hidden / tp) column shard
    b1: jax.Array  # (d_hidden / tp,)
    w2: jax.Array  # (d_hidden / tp, d_out) row shard
    b2: jax.Array  # (d_out,) replicated (only tp-rank 0's bias is added)


def init_params(key, d_in, d_hidden, d_out, tp_size, dtype=jnp.float32):
    """Global parameter arrays laid out for TP sharding on axis tp.

    Returns arrays shaped for a ``(dp, tp)`` mesh: the hidden dimension
    carries the tp shards.
    """
    if d_hidden % tp_size:
        raise ValueError(
            f"d_hidden={d_hidden} must be divisible by tp_size={tp_size}"
        )
    k1, k2 = jax.random.split(key)
    scale1 = (2.0 / d_in) ** 0.5
    scale2 = (2.0 / d_hidden) ** 0.5
    w1 = jax.random.normal(k1, (d_in, d_hidden), dtype) * scale1
    w2 = jax.random.normal(k2, (d_hidden, d_out), dtype) * scale2
    b1 = jnp.zeros((d_hidden,), dtype)
    b2 = jnp.zeros((d_out,), dtype)
    return MLPParams(w1, b1, w2, b2)


def _forward(params, x, comm_tp, token):
    """TP forward: local matmuls + one output allreduce (the g op)."""
    h = jax.nn.relu(x @ params.w1 + params.b1)  # (B, hid/tp) local
    y_partial = h @ params.w2  # (B, d_out) partial sum
    y, token = allreduce(y_partial, reductions.SUM, comm=comm_tp, token=token)
    # bias is replicated; add once (scaled by 1/tp it would drift — add
    # full bias after the reduce instead)
    return y + params.b2, token


def make_train_step(comm_dp, comm_tp, lr=1e-2):
    """Per-device SPMD train step; call inside shard_map over (dp, tp).

    ``batch = (x, targets)`` holds this device's micro-batch (identical
    across the tp axis, sharded across dp).
    """

    def step(params, batch):
        x, targets = batch
        token = create_token()

        def loss_fn(p):
            y, _tok = _forward(p, x, comm_tp, token)
            return jnp.mean((y - targets) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)

        # DP gradient averaging (README.rst:61-80 pattern)
        tok = create_token()
        dp = float(comm_dp.size)
        synced = []
        for g in grads:
            g_sum, tok = allreduce(g, reductions.SUM, comm=comm_dp, token=tok)
            synced.append(g_sum / dp)
        grads = MLPParams(*synced)

        # loss is averaged too, for logging parity across devices
        loss_sum, tok = allreduce(loss, reductions.SUM, comm=comm_dp, token=tok)
        loss = loss_sum / dp

        params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        return params, loss

    return step


def make_global_train_step(mesh, comm_dp, comm_tp, lr=1e-2):
    """Jitted global train step over a ("dp", "tp") mesh.

    Parameters enter with their hidden dimension sharded over tp and
    replicated over dp; the batch is sharded over dp.  The TP forward
    goes through :func:`allreduce` (and its backward through the
    identity-transpose rule); the DP gradient sync uses ``lax.psum``
    directly so the updated parameters are *typed* replicated over dp —
    which lets the out_specs declare them unsharded on that axis.
    """
    from jax import lax

    dp_ax, tp_ax = comm_dp.axes[0], comm_tp.axes[0]
    dp, tp = float(comm_dp.size), float(comm_tp.size)

    param_specs = MLPParams(
        w1=jax.P(None, tp_ax),
        b1=jax.P(tp_ax),
        w2=jax.P(tp_ax, None),
        b2=jax.P(None),
    )
    batch_specs = (jax.P(dp_ax, None), jax.P(dp_ax, None))

    def sync_grad(g, tp_sharded):
        if tp_sharded:
            return lax.psum(g, dp_ax) / dp
        # replicated params: identical grads across tp; psum over both
        # axes (÷ tp) re-establishes the replicated typing
        return lax.psum(g, (dp_ax, tp_ax)) / (dp * tp)

    def local_step(params, batch):
        x, targets = batch
        token = create_token()

        def loss_fn(p):
            y, _tok = _forward(p, x, comm_tp, token)
            return jnp.mean((y - targets) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = MLPParams(
            w1=sync_grad(grads.w1, True),
            b1=sync_grad(grads.b1, True),
            w2=sync_grad(grads.w2, True),
            b2=sync_grad(grads.b2, False),
        )
        params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        return params, loss[None]

    return jax.jit(
        jax.shard_map(
            local_step,
            mesh=mesh,
            in_specs=(param_specs, batch_specs),
            out_specs=(param_specs, jax.P((dp_ax, tp_ax))),
        )
    )
