"""Decoder-transformer train step over a 3-D ``(dp, tp, sp)`` mesh.

The composition showcase: every parallelism family the library ships,
in one differentiable training step —

* **TP** (Megatron f/g pair over ``tp``): qkv / mlp-up projections
  column-sharded, output / mlp-down row-sharded. The "g" collective is
  :func:`~mpi4jax_tpu.ops.allreduce.allreduce` (forward sum, identity
  backward); the "f" collective falls out of the reference's
  double-transpose convention for free — binding the allreduce
  primitive with ``transpose=True`` lowers to an identity whose
  *transpose* is a real allreduce (reference:
  mpi4jax/_src/collective_ops/allreduce.py:77-79, :182-194), i.e.
  exactly "identity forward, all-reduce backward".
* **SP/CP** (ring attention over ``sp``): the sequence axis is sharded;
  KV blocks rotate via ``sendrecv``/``ppermute`` with causal masking,
  gradients ride the ring backward (sendrecv transpose contract).
  Grouped-query attention supported (``kv_heads < heads``).
* **DP** over ``dp``: per-device micro-batches; gradients synced with
  typed ``psum`` so the updated parameters stay replicated.

Oracle-tested against an unsharded single-device implementation
(tests/parallel/test_transformer.py): forward loss and one SGD step
match to collective-roundoff.
"""

import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from mpi4jax_tpu.ops import reductions
from mpi4jax_tpu.ops._core import create_token
from mpi4jax_tpu.ops.allreduce import allreduce, allreduce_p
from mpi4jax_tpu.parallel.longseq import local_attention, ring_attention

__all__ = [
    "TransformerConfig",
    "BlockParams",
    "TransformerParams",
    "init_params",
    "make_global_train_step",
    "make_global_decode",
    "reference_loss",
    "reference_greedy_decode",
    "reference_sample_decode",
    "CHECKPOINT_NAMES",
]

# checkpoint_name tags attached inside each layer (see _forward_sharded);
# remat may be given as a tuple drawn from these to pick a custom
# save-list between full remat (save nothing) and "names" (the default
# q/k/attn-out/mlp-out sweet spot)
CHECKPOINT_NAMES = ("qkv", "v_proj", "attn_out", "mlp_out")


class TransformerConfig(NamedTuple):
    vocab: int = 64
    d_model: int = 32
    layers: int = 2
    heads: int = 4
    kv_heads: int = 2  # < heads = grouped-query attention
    head_dim: int = 8
    d_ff: int = 64
    eps: float = 1e-6
    # single-device attention kernel ("auto" / "flash" / "xla", see
    # parallel.longseq.local_attention); only reaches the sp=1 shortcut
    # and the ulysses full-sequence call — the multi-rank ring path has
    # its own blockwise schedule
    attn_impl: str = "auto"
    # >0: compute the loss in token chunks of this size (must divide the
    # local sequence length) — the head matmul and logsumexp run per
    # chunk under jax.checkpoint, so the full [B, S, V] logits tensor is
    # NEVER materialised (2.1 GB bf16 at the 940M/seq-2048/b16 MFU
    # config, 4.3 GB at b32 — the allocation that OOMs the larger-batch
    # and heavier-save-list configs).  The backward recomputes each
    # chunk's logits: one extra head matmul of FLOPs in exchange for
    # the logits' round-trips.  0 = off (single streaming-CE pass).
    ce_chunk: int = 0


class BlockParams(NamedTuple):
    ln1: jax.Array  # (L, d)              replicated
    wq: jax.Array   # (L, d, Hq*dh)       column-sharded over tp
    wk: jax.Array   # (L, d, Hkv*dh)      column-sharded over tp
    wv: jax.Array   # (L, d, Hkv*dh)      column-sharded over tp
    wo: jax.Array   # (L, Hq*dh, d)       row-sharded over tp
    ln2: jax.Array  # (L, d)              replicated
    w1: jax.Array   # (L, d, F)           column-sharded over tp
    w2: jax.Array   # (L, F, d)           row-sharded over tp


class TransformerParams(NamedTuple):
    embed: jax.Array  # (V, d)  replicated
    blocks: BlockParams
    ln_f: jax.Array   # (d,)    replicated
    head: jax.Array   # (d, V)  replicated


def init_params(key, cfg, dtype=jnp.float32):
    """Global parameter arrays (shard with :func:`param_specs`)."""
    c = cfg
    ks = jax.random.split(key, 8)

    def norm(k, shape, fan_in):
        return jax.random.normal(k, shape, dtype) * (1.0 / math.sqrt(fan_in))

    L, d, dh = c.layers, c.d_model, c.head_dim
    blocks = BlockParams(
        ln1=jnp.ones((L, d), dtype),
        wq=norm(ks[0], (L, d, c.heads * dh), d),
        wk=norm(ks[1], (L, d, c.kv_heads * dh), d),
        wv=norm(ks[2], (L, d, c.kv_heads * dh), d),
        wo=norm(ks[3], (L, c.heads * dh, d), c.heads * dh),
        ln2=jnp.ones((L, d), dtype),
        w1=norm(ks[4], (L, d, c.d_ff), d),
        w2=norm(ks[5], (L, c.d_ff, d), c.d_ff),
    )
    return TransformerParams(
        embed=norm(ks[6], (c.vocab, d), d),
        blocks=blocks,
        ln_f=jnp.ones((d,), dtype),
        head=norm(ks[7], (d, c.vocab), d),
    )


def param_specs(tp_ax):
    """PartitionSpecs: TP shards live on the projections' head/ff dims."""
    blocks = BlockParams(
        ln1=jax.P(None, None),
        wq=jax.P(None, None, tp_ax),
        wk=jax.P(None, None, tp_ax),
        wv=jax.P(None, None, tp_ax),
        wo=jax.P(None, tp_ax, None),
        ln2=jax.P(None, None),
        w1=jax.P(None, None, tp_ax),
        w2=jax.P(None, tp_ax, None),
    )
    return TransformerParams(
        embed=jax.P(None, None),
        blocks=blocks,
        ln_f=jax.P(None),
        head=jax.P(None, None),
    )


def _check_tp_divisibility(cfg, tp):
    for name, heads in (("heads", cfg.heads), ("kv_heads", cfg.kv_heads)):
        if heads % tp:
            raise ValueError(
                f"cfg.{name}={heads} must be divisible by the tensor-"
                f"parallel size {tp} (each tp rank owns "
                f"{name}/tp heads; for MQA-style configs with fewer kv "
                f"heads than tp ranks, replicate kv heads to tp first)"
            )


def _rmsnorm(x, g, eps):
    return x * lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps) * g


def _f_collective(x, comm, token):
    """Megatron "f": identity forward, all-reduce backward over tp.

    Implemented as the allreduce primitive bound with ``transpose=True``
    (lowers to identity; its AD transpose is the real allreduce — the
    reference's double-transpose contract)."""
    res, stamp = allreduce_p.bind(
        x, token.stamp, op=reductions.SUM, comm=comm, transpose=True
    )
    return res, token.with_stamp(stamp)


def _dense_mlp(h2, bp, cfg, comm_tp, comm_sp, token):
    """Megatron MLP: column-sharded up, row-sharded down, g-allreduce."""
    h2, token = _f_collective(h2, comm_tp, token)
    m_part = jax.nn.gelu(h2 @ bp.w1) @ bp.w2
    return allreduce(m_part, reductions.SUM, comm=comm_tp, token=token)


def _forward_sharded(
    params, tokens, cfg, comm_tp, comm_sp, mesh_axes, mlp=None,
    sequence="ring", remat=False, return_hidden=False,
):
    """Per-device forward; call inside shard_map over (dp, tp, sp).

    ``tokens``: local [B_local, S_local] int32.  Activations are
    replicated across tp, sequence-sharded across sp.  ``mesh_axes`` is
    the full axis set of the enclosing shard_map: activations are
    typed varying over all of it (collective outputs vary on their own
    axis, so the layer-scan carry must start that way too).

    ``mlp(h2, bp, cfg, comm_tp, comm_sp, token) -> (out, token)`` is
    the MLP sublayer (post-ln2); defaults to the dense Megatron pair —
    models/moe_transformer.py substitutes the expert-parallel MoE here.
    An mlp may instead return ``(out, token, aux)`` with ``aux`` a
    scalar auxiliary-loss contribution (e.g. MoE load-balancing / router
    z-loss); the per-layer contributions are summed and returned beside
    the logits.

    ``sequence`` picks the context-parallel attention scheme over sp:
    ``"ring"`` (KV blocks rotate, sendrecv transpose carries the
    gradient) or ``"ulysses"`` (two all-to-alls reshard heads↔sequence
    around full-sequence local attention).  Both compute exact
    attention — the same oracle covers either.
    """
    from mpi4jax_tpu.ops._core import promote_vma
    from mpi4jax_tpu.parallel.longseq import ulysses_attention

    mlp = mlp or _dense_mlp
    tp = comm_tp.size
    dh = cfg.head_dim
    hq_l, hk_l = cfg.heads // tp, cfg.kv_heads // tp
    b, s = tokens.shape
    if sequence not in ("ring", "ulysses"):
        raise ValueError(
            f"sequence must be 'ring' or 'ulysses', got {sequence!r}"
        )
    seq_attn = ring_attention if sequence == "ring" else ulysses_attention

    x = promote_vma(params.embed[tokens], mesh_axes)  # (B, S_local, d)
    aux0 = promote_vma(jnp.zeros((), jnp.float32), mesh_axes)

    from jax.ad_checkpoint import checkpoint_name

    def layer(carry, bp):
        x, aux = carry
        token = create_token()
        h = _rmsnorm(x, bp.ln1, cfg.eps)
        h, token = _f_collective(h, comm_tp, token)
        # checkpoint_name tags are inert except under remat="names",
        # whose policy saves exactly these tensors (see below)
        q = checkpoint_name((h @ bp.wq).reshape(b, s, hq_l, dh), "qkv")
        k = checkpoint_name((h @ bp.wk).reshape(b, s, hk_l, dh), "qkv")
        # v is tagged apart: the names policy recomputes it (one cheap
        # [t,d]x[d,d] matmul off the already-recomputed h) — the 128 MB
        # per layer it would pin is what lets batch 16 fit in HBM
        v = checkpoint_name((h @ bp.wv).reshape(b, s, hk_l, dh), "v_proj")
        attn, token = seq_attn(
            q, k, v, comm_sp, causal=True, token=token,
            impl=getattr(cfg, "attn_impl", "auto"),
        )
        a_part = attn.reshape(b, s, hq_l * dh) @ bp.wo
        a, token = allreduce(a_part, reductions.SUM, comm=comm_tp, token=token)
        x = x + checkpoint_name(a, "attn_out")

        h2 = _rmsnorm(x, bp.ln2, cfg.eps)
        res = mlp(h2, bp, cfg, comm_tp, comm_sp, token)
        m = checkpoint_name(res[0], "mlp_out")
        if len(res) > 2:  # (out, token, aux) — MoE auxiliary losses
            aux = aux + res[2]
        return (x + m, aux), None

    if isinstance(remat, (tuple, list)) and not remat:
        # () is falsy — it would silently skip the remat block below
        # and benchmark the non-remat path instead of erroring
        raise ValueError(
            "empty remat save-list; use remat=True for full remat or a "
            f"non-empty subset of {CHECKPOINT_NAMES}"
        )
    if remat:
        # rematerialise each layer in the backward pass: activation
        # memory drops from O(layers) to O(1) layers (plus the scan
        # carry) at ~1/3 extra FLOPs — the standard long-context lever
        # on HBM-bound chips.  The collectives re-execute under remat;
        # token ordering is per-layer-instance so replay is safe.
        # remat="dots" keeps every batched-matmul output (qkv/o/mlp
        # projections) and recomputes only the cheap rest — in practice
        # the attention internals, whose [T, T] score tensors are the
        # memory hog — recovering most of full-remat's memory saving at
        # a fraction of its ~1/3 FLOP overhead.
        # remat="names" is the measured sweet spot on bandwidth-starved
        # chips (docs/performance.md step timeline): keep FOUR
        # [tokens, d]-sized tensors per layer (q, k, attn-out, mlp-out
        # — v is tagged "v_proj", deliberately outside the save list)
        # and recompute only the cheap glue (rmsnorms, residual adds,
        # gelu) plus v, the single wide w1 matmul, and the flash
        # forward — ~0.9N recompute FLOPs vs full remat's 2N, at ~1/13
        # of the activation memory the dots policy would pin (it saves
        # the [tokens, d_ff] w1 outputs; this policy's whole point is
        # NOT saving those).
        # An explicit tuple/list of tag names selects a CUSTOM save
        # list — the memory/recompute dial exposed to sweeps (e.g. at
        # seq 32k, where the standard names list OOMs, a lighter
        # ("attn_out", "mlp_out") list can still fit).
        if remat == "dots":
            layer = jax.checkpoint(
                layer,
                policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            )
        elif remat == "names":
            layer = jax.checkpoint(
                layer,
                policy=jax.checkpoint_policies.save_only_these_names(
                    "qkv", "attn_out", "mlp_out"
                ),
            )
        elif isinstance(remat, (tuple, list)):
            unknown = set(remat) - set(CHECKPOINT_NAMES)
            if unknown:
                raise ValueError(
                    f"unknown checkpoint tag(s) {sorted(unknown)}; the "
                    f"layer tags are {CHECKPOINT_NAMES}"
                )
            layer = jax.checkpoint(
                layer,
                policy=jax.checkpoint_policies.save_only_these_names(
                    *remat
                ),
            )
        elif remat is True:
            layer = jax.checkpoint(layer)
        else:
            raise ValueError(
                f"remat must be False, True, 'dots', 'names' or a "
                f"tuple of tag names, got {remat!r}"
            )
    (x, aux), _ = lax.scan(layer, (x, aux0), params.blocks)
    x = _rmsnorm(x, params.ln_f, cfg.eps)
    if return_hidden:
        # chunked-CE path: the caller applies the head per token chunk
        return x, aux  # (B, S_local, d) final hidden, aux-loss sum
    return x @ params.head, aux  # (B, S_local, V) logits, aux-loss sum


def _ce(logits, targets):
    """Streaming cross-entropy: ``mean(lse - logits[target])``.

    Mathematically identical to log_softmax + gather (the picked
    log-probability IS ``logits[target] - logsumexp``), but never
    materialises a float32 ``[B, S, V]`` tensor: the f32 conversion
    fuses into the logsumexp reductions, so XLA reads the bf16 logits
    and writes only ``[B, S]`` statistics.  The log_softmax form cost
    ~12 GB/step of f32 HBM round-trips on the MFU config's
    ``[16, 2048, 32768]`` logits (step timeline, docs/performance.md)."""
    lse = jax.scipy.special.logsumexp(logits.astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(logits, targets[..., None], axis=-1)
    return (lse - picked[..., 0].astype(jnp.float32)).mean()


def _ce_chunked(x, head, targets, chunk, mesh_axes=()):
    """Chunked cross-entropy: the head matmul + streaming CE run per
    token chunk inside a ``lax.scan`` whose body is ``jax.checkpoint``ed
    — the full ``[B, S, V]`` logits tensor is never materialised (only
    one ``[B, chunk, V]`` block lives at a time), and the backward
    recomputes each chunk's logits instead of loading stored ones.
    Same math as :func:`_ce` (per-chunk f32 sums, one final divide), so
    results agree to f32 reduction-order roundoff.

    ``x``: [B, S_local, d] final hidden; ``head``: [d, V]."""
    b, s, d = x.shape
    if s % chunk:
        raise ValueError(
            f"ce_chunk={chunk} must divide the local sequence length "
            f"{s} (global seq / sp size)"
        )
    n = s // chunk
    xs = jnp.moveaxis(x.reshape(b, n, chunk, d), 1, 0)  # [n, B, c, d]
    ts = jnp.moveaxis(targets.reshape(b, n, chunk), 1, 0)  # [n, B, c]

    def blk(acc, inp):
        xb, tb = inp
        logits = xb @ head  # [B, c, V] — freed when the chunk ends
        lse = jax.scipy.special.logsumexp(
            logits.astype(jnp.float32), axis=-1
        )
        picked = jnp.take_along_axis(logits, tb[..., None], axis=-1)
        return acc + (lse - picked[..., 0].astype(jnp.float32)).sum(), None

    from mpi4jax_tpu.ops._core import promote_vma

    # the scan carry must match the body output's varying-axes type
    # under shard_map (same promotion as the layer scan's carry)
    acc0 = promote_vma(jnp.float32(0.0), mesh_axes)
    total, _ = lax.scan(jax.checkpoint(blk), acc0, (xs, ts))
    return total / (b * s)


def make_global_train_step(
    mesh, comm_dp, comm_tp, comm_sp, cfg, lr=1e-1, *, mlp=None, specs=None,
    sequence="ring", remat=False, donate=False,
):
    """Jitted global train step over a ``(dp, tp, sp)`` mesh.

    ``batch = (tokens, targets)``, both global ``[B, S]`` int32 sharded
    ``(dp, sp)`` (targets are the caller's shifted next tokens — the
    shift crosses sp shard boundaries, so it is done globally).
    Returns ``(new_params, loss)``.

    ``mlp`` / ``specs`` substitute the MLP sublayer and the parameter
    PartitionSpecs (see :func:`_forward_sharded`; used by the MoE
    variant, models/moe_transformer.py).  ``sequence`` picks the
    context-parallel attention scheme ("ring" or "ulysses" — the
    latter needs the per-tp-rank head counts divisible by the sp
    size).  ``remat=True`` wraps each layer in ``jax.checkpoint`` —
    activation memory O(1) layers instead of O(layers), ~1/3 extra
    FLOPs; gradients are unchanged (same math, recomputed).
    ``remat="dots"`` / ``remat="names"`` select partial policies (see
    ``_forward_sharded``); ``donate=True`` donates the params argument
    to the update (training-loop idiom).
    """
    dp_ax = comm_dp.axes[0]
    tp_ax = comm_tp.axes[0]
    sp_ax = comm_sp.axes[0]
    if sequence not in ("ring", "ulysses"):
        raise ValueError(
            f"sequence must be 'ring' or 'ulysses', got {sequence!r}"
        )
    n_data = float(comm_dp.size * comm_sp.size)
    tp = float(comm_tp.size)
    _check_tp_divisibility(cfg, comm_tp.size)
    if sequence == "ulysses" and comm_sp.size > 1:
        # checked after tp-divisibility so invalid-everywhere configs
        # get the general diagnosis, not ulysses-specific advice
        for name, heads in (("heads", cfg.heads), ("kv_heads", cfg.kv_heads)):
            if (heads // comm_tp.size) % comm_sp.size:
                raise ValueError(
                    f"sequence='ulysses' needs cfg.{name}/tp divisible by "
                    f"the sp size: {heads}//{comm_tp.size} per tp rank, "
                    f"sp={comm_sp.size} (for GQA, repeat kv heads or use "
                    f"sequence='ring')"
                )

    specs = param_specs(tp_ax) if specs is None else specs
    batch_specs = (jax.P(dp_ax, sp_ax), jax.P(dp_ax, sp_ax))

    def sync_grad(g, spec):
        # shard_map's vma-aware AD has ALREADY psum'ed each param's
        # cotangent over every axis the param is invariant on (the
        # transpose of replication is a sum) — adding explicit psums
        # here would double-count.  Only scaling remains:
        if tp_ax in tuple(spec):
            # tp-sharded: g = sum over (dp, sp) of the per-rank local
            # grads; the global loss is the mean of the local losses
            return g / n_data
        # replicated: g additionally summed over tp, but the
        # f-collectives made each rank's grad the FULL tp-sum already,
        # so the automatic tp-sum overcounts by tp.  (sp-sharded MoE
        # expert params land here too: their cross-device contributions
        # arrive through the alltoall transpose — same scaling class.)
        return g / (n_data * tp)

    def local_step(params, batch):
        tokens, targets = batch

        ce_chunk = getattr(cfg, "ce_chunk", 0)

        def loss_fn(p):
            out, aux = _forward_sharded(
                p, tokens, cfg, comm_tp, comm_sp, (dp_ax, tp_ax, sp_ax),
                mlp=mlp, sequence=sequence, remat=remat,
                return_hidden=bool(ce_chunk),
            )
            if ce_chunk:
                return _ce_chunked(
                    out, p.head, targets, ce_chunk,
                    mesh_axes=(dp_ax, tp_ax, sp_ax),
                ) + aux
            return _ce(out, targets) + aux

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = jax.tree.map(sync_grad, grads, specs)
        params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        loss = lax.psum(loss, (dp_ax, tp_ax, sp_ax)) / (n_data * tp)
        return params, loss[None]

    return jax.jit(
        jax.shard_map(
            local_step,
            mesh=mesh,
            in_specs=(specs, batch_specs),
            out_specs=(specs, jax.P((dp_ax, tp_ax, sp_ax))),
        ),
        # donate=True releases the old params' buffers to the update
        # (the training-loop idiom `params, loss = step(params, ...)`);
        # callers that reuse params after the call keep the default
        donate_argnums=(0,) if donate else (),
    )


def _attn_residual(x, bp, cfg):
    """Unsharded attention sublayer: ln1 → QKV → causal attention → wo,
    plus the residual.  THE single copy of the dense layer's attention
    math — the oracles and the pipeline stage all call it."""
    b, s, _ = x.shape
    h = _rmsnorm(x, bp.ln1, cfg.eps)
    q = (h @ bp.wq).reshape(b, s, cfg.heads, cfg.head_dim)
    k = (h @ bp.wk).reshape(b, s, cfg.kv_heads, cfg.head_dim)
    v = (h @ bp.wv).reshape(b, s, cfg.kv_heads, cfg.head_dim)
    attn = local_attention(q, k, v, causal=True, impl="xla")
    return x + attn.reshape(b, s, -1) @ bp.wo


def dense_layer(x, bp, cfg):
    """One full unsharded decoder layer (attention + dense MLP)."""
    x = _attn_residual(x, bp, cfg)
    h2 = _rmsnorm(x, bp.ln2, cfg.eps)
    return x + jax.nn.gelu(h2 @ bp.w1) @ bp.w2


def reference_loss(params, tokens, targets, cfg):
    """Unsharded oracle: identical math on one device."""
    x = params.embed[tokens]

    def layer(x, bp):
        return dense_layer(x, bp, cfg), None

    x, _ = lax.scan(layer, x, params.blocks)
    x = _rmsnorm(x, params.ln_f, cfg.eps)
    return _ce(x @ params.head, targets)


# --------------------------- inference -----------------------------


def _choose_token(logits, pos, key, row_ids, sampler, temperature, top_k):
    """Next-token choice from ``[B, V]`` logits — THE single copy shared
    by the sharded decoder and the unsharded oracle (like
    :func:`_attn_residual` for the layer math).

    Sampling is shard-invariant by construction: each row's draw uses
    ``fold_in(fold_in(key, pos), global_row_id)``, so the randomness
    for a given (sequence, position) is identical however the batch is
    sharded over dp — the sharded decoder matches the unsharded oracle
    bitwise given the same key."""
    if sampler == "greedy":
        return jnp.argmax(logits, axis=-1)
    logits = logits.astype(jnp.float32) / temperature
    if top_k is not None:
        # keep the k highest logits per row; ties at the threshold stay
        # eligible (same rule in the oracle, so they cancel)
        thresh = jax.lax.top_k(logits, int(top_k))[0][..., -1:]
        logits = jnp.where(logits >= thresh, logits, -jnp.inf)
    step_key = jax.random.fold_in(key, pos)
    row_keys = jax.vmap(lambda r: jax.random.fold_in(step_key, r))(row_ids)
    return jax.vmap(jax.random.categorical)(row_keys, logits)


def _check_sampler(sampler, temperature, top_k, vocab):
    if sampler not in ("greedy", "categorical"):
        raise ValueError(
            f"sampler must be 'greedy' or 'categorical', got {sampler!r}"
        )
    if sampler == "greedy":
        # greedy ignores both knobs — setting one is a forgotten
        # sampler="categorical", not a request for deterministic output
        if temperature != 1.0 or top_k is not None:
            raise ValueError(
                "temperature/top_k only apply to sampler='categorical' "
                f"(got sampler='greedy' with temperature={temperature}, "
                f"top_k={top_k})"
            )
        return
    if not temperature > 0:
        raise ValueError(f"temperature must be > 0, got {temperature}")
    if top_k is not None and (
        int(top_k) != top_k or not 0 < int(top_k) <= vocab
    ):
        raise ValueError(
            f"top_k must be an integer in (0, vocab={vocab}], got {top_k!r}"
        )


def _decode_step_sharded(params, cache, last_tok, pos, cfg, comm_tp, hq_l, hk_l):
    """One decode step on the local tp shard: embed the last token,
    run the cached attention + MLP, and return the position's logits —
    the caller picks the next token (greedy or sampled).

    ``cache``: (layers, 2, B, S_max, Hkv_local, dh) — K/V per layer.
    ``last_tok``: (B,) int32; ``pos``: scalar int32 write position.
    Returns (cache, logits).
    """
    dh = cfg.head_dim
    b = last_tok.shape[0]
    x = params.embed[last_tok][:, None, :]  # (B, 1, d)
    token = create_token()

    def layer(carry, inputs):
        x, token = carry
        bp, kv = inputs
        h = _rmsnorm(x, bp.ln1, cfg.eps)
        h, token = _f_collective(h, comm_tp, token)
        q = (h @ bp.wq).reshape(b, 1, hq_l, dh)
        k_new = (h @ bp.wk).reshape(b, 1, hk_l, dh)
        v_new = (h @ bp.wv).reshape(b, 1, hk_l, dh)
        k_cache = lax.dynamic_update_slice(kv[0], k_new, (0, pos, 0, 0))
        v_cache = lax.dynamic_update_slice(kv[1], v_new, (0, pos, 0, 0))
        # attend over positions <= pos (masked full-cache attention;
        # q_offset=pos makes the causal mask pass exactly those)
        attn = local_attention(
            q, k_cache, v_cache, causal=True, q_offset=pos, impl="xla"
        )
        a_part = attn.reshape(b, 1, hq_l * dh) @ bp.wo
        a, token = allreduce(a_part, reductions.SUM, comm=comm_tp, token=token)
        x = x + a
        h2 = _rmsnorm(x, bp.ln2, cfg.eps)
        h2, token = _f_collective(h2, comm_tp, token)
        m_part = jax.nn.gelu(h2 @ bp.w1) @ bp.w2
        m, token = allreduce(m_part, reductions.SUM, comm=comm_tp, token=token)
        return (x + m, token), jnp.stack([k_cache, v_cache])

    (x, _token), cache = lax.scan(layer, (x, token), (params.blocks, cache))
    x = _rmsnorm(x, params.ln_f, cfg.eps)
    logits = (x @ params.head)[:, 0, :]  # (B, V)
    return cache, logits


def _prefill_sharded(
    params, prompt, cfg, comm_tp, hq_l, hk_l, max_len, impl="xla",
    logits_pos=None,
):
    """Batched prefill on the local tp shard: one causal forward pass
    over the whole prompt, writing every prompt position's K/V into the
    (max_len-budget) cache and returning the greedy next token after
    the last prompt position.

    Identical math to running :func:`_decode_step_sharded` position by
    position — the attention is causal and the projections are
    per-position — but the matmuls are [B, P, ·] instead of P
    sequential [B, 1, ·] calls, so the prompt costs one MXU-shaped
    forward instead of P dispatches.  Returns ``(cache, logits)`` with
    the LAST prompt position's ``[B, V]`` logits — the caller picks
    the next token (greedy or sampled).

    ``logits_pos`` (traced scalar) returns the logits of THAT position
    instead of the last one: the serving engine right-pads prompts to
    a compile-size bucket (one executable per bucket, not per length)
    and reads the logits at the true last prompt position — the padded
    tail positions are causally invisible to it, and their garbage KV
    is overwritten in order by the decode steps that follow
    (mpi4jax_tpu/serving/engine.py).
    """
    dh = cfg.head_dim
    b, p_len = prompt.shape
    x = params.embed[prompt]  # (B, P, d)
    token = create_token()
    pad = max_len - p_len

    def layer(carry, bp):
        x, token = carry
        h = _rmsnorm(x, bp.ln1, cfg.eps)
        h, token = _f_collective(h, comm_tp, token)
        q = (h @ bp.wq).reshape(b, p_len, hq_l, dh)
        k = (h @ bp.wk).reshape(b, p_len, hk_l, dh)
        v = (h @ bp.wv).reshape(b, p_len, hk_l, dh)
        attn = local_attention(q, k, v, causal=True, impl=impl)
        a_part = attn.reshape(b, p_len, hq_l * dh) @ bp.wo
        a, token = allreduce(a_part, reductions.SUM, comm=comm_tp, token=token)
        x = x + a
        h2 = _rmsnorm(x, bp.ln2, cfg.eps)
        h2, token = _f_collective(h2, comm_tp, token)
        m_part = jax.nn.gelu(h2 @ bp.w1) @ bp.w2
        m, token = allreduce(m_part, reductions.SUM, comm=comm_tp, token=token)
        kv = jnp.stack([
            jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0))),
            jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0))),
        ])
        return (x + m, token), kv

    (x, _token), cache = lax.scan(layer, (x, token), params.blocks)
    x = _rmsnorm(x, params.ln_f, cfg.eps)
    if logits_pos is None:
        last = x[:, -1, :]  # (B, d): last prompt position
    else:
        last = lax.dynamic_index_in_dim(
            x, logits_pos, axis=1, keepdims=False
        )
    logits = last @ params.head  # (B, V)
    return cache, logits


def make_global_decode(
    mesh, comm_dp, comm_tp, cfg, max_len, *, prefill="batched",
    kv_bucket=None, prefill_impl="xla", sampler="greedy",
    temperature=1.0, top_k=None,
):
    """Jitted greedy autoregressive decoder over a ``(dp, tp)`` mesh.

    ``decode(params, prompt)``: ``prompt`` is global ``[B, P]`` int32
    sharded over dp (tp-replicated).  ``prefill="batched"`` (default)
    processes the whole prompt in ONE causal forward pass that fills
    the KV cache — the prompt costs a single MXU-shaped forward instead
    of P sequential steps; ``prefill="stepwise"`` keeps the
    position-at-a-time path (same math, the original formulation — the
    equivalence is pinned by tests/parallel/test_decode.py).  Then
    generates ``max_len - P`` greedy tokens.  Returns global
    ``[B, max_len]`` int32 — prompt followed by the generated
    continuation.  Matches :func:`reference_greedy_decode` exactly
    (same math; tp roundoff only).

    ``sampler="categorical"`` draws each continuation token from the
    (temperature-scaled, optionally top-k-truncated) softmax instead of
    the argmax; the returned callable then takes a third argument,
    ``decode(params, prompt, key)`` (a ``jax.random.PRNGKey``).  The
    draw for a given (row, position) folds the GLOBAL row id and the
    position into the key, so the sharded sampler matches
    :func:`reference_sample_decode` bitwise under any dp sharding.

    ``prefill_impl`` picks the batched prefill's attention kernel:
    ``"xla"`` (default — dense scores; the right choice for short
    prompts, where the flash kernel's block pipeline costs more than it
    saves) or ``"flash"`` (the Pallas blockwise kernel, ops/flash.py)
    for LONG prompts, where the dense [P, P] score tensor dominates the
    prefill — the long-context inference analog of the training-side
    crossover (docs/performance.md "Flash vs dense").  Token-identical
    either way (same math; the equivalence is pinned on-chip).

    ``kv_bucket=N`` runs the generate loop in KV-length buckets: the
    scan carry is a cache VIEW whose static length grows by N per
    segment (a python loop of scans inside the same jit), so each step
    reads/attends only ``ceil((pos+1)/N)·N`` cache positions instead of
    the full ``max_len`` budget.  Decode is KV-bandwidth-bound at large
    batch, and with the un-bucketed loop every step pays the PADDED
    budget read — at the bench's batch-32 point that padding tax is the
    measured ~2× gap to the bandwidth bound (docs/performance.md).
    Token-exact vs the un-bucketed loop (garbage positions beyond
    ``pos`` are causally masked either way).
    """
    dp_ax, tp_ax = comm_dp.axes[0], comm_tp.axes[0]
    tp = comm_tp.size
    _check_tp_divisibility(cfg, tp)
    hq_l, hk_l = cfg.heads // tp, cfg.kv_heads // tp
    specs = param_specs(tp_ax)
    if prefill not in ("batched", "stepwise"):
        raise ValueError(
            f"prefill must be 'batched' or 'stepwise', got {prefill!r}"
        )
    if prefill_impl not in ("xla", "flash"):
        raise ValueError(
            f"prefill_impl must be 'xla' or 'flash', got {prefill_impl!r}"
        )
    _check_sampler(sampler, temperature, top_k, cfg.vocab)
    if kv_bucket is not None and (
        int(kv_bucket) != kv_bucket or not 0 < int(kv_bucket) <= max_len
    ):
        raise ValueError(
            f"kv_bucket must be an integer in (0, max_len={max_len}], "
            f"got {kv_bucket!r}"
        )

    def local_decode(params, prompt, key):
        from mpi4jax_tpu.ops._core import promote_vma

        b, p_len = prompt.shape
        if p_len > max_len:
            raise ValueError(
                f"prompt length {p_len} exceeds max_len={max_len} "
                f"(the decoder's static sequence budget)"
            )
        prompt = promote_vma(prompt, (dp_ax, tp_ax))
        key = promote_vma(key, (dp_ax, tp_ax))
        # global row ids: the sampling key folds these in, so draws are
        # identical under any dp sharding (see _choose_token)
        row_ids = lax.axis_index(dp_ax) * b + jnp.arange(b)
        out = promote_vma(
            jnp.zeros((b, max_len), prompt.dtype), (dp_ax, tp_ax)
        )
        out = lax.dynamic_update_slice(out, prompt, (0, 0))

        def choose(logits, pos):
            return _choose_token(
                logits, pos, key, row_ids, sampler, temperature, top_k
            ).astype(prompt.dtype)

        if prefill == "batched" and p_len > 1:
            cache, pre_logits = _prefill_sharded(
                params, prompt, cfg, comm_tp, hq_l, hk_l, max_len,
                impl=prefill_impl,
            )
            if p_len < max_len:
                # the token at position p_len is chosen from position
                # p_len - 1's logits
                nxt = choose(pre_logits, p_len - 1)
                out = lax.dynamic_update_slice(
                    out, nxt[:, None], (0, p_len)
                )
            start = p_len  # positions start..max_len-2 remain
        else:
            cache = promote_vma(
                jnp.zeros(
                    (cfg.layers, 2, b, max_len, hk_l, cfg.head_dim),
                    params.embed.dtype,
                ),
                (dp_ax, tp_ax),
            )
            start = 0

        def step(carry, pos):
            # pos runs start..max_len-2, so pos+1 is always a valid slot
            cache, out = carry
            last = lax.dynamic_index_in_dim(
                out, pos, axis=1, keepdims=False
            )
            cache, logits = _decode_step_sharded(
                params, cache, last, pos, cfg, comm_tp, hq_l, hk_l
            )
            # inside the prompt, keep the given token; past it, append
            # the chosen (greedy or sampled) token
            nxt = choose(logits, pos)
            cur = lax.dynamic_index_in_dim(out, pos + 1, axis=1, keepdims=False)
            write = jnp.where(pos + 1 < p_len, cur, nxt)
            out = lax.dynamic_update_slice(out, write[:, None], (0, pos + 1))
            return (cache, out), None

        if kv_bucket is None:
            (cache, out), _ = lax.scan(
                step, (cache, out), jnp.arange(start, max_len - 1)
            )
        else:
            # bucketed KV growth: segment s scans positions
            # [prev, min(end_s, max_len-1)) with a cache view of STATIC
            # length end_s (pos < end_s throughout, so the causal mask
            # and the pos-slot write both stay in range); between
            # segments the view is zero-padded to the next bucket
            # boundary.  The python loop is over static bounds — one
            # executable, ~max_len/kv_bucket scan instances.
            bk = int(kv_bucket)
            ends = list(range((start // bk + 1) * bk, max_len, bk))
            ends.append(max_len)
            view = cache[:, :, :, : ends[0]]
            prev = start
            for i, end in enumerate(ends):
                if i:
                    view = jnp.pad(
                        view,
                        (
                            (0, 0), (0, 0), (0, 0),
                            (0, end - ends[i - 1]), (0, 0), (0, 0),
                        ),
                    )
                hi = min(end, max_len - 1)
                (view, out), _ = lax.scan(
                    step, (view, out), jnp.arange(prev, hi)
                )
                prev = hi
        # every tp rank computed the identical sequence, but collective
        # outputs are varying-typed; a masked psum re-establishes the
        # replicated typing the out_specs declare
        tp_rank = lax.axis_index(tp_ax)
        return lax.psum(
            jnp.where(tp_rank == 0, out, jnp.zeros((), out.dtype)), tp_ax
        )

    decode = jax.jit(
        jax.shard_map(
            local_decode,
            mesh=mesh,
            in_specs=(specs, jax.P(dp_ax, None), jax.P(None)),
            out_specs=jax.P(dp_ax, None),
        )
    )
    if sampler == "greedy":
        # greedy ignores the key: keep the two-argument call surface
        _zero_key = jax.random.PRNGKey(0)
        return lambda params, prompt: decode(params, prompt, _zero_key)

    def _raw_key(key):
        # accept both key styles: new-style typed keys (jax.random.key,
        # rank 0 — would trip the rank-1 P(None) spec) unwrap to their
        # uint32 data; legacy PRNGKey arrays pass through
        if jnp.issubdtype(jnp.asarray(key).dtype, jax.dtypes.prng_key):
            return jax.random.key_data(key)
        return key

    return lambda params, prompt, key: decode(params, prompt, _raw_key(key))


def reference_greedy_decode(params, prompt, cfg, max_len):
    """Unsharded oracle: full-sequence recompute per position."""
    b, p_len = prompt.shape
    if p_len > max_len:
        raise ValueError(
            f"prompt length {p_len} exceeds max_len={max_len}"
        )
    out = jnp.zeros((b, max_len), prompt.dtype)
    out = lax.dynamic_update_slice(out, prompt, (0, 0))

    def body(pos, out):
        x = params.embed[out]

        def layer(x, bp):
            return dense_layer(x, bp, cfg), None

        x, _ = lax.scan(layer, x, params.blocks)
        x = _rmsnorm(x, params.ln_f, cfg.eps)
        logits = x @ params.head  # (B, max_len, V)
        step_logits = lax.dynamic_index_in_dim(
            logits, pos, axis=1, keepdims=False
        )
        nxt = jnp.argmax(step_logits, axis=-1).astype(out.dtype)
        cur = lax.dynamic_index_in_dim(out, pos + 1, axis=1, keepdims=False)
        write = jnp.where(pos + 1 < p_len, cur, nxt)
        return lax.dynamic_update_slice(out, write[:, None], (0, pos + 1))

    return lax.fori_loop(0, max_len - 1, body, out)


def reference_sample_decode(
    params, prompt, cfg, max_len, key, *, temperature=1.0, top_k=None
):
    """Unsharded sampling oracle: full-sequence recompute per position,
    next tokens drawn through the SAME :func:`_choose_token` (per-row
    fold_in of position and global row id) as the sharded decoder — so
    ``make_global_decode(..., sampler="categorical")`` must match it
    bitwise given the same key, under any dp/tp sharding."""
    _check_sampler("categorical", temperature, top_k, cfg.vocab)
    b, p_len = prompt.shape
    if p_len > max_len:
        raise ValueError(
            f"prompt length {p_len} exceeds max_len={max_len}"
        )
    row_ids = jnp.arange(b)
    out = jnp.zeros((b, max_len), prompt.dtype)
    out = lax.dynamic_update_slice(out, prompt, (0, 0))

    def body(pos, out):
        x = params.embed[out]

        def layer(x, bp):
            return dense_layer(x, bp, cfg), None

        x, _ = lax.scan(layer, x, params.blocks)
        x = _rmsnorm(x, params.ln_f, cfg.eps)
        logits = x @ params.head  # (B, max_len, V)
        step_logits = lax.dynamic_index_in_dim(
            logits, pos, axis=1, keepdims=False
        )
        nxt = _choose_token(
            step_logits, pos, key, row_ids, "categorical", temperature,
            top_k,
        ).astype(out.dtype)
        cur = lax.dynamic_index_in_dim(out, pos + 1, axis=1, keepdims=False)
        write = jnp.where(pos + 1 < p_len, cur, nxt)
        return lax.dynamic_update_slice(out, write[:, None], (0, pos + 1))

    return lax.fori_loop(0, max_len - 1, body, out)


# -- t4j-lint entries: the DPxTPxSP train step's schedule on the
# smallest composed mesh (2,2,2) — TP Megatron f/g, SP ring attention,
# DP grad sync all in one extracted schedule.


def _lint_train_step():
    import jax as _jax

    from mpi4jax_tpu.parallel.comm import MeshComm

    mesh = _jax.make_mesh(
        (2, 2, 2), ("dp", "tp", "sp"),
        axis_types=(_jax.sharding.AxisType.Auto,) * 3,
    )
    world = MeshComm.from_mesh(mesh)
    cfg = TransformerConfig(
        vocab=32, d_model=16, layers=2, heads=4, kv_heads=2, head_dim=8,
        d_ff=32,
    )
    params = init_params(_jax.random.PRNGKey(0), cfg)
    tokens = _jax.random.randint(
        _jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab
    )
    step = make_global_train_step(
        mesh, world.sub("dp"), world.sub("tp"), world.sub("sp"), cfg,
        lr=1e-1,
    )
    return step(params, (tokens, jnp.roll(tokens, -1, axis=1)))


T4J_LINT_ENTRIES = [("train_step_2x2x2", _lint_train_step)]
