"""Flagship workload: nonlinear shallow-water solver, SPMD over a TPU mesh.

Behavioural parity target: the reference's demo application
(examples/shallow_water.py, adapted there from dionhaefner/shallow-water)
— a C-grid nonlinear shallow-water model with the Sadourny (1975)
energy-conserving potential-vorticity scheme, Adams–Bashforth-2 stepping
with coefficients (1.6, −0.6) (shallow_water.py:126-127), periodic-x /
solid-wall-y boundaries, lateral viscosity, and a 1-cell ghost ring
exchanged ~12× per step (shallow_water.py:277-412).  The published
benchmark numbers (BASELINE.md) come from this workload on a 100×
enlarged domain (3600×1800).

TPU-first redesign (not a port):

* **One SPMD program** over a ``("y", "x")`` device mesh via
  ``jax.shard_map`` instead of one MPI process per rank: rank-dependent
  behaviour (wall masks, coordinate offsets) uses ``lax.axis_index``
  instead of Python branching, so a single compiled executable serves
  every device — and a 1×1 mesh runs the identical program on one chip.
* **Halo exchange = ppermute** (parallel/halo.py): each direction is one
  ICI nearest-neighbour transfer fused into the step, replacing ~4
  blocking host MPI calls per field (SURVEY §3.4: the reference crosses
  the process boundary ~5000× per outer tick; here the entire multistep
  loop is one XLA executable that never leaves HBM).
* **Distributed initial conditions**: each device evaluates the analytic
  jet on its own coordinate slab, and the geostrophic cumulative
  integral — a *global* cumsum in the reference
  (shallow_water.py:147-149) — becomes an mpi4jax_tpu ``scan`` (prefix
  sum) over the y axis plus an ``allreduce`` for the mean, so no device
  ever materialises the global grid.
* Everything is float32 (TPU-native; matches JAX-default behaviour of
  the reference) and the hot loop sits in ``lax.fori_loop`` inside one
  ``jit`` (shallow_water.py:415-420 does the same).
"""

import math
from dataclasses import dataclass, replace
from functools import partial
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from mpi4jax_tpu.ops import reductions
from mpi4jax_tpu.ops._core import as_token
from mpi4jax_tpu.ops.allreduce import allreduce
from mpi4jax_tpu.ops.collectives import allgather, scan
from mpi4jax_tpu.parallel.halo import halo_exchange_2d, halo_exchange_2d_batch

__all__ = [
    "SWConfig",
    "SWState",
    "initial_state",
    "shallow_water_step",
    "make_multistep",
    "make_solver",
    "gather_global",
]

DAY_IN_SECONDS = 86_400.0


@dataclass(frozen=True)
class SWConfig:
    """Static model configuration (hashable: used as a jit-static arg)."""

    ny: int = 180  # global interior cells, y
    nx: int = 360  # global interior cells, x
    dx: float = 5e3  # metres
    dy: float = 5e3
    gravity: float = 9.81
    depth: float = 100.0
    coriolis_f: float = 2e-4
    coriolis_beta: float = 2e-11
    periodic_x: bool = True
    ab_a: float = 1.6  # Adams–Bashforth coefficients (reference :126-127)
    ab_b: float = -0.6
    dtype: str = "float32"
    # Ghost-ring width. 1 = the reference's layout (~12 exchanges/step,
    # shallow_water.py:277-412 there). 2 = wide-halo schedule: all
    # intermediate fields (fluxes, vorticity, kinetic energy, viscosity
    # gradients) are recomputed locally inside the ghost region, so a
    # step needs only 2 exchange rounds of the prognostic fields (5
    # exchanges). 4 = single-exchange schedule: one batched exchange of
    # (h, u, v) per step; the post-update viscosity operates on locally
    # recomputed ring-2 values and tendencies are never communicated
    # (they stay valid on ring-2 inductively). Identical numerics for
    # all widths (tested equal to the narrow path).
    ghost: int = 1

    @property
    def lateral_viscosity(self):
        return 1e-3 * self.coriolis_f * self.dx**2

    @property
    def dt(self):
        # CFL-limited gravity-wave time step (reference :137)
        return 0.125 * min(self.dx, self.dy) / math.sqrt(self.gravity * self.depth)

    @property
    def length_x(self):
        return self.nx * self.dx

    @property
    def length_y(self):
        return self.ny * self.dy

    def local_interior(self, comm):
        py, px = comm.axis_sizes
        if self.ny % py or self.nx % px:
            raise ValueError(
                f"grid {self.ny}x{self.nx} not divisible by mesh {py}x{px}"
            )
        return self.ny // py, self.nx // px

    def bench_size(self):
        """The published-benchmark domain: 100× the demo cell count
        (docs/shallow-water.rst:49-51 → 3600×1800), on the wide-halo
        schedule (the fastest single-chip configuration — on one chip
        permutes are elided, so the ghost=4 schedule's fewer rounds buy
        nothing and its extra masking costs; numerics identical)."""
        return replace(self, ny=1800, nx=3600, ghost=2)


class SWState(NamedTuple):
    h: jax.Array
    u: jax.Array
    v: jax.Array
    dh: jax.Array
    du: jax.Array
    dv: jax.Array


def _device_coords(comm):
    """(iy, ix) coordinates of this device on the ("y","x") comm."""
    iy = lax.axis_index((comm.axes[0],))
    ix = lax.axis_index((comm.axes[1],))
    return iy, ix


def _local_mesh_coords(cfg, comm):
    """Per-device physical coordinates of the local block incl. ghosts."""
    G = cfg.ghost
    ny_l, nx_l = cfg.local_interior(comm)
    iy, ix = _device_coords(comm)
    # interior cell j of this device has global index iy*ny_l + j; the
    # ghost ring shifts indices by -G
    jy = jnp.arange(-G, ny_l + G, dtype=cfg.dtype) + (iy * ny_l).astype(cfg.dtype)
    jx = jnp.arange(-G, nx_l + G, dtype=cfg.dtype) + (ix * nx_l).astype(cfg.dtype)
    y = jy * cfg.dy
    x = jx * cfg.dx
    return jnp.meshgrid(y, x, indexing="ij")


def _coriolis(cfg, yy):
    return (cfg.coriolis_f + yy * cfg.coriolis_beta).astype(cfg.dtype)


def _wall_masks(comm):
    """(is_north_edge, is_south_edge) row masks for solid-wall BCs."""
    py, _ = comm.axis_sizes
    iy, _ = _device_coords(comm)
    return iy == py - 1, iy == 0


def initial_state(cfg, comm, *, token=None):
    """Geostrophically balanced zonal jet + perturbation, built
    device-locally (reference builds it globally then slices,
    shallow_water.py:138-170).

    Must be called inside the model's shard_map.
    """
    token = as_token(token)
    G = cfg.ghost
    yy, xx = _local_mesh_coords(cfg, comm)
    ly, lx = cfg.length_y, cfg.length_x

    u0 = 10.0 * jnp.exp(-((yy - 0.5 * ly) ** 2) / (0.02 * lx) ** 2)
    v0 = jnp.zeros_like(u0)

    # geostrophic balance h_y = -(f/g) u, integrated along global y.
    # Local trapezoid-free cumsum + exclusive cross-device prefix via the
    # scan collective over the y sub-communicator.
    integrand = (-cfg.dy * u0 * _coriolis(cfg, yy) / cfg.gravity).astype(cfg.dtype)
    interior = integrand[G:-G, :]
    local_cum = jnp.cumsum(interior, axis=0)
    local_total = local_cum[-1, :]
    ycomm = comm.sub(comm.axes[0])
    incl, token = scan(local_total, reductions.SUM, comm=ycomm, token=token)
    offset = incl - local_total  # exclusive prefix of previous y-blocks
    h_geo = jnp.pad(local_cum + offset[None, :], ((G, G), (0, 0)), mode="edge")

    # centre around the mean depth: global mean via allreduce
    ny_l, nx_l = cfg.local_interior(comm)
    local_sum = h_geo[G:-G, G:-G].sum()
    total, token = allreduce(local_sum, reductions.SUM, comm=comm, token=token)
    n_cells = float(cfg.ny * cfg.nx)
    h_mean = total / n_cells

    h0 = (
        cfg.depth
        + h_geo
        - h_mean
        + 0.2
        * jnp.sin(xx / lx * 10.0 * jnp.pi)
        * jnp.cos(yy / ly * 8.0 * jnp.pi)
    ).astype(cfg.dtype)

    per = (False, cfg.periodic_x)
    h0, token = halo_exchange_2d(h0, comm, periodic=per, token=token, width=G)
    u0, token = halo_exchange_2d(
        u0.astype(cfg.dtype), comm, periodic=per, token=token, width=G
    )
    v0, token = halo_exchange_2d(
        v0.astype(cfg.dtype), comm, periodic=per, token=token, width=G
    )

    if G == 2:
        zeros = jnp.zeros((ny_l, nx_l), h0.dtype)  # wide: interior-only
    else:
        zeros = jnp.zeros_like(h0)  # narrow + single-exchange: full-shape
    return SWState(h0, u0, v0, zeros, zeros, zeros), token


# -- finite-difference helpers on (ny+2, nx+2) blocks ---------------------
# interior view: [1:-1, 1:-1]; neighbours: e/w shift x, n/s shift y.


def _i(a):
    return a[1:-1, 1:-1]


def _e(a):
    return a[1:-1, 2:]


def _w(a):
    return a[1:-1, :-2]


def _n(a):
    return a[2:, 1:-1]


def _s(a):
    return a[:-2, 1:-1]


def _ne(a):
    return a[2:, 2:]


def _set_interior(a, val):
    return a.at[1:-1, 1:-1].set(val)


def shallow_water_step(state, cfg, comm, *, first_step=False, token=None):
    """One model step (reference: shallow_water.py:277-412, same scheme).

    ``cfg.ghost == 1``: the reference's schedule, ~12 halo exchanges per
    step.  ``cfg.ghost == 2``: wide-halo schedule, 5 exchanges per step
    (see :func:`_step_wide`).  ``cfg.ghost == 4``: single-exchange
    schedule, one batched exchange per step (see :func:`_step_wide4`).
    All numerically identical.
    """
    if cfg.ghost == 2:
        return _step_wide(state, cfg, comm, first_step=first_step, token=token)
    if cfg.ghost == 4:
        return _step_wide4(state, cfg, comm, first_step=first_step, token=token)
    if cfg.ghost != 1:
        raise ValueError(f"ghost width must be 1, 2 or 4, got {cfg.ghost}")
    token = as_token(token)
    per = (False, cfg.periodic_x)
    exchange = partial(halo_exchange_2d, comm=comm, periodic=per)
    is_north, _is_south = _wall_masks(comm)
    dx, dy, g = cfg.dx, cfg.dy, cfg.gravity

    h, u, v, dh, du, dv = state

    def wall_v(a):
        """v = 0 on the northern wall row (reference :401-402)."""
        return jnp.where(is_north, a.at[-2, :].set(0.0), a)

    # cell-centred height with edge-padded ghosts, then exchanged
    hc = jnp.pad(h[1:-1, 1:-1], 1, mode="edge")
    hc, token = exchange(hc, token=token)

    # mass fluxes on cell faces
    fe = _set_interior(jnp.zeros_like(u), 0.5 * (_i(hc) + _e(hc)) * _i(u))
    fn = _set_interior(jnp.zeros_like(v), 0.5 * (_i(hc) + _n(hc)) * _i(v))
    fe, token = exchange(fe, token=token)
    fn, token = exchange(fn, token=token)
    fn = wall_v(fn)

    dh_new = _set_interior(
        dh, -(_i(fe) - _w(fe)) / dx - (_i(fn) - _s(fn)) / dy
    )

    # potential vorticity (planetary + relative, over face-mean depth)
    yy, _xx = _local_mesh_coords(cfg, comm)
    rel_vort = (_e(v) - _i(v)) / dx - (_n(u) - _i(u)) / dy
    q_int = (_coriolis(cfg, yy)[1:-1, 1:-1] + rel_vort) / (
        0.25 * (_i(hc) + _e(hc) + _n(hc) + _ne(hc))
    )
    q = _set_interior(jnp.zeros_like(h), q_int)
    q, token = exchange(q, token=token)

    # momentum tendencies: pressure gradient + PV flux (Sadourny 1975)
    du_new = _set_interior(
        du,
        -g * (_e(h) - _i(h)) / dx
        + 0.5
        * (
            _i(q) * 0.5 * (_i(fn) + _e(fn))
            + _s(q) * 0.5 * (_s(fn) + fn[:-2, 2:])
        ),
    )
    dv_new = _set_interior(
        dv,
        -g * (_n(h) - _i(h)) / dy
        - 0.5
        * (
            _i(q) * 0.5 * (_i(fe) + _n(fe))
            + _w(q) * 0.5 * (_w(fe) + fe[2:, :-2])
        ),
    )

    # kinetic energy gradient
    ke = _set_interior(
        jnp.zeros_like(h),
        0.5 * (0.5 * (_i(u) ** 2 + _w(u) ** 2) + 0.5 * (_i(v) ** 2 + _s(v) ** 2)),
    )
    ke, token = exchange(ke, token=token)
    du_new = du_new.at[1:-1, 1:-1].add(-(_e(ke) - _i(ke)) / dx)
    dv_new = dv_new.at[1:-1, 1:-1].add(-(_n(ke) - _i(ke)) / dy)

    # time step: forward Euler bootstrap, then AB2 (reference :345-371)
    dt = jnp.asarray(cfg.dt, h.dtype)
    if first_step:
        u = u.at[1:-1, 1:-1].add(dt * _i(du_new))
        v = v.at[1:-1, 1:-1].add(dt * _i(dv_new))
        h = h.at[1:-1, 1:-1].add(dt * _i(dh_new))
    else:
        a, b = cfg.ab_a, cfg.ab_b
        u = u.at[1:-1, 1:-1].add(dt * (a * _i(du_new) + b * _i(du)))
        v = v.at[1:-1, 1:-1].add(dt * (a * _i(dv_new) + b * _i(dv)))
        h = h.at[1:-1, 1:-1].add(dt * (a * _i(dh_new) + b * _i(dh)))

    h, token = exchange(h, token=token)
    u, token = exchange(u, token=token)
    v, token = exchange(v, token=token)
    v = wall_v(v)

    # lateral friction (the reference's v-branch reads u in two stencils,
    # shallow_water.py:395-400 — reproduced here as v for correct physics;
    # flop/communication profile is identical)
    nu = cfg.lateral_viscosity
    if nu > 0:
        gx = _set_interior(jnp.zeros_like(u), nu * (_e(u) - _i(u)) / dx)
        gy = _set_interior(jnp.zeros_like(u), nu * (_n(u) - _i(u)) / dy)
        gx, token = exchange(gx, token=token)
        gy, token = exchange(gy, token=token)
        u = u.at[1:-1, 1:-1].add(
            dt * ((_i(gx) - _w(gx)) / dx + (_i(gy) - _s(gy)) / dy)
        )
        gx = _set_interior(jnp.zeros_like(v), nu * (_e(v) - _i(v)) / dx)
        gy = _set_interior(jnp.zeros_like(v), nu * (_n(v) - _i(v)) / dy)
        gx, token = exchange(gx, token=token)
        gy, token = exchange(gy, token=token)
        v = v.at[1:-1, 1:-1].add(
            dt * ((_i(gx) - _w(gx)) / dx + (_i(gy) - _s(gy)) / dy)
        )
        v = wall_v(v)

    return SWState(h, u, v, dh_new, du_new, dv_new), token


def _ring_view(a, r, dy=0, dx=0, *, G=2):
    """Ring-``r`` view of a ``(n + 2G)``-shaped block, shifted ``(dy, dx)``.

    Rows/cols within ``r`` rings of the interior, read at offset
    ``(dy, dx)`` — the wide-halo generalisation of the ``_i/_e/_w/_n/_s``
    helpers (those are the ``G=1, r=0`` cases).  Pure slicing: fuses into
    whatever consumes it.
    """
    y0 = G - r + dy
    x0 = G - r + dx
    return a[y0 : y0 + a.shape[0] - 2 * (G - r), x0 : x0 + a.shape[1] - 2 * (G - r)]


def _zero_wall_rows(a_r1, is_south, is_north, *, extra_north_interior=False):
    """Zero a ring-1 field's ghost rows on wall devices.

    Reproduces the narrow schedule exactly: intermediate fields are
    built on a zeros template and their wall-side ghost rows are never
    written by the (non-periodic) y exchange, so they are 0 there.
    ``extra_north_interior`` additionally zeroes the last interior row
    (the reference's ``wall_v`` on the northern flux, :401-402 there).
    """
    n = a_r1.shape[0]
    rows = lax.broadcasted_iota(jnp.int32, a_r1.shape, 0)
    kill = (is_south & (rows == 0)) | (is_north & (rows == n - 1))
    if extra_north_interior:
        kill = kill | (is_north & (rows == n - 2))
    return jnp.where(kill, jnp.zeros((), a_r1.dtype), a_r1)


def _step_wide(state, cfg, comm, *, first_step=False, token=None):
    """Wide-halo (ghost=2) step: communicate prognostic fields only.

    The narrow schedule exchanges every intermediate field because a
    1-cell ghost ring can't support compound stencils (~12 exchanges per
    step — the reference's structure, shallow_water.py:277-412). With a
    2-cell ring, the fluxes, potential vorticity, kinetic energy, and
    viscosity gradients are all *recomputed locally* one ring into the
    ghost region from the exchanged ``h``/``u``/``v``, so a step is:

        round 1: exchange h, u, v   → all tendencies, AB2 update
        round 2: exchange u, v      → viscosity, wall condition

    5 thin exchanges instead of 12 (and 2 ordering rounds instead of
    12, which is what matters at scale: SURVEY §3.4 — per-exchange
    dispatch/launch latency dominates the reference's scaling).
    Numerically identical to the narrow path up to FMA/fusion roundoff
    (asserted at ~ulp tolerance by
    tests/test_shallow_water.py::test_wide_equals_narrow): the ~1%
    redundant ghost-ring flops ride along with already-loaded data.

    Tendencies are stored interior-shaped (the ghost region of a
    tendency is never read).
    """
    G = 2
    if not cfg.periodic_x:
        raise NotImplementedError(
            "wide-halo schedule currently requires periodic_x=True "
            "(x-boundary clamps are not implemented); use ghost=1"
        )
    token = as_token(token)
    per = (False, True)
    ny_l, nx_l = cfg.local_interior(comm)
    is_north, is_south = _wall_masks(comm)
    dx, dy, g = cfg.dx, cfg.dy, cfg.gravity

    h, u, v, dh, du, dv = state
    dt = jnp.asarray(cfg.dt, h.dtype)
    V = _ring_view

    def wall_v_full(a):
        """v = 0 on the northern wall row (last interior row)."""
        return jnp.where(is_north, a.at[-(G + 1), :].set(0.0), a)

    # --- round 1: refresh prognostic ghosts (2-deep, corners valid) ---
    h, token = halo_exchange_2d(h, comm, periodic=per, token=token, width=G)
    u, token = halo_exchange_2d(u, comm, periodic=per, token=token, width=G)
    v, token = halo_exchange_2d(v, comm, periodic=per, token=token, width=G)

    # cell-centred height: narrow builds it by edge-padding the interior
    # and exchanging; here it is h with wall ghost rows clamped to the
    # adjacent interior row (interior + internal/periodic ghosts equal h)
    rows = lax.broadcasted_iota(jnp.int32, h.shape, 0)
    hc = jnp.where(is_south & (rows < G), h[G : G + 1, :], h)
    hc = jnp.where(
        is_north & (rows >= ny_l + G), h[ny_l + G - 1 : ny_l + G, :], hc
    )

    # --- ring-1 intermediates, all local ---
    fe = 0.5 * (V(hc, 1) + V(hc, 1, 0, 1)) * V(u, 1)
    fn = 0.5 * (V(hc, 1) + V(hc, 1, 1, 0)) * V(v, 1)
    fe = _zero_wall_rows(fe, is_south, is_north)
    fn = _zero_wall_rows(fn, is_south, is_north, extra_north_interior=True)

    dh_new = -(_i(fe) - _w(fe)) / dx - (_i(fn) - _s(fn)) / dy

    yy, _xx = _local_mesh_coords(cfg, comm)
    rel_vort = (V(v, 1, 0, 1) - V(v, 1)) / dx - (V(u, 1, 1, 0) - V(u, 1)) / dy
    q = (_coriolis(cfg, V(yy, 1)) + rel_vort) / (
        0.25 * (V(hc, 1) + V(hc, 1, 0, 1) + V(hc, 1, 1, 0) + V(hc, 1, 1, 1))
    )
    q = _zero_wall_rows(q, is_south, is_north)

    du_new = -g * (V(h, 0, 0, 1) - V(h, 0)) / dx + 0.5 * (
        _i(q) * 0.5 * (_i(fn) + _e(fn))
        + _s(q) * 0.5 * (_s(fn) + fn[:-2, 2:])
    )
    dv_new = -g * (V(h, 0, 1, 0) - V(h, 0)) / dy - 0.5 * (
        _i(q) * 0.5 * (_i(fe) + _n(fe))
        + _w(q) * 0.5 * (_w(fe) + fe[2:, :-2])
    )

    ke = 0.5 * (
        0.5 * (V(u, 1) ** 2 + V(u, 1, 0, -1) ** 2)
        + 0.5 * (V(v, 1) ** 2 + V(v, 1, -1, 0) ** 2)
    )
    ke = _zero_wall_rows(ke, is_south, is_north)
    du_new = du_new - (_e(ke) - _i(ke)) / dx
    dv_new = dv_new - (_n(ke) - _i(ke)) / dy

    # --- AB2 update (interior) ---
    if first_step:
        h = h.at[G:-G, G:-G].add(dt * dh_new)
        u = u.at[G:-G, G:-G].add(dt * du_new)
        v = v.at[G:-G, G:-G].add(dt * dv_new)
    else:
        a, b = cfg.ab_a, cfg.ab_b
        h = h.at[G:-G, G:-G].add(dt * (a * dh_new + b * dh))
        u = u.at[G:-G, G:-G].add(dt * (a * du_new + b * du))
        v = v.at[G:-G, G:-G].add(dt * (a * dv_new + b * dv))
    v = wall_v_full(v)

    # --- round 2: refresh u/v ghosts for the viscosity stencils ---
    nu = cfg.lateral_viscosity
    if nu > 0:
        u, token = halo_exchange_2d(u, comm, periodic=per, token=token, width=G)
        v, token = halo_exchange_2d(v, comm, periodic=per, token=token, width=G)
        gx = nu * (V(u, 1, 0, 1) - V(u, 1)) / dx
        gy = nu * (V(u, 1, 1, 0) - V(u, 1)) / dy
        gx = _zero_wall_rows(gx, is_south, is_north)
        gy = _zero_wall_rows(gy, is_south, is_north)
        u = u.at[G:-G, G:-G].add(
            dt * ((_i(gx) - _w(gx)) / dx + (_i(gy) - _s(gy)) / dy)
        )
        gx = nu * (V(v, 1, 0, 1) - V(v, 1)) / dx
        gy = nu * (V(v, 1, 1, 0) - V(v, 1)) / dy
        gx = _zero_wall_rows(gx, is_south, is_north)
        gy = _zero_wall_rows(gy, is_south, is_north)
        v = v.at[G:-G, G:-G].add(
            dt * ((_i(gx) - _w(gx)) / dx + (_i(gy) - _s(gy)) / dy)
        )
        v = wall_v_full(v)

    return SWState(h, u, v, dh_new, du_new, dv_new), token


def _step_wide4(state, cfg, comm, *, first_step=False, token=None):
    """Single-exchange (ghost=4) step: one batched halo round per step.

    Extends the wide-halo recompute (:func:`_step_wide`) so the whole
    step — including the post-update viscosity, which in the reference
    reads *updated* velocities with refreshed ghosts
    (shallow_water.py:384-400 there) — is local after a single 4-deep
    batched exchange of ``(h, u, v)``:

        exchange h,u,v (width 4, one ppermute per direction for all 3)
        ring-3: fluxes, potential vorticity, kinetic energy
        ring-2: tendencies, AB2 update of h/u/v
        ring-1: viscosity gradients of the *locally updated* u/v
        interior: viscosity divergence

    Tendencies are stored full-shape, valid on ring-2, and are never
    communicated: each step recomputes them on ring-2 from the freshly
    exchanged prognostics, so validity is maintained inductively.
    On dispatch-latency-bound runtimes this schedule's win is op count:
    4 permutes + 1 round per step vs the narrow schedule's ~48 permutes
    in 12 rounds.  Numerically identical to the other schedules
    (tests/test_shallow_water.py::test_wide4_equals_narrow).
    """
    G = 4
    if not cfg.periodic_x:
        raise NotImplementedError(
            "single-exchange schedule requires periodic_x=True; use ghost=1"
        )
    ny_l, nx_l = cfg.local_interior(comm)
    if ny_l < G or nx_l < G:
        raise ValueError(
            f"ghost=4 needs local blocks >= 4x4, got {ny_l}x{nx_l}"
        )
    token = as_token(token)
    is_north, is_south = _wall_masks(comm)
    dx, dy, g = cfg.dx, cfg.dy, cfg.gravity

    h, u, v, dh, du, dv = state
    dt = jnp.asarray(cfg.dt, h.dtype)

    # --- the step's only exchange round ---
    (h, u, v), token = halo_exchange_2d_batch(
        [h, u, v], comm, periodic=(False, True), token=token, width=G
    )

    rows = lax.broadcasted_iota(jnp.int32, h.shape, 0)
    # cell-centred height: wall ghost rows clamped (edge-pad semantics)
    hc = jnp.where(is_south & (rows < G), h[G : G + 1, :], h)
    hc = jnp.where(
        is_north & (rows >= ny_l + G), h[ny_l + G - 1 : ny_l + G, :], hc
    )

    V = _ring_view

    def grow(shape, ring):
        """Global array-row index of each element of a ring-r field."""
        return (G - ring) + lax.broadcasted_iota(jnp.int32, shape, 0)

    def zero_wall(a, ring, extra_north_interior=False):
        gr = grow(a.shape, ring)
        kill = (is_south & (gr < G)) | (is_north & (gr >= ny_l + G))
        if extra_north_interior:
            kill = kill | (is_north & (gr == ny_l + G - 1))
        return jnp.where(kill, jnp.zeros((), a.dtype), a)

    # --- ring-3 intermediates, all local ---
    fe = 0.5 * (V(hc, 3, G=G) + V(hc, 3, 0, 1, G=G)) * V(u, 3, G=G)
    fn = 0.5 * (V(hc, 3, G=G) + V(hc, 3, 1, 0, G=G)) * V(v, 3, G=G)
    fe = zero_wall(fe, 3)
    fn = zero_wall(fn, 3, extra_north_interior=True)

    yy, _xx = _local_mesh_coords(cfg, comm)
    rel_vort = (V(v, 3, 0, 1, G=G) - V(v, 3, G=G)) / dx - (
        V(u, 3, 1, 0, G=G) - V(u, 3, G=G)
    ) / dy
    q = (_coriolis(cfg, V(yy, 3, G=G)) + rel_vort) / (
        0.25
        * (
            V(hc, 3, G=G)
            + V(hc, 3, 0, 1, G=G)
            + V(hc, 3, 1, 0, G=G)
            + V(hc, 3, 1, 1, G=G)
        )
    )
    q = zero_wall(q, 3)

    ke = 0.5 * (
        0.5 * (V(u, 3, G=G) ** 2 + V(u, 3, 0, -1, G=G) ** 2)
        + 0.5 * (V(v, 3, G=G) ** 2 + V(v, 3, -1, 0, G=G) ** 2)
    )
    ke = zero_wall(ke, 3)

    # --- ring-2 tendencies (ring-2 views of the ring-3 fields) ---
    def R2(a, dyr=0, dxr=0):
        return _ring_view(a, 2, dyr, dxr, G=3)

    dh_new = -(R2(fe) - R2(fe, 0, -1)) / dx - (R2(fn) - R2(fn, -1, 0)) / dy
    du_new = -g * (V(h, 2, 0, 1, G=G) - V(h, 2, G=G)) / dx + 0.5 * (
        R2(q) * 0.5 * (R2(fn) + R2(fn, 0, 1))
        + R2(q, -1, 0) * 0.5 * (R2(fn, -1, 0) + R2(fn, -1, 1))
    )
    dv_new = -g * (V(h, 2, 1, 0, G=G) - V(h, 2, G=G)) / dy - 0.5 * (
        R2(q) * 0.5 * (R2(fe) + R2(fe, 1, 0))
        + R2(q, 0, -1) * 0.5 * (R2(fe, 0, -1) + R2(fe, 1, -1))
    )
    du_new = du_new - (R2(ke, 0, 1) - R2(ke)) / dx
    dv_new = dv_new - (R2(ke, 1, 0) - R2(ke)) / dy

    # --- AB2 update on ring-2 (wall devices freeze beyond-wall rows) ---
    def R2full(a):
        return _ring_view(a, 2, G=G)

    if first_step:
        h2 = R2full(h) + dt * dh_new
        u2 = R2full(u) + dt * du_new
        v2 = R2full(v) + dt * dv_new
    else:
        a_, b_ = cfg.ab_a, cfg.ab_b
        h2 = R2full(h) + dt * (a_ * dh_new + b_ * R2full(dh))
        u2 = R2full(u) + dt * (a_ * du_new + b_ * R2full(du))
        v2 = R2full(v) + dt * (a_ * dv_new + b_ * R2full(dv))

    gr2 = grow(h2.shape, 2)
    frozen = (is_south & (gr2 < G)) | (is_north & (gr2 >= ny_l + G))
    h2 = jnp.where(frozen, R2full(h), h2)
    u2 = jnp.where(frozen, R2full(u), u2)
    v2 = jnp.where(frozen, R2full(v), v2)
    # v = 0 on the northern wall row (last interior row)
    wall_row = is_north & (gr2 == ny_l + G - 1)
    v2 = jnp.where(wall_row, jnp.zeros((), v2.dtype), v2)

    # --- viscosity on the locally recomputed ring-2 velocities ---
    nu = cfg.lateral_viscosity
    if nu > 0:

        def visc_div(w2):
            gx = nu * (V(w2, 1, 0, 1, G=2) - V(w2, 1, G=2)) / dx
            gy = nu * (V(w2, 1, 1, 0, G=2) - V(w2, 1, G=2)) / dy
            gx = zero_wall(gx, 1)
            gy = zero_wall(gy, 1)
            return (V(gx, 0, G=1) - V(gx, 0, 0, -1, G=1)) / dx + (
                V(gy, 0, G=1) - V(gy, 0, -1, 0, G=1)
            ) / dy

        u2 = u2 + jnp.pad(dt * visc_div(u2), 2)
        v2 = v2 + jnp.pad(dt * visc_div(v2), 2)
        v2 = jnp.where(wall_row, jnp.zeros((), v2.dtype), v2)

    # --- one store per field ---
    h = h.at[2:-2, 2:-2].set(h2)
    u = u.at[2:-2, 2:-2].set(u2)
    v = v.at[2:-2, 2:-2].set(v2)
    dh = dh.at[2:-2, 2:-2].set(dh_new)
    du = du.at[2:-2, 2:-2].set(du_new)
    dv = dv.at[2:-2, 2:-2].set(dv_new)

    return SWState(h, u, v, dh, du, dv), token


def _mesh_specs(comm):
    spec = jax.P(*comm.axes)
    return SWState(*([spec] * 6))


def make_multistep(cfg, comm, num_steps, *, donate=False):
    """Jitted global function advancing the model ``num_steps`` steps —
    the reference's ``do_multistep`` (shallow_water.py:415-420): the whole
    loop is one XLA executable.

    ``donate=True`` donates the input state's buffers (in-place update;
    the passed-in state is consumed).  Saves one full state copy per
    call — use it for ``state = multi(state)``-style driver loops.
    """

    def local_fn(state):
        def body(_, s):
            s, _tok = shallow_water_step(s, cfg, comm)
            return s

        return lax.fori_loop(0, num_steps, body, state)

    specs = _mesh_specs(comm)
    return jax.jit(
        jax.shard_map(
            local_fn, mesh=comm.mesh, in_specs=(specs,), out_specs=specs
        ),
        donate_argnums=(0,) if donate else (),
    )


def make_init(cfg, comm):
    """Jitted global initial-condition builder (returns sharded SWState)."""

    def local_fn():
        state, _tok = initial_state(cfg, comm)
        return state

    specs = _mesh_specs(comm)
    return jax.jit(
        jax.shard_map(local_fn, mesh=comm.mesh, in_specs=(), out_specs=specs)
    )


def make_first_step(cfg, comm):
    def local_fn(state):
        state, _tok = shallow_water_step(state, cfg, comm, first_step=True)
        return state

    specs = _mesh_specs(comm)
    return jax.jit(
        jax.shard_map(local_fn, mesh=comm.mesh, in_specs=(specs,), out_specs=specs)
    )


def make_solver(
    cfg,
    comm,
    num_multisteps=10,
    on_chunk=None,
    checkpoint_dir=None,
    checkpoint_every=1,
):
    """Full driver: init → bootstrap step → repeated jitted multisteps.

    Returns ``solve(t1_seconds) -> (state, wall_seconds, n_steps)`` where
    wall time covers only the post-compile hot loop, matching the
    reference's benchmark methodology (shallow_water.py:450-470).

    ``on_chunk(state, t_seconds)``, if given, is called after every
    multistep chunk (including the warm-up one) — e.g. to collect
    animation frames, as the reference's plotting loop does
    (shallow_water.py:586-599 there).  Callback time is included in the
    wall clock, so don't combine with benchmark timing.

    ``checkpoint_dir`` enables resumable runs (SURVEY §5.4 — absent in
    the reference): every ``checkpoint_every`` chunks the sharded state
    and model time are saved via :mod:`mpi4jax_tpu.utils.checkpoint`,
    and a fresh ``solve`` in the same directory resumes from the latest
    checkpoint instead of re-initialising.  Save time is included in
    the wall clock — don't combine with benchmark timing either.
    """
    import time

    init = make_init(cfg, comm)
    first = make_first_step(cfg, comm)
    multi = make_multistep(cfg, comm, num_multisteps)

    from mpi4jax_tpu.utils.runtime import drain

    def sync(state):
        return drain(state.h)

    def solve(t1):
        mgr = None
        if checkpoint_dir is not None:
            from mpi4jax_tpu.utils import checkpoint as _ckpt

            mgr = _ckpt.Manager(checkpoint_dir)
        try:
            latest = mgr.latest_step() if mgr is not None else None
            step_fn = multi
            if latest is not None:
                # resume: restore against an ABSTRACT template (shapes
                # from eval_shape + the solver's shardings) — no init /
                # warm-up compute is spent on state that is about to be
                # replaced.  AOT-compile the multistep so the timed loop
                # still excludes compilation.
                chunk = latest
                resumed = True
                specs = _mesh_specs(comm)
                abstract = jax.tree.map(
                    lambda s, sp: jax.ShapeDtypeStruct(
                        s.shape,
                        s.dtype,
                        sharding=jax.NamedSharding(comm.mesh, sp),
                    ),
                    jax.eval_shape(init),
                    specs,
                )
                restored = mgr.restore(
                    chunk, like={"state": abstract, "t": np.float64(0.0)}
                )
                state = SWState(*restored["state"])
                t = float(restored["t"])
                step_fn = multi.lower(state).compile()
            else:
                chunk = 0
                resumed = False
                state = init()
                state = first(state)
                t = cfg.dt
                # warm-up compile (excluded from timing, as in the
                # reference)
                state = multi(state)
                t += cfg.dt * num_multisteps
            sync(state)
            if on_chunk is not None:
                on_chunk(state, t)
            steps = 0
            start = time.perf_counter()
            # always time at least one multistep on a FRESH run, even if
            # the warm-up call already advanced past t1 (short runs /
            # large chunks).  A resumed run must not: rerunning a
            # completed run in the same directory would otherwise push
            # the trajectory past t1 and save checkpoints beyond it.
            while t < t1 or (steps == 0 and not resumed):
                state = step_fn(state)
                t += cfg.dt * num_multisteps
                steps += num_multisteps
                chunk += 1
                if on_chunk is not None:
                    on_chunk(state, t)
                if mgr is not None:
                    mgr.maybe_save(
                        chunk,
                        {"state": state, "t": np.float64(t)},
                        every=checkpoint_every,
                    )
            sync(state)
            wall = time.perf_counter() - start
            return state, wall, steps
        finally:
            if mgr is not None:
                mgr.close()

    return solve


def gather_global(local_field, comm, *, ghost=1):
    """Reassemble a global interior field from per-device blocks (the
    reference gathers to rank 0 for plotting, shallow_water.py:586-593).

    Must be called inside shard_map; returns the (ny, nx) global array
    (replicated logical value, device-varying layout).
    """
    G = ghost
    blocks, _ = allgather(local_field[G:-G, G:-G], comm=comm)
    py, px = comm.axis_sizes
    ny_l, nx_l = local_field.shape[0] - 2 * G, local_field.shape[1] - 2 * G
    grid = blocks.reshape(py, px, ny_l, nx_l)
    return grid.transpose(0, 2, 1, 3).reshape(py * ny_l, px * nx_l)


# -- t4j-lint entries: the model's own communication schedule, one per
# ghost-width schedule variant (1 = reference layout, 2 = wide-halo,
# 4 = single-exchange) — the three schedules differ in exchange
# structure and each must stay contract-clean.


def _lint_step(ghost):
    def thunk():
        import jax

        from mpi4jax_tpu.parallel.comm import MeshComm

        mesh = jax.make_mesh(
            (2, 4), ("y", "x"),
            axis_types=(jax.sharding.AxisType.Auto,) * 2,
        )
        comm = MeshComm.from_mesh(mesh)
        cfg = SWConfig(ny=8, nx=16, ghost=ghost)
        return make_multistep(cfg, comm, num_steps=1)(
            make_init(cfg, comm)()
        )

    thunk.__name__ = f"step_ghost{ghost}"
    return thunk


T4J_LINT_ENTRIES = [_lint_step(g) for g in (1, 2, 4)]
