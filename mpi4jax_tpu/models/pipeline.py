"""Pipeline parallelism built from the communication primitives.

The reference names the ring step (`sendrecv` to rank±1) as its "PP
building block" and prescribes "PP microbatch loops in `lax.scan`"
(SURVEY §2.4).  This module delivers that block as a working schedule:
a GPipe-style pipeline where each rank of a ``pp`` communicator owns
one stage, activations hand off along the chain via :func:`sendrecv`
(one `ppermute` per tick on ICI), and the microbatch loop is a single
``lax.scan`` — so the whole pipeline, bubbles and all, is one XLA
executable.  Reverse-mode differentiation works end to end: the
transpose of the forward handoff is the backward handoff in the
opposite direction (the reference's sendrecv transpose contract,
sendrecv.py:366-385).

Schedule: with S stages and M microbatches, the scan runs T = M + S - 1
ticks.  At tick t, stage s computes microbatch (t - s) when that index
is valid; invalid (bubble) slots compute on zeros and are masked out.
"""

import jax
import jax.numpy as jnp
from jax import lax

from mpi4jax_tpu.ops._core import as_token, promote_vma, vma_of
from mpi4jax_tpu.ops.p2p import sendrecv

__all__ = ["pipeline_apply"]


def pipeline_apply(stage_fn, stage_params, microbatches, comm, *, token=None):
    """Run a stage-sharded function as a pipeline over ``comm``.

    Must be called inside the ``shard_map`` that shards stages over the
    (single-axis) ``comm``.

    Args:
      stage_fn: ``(params, activation) -> activation`` — this rank's
        stage (uniform signature across stages; rank-dependent behaviour
        belongs in ``stage_params``).
      stage_params: this rank's stage parameters.
      microbatches: ``(M, mb, ...)`` — the input microbatches. Only
        stage 0 reads them; other ranks pass the same-shaped array
        (contents ignored) so the SPMD program is uniform.
      comm: single-axis MeshComm; rank = stage index.
      token: optional ordering token.

    Returns:
      ``(outputs, token)`` where ``outputs`` is ``(M, mb, ...)`` holding
      the final-stage results on the **last** rank (other ranks hold
      zeros — gather/bcast explicitly if every rank needs them,
      mirroring the reference's rooted-output convention).
    """
    token = as_token(token)
    if len(comm.axes) != 1:
        raise ValueError("pipeline_apply needs a single-axis communicator")
    n_stages = comm.size
    n_micro = microbatches.shape[0]
    rank = comm.rank()
    mb_shape = microbatches.shape[1:]

    fwd = [(r, r + 1) for r in range(n_stages - 1)]  # stage r -> r+1

    # probe the activation shape/dtype: stage outputs must be uniform
    # (pipeline handoff needs a static wire shape)
    out_shape = jax.eval_shape(
        stage_fn, stage_params, jax.ShapeDtypeStruct(
            mb_shape, microbatches.dtype
        )
    )
    if out_shape.shape != mb_shape or out_shape.dtype != microbatches.dtype:
        raise ValueError(
            "pipeline_apply requires shape/dtype-preserving stages (the "
            "handoff wire doubles as the next stage's input): stage_fn "
            f"maps {mb_shape}/{microbatches.dtype} -> "
            f"{out_shape.shape}/{out_shape.dtype}"
        )

    def tick(carry, t):
        incoming, outputs, token = carry
        # stage 0 feeds itself from the microbatch buffer; other stages
        # use the activation handed off at the previous tick
        mb_idx = t - rank
        valid = (mb_idx >= 0) & (mb_idx < n_micro)
        safe_idx = jnp.clip(mb_idx, 0, n_micro - 1)
        x0 = lax.dynamic_index_in_dim(
            microbatches, safe_idx, keepdims=False
        ).astype(incoming.dtype)
        a_in = jnp.where(rank == 0, x0, incoming)
        a_out = stage_fn(stage_params, a_in)
        a_out = jnp.where(valid, a_out, jnp.zeros_like(a_out))
        # last stage banks its result; everyone ships downstream
        is_last = rank == n_stages - 1
        outputs = lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(
                valid & is_last,
                a_out,
                lax.dynamic_index_in_dim(outputs, safe_idx, keepdims=False),
            ),
            safe_idx,
            0,
        )
        if fwd:
            incoming, token = sendrecv(
                a_out,
                jnp.zeros_like(a_out),
                source=fwd,
                dest=fwd,
                comm=comm,
                token=token,
            )
        else:
            incoming = a_out
        return (incoming, outputs, token), None

    # the carries become device-varying after the first handoff; start
    # them varying so the scan carry type is stable.  The activations
    # also inherit any varying axes the inputs/params carry from an
    # enclosing mesh (e.g. a dp axis sharding the microbatches), so the
    # carry axes are the union.
    carry_axes = list(comm.axes)
    for leaf in jax.tree.leaves((microbatches, stage_params)):
        for ax in vma_of(leaf) or ():
            if ax not in carry_axes:
                carry_axes.append(ax)
    carry_axes = tuple(carry_axes)

    incoming0 = promote_vma(
        jnp.zeros(out_shape.shape, out_shape.dtype), carry_axes
    )
    outputs0 = promote_vma(
        jnp.zeros((n_micro, *out_shape.shape), out_shape.dtype), carry_axes
    )
    token = token.with_stamp(promote_vma(token.stamp, carry_axes))
    (_, outputs, token), _ = lax.scan(
        tick,
        (incoming0, outputs0, token),
        jnp.arange(n_micro + n_stages - 1),
    )
    return outputs, token
