"""Pipeline parallelism built from the communication primitives.

The reference names the ring step (`sendrecv` to rank±1) as its "PP
building block" and prescribes "PP microbatch loops in `lax.scan`"
(SURVEY §2.4).  This module delivers that block as two working
schedules, both running the microbatch loop as a single ``lax.scan``
(the whole pipeline, bubbles and all, is one XLA executable) with
activations handed off along the chain via :func:`sendrecv` (one ICI
``ppermute`` per tick):

* **GPipe** (:func:`pipeline_apply`): forward-only schedule;
  reverse-mode AD transposes the scan, so the executed program is
  all-forwards-then-all-backwards and the scan residuals stash every
  microbatch's activations (O(M) memory).  The transpose of the
  forward handoff is the backward handoff in the opposite direction
  (the reference's sendrecv transpose contract, sendrecv.py:366-385).
* **1F1B** (:func:`pipeline_train`): the production schedule — each
  steady-state tick runs one forward AND one backward microbatch per
  stage, cotangents flowing upstream on a second ``sendrecv`` wire.
  The backward is built manually (per-stage ``jax.vjp`` with
  forward recompute, i.e. remat), so in-flight activations are bounded
  by the ring stash of ``min(M, 2S-1)`` microbatch *inputs* instead of
  GPipe's M× per-layer residuals.

GPipe tick math: T = M + S - 1 ticks; at tick t, stage s computes
microbatch (t - s) when valid.  1F1B tick math: T = M + 2(S-1) ticks;
at tick t stage s forwards microbatch ``t - s`` and backwards
microbatch ``t - (2(S-1) - s)`` (the last stage backwards a microbatch
in the same tick it forwards it — the loss cotangent is local).
Invalid (bubble) slots compute on stashed/zero data and are masked out.
"""

import jax
import jax.numpy as jnp
from jax import lax

from mpi4jax_tpu.ops._core import as_token, promote_vma, vma_of
from mpi4jax_tpu.ops.p2p import sendrecv

__all__ = ["pipeline_apply", "pipeline_train"]


def pipeline_apply(stage_fn, stage_params, microbatches, comm, *, token=None):
    """Run a stage-sharded function as a pipeline over ``comm``.

    Must be called inside the ``shard_map`` that shards stages over the
    (single-axis) ``comm``.

    Args:
      stage_fn: ``(params, activation) -> activation`` — this rank's
        stage (uniform signature across stages; rank-dependent behaviour
        belongs in ``stage_params``).
      stage_params: this rank's stage parameters.
      microbatches: ``(M, mb, ...)`` — the input microbatches. Only
        stage 0 reads them; other ranks pass the same-shaped array
        (contents ignored) so the SPMD program is uniform.
      comm: single-axis MeshComm; rank = stage index.
      token: optional ordering token.

    Returns:
      ``(outputs, token)`` where ``outputs`` is ``(M, mb, ...)`` holding
      the final-stage results on the **last** rank (other ranks hold
      zeros — gather/bcast explicitly if every rank needs them,
      mirroring the reference's rooted-output convention).
    """
    token = as_token(token)
    if len(comm.axes) != 1:
        raise ValueError("pipeline_apply needs a single-axis communicator")
    n_stages = comm.size
    n_micro = microbatches.shape[0]
    rank = comm.rank()
    mb_shape = microbatches.shape[1:]

    fwd = [(r, r + 1) for r in range(n_stages - 1)]  # stage r -> r+1

    # probe the activation shape/dtype: stage outputs must be uniform
    # (pipeline handoff needs a static wire shape)
    out_shape = jax.eval_shape(
        stage_fn, stage_params, jax.ShapeDtypeStruct(
            mb_shape, microbatches.dtype
        )
    )
    if out_shape.shape != mb_shape or out_shape.dtype != microbatches.dtype:
        raise ValueError(
            "pipeline_apply requires shape/dtype-preserving stages (the "
            "handoff wire doubles as the next stage's input): stage_fn "
            f"maps {mb_shape}/{microbatches.dtype} -> "
            f"{out_shape.shape}/{out_shape.dtype}"
        )

    def tick(carry, t):
        incoming, outputs, token = carry
        # stage 0 feeds itself from the microbatch buffer; other stages
        # use the activation handed off at the previous tick
        mb_idx = t - rank
        valid = (mb_idx >= 0) & (mb_idx < n_micro)
        safe_idx = jnp.clip(mb_idx, 0, n_micro - 1)
        x0 = lax.dynamic_index_in_dim(
            microbatches, safe_idx, keepdims=False
        ).astype(incoming.dtype)
        a_in = jnp.where(rank == 0, x0, incoming)
        a_out = stage_fn(stage_params, a_in)
        a_out = jnp.where(valid, a_out, jnp.zeros_like(a_out))
        # last stage banks its result; everyone ships downstream
        is_last = rank == n_stages - 1
        outputs = lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(
                valid & is_last,
                a_out,
                lax.dynamic_index_in_dim(outputs, safe_idx, keepdims=False),
            ),
            safe_idx,
            0,
        )
        if fwd:
            incoming, token = sendrecv(
                a_out,
                jnp.zeros_like(a_out),
                source=fwd,
                dest=fwd,
                comm=comm,
                token=token,
            )
        else:
            incoming = a_out
        return (incoming, outputs, token), None

    # the carries become device-varying after the first handoff; start
    # them varying so the scan carry type is stable.  The activations
    # also inherit any varying axes the inputs/params carry from an
    # enclosing mesh (e.g. a dp axis sharding the microbatches), so the
    # carry axes are the union.
    carry_axes = list(comm.axes)
    for leaf in jax.tree.leaves((microbatches, stage_params)):
        for ax in vma_of(leaf) or ():
            if ax not in carry_axes:
                carry_axes.append(ax)
    carry_axes = tuple(carry_axes)

    incoming0 = promote_vma(
        jnp.zeros(out_shape.shape, out_shape.dtype), carry_axes
    )
    outputs0 = promote_vma(
        jnp.zeros((n_micro, *out_shape.shape), out_shape.dtype), carry_axes
    )
    token = token.with_stamp(promote_vma(token.stamp, carry_axes))
    (_, outputs, token), _ = lax.scan(
        tick,
        (incoming0, outputs0, token),
        jnp.arange(n_micro + n_stages - 1),
    )
    return outputs, token


def _carry_axes_for(comm, *trees):
    """Union of the comm's axes and any varying axes the inputs carry
    from an enclosing mesh (shared by both schedules)."""
    axes = list(comm.axes)
    for leaf in jax.tree.leaves(trees):
        for ax in vma_of(leaf) or ():
            if ax not in axes:
                axes.append(ax)
    return tuple(axes)


def pipeline_train(
    stage_fn, stage_params, head_fn, head_params, microbatches, extras,
    comm, *, token=None,
):
    """1F1B pipeline schedule with a manually built backward.

    The production schedule (Megatron/PipeDream-flush): after warmup,
    every tick runs one forward AND one backward microbatch per stage,
    so at most ``2S-1`` microbatch inputs are in flight per stage —
    GPipe (``jax.grad`` over :func:`pipeline_apply`) stashes all ``M``
    microbatches' per-layer residuals instead.  The backward recomputes
    each stage's forward from the stashed input (``jax.vjp``), i.e.
    rematerialisation is built into the schedule.

    Tick math (S stages, M microbatches, T = M + 2(S-1) ticks): stage
    ``s`` forwards microbatch ``t - s`` and backwards microbatch
    ``t - (2(S-1) - s)``.  The last stage backwards a microbatch in the
    tick it forwards it (the loss cotangent is local); cotangents for
    earlier stages ride an upstream ``sendrecv`` wire, one tick behind
    the downstream stage's backward — the explicit form of the
    reference's "gradients travel the reverse network direction"
    contract (sendrecv.py:366-385).

    Args:
      stage_fn: ``(stage_params, a) -> a`` shape/dtype-preserving stage.
      stage_params: this rank's stage parameters (pp-sharded pytree).
      head_fn: ``(head_params, a, extra) -> scalar`` per-microbatch loss
        head, applied to the LAST stage's output (other ranks compute it
        masked — the SPMD program is uniform).
      head_params: loss-head parameters (replicated pytree).
      microbatches: ``(M, mb, ...)`` inputs; only stage 0 reads them.
      extras: ``(M, ...)`` pytree of per-microbatch loss inputs (e.g.
        targets), indexed at the last stage.
      comm: single-axis MeshComm; rank = stage index.

    Returns ``(loss_sum, d_stage_params, d_head_params, d_microbatches,
    token)``: the SUM over microbatches of the per-microbatch losses and
    its gradients (divide by M for the mean).  ``d_head_params`` is
    nonzero only on the last stage and ``d_microbatches`` only on stage
    0 — psum over the pp axis (which shard_map does automatically for
    replicated outputs) adds zeros from the other stages.
    """
    token = as_token(token)
    if len(comm.axes) != 1:
        raise ValueError("pipeline_train needs a single-axis communicator")
    n_stages = comm.size
    n_micro = microbatches.shape[0]
    rank = comm.rank()
    mb_shape = microbatches.shape[1:]
    dtype = microbatches.dtype

    fwd = [(r, r + 1) for r in range(n_stages - 1)]  # activations s -> s+1
    bwd = [(r + 1, r) for r in range(n_stages - 1)]  # cotangents s+1 -> s

    out_sd = jax.eval_shape(
        stage_fn, stage_params, jax.ShapeDtypeStruct(mb_shape, dtype)
    )
    if out_sd.shape != mb_shape or out_sd.dtype != dtype:
        raise ValueError(
            "pipeline_train requires shape/dtype-preserving stages, got "
            f"{mb_shape}/{dtype} -> {out_sd.shape}/{out_sd.dtype}"
        )

    stash_k = min(n_micro, 2 * n_stages - 1)
    is_first = rank == 0
    is_last = rank == n_stages - 1
    lag = 2 * (n_stages - 1)  # bwd of mb i at stage s runs at i + lag - s

    carry_axes = _carry_axes_for(
        comm, microbatches, extras, stage_params, head_params
    )
    # Both param trees must be DEVICE-VARYING before the per-tick vjps:
    # differentiating wrt an unvarying (replicated-over-some-axis) input
    # makes jax's replication rule psum the cotangent across that axis —
    # which would mix every stage's head-vjp of its *mid-pipeline*
    # activations into the last stage's gradient, and silently pre-sum
    # stage grads over any enclosing data-parallel axis the caller then
    # double-counts.  Varying params keep every vjp local: ALL returned
    # gradients are strictly per-device, and the caller owns every
    # cross-device reduction (psum over pp adds zeros from the masked
    # stages; psum over dp sums the groups).
    head_params, stage_params = jax.tree.map(
        lambda x: promote_vma(jnp.asarray(x), carry_axes),
        (head_params, stage_params),
    )

    def tick(carry, t):
        (incoming_a, incoming_g, stash, loss_acc, d_stage, d_head,
         d_mbs, token) = carry

        # ---- forward slot: microbatch f = t - rank
        f_idx = t - rank
        f_valid = (f_idx >= 0) & (f_idx < n_micro)
        f_safe = jnp.clip(f_idx, 0, n_micro - 1)
        x0 = lax.dynamic_index_in_dim(
            microbatches, f_safe, keepdims=False
        ).astype(dtype)
        a_in = jnp.where(is_first, x0, incoming_a)
        a_out = stage_fn(stage_params, a_in)
        a_out = jnp.where(f_valid, a_out, jnp.zeros_like(a_out))
        # masked write: during drain, invalid fwd slots must not clobber
        # the stash entry a still-pending backward will read
        stash_slot = f_safe % stash_k
        prev_entry = lax.dynamic_index_in_dim(
            stash, stash_slot, keepdims=False
        )
        stash = lax.dynamic_update_index_in_dim(
            stash, jnp.where(f_valid, a_in, prev_entry), stash_slot, 0
        )

        # loss head on this tick's forward (meaningful on the last
        # stage; the cotangent seeds the SAME tick's backward there)
        extra_f = jax.tree.map(
            lambda e: lax.dynamic_index_in_dim(e, f_safe, keepdims=False),
            extras,
        )
        loss_mb, head_vjp = jax.vjp(head_fn, head_params, a_out, extra_f)
        seed = promote_vma(
            jnp.ones((), loss_mb.dtype), vma_of(loss_mb) or ()
        )
        d_head_mb, g_self, _ = head_vjp(seed)
        take_loss = f_valid & is_last
        loss_acc = loss_acc + jnp.where(take_loss, loss_mb, 0.0)
        d_head = jax.tree.map(
            lambda acc, g: acc + jnp.where(take_loss, g, jnp.zeros_like(g)),
            d_head, d_head_mb,
        )

        # ---- backward slot: microbatch b = t - (lag - rank)
        b_idx = t - (lag - rank)
        b_valid = (b_idx >= 0) & (b_idx < n_micro)
        b_safe = jnp.clip(b_idx, 0, n_micro - 1)
        a_stash = lax.dynamic_index_in_dim(
            stash, b_safe % stash_k, keepdims=False
        )
        # remat: rebuild this stage's vjp at the stashed input
        _, stage_vjp = jax.vjp(stage_fn, stage_params, a_stash)
        g_out = jnp.where(is_last, g_self, incoming_g)
        d_stage_mb, d_a_in = stage_vjp(g_out.astype(out_sd.dtype))
        d_stage = jax.tree.map(
            lambda acc, g: acc + jnp.where(b_valid, g, jnp.zeros_like(g)),
            d_stage, d_stage_mb,
        )
        d_a_in = jnp.where(b_valid, d_a_in, jnp.zeros_like(d_a_in))
        d_mbs = lax.dynamic_update_index_in_dim(
            d_mbs,
            jnp.where(
                b_valid & is_first,
                d_a_in,
                lax.dynamic_index_in_dim(d_mbs, b_safe, keepdims=False),
            ),
            b_safe,
            0,
        )

        # ---- wires: activations downstream, cotangents upstream
        if fwd:
            incoming_a, token = sendrecv(
                a_out, jnp.zeros_like(a_out), source=fwd, dest=fwd,
                comm=comm, token=token,
            )
            incoming_g, token = sendrecv(
                d_a_in, jnp.zeros_like(d_a_in), source=bwd, dest=bwd,
                comm=comm, token=token,
            )
        else:
            incoming_a, incoming_g = a_out, d_a_in
        return (
            (incoming_a, incoming_g, stash, loss_acc, d_stage, d_head,
             d_mbs, token),
            None,
        )

    def dev0(x):
        return promote_vma(jnp.zeros(x.shape, x.dtype), carry_axes)

    carry0 = (
        dev0(jax.ShapeDtypeStruct(mb_shape, dtype)),           # incoming_a
        dev0(jax.ShapeDtypeStruct(mb_shape, out_sd.dtype)),    # incoming_g
        dev0(jax.ShapeDtypeStruct((stash_k, *mb_shape), dtype)),  # stash
        promote_vma(jnp.zeros((), jnp.float32), carry_axes),   # loss_acc
        jax.tree.map(dev0, jax.eval_shape(lambda p: p, stage_params)),
        jax.tree.map(dev0, jax.eval_shape(lambda p: p, head_params)),
        dev0(jax.ShapeDtypeStruct((n_micro, *mb_shape), out_sd.dtype)),
        token.with_stamp(promote_vma(token.stamp, carry_axes)),
    )
    (_, _, _, loss_sum, d_stage, d_head, d_mbs, token), _ = lax.scan(
        tick, carry0, jnp.arange(n_micro + lag)
    )
    return loss_sum, d_stage, d_head, d_mbs, token
