"""Pipeline-parallel decoder transformer over a ``(dp, pp)`` mesh.

Completes the transformer-parallelism matrix: models/transformer.py
composes dp×tp×sp (Megatron + ring attention), moe_transformer.py adds
ep — this module runs the *same* decoder (identical parameters and
math: :func:`~mpi4jax_tpu.models.transformer.init_params` /
``reference_loss`` are reused verbatim) with its **layers sharded into
pipeline stages** over ``pp``, scheduled by
:func:`~mpi4jax_tpu.models.pipeline.pipeline_apply` — the GPipe
microbatch loop in one ``lax.scan``, activations handed off by
``sendrecv`` (one ICI ``ppermute`` per tick), gradients riding the
reversed handoff (the reference's sendrecv transpose contract,
sendrecv.py:366-385 there).

Gradient flow is the interesting part: each device differentiates its
*locally masked* loss (nonzero only on the last stage), and the
cotangents for earlier stages' layers arrive **through the transposed
pipeline** — there is no explicit cross-stage gradient collective to
get wrong.  Replicated params (embedding, final head) contribute from
exactly one stage each (the ``rank == 0`` feed and the last-stage
readout), so shard_map's automatic pp-psum of their cotangents adds
zeros from the other stages — no overcount, no extra scaling.
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from mpi4jax_tpu.models.pipeline import pipeline_apply, pipeline_train
from mpi4jax_tpu.models.transformer import (
    _ce,
    _rmsnorm,
    TransformerConfig,
    dense_layer,
    init_params,
    param_specs as _dense_param_specs,
    reference_loss,
)

__all__ = [
    "TransformerConfig",
    "init_params",
    "reference_loss",
    "param_specs",
    "make_global_train_step",
]


def param_specs(pp_ax):
    """Layers sharded into stages over ``pp``; everything else
    replicated (no tp in the pipeline variant)."""
    dense = _dense_param_specs(tp_ax=None)
    blocks = type(dense.blocks)(
        *(jax.P(pp_ax, *spec[1:]) for spec in dense.blocks)
    )
    return dense._replace(blocks=blocks)


def _stage_fn(cfg, stage_blocks, a):
    """This rank's layer slice: scan the local blocks over the
    activation (shape-preserving, as the pipeline wire requires)."""
    from mpi4jax_tpu.ops._core import promote_vma, vma_of

    # the layer scan's carry must match the blocks' varying axes from
    # tick 0 (pipeline_apply's shape probe passes an unvarying template)
    a = promote_vma(a, vma_of(stage_blocks.ln1) or ())

    def f(x, bp):
        return dense_layer(x, bp, cfg), None

    out, _ = lax.scan(f, a, stage_blocks)
    return out


def make_global_train_step(
    mesh, comm_dp, comm_pp, cfg, n_micro, lr=1e-1, schedule="gpipe"
):
    """Jitted global train step over a ``(dp, pp)`` mesh.

    ``batch = (tokens, targets)``, global ``[B, S]`` int32 sharded over
    ``dp``; each dp group runs an independent pipeline of
    ``comm_pp.size`` stages with ``n_micro`` microbatches.  Requires
    ``cfg.layers % comm_pp.size == 0`` and the per-dp-group batch
    divisible by ``n_micro``.  Returns ``(new_params, loss)``.

    ``schedule``: ``"gpipe"`` differentiates the forward pipeline with
    ``jax.grad`` (all-forward-then-all-backward; scan residuals stash
    every microbatch), ``"1f1b"`` runs the interleaved
    :func:`~mpi4jax_tpu.models.pipeline.pipeline_train` schedule
    (bounded in-flight activations, built-in remat).  Both are
    oracle-equal to the dense model — tests/parallel/test_pp_transformer.
    """
    if schedule not in ("gpipe", "1f1b"):
        raise ValueError(
            f"schedule must be 'gpipe' or '1f1b', got {schedule!r}"
        )
    dp_ax, pp_ax = comm_dp.axes[0], comm_pp.axes[0]
    dp = float(comm_dp.size)
    stages = comm_pp.size
    if cfg.layers % stages:
        raise ValueError(
            f"cfg.layers={cfg.layers} must be divisible by the pipeline "
            f"size {stages} (equal layer slices per stage)"
        )

    specs = param_specs(pp_ax)
    batch_specs = (jax.P(dp_ax, None), jax.P(dp_ax, None))

    def local_step(params, batch):
        tokens, targets = batch  # (B_loc, S) int32
        b_loc, s = tokens.shape
        if b_loc % n_micro:
            raise ValueError(
                f"per-dp-group batch {b_loc} must be divisible by "
                f"n_micro={n_micro}"
            )
        mb = b_loc // n_micro

        if schedule == "gpipe":

            def loss_fn(p):
                x = p.embed[tokens]  # every rank embeds; stage 0 wins
                mbs = x.reshape(n_micro, mb, s, cfg.d_model)
                out, _tok = pipeline_apply(
                    partial(_stage_fn, cfg), p.blocks, mbs, comm_pp
                )
                h = _rmsnorm(
                    out.reshape(b_loc, s, cfg.d_model), p.ln_f, cfg.eps
                )
                logits = h @ p.head
                # valid only on the last stage; masked elsewhere so each
                # device's loss is exactly its pipeline's contribution
                is_last = comm_pp.rank() == stages - 1
                return jnp.where(is_last, _ce(logits, targets), 0.0)

            loss, grads = jax.value_and_grad(loss_fn)(params)
        else:  # 1f1b: manual backward through the interleaved schedule
            x = params.embed[tokens]
            mbs = x.reshape(n_micro, mb, s, cfg.d_model)
            tmbs = targets.reshape(n_micro, mb, s)

            def head_fn(hp, a, tgt):
                ln_f, head = hp
                h = _rmsnorm(a, ln_f, cfg.eps)
                return _ce(h @ head, tgt)

            loss_sum, d_blocks, (d_ln_f, d_head), d_mbs, _tok = (
                pipeline_train(
                    partial(_stage_fn, cfg), params.blocks,
                    head_fn, (params.ln_f, params.head),
                    mbs, tmbs, comm_pp,
                )
            )
            # per-microbatch losses are means over 1/M of the batch:
            # sum/M == the gpipe path's whole-batch mean (and same for
            # the gradients)
            loss = loss_sum / n_micro
            dx = d_mbs.reshape(b_loc, s, cfg.d_model) / n_micro
            d_embed = jnp.zeros_like(params.embed).at[tokens].add(
                dx.astype(params.embed.dtype)
            )
            grads = params._replace(
                embed=d_embed,
                blocks=jax.tree.map(lambda g: g / n_micro, d_blocks),
                ln_f=d_ln_f / n_micro,
                head=d_head / n_micro,
            )
            # match gpipe's AD-inserted psums for replicated params:
            # embed/ln_f/head get contributions from one stage each (pp
            # sum adds zeros elsewhere), and EVERY grad class sums over
            # dp (the AD path does this via the replication rule; the
            # manual path must do it explicitly)
            grads = grads._replace(
                embed=lax.psum(grads.embed, pp_ax),
                ln_f=lax.psum(grads.ln_f, pp_ax),
                head=lax.psum(grads.head, pp_ax),
            )
            grads = jax.tree.map(lambda g: lax.psum(g, dp_ax), grads)
            loss = lax.psum(loss, pp_ax)
        # blocks are pp-sharded (no automatic sum); replicated params'
        # automatic (dp, pp)-psum adds zeros from non-contributing
        # stages — every param class needs only the dp mean scaling
        grads = jax.tree.map(lambda g: g / dp, grads)
        params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        if schedule == "gpipe":
            loss = lax.psum(loss, (dp_ax, pp_ax)) / dp
        else:
            loss = lax.psum(loss, dp_ax) / dp
        return params, loss[None]

    return jax.jit(
        jax.shard_map(
            local_step,
            mesh=mesh,
            in_specs=(specs, batch_specs),
            out_specs=(specs, jax.P((dp_ax, pp_ax))),
        )
    )
