"""Mixture-of-experts decoder transformer over a ``(dp, tp, sp)`` mesh.

Extends the dense composition showcase (models/transformer.py) with the
last parallelism family the library ships: **expert parallelism** on the
``alltoall`` building block (the reference names alltoall as its
expert-dispatch primitive — SURVEY §2.4, reference alltoall.py:35-74).
The ``sp`` mesh axis does double duty: the sequence axis for ring
attention *and* the expert-parallel axis for the MoE MLP — experts live
sharded across the same devices whose token shards they serve, so the
dispatch/combine pair rides two ICI ``all_to_all``s per layer.

Routing is **local expert choice** (per-device, capacity factor 1):
each expert takes its top-``capacity`` tokens *of this device's token
shard*, where ``capacity = local_tokens / n_experts``.  This is chosen
over token-choice top-k because it is perfectly load-balanced by
construction — every (source device, expert) bucket has identical
static shape, which is what turns the dispatch into one fused ICI
collective instead of a host gather — and needs no auxiliary balancing
loss.  Tokens chosen by several experts receive the gate-weighted sum;
tokens chosen by none pass through the residual only.

Differentiable end to end: gates through ``top_k``'s value gradient,
dispatch/combine through ``alltoall``'s self-inverse transpose, the
dense path through the Megatron f/g allreduce pair and the ring
(sendrecv-transpose) attention — one SGD step matches the unsharded
oracle (tests/parallel/test_moe_transformer.py).
"""

import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from mpi4jax_tpu.ops.collectives import alltoall
from mpi4jax_tpu.models.transformer import (
    _attn_residual,
    _ce,
    _rmsnorm,
    make_global_train_step as _make_dense_train_step,
)

__all__ = [
    "MoEConfig",
    "MoEBlockParams",
    "MoEParams",
    "init_params",
    "make_global_train_step",
    "reference_loss",
]


class MoEConfig(NamedTuple):
    vocab: int = 64
    d_model: int = 32
    layers: int = 2
    heads: int = 4
    kv_heads: int = 2
    head_dim: int = 8
    experts: int = 4      # total experts; must divide by the sp size
    d_ff: int = 64        # per-expert FFN width
    eps: float = 1e-6
    routing: str = "expert_choice"  # or "topk" (GShard/Switch)
    router_k: int = 2     # experts per token under routing="topk"
    aux_weight: float = 0.0  # Switch load-balancing loss weight
    z_weight: float = 0.0    # ST-MoE router z-loss weight (typ. 1e-3)


class MoEBlockParams(NamedTuple):
    ln1: jax.Array  # (L, d)            replicated
    wq: jax.Array   # (L, d, Hq*dh)     column-sharded over tp
    wk: jax.Array   # (L, d, Hkv*dh)    column-sharded over tp
    wv: jax.Array   # (L, d, Hkv*dh)    column-sharded over tp
    wo: jax.Array   # (L, Hq*dh, d)     row-sharded over tp
    ln2: jax.Array  # (L, d)            replicated
    wr: jax.Array   # (L, d, E)         router, replicated
    w1e: jax.Array  # (L, E, d, F)      expert-sharded over sp (dim 1)
    w2e: jax.Array  # (L, E, F, d)      expert-sharded over sp (dim 1)


class MoEParams(NamedTuple):
    embed: jax.Array
    blocks: MoEBlockParams
    ln_f: jax.Array
    head: jax.Array


def init_params(key, cfg, dtype=jnp.float32):
    c = cfg
    ks = jax.random.split(key, 9)

    def norm(k, shape, fan_in):
        return jax.random.normal(k, shape, dtype) * (1.0 / math.sqrt(fan_in))

    L, d, dh, E = c.layers, c.d_model, c.head_dim, c.experts
    blocks = MoEBlockParams(
        ln1=jnp.ones((L, d), dtype),
        wq=norm(ks[0], (L, d, c.heads * dh), d),
        wk=norm(ks[1], (L, d, c.kv_heads * dh), d),
        wv=norm(ks[2], (L, d, c.kv_heads * dh), d),
        wo=norm(ks[3], (L, c.heads * dh, d), c.heads * dh),
        ln2=jnp.ones((L, d), dtype),
        wr=norm(ks[4], (L, d, E), d),
        w1e=norm(ks[5], (L, E, d, c.d_ff), d),
        w2e=norm(ks[6], (L, E, c.d_ff, d), c.d_ff),
    )
    return MoEParams(
        embed=norm(ks[7], (c.vocab, d), d),
        blocks=blocks,
        ln_f=jnp.ones((d,), dtype),
        head=norm(ks[8], (d, c.vocab), d),
    )


def param_specs(tp_ax, sp_ax):
    blocks = MoEBlockParams(
        ln1=jax.P(None, None),
        wq=jax.P(None, None, tp_ax),
        wk=jax.P(None, None, tp_ax),
        wv=jax.P(None, None, tp_ax),
        wo=jax.P(None, tp_ax, None),
        ln2=jax.P(None, None),
        wr=jax.P(None, None, None),
        w1e=jax.P(None, sp_ax, None, None),
        w2e=jax.P(None, sp_ax, None, None),
    )
    return MoEParams(
        embed=jax.P(None, None),
        blocks=blocks,
        ln_f=jax.P(None),
        head=jax.P(None, None),
    )


def _route_local(logits, n_experts):
    """Local expert-choice routing on this device's ``(T, E)`` router
    logits.

    Returns ``(gates, idx)`` each ``(E, capacity)``: expert ``e`` takes
    its ``capacity = T // E`` highest-probability local tokens.
    """
    t = logits.shape[0]
    if t % n_experts:
        raise ValueError(
            f"local token count {t} must be divisible by experts="
            f"{n_experts} (capacity-1 expert choice)"
        )
    cap = t // n_experts
    probs = jax.nn.softmax(logits, axis=-1)  # (T, E)
    gates, idx = lax.top_k(probs.T, cap)  # (E, cap) each
    return gates, idx


def _expert_ffn(recv, w1e, w2e):
    """Per-slot expert FFN: ``recv`` is (src, e_local, cap, d)."""
    h = jnp.einsum("seci,eif->secf", recv, w1e)
    h = jax.nn.gelu(h)
    return jnp.einsum("secf,efi->seci", h, w2e)


def _route(xt, wr, cfg):
    """Dispatch-ready routing under either scheme.

    Returns ``(gates, idx, buckets, aux)`` with the first three
    expert-major ``(E, cap, …)``; expert-choice buckets are always
    fully valid, topk buckets zero their unfilled/overflow slots (their
    gate is zero too).  ``aux`` is the weighted auxiliary-loss scalar
    (Switch load-balancing + router z-loss per ``cfg.aux_weight`` /
    ``cfg.z_weight``), or ``None`` when both weights are zero (so the
    default config's jaxpr is unchanged).
    """
    from mpi4jax_tpu.parallel.moe import (
        default_capacity,
        load_balancing_loss,
        router_z_loss,
        topk_route,
    )

    logits = xt @ wr
    if cfg.routing == "topk":
        scores = jax.nn.softmax(logits, axis=-1)
        cap = default_capacity(cfg.router_k, xt.shape[0], cfg.experts)
        idx, gates, valid = topk_route(scores, cfg.router_k, cap)
        buckets = xt[idx] * valid[..., None].astype(xt.dtype)
    elif cfg.routing == "expert_choice":
        gates, idx = _route_local(logits, cfg.experts)
        scores, buckets = None, xt[idx]
    else:
        raise ValueError(
            f"cfg.routing must be 'expert_choice' or 'topk', got "
            f"{cfg.routing!r}"
        )
    aux = None
    if cfg.aux_weight or cfg.z_weight:
        aux = jnp.zeros((), jnp.float32)
        if cfg.z_weight:
            aux = aux + cfg.z_weight * router_z_loss(logits)
        if cfg.aux_weight and cfg.routing == "topk":
            # expert choice is load-balanced by construction; the
            # balance loss only applies to token-choice routing
            aux = aux + cfg.aux_weight * load_balancing_loss(
                scores, cfg.router_k
            )
    return gates, idx, buckets, aux


def _moe_ffn(h, wr, w1e, w2e, cfg, comm_ep, token):
    """MoE MLP: route → alltoall dispatch → expert FFN → alltoall
    combine → gate-weighted scatter-add.  ``h``: (b, s_local, d).
    Returns ``(y, token)`` — or ``(y, token, aux)`` when the config
    enables auxiliary router losses."""
    ep = comm_ep.size
    e_local = cfg.experts // ep
    b, s, d = h.shape
    xt = h.reshape(b * s, d)
    gates, idx, buckets, aux = _route(xt, wr, cfg)  # (E, cap, ...)
    # expert e lives on ep-rank e // e_local: grouping experts by
    # destination is a reshape because the layout is contiguous
    cap = buckets.shape[1]
    send = buckets.reshape(ep, e_local, cap, d)
    recv, token = alltoall(send, comm=comm_ep, token=token)
    out = _expert_ffn(recv, w1e, w2e)  # (src, e_local, cap, d)
    back, token = alltoall(out, comm=comm_ep, token=token)
    vals = back.reshape(cfg.experts, cap, d)
    y = jnp.zeros_like(xt).at[idx.reshape(-1)].add(
        (gates[..., None] * vals).reshape(-1, d)
    )
    y = y.reshape(b, s, d)
    if aux is None:
        return y, token
    return y, token, aux


def _moe_mlp(h2, bp, cfg, comm_tp, comm_sp, token):
    """MLP-sublayer callback for the shared transformer scaffold."""
    return _moe_ffn(h2, bp.wr, bp.w1e, bp.w2e, cfg, comm_sp, token)


def make_global_train_step(
    mesh, comm_dp, comm_tp, comm_sp, cfg, lr=1e-1, *, remat=False
):
    """Jitted global train step over a ``(dp, tp, sp)`` mesh with the
    MoE MLP expert-sharded over ``sp``.

    Delegates to the dense transformer's step builder (one scaffold —
    attention, grad sync, jit/shard_map wrapper — shared between both
    models) with the MoE sublayer and expert-sharded PartitionSpecs
    substituted.  Additionally requires ``cfg.experts % comm_sp.size
    == 0``; under ``routing="expert_choice"`` the per-device token
    count must also be divisible by ``cfg.experts`` (capacity-1 expert
    choice), while ``routing="topk"`` uses ceil capacity and has no
    such requirement.
    """
    if cfg.experts % comm_sp.size:
        raise ValueError(
            f"cfg.experts={cfg.experts} must be divisible by the "
            f"expert-parallel (sp) size {comm_sp.size}"
        )
    return _make_dense_train_step(
        mesh, comm_dp, comm_tp, comm_sp, cfg, lr,
        mlp=_moe_mlp,
        specs=param_specs(comm_tp.axes[0], comm_sp.axes[0]),
        remat=remat,
    )


def reference_loss(params, tokens, targets, cfg, dp, sp):
    """Unsharded oracle replicating the sharded semantics exactly.

    Expert *selection* is per-device (local expert choice), so the
    oracle partitions the global batch into the same ``(dp, sp)`` token
    blocks the mesh would hold and routes within each block; the expert
    FFN itself is pointwise per token, so which device hosted an expert
    is irrelevant to the value.  When the config enables auxiliary
    router losses, the oracle adds the mean over blocks of the
    per-block (layer-summed) aux — exactly what the sharded step's
    ``psum(local_loss)/(n_data·tp)`` reduces to.
    """
    b, s = tokens.shape
    b_loc, s_loc = b // dp, s // sp
    x = params.embed[tokens]

    def moe_block(xt, wr, w1e, w2e):
        gates, idx, buckets, aux = _route(xt, wr, cfg)
        vals = _expert_ffn(
            buckets[None], w1e, w2e
        )[0]  # (E, cap, d): all experts local
        y = jnp.zeros_like(xt).at[idx.reshape(-1)].add(
            (gates[..., None] * vals).reshape(-1, xt.shape[-1])
        )
        return y, (jnp.zeros((), jnp.float32) if aux is None else aux)

    def layer(carry, bp):
        x, aux = carry
        x = _attn_residual(x, bp, cfg)
        h2 = _rmsnorm(x, bp.ln2, cfg.eps)
        # route within each (dp, sp) block, exactly as the mesh does
        blocks = h2.reshape(dp, b_loc, sp, s_loc, cfg.d_model)
        blocks = blocks.transpose(0, 2, 1, 3, 4).reshape(
            dp * sp, b_loc * s_loc, cfg.d_model
        )
        m, aux_blocks = jax.vmap(
            lambda xt: moe_block(xt, bp.wr, bp.w1e, bp.w2e)
        )(blocks)
        m = m.reshape(dp, sp, b_loc, s_loc, cfg.d_model).transpose(
            0, 2, 1, 3, 4
        ).reshape(b, s, cfg.d_model)
        return (x + m, aux + aux_blocks.mean()), None

    (x, aux), _ = lax.scan(layer, (x, jnp.zeros((), jnp.float32)), params.blocks)
    x = _rmsnorm(x, params.ln_f, cfg.eps)
    return _ce(x @ params.head, targets) + aux


def routing_report(params, tokens, cfg, dp=1, sp=1):
    """Router-quality diagnostics for ``routing="topk"`` (unsharded;
    same per-``(dp, sp)``-block routing as the mesh step).

    Returns a dict of concrete floats/arrays:
      ``load`` — ``(E,)`` fraction of routing assignments per expert
        (pre-capacity), averaged over blocks and layers; uniform = 1/E;
      ``balance_loss`` — unweighted Switch load-balancing loss (1 =
        perfectly balanced, up to E at collapse);
      ``z_loss`` — unweighted router z-loss;
      ``dropped_fraction`` — fraction of assignments that overflowed
        expert capacity (the VERDICT-named drop metric).

    Expert-choice routing is load-balanced by construction (every
    expert takes exactly ``T/E`` tokens), so the report refuses it
    rather than printing constants.
    """
    from mpi4jax_tpu.parallel.moe import (
        default_capacity,
        dropped_fraction,
        router_z_loss,
        topk_route,
    )

    if cfg.routing != "topk":
        raise ValueError(
            "routing_report applies to routing='topk' only; expert-"
            "choice routing is load-balanced by construction"
        )
    b, s = tokens.shape
    b_loc, s_loc = b // dp, s // sp
    t_loc = b_loc * s_loc
    cap = default_capacity(cfg.router_k, t_loc, cfg.experts)
    x = params.embed[tokens]

    def block_pass(xt, bp):
        """One routing pass per block: the MoE sublayer output AND the
        diagnostics, from the same logits/route (no duplicate dispatch
        logic to keep in sync with _route)."""
        logits = xt @ bp.wr
        probs = jax.nn.softmax(logits, axis=-1)
        idx, gates, valid = topk_route(probs, cfg.router_k, cap)
        buckets = xt[idx] * valid[..., None].astype(xt.dtype)
        vals = _expert_ffn(buckets[None], bp.w1e, bp.w2e)[0]
        y = jnp.zeros_like(xt).at[idx.reshape(-1)].add(
            (gates[..., None] * vals).reshape(-1, xt.shape[-1])
        )
        _, top = lax.top_k(probs, cfg.router_k)
        counts = jnp.zeros((cfg.experts,), jnp.float32).at[
            top.reshape(-1)
        ].add(1.0)
        f = counts / (t_loc * cfg.router_k)  # assignment fractions
        stats = (
            f,
            cfg.experts * jnp.sum(f * probs.mean(0)),  # Switch balance
            router_z_loss(logits),
            dropped_fraction(valid, t_loc, cfg.router_k),
        )
        return y, stats

    loads, balances, zs, drops = [], [], [], []
    for li in range(cfg.layers):
        bp = jax.tree.map(lambda p: p[li], params.blocks)
        x_attn = _attn_residual(x, bp, cfg)
        h2 = _rmsnorm(x_attn, bp.ln2, cfg.eps)
        blocks = h2.reshape(dp, b_loc, sp, s_loc, cfg.d_model)
        blocks = blocks.transpose(0, 2, 1, 3, 4).reshape(
            dp * sp, t_loc, cfg.d_model
        )
        m, (load, bal, z, drop) = jax.vmap(
            lambda xt: block_pass(xt, bp)
        )(blocks)
        loads.append(load.mean(0))
        balances.append(bal.mean())
        zs.append(z.mean())
        drops.append(drop.mean())
        m = m.reshape(dp, sp, b_loc, s_loc, cfg.d_model).transpose(
            0, 2, 1, 3, 4
        ).reshape(b, s, cfg.d_model)
        x = x_attn + m
    return {
        "load": jnp.stack(loads).mean(0),
        "balance_loss": float(jnp.stack(balances).mean()),
        "z_loss": float(jnp.stack(zs).mean()),
        "dropped_fraction": float(jnp.stack(drops).mean()),
    }
