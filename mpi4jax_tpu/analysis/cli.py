"""``t4j-lint`` — command-line front end of the contract verifier.

Lints the communication schedules of Python programs before any byte
moves::

    t4j-lint examples/shallow_water.py mpi4jax_tpu/models/transformer.py
    python -m mpi4jax_tpu.analysis.cli --list examples/shallow_water.py

A target file declares what to lint via a module-level

    T4J_LINT_ENTRIES = [("name", zero_arg_thunk), ...]

list: each thunk builds a representative (small) input set and runs the
program's communication path; the CLI traces it with
:func:`~mpi4jax_tpu.analysis.verify_comm` — nothing executes, so
entries are cheap even for programs whose real inputs are huge.  Files
without ``T4J_LINT_ENTRIES`` are reported as skipped (exit code is
unaffected): lint coverage is opt-in per program, exactly like a test.

Exit codes: 0 clean, 1 findings, 2 usage/target errors — the usual
linter contract so CI lanes (tools/ci_smoke.sh lint lane) can gate on
it.
"""

import argparse
import importlib.util
import os
import pathlib
import sys

__all__ = ["main"]


def _ensure_devices():
    """Give mesh-backed entries a virtual 8-device CPU slice, mirroring
    tests/conftest.py — must happen before jax initialises."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _load_module(path):
    path = pathlib.Path(path).resolve()
    name = f"_t4j_lint_{path.stem}_{abs(hash(str(path))) % 10**8}"
    spec = importlib.util.spec_from_file_location(name, path)
    if spec is None or spec.loader is None:
        raise ImportError(f"cannot load {path}")
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def _entries(mod):
    raw = getattr(mod, "T4J_LINT_ENTRIES", None)
    if raw is None:
        return None
    out = []
    for item in raw:
        if callable(item):
            out.append((getattr(item, "__name__", "entry"), item))
        else:
            name, thunk = item
            out.append((str(name), thunk))
    return out


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="t4j-lint",
        description="trace-time communication contract verifier "
        "(rule catalog: docs/static-analysis.md)",
    )
    parser.add_argument("files", nargs="+", help="Python files to lint")
    parser.add_argument(
        "--mode", default="full", choices=["fingerprint", "full"],
        help="verification depth (default: full)",
    )
    parser.add_argument(
        "--list", action="store_true",
        help="list each file's lint entries without verifying",
    )
    parser.add_argument(
        "-q", "--quiet", action="store_true",
        help="only print findings and the final summary",
    )
    parser.add_argument(
        "--coalesce", action="store_true",
        help="advisory: report runs of small same-peer messages in each "
        "entry's recorded schedule that the fused wire path would "
        "collapse into one frame (docs/performance.md \"small-message "
        "coalescing\")",
    )
    parser.add_argument(
        "--coalesce-bytes", type=int, default=None, metavar="BYTES",
        help="threshold for --coalesce (default: the effective "
        "T4J_COALESCE_BYTES); implies --coalesce",
    )
    args = parser.parse_args(argv)
    if args.coalesce_bytes is not None:
        args.coalesce = True

    _ensure_devices()
    from mpi4jax_tpu.analysis.verify import verify_comm

    n_findings = 0
    n_entries = 0
    broken = 0
    for path in args.files:
        try:
            mod = _load_module(path)
        except Exception as exc:
            print(f"{path}: cannot import target: {exc}", file=sys.stderr)
            broken += 1
            continue
        entries = _entries(mod)
        if entries is None:
            if not args.quiet:
                print(f"{path}: no T4J_LINT_ENTRIES, skipped")
            continue
        for name, thunk in entries:
            if args.list:
                print(f"{path}::{name}")
                continue
            n_entries += 1
            try:
                report = verify_comm(thunk, mode=args.mode)()
            except Exception as exc:
                print(
                    f"{path}::{name}: verification crashed: {exc}",
                    file=sys.stderr,
                )
                broken += 1
                continue
            for note in report.notes:
                print(f"{path}::{name}: note: {note}")
            if args.coalesce:
                # feed the recorded schedule forward into the
                # coalescing planner (the run-time ops apply the same
                # T4J_COALESCE_BYTES gate; this makes the plan visible)
                from mpi4jax_tpu import tuning

                threshold = (
                    tuning.coalesce_bytes()
                    if args.coalesce_bytes is None
                    else args.coalesce_bytes
                )
                runs = tuning.coalesce.find_runs(report.events, threshold)
                print(f"{path}::{name}: "
                      + tuning.coalesce.render_plan(runs, threshold))
            if report.ok:
                if not args.quiet:
                    print(f"{path}::{name}: {report}")
            else:
                n_findings += len(report.findings)
                for f in report.findings:
                    print(f"{path}::{name}: {f}")

    if not args.list and not args.quiet:
        print(
            f"t4j-lint: {n_entries} entr{'y' if n_entries == 1 else 'ies'}"
            f" checked, {n_findings} finding(s)"
        )
    if broken:
        return 2
    return 1 if n_findings else 0


if __name__ == "__main__":
    sys.exit(main())
