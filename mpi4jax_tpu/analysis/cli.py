"""``t4j-lint`` / ``t4j-verify`` — command-line front ends of the
contract verifier.

Lints the communication schedules of Python programs before any byte
moves::

    t4j-lint examples/shallow_water.py mpi4jax_tpu/models/transformer.py
    python -m mpi4jax_tpu.analysis.cli --list examples/shallow_water.py

A target file declares what to lint via a module-level

    T4J_LINT_ENTRIES = [("name", zero_arg_thunk), ...]

list: each thunk builds a representative (small) input set and runs the
program's communication path; the CLI traces it with
:func:`~mpi4jax_tpu.analysis.verify_comm` — nothing executes, so
entries are cheap even for programs whose real inputs are huge.  Files
without ``T4J_LINT_ENTRIES`` are reported as skipped (exit code is
unaffected): lint coverage is opt-in per program, exactly like a test.

``t4j-verify`` (:func:`verify_main`) adds the cross-rank simulator
(analysis/simulate.py, rules T4J010–T4J014) over three input shapes::

    t4j-verify examples/shallow_water.py        # trace + specialize
    t4j-verify --traces r0.json r1.json         # per-rank recordings
    t4j-verify --plan-stream serve_plans.jsonl  # serving control plane

The ``--traces`` and ``--plan-stream`` paths never import jax — a
trace recorded on a pod replays on any machine.

Both commands share the linter exit-code contract (documented in
docs/static-analysis.md, gated on by tools/ci_smoke.sh):

* **0** — clean: every target checked, no findings;
* **1** — findings: at least one rule fired;
* **2** — usage or trace error: a target failed to import, a trace
  file was malformed, or verification itself crashed.

``--format json`` prints one JSON object on stdout (``findings`` list
with ``rule``/``message``/``src_info``/``where``, plus counters) so CI
gates on structure + exit code instead of grepping prose.
"""

import argparse
import importlib.util
import json
import os
import pathlib
import sys

__all__ = ["main", "verify_main"]


def _ensure_devices():
    """Give mesh-backed entries a virtual 8-device CPU slice, mirroring
    tests/conftest.py — must happen before jax initialises."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _load_module(path):
    path = pathlib.Path(path).resolve()
    name = f"_t4j_lint_{path.stem}_{abs(hash(str(path))) % 10**8}"
    spec = importlib.util.spec_from_file_location(name, path)
    if spec is None or spec.loader is None:
        raise ImportError(f"cannot load {path}")
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def _entries(mod):
    raw = getattr(mod, "T4J_LINT_ENTRIES", None)
    if raw is None:
        return None
    out = []
    for item in raw:
        if callable(item):
            out.append((getattr(item, "__name__", "entry"), item))
        else:
            name, thunk = item
            out.append((str(name), thunk))
    return out


class _Output:
    """Collects findings for text or JSON emission with one code path.

    Text mode prints findings as they arrive (a linter's expected
    behaviour); JSON mode buffers everything and prints one object at
    the end so stdout is machine-parseable.
    """

    def __init__(self, fmt, quiet=False):
        self.fmt = fmt
        self.quiet = quiet
        self.findings = []
        self.errors = []
        self.notes = []

    def finding(self, where, f):
        self.findings.append({
            "where": where,
            "rule": f.rule,
            "message": f.message,
            "src_info": f.src_info,
        })
        if self.fmt == "text":
            print(f"{where}: {f}")

    def error(self, where, msg):
        self.errors.append({"where": where, "message": str(msg)})
        if self.fmt == "text":
            print(f"{where}: {msg}", file=sys.stderr)

    def note(self, where, msg):
        self.notes.append({"where": where, "message": str(msg)})
        if self.fmt == "text" and not self.quiet:
            print(f"{where}: note: {msg}")

    def info(self, text):
        if self.fmt == "text" and not self.quiet:
            print(text)

    def finish(self, prog, n_checked):
        code = 2 if self.errors else (1 if self.findings else 0)
        if self.fmt == "json":
            print(json.dumps({
                "tool": prog,
                "checked": n_checked,
                "findings": self.findings,
                "errors": self.errors,
                "notes": self.notes,
                "exit_code": code,
            }, indent=2))
        elif not self.quiet:
            print(
                f"{prog}: {n_checked} "
                f"entr{'y' if n_checked == 1 else 'ies'} checked, "
                f"{len(self.findings)} finding(s)"
                + (f", {len(self.errors)} error(s)" if self.errors
                   else "")
            )
        return code


def _simulate_events(events, out, where, max_states, eager_bytes):
    """Specialize one SPMD trace per rank and run the match engine on
    each communicator group (rules T4J010–T4J014)."""
    from mpi4jax_tpu.analysis import simulate as sim

    n = 0
    for comm_id, schedules in sim.specialize_spmd(events):
        result = sim.simulate(
            schedules, max_states=max_states, eager_bytes=eager_bytes
        )
        n += 1
        for note in result.notes:
            out.note(where, f"[comm {comm_id}] {note}")
        for f in result.findings:
            out.finding(f"{where}[comm {comm_id}]", f)
    if n == 0:
        out.note(where, "no multi-rank communicator in the recorded "
                        "schedule; nothing to simulate")


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="t4j-lint",
        description="trace-time communication contract verifier "
        "(rule catalog: docs/static-analysis.md)",
    )
    parser.add_argument("files", nargs="+", help="Python files to lint")
    parser.add_argument(
        "--mode", default="full", choices=["fingerprint", "full"],
        help="verification depth (default: full)",
    )
    parser.add_argument(
        "--list", action="store_true",
        help="list each file's lint entries without verifying",
    )
    parser.add_argument(
        "-q", "--quiet", action="store_true",
        help="only print findings and the final summary",
    )
    parser.add_argument(
        "--coalesce", action="store_true",
        help="advisory: report runs of small same-peer messages in each "
        "entry's recorded schedule that the fused wire path would "
        "collapse into one frame (docs/performance.md \"small-message "
        "coalescing\")",
    )
    parser.add_argument(
        "--coalesce-bytes", type=int, default=None, metavar="BYTES",
        help="threshold for --coalesce (default: the effective "
        "T4J_COALESCE_BYTES); implies --coalesce",
    )
    parser.add_argument(
        "--format", default="text", choices=["text", "json"],
        help="output format; json prints one machine-readable object "
        "(default: text)",
    )
    parser.add_argument(
        "--simulate", action="store_true",
        help="also run the cross-rank match-engine simulator over each "
        "entry's recorded schedule (rules T4J010–T4J014; same engine "
        "as t4j-verify)",
    )
    parser.add_argument(
        "--max-states", type=int, default=None, metavar="N",
        help="wildcard-exploration state cap for --simulate",
    )
    args = parser.parse_args(argv)
    if args.coalesce_bytes is not None:
        args.coalesce = True

    _ensure_devices()
    from mpi4jax_tpu.analysis import simulate as sim
    from mpi4jax_tpu.analysis.verify import verify_comm

    out = _Output(args.format, quiet=args.quiet)
    n_entries = 0
    for path in args.files:
        try:
            mod = _load_module(path)
        except Exception as exc:
            out.error(path, f"cannot import target: {exc}")
            continue
        entries = _entries(mod)
        if entries is None:
            out.info(f"{path}: no T4J_LINT_ENTRIES, skipped")
            continue
        for name, thunk in entries:
            where = f"{path}::{name}"
            if args.list:
                print(where)
                continue
            n_entries += 1
            try:
                report = verify_comm(thunk, mode=args.mode)()
            except Exception as exc:
                out.error(where, f"verification crashed: {exc}")
                continue
            for note in report.notes:
                out.note(where, note)
            if args.coalesce:
                # feed the recorded schedule forward into the
                # coalescing planner (the run-time ops apply the same
                # T4J_COALESCE_BYTES gate; this makes the plan visible)
                from mpi4jax_tpu import tuning

                threshold = (
                    tuning.coalesce_bytes()
                    if args.coalesce_bytes is None
                    else args.coalesce_bytes
                )
                runs = tuning.coalesce.find_runs(report.events, threshold)
                out.info(f"{where}: "
                         + tuning.coalesce.render_plan(runs, threshold))
            if report.ok:
                out.info(f"{where}: {report}")
            else:
                for f in report.findings:
                    out.finding(where, f)
            if args.simulate:
                _simulate_events(
                    report.events, out, where,
                    args.max_states or sim.DEFAULT_MAX_STATES,
                    sim.DEFAULT_EAGER_BYTES,
                )

    if args.list:
        return 0
    return out.finish("t4j-lint", n_entries)


def verify_main(argv=None):
    parser = argparse.ArgumentParser(
        prog="t4j-verify",
        description="cross-rank schedule simulator: MPI-semantics "
        "deadlock, nondeterminism and matching checks before a job "
        "ever opens a socket (rules T4J010–T4J014, "
        "docs/static-analysis.md)",
    )
    parser.add_argument(
        "files", nargs="*",
        help="Python files with T4J_LINT_ENTRIES: each entry is "
        "traced, specialized per rank, and simulated",
    )
    parser.add_argument(
        "--traces", nargs="+", metavar="SCHEDULE.json",
        help="per-rank schedule files (record.dump_schedule output), "
        "one whole job per invocation; never imports jax",
    )
    parser.add_argument(
        "--plan-stream", metavar="STREAM.jsonl",
        help="recorded serving plan stream (ServingEngine plan_log / "
        "T4J_PLAN_LOG): replays the follower mirror and simulates the "
        "control-plane broadcasts; never imports jax",
    )
    parser.add_argument(
        "--format", default="text", choices=["text", "json"],
    )
    parser.add_argument(
        "--max-states", type=int, default=None, metavar="N",
        help="wildcard-exploration state cap (default 4096)",
    )
    parser.add_argument(
        "--eager-bytes", type=int, default=None, metavar="BYTES",
        help="send eager/rendezvous threshold (default 65536)",
    )
    parser.add_argument("-q", "--quiet", action="store_true")
    args = parser.parse_args(argv)
    if not args.files and not args.traces and not args.plan_stream:
        parser.error("nothing to verify: give Python files, --traces, "
                     "or --plan-stream")

    from mpi4jax_tpu.analysis import simulate as sim

    max_states = args.max_states or sim.DEFAULT_MAX_STATES
    eager = (sim.DEFAULT_EAGER_BYTES if args.eager_bytes is None
             else args.eager_bytes)
    out = _Output(args.format, quiet=args.quiet)
    n_checked = 0

    if args.traces:
        from mpi4jax_tpu.analysis.record import load_schedule

        schedules = []
        try:
            loaded = [load_schedule(p) for p in args.traces]
        except (OSError, ValueError) as exc:
            out.error("--traces", exc)
            loaded = None
        if loaded is not None:
            # order by recorded rank when every file carries one,
            # else positionally
            if all(r is not None for r, _e in loaded):
                loaded.sort(key=lambda re: int(re[0]))
            schedules = [e for _r, e in loaded]
            n_checked += 1
            where = "+".join(args.traces)
            result = sim.simulate(
                schedules, max_states=max_states, eager_bytes=eager
            )
            for note in result.notes:
                out.note(where, note)
            for f in result.findings:
                out.finding(where, f)
            if result.ok:
                out.info(f"{where}: {len(schedules)} rank schedule(s) "
                         "simulated clean "
                         f"({result.states} state(s) explored)")

    if args.plan_stream:
        from mpi4jax_tpu.serving import plan as plan_mod

        where = args.plan_stream
        try:
            meta, vecs = plan_mod.load_plan_stream(args.plan_stream)
        except (OSError, plan_mod.PlanError) as exc:
            out.error(where, exc)
            meta = None
        if meta is not None:
            n_checked += 1
            before = len(out.findings)
            for f in plan_mod.replay_stream(meta, vecs, source=where):
                out.finding(where, f)
            schedules = plan_mod.plan_stream_schedule(
                meta, vecs, source=where
            )
            result = sim.simulate(
                schedules, max_states=max_states, eager_bytes=eager
            )
            for f in result.findings:
                out.finding(where, f)
            if len(out.findings) == before:
                out.info(
                    f"{where}: {len(vecs)} plan(s) replayed clean over "
                    f"{len(schedules)} rank(s)")

    if args.files:
        _ensure_devices()
        from mpi4jax_tpu.analysis.verify import verify_comm

        for path in args.files:
            try:
                mod = _load_module(path)
            except Exception as exc:
                out.error(path, f"cannot import target: {exc}")
                continue
            entries = _entries(mod)
            if entries is None:
                out.info(f"{path}: no T4J_LINT_ENTRIES, skipped")
                continue
            for name, thunk in entries:
                where = f"{path}::{name}"
                n_checked += 1
                try:
                    report = verify_comm(thunk, mode="full")()
                except Exception as exc:
                    out.error(where, f"verification crashed: {exc}")
                    continue
                for f in report.findings:
                    out.finding(where, f)
                _simulate_events(report.events, out, where,
                                 max_states, eager)

    return out.finish("t4j-verify", n_checked)


if __name__ == "__main__":
    sys.exit(main())
