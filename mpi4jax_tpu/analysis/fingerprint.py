"""Cross-rank schedule-fingerprint pass (rule T4J007).

The static single-trace pass sees one rank's program; on the
multi-process (MPMD) backend the classic failure mode is *divergence* —
per-rank Python control flow makes rank A trace an ``allreduce`` where
rank B traced a ``bcast``, and the job hangs until PR 1's
``T4J_OP_TIMEOUT`` deadline converts the hang into a ``BridgeError``
after a full timeout.  This pass turns that into an immediate,
attributed error: before executing, every rank serialises its extracted
schedule (op kind, comm key, dtype, shape, reduce op, root, tag,
per-comm order — contracts.step_signature), exchanges the serialisation
with every other rank, and every rank independently diffs the per-comm
sections it is a member of.  Divergence raises
:class:`~.contracts.CommContractError` on *every* member naming the
first differing step, so each job log carries the full diagnosis.

Two transports, matching the repo's two multi-rank tiers:

* **proc** — the native bridge's world allgather
  (native/runtime.py host_allgather), available whenever the process is
  part of a launched job.  One fixed-size buffer per rank; wall cost is
  one small collective, orders of magnitude below any op deadline.
* **in-process** — the rendezvous registry's barrier-style value
  exchange (ops/_rendezvous.py ``exchange``), for thread-per-rank MPMD
  harnesses and the analyzer's own tests.  Mesh/self programs are
  single-trace by construction (SPMD: one program, every device), so
  there is nothing to exchange — divergence is impossible, and the
  pass is a no-op without an explicit ``world``.
"""

import hashlib
import json

import numpy as np

from mpi4jax_tpu.analysis.contracts import (
    CommContractError,
    divergence_message,
    step_signature,
)

__all__ = ["exchange_and_check", "serialize_schedule", "FP_BYTES"]

FP_BYTES = 16384          # fixed exchange-buffer size per rank
_MAX_SECTION_STEPS = 200  # above this a section ships digest-only

# Point-to-point steps are EXCLUDED from the per-comm lockstep diff:
# their per-rank asymmetry is the norm, not divergence (`if rank == 0:
# send else: recv` is the canonical correct p2p program, and would
# false-positive a positional comparison).  P2p agreement is envelope
# matching — the cross-rank simulator's job (the @sched rung below;
# rules T4J010/T4J011/T4J012) in full mode, and the runtime rendezvous
# engine's otherwise.  Collectives stay lockstep-diffed: every member
# must issue the same sequence.
_P2P_KINDS = frozenset((
    "send", "isend", "recv", "irecv", "sendrecv", "sendrecv_multi",
))


def serialize_schedule(events, with_sched=False):
    """Canonical per-comm serialisation of one rank's schedule.

    Sections are ordered by first appearance; each carries the comm's
    member ranks (so ranks outside a communicator skip its section),
    a digest of the full step sequence, and — for reasonably sized
    schedules — the per-step signature lines used to name the first
    differing step.

    ``with_sched=True`` appends an ``@sched`` section: the full event
    export (record.event_to_dict, one compact JSON object per line)
    that lets every receiving rank run the cross-rank match-engine
    simulator (analysis/simulate.py) over the assembled whole-job
    schedule — catching schedules that AGREE per-comm yet still
    deadlock.  The degrade ladder runs full+sim -> full -> digest-only
    -> one global digest; a rung is dropped whole, never truncated,
    because a cut-off tail would silently compare equal.
    """
    sections = []  # (comm_header, [step lines])
    index = {}
    for ev in events:
        if ev.kind in _P2P_KINDS:
            continue  # envelope-matched, not lockstep (see _P2P_KINDS)
        key = _comm_header(ev)
        if key not in index:
            index[key] = len(sections)
            sections.append((key, []))
        sections[index[key]][1].append(step_signature(ev))
    def render(with_steps, sched=False):
        out = []
        for header, lines in sections:
            digest = hashlib.sha256(
                "\n".join(lines).encode()
            ).hexdigest()[:16]
            out.append(f"@comm {header} n={len(lines)} sha={digest}")
            if with_steps and len(lines) <= _MAX_SECTION_STEPS:
                out.extend(lines)
        if sched:
            from mpi4jax_tpu.analysis.record import event_to_dict

            out.append(f"@sched n={len(events)}")
            for ev in events:
                out.append(json.dumps(
                    event_to_dict(ev), separators=(",", ":")
                ))
        return "\n".join(out).encode()

    text = render(with_steps=True, sched=with_sched) if with_sched \
        else b""
    if not text or len(text) >= FP_BYTES:
        text = render(with_steps=True)
    if len(text) >= FP_BYTES:
        text = render(with_steps=False)
    if len(text) >= FP_BYTES:
        text = (
            "@comm <all> members=* n=%d sha=%s"
            % (len(events), hashlib.sha256(text).hexdigest()[:16])
        ).encode()
    return text


def _comm_header(ev):
    members = ",".join(map(str, ev.comm_ranks)) if ev.comm_ranks else "*"
    return f"{'/'.join(map(str, ev.comm_key))} members={members}"


def exchange_and_check(events, world=None, timeout=None,
                       local_findings=(), simulate=False):
    """Exchange this rank's schedule and raise on divergence.

    ``world`` is ``None`` (auto: use the proc tier when the native
    bridge is initialised, else no-op) or an explicit ``(rank, size)``
    pair routing through the in-process rendezvous exchange.  Returns
    the number of peer schedules compared (0 = pass skipped).

    ``local_findings`` (rule IDs) marks this rank's schedule as locally
    broken: the rank still participates — the exchange is a collective
    and sitting out would wedge every clean peer — but posts a sentinel,
    and the *peers* raise immediately naming it.

    ``simulate=True`` ships the full event export when it fits
    (``@sched`` section) and, when every rank's blob carries one, runs
    the whole-job match-engine simulator after the per-comm diffs pass
    — so a divergence verdict can cite an actual deadlock cycle
    (T4J010/T4J013) or wildcard race (T4J011) instead of only a digest
    mismatch, and agreement no longer means safety.
    """
    if local_findings:
        payload = ("!findings " + ",".join(local_findings)).encode()
    else:
        payload = serialize_schedule(events, with_sched=simulate)
    if world is not None:
        rank, size = int(world[0]), int(world[1])
        if size <= 1:
            return 0
        from mpi4jax_tpu.ops import _rendezvous

        blobs = _rendezvous.exchange(
            "t4j-fingerprint", rank, size, payload,
            timeout=timeout if timeout is not None else 60.0,
        )
    else:
        blobs = _proc_exchange(payload)
        if blobs is None:
            return 0
        from mpi4jax_tpu.native import runtime

        rank = runtime.world_rank()
    _compare(blobs, my_rank=rank, simulate=simulate)
    return len(blobs)


def _proc_exchange(payload):
    """World allgather of the fixed-size fingerprint buffer over the
    native bridge; returns None when not in a multi-process job."""
    from mpi4jax_tpu.native import runtime

    if not runtime.available():
        return None
    runtime.ensure_initialized()
    if runtime.world_size() <= 1:
        return None
    buf = np.zeros(FP_BYTES, np.uint8)
    raw = np.frombuffer(payload, np.uint8)
    buf[: raw.size] = raw
    gathered = runtime.host_allgather(0, buf)  # handle 0 = world comm
    return [bytes(row.tobytes()).rstrip(b"\x00") for row in gathered]


def _compare(blobs, my_rank=None, simulate=False):
    """Diff every per-comm section this process is a member of; raise
    CommContractError naming the first differing step on mismatch."""
    broken = {
        r: blob.decode(errors="replace")[len("!findings "):]
        for r, blob in enumerate(blobs)
        if blob.startswith(b"!findings ")
    }
    if broken:
        if my_rank in broken:
            # this rank's own Report carries the detail; don't bury it
            # under a CommContractError about itself
            return
        sides = "; ".join(
            f"rank {r}: {rules}" for r, rules in sorted(broken.items())
        )
        raise CommContractError(
            "T4J007: peer rank(s) failed local contract verification "
            f"({sides}) — executing would desynchronise the schedule. "
            "See the failing rank's own report for the findings."
        )
    parsed = [_parse(blob) for blob in blobs]
    all_comms = []
    for sections in parsed:
        for comm_id in sections:
            if comm_id != "@sched" and comm_id not in all_comms:
                all_comms.append(comm_id)
    for comm_id in all_comms:
        members = _members(comm_id, len(blobs))
        if my_rank is not None and my_rank not in members:
            continue
        rows = []
        for r in members:
            if r < len(parsed) and comm_id in parsed[r]:
                rows.append((r, parsed[r][comm_id]))
            else:
                rows.append((r, {"sha": "<missing>", "lines": []}))
        shas = {sec["sha"] for _, sec in rows}
        if len(shas) <= 1:
            continue
        # locate the first differing step when step lines are present
        from mpi4jax_tpu.analysis.contracts import first_divergence

        lines_by_rank = []
        rank_of_row = {}
        for i, (r, sec) in enumerate(rows):
            rank_of_row[i] = r
            lines_by_rank.append(sec["lines"])
        div = (
            first_divergence(lines_by_rank)
            if any(lines_by_rank) else None
        )
        if div is not None:
            step, details = div
            details = {rank_of_row[i]: v for i, v in details.items()}
            raise CommContractError(divergence_message(
                step, details,
                deadline_hint=f"comm {comm_id.split(' ')[0]}",
            ))
        sides = "; ".join(
            f"rank {r}: sha={sec['sha']}" for r, sec in rows
        )
        raise CommContractError(
            f"T4J007: communication schedules diverge on comm "
            f"{comm_id.split(' ')[0]}: {sides} (schedules too large to "
            "inline; re-run with a smaller program to see the step)."
        )

    # Every per-comm section agrees.  Agreement is not safety: run the
    # match-engine simulator over the assembled whole-job schedule when
    # every rank shipped its full event export (the @sched rung of the
    # degrade ladder).  Orphan checking stays off here — a rank outside
    # some communicator legitimately never posts the matching op.
    if simulate and all("@sched" in p for p in parsed):
        from mpi4jax_tpu.analysis import simulate as _sim

        schedules = [
            _sim.schedule_from_events(
                p["@sched"]["events"], rank=r, world=len(blobs)
            )
            for r, p in enumerate(parsed)
        ]
        result = _sim.simulate(schedules, orphans=False)
        if result.findings:
            lines = "\n".join(f"  {f}" for f in result.findings)
            raise CommContractError(
                "cross-rank simulation of the exchanged schedules "
                f"found {len(result.findings)} hazard(s) — the "
                "schedules agree per-comm but cannot complete "
                f"together:\n{lines}",
                findings=result.findings,
            )


def _parse(blob):
    sections = {}
    current = None
    sched = None
    for line in blob.decode(errors="replace").splitlines():
        if line.startswith("@comm "):
            head = line[len("@comm "):]
            comm_id, _, rest = head.partition(" n=")
            sha = rest.partition("sha=")[2]
            current = {"sha": sha, "lines": []}
            sections[comm_id] = current
            sched = None
        elif line.startswith("@sched"):
            sched = {"events": []}
            sections["@sched"] = sched
            current = None
        elif sched is not None and line:
            try:
                sched["events"].append(json.loads(line))
            except ValueError:
                pass  # a malformed line degrades to fewer events
        elif current is not None and line:
            current["lines"].append(line)
    return sections


def _members(comm_id, world_size):
    part = comm_id.partition("members=")[2]
    if not part or part == "*":
        return list(range(world_size))
    try:
        return [int(tok) for tok in part.split(",") if tok != ""]
    except ValueError:
        return list(range(world_size))
