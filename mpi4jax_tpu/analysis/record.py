"""Trace-time event recording for the communication-contract analyzer.

While a :func:`mpi4jax_tpu.analysis.verify_comm` extraction is running,
every public communication op reports itself here from the shared
``publishes_token`` wrapper (ops/_core.py) — the one choke point every
op already passes through for profiling scopes, debug logging and
ambient-token publication.  Recording captures exactly the metadata the
op itself validated (comm, tag, pattern, dtype/shape, reduce op, root)
plus the identities of the incoming and outgoing Token objects, which
is what the chain rules (T4J001/T4J002) key on.

Zero overhead when no scope is active: the wrapper checks one
module-level flag before doing anything else (same contract as the
debug logging's ``config.debug_enabled()`` fast path).

Reentrancy: some public ops are implemented via other public ops
(``gather`` -> ``allgather`` and ``reduce`` -> ``allreduce`` on the
mesh backend, collectives.py).  Only the *outermost* call is recorded —
the schedule is the sequence of ops the user program issued, and the
inner call is an implementation detail that would otherwise make one
user step count twice.
"""

import inspect
import json
import threading
import traceback

__all__ = [
    "active",
    "dump_schedule",
    "event_to_dict",
    "load_schedule",
    "record_op",
    "recording",
    "take_events",
]

_state = threading.local()


def _stack():
    st = getattr(_state, "scopes", None)
    if st is None:
        st = _state.scopes = []
    return st


def active():
    """Fast check used by the op-layer hook (ops/_core.py)."""
    return bool(getattr(_state, "scopes", None))


class _Scope:
    def __init__(self):
        self.events = []
        self.seq = 0
        self.depth = 0  # >0 while inside a recorded op (reentrancy guard)
        # strong refs to every Token seen: events key chains on id(),
        # and a freed Token's address could otherwise be recycled for a
        # later one, aliasing distinct chain links across events
        self.tokens = []
        # id of the previous event's outgoing token, for linking ops
        # that chain through the ambient auto_tokenize context
        # (token=None resolves inside the op, invisible to the hook)
        self.last_out = None


class recording:
    """Context manager collecting CommEvents from the op layer."""

    def __enter__(self):
        self.scope = _Scope()
        _stack().append(self.scope)
        return self

    def __exit__(self, *exc):
        _stack().pop()
        return False

    @property
    def events(self):
        return list(self.scope.events)


def take_events():
    """Events of the innermost active scope (ordered)."""
    st = _stack()
    return list(st[-1].events) if st else []


# ------------------------------------------------------------- capture

# Parameter names the ops use, normalised to CommEvent fields.  The op
# signatures are bound with inspect so new ops with the same vocabulary
# are picked up without touching this module.
_DATA_PARAMS = ("x", "sendbuf")
_TAG_PARAMS = ("tag", "sendtag")


def record_op(name, fn, args, kwargs, out):
    """Called by ``publishes_token`` after a successful op call.

    ``out`` is the op's return value (used for the outgoing token
    identity and the staged-send bookkeeping).  Never raises: an
    analyzer bug must not take down the traced program — it degrades to
    an event with fewer fields.
    """
    st = _stack()
    if not st:
        return
    scope = st[-1]
    if scope.depth > 1:
        return  # nested public op: the outer event covers it
    try:
        ev = _build_event(scope, name, fn, args, kwargs, out)
    except Exception:
        ev = None
    if ev is not None:
        # Hardening for the reentrancy guard's edge cases: a composite
        # op returns its inner op's result verbatim, so if the inner
        # call ever escapes the depth guard (a raising __enter__, an op
        # calling another op outside its op_frame) the duplicate event
        # carries the SAME outgoing token and anchor as the one before
        # it.  A user program genuinely repeating an op always threads
        # a fresh token, so this collapses only true double-records.
        prev = scope.events[-1] if scope.events else None
        if (
            prev is not None
            and ev.token_out is not None
            and prev.token_out == ev.token_out
            and prev.kind == ev.kind
            and prev.src_info == ev.src_info
        ):
            return
        scope.events.append(ev)


class op_frame:
    """Marks 'inside a public op' for the reentrancy guard; used by
    ``publishes_token`` around the op body so nested public-op calls
    are attributed to the outermost one."""

    def __enter__(self):
        st = _stack()
        if st:
            st[-1].depth += 1
        return self

    def __exit__(self, *exc):
        st = _stack()
        if st:
            st[-1].depth -= 1
        return False


def _build_event(scope, name, fn, args, kwargs, out):
    from mpi4jax_tpu.analysis.contracts import CommEvent
    from mpi4jax_tpu.ops._core import Token, _ambient_stack, comm_key
    from mpi4jax_tpu.utils.validation import check_comm

    try:
        bound = inspect.signature(fn).bind(*args, **kwargs)
        bound.apply_defaults()
        params = dict(bound.arguments)
    except TypeError:
        params = dict(kwargs)

    comm = check_comm(params.get("comm"))
    token_in = params.get("token")
    token_out = _find_token(out)
    token_in_id = id(token_in) if isinstance(token_in, Token) else None
    if token_in_id is None and _ambient_stack():
        # token=None under auto_tokenize resolves to the ambient chain
        # inside the op; model the chain the ambient context maintains
        # by linking to the previous op's outgoing token, or the chain
        # rules would see every ambient op as an orphan
        token_in_id = scope.last_out
    if isinstance(token_in, Token):
        scope.tokens.append(token_in)
    if token_out is not None:
        scope.tokens.append(token_out)

    data = None
    for p in _DATA_PARAMS:
        if params.get(p) is not None:
            data = params[p]
            break

    rank = None
    if comm.backend != "mesh":
        try:
            rank = int(comm.rank())
        except Exception:
            rank = None

    tag = None
    for p in _TAG_PARAMS:
        if p in params:
            tag = _static_int(params[p])
            break

    # async request chain (T4J008, docs/async.md): identity of the
    # Request a nonblocking op returned and of the Request(s) a
    # wait/waitall/test consumed.  Strong refs join scope.tokens for
    # the same id-recycling reason.
    request_out = None
    requests_in = ()
    try:
        from mpi4jax_tpu.ops.async_ import Request

        rin = []
        for v in params.values():
            if isinstance(v, Request):
                rin.append(v)
            elif isinstance(v, (list, tuple)):
                rin.extend(i for i in v if isinstance(i, Request))
        out_req = None
        if isinstance(out, Request):
            out_req = out
        elif isinstance(out, tuple):
            for item in out:
                if isinstance(item, Request):
                    out_req = item
                    break
        scope.tokens.extend(rin)
        requests_in = tuple(id(r) for r in rin)
        if out_req is not None:
            scope.tokens.append(out_req)
            request_out = id(out_req)
    except Exception:
        pass

    ev = CommEvent(
        seq=scope.seq,
        kind=name,
        comm_key=comm_key(comm),
        backend=comm.backend,
        comm_size=int(comm.size),
        dtype=str(getattr(data, "dtype", "")) if data is not None else "",
        shape=tuple(getattr(data, "shape", ())) if data is not None else (),
        reduce_op=_op_name(params.get("op")),
        tag=tag,
        source=_spec(params.get("source")),
        dest=_spec(params.get("dest")),
        root=_static_int(params.get("root")),
        rank=rank,
        comm_ranks=_comm_ranks(comm),
        token_in=token_in_id,
        token_out=id(token_out) if token_out is not None else None,
        pending_out=_pending_summary(token_out),
        src_info=_user_frame(),
        request_out=request_out,
        requests_in=requests_in,
    )
    scope.seq += 1
    if token_out is not None:
        scope.last_out = id(token_out)
    return ev


def _find_token(out):
    from mpi4jax_tpu.ops._core import Token

    if isinstance(out, Token):
        return out
    if isinstance(out, tuple):
        for item in out:
            if isinstance(item, Token):
                return item
    return None


def _pending_summary(token):
    if token is None or not getattr(token, "pending_meta", ()):
        return ()
    return tuple(
        f"tag={m.tag} perm={m.perm} {m.dtype}[{'x'.join(map(str, m.shape))}]"
        for m in token.pending_meta
    )


def _comm_ranks(comm):
    """World ranks of the comm's members when the backend knows them
    (ProcComm carries .ranks); empty means 'all ranks' to the
    fingerprint pass."""
    ranks = getattr(comm, "ranks", None)
    if ranks is None:
        return ()
    try:
        return tuple(int(r) for r in ranks)
    except (TypeError, ValueError):
        return ()


def _op_name(op):
    if op is None:
        return ""
    name = getattr(op, "name", None)
    if name is None:
        return str(op)
    return f"user:{name}" if getattr(op, "is_user", False) else str(name)


def _static_int(value):
    import numpy as np

    if isinstance(value, (int, np.integer)) and not isinstance(
        value, (bool, np.bool_)
    ):
        return int(value)
    return None


def _spec(spec):
    """Normalise a p2p partner spec for the event record."""
    import numpy as np

    if spec is None:
        return None
    if isinstance(spec, (int, np.integer)) and not isinstance(
        spec, (bool, np.bool_)
    ):
        return "ANY" if int(spec) == -1 else int(spec)
    if callable(spec):
        return "callable"
    if isinstance(spec, (list, tuple)):
        try:
            return tuple(sorted((int(s), int(d)) for s, d in spec))
        except (TypeError, ValueError):
            return "static"
    import jax

    if isinstance(spec, jax.core.Tracer):
        return "traced"
    return "static"


_LIB_MARKERS = (
    "mpi4jax_tpu/ops",
    "mpi4jax_tpu/analysis",
    "mpi4jax_tpu/parallel",
    "mpi4jax_tpu/serving",
    "jax/",
)


def _user_frame():
    """Innermost stack frame outside the library — the finding anchor."""
    for fr in reversed(traceback.extract_stack(limit=40)):
        fname = fr.filename.replace("\\", "/")
        if any(m in fname for m in _LIB_MARKERS):
            continue
        if "/site-packages/" in fname or fname.startswith("<"):
            continue
        return f"{fr.filename}:{fr.lineno}"
    return ""


# ------------------------------------------------------ schedule export

# The JSON schedule format consumed by analysis/simulate.py and
# ``t4j-verify --traces``: one object per file with a format tag and
# the event list.  Every value is a JSON scalar/array, so a trace
# recorded on a TPU pod replays on any machine (including old-jax
# containers where this module loads via the test stub loader).
_SCHEDULE_FORMAT = "t4j-schedule-v1"

_EXPORT_FIELDS = (
    "seq", "kind", "comm_key", "backend", "comm_size", "dtype",
    "shape", "reduce_op", "tag", "source", "dest", "root", "rank",
    "comm_ranks", "src_info", "request_out", "requests_in",
)


def event_to_dict(ev):
    """A CommEvent as a plain JSON-ready dict.

    Token identities are deliberately dropped — they are process-local
    addresses, meaningless across ranks or runs.  The rank's effective
    wire mode is stamped onto compression-eligible steps (f32 SUM
    reductions — the same gate as ``step_signature``) so the simulator
    can run the cross-rank T4J014 check offline.
    """
    from mpi4jax_tpu.analysis.contracts import _effective_wire_dtype

    d = {}
    for f in _EXPORT_FIELDS:
        v = getattr(ev, f, None)
        if isinstance(v, tuple):
            v = list(v)
        d[f] = v
    if ev.reduce_op == "sum" and ev.dtype == "float32":
        d["wire"] = _effective_wire_dtype()
    return d


def dump_schedule(events, path, rank=None):
    """Write one rank's recorded events as a JSON schedule file."""
    doc = {
        "format": _SCHEDULE_FORMAT,
        "rank": rank,
        "events": [event_to_dict(ev) for ev in events],
    }
    with open(path, "w") as fh:
        json.dump(doc, fh, indent=None, separators=(",", ":"))
        fh.write("\n")


def load_schedule(path):
    """Read a schedule file back as ``(rank, [event dicts])``.

    Returns plain dicts (not CommEvents): the simulator duck-types its
    events, and reconstructing the frozen dataclass would drag in
    fields the export deliberately dropped.  Raises ``ValueError`` on a
    wrong format tag so ``t4j-verify`` can exit 2 with a real message
    instead of a KeyError.
    """
    with open(path) as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict) or doc.get("format") != _SCHEDULE_FORMAT:
        raise ValueError(
            f"{path}: not a {_SCHEDULE_FORMAT} schedule file "
            f"(format={doc.get('format')!r})"
            if isinstance(doc, dict)
            else f"{path}: not a JSON object"
        )
    events = doc.get("events")
    if not isinstance(events, list):
        raise ValueError(f"{path}: 'events' must be a list")
    return doc.get("rank"), events
