"""Closed-jaxpr walker for the communication-contract analyzer.

Walks a traced program's jaxpr, recursing into every sub-jaxpr
(``pjit``/``scan``/``while``/``cond``/``custom_*`` — discovered
generically from eqn params, the same recursion the reference's
auto-tokenize interpreter performs over control flow), and provides the
two things the Python-level event recorder cannot see:

* **communication eqns in lowered form** — every public op wraps itself
  in ``jax.named_scope("mpi4jax_tpu.<op>")`` (ops/_core.py), so its
  lowered eqns carry that scope on their ``source_info.name_stack``
  regardless of backend (mesh psum/ppermute, proc ffi_call/io_callback).
  Consecutive eqns under one scope collapse to one *op occurrence*.
* **rank-provenance of branch predicates** — outputs of ``axis_index``
  (the mesh backend's ``comm.rank()``) are tainted and the taint is
  propagated through eqns and into sub-jaxprs, so a ``cond`` whose
  predicate derives from the rank is recognisable (rule T4J005).

Rank-dependent ``cond`` is only a contract violation when the branches
*communicate differently*: uniform branches (same op occurrences, same
shapes/dtypes/axes) are legal — e.g. masking a halo edge.  Divergent
branch schedules under a rank-derived predicate are exactly the
"collective matching depends on control flow" bug class MPI-Checker
flags statically; on the proc backend the same bug class is per-process
Python control flow, invisible to a single trace, which is what the
cross-rank fingerprint pass (analysis/fingerprint.py) exists for.
"""

from mpi4jax_tpu.analysis.contracts import Finding

__all__ = ["walk_comm_jaxpr", "OpOccurrence"]

_SCOPE_PREFIX = "mpi4jax_tpu."


class OpOccurrence:
    """One communication op as seen in the lowered jaxpr.

    ``n_eqns`` counts the lowered eqns merged into this occurrence.  It
    is part of the comparison signature: two *adjacent* calls of one op
    from the same source line are indistinguishable by scope and
    callsite, but they double the eqn run — identical programs lower to
    identical eqn counts, so a count mismatch means a schedule mismatch.
    """

    def __init__(self, op, detail, src_info, path):
        self.op = op            # "allreduce", "send", ...
        self.detail = detail    # hashable descriptor for comparisons
        self.src_info = src_info
        self.path = path        # control-flow nesting, e.g. ("cond[0]",)
        self.n_eqns = 1

    def signature(self):
        return (self.op, self.detail, self.n_eqns)

    def __repr__(self):
        return f"OpOccurrence({self.op}, {self.detail}, n={self.n_eqns})"


def walk_comm_jaxpr(closed_jaxpr):
    """Returns ``(occurrences, findings)`` for a closed jaxpr.

    ``occurrences`` is the flat, program-ordered list of communication
    op occurrences (loop bodies contribute once — the schedule is
    symbolic); ``findings`` currently carries rule T4J005.
    """
    occurrences = []
    findings = []
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    _walk(jaxpr, set(), (), occurrences, findings)
    return occurrences, findings


def _walk(jaxpr, tainted_invars, path, occurrences, findings):
    """``tainted_invars``: set of this jaxpr's invars carrying
    rank-derived values (object identity of Var)."""
    tainted = set(tainted_invars)
    current_scope = None
    current_occ = None  # the run's own occurrence — recursion into a
    #                     sub-jaxpr may append nested occurrences, so
    #                     occurrences[-1] is not necessarily it
    for eqn in jaxpr.eqns:
        prim = getattr(eqn.primitive, "name", str(eqn.primitive))
        # -- taint seeding and propagation ------------------------------
        if prim == "axis_index":
            tainted.update(eqn.outvars)
        elif any(_is_tainted(v, tainted) for v in eqn.invars):
            tainted.update(eqn.outvars)

        # -- communication-op occurrence collapse -----------------------
        # one public op lowers to several adjacent eqns sharing the
        # same scope; collapse them to one occurrence.  The user call
        # site is part of the key so two back-to-back calls of the
        # same op (identical scope strings) stay two occurrences.
        scope = _comm_scope(eqn)
        if scope is not None:
            occ_key = (scope, _src(eqn))
            if occ_key != current_scope:
                current_occ = OpOccurrence(
                    op=scope.split(".", 1)[1],
                    detail=_eqn_detail(eqn),
                    src_info=_src(eqn),
                    path=path,
                )
                occurrences.append(current_occ)
            else:
                current_occ.n_eqns += 1
            current_scope = occ_key
        else:
            current_scope = None
            current_occ = None

        # -- rank-dependent cond (T4J005) -------------------------------
        if prim == "cond":
            branches = _branches(eqn)
            pred_tainted = bool(eqn.invars) and _is_tainted(
                eqn.invars[0], tainted
            )
            branch_occs = []
            for bi, br in enumerate(branches):
                sub_occ = []
                sub_taint = _map_subinvars(br, eqn.invars[1:], tainted)
                _walk(br, sub_taint, path + (f"cond[{bi}]",),
                      sub_occ, findings)
                branch_occs.append(sub_occ)
                occurrences.extend(sub_occ)
            if pred_tainted and _branches_disagree(branch_occs):
                where = _first_comm_src(branch_occs)
                findings.append(Finding(
                    rule="T4J005",
                    message=(
                        "cond predicate derives from the communicator "
                        "rank (axis_index) and its branches issue "
                        "different communication schedules: "
                        f"{_describe_branches(branch_occs)}. Under SPMD "
                        "every device must issue the same collective "
                        "sequence; hoist the collective out of the "
                        "branch or make the branches communicate "
                        "identically."
                    ),
                    src_info=where,
                ))
            continue  # sub-jaxprs already walked

        # -- generic recursion into sub-jaxprs --------------------------
        for sub in _sub_jaxprs(eqn):
            any_taint = any(_is_tainted(v, tainted) for v in eqn.invars)
            if prim in _POSITIONAL_PRIMS:
                # call-like primitives pass their operands through to
                # the sub-jaxpr positionally (pjit exactly; shard_map /
                # custom_partitioning may curry constants in front, so
                # align the zip at the TAIL) — precise mapping keeps an
                # untainted shard_map operand untainted inside, so a
                # cond on plain data inside shard_map does not
                # false-positive T4J005 just because axis_index was
                # used elsewhere in the call
                sub_taint = _tail_align_taint(sub, eqn.invars, tainted)
            else:
                sub_taint = (
                    set(sub.invars) if any_taint else set()
                )  # conservative: taint everywhere if any operand is
            #      tainted (scan/while reorder operands into carries)
            _walk(sub, sub_taint, path + (prim,), occurrences, findings)
    return tainted


# Primitives whose sub-jaxpr invars line up positionally with the eqn
# invars.  shard_map is the ROADMAP item-1 target: a collective under a
# rank-dependent branch INSIDE shard_map must still raise T4J005, which
# needs taint to flow through the shard_map call boundary (axis_index
# inside the body is also seeded directly — both routes must work).
_POSITIONAL_PRIMS = frozenset({
    "pjit", "shard_map", "custom_partitioning", "closed_call",
    "core_call", "xla_call",
})


def _tail_align_taint(sub_jaxpr, outer_invars, tainted):
    """Map outer operand taint onto sub-jaxpr invars, aligning at the
    tail (leading sub invars with no outer counterpart — lifted
    constants — stay untainted)."""
    sub_in = list(sub_jaxpr.invars)
    outer = list(outer_invars)
    sub_taint = set()
    for inner, out_v in zip(reversed(sub_in), reversed(outer)):
        if _is_tainted(out_v, tainted):
            sub_taint.add(inner)
    return sub_taint


def _is_tainted(var, tainted):
    # Literals are never tainted; Var identity is unique per jaxpr
    return not hasattr(var, "val") and var in tainted


def _comm_scope(eqn):
    """The innermost ``mpi4jax_tpu.<op>`` segment of the eqn's name
    stack, or None."""
    try:
        stack = str(eqn.source_info.name_stack)
    except Exception:
        return None
    hit = None
    for seg in stack.split("/"):
        if seg.startswith(_SCOPE_PREFIX):
            hit = seg
    return hit


def _eqn_detail(eqn):
    """Hashable descriptor of a comm eqn for branch comparison: lowered
    primitive, operand/result types, and the collective-identity params
    (axes, permutation, groups) when present."""
    prim = getattr(eqn.primitive, "name", str(eqn.primitive))
    avals = tuple(
        str(getattr(v, "aval", "?")) for v in (*eqn.invars, *eqn.outvars)
    )
    params = []
    for key in ("axes", "axis_name", "perm", "axis_index_groups", "op",
                "root", "tag", "source", "dest", "comm"):
        if key in eqn.params:
            params.append((key, _hashable(eqn.params[key])))
    return (prim, avals, tuple(params))


def _hashable(v):
    try:
        hash(v)
        return v
    except TypeError:
        return str(v)


def _src(eqn):
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        if frame is not None:
            return f"{frame.file_name}:{frame.start_line}"
    except Exception:
        pass
    return ""


def _branches(eqn):
    out = []
    for br in eqn.params.get("branches", ()):
        out.append(getattr(br, "jaxpr", br))
    return out


def _map_subinvars(sub_jaxpr, outer_operands, tainted):
    """Positional taint mapping from a cond's operands onto a branch
    jaxpr's invars."""
    sub_taint = set()
    for outer, inner in zip(outer_operands, sub_jaxpr.invars):
        if _is_tainted(outer, tainted):
            sub_taint.add(inner)
    return sub_taint


def _sub_jaxprs(eqn):
    """Every jaxpr nested in an eqn's params (pjit's ``jaxpr``, scan's
    ``jaxpr``, while's ``cond_jaxpr``/``body_jaxpr``, custom_jvp's
    ``call_jaxpr``, ...), discovered generically so new primitives keep
    working."""
    subs = []
    for value in eqn.params.values():
        subs.extend(_as_jaxprs(value))
    return subs


def _as_jaxprs(value):
    inner = getattr(value, "jaxpr", None)
    if inner is not None and hasattr(inner, "eqns"):
        return [inner]
    if hasattr(value, "eqns"):
        return [value]
    if isinstance(value, (tuple, list)):
        out = []
        for v in value:
            out.extend(_as_jaxprs(v))
        return out
    return []


def _branches_disagree(branch_occs):
    sigs = [tuple(o.signature() for o in occs) for occs in branch_occs]
    return len(set(sigs)) > 1


def _first_comm_src(branch_occs):
    for occs in branch_occs:
        for o in occs:
            if o.src_info:
                return o.src_info
    return ""


def _describe_branches(branch_occs):
    return "; ".join(
        f"branch {i}: [{', '.join(o.op for o in occs) or 'no comm'}]"
        for i, occs in enumerate(branch_occs)
    )
