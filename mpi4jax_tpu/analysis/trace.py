"""Schedule extraction: trace a program and capture its communication
schedule without executing it.

``extract_schedule`` traces the callable with ``jax.make_jaxpr`` under
an active recording scope (analysis/record.py), so every public op the
program issues reports one :class:`~.contracts.CommEvent` in program
order, and the closed jaxpr is retained for the control-flow pass
(analysis/jaxpr_walk.py).  No backend I/O happens: tracing stops at
abstract values, exactly like ``jax.eval_shape``.

Contract violations the op layer rejects eagerly (unmatched recv,
shape/dtype mismatch against a staged send, out-of-range roots/peers,
non-permutation patterns...) raise *during* tracing; they are caught
here and converted to findings with stable rule IDs
(contracts.classify_trace_error) so one lint run reports them uniformly
alongside the schedule rules instead of dying on the first one.
Unrecognised exceptions propagate — a bug in the traced program is not
a lint finding.
"""

import traceback

from mpi4jax_tpu.analysis import record
from mpi4jax_tpu.analysis.contracts import Finding, classify_trace_error

__all__ = ["extract_schedule", "Extraction"]


class Extraction:
    """Result of tracing a program for analysis."""

    def __init__(self, events, closed_jaxpr, error_findings, notes=()):
        self.events = events
        self.closed_jaxpr = closed_jaxpr
        self.error_findings = error_findings
        self.notes = list(notes)


def extract_schedule(fn, args=(), kwargs=None):
    """Trace ``fn(*args, **kwargs)`` and extract its comm schedule.

    Returns an :class:`Extraction`.  The traced callable's return value
    is reduced to its jax-typeable leaves, so programs returning
    auxiliary Python objects (e.g. a :class:`~mpi4jax_tpu.Status`)
    still trace.
    """
    import jax

    kwargs = dict(kwargs or {})

    def thunk():
        out = fn(*args, **kwargs)
        leaves = jax.tree_util.tree_leaves(out)
        return [
            leaf for leaf in leaves
            if hasattr(leaf, "dtype") or isinstance(leaf, (int, float))
        ]

    error_findings = []
    closed = None
    notes = []
    with record.recording() as rec:
        try:
            closed = jax.make_jaxpr(thunk)()
        except Exception as exc:
            rule = classify_trace_error(exc)
            if rule is None:
                raise
            error_findings.append(Finding(
                rule=rule,
                message=str(exc),
                src_info=_exc_user_frame(exc),
            ))
        events = rec.events

    if closed is not None and not events:
        # a cached jax.jit inside fn can satisfy the trace without
        # re-running the Python body, hiding ops from the recorder;
        # surface that instead of silently reporting a clean schedule
        from mpi4jax_tpu.analysis.jaxpr_walk import walk_comm_jaxpr

        occurrences, _ = walk_comm_jaxpr(closed)
        if occurrences:
            notes.append(
                f"recorded 0 op events but the jaxpr contains "
                f"{len(occurrences)} communication op occurrence(s): a "
                "pre-traced jax.jit cache entry was reused. Wrap the "
                "underlying (un-jitted) function, or verify before its "
                "first execution."
            )
    return Extraction(events, closed, error_findings, notes)


_LIB_MARKERS = ("mpi4jax_tpu/ops", "mpi4jax_tpu/analysis", "jax/",
                "/site-packages/")


def _exc_user_frame(exc):
    tb = getattr(exc, "__traceback__", None)
    best = ""
    for fr in traceback.extract_tb(tb):
        fname = fr.filename.replace("\\", "/")
        if any(m in fname for m in _LIB_MARKERS) or fname.startswith("<"):
            continue
        best = f"{fr.filename}:{fr.lineno}"
    return best
