"""Cross-rank schedule simulator — rules T4J010..T4J014 (``t4j-verify``).

The fingerprint pass (analysis/fingerprint.py) catches schedules that
*diverge*; this module covers the complementary blind spot: schedules
that AGREE step for step and still deadlock or complete
nondeterministically when the ranks' schedules meet on the wire.  Given
one recorded schedule per rank (the PR-4 recorder's events, a JSON
export from :func:`..record.dump_schedule`, or N per-rank schedules
specialised from one SPMD trace via :func:`specialize_spmd`), it
symbolically executes them under the runtime's actual semantics:

* **per-rank in-order submission** — each rank posts its ops in program
  order and a blocking op stops the rank (the engine's
  MPI_THREAD_SERIALIZED cross-comm ordering: a blocked op on comm A
  blocks later ops on comm B too);
* **posted-order receive matching** — among receives that could match
  one message the earliest-posted wins, and messages between a fixed
  (sender, receiver) pair never overtake each other (the PR-7
  ``frame_matches`` contract);
* **eager/rendezvous sends** — sends at or under ``eager_bytes``
  buffer and complete immediately (the wire path stages small
  payloads); larger sends block until matched (TCP backpressure —
  the classic MPI eager-threshold semantics, and the reason send/send
  cycles only deadlock above the threshold);
* **nonblocking requests** — ``isend``/``irecv``/``iallreduce``/
  ``ireduce_scatter`` post immediately and their rank proceeds; the
  ``wait``/``waitall`` consuming the request blocks until completion;
* **collectives as all-member sync points** — the k-th collective a
  rank issues on a comm joins the comm's k-th slot; the slot completes
  when every member has arrived with an agreeing op signature.

Wildcard receives (``ANY_SOURCE``/``ANY_TAG``) are the only source of
nondeterminism, so they are the only branch points: the exploration is
a bounded DPOR-style DFS that forks the match engine once per visible
candidate sender whenever a wildcard receive could match more than one
message, capped at ``max_states`` explored states (the cap is reported,
never silent).  Deterministic matches are confluent and applied
greedily.

Like the rest of the analyzer's pure cores (contracts.py, tuning/,
telemetry/), this module imports nothing from jax or the package at
module scope except the contracts rule core, so it loads on old-jax
containers via the stub-parent loader (tests/analysis/conftest.py) and
events are duck-typed (:class:`~.contracts.CommEvent` or plain dicts).
"""

from mpi4jax_tpu.analysis.contracts import Finding, dedupe_findings

__all__ = [
    "DEFAULT_EAGER_BYTES",
    "DEFAULT_MAX_STATES",
    "SimResult",
    "schedule_from_events",
    "simulate",
    "specialize_spmd",
]

# sends at or under this many payload bytes complete eagerly (buffered
# on the wire path); larger sends are rendezvous and block until
# matched — the same order of magnitude as classic MPI eager limits
DEFAULT_EAGER_BYTES = 65536
DEFAULT_MAX_STATES = 4096

_ITEMSIZE = {
    "float32": 4, "float64": 8, "int8": 1, "int16": 2, "int32": 4,
    "int64": 8, "uint8": 1, "uint16": 2, "uint32": 4, "uint64": 8,
    "bool": 1, "complex64": 8, "complex128": 16, "float16": 2,
    "bfloat16": 2,
}

_SEND_KINDS = ("send", "isend")
_RECV_KINDS = ("recv", "irecv")
_SENDRECV_KINDS = ("sendrecv", "sendrecv_multi")
_WAIT_KINDS = ("wait", "waitall")
_ICOLL_KINDS = ("iallreduce", "ireduce_scatter")
_NOOP_KINDS = ("test",)


def _get(ev, name, default=None):
    if isinstance(ev, dict):
        return ev.get(name, default)
    return getattr(ev, name, default)


def _comm_id(ev):
    key = _get(ev, "comm_key")
    if isinstance(key, (tuple, list)):
        return "/".join(str(p) for p in key)
    return str(key)


def _payload_bytes(ev):
    shape = _get(ev, "shape") or ()
    n = 1
    for d in shape:
        n *= int(d)
    return n * _ITEMSIZE.get(str(_get(ev, "dtype") or ""), 4)


def _norm_spec(spec):
    """JSON round-trips turn pair tuples into lists; normalise back."""
    if isinstance(spec, list):
        try:
            return tuple(sorted((int(s), int(d)) for s, d in spec))
        except (TypeError, ValueError):
            return "static"
    return spec


def _resolve_pairs(spec, rank, want):
    """Resolve a permutation pair spec for ``rank``: ``want="dest"``
    returns the d with s==rank, ``want="source"`` the s with d==rank,
    or None when the rank has no pair (a non-periodic edge)."""
    for s, d in spec:
        if want == "dest" and int(s) == rank:
            return int(d)
        if want == "source" and int(d) == rank:
            return int(s)
    return None


class _Op:
    """One normalised schedule step of one rank."""

    __slots__ = ("rank", "idx", "kind", "cat", "comm", "members",
                 "dest", "source", "tag", "nbytes", "req", "reqs",
                 "src_info", "sig", "wire", "unknown_peer",
                 "dtype", "redop")

    def __repr__(self):
        return f"_Op(r{self.rank}#{self.idx} {self.sig})"


def _op_sig(ev, kind):
    bits = [kind, _comm_id(ev)]
    dtype, shape = _get(ev, "dtype"), _get(ev, "shape")
    if dtype or shape:
        bits.append(f"{dtype}[{'x'.join(str(d) for d in shape or ())}]")
    red = _get(ev, "reduce_op")
    if red:
        bits.append(f"op={red}")
    root = _get(ev, "root")
    if root is not None:
        bits.append(f"root={root}")
    return " ".join(bits)


def schedule_from_events(events, rank=None, world=None, wire=None):
    """Normalise one rank's recorded events into simulator ops.

    ``rank`` overrides the per-event rank (needed for mesh events,
    where the rank is a traced value and records as None); ``world``
    supplies the default member set for comms whose membership is not
    recorded; ``wire`` overrides the rank's compressed-collective wire
    mode (else each event's exported ``wire`` field is used).
    """
    ops = []
    for idx, ev in enumerate(events):
        kind = str(_get(ev, "kind") or "")
        op = _Op()
        op.rank = _get(ev, "rank") if rank is None else rank
        op.idx = idx
        op.kind = kind
        op.comm = _comm_id(ev)
        members = tuple(_get(ev, "comm_ranks") or ())
        if not members:
            size = int(_get(ev, "comm_size") or 1)
            members = tuple(range(size if world is None else world))
        op.members = members
        op.dest = _norm_spec(_get(ev, "dest"))
        op.source = _norm_spec(_get(ev, "source"))
        op.tag = _get(ev, "tag")
        op.nbytes = _payload_bytes(ev)
        op.req = _get(ev, "request_out")
        op.reqs = tuple(_get(ev, "requests_in") or ())
        op.src_info = str(_get(ev, "src_info") or "")
        op.sig = _op_sig(ev, kind)
        op.wire = wire if wire is not None else _get(ev, "wire")
        op.unknown_peer = False
        op.dtype = str(_get(ev, "dtype") or "")
        op.redop = str(_get(ev, "reduce_op") or "")

        if kind in _SEND_KINDS or kind in _RECV_KINDS \
                or kind in _SENDRECV_KINDS:
            op.cat = ("sendrecv" if kind in _SENDRECV_KINDS
                      else "send" if kind in _SEND_KINDS else "recv")
            for attr in ("dest", "source"):
                spec = getattr(op, attr)
                if isinstance(spec, tuple) and op.rank is not None:
                    setattr(op, attr, _resolve_pairs(
                        spec, op.rank,
                        "dest" if attr == "dest" else "source"))
                elif spec in ("traced", "callable", "static"):
                    op.unknown_peer = True
        elif kind in _WAIT_KINDS:
            op.cat = "wait"
        elif kind in _NOOP_KINDS:
            op.cat = "noop"
        elif kind in _ICOLL_KINDS:
            op.cat = "icoll"
        elif len(op.members) > 1:
            # everything else with a multi-member comm is an all-member
            # sync point (allreduce, bcast, barrier, halo composites...)
            op.cat = "coll"
        else:
            op.cat = "noop"
        ops.append(op)
    return ops


def specialize_spmd(events, world=None):
    """Split one SPMD trace into per-rank schedules, one group per
    communicator.

    Under SPMD every rank runs the same program, so rank r's schedule
    is the trace itself with ``rank=r`` and permutation pair specs
    resolved per rank.  Membership of sub-communicators (grid axes) in
    the world is not recorded on the mesh backend, so each comm is
    simulated in its own ``comm_size``-rank group — sound for a single
    trace, since cross-comm ordering inversions need rank-divergent
    programs, which one trace cannot express (per-rank MPMD traces go
    through :func:`simulate` whole).  Returns a list of
    ``(comm_id, [rank0_ops, rank1_ops, ...])`` groups.
    """
    by_comm = {}
    for ev in events:
        by_comm.setdefault(_comm_id(ev), []).append(ev)
    groups = []
    for comm_id, evs in by_comm.items():
        size = max(int(_get(ev, "comm_size") or 1) for ev in evs)
        if size <= 1:
            continue
        schedules = []
        for r in range(size):
            ops = schedule_from_events(evs, rank=r, world=size)
            # drop p2p halves the pattern gives this rank no part in
            # (non-periodic edges resolve to None)
            ops = [op for op in ops
                   if not (op.cat == "send" and op.dest is None)
                   and not (op.cat == "recv" and op.source is None)]
            for i, op in enumerate(ops):
                op.idx = i
            schedules.append(ops)
        groups.append((comm_id, schedules))
    return groups


class SimResult:
    """Outcome of one :func:`simulate` run."""

    def __init__(self, findings, outcomes, states, truncated, notes):
        self.findings = findings
        self.outcomes = outcomes        # distinct terminal match maps
        self.states = states            # states explored
        self.truncated = truncated      # hit max_states
        self.notes = list(notes)

    @property
    def ok(self):
        return not self.findings

    def __repr__(self):
        return (f"SimResult(findings={len(self.findings)}, "
                f"outcomes={len(self.outcomes)}, states={self.states}, "
                f"truncated={self.truncated})")


# ------------------------------------------------------------ match engine


class _State:
    """One node of the exploration: every mutable matching fact."""

    __slots__ = ("pc", "blocked", "sends", "recvs", "reqs_done",
                 "slots", "coll_count", "matches", "post_ctr", "dead")

    @classmethod
    def initial(cls, n_ranks):
        st = cls()
        st.pc = [0] * n_ranks
        st.blocked = [None] * n_ranks   # rank -> blocking descriptor
        st.sends = []                   # posted send records (dicts)
        st.recvs = []                   # posted recv records (dicts)
        st.reqs_done = set()
        st.slots = {}                   # comm -> [slot dicts]
        st.coll_count = {}              # (comm, rank) -> arrivals so far
        st.matches = {}                 # (rank, idx) -> (sender, tag)
        st.post_ctr = 0
        st.dead = None                  # finding that killed the branch
        return st

    def clone(self):
        st = _State()
        st.pc = list(self.pc)
        st.blocked = list(self.blocked)
        st.sends = [dict(s) for s in self.sends]
        st.recvs = [dict(r) for r in self.recvs]
        st.reqs_done = set(self.reqs_done)
        st.slots = {
            c: [{"arrived": dict(s["arrived"]), "done": s["done"]}
                for s in slots]
            for c, slots in self.slots.items()
        }
        st.coll_count = dict(self.coll_count)
        st.matches = dict(self.matches)
        st.post_ctr = self.post_ctr
        st.dead = self.dead
        return st


def _anchor(op):
    return f" at {op.src_info}" if op.src_info else ""


def simulate(schedules, *, eager_bytes=DEFAULT_EAGER_BYTES,
             max_states=DEFAULT_MAX_STATES, orphans=True,
             max_findings=16):
    """Symbolically execute ``schedules`` (one op list per rank, from
    :func:`schedule_from_events`) and return a :class:`SimResult`.

    ``orphans=False`` skips the whole-job T4J012 envelope pre-pass —
    used by the fingerprint exchange, where a partial-world schedule
    set would make absence-of-a-recv a false positive.
    """
    schedules = [
        s if (s and isinstance(s[0], _Op)) else
        schedule_from_events(s, rank=r, world=len(schedules))
        for r, s in enumerate(schedules)
    ]
    findings = []
    notes = []
    if orphans:
        findings += _check_orphans(schedules)
    findings += _check_wire_mix(schedules)
    unknowable = sorted({
        op.comm for ops in schedules for op in ops if op.unknown_peer
    })
    if unknowable:
        notes.append(
            "p2p routing on comm(s) %s is dynamic "
            "(traced/callable partner): match simulation skipped for "
            "those ops" % ", ".join(unknowable))

    # --------------------------------------------- bounded DFS exploration
    outcomes = {}           # frozenset(match items) -> representative
    deadlocks = []          # (finding, match-map) per stuck terminal
    states = 0
    truncated = False
    stack = [_State.initial(len(schedules))]
    while stack:
        if states >= max_states:
            truncated = True
            break
        st = stack.pop()
        states += 1
        choice = _run_to_fixpoint(st, schedules, eager_bytes)
        if st.dead is not None:
            deadlocks.append((st.dead, dict(st.matches)))
            continue
        if choice is not None:
            recv, cands = choice
            for cand in cands:
                branch = st.clone()
                _apply_match(branch, _find_record(branch.recvs, recv),
                             _find_record(branch.sends, cand),
                             schedules, eager_bytes)
                stack.append(branch)
            continue
        if _all_done(st, schedules):
            key = frozenset(st.matches.items())
            outcomes.setdefault(key, dict(st.matches))
        else:
            f = _deadlock_finding(st, schedules)
            deadlocks.append((f, dict(st.matches)))

    for f, _m in deadlocks:
        if f is not None:
            findings.append(f)
    if len(outcomes) > 1:
        findings.append(_nondet_finding(outcomes, schedules))
    elif outcomes and deadlocks:
        findings.append(Finding(
            rule="T4J011",
            message=(
                "wildcard nondeterminism: one ANY_SOURCE/ANY_TAG match "
                "order completes the job while another deadlocks "
                "(see the T4J010/T4J013 finding for the blocking "
                "order) — the racing receives make completion "
                "order-dependent."
            ),
        ))
    if truncated:
        notes.append(
            f"exploration capped at max_states={max_states}: wildcard "
            "branches beyond the cap were not explored (findings are "
            "sound but possibly incomplete)")
    findings = dedupe_findings(findings)[:max_findings]
    return SimResult(findings, list(outcomes.values()), states,
                     truncated, notes)


def _find_record(records, rec):
    """Locate ``rec``'s copy in a cloned state by its post id."""
    for r in records:
        if r["post"] == rec["post"]:
            return r
    raise KeyError(rec["post"])


def _all_done(st, schedules):
    return all(
        st.blocked[r] is None and st.pc[r] >= len(schedules[r])
        for r in range(len(schedules))
    )


def _run_to_fixpoint(st, schedules, eager_bytes):
    """Advance every rank and apply deterministic matches until no
    progress; returns a wildcard choice point ``(recv_rec, [send_rec,
    ...])`` when that is the only way forward, else None."""
    progress = True
    while progress and st.dead is None:
        progress = False
        for r in range(len(schedules)):
            if _advance_rank(st, r, schedules, eager_bytes):
                progress = True
            if st.dead is not None:
                return None
        while True:
            det = _deterministic_match(st)
            if det is None:
                break
            recv, send = det
            _apply_match(st, recv, send, schedules, eager_bytes)
            progress = True
    if st.dead is not None:
        return None
    return _wildcard_choice(st)


def _advance_rank(st, r, schedules, eager_bytes):
    """Post ops for rank ``r`` until it blocks or its schedule ends.
    Returns True when anything happened."""
    moved = False
    while st.dead is None:
        blk = st.blocked[r]
        if blk is not None:
            if not _try_unblock(st, r, blk):
                return moved
            st.blocked[r] = None
            moved = True
        ops = schedules[r]
        if st.pc[r] >= len(ops):
            return moved
        op = ops[st.pc[r]]
        st.pc[r] += 1
        moved = True
        if op.cat == "noop" or op.unknown_peer:
            continue
        if op.cat == "send":
            if op.dest is None:
                # MPI_PROC_NULL semantics (non-periodic halo edge):
                # the send half is a no-op
                if op.req is not None:
                    st.reqs_done.add(op.req)
                continue
            rec = _post_send(st, op, eager_bytes)
            if op.kind == "send" and not rec["completed"]:
                st.blocked[r] = ("send", op, rec["post"])
        elif op.cat == "recv":
            rec = _post_recv(st, op)
            if op.kind == "recv":
                st.blocked[r] = ("recv", op, rec["post"])
        elif op.cat == "sendrecv":
            spost = rpost = None
            if op.dest is not None:
                spost = _post_send(st, op, eager_bytes)["post"]
            if op.source is not None:
                rpost = _post_recv(st, op)["post"]
            if spost is not None or rpost is not None:
                st.blocked[r] = ("sendrecv", op, spost, rpost)
        elif op.cat == "wait":
            remaining = tuple(q for q in op.reqs
                              if q not in st.reqs_done)
            if remaining:
                st.blocked[r] = ("wait", op, remaining)
        elif op.cat in ("coll", "icoll"):
            slot_i = _arrive_collective(st, op)
            if st.dead is not None:
                return moved
            slot = st.slots[op.comm][slot_i]
            if not slot["done"] and op.cat == "coll":
                st.blocked[r] = ("coll", op, slot_i)
    return moved


def _post_send(st, op, eager_bytes):
    eager = op.nbytes <= eager_bytes
    rec = {
        "post": st.post_ctr, "rank": op.rank, "idx": op.idx,
        "comm": op.comm, "dest": op.dest, "tag": op.tag,
        "matched": False, "completed": eager, "req": op.req,
        "sig": op.sig, "src_info": op.src_info, "nbytes": op.nbytes,
    }
    st.post_ctr += 1
    st.sends.append(rec)
    if eager and op.req is not None:
        st.reqs_done.add(op.req)
    return rec


def _post_recv(st, op):
    rec = {
        "post": st.post_ctr, "rank": op.rank, "idx": op.idx,
        "comm": op.comm, "source": op.source, "tag": op.tag,
        "matched": False, "req": op.req, "sig": op.sig,
        "src_info": op.src_info, "members": op.members,
    }
    st.post_ctr += 1
    st.recvs.append(rec)
    return rec


def _arrive_collective(st, op):
    """Join the comm's next slot for this rank; completes the slot when
    every member has arrived with an agreeing signature."""
    slots = st.slots.setdefault(op.comm, [])
    k = st.coll_count.get((op.comm, op.rank), 0)
    st.coll_count[(op.comm, op.rank)] = k + 1
    while len(slots) <= k:
        slots.append({"arrived": {}, "done": False})
    slot = slots[k]
    slot["arrived"][op.rank] = op
    if len(slot["arrived"]) >= len(op.members):
        sigs = {a.sig for a in slot["arrived"].values()}
        if len(sigs) > 1:
            sides = "; ".join(
                f"rank {rk}: {a.sig}{_anchor(a)}"
                for rk, a in sorted(slot["arrived"].items())
            )
            st.dead = Finding(
                rule="T4J013",
                message=(
                    f"collective ordering inversion on comm {op.comm}: "
                    f"every member arrived at collective slot {k} but "
                    f"with different ops — {sides}. The ranks entered "
                    "the comm's collectives in different interleavings; "
                    "each blocks inside a different collective and none "
                    "can complete."
                ),
                src_info=op.src_info,
            )
            return k
        slot["done"] = True
        for a in slot["arrived"].values():
            if a.req is not None:
                st.reqs_done.add(a.req)
    return k


def _try_unblock(st, r, blk):
    kind = blk[0]
    if kind == "send":
        return _find_record(st.sends, {"post": blk[2]})["completed"]
    if kind == "recv":
        return _find_record(st.recvs, {"post": blk[2]})["matched"]
    if kind == "sendrecv":
        s_ok = (blk[2] is None
                or _find_record(st.sends, {"post": blk[2]})["completed"])
        r_ok = (blk[3] is None
                or _find_record(st.recvs, {"post": blk[3]})["matched"])
        return s_ok and r_ok
    if kind == "wait":
        return all(q in st.reqs_done for q in blk[2])
    if kind == "coll":
        op = blk[1]
        return st.slots[op.comm][blk[2]]["done"]
    return False


def _envelope_match(recv, send):
    if recv["comm"] != send["comm"]:
        return False
    if send["dest"] != recv["rank"]:
        return False
    src = recv["source"]
    if src not in ("ANY", None) and src != send["rank"]:
        return False
    rtag, stag = recv["tag"], send["tag"]
    if rtag in (None, -1, "ANY"):
        return True
    return rtag == stag


def _candidates(st, recv):
    """Matchable sends for a posted recv: per sender, the earliest
    unmatched posted send (non-overtaking).  Posted-order priority: a
    send is NOT a candidate when an earlier-posted unmatched recv on
    the same rank also matches its envelope — that recv gets the
    message first (the ``frame_matches`` posted-order contract)."""
    per_sender = {}
    for s in st.sends:
        if s["matched"] or not _envelope_match(recv, s):
            continue
        claimed = any(
            r2["post"] < recv["post"] and not r2["matched"]
            and r2["rank"] == recv["rank"] and _envelope_match(r2, s)
            for r2 in st.recvs
        )
        if claimed:
            continue
        prev = per_sender.get(s["rank"])
        if prev is None or s["post"] < prev["post"]:
            per_sender[s["rank"]] = s
    return [per_sender[k] for k in sorted(per_sender)]


def _deterministic_match(st):
    """The earliest-posted unmatched recv with exactly one candidate,
    or a non-wildcard recv with any candidate."""
    for recv in sorted((r for r in st.recvs if not r["matched"]),
                       key=lambda r: r["post"]):
        cands = _candidates(st, recv)
        if len(cands) == 1:
            return recv, cands[0]
    return None


def _wildcard_choice(st):
    for recv in sorted((r for r in st.recvs if not r["matched"]),
                       key=lambda r: r["post"]):
        cands = _candidates(st, recv)
        if len(cands) > 1:
            return recv, cands
    return None


def _apply_match(st, recv, send, schedules, eager_bytes):
    recv["matched"] = True
    send["matched"] = True
    send["completed"] = True
    if send["req"] is not None:
        st.reqs_done.add(send["req"])
    if recv["req"] is not None:
        st.reqs_done.add(recv["req"])
    st.matches[(recv["rank"], recv["idx"])] = (
        send["rank"], send["tag"]
    )


# ------------------------------------------------------- stuck-state report


def _wait_edges(st, r, schedules):
    """Outgoing wait-for edges of a blocked rank: (target_rank, label,
    is_collective)."""
    blk = st.blocked[r]
    if blk is None:
        return []
    kind, op = blk[0], blk[1]
    edges = []
    if kind == "send" or (kind == "sendrecv" and blk[2] is not None
                          and not _find_record(
                              st.sends, {"post": blk[2]})["completed"]):
        d = op.dest
        edges.append((d, f"{op.sig} dest={d} tag={op.tag}"
                         f"{_anchor(op)} waits for rank {d} to post a "
                         "matching recv (rendezvous send over the "
                         "eager threshold)", False))
    recv_post = blk[3] if kind == "sendrecv" else (
        blk[2] if kind == "recv" else None)
    if recv_post is not None and not _find_record(
            st.recvs, {"post": recv_post})["matched"]:
        rec = _find_record(st.recvs, {"post": recv_post})
        src = rec["source"]
        if src in ("ANY", None):
            for m in op.members:
                if m != r:
                    edges.append((m, f"{op.sig} source=ANY tag={op.tag}"
                                     f"{_anchor(op)} waits for any "
                                     "matching send", False))
        else:
            edges.append((src, f"{op.sig} source={src} tag={op.tag}"
                              f"{_anchor(op)} waits for rank {src} to "
                              "send", False))
    if kind == "wait":
        for q in blk[2]:
            origin = _req_origin(schedules[r], q)
            if origin is None:
                continue
            if origin.cat == "send":
                edges.append((origin.dest,
                              f"wait on {origin.sig}{_anchor(op)} "
                              f"waits for rank {origin.dest} to recv",
                              False))
            elif origin.cat == "recv":
                if origin.source in ("ANY", None):
                    for m in origin.members:
                        if m != r:
                            edges.append((m, f"wait on {origin.sig}"
                                             f"{_anchor(op)}", False))
                else:
                    edges.append((origin.source,
                                  f"wait on {origin.sig}{_anchor(op)} "
                                  f"waits for rank {origin.source} to "
                                  "send", False))
            elif origin.cat == "icoll":
                for m in _missing_members(st, origin):
                    edges.append((m, f"wait on {origin.sig}"
                                     f"{_anchor(op)} waits for rank "
                                     f"{m} to join the collective",
                                  True))
    if kind == "coll":
        for m in _missing_members(st, op):
            edges.append((m, f"{op.sig}{_anchor(op)} waits for rank "
                             f"{m} to join the collective", True))
    return edges


def _req_origin(ops, req):
    for op in ops:
        if op.req == req:
            return op
    return None


def _missing_members(st, op):
    slots = st.slots.get(op.comm, ())
    for slot in slots:
        if not slot["done"] and op.rank in slot["arrived"] and \
                slot["arrived"][op.rank] is op:
            return [m for m in op.members if m not in slot["arrived"]]
    return [m for m in op.members if m != op.rank]


def _deadlock_finding(st, schedules):
    """Classify a stuck state: wait-for cycle -> T4J010/T4J013, sink
    waiting on terminated ranks -> dynamic orphan (T4J012)."""
    graph = {}
    for r in range(len(schedules)):
        edges = _wait_edges(st, r, schedules)
        if edges:
            graph[r] = edges
    cycle = _find_cycle(graph)
    if cycle is not None:
        has_coll = any(is_coll for _t, _l, is_coll in
                       (graph[r][i] for r, i in cycle))
        steps = []
        for r, i in cycle:
            _target, label, _c = graph[r][i]
            steps.append(f"rank {r}: {label}")
        rule = "T4J013" if has_coll else "T4J010"
        head = ("collective ordering inversion"
                if has_coll else "cross-rank deadlock")
        anchor = ""
        for r, i in cycle:
            op = st.blocked[r][1]
            if op.src_info:
                anchor = op.src_info
                break
        return Finding(
            rule=rule,
            message=(
                f"{head}: wait-for cycle of length {len(cycle)}: "
                + "; ".join(steps)
                + " — every edge blocks under MPI matching semantics, "
                "so no rank can ever proceed."
            ),
            src_info=anchor,
        )
    # no cycle: some blocked rank waits only on ranks that finished
    for r, edges in sorted(graph.items()):
        targets = {t for t, _l, _c in edges}
        done = {t for t in targets
                if t >= len(schedules) or (
                    st.blocked[t] is None
                    and st.pc[t] >= len(schedules[t]))}
        if targets and targets == done:
            _t, label, _c = edges[0]
            return Finding(
                rule="T4J012",
                message=(
                    f"orphan matching: rank {r}: {label}, but every "
                    "rank it waits on has already finished its "
                    "schedule — the matching op is never posted."
                ),
                src_info=st.blocked[r][1].src_info,
            )
    if graph:
        r, edges = sorted(graph.items())[0]
        _t, label, _c = edges[0]
        return Finding(
            rule="T4J010",
            message=(
                f"cross-rank deadlock: the job is stuck with rank {r}: "
                f"{label} and no match engine transition enabled."
            ),
            src_info=st.blocked[r][1].src_info,
        )
    return None


def _find_cycle(graph):
    """Any cycle in the wait-for digraph as [(rank, edge_index), ...]."""
    WHITE, GREY, BLACK = 0, 1, 2
    color = {r: WHITE for r in graph}
    path = []

    def dfs(r):
        color[r] = GREY
        for i, (target, _label, _c) in enumerate(graph[r]):
            if target not in graph:
                continue
            if color.get(target, WHITE) == GREY:
                start = next(j for j, (pr, _pi) in enumerate(path)
                             if pr == target)
                return path[start:] + [(r, i)]
            if color.get(target, WHITE) == WHITE:
                path.append((r, i))
                hit = dfs(target)
                path.pop()
                if hit is not None:
                    return hit
        color[r] = BLACK
        return None

    for r in sorted(graph):
        if color[r] == WHITE:
            hit = dfs(r)
            if hit is not None:
                # rotate so the edge indices line up with their rank
                fixed = []
                n = len(hit)
                for j in range(n):
                    rr = hit[j][0]
                    # edge index recorded when LEAVING rr is hit[j][1]
                    fixed.append((rr, hit[j][1]))
                return fixed
    return None


def _nondet_finding(outcomes, schedules):
    keys = sorted(
        {k for m in outcomes.values() for k in m},
    )
    first = None
    senders = set()
    for key in keys:
        vals = {tuple(m.get(key, ("<unmatched>", None)))
                for m in outcomes.values()}
        if len(vals) > 1:
            first = key
            senders = {v[0] for v in vals}
            break
    r, idx = first if first else (None, None)
    anchor = ""
    desc = "a wildcard receive"
    if r is not None and idx is not None and r < len(schedules):
        for op in schedules[r]:
            if op.idx == idx:
                anchor = op.src_info
                desc = f"rank {r}: {op.sig} source=ANY{_anchor(op)}"
                break
    return Finding(
        rule="T4J011",
        message=(
            f"wildcard nondeterminism: {desc} can match sends from "
            f"ranks {sorted((str(s) for s in senders if s is not None))} "
            f"depending on arrival order — {len(outcomes)} distinct "
            "final states are reachable. Pin the source (or make the "
            "result order-insensitive)."
        ),
        src_info=anchor,
    )


# ----------------------------------------------------- whole-job pre-passes


def _check_orphans(schedules):
    """T4J012 — whole-job envelope closure, per comm: every send must
    have a potential receiver in the dest rank's schedule, every recv a
    potential sender.  Count-based greedy matching: specific receives
    consume matching sends first, wildcard receives absorb the rest."""
    findings = []
    sends = []          # (comm, sender, dest, tag, op)
    recvs = []          # (comm, receiver, source, tag, op)
    dynamic_comms = set()
    for ops in schedules:
        for op in ops:
            if op.unknown_peer:
                dynamic_comms.add(op.comm)
                continue
            if op.cat in ("send", "sendrecv") and op.dest is not None:
                sends.append([op.comm, op.rank, op.dest, op.tag, op,
                              False])
            if op.cat in ("recv", "sendrecv") and \
                    (op.source is not None or op.cat == "recv"):
                recvs.append([op.comm, op.rank, op.source, op.tag, op,
                              False])
    # pass 1: specific receives claim matching sends
    for rv in recvs:
        if rv[2] in ("ANY", None):
            continue
        for sd in sends:
            if sd[5] or sd[0] != rv[0] or sd[0] in dynamic_comms:
                continue
            if sd[2] == rv[1] and sd[1] == rv[2] and \
                    _tags_match(rv[3], sd[3]):
                sd[5] = rv[5] = True
                break
    # pass 2: wildcard receives absorb remaining sends to their rank
    for rv in recvs:
        if rv[5] or rv[2] not in ("ANY", None):
            continue
        for sd in sends:
            if sd[5] or sd[0] != rv[0] or sd[0] in dynamic_comms:
                continue
            if sd[2] == rv[1] and _tags_match(rv[3], sd[3]):
                sd[5] = rv[5] = True
                break
    for comm, sender, dest, tag, op, used in sends:
        if used or comm in dynamic_comms:
            continue
        findings.append(Finding(
            rule="T4J012",
            message=(
                f"orphan send: rank {sender}: {op.sig} dest={dest} "
                f"tag={tag}{_anchor(op)} is never received — no recv "
                f"in rank {dest}'s schedule matches its envelope "
                "(whole-job scope)."
            ),
            src_info=op.src_info,
        ))
    for comm, receiver, source, tag, op, used in recvs:
        if used or comm in dynamic_comms:
            continue
        src_txt = "ANY" if source in ("ANY", None) else source
        findings.append(Finding(
            rule="T4J012",
            message=(
                f"orphan recv: rank {receiver}: {op.sig} "
                f"source={src_txt} tag={tag}{_anchor(op)} can never be "
                "satisfied — no unclaimed send in any schedule "
                "matches its envelope (whole-job scope)."
            ),
            src_info=op.src_info,
        ))
    return findings


def _tags_match(rtag, stag):
    if rtag in (None, -1, "ANY"):
        return True
    return rtag == stag


def _check_wire_mix(schedules):
    """T4J014 — ROADMAP item 5: member ranks of one comm must agree on
    the compressed-collective wire mode for the reduction steps the
    compression gate applies to.  Needs every rank's schedule in hand
    (the fingerprint pass can only compare; this sees the whole comm)."""
    findings = []
    by_comm = {}
    for ops in schedules:
        for op in ops:
            if op.cat not in ("coll", "icoll"):
                continue
            if op.wire is None:
                continue
            # only the compression gate's eligible steps (f32 SUM
            # reductions — the T4J009 contract) carry a wire mode
            if op.redop != "sum" or op.dtype != "float32":
                continue
            by_comm.setdefault(op.comm, {}).setdefault(
                op.rank, set()).add((str(op.wire), op.src_info))
    for comm, per_rank in sorted(by_comm.items()):
        modes = {}
        for rank, pairs in per_rank.items():
            for mode, _src in pairs:
                modes.setdefault(mode, []).append(rank)
        if len(modes) <= 1:
            continue
        sides = "; ".join(
            f"rank{'s' if len(rs) > 1 else ''} "
            f"{','.join(str(x) for x in sorted(set(rs)))}: wire={m}"
            for m, rs in sorted(modes.items())
        )
        anchor = ""
        for pairs in per_rank.values():
            for _m, src in pairs:
                if src:
                    anchor = src
                    break
            if anchor:
                break
        findings.append(Finding(
            rule="T4J014",
            message=(
                f"cross-rank wire-dtype mix on comm {comm}: {sides}. "
                "Compression eligibility is a wire framing contract — "
                "mixed modes corrupt the reduction mid-ring. Set "
                "T4J_WIRE_DTYPE identically on every rank (or let the "
                "tuning broadcast decide)."
            ),
            src_info=anchor,
        ))
    return findings
