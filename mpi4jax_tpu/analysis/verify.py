"""Public entry points of the communication-contract verifier.

* :func:`verify_comm` — the lint API: ``verify_comm(fn)(*args)`` traces
  ``fn`` (no execution, no network I/O), runs the static single-trace
  pass and — when a multi-rank world is reachable — the cross-rank
  fingerprint pass, and returns a :class:`Report` of findings with
  stable rule IDs (docs/static-analysis.md).
* :func:`guard` — the deploy hook: wraps a step function so its first
  call per input signature verifies before executing, governed by
  ``T4J_VERIFY=off|fingerprint|full`` (utils/config.py).  ``off`` is a
  zero-overhead passthrough; ``fingerprint`` exchanges schedule digests
  across ranks (turning a would-be deadlock-until-T4J_OP_TIMEOUT into
  an immediate :class:`~.contracts.CommContractError`); ``full`` adds
  the whole static rule catalog and raises on any finding.
"""

import functools

from mpi4jax_tpu.analysis import fingerprint as _fp
from mpi4jax_tpu.analysis.contracts import CommContractError
from mpi4jax_tpu.analysis.trace import extract_schedule

__all__ = ["Report", "verify_comm", "guard", "CommContractError"]


class Report:
    """Outcome of one static verification run."""

    def __init__(self, findings, events, notes=(), peers_checked=0):
        self.findings = list(findings)
        self.events = list(events)
        self.notes = list(notes)
        self.peers_checked = peers_checked

    @property
    def ok(self):
        return not self.findings

    def raise_if_findings(self):
        if self.findings:
            lines = "\n".join(f"  {f}" for f in self.findings)
            raise CommContractError(
                f"communication contract verification failed with "
                f"{len(self.findings)} finding(s):\n{lines}",
                findings=self.findings,
            )
        return self

    def __str__(self):
        if self.ok:
            extra = f", {self.peers_checked} peer schedules" if (
                self.peers_checked
            ) else ""
            return (
                f"clean: {len(self.events)} communication op(s) "
                f"verified{extra}"
            )
        return "\n".join(str(f) for f in self.findings)

    def __repr__(self):
        return (
            f"Report(findings={len(self.findings)}, "
            f"events={len(self.events)}, ok={self.ok})"
        )


def verify_comm(fn, *, mode=None, world=None):
    """Wrap ``fn`` so calling the wrapper *verifies* instead of runs.

    ``verify_comm(fn)(*args, **kwargs)`` returns a :class:`Report`.
    ``mode`` overrides ``T4J_VERIFY`` (explicit verification defaults
    to ``full``); ``world=(rank, size)`` routes the fingerprint
    exchange through the in-process rendezvous registry for MPMD-style
    harnesses — by default the proc tier is used when this process is
    part of a launched job, and the pass is skipped otherwise (an SPMD
    trace cannot diverge from itself).
    """

    @functools.wraps(fn)
    def run(*args, **kwargs):
        # an explicit verify_comm call means "lint this": default to
        # the full catalog regardless of the ambient T4J_VERIFY (which
        # governs the implicit guard hook and defaults to off)
        return _verify_once(
            fn, args, kwargs, mode="full" if mode is None else mode,
            world=world,
        )

    return run


def _verify_once(fn, args, kwargs, mode, world):
    from mpi4jax_tpu.analysis.contracts import (
        check_schedule,
        dedupe_findings,
    )
    from mpi4jax_tpu.analysis.jaxpr_walk import walk_comm_jaxpr
    from mpi4jax_tpu.utils import config

    mode = config.verify_mode() if mode is None else str(mode)
    if mode == "off":
        return Report((), ())
    if mode not in ("fingerprint", "full"):
        raise ValueError(
            f"verify mode must be off|fingerprint|full, got {mode!r}"
        )

    extraction = extract_schedule(fn, args, kwargs)
    findings = list(extraction.error_findings)
    if mode == "full":
        findings += check_schedule(extraction.events)
        if extraction.closed_jaxpr is not None:
            _, jaxpr_findings = walk_comm_jaxpr(extraction.closed_jaxpr)
            findings += jaxpr_findings
    # composite ops (gather -> allgather) can double-report one user
    # call site when an inner op slips the reentrancy guard; static
    # rules fire per event, so the same anchor would repeat
    findings = dedupe_findings(findings)

    # ALWAYS participate in the exchange, findings or not: the exchange
    # is a collective, and a rank that silently sat out because of a
    # local finding would wedge every clean peer in it — the exact
    # hang-until-deadline this pass exists to eliminate.  A rank with
    # local findings posts a sentinel instead of a schedule; its peers
    # raise immediately naming that rank, while the rank itself gets
    # its Report.
    peers = _fp.exchange_and_check(
        extraction.events, world=world,
        local_findings=[f.rule for f in findings],
        # full mode ships the @sched event export so agreement gets
        # checked by the cross-rank simulator too (T4J010/011/013/014)
        simulate=(mode == "full"),
    )
    return Report(
        findings, extraction.events, extraction.notes, peers_checked=peers
    )


def guard(fn=None, *, mode=None, world=None):
    """Verify-before-execute wrapper for a step function.

    Usable as ``guard(step)`` or ``@guard``.  Verification runs once
    per input signature (shapes/dtypes of the flattened args) and is
    then cached, so steady-state calls pay one dict lookup.  With
    ``T4J_VERIFY=off`` (the default) the wrapper is a passthrough.
    """
    if fn is None:
        return functools.partial(guard, mode=mode, world=world)

    cache = {}

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        from mpi4jax_tpu.utils import config

        eff_mode = config.verify_mode() if mode is None else str(mode)
        if eff_mode != "off":
            key = (eff_mode, _signature_key(args, kwargs))
            if key not in cache:
                report = _verify_once(
                    fn, args, kwargs, mode=eff_mode, world=world
                )
                report.raise_if_findings()
                cache[key] = True
        return fn(*args, **kwargs)

    return wrapper


def _signature_key(args, kwargs):
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    sig = tuple(
        (getattr(x, "shape", None), str(getattr(x, "dtype", type(x))))
        for x in leaves
    )
    return (str(treedef), sig)
