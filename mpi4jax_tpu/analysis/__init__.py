"""mpi4jax_tpu.analysis — trace-time communication contract verifier.

Because every mpi4jax_tpu program is *traced*, its complete
communication schedule is known before the first byte moves.  This
subsystem exploits that to reject broken programs up front, the
capability classic MPI tooling (MUST's deadlock detection, MPI-Checker's
send/recv matching) can only approximate over C sources:

* :func:`verify_comm` / ``t4j-lint`` — the static single-trace pass:
  token-chain misuse, unmatched/mismatched send-recv envelopes,
  self-deadlocking wait-for orders, collectives under rank-dependent
  branches, op/comm contract violations.  Stable rule IDs T4J001...
  (docs/static-analysis.md).
* :func:`guard` + ``T4J_VERIFY=off|fingerprint|full`` — the cross-rank
  schedule-fingerprint pass: each rank hashes its extracted schedule
  and exchanges digests before executing, so MPMD schedule divergence
  raises :class:`CommContractError` immediately on every rank instead
  of hanging until ``T4J_OP_TIMEOUT``.
"""

from mpi4jax_tpu.analysis.contracts import (
    CommContractError,
    CommEvent,
    Finding,
    RULES,
)
from mpi4jax_tpu.analysis.verify import Report, guard, verify_comm

__all__ = [
    "CommContractError",
    "CommEvent",
    "Finding",
    "RULES",
    "Report",
    "guard",
    "verify_comm",
]
