"""Communication-contract rule core for ``t4j-lint`` / ``verify_comm``.

This module is the *pure* half of the analyzer: the rule catalog, the
symbolic-schedule data model, the schedule checks, and the fingerprint
hashing.  It deliberately imports **nothing** from jax or the rest of
the package at module scope, so the rule logic is unit-testable on any
container (including old-jax ones where the package itself cannot
import) by loading this file directly — see tests/analysis/conftest.py.

Background: classic MPI verifiers split the same way — MUST's runtime
deadlock detector and MPI-Checker's static send/recv matching both
operate on an extracted per-process *communication schedule*, not on
the host language.  Because mpi4jax_tpu programs are traced, the
schedule here is exact (every op the program will ever issue appears
once, in program order), which makes the classic checks decidable at
trace time: token misuse, unmatched or mismatched envelopes,
self-deadlocking wait-for orders, and rank-divergent branches are all
reported before the first byte moves (docs/static-analysis.md).

The impure halves live next door: :mod:`.record` captures events from
the op layer while a trace runs, :mod:`.jaxpr_walk` recurses into
pjit/scan/while/cond sub-jaxprs, :mod:`.fingerprint` exchanges schedule
digests across ranks.
"""

import hashlib
import os
import re
from dataclasses import dataclass

__all__ = [
    "RULES",
    "CommEvent",
    "Finding",
    "CommContractError",
    "check_schedule",
    "classify_trace_error",
    "dedupe_findings",
    "step_signature",
    "schedule_lines",
    "schedule_digest",
    "first_divergence",
    "NATIVE_DTYPES",
]


# ----------------------------------------------------------- rule catalog
#
# Stable IDs: tooling (CI greps, issue trackers, suppressions) keys on
# these, so an ID is never renumbered or reused once released.  The
# catalog with examples lives in docs/static-analysis.md.

RULES = {
    "T4J001": "forked token chain: one token consumed by more than one "
              "communication op",
    "T4J002": "dropped pending send: a staged send is never matched by a "
              "recv before its token chain ends",
    "T4J003": "send/recv envelope mismatch: no staged send can satisfy "
              "this recv under the comm's world (peer/tag/shape/dtype)",
    "T4J004": "point-to-point wait-for cycle (self-deadlock): a blocking "
              "recv is ordered before the only send that could satisfy it",
    "T4J005": "collective under rank-dependent branch: cond branches "
              "selected by a rank-derived predicate disagree on their "
              "communication schedule",
    "T4J006": "op/comm contract mismatch: dtype, shape, reduce-op, root "
              "or partner rank disagrees with the communicator",
    "T4J007": "cross-rank schedule divergence: ranks extracted different "
              "communication schedules for one program (fingerprint pass)",
    "T4J008": "request never waited: a nonblocking op's request is not "
              "consumed by wait/waitall before the trace ends, or a "
              "request is waited more than once",
    "T4J009": "mixed wire dtypes on one communicator: ranks disagree on "
              "the compressed-collective wire dtype for a reduction step "
              "(T4J_WIRE_DTYPE must be set uniformly across every rank)",
    # T4J010..T4J014 are the cross-rank *simulator* rules
    # (analysis/simulate.py, ``t4j-verify``): they need every rank's
    # schedule in hand, which is exactly what the fingerprint pass's
    # agreeing-schedules blind spot is — schedules that AGREE step for
    # step can still deadlock or complete nondeterministically.
    "T4J010": "cross-rank deadlock: the ranks' schedules form a "
              "wait-for cycle under MPI matching semantics "
              "(posted-order receives, rendezvous sends over the eager "
              "threshold, in-order submission per rank)",
    "T4J011": "wildcard nondeterminism: an ANY_SOURCE/ANY_TAG receive "
              "admits two match orders that reach different final "
              "states (racing senders)",
    "T4J012": "orphan matching: a send no schedule ever receives, or a "
              "receive no schedule ever sends to, at whole-job scope",
    "T4J013": "collective ordering inversion: ranks interleave "
              "collectives and point-to-point ops (or two collectives) "
              "in an order that cyclically blocks",
    "T4J014": "cross-rank wire-dtype mix: member ranks of one "
              "communicator disagree on compressed-collective "
              "eligibility or wire mode for matching reduction steps",
}


class CommContractError(RuntimeError):
    """A communication-contract violation detected before execution.

    Raised by the cross-rank fingerprint pass on schedule divergence
    (rule T4J007) and by :func:`mpi4jax_tpu.analysis.guard` when the
    static pass reports findings.  Carries ``findings`` (list of
    :class:`Finding`) when produced by the static pass.
    """

    def __init__(self, message, findings=()):
        super().__init__(message)
        self.findings = list(findings)


@dataclass(frozen=True)
class CommEvent:
    """One communication op in a rank's extracted schedule.

    ``token_in`` / ``token_out`` are opaque identities (``id()`` of the
    Token objects at trace time) used for chain analysis; ``pending_out``
    summarises the token's staged-send queue after the op (mirroring
    ``Token.pending`` / ``Token.pending_meta`` bookkeeping in
    ops/_core.py).  ``rank`` is the calling rank when it is static
    (self/proc backends) and ``None`` on the mesh backend, where the
    rank is a traced value.
    """

    seq: int
    kind: str                 # public op name: "allreduce", "send", ...
    comm_key: tuple           # ops/_core.comm_key(comm)
    backend: str              # "mesh" | "self" | "proc"
    comm_size: int
    dtype: str = ""
    shape: tuple = ()
    reduce_op: str = ""       # "" for non-reductions
    tag: int | None = None
    source: object = None     # int | tuple(pairs) | "ANY" | "traced" | None
    dest: object = None
    root: int | None = None
    rank: int | None = None
    comm_ranks: tuple = ()    # world ranks of the comm's members, if known
    token_in: int | None = None
    token_out: int | None = None
    pending_out: tuple = ()   # tuple of short strings, one per staged send
    src_info: str = ""        # "file.py:123" best-effort user frame
    scope: tuple = ()         # trace-nesting path, outermost first
    # async request chain (docs/async.md): identity of the Request a
    # nonblocking op returned, and of the Request(s) a wait/waitall/
    # test consumed — T4J008 keys on these
    request_out: int | None = None
    requests_in: tuple = ()

    def describe(self):
        bits = [self.kind, f"comm={_fmt_comm(self.comm_key)}"]
        if self.shape or self.dtype:
            bits.append(f"{self.dtype}[{'x'.join(map(str, self.shape))}]")
        if self.reduce_op:
            bits.append(f"op={self.reduce_op}")
        if self.root is not None:
            bits.append(f"root={self.root}")
        if self.dest is not None:
            bits.append(f"dest={self.dest}")
        if self.source is not None:
            bits.append(f"source={self.source}")
        if self.tag is not None:
            bits.append(f"tag={self.tag}")
        return " ".join(bits)


def _fmt_comm(comm_key):
    try:
        return "/".join(str(p) for p in comm_key)
    except TypeError:
        return str(comm_key)


@dataclass(frozen=True)
class Finding:
    """One rule violation, with stable ID and source anchoring."""

    rule: str                 # "T4J001" ...
    message: str
    src_info: str = ""
    event_seq: int | None = None

    def __str__(self):
        loc = f" [{self.src_info}]" if self.src_info else ""
        return f"{self.rule}: {self.message}{loc}"


def _finding(rule, message, event=None):
    return Finding(
        rule=rule,
        message=message,
        src_info=event.src_info if event is not None else "",
        event_seq=event.seq if event is not None else None,
    )


def dedupe_findings(findings):
    """Collapse findings that say the same thing about the same place.

    A composite op (``gather`` -> ``allgather`` on the mesh backend)
    records under the reentrancy guard; when the guard's edge cases let
    both the outer and the inner op produce an event, the two events
    share one file:line anchor and every schedule rule that fires on
    them fires twice — the same anchor then repeats in ``--coalesce``
    output and in reports.  Key on ``(rule, src_info, message)`` with
    the step number stripped, preserving first-seen order; findings
    without an anchor are never collapsed (nothing ties them together).
    """
    seen = set()
    out = []
    for f in findings:
        if not f.src_info:
            out.append(f)
            continue
        key = (f.rule, f.src_info, re.sub(r"\bstep \d+\b", "step *",
                                          f.message))
        if key in seen:
            continue
        seen.add(key)
        out.append(f)
    return out


# ------------------------------------------------------- schedule checks


def check_schedule(events):
    """Run every pure-schedule rule over an ordered event list.

    Returns a list of :class:`Finding` (empty when the schedule is
    clean).  Rules needing the jaxpr (T4J005) or other ranks (T4J007)
    live in :mod:`.jaxpr_walk` / :mod:`.fingerprint`.
    """
    findings = []
    findings += _check_token_forks(events)
    findings += _check_dropped_sends(events)
    findings += _check_self_deadlock(events)
    findings += _check_native_dtypes(events)
    findings += _check_requests(events)
    return findings


def _check_token_forks(events):
    """T4J001 — each token identity may be consumed by at most one op.

    Consuming a token twice forks the ordering chain: the two branches
    carry no mutual ordering, so the relative execution order of their
    collectives is undefined across devices — the exact failure mode
    the reference declares UB (docs/sharp-bits.rst there) and that
    surfaces as a cross-device deadlock at runtime.
    """
    findings = []
    first_use = {}
    for ev in events:
        if ev.token_in is None:
            continue
        prev = first_use.get(ev.token_in)
        if prev is not None:
            findings.append(_finding(
                "T4J001",
                f"token consumed by {prev.kind} (step {prev.seq}"
                f"{', ' + prev.src_info if prev.src_info else ''}) is "
                f"consumed again by {ev.kind}: the ordering chain forks "
                "and the two branches may execute in different orders "
                "on different devices. Thread the token returned by "
                f"{prev.kind} instead.",
                ev,
            ))
        else:
            first_use[ev.token_in] = ev
    return findings


def _check_dropped_sends(events):
    """T4J002 — staged sends must be drained before their chain ends.

    Mirrors ``Token.assert_drained`` (ops/_core.py), but at lint time
    over the whole trace: a token that still carries pending sends and
    is never consumed by a later op means those payloads can never be
    delivered (the matching recv would have had to pop them from this
    very token).
    """
    consumed = {ev.token_in for ev in events if ev.token_in is not None}
    findings = []
    for ev in events:
        if not ev.pending_out:
            continue
        if ev.token_out is not None and ev.token_out in consumed:
            continue  # chain continues; a later op may drain it
        descs = "; ".join(ev.pending_out)
        findings.append(_finding(
            "T4J002",
            f"token returned by {ev.kind} still carries unmatched "
            f"send(s) [{descs}] and no later op consumes it. Every "
            "send must be paired with a recv on the same token chain "
            "within the trace.",
            ev,
        ))
    return findings


def _check_self_deadlock(events):
    """T4J004 — per-rank wait-for order on blocking p2p (proc backend).

    The proc tier executes ops in program order and its ``recv``
    blocks.  A ``recv(source=me)`` can therefore only be satisfied by a
    ``send(dest=me)`` issued *earlier* in this same rank's schedule; if
    the only matching send comes later (or never), the recv blocks
    forever — the minimal wait-for cycle, detectable from one rank's
    schedule alone (cross-rank cycles are the fingerprint pass's and
    the runtime deadline's job).
    """
    findings = []
    by_comm = {}
    for ev in events:
        if ev.backend != "proc":
            continue
        by_comm.setdefault(ev.comm_key, []).append(ev)
    for seq_events in by_comm.values():
        # multiset of sends-to-self already issued: (tag,) -> count
        posted = {}
        for ev in seq_events:
            me = ev.rank
            if me is None:
                continue
            if ev.kind == "send" and ev.dest == me:
                posted[ev.tag] = posted.get(ev.tag, 0) + 1
            elif ev.kind == "recv" and ev.source == me:
                want = ev.tag
                match = None
                for tag in posted:
                    if posted[tag] <= 0:
                        continue
                    if want is None or want == -1 or tag == want:
                        match = tag
                        break
                if match is not None:
                    posted[match] -= 1
                    continue
                later = [
                    o for o in seq_events
                    if o.seq > ev.seq and o.kind == "send" and o.dest == me
                    and (want is None or want == -1 or o.tag == want)
                ]
                if later:
                    where = later[0]
                    findings.append(_finding(
                        "T4J004",
                        f"recv(source={me}, tag={_fmt_tag(want)}) on rank "
                        f"{me} blocks before the matching send at step "
                        f"{where.seq}"
                        f"{' (' + where.src_info + ')' if where.src_info else ''}"
                        " executes: a rank cannot receive from itself "
                        "before it has sent (wait-for cycle of length 1). "
                        "Issue the send first.",
                        ev,
                    ))
                else:
                    findings.append(_finding(
                        "T4J004",
                        f"recv(source={me}, tag={_fmt_tag(want)}) on rank "
                        f"{me} waits for a send-to-self that this rank's "
                        "schedule never issues: it can never complete.",
                        ev,
                    ))
    return findings


def _fmt_tag(tag):
    return "ANY" if tag in (None, -1) else tag


def _check_requests(events):
    """T4J008 — async request discipline (docs/async.md).

    Every request a nonblocking op returns must be consumed by a
    wait/waitall exactly once within the trace: a never-waited request
    leaks its buffers and silently drops the op's completion ordering
    (on the proc tier the runtime reports the leak only at finalize —
    long after the bug); a doubly-waited request raises at runtime on
    the second wait, mid-job.  Both are decidable from one rank's
    schedule.  ``test`` probes do not consume (MPI_Test-and-then-wait
    is the documented idiom), so they are not counted as waits.
    """
    findings = []
    produced = {}   # request identity -> producing event
    consumed = {}   # request identity -> first consuming event
    for ev in events:
        if ev.request_out is not None:
            produced[ev.request_out] = ev
        if ev.kind == "test":
            continue  # probe: does not consume
        for rid in ev.requests_in:
            prev = consumed.get(rid)
            if prev is not None:
                origin = produced.get(rid)
                findings.append(_finding(
                    "T4J008",
                    f"request returned by "
                    f"{origin.kind if origin else 'a nonblocking op'}"
                    f"{' (step ' + str(origin.seq) + ')' if origin else ''}"
                    f" is waited again by {ev.kind} after "
                    f"{prev.kind} (step {prev.seq}) already consumed it: "
                    "a request may be waited exactly once.",
                    ev,
                ))
            else:
                consumed[rid] = ev
    for rid, origin in produced.items():
        if rid not in consumed:
            findings.append(_finding(
                "T4J008",
                f"request returned by {origin.kind} is never consumed by "
                "wait/waitall before the trace ends: the operation's "
                "completion is unobservable and its buffers stay pinned "
                "(request leak — the runtime reports it only at "
                "finalize). Wait every nonblocking request exactly "
                "once.",
                origin,
            ))
    return findings


# dtype names the native bridge can move (native/runtime.py
# _DTYPE_CODES; kept as a name list here so this module stays
# import-free — drift is pinned by tests/analysis/test_rules.py)
NATIVE_DTYPES = frozenset({
    "float32", "float64", "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64", "bool",
    "complex64", "complex128", "float16", "bfloat16",
})


def _check_native_dtypes(events):
    """T4J006 — proc-tier ops must use dtypes the native bridge can
    move; anything else dies at execution time inside a callback with a
    much less useful traceback."""
    findings = []
    for ev in events:
        if ev.backend == "proc" and ev.dtype and ev.dtype not in NATIVE_DTYPES:
            findings.append(_finding(
                "T4J006",
                f"{ev.kind} on a proc communicator uses dtype "
                f"{ev.dtype}, which the native bridge cannot move "
                "(supported: the 15-entry table in native/runtime.py). "
                "Cast before the op.",
                ev,
            ))
    return findings


# ------------------------------------- trace-error classification (T4J00x)
#
# The op layer already rejects many contract violations eagerly at
# trace time (ops/p2p.py, ops/collectives.py, utils/validation.py).
# Under verify_comm those exceptions become *findings* with stable rule
# IDs instead of a crash mid-trace, so one lint run reports them
# uniformly alongside the schedule rules.  Matchers key on stable
# phrases from the ops' own error messages (their tests assert on the
# same phrases, so they are load-bearing strings already).

_ERROR_RULES = (
    # p2p trace-time matching failures -> envelope mismatch
    (r"recv found no matching in-trace send", "T4J003"),
    (r"recv template shape/dtype .* does not match staged send", "T4J003"),
    (r"pattern is not a permutation", "T4J003"),
    (r"still carries unmatched send", "T4J002"),
    (r"was never matched by a recv", "T4J002"),
    # op/comm contract violations the validation layer rejects
    (r"out of range for communicator", "T4J006"),
    (r"alltoall input must have shape", "T4J006"),
    (r"reduce_scatter input must have shape", "T4J006"),
    (r"[Ss]catter input must have shape", "T4J006"),
    (r"unsupported dtype for the native bridge", "T4J006"),
    (r"must describe one global permutation", "T4J003"),
    (r"requires uniform send/recv\s+shapes", "T4J006"),
    (r"bare integer rank is ambiguous under SPMD", "T4J006"),
)


def classify_trace_error(exc):
    """Map a trace-time exception from the op layer to a rule ID.

    Returns ``None`` when the exception is not a recognised
    communication-contract violation (it should then propagate — an
    unrelated bug in the traced program is not a lint finding).
    """
    text = str(exc)
    for pattern, rule in _ERROR_RULES:
        if re.search(pattern, text):
            return rule
    return None


# ----------------------------------------------------------- fingerprints


def _effective_wire_dtype():
    """This rank's effective compressed-collective wire dtype
    (``off|bf16|fp8``), preferring the native bridge's answer (which
    reflects the calibrator's fit applied at tuning startup) over the
    raw env knob.  Invalid env spellings read as ``off`` here — loud
    validation is utils/config.py's job at bridge init."""
    try:
        from mpi4jax_tpu.native import runtime

        info = runtime.wire_dtype_info()
        if info:
            return info.get("wire_dtype", "off")
    except Exception:
        pass
    mode = str(os.environ.get("T4J_WIRE_DTYPE") or "").strip().lower()
    return mode if mode in ("bf16", "fp8") else "off"


def step_signature(ev, wire_dtype=None):
    """Canonical one-line signature of a schedule step.

    This is the unit of cross-rank agreement: two ranks executing "the
    same program" must produce identical signature sequences.  Fields
    that legitimately differ per rank (the rank itself, source info,
    token identities) are excluded; fields that must agree (op kind,
    comm identity and size, dtype/shape, reduce op, root, tag, and the
    p2p pattern) are included.

    The trailing field is the rank's effective **wire dtype** for steps
    the compressed-collective policy applies to (f32 SUM reductions,
    docs/performance.md "Compressed collectives") — a per-RANK knob that
    must nevertheless agree across a comm, because mixed modes run
    mismatched wire framing and corrupt the reduction.  Divergence only
    in this field is reported as rule T4J009 rather than the generic
    T4J007 (:func:`divergence_message`).  ``wire_dtype`` overrides the
    ambient mode (tests, offline replay of another job's schedule).
    """
    parts = [
        ev.kind,
        _fmt_comm(ev.comm_key),
        f"n={ev.comm_size}",
        ev.dtype or "-",
        "x".join(map(str, ev.shape)) if ev.shape else "-",
        ev.reduce_op or "-",
        f"root={ev.root}" if ev.root is not None else "-",
        f"tag={ev.tag}" if ev.tag is not None else "-",
    ]
    # p2p patterns: a static global pattern must agree verbatim; a
    # per-rank int partner legitimately differs across MPMD ranks, so
    # it is reduced to its *kind* (the matched pair is the other rank's
    # business — MPI's envelope matching, checked at runtime)
    for name, spec in (("dst", ev.dest), ("src", ev.source)):
        if spec is None:
            parts.append("-")
        elif isinstance(spec, tuple):
            parts.append(f"{name}={spec}")
        else:
            parts.append(f"{name}:{_spec_kind(spec)}")
    if ev.reduce_op == "sum" and ev.dtype == "float32":
        mode = _effective_wire_dtype() if wire_dtype is None else wire_dtype
        parts.append(f"wire={mode}")
    else:
        # integer/MIN/MAX and non-reduction steps never compress
        # (native comm_wire_dtype gate) — no wire field to disagree on
        parts.append("-")
    return "|".join(parts)


def _spec_kind(spec):
    if spec == "ANY":
        return "any"
    if spec == "traced":
        return "traced"
    if isinstance(spec, int):
        return "rank"
    return "static"


def schedule_lines(events):
    """The schedule as an ordered list of signature lines."""
    return [step_signature(ev) for ev in events]


def schedule_digest(events):
    """(n_steps, 32-byte sha256) over the canonical schedule text."""
    text = "\n".join(schedule_lines(events))
    return len(events), hashlib.sha256(text.encode()).digest()


def first_divergence(lines_by_rank):
    """Locate the first differing step across ranks' schedule lines.

    ``lines_by_rank`` is a list (indexed by rank) of line lists.
    Returns ``(step_index, details)`` where ``details`` maps rank ->
    its line at that step (or ``"<schedule ends>"``), or ``None`` when
    all schedules agree.
    """
    if not lines_by_rank:
        return None
    longest = max(len(lines) for lines in lines_by_rank)
    for i in range(longest):
        seen = {}
        for rank, lines in enumerate(lines_by_rank):
            line = lines[i] if i < len(lines) else "<schedule ends>"
            seen.setdefault(line, rank)
        if len(seen) > 1:
            details = {}
            for rank, lines in enumerate(lines_by_rank):
                details[rank] = (
                    lines[i] if i < len(lines) else "<schedule ends>"
                )
            return i, details
        if not seen:
            break
    return None


def _wire_only_divergence(details):
    """If every rank's line at the diverging step agrees except in the
    trailing ``wire=`` field, return the set of modes in play (the
    T4J009 case); else ``None`` (generic T4J007)."""
    rows = [str(line).split("|") for line in details.values()]
    if len(rows) < 2 or any(len(r) < 2 for r in rows):
        return None
    if any(len(r) != len(rows[0]) for r in rows):
        return None
    if len({"|".join(r[:-1]) for r in rows}) != 1:
        return None
    tails = {r[-1] for r in rows}
    if len(tails) > 1 and all(t.startswith("wire=") for t in tails):
        return sorted(t[len("wire="):] for t in tails)
    return None


def divergence_message(step, details, deadline_hint=None):
    """Human-readable CommContractError text naming the first differing
    step — raised identically on every rank so each job log carries the
    full diagnosis regardless of which rank the user inspects.

    A divergence confined to the wire-dtype field is its own rule: the
    SCHEDULE agrees, the per-rank compression knob doesn't — the fix is
    environmental (set ``T4J_WIRE_DTYPE`` uniformly, or let the tuning
    broadcast set it), not a code change, so the message says so under
    the dedicated ID T4J009."""
    by_line = {}
    for rank, line in sorted(details.items()):
        by_line.setdefault(line, []).append(rank)
    sides = "; ".join(
        f"rank{'s' if len(ranks) > 1 else ''} "
        f"{','.join(map(str, ranks))}: {line}"
        for line, ranks in by_line.items()
    )
    modes = _wire_only_divergence(details)
    if modes is not None:
        msg = (
            f"T4J009: ranks mix compressed-collective wire dtypes "
            f"({'/'.join(modes)}) on one communicator, first at step "
            f"{step}: {sides}. The schedules agree — the per-rank "
            "T4J_WIRE_DTYPE knob does not; set it identically on every "
            "rank (or unset it and let the tuning broadcast decide) "
            "(docs/static-analysis.md)."
        )
    else:
        msg = (
            f"T4J007: communication schedules diverge at step {step}: "
            f"{sides}. Every rank of a communicator must issue the same "
            "collective sequence; a rank-dependent branch or a mismatched "
            "tag/shape/reduce-op is the usual cause "
            "(docs/static-analysis.md)."
        )
    if deadline_hint:
        msg += f" ({deadline_hint})"
    return msg
