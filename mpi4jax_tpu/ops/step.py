"""Step markers: user-declared iteration boundaries for telemetry.

Per-step performance diagnosis (``t4j-diagnose``,
docs/observability.md "diagnosing a slow step") needs ground truth for
where one training/serving step ends and the next begins — inferring
boundaries from op cadence breaks the moment a step issues a variable
number of collectives.  :func:`annotate_step` / :func:`step_scope` are
that ground truth: each call emits a step-boundary event into the
native telemetry ring (kind 60, counters mode up — one pair per step,
negligible cost) and a named row on the python recorder lane (trace
mode), so every rank's "step k" is the same user-level iteration and
the cross-rank merger/diagnoser can align, attribute, and compare
steps by index.

Two idioms::

    for batch in data:                      # marker style (torch-like)
        m.annotate_step("train")            # closes the previous step
        loss = train_step(state, batch)
    m.end_step()                            # close the last one

    for batch in data:                      # scope style
        with m.step_scope("train"):
            loss = train_step(state, batch)

Call these at host level, OUTSIDE jit (one call per executed step —
inside a traced function they would fire once at trace time, marking
nothing).  Steps never nest: ``annotate_step`` auto-closes the open
step, and a ``step_scope`` inside another closes the outer one first
(diagnose flags the imbalance).  A rank that dies mid-step leaves its
last step open on purpose — diagnose closes it at the rank's last
event, which is exactly the truncated span a post-mortem wants.

Import-free of jax (stdlib only) like the telemetry package, so the
standalone harnesses and old-jax containers can load it.
"""

import threading
from contextlib import contextmanager

__all__ = ["annotate_step", "end_step", "step_scope", "current_step"]

_PHASE_BEGIN, _PHASE_END = 1, 2

_state = {
    "lock": threading.Lock(),
    "index": -1,   # last assigned step index
    "open": None,  # (index, name) of the currently open step
}


def _emit(index, phase, name):
    # native ring first (counters mode up; no-op when the bridge was
    # never loaded), then the python recorder lane (trace mode) which
    # carries the NAME — the 32-byte native record has no string field,
    # so names ride as "step:<name>" rows with the index in nbytes
    try:
        from mpi4jax_tpu.native import runtime

        runtime.annotate_step(index, phase)
    except Exception:
        pass  # a marker must never fail the step it marks
    from mpi4jax_tpu.telemetry import recorder

    recorder.record(f"step:{name}", phase, nbytes=index)


def annotate_step(name="step"):
    """Mark the boundary of a new step: closes the currently open step
    (if any) and opens the next one.  Returns the new step's index
    (0-based, monotone per process).  Call once per executed iteration,
    at host level outside jit."""
    name = str(name)
    with _state["lock"]:
        if _state["open"] is not None:
            idx, open_name = _state["open"]
            _emit(idx, _PHASE_END, open_name)
        _state["index"] += 1
        idx = _state["index"]
        _state["open"] = (idx, name)
        _emit(idx, _PHASE_BEGIN, name)
        return idx


def end_step():
    """Close the currently open step (no-op when none is open).  The
    marker-style loop calls this once after the loop so the last step
    gets a real end instead of a truncated one."""
    with _state["lock"]:
        if _state["open"] is None:
            return
        idx, name = _state["open"]
        _state["open"] = None
        _emit(idx, _PHASE_END, name)


@contextmanager
def step_scope(name="step"):
    """Context-manager form: begin a step on entry, end it on exit.
    Yields the step index."""
    idx = annotate_step(name)
    try:
        yield idx
    finally:
        with _state["lock"]:
            if _state["open"] is not None and _state["open"][0] == idx:
                _, open_name = _state["open"]
                _state["open"] = None
                _emit(idx, _PHASE_END, open_name)
            # else: a nested annotate_step already closed us — the
            # imbalance is visible to diagnose via the step stream


def current_step():
    """``(index, name)`` of the open step, or ``None``."""
    with _state["lock"]:
        return _state["open"]


def _reset():
    """Test hook: forget all step state."""
    with _state["lock"]:
        _state["index"] = -1
        _state["open"] = None
