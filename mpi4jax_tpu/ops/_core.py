"""Token plumbing and shared machinery for the communication ops.

The reference threads an XLA token through every op and marks lowerings
``has_side_effect=True`` so XLA cannot reorder or DCE communication
(mpi4jax/_src/collective_ops/allreduce.py:58-66, _src/jax_compat.py:24-50;
token misuse declared UB in docs/sharp-bits.rst:6-34).  On TPU the same
ordering contract is expressed through *data dependence*: a
:class:`Token` carries a scalar "stamp" array, and every op is fenced with
``lax.optimization_barrier`` so its collective depends on the incoming
stamp and the outgoing stamp depends on the collective's result.  Under
SPMD, XLA schedules collectives in a program order consistent across all
devices, so a connected token chain is sufficient to rule out cross-device
mismatches and deadlocks.

The token additionally carries the *pending-send queue*: in SPMD there is
no per-rank control flow, so a ``send`` stages its payload on the token at
trace time and the matching ``recv`` consumes it, emitting a single fused
``ppermute`` (see :mod:`mpi4jax_tpu.ops.p2p`).  This materialises MPI's
eager-send/matching-recv semantics at trace time instead of at runtime.
"""

import threading
from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.tree_util import register_pytree_node

__all__ = [
    "Token",
    "create_token",
    "as_token",
    "token_array",
    "ANY_SOURCE",
    "ANY_TAG",
]

ANY_SOURCE = -1
ANY_TAG = -1


@dataclass(frozen=True)
class PendingSendMeta:
    """Static descriptor of a staged send (aux data of the Token pytree)."""

    perm: tuple  # tuple of (source_rank, dest_rank) pairs
    tag: int
    comm_key: tuple  # (backend, axes/context) identifying the communicator
    shape: tuple
    dtype: str


class Token:
    """Opaque ordering token returned by every communication op.

    A pytree whose children are the ordering stamp plus any staged
    (pending) send payloads; the matching metadata is static aux data.
    """

    def __init__(self, stamp=None, pending=(), pending_meta=()):
        if stamp is None:
            stamp = jnp.zeros((), jnp.float32)
        self.stamp = stamp
        self.pending = tuple(pending)
        self.pending_meta = tuple(pending_meta)
        if len(self.pending) != len(self.pending_meta):
            raise ValueError("pending payloads and metadata out of sync")

    def push_send(self, payload, meta):
        return Token(
            self.stamp,
            self.pending + (payload,),
            self.pending_meta + (meta,),
        )

    def pop_send(self, index):
        """Remove pending send ``index``; returns (payload, meta, token)."""
        payload = self.pending[index]
        meta = self.pending_meta[index]
        tok = Token(
            self.stamp,
            self.pending[:index] + self.pending[index + 1 :],
            self.pending_meta[:index] + self.pending_meta[index + 1 :],
        )
        return payload, meta, tok

    def with_stamp(self, stamp):
        return Token(stamp, self.pending, self.pending_meta)

    def assert_drained(self):
        """Raise if sends were staged but never matched by a recv."""
        if self.pending:
            descs = [f"tag={m.tag} perm={m.perm}" for m in self.pending_meta]
            raise RuntimeError(
                "token still carries unmatched send(s): "
                + "; ".join(descs)
                + ". Every mpi4jax_tpu.send must be paired with a recv in "
                "the same trace (SPMD programs are uniform across devices)."
            )
        return self

    def __repr__(self):
        return f"Token(pending={len(self.pending)})"


def _token_flatten(tok):
    return (tok.stamp, *tok.pending), tok.pending_meta


def _token_unflatten(meta, children):
    return Token(children[0], children[1:], meta)


register_pytree_node(Token, _token_flatten, _token_unflatten)


def create_token(arg=None):
    """Create a fresh communication token.

    ``arg`` is accepted (and ignored) for call-compatibility with
    ``jax.lax.create_token`` / the reference examples.
    """
    del arg
    return Token()


def as_token(token):
    """Coerce user-supplied token values (None / array / Token) to a Token.

    Under :func:`mpi4jax_tpu.experimental.auto_tokenize`, ``token=None``
    resolves to the ambient token instead of a fresh one, so consecutive
    ops chain automatically (the reference's auto-token-threading
    transform, mpi4jax/experimental/tokenizer.py:108-164, reimagined as
    an ambient context rather than a jaxpr interpreter).
    """
    if token is None:
        stack = _ambient_stack()
        if stack:
            return stack[-1].resolve()
        return Token()
    if isinstance(token, Token):
        return token
    from jax._src import core as _jcore

    if isinstance(token, getattr(_jcore, "Token", ())) or isinstance(
        getattr(token, "aval", None), getattr(_jcore, "AbstractToken", ())
    ):
        # jax.lax.create_token() value (concrete or traced — the
        # reference's idiom, shallow_water.py:165 there): an opaque
        # ordering token with no data — ordering here rides this
        # library's own stamp chain
        return Token()
    if isinstance(token, jax.Array) or hasattr(token, "dtype"):
        return Token(jnp.asarray(token, jnp.float32).reshape(()) * 0)
    raise TypeError(f"cannot interpret {type(token)} as a communication token")


# -- ambient-token context (backing store for experimental.auto_tokenize) --

_ambient = threading.local()


def _ambient_stack():
    stack = getattr(_ambient, "stack", None)
    if stack is None:
        stack = _ambient.stack = []
    return stack


def _current_trace():
    from jax._src import core as _jcore

    return _jcore.trace_ctx.trace


def _is_ancestor(trace, current):
    """True iff ``trace`` is ``current`` or on its parent chain."""
    t = current
    while t is not None:
        if t is trace:
            return True
        t = getattr(t, "parent_trace", None)
    return False


def _pending_multiset(tok):
    """Multiset {(payload identity, meta): count} of a token's pendings."""
    counts = {}
    for p, meta in zip(tok.pending, tok.pending_meta):
        key = (id(p), meta)
        counts[key] = counts.get(key, 0) + 1
    return counts


class AmbientChain:
    """Per-auto_tokenize-scope token chain, stratified by JAX trace.

    Tokens committed inside an inner trace (a scan/while body, a cond
    branch, a nested jit) are only valid while that trace is live; using
    them afterwards leaks a tracer.  Each committed token is therefore
    recorded with the trace it was created under, and lookups discard
    levels whose trace is not an ancestor of the current one — exiting a
    control-flow body transparently resumes the chain from the enclosing
    trace's token.  (The reference instead rewrites control-flow
    sub-jaxprs to carry the token through — tokenizer.py:19-105; the
    stratification here gives the same user-visible chaining without a
    jaxpr interpreter.)
    """

    def __init__(self):
        self.levels = []  # [(trace, token)], outermost first

    def _prune(self):
        """Drop levels whose trace has exited, auditing their pending
        sends: entries also tracked at the surviving outer level are fine
        (consumption is propagated by ``commit``), live payloads staged
        in the dead trace are hoisted out, and dead-trace payloads that
        were never matched raise — they can never be delivered."""
        cur = _current_trace()
        while self.levels and not _is_ancestor(self.levels[-1][0], cur):
            tr, tok = self.levels.pop()
            if not tok.pending:
                continue
            parent_tok = self.levels[-1][1] if self.levels else Token()
            parent_keys = _pending_multiset(parent_tok)
            for p, meta in zip(tok.pending, tok.pending_meta):
                key = (id(p), meta)
                if parent_keys.get(key, 0) > 0:
                    parent_keys[key] -= 1
                    continue  # outer level still tracks this send
                if isinstance(p, jax.core.Tracer) and _is_ancestor(tr, p._trace):
                    raise RuntimeError(
                        "a send staged inside a control-flow body / nested "
                        f"jit (tag={meta.tag}, perm={meta.perm}) was never "
                        "matched by a recv before its trace exited; it can "
                        "no longer be delivered. Pair every send with a "
                        "recv inside the same control-flow scope."
                    )
                # payload from an enclosing trace, staged while tracing
                # the inner scope: still deliverable — hoist it out
                parent_tok = parent_tok.push_send(p, meta)
            if self.levels:
                self.levels[-1] = (self.levels[-1][0], parent_tok)
            elif parent_tok.pending:
                self.levels.append((cur, parent_tok))
        return cur

    def resolve(self):
        cur = self._prune()
        if not self.levels:
            self.levels.append((cur, Token()))
        return self.levels[-1][1]

    def commit(self, token):
        cur = self._prune()
        if self.levels and self.levels[-1][0] is cur:
            self.levels[-1] = (cur, token)
        else:
            self.levels.append((cur, token))
        # Propagate consumption: a pending entry an ancestor level tracks
        # that is gone from the committed token was matched by a recv in
        # this (deeper) trace — drop it from the ancestor too, or it would
        # be delivered twice when the inner trace exits.
        kept = _pending_multiset(token)
        for i in range(len(self.levels) - 1):
            tr, tok = self.levels[i]
            if not tok.pending:
                continue
            avail = dict(kept)
            new_p, new_m = [], []
            for p, meta in zip(tok.pending, tok.pending_meta):
                key = (id(p), meta)
                if avail.get(key, 0) > 0:
                    avail[key] -= 1
                    new_p.append(p)
                    new_m.append(meta)
            if len(new_p) != len(tok.pending):
                self.levels[i] = (tr, Token(tok.stamp, new_p, new_m))


def commit_token(token):
    """Publish an op's output token to the ambient chain (no-op when no
    auto_tokenize scope is active)."""
    stack = _ambient_stack()
    if stack:
        stack[-1].commit(token)
    return token


# ops whose debug log uses the reference's MPI_<Op> wire name
_LOGGED_OPS = {
    "allgather", "allreduce", "alltoall", "barrier", "bcast", "gather",
    "recv", "reduce", "scan", "scatter", "send", "sendrecv",
}

_ALNUM = (
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789"
)


def _first_array(tree):
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "dtype") and hasattr(leaf, "size"):
            return leaf
    return None


def _rid_str(code):
    """8-char call id from a 32-bit code (the reference uses 8 random
    alphanumerics, mpi_xla_bridge.pyx:47-52)."""
    chars = []
    code = int(code) & 0xFFFFFFFF
    for _ in range(8):
        code, r = divmod(code, len(_ALNUM))
        chars.append(_ALNUM[r])
    return "".join(chars)


# per-execution timers, keyed by the execution-unique (rank, call id):
# concurrent executions of one call site cannot collide on the key
_debug_timers = {}
_debug_timers_mu = threading.Lock()


def _debug_emit(line):
    """One atomic line to stdout: concurrent executions emit from
    multiple callback threads, and ``print`` writes text and newline
    separately — torn lines would corrupt the debug-log wire format the
    observability tests (and any log parser) key on."""
    import sys

    with _debug_timers_mu:
        sys.stdout.write(line + "\n")
        sys.stdout.flush()


def _scalar(v):
    """First element of a possibly-batched callback operand (vmap may
    hand the callback a stacked value; the id is replicated)."""
    return int(np.ravel(np.asarray(v))[0])


def _debug_begin(name, args, kwargs, comm):
    """Stage the reference-format begin line and start the call timer.

    Wire format follows the reference's bridge logging exactly
    (mpi_xla_bridge.pyx:47-60): ``r{rank} | {8-char random id} |
    MPI_<Op> with {n} items`` at execution time, then a matching
    ``MPI_<Op> done with code 0 (1.23e-04s)`` line from
    :func:`_debug_end`.  Toggled by MPI4JAX_TPU_DEBUG /
    utils.config.set_debug; zero cost when disabled (nothing is staged
    at trace time).

    Structure (three callbacks per op, for transform-safety AND
    execution-unique pairing):

    * a ``pure_callback`` whose only operands are the rank and a
      trace-time nonce generates the per-execution id and a fallback
      start time.  Keeping user data out of its operands keeps it out
      of reach of JVP/vmap traces — ``pure_callback`` supports neither
      (the reference suite runs grad/vmap tests with logging enabled).
    * the begin/done lines print from ``jax.debug.callback`` (which is
      transform-proof by design), data-dependent on the op's
      operands/results for best-effort placement, carrying the id.
    * timers pair begin→done through :data:`_debug_timers` keyed by the
      unique id, so concurrent executions of one call site cannot
      mispair (the done callback falls back to the generated start time
      if it somehow runs before its begin — callbacks are unordered).
    """
    import random
    import time

    arr = _first_array((args, kwargs))
    nitems = int(arr.size) if arr is not None else 0
    opname = "MPI_" + name.capitalize()
    try:
        rank = comm.rank()
    except Exception:
        rank = -1

    def gen_cb(rank_val, _nonce):
        hi, lo = divmod(time.perf_counter_ns(), 1 << 31)
        return (
            np.uint32(random.getrandbits(32)),
            np.int32(hi),
            np.int32(lo),
        )

    # trace-time nonce: makes each call site's generator unique so XLA
    # can't CSE two otherwise-identical callbacks into one id
    nonce = jnp.uint32(random.getrandbits(32))
    rid, t_hi, t_lo = jax.pure_callback(
        gen_cb,
        (
            jax.ShapeDtypeStruct((), np.uint32),
            jax.ShapeDtypeStruct((), np.int32),
            jax.ShapeDtypeStruct((), np.int32),
        ),
        jnp.asarray(rank),
        nonce,
    )

    def begin_cb(rank_val, rid_val, *_deps):
        r, i = _scalar(rank_val), _scalar(rid_val)
        with _debug_timers_mu:
            # bound the dict: entries orphan when a done callback ran
            # before its begin (unordered callbacks) or an execution
            # aborted between the two; evict oldest-inserted first
            while len(_debug_timers) >= 4096:
                _debug_timers.pop(next(iter(_debug_timers)))
            _debug_timers[(r, i)] = time.perf_counter_ns()
        _debug_emit(f"r{r} | {_rid_str(i)} | {opname} with {nitems} items")

    deps = (arr,) if arr is not None else ()
    jax.debug.callback(begin_cb, jnp.asarray(rank), rid, *deps)
    return {"opname": opname, "rank": rank, "carry": (rid, t_hi, t_lo)}


def _debug_end(state, out):
    import time

    opname = state["opname"]

    def end_cb(rank_val, rid, t_hi, t_lo, *_deps):
        r, i = _scalar(rank_val), _scalar(rid)
        with _debug_timers_mu:
            t0_ns = _debug_timers.pop(
                (r, i), (_scalar(t_hi) << 31) + _scalar(t_lo)
            )
        dt = (time.perf_counter_ns() - t0_ns) / 1e9
        _debug_emit(
            f"r{r} | {_rid_str(i)} | {opname} done with code 0 ({dt:.2e}s)"
        )

    arr = _first_array(out)
    deps = (arr,) if arr is not None else ()
    jax.debug.callback(
        end_cb, jnp.asarray(state["rank"]), *state["carry"], *deps
    )


def _tel_nbytes(args, kwargs):
    arr = _first_array((args, kwargs))
    if arr is None:
        return 0
    try:
        return int(arr.size) * np.dtype(arr.dtype).itemsize
    except Exception:
        return 0


def publishes_token(fn):
    """Instrumentation wrapper for every public op: profiler scope,
    opt-in per-call debug logging, opt-in telemetry bracketing
    (T4J_TELEMETRY=trace — the Python-level begin/end events that
    enclose the native segment events on the merged timeline,
    docs/observability.md), publication of the returned Token (if any)
    to the ambient auto_tokenize chain, and — while a ``verify_comm``
    extraction is active — reporting the call to the contract analyzer
    (analysis/record.py).

    The ``jax.named_scope`` below is load-bearing for the analyzer too:
    it stamps every lowered eqn's name stack with ``mpi4jax_tpu.<op>``,
    which is how the jaxpr walker (analysis/jaxpr_walk.py) identifies
    communication eqns inside control-flow sub-jaxprs regardless of
    backend.
    """
    import contextlib
    import functools

    name = fn.__name__

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        from mpi4jax_tpu.utils import config

        log_state = None
        if config.debug_enabled() and name in _LOGGED_OPS:
            from mpi4jax_tpu.utils.validation import check_comm

            log_state = _debug_begin(
                name, args, kwargs, check_comm(kwargs.get("comm"))
            )
        # Python-level op bracket: at execution time for eager/proc
        # calls (the MPMD idiom), at trace time under jit — the staged
        # tier additionally brackets its runtime callbacks
        # (ops/_proc.py), which is where in-jit wall time is spent.
        # EVERY wrapped op is bracketed (reduce_scatter, the halo and
        # attention composites included) — _LOGGED_OPS is the debug
        # log's MPI_<Op> wire-name set, a different concern.
        tel_scope = contextlib.nullcontext()
        from mpi4jax_tpu.telemetry import recorder as _telrec

        if _telrec.tracing():
            tel_scope = _telrec.py_op(name, _tel_nbytes(args, kwargs))
        from mpi4jax_tpu.analysis import record as _arecord

        with tel_scope:
            if _arecord.active():
                with _arecord.op_frame():
                    with jax.named_scope(f"mpi4jax_tpu.{name}"):
                        out = fn(*args, **kwargs)
                    _arecord.record_op(name, fn, args, kwargs, out)
            else:
                with jax.named_scope(f"mpi4jax_tpu.{name}"):
                    out = fn(*args, **kwargs)
        token = None
        if isinstance(out, Token):
            token = out
        elif isinstance(out, tuple):
            for item in out:
                if isinstance(item, Token):
                    token = item
                    break
        if token is not None:
            commit_token(token)
        if log_state is not None:
            _debug_end(log_state, out)
        return out

    return wrapper


def token_array(token):
    """The raw stamp array (for interop with array-token code)."""
    return as_token(token).stamp


def fence_in(token, *arrays):
    """Make ``arrays`` depend on the token's stamp (pre-collective fence)."""
    from mpi4jax_tpu.utils import config

    if not config.fences_enabled():
        return token, arrays
    out = lax.optimization_barrier((token.stamp, *arrays))
    return token.with_stamp(out[0]), out[1:]


def fence_out(token, *arrays):
    """Make the token's stamp depend on ``arrays`` (post-collective fence)."""
    from mpi4jax_tpu.utils import config

    if not config.fences_enabled():
        return token, arrays
    out = lax.optimization_barrier((token.stamp, *arrays))
    return token.with_stamp(out[0]), out[1:]


def vma_of(x):
    """``x``'s varying-manual-axes tuple, or ``None`` when the aval has
    no vma typing at all (older JAX) — callers treating None as "no
    axes" should use ``vma_of(x) or ()``."""
    import jax

    try:
        return tuple(jax.typeof(x).vma)
    except AttributeError:
        return None


def promote_vma(x, axes):
    """Promote ``x`` to be device-varying over all of ``axes``.

    JAX's collectives require a uniform varying-state across the named
    axes; values derived from only one mesh axis (e.g. a y-coordinate
    field on a ("y","x") comm) must be explicitly ``pvary``-ed before a
    multi-axis collective.  No-op outside shard_map and for already-
    varying values.
    """
    vma = vma_of(x)
    if vma is None:
        return x
    missing = tuple(a for a in axes if a not in vma)
    if missing:
        if hasattr(lax, "pcast"):
            x = lax.pcast(x, missing, to="varying")
        else:
            x = lax.pvary(x, missing)
    return x


def comm_key(comm):
    """Hashable identity of a communicator for send/recv matching."""
    if comm.backend == "mesh":
        return ("mesh", comm.axes, comm.context)
    return (comm.backend, comm.context)
