"""Pallas TPU flash attention: the blockwise-local-attention hot op.

``local_attention`` (parallel/longseq.py) is the FLOPs core of both
sequence-parallel schemes; the dense XLA form materialises the [Tq, Tk]
score matrix in HBM.  This kernel streams K/V blocks through VMEM with
online-softmax statistics in scratch, so scores never leave the chip —
the standard flash-attention schedule (Dao et al. 2022) expressed in
Pallas idioms: sequential minormost grid dimension as the K loop, VMEM
scratch carried across grid steps, masking via 2-D iota.

Public entry: :func:`flash_attention` with the same contract as
``local_attention`` ([B, T, H, D] operands, float32 accumulation,
``causal`` with static block offsets).  ``interpret=True`` runs the
kernel on CPU for tests.  Reverse-mode differentiable with a BLOCKWISE
backward (the standard dFlashAttention pair): the forward additionally
saves the per-row log-sum-exp, and two kernels recompute scores per
block — one accumulating (dK, dV) per key block over query blocks, one
accumulating dQ per query block over key blocks — so the backward
never materialises the [Tq, Tk] score matrix either.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention"]

_NEG = -0.7 * float(jnp.finfo(jnp.float32).max)
_INF = float("inf")
_LANES = 128  # TPU lane width: scratch statistics are (block_q, _LANES)


def _tri_iq_ik(t):
    """Row-major lower-triangle index: flat ``t`` -> (iq, ik) with
    ik <= iq.  The float sqrt is exact for any realistic block count;
    the two `where` guards absorb boundary roundoff anyway."""
    tf = t.astype(jnp.float32)
    iq = jnp.floor((jnp.sqrt(8.0 * tf + 1.0) - 1.0) / 2.0).astype(jnp.int32)
    tri = iq * (iq + 1) // 2
    iq = jnp.where(t < tri, iq - 1, iq)
    iq = jnp.where(t >= (iq + 1) * (iq + 2) // 2, iq + 1, iq)
    ik = t - iq * (iq + 1) // 2
    return iq, ik


def _tri_gate(causal, q_offset, k_offset, tq, tk, pad_q, pad_k, block_q,
              block_k):
    """True when the squashed-triangle causal grid applies: square
    unsharded causal attention with no padding and equal blocks.  The
    triangle grid visits only the ~half of the blocks the causal mask
    keeps (and masks only the diagonal ones), measured ~1.4x over the
    rectangular grid at seq 8192 (docs/performance.md); sharded
    (offset) and padded cases keep the general rectangular path.

    The flat triangle index is inverted with a float32 sqrt
    (:func:`_tri_iq_ik`) whose ±1 boundary guards absorb at most one
    index of error.  At the 2^22 flat-index cap, ``8*t+1`` ≈ 2^25 — a
    couple of f32 ulps of representation error plus the sqrt's
    half-ulp, i.e. an absolute error on ``sqrt ≈ 2^12.5`` of ~1e-3,
    far below the ±1 the guards absorb (the guards would only be
    outrun near ``t ≈ 2^45``).  The static cap keeps that argument
    comfortably valid instead of letting an extreme block count
    (~2896 query blocks — seq ≈ 1.5M at block 512) silently mis-map
    blocks (ADVICE r4)."""
    if not (
        causal
        and q_offset == k_offset
        and tq == tk
        and pad_q == 0
        and pad_k == 0
        and block_q == block_k
    ):
        return False
    nq = tq // block_q
    return nq * (nq + 1) // 2 <= 1 << 22


def _union_vma_sds(shape, dtype, *arrays):
    """ShapeDtypeStruct carrying the union of the operands' varying
    manual axes (required by shard_map's vma checking for pallas_call
    outputs); plain struct on JAX builds without vma typing."""
    from mpi4jax_tpu.ops._core import vma_of

    vmas = [vma_of(a) for a in arrays]
    if all(v is None for v in vmas):
        return jax.ShapeDtypeStruct(shape, dtype)
    axes = set()
    for v in vmas:
        axes.update(v or ())
    return jax.ShapeDtypeStruct(shape, dtype, vma=frozenset(axes))


def _kernel(
    q_ref,
    k_ref,
    v_ref,
    *rest,
    scale,
    causal,
    q_offset,
    k_offset,
    kv_len,
    block_q,
    block_k,
    num_k,
    with_lse,
    triangle,
):
    # triangle runs carry a precomputed additive causal-mask bias as a
    # 4th input (0 on visible entries, ~_NEG on masked, bf16): one VPU
    # add on the diagonal blocks replaces the iota+compare+select
    # stack; masked scores collapse to ~-2.4e38 whose exp underflows to
    # exactly 0, so every OBSERVABLE quantity (w, l, m on rows with a
    # visible entry — every triangle row has one) matches the where()
    # form
    if triangle:
        mask_ref, o_ref, *rest = rest
    else:
        mask_ref = None
        o_ref, *rest = rest
    if with_lse:
        m_out_ref, l_out_ref, acc_ref, m_ref, l_ref = rest
    else:
        m_out_ref, l_out_ref = None, None
        acc_ref, m_ref, l_ref = rest
    if triangle:
        # squashed causal grid: only the lower-triangle blocks are
        # visited (the rest are fully masked anyway), and only the
        # diagonal block pays the mask/iota VPU work — measured ~1.4x
        # at seq 8192 over the rectangular grid + full masking
        iq, ik = _tri_iq_ik(pl.program_id(1))
    else:
        iq = pl.program_id(1)
        ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)

    def _compute(mask_causal):
        # the softmax scale rides the [bq, D] query block instead of the
        # [bq, bk] score block — one full-block VPU pass saved per visit
        # (bk/D× fewer multiplies); f32 so no operand rounding is added
        q = q_ref[0].astype(jnp.float32) * scale  # [bq, D]
        k = k_ref[0].astype(jnp.float32)  # [bk, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bk] f32, already scaled

        if mask_causal and triangle:
            # diagonal block of the squashed grid: add the precomputed
            # bias (one pass; masked entries collapse to ~_NEG and
            # their exp underflows to exactly 0 — see the signature
            # note)
            s = s + mask_ref[...]
        elif not triangle:
            # local (unpadded-array) positions of this block's rows/cols
            krow = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
        if mask_causal and not triangle:
            qpos = q_offset + iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            # causally-masked REAL keys get the finite _NEG (the dense
            # oracle's convention: a fully-masked row degrades to uniform
            # weights over the real keys)
            s = jnp.where(qpos >= k_offset + krow, s, _NEG)
        if not triangle:
            # padded K rows are excluded outright (-inf): exp(-inf - m)
            # == 0 for any finite m, and m stays finite because the
            # scratch starts at _NEG — so padding never contributes to
            # l, matching the unpadded oracle even for fully-masked
            # rows.  (The triangle path is gated on zero padding.)
            s = jnp.where(krow < kv_len, s, -_INF)

        m_prev = m_ref[:, :1]  # [bq, 1] (lanes replicated)
        m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        if q_ref.dtype == jnp.bfloat16:
            # bf16 transcendental: the exp argument is rounded to 8
            # mantissa bits (~0.4% weight error — inside the bf16
            # operands' own precision budget; the backward recomputes
            # the SAME bf16 weights, so fwd/bwd stay self-consistent)
            # and the PV contraction consumes w without a cast pass
            w = jnp.exp((s - m_new).astype(jnp.bfloat16))
        else:
            w = jnp.exp(s - m_new)  # [bq, bk]
        l_ref[...] = l_ref[...] * corr + w.sum(
            axis=1, keepdims=True, dtype=jnp.float32
        )
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        # the weights ride the MXU in the INPUT dtype (f32 accumulate):
        # for bf16 operands that rounds w to 8 mantissa bits — inside
        # the operands' own precision budget (the flash-standard
        # mixed-precision contraction) — and keeps the PV matmul on the
        # fast MXU path; f32 inputs keep exact f32 weights (the tests'
        # oracle-equality mode)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            w.astype(v_ref.dtype),
            v_ref[0],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if triangle:
        @pl.when(ik == iq)
        def _diag():
            _compute(True)

        @pl.when(ik != iq)
        def _interior():
            _compute(False)
    else:
        # NB on this path causal block-SKIPPING (pl.when around the body
        # for fully masked blocks) was measured and rejected at 2048
        # (12.3 vs 11.6 ms); the triangle grid above is the form of
        # skipping that does pay (no visit, no DMA, no conditional on
        # the hot interior blocks).
        _compute(causal)

    last = (ik == iq) if triangle else (ik == num_k - 1)

    @pl.when(last)
    def _finalize():
        o_ref[0] = (acc_ref[...] / l_ref[:, :1]).astype(o_ref.dtype)
        if with_lse:
            # softmax residuals for the backward, stored (rows, 1) —
            # the trailing singleton keeps the block Mosaic-legal.  m
            # and l are saved SEPARATELY, never fused into m + log(l):
            # on fully-masked rows m == _NEG (~-2.4e38) absorbs log(n)
            # entirely in float32, which would inflate the recomputed
            # weights from 1/n to 1 and scale dV by n.
            m_out_ref[0] = m_ref[:, :1]
            l_out_ref[0] = l_ref[:, :1]


def flash_attention(
    q,
    k,
    v,
    *,
    causal=False,
    scale=None,
    q_offset=0,
    k_offset=0,
    block_q=1024,
    block_k=1024,
    interpret=False,
):
    """Blockwise attention, same contract as ``local_attention``.

    Block sizes default to 1024 — the r5 sweep at seq 2048/b16/h16/d128
    measured fwd+bwd 10.45 ms at 1024x1024 vs 13.30 ms at the old
    512x512 default and worse at every other feasible pair (1024x2048
    and 2048x* exceed VMEM; absolute times swing ±30% with co-tenancy —
    docs/performance.md) — and are clamped down for short sequences.

    ``q``: [B, Tq, H, D]; ``k``/``v``: [B, Tk, H, D].  Sequence lengths
    are padded internally to the block sizes (padded K rows are masked
    out of the softmax; padded Q rows are dropped on return).
    ``q_offset``/``k_offset`` are the global positions of the first
    row/column, for causal masking of sequence-sharded blocks.

    ``scale`` and the offsets are trace-time constants (they are baked
    into the kernel); pass Python numbers, not traced values.

    Grouped-query attention (``k``/``v`` with fewer heads, ``Hq % Hkv
    == 0``) is supported by repeating kv heads before the kernel — the
    VMEM streaming win is kept, at Hq/Hkv× kv HBM footprint; gradients
    flow back through the repeat (summed per kv head).
    """
    d = q.shape[-1]
    hq, hk = q.shape[2], k.shape[2]
    if hq != hk:
        if hq % hk:
            raise ValueError(
                f"flash_attention: query heads must be a multiple of kv "
                f"heads, got Hq={hq}, Hkv={hk}"
            )
        k = jnp.repeat(k, hq // hk, axis=2)
        v = jnp.repeat(v, hq // hk, axis=2)
    scale = (1.0 / math.sqrt(d)) if scale is None else float(scale)
    return _flash_vjp(
        q, k, v, bool(causal), scale, int(q_offset), int(k_offset),
        int(block_q), int(block_k), bool(interpret),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash_vjp(
    q, k, v, causal, scale, q_offset, k_offset, block_q, block_k, interpret
):
    return _flash_fwd_impl(
        q, k, v, causal, scale, q_offset, k_offset, block_q, block_k,
        interpret,
    )


def _flash_fwd(
    q, k, v, causal, scale, q_offset, k_offset, block_q, block_k, interpret
):
    # NB a checkpoint_name tag on these residuals CANNOT spare the
    # forward replay under jax.checkpoint: linearising the custom_vjp
    # call re-runs this fwd rule regardless of what a save-names policy
    # keeps (measured r5 — the tagged variant still traced 4 kernel
    # classes and paid an extra o-proj recompute).
    out, m_res, l_res = _flash_fwd_impl(
        q, k, v, causal, scale, q_offset, k_offset, block_q, block_k,
        interpret, with_lse=True,
    )
    return out, (q, k, v, out, m_res, l_res)


def _bwd_block(
    q_ref, k_ref, v_ref, g_ref, m_ref, l_ref, delta_ref, *, iq, ik, scale,
    scale_on, mask_causal, mask_kv, q_offset, k_offset, kv_len, block_q,
    block_k, mask_ref=None,
):
    """Shared per-block backward math: recompute masked scores and the
    softmax weights from the saved (m, l) statistics, then form ds —
    the cotangent of the SCALED scores, with the softmax scale folded
    into one [block, D] operand instead of two [bq, bk] passes
    (``scale_on``: the dkv kernel scales q — its dk contraction then
    absorbs the score-cotangent's trailing ·scale through the scaled q
    — the dq kernel scales k, symmetrically).  ``ds`` is zeroed outside
    the visible set exactly as the dense oracle's ``where`` vjp does
    (this is what keeps the fully-masked-row uniform-weights convention
    gradient-exact: those rows produce p == 1/n but ds == 0).

    ``mask_causal``/``mask_kv`` select which mask terms this block
    needs: the triangle grid's interior blocks are fully visible and
    unpadded, so they skip the iota/where VPU work entirely."""
    q = q_ref[0].astype(jnp.float32)  # [bq, D]
    k = k_ref[0].astype(jnp.float32)  # [bk, D]
    v = v_ref[0]  # [bk, D]
    g = g_ref[0]  # [bq, D]
    if scale_on == "q":
        q = q * scale
    else:
        k = k * scale
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # scaled scores
    visible = None
    if mask_causal and mask_ref is not None:
        # triangle diagonal block: one additive pass; masked entries
        # collapse to ~_NEG, so p underflows to exactly 0.0 and ds is
        # exactly 0 there with no visible-mask select at all
        s = s + mask_ref[...]
    else:
        if mask_causal or mask_kv:
            krow = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
        if mask_kv:
            visible = krow < kv_len
        if mask_causal:
            qpos = q_offset + iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            causal_ok = qpos >= k_offset + krow
            visible = (
                causal_ok if visible is None else (visible & causal_ok)
            )
            s = jnp.where(causal_ok, s, _NEG)
        if mask_kv:
            s = jnp.where(krow < kv_len, s, -_INF)
    # p from the saved statistics ((rows, 1) columns broadcast across
    # the block): exp(s - m) / l — NOT exp(s - (m + log l)), whose f32
    # fusion loses log(l) against the huge _NEG on fully-masked rows
    # and would inflate those rows' weights from 1/n to 1.  Padded q
    # rows carry m == +inf (host-side padding) so p is exactly 0 there.
    # bf16 operands recompute the forward's own bf16-exp weights (the l
    # statistic summed exactly these), keeping fwd/bwd self-consistent.
    if q_ref.dtype == jnp.bfloat16:
        p = jnp.exp((s - m_ref[0]).astype(jnp.bfloat16)).astype(
            jnp.float32
        ) / l_ref[0]
    else:
        p = jnp.exp(s - m_ref[0]) / l_ref[0]  # [bq, bk]
    dp = jax.lax.dot_general(
        g, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    # NO trailing ·scale: the caller's contraction against the scaled
    # operand (q in dkv, k in dq) supplies it
    ds = p * (dp - delta_ref[0])
    if visible is not None:
        ds = jnp.where(visible, ds, 0.0)
    return q, k, g, p, ds


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, g_ref, m_ref, l_ref, delta_ref, *rest, scale,
    causal, q_offset, k_offset, kv_len, block_q, block_k, num_q, triangle,
):
    """dK/dV: one key block per (middle) row, accumulated over the
    sequential query blocks.  On the triangle grid the visible set is
    ``iq >= ik``: the flat index walks key-block rows with iq ascending
    ik..n-1, the diagonal block is the only one needing the mask, and
    the fully-masked iq < ik blocks are never visited at all."""
    if triangle:
        mask_ref, dk_ref, dv_ref, dk_acc, dv_acc = rest
    else:
        mask_ref = None
        dk_ref, dv_ref, dk_acc, dv_acc = rest
    if triangle:
        # reverse the fwd's lower-triangle walk: rows keyed by ik, iq
        # ascending within each row
        n = num_q
        total = n * (n + 1) // 2
        a, bb = _tri_iq_ik(total - 1 - pl.program_id(1))
        ik = n - 1 - a
        iq = n - 1 - bb
    else:
        ik = pl.program_id(1)
        iq = pl.program_id(2)

    first = (iq == ik) if triangle else (iq == 0)
    last = iq == num_q - 1

    @pl.when(first)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def _accumulate(mask_causal):
        q, _k, g, p, ds = _bwd_block(
            q_ref, k_ref, v_ref, g_ref, m_ref, l_ref, delta_ref, iq=iq,
            ik=ik, scale=scale, scale_on="q", mask_causal=mask_causal,
            mask_kv=not triangle, q_offset=q_offset, k_offset=k_offset,
            kv_len=kv_len, block_q=block_q, block_k=block_k,
            mask_ref=mask_ref,
        )
        # dV += P^T @ dO ; dK += dS^T @ Q   (contract the q-block dim).
        # p rides the MXU in g's storage dtype (f32 accumulate); the dK
        # contraction stays f32×f32 — q is already the f32 scaled local
        # (the scale-folding operand), and f32 dots measured the same
        # as bf16 on this kernel (it is DMA-, not MXU-, bound)
        dv_acc[...] += jax.lax.dot_general(
            p.astype(g.dtype), g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if triangle:
        @pl.when(iq == ik)
        def _diag():
            _accumulate(True)

        @pl.when(iq != ik)
        def _interior():
            _accumulate(False)
    else:
        _accumulate(causal)

    @pl.when(last)
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, g_ref, m_ref, l_ref, delta_ref, *rest, scale,
    causal, q_offset, k_offset, kv_len, block_q, block_k, num_k, triangle,
):
    """dQ: one query block per (middle) row, accumulated over the
    sequential key blocks (triangle: ik ascending 0..iq, diagonal
    masked, nothing above it visited)."""
    if triangle:
        mask_ref, dq_ref, dq_acc = rest
    else:
        mask_ref = None
        dq_ref, dq_acc = rest
    if triangle:
        iq, ik = _tri_iq_ik(pl.program_id(1))
    else:
        iq = pl.program_id(1)
        ik = pl.program_id(2)

    first = ik == 0
    last = (ik == iq) if triangle else (ik == num_k - 1)

    @pl.when(first)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    def _accumulate(mask_causal):
        _q, k, _g, _p, ds = _bwd_block(
            q_ref, k_ref, v_ref, g_ref, m_ref, l_ref, delta_ref, iq=iq,
            ik=ik, scale=scale, scale_on="k", mask_causal=mask_causal,
            mask_kv=not triangle, q_offset=q_offset, k_offset=k_offset,
            kv_len=kv_len, block_q=block_q, block_k=block_k,
            mask_ref=mask_ref,
        )
        dq_acc[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if triangle:
        @pl.when(ik == iq)
        def _diag():
            _accumulate(True)

        @pl.when(ik != iq)
        def _interior():
            _accumulate(False)
    else:
        _accumulate(causal)

    @pl.when(last)
    def _finalize():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _flash_bwd(
    causal, scale, q_offset, k_offset, block_q, block_k, interpret, res, g
):
    q, k, v, out, m_res, l_res = res
    b, tq, h, d = q.shape
    tk = k.shape[1]
    # scale is a nondiff arg already resolved to a float by
    # flash_attention before the custom_vjp — no re-defaulting here
    block_q, block_k, pad_q, pad_k = _blocks(tq, tk, block_q, block_k)

    qf = _fold(q, pad_q, b, h, d)
    kf = _fold(k, pad_k, b, h, d)
    vf = _fold(v, pad_k, b, h, d)
    gf = _fold(g, pad_q, b, h, d)
    outf = _fold(out, pad_q, b, h, d)
    # the standard softmax-vjp identity: delta_i = Σ_k P_ik dP_ik
    #                                            = rowsum(dO * O);
    # trailing singleton keeps the (1, block_q, 1) blocks Mosaic-legal
    delta = (gf.astype(jnp.float32) * outf.astype(jnp.float32)).sum(
        -1, keepdims=True
    )
    # padded q rows: m == +inf (and l == 1, not 0 — a 0 would turn the
    # harmless p into nan, and 0 * nan poisons the accumulators) makes
    # their softmax weights exactly 0
    m_pad = jnp.pad(
        m_res, ((0, 0), (0, pad_q)), constant_values=_INF
    ).astype(jnp.float32)[..., None]
    l_pad = jnp.pad(
        l_res, ((0, 0), (0, pad_q)), constant_values=1.0
    ).astype(jnp.float32)[..., None]

    nq = qf.shape[1] // block_q
    nk = kf.shape[1] // block_k
    triangle = _tri_gate(
        causal, q_offset, k_offset, tq, tk, pad_q, pad_k, block_q, block_k
    )
    common = dict(
        scale=scale, causal=causal, q_offset=q_offset, k_offset=k_offset,
        kv_len=tk, block_q=block_q, block_k=block_k, triangle=triangle,
    )
    n_tri = nq * (nq + 1) // 2

    if triangle:
        def dkv_qmap(bh, t):
            a, bb = _tri_iq_ik(n_tri - 1 - t)
            return (bh, nq - 1 - bb, 0)

        def dkv_kmap(bh, t):
            a, bb = _tri_iq_ik(n_tri - 1 - t)
            return (bh, nq - 1 - a, 0)

        dkv_grid = (b * h, n_tri)
    else:
        def dkv_qmap(bh, ik, iq):
            return (bh, iq, 0)

        def dkv_kmap(bh, ik, iq):
            return (bh, ik, 0)

        dkv_grid = (b * h, nk, nq)

    bwd_operands = [qf, kf, vf, gf, m_pad, l_pad, delta]
    if triangle:
        bwd_operands.append(_causal_bias(block_q, block_k, qf, kf, vf, gf))

    def specs_for(qmap, kmap):
        specs = [
            pl.BlockSpec((1, block_q, d), qmap),
            pl.BlockSpec((1, block_k, d), kmap),
            pl.BlockSpec((1, block_k, d), kmap),
            pl.BlockSpec((1, block_q, d), qmap),
            pl.BlockSpec((1, block_q, 1), qmap),
            pl.BlockSpec((1, block_q, 1), qmap),
            pl.BlockSpec((1, block_q, 1), qmap),
        ]
        if triangle:
            specs.append(
                pl.BlockSpec((block_q, block_k), lambda *_: (0, 0))
            )
        return specs

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, num_q=nq, **common),
        grid=dkv_grid,
        in_specs=specs_for(dkv_qmap, dkv_kmap),
        out_specs=[
            pl.BlockSpec((1, block_k, d), dkv_kmap),
            pl.BlockSpec((1, block_k, d), dkv_kmap),
        ],
        out_shape=(
            _union_vma_sds((b * h, nk * block_k, d), k.dtype, qf, kf, vf, gf),
            _union_vma_sds((b * h, nk * block_k, d), v.dtype, qf, kf, vf, gf),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(*bwd_operands)

    if triangle:
        def dq_qmap(bh, t):
            iq, _ik = _tri_iq_ik(t)
            return (bh, iq, 0)

        def dq_kmap(bh, t):
            _iq, ik = _tri_iq_ik(t)
            return (bh, ik, 0)

        dq_grid = (b * h, n_tri)
    else:
        def dq_qmap(bh, iq, ik):
            return (bh, iq, 0)

        def dq_kmap(bh, iq, ik):
            return (bh, ik, 0)

        dq_grid = (b * h, nq, nk)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, num_k=nk, **common),
        grid=dq_grid,
        in_specs=specs_for(dq_qmap, dq_kmap),
        out_specs=pl.BlockSpec((1, block_q, d), dq_qmap),
        out_shape=_union_vma_sds(
            (b * h, nq * block_q, d), q.dtype, qf, kf, vf, gf
        ),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(*bwd_operands)

    return (
        _unfold(dq, tq, b, h, d),
        _unfold(dk, tk, b, h, d),
        _unfold(dv, tk, b, h, d),
    )


_flash_vjp.defvjp(_flash_fwd, _flash_bwd)


def _blocks(tq, tk, block_q, block_k):
    """Clamped block sizes and padding shared by forward and backward
    (they MUST agree: the backward re-pads the forward's residuals).

    (Measured caution, r5: do NOT clamp long sequences down to 512² —
    the 1024² blocks are worth +28% at seq 16384 and +37% at 32768,
    bf16.  FLOAT32 operands at those lengths can push the dq backward
    kernel past the 16 MB scoped-VMEM stack limit under partial-remat
    graph shapes; callers training long context in f32 should pass
    block_q=block_k=512 explicitly — the bench presets train bf16.)"""
    block_q = min(block_q, max(tq, 8))
    block_k = min(block_k, max(tk, 8))
    return block_q, block_k, (-tq) % block_q, (-tk) % block_k


def _causal_bias(block_q, block_k, *arrays):
    """Additive causal mask for the triangle grid's diagonal blocks:
    0 on the visible lower triangle, the finite ``_NEG`` elsewhere.
    Built once per call outside the kernel (XLA folds it to a
    constant); carries the operands' vma union for shard_map."""
    vis = jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    ) >= jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    # bf16: same exponent range as f32, so the ~-2.4e38 sentinel
    # survives the rounding, any finite score it is added to still
    # collapses to ~_NEG, and exp underflows to exactly 0 — while the
    # block costs half the VMEM/DMA of an f32 mask (the fused backward
    # kernel is within ~2 MB of the 16 MB scoped-vmem limit at 1024²)
    bias = jnp.where(vis, 0.0, _NEG).astype(jnp.bfloat16)
    from mpi4jax_tpu.ops._core import promote_vma, vma_of

    axes = set()
    for a in arrays:
        axes.update(vma_of(a) or ())
    if axes:
        bias = promote_vma(bias, tuple(sorted(axes)))
    return bias


def _fold(x, pad, b, h, d):
    """[B, T, H, D] -> [B*H, T(+pad), D]."""
    x = jnp.pad(x, ((0, 0), (0, pad), (0, 0), (0, 0)))
    return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)


def _unfold(x, tq, b, h, d):
    """Inverse of :func:`_fold` (drops the padding)."""
    return x[:, :tq, :].reshape(b, h, tq, d).transpose(0, 2, 1, 3)


def _flash_fwd_impl(
    q, k, v, causal, scale, q_offset, k_offset, block_q, block_k,
    interpret, with_lse=False,
):
    b, tq, h, d = q.shape
    tk = k.shape[1]
    # scale arrives as a resolved float (flash_attention defaults it
    # before the custom_vjp) — no re-defaulting here or in _flash_bwd

    block_q, block_k, pad_q, pad_k = _blocks(tq, tk, block_q, block_k)
    qf = _fold(q, pad_q, b, h, d)
    kf = _fold(k, pad_k, b, h, d)
    vf = _fold(v, pad_k, b, h, d)
    nq = qf.shape[1] // block_q
    nk = kf.shape[1] // block_k
    triangle = _tri_gate(
        causal, q_offset, k_offset, tq, tk, pad_q, pad_k, block_q, block_k
    )

    kernel = functools.partial(
        _kernel,
        scale=scale,
        causal=causal,
        q_offset=q_offset,
        k_offset=k_offset,
        kv_len=tk,
        block_q=block_q,
        block_k=block_k,
        num_k=nk,
        with_lse=with_lse,
        triangle=triangle,
    )
    if triangle:
        grid = (b * h, nq * (nq + 1) // 2)

        def qmap(bh, t):
            iq, _ik = _tri_iq_ik(t)
            return (bh, iq, 0)

        def kmap(bh, t):
            _iq, ik = _tri_iq_ik(t)
            return (bh, ik, 0)
    else:
        grid = (b * h, nq, nk)

        def qmap(bh, iq, ik):
            return (bh, iq, 0)

        def kmap(bh, iq, ik):
            return (bh, ik, 0)

    out_specs = [pl.BlockSpec((1, block_q, d), qmap)]
    # inside shard_map the output varies over the union of the
    # operands' varying axes; check_vma requires it spelled out
    out_shape = [
        _union_vma_sds((b * h, nq * block_q, d), q.dtype, qf, kf, vf),
    ]
    if with_lse:
        for _ in range(2):  # m and l residuals
            out_specs.append(pl.BlockSpec((1, block_q, 1), qmap))
            out_shape.append(
                _union_vma_sds(
                    (b * h, nq * block_q, 1), jnp.float32, qf, kf, vf
                )
            )
    in_specs = [
        pl.BlockSpec((1, block_q, d), qmap),
        pl.BlockSpec((1, block_k, d), kmap),
        pl.BlockSpec((1, block_k, d), kmap),
    ]
    operands = [qf, kf, vf]
    if triangle:
        in_specs.append(
            pl.BlockSpec((block_q, block_k), lambda *_: (0, 0))
        )
        operands.append(_causal_bias(block_q, block_k, qf, kf, vf))
    res = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs if with_lse else out_specs[0],
        out_shape=tuple(out_shape) if with_lse else out_shape[0],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(*operands)

    if with_lse:
        out, m_res, l_res = res
        return _unfold(out, tq, b, h, d), m_res[:, :tq, 0], l_res[:, :tq, 0]
    return _unfold(res, tq, b, h, d)
