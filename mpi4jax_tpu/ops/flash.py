"""Pallas TPU flash attention: the blockwise-local-attention hot op.

``local_attention`` (parallel/longseq.py) is the FLOPs core of both
sequence-parallel schemes; the dense XLA form materialises the [Tq, Tk]
score matrix in HBM.  This kernel streams K/V blocks through VMEM with
online-softmax statistics in scratch, so scores never leave the chip —
the standard flash-attention schedule (Dao et al. 2022) expressed in
Pallas (see /opt/skills/guides/pallas_guide.md for the idioms used:
sequential minormost grid dimension as the K loop, VMEM scratch carried
across grid steps, masking via 2-D iota).

Public entry: :func:`flash_attention` with the same contract as
``local_attention`` ([B, T, H, D] operands, float32 accumulation,
``causal`` with static block offsets).  ``interpret=True`` runs the
kernel on CPU for tests.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention"]

_NEG = -0.7 * float(jnp.finfo(jnp.float32).max)
_LANES = 128  # TPU lane width: scratch statistics are (block_q, _LANES)


def _kernel(
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    acc_ref,
    m_ref,
    l_ref,
    *,
    scale,
    causal,
    q_offset,
    k_offset,
    kv_len,
    block_q,
    block_k,
    num_k,
):
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)

    q = q_ref[0].astype(jnp.float32)  # [bq, D]
    k = k_ref[0].astype(jnp.float32)  # [bk, D]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    s = s * scale  # [bq, bk]

    # local (unpadded-array) positions of this block's rows/cols
    krow = ik * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    visible = krow < kv_len  # padded K rows never contribute
    if causal:
        qpos = q_offset + iq * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        visible = visible & (qpos >= k_offset + krow)
    s = jnp.where(visible, s, _NEG)

    m_prev = m_ref[:, :1]  # [bq, 1] (lanes replicated)
    m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
    corr = jnp.exp(m_prev - m_new)
    w = jnp.exp(s - m_new)  # [bq, bk]
    l_ref[...] = l_ref[...] * corr + w.sum(axis=1, keepdims=True)
    m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
    acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
        w,
        v_ref[0].astype(jnp.float32),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(ik == num_k - 1)
    def _finalize():
        o_ref[0] = (acc_ref[...] / l_ref[:, :1]).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=(
        "causal", "scale", "q_offset", "k_offset", "block_q", "block_k",
        "interpret",
    ),
)
def flash_attention(
    q,
    k,
    v,
    *,
    causal=False,
    scale=None,
    q_offset=0,
    k_offset=0,
    block_q=128,
    block_k=128,
    interpret=False,
):
    """Blockwise attention, same contract as ``local_attention``.

    ``q``: [B, Tq, H, D]; ``k``/``v``: [B, Tk, H, D].  Sequence lengths
    are padded internally to the block sizes (padded K rows are masked
    out of the softmax; padded Q rows are dropped on return).
    ``q_offset``/``k_offset`` are the global positions of the first
    row/column, for causal masking of sequence-sharded blocks.
    """
    b, tq, h, d = q.shape
    tk = k.shape[1]
    scale = (1.0 / math.sqrt(d)) if scale is None else scale

    block_q = min(block_q, max(tq, 8))
    block_k = min(block_k, max(tk, 8))
    pad_q = (-tq) % block_q
    pad_k = (-tk) % block_k

    # [B, T, H, D] -> [B*H, T, D]
    def fold(x, pad):
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0), (0, 0)))
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    qf, kf, vf = fold(q, pad_q), fold(k, pad_k), fold(v, pad_k)
    nq = qf.shape[1] // block_q
    nk = kf.shape[1] // block_k

    kernel = functools.partial(
        _kernel,
        scale=scale,
        causal=causal,
        q_offset=q_offset,
        k_offset=k_offset,
        kv_len=tk,
        block_q=block_q,
        block_k=block_k,
        num_k=nk,
    )
    out = pl.pallas_call(
        kernel,
        grid=(b * h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, iq, ik: (bh, iq, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, iq, ik: (bh, ik, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, iq, ik: (bh, ik, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, iq, ik: (bh, iq, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, nq * block_q, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)

    out = out[:, :tq, :].reshape(b, h, tq, d).transpose(0, 2, 1, 3)
    return out
