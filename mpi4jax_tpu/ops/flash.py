"""Pallas TPU flash attention: the blockwise-local-attention hot op.

``local_attention`` (parallel/longseq.py) is the FLOPs core of both
sequence-parallel schemes; the dense XLA form materialises the [Tq, Tk]
score matrix in HBM.  This kernel streams K/V blocks through VMEM with
online-softmax statistics in scratch, so scores never leave the chip —
the standard flash-attention schedule (Dao et al. 2022) expressed in
Pallas idioms: sequential minormost grid dimension as the K loop, VMEM
scratch carried across grid steps, masking via 2-D iota.

Public entry: :func:`flash_attention` with the same contract as
``local_attention`` ([B, T, H, D] operands, float32 accumulation,
``causal`` with static block offsets).  ``interpret=True`` runs the
kernel on CPU for tests.  Reverse-mode differentiable: the backward
pass recomputes attention densely (same cost/memory as differentiating
the dense path; the VMEM win applies to the forward).
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention"]

_NEG = -0.7 * float(jnp.finfo(jnp.float32).max)
_INF = float("inf")
_LANES = 128  # TPU lane width: scratch statistics are (block_q, _LANES)


def _union_vma_sds(shape, dtype, *arrays):
    """ShapeDtypeStruct carrying the union of the operands' varying
    manual axes (required by shard_map's vma checking for pallas_call
    outputs); plain struct on JAX builds without vma typing."""
    from mpi4jax_tpu.ops._core import vma_of

    vmas = [vma_of(a) for a in arrays]
    if all(v is None for v in vmas):
        return jax.ShapeDtypeStruct(shape, dtype)
    axes = set()
    for v in vmas:
        axes.update(v or ())
    return jax.ShapeDtypeStruct(shape, dtype, vma=frozenset(axes))


def _kernel(
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    acc_ref,
    m_ref,
    l_ref,
    *,
    scale,
    causal,
    q_offset,
    k_offset,
    kv_len,
    block_q,
    block_k,
    num_k,
):
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)

    def _compute():
        # NB: causal block-SKIPPING (pl.when around this body for fully
        # masked blocks) was measured and rejected: it read slightly
        # slower at 2048x2048 (12.3 vs 11.6 ms) — the kernel is
        # pipeline-bound, and the conditional costs more than the saved
        # half-block FLOPs.
        q = q_ref[0].astype(jnp.float32)  # [bq, D]
        k = k_ref[0].astype(jnp.float32)  # [bk, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = s * scale  # [bq, bk]

        # local (unpadded-array) positions of this block's rows/cols
        krow = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        if causal:
            qpos = q_offset + iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            # causally-masked REAL keys get the finite _NEG (the dense
            # oracle's convention: a fully-masked row degrades to uniform
            # weights over the real keys)
            s = jnp.where(qpos >= k_offset + krow, s, _NEG)
        # padded K rows are excluded outright (-inf): exp(-inf - m) == 0
        # for any finite m, and m stays finite because the scratch starts
        # at _NEG — so padding never contributes to l, matching the
        # unpadded oracle even for fully-masked rows
        s = jnp.where(krow < kv_len, s, -_INF)

        m_prev = m_ref[:, :1]  # [bq, 1] (lanes replicated)
        m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        w = jnp.exp(s - m_new)  # [bq, bk]
        l_ref[...] = l_ref[...] * corr + w.sum(axis=1, keepdims=True)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            w,
            v_ref[0].astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    _compute()

    @pl.when(ik == num_k - 1)
    def _finalize():
        o_ref[0] = (acc_ref[...] / l_ref[:, :1]).astype(o_ref.dtype)


def flash_attention(
    q,
    k,
    v,
    *,
    causal=False,
    scale=None,
    q_offset=0,
    k_offset=0,
    block_q=512,
    block_k=512,
    interpret=False,
):
    """Blockwise attention, same contract as ``local_attention``.

    Block sizes default to 512 — measured ~2.6x faster than the
    original 128x128 on v5e at seq 2048 (less grid/revisit overhead,
    fuller MXU; docs/performance.md) — and are clamped down for short
    sequences.

    ``q``: [B, Tq, H, D]; ``k``/``v``: [B, Tk, H, D].  Sequence lengths
    are padded internally to the block sizes (padded K rows are masked
    out of the softmax; padded Q rows are dropped on return).
    ``q_offset``/``k_offset`` are the global positions of the first
    row/column, for causal masking of sequence-sharded blocks.

    ``scale`` and the offsets are trace-time constants (they are baked
    into the kernel); pass Python numbers, not traced values.

    Grouped-query attention (``k``/``v`` with fewer heads, ``Hq % Hkv
    == 0``) is supported by repeating kv heads before the kernel — the
    VMEM streaming win is kept, at Hq/Hkv× kv HBM footprint; gradients
    flow back through the repeat (summed per kv head).
    """
    d = q.shape[-1]
    hq, hk = q.shape[2], k.shape[2]
    if hq != hk:
        if hq % hk:
            raise ValueError(
                f"flash_attention: query heads must be a multiple of kv "
                f"heads, got Hq={hq}, Hkv={hk}"
            )
        k = jnp.repeat(k, hq // hk, axis=2)
        v = jnp.repeat(v, hq // hk, axis=2)
    scale = (1.0 / math.sqrt(d)) if scale is None else float(scale)
    return _flash_vjp(
        q, k, v, bool(causal), scale, int(q_offset), int(k_offset),
        int(block_q), int(block_k), bool(interpret),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash_vjp(
    q, k, v, causal, scale, q_offset, k_offset, block_q, block_k, interpret
):
    return _flash_fwd_impl(
        q, k, v, causal, scale, q_offset, k_offset, block_q, block_k,
        interpret,
    )


def _dense_reference(q, k, v, causal, scale, q_offset, k_offset):
    """The oracle the kernel reproduces (longseq.local_attention's math,
    duplicated here to avoid an import cycle); used for the backward
    pass residual-free recompute."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])
        kpos = k_offset + jnp.arange(k.shape[1])
        mask = qpos[:, None] >= kpos[None, :]
        s = jnp.where(mask[None, None], s, _NEG)
    w = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", w.astype(v.dtype), v)
    return out.astype(q.dtype)


def _flash_fwd(
    q, k, v, causal, scale, q_offset, k_offset, block_q, block_k, interpret
):
    out = _flash_fwd_impl(
        q, k, v, causal, scale, q_offset, k_offset, block_q, block_k,
        interpret,
    )
    return out, (q, k, v)


def _flash_bwd(
    causal, scale, q_offset, k_offset, block_q, block_k, interpret, res, g
):
    q, k, v = res
    # dense recompute: same FLOPs/memory as differentiating the dense
    # path — the flash forward's VMEM win is kept, gradients stay exact
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _dense_reference(
            q_, k_, v_, causal, scale, q_offset, k_offset
        ),
        q, k, v,
    )
    return vjp(g)


_flash_vjp.defvjp(_flash_fwd, _flash_bwd)


def _flash_fwd_impl(
    q, k, v, causal, scale, q_offset, k_offset, block_q, block_k, interpret
):
    b, tq, h, d = q.shape
    tk = k.shape[1]
    scale = (1.0 / math.sqrt(d)) if scale is None else scale

    block_q = min(block_q, max(tq, 8))
    block_k = min(block_k, max(tk, 8))
    pad_q = (-tq) % block_q
    pad_k = (-tk) % block_k

    # [B, T, H, D] -> [B*H, T, D]
    def fold(x, pad):
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0), (0, 0)))
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    qf, kf, vf = fold(q, pad_q), fold(k, pad_k), fold(v, pad_k)
    nq = qf.shape[1] // block_q
    nk = kf.shape[1] // block_k

    kernel = functools.partial(
        _kernel,
        scale=scale,
        causal=causal,
        q_offset=q_offset,
        k_offset=k_offset,
        kv_len=tk,
        block_q=block_q,
        block_k=block_k,
        num_k=nk,
    )
    out = pl.pallas_call(
        kernel,
        grid=(b * h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, iq, ik: (bh, iq, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, iq, ik: (bh, ik, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, iq, ik: (bh, ik, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, iq, ik: (bh, iq, 0)),
        # inside shard_map the output varies over the union of the
        # operands' varying axes; check_vma requires it spelled out
        out_shape=_union_vma_sds(
            (b * h, nq * block_q, d), q.dtype, qf, kf, vf
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)

    out = out[:, :tq, :].reshape(b, h, tq, d).transpose(0, 2, 1, 3)
    return out
