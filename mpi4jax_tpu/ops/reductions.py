"""Reduction operator objects (the MPI.Op equivalents).

The reference keys its reductions off mpi4py ``MPI.Op`` handles wrapped
hashable so they can ride along as primitive parameters
(reference: mpi4jax/_src/utils.py:77-96, dtype map at utils.py:43-71).
Here an :class:`Op` is a small frozen value object that is natively hashable
and knows how to realise itself three ways:

* as an XLA cross-device collective (``lax.psum`` / ``lax.pmin`` /
  ``lax.pmax``) when a fast ICI path exists,
* as a pairwise ``combine`` function (for ppermute-ladder prefix scans and
  all_gather+reduce fallbacks),
* with an ``identity`` element per dtype (for ``lax.reduce``).
"""

from dataclasses import dataclass

import numpy as np
import jax.numpy as jnp
from jax import lax

__all__ = [
    "Op",
    "SUM",
    "PROD",
    "MIN",
    "MAX",
    "LAND",
    "LOR",
    "LXOR",
    "BAND",
    "BOR",
    "BXOR",
    "named_op",
]


@dataclass(frozen=True)
class Op:
    """A reduction operator usable as a static (hashable) primitive param.

    User-defined operators are constructed with :meth:`Op.create` — the
    analog of ``MPI.Op.Create`` (the reference passes such handles
    straight to MPI_Allreduce, mpi4jax/_src/utils.py:77-96).  Two
    ``create`` calls yield distinct ops even with the same name (the
    combine function's identity participates in equality/hashing), so a
    recompile is keyed correctly.
    """

    name: str
    user_combine: object = None  # callable (a, b) -> c, elementwise
    user_identity: object = None  # scalar identity element, or None
    commute: bool = True

    @classmethod
    def create(cls, combine, *, name="user_op", identity=None, commute=True):
        """Build a user-defined reduction operator (MPI.Op.Create analog).

        ``combine`` must be an associative, elementwise, jax-traceable
        binary function (MPI imposes the same associativity
        requirement).  ``commute=False`` guarantees rank-order
        application, like MPI's commute flag.  ``identity`` is optional
        and unused by the current lowerings (reductions fold over the
        gathered operands in rank order).
        """
        if not callable(combine):
            raise TypeError("combine must be callable, got " + repr(combine))
        return cls(
            name=name,
            user_combine=combine,
            user_identity=identity,
            commute=commute,
        )

    @classmethod
    def Create(cls, function, commute=False):
        """mpi4py-spelled alias of :meth:`create` (``MPI.Op.Create``).

        mpi4py's op functions mutate raw buffers; here ``function`` must
        be an elementwise, jax-traceable ``(a, b) -> c`` — the
        functional equivalent (documented in docs/api.md).  Defaults
        ``commute=False`` exactly as mpi4py does.
        """
        return cls.create(function, name="user_op", commute=commute)

    @property
    def is_user(self):
        return self.user_combine is not None

    def combine(self, a, b):
        if self.is_user:
            return self.user_combine(a, b)
        return _COMBINE[self.name](a, b)

    def identity(self, dtype):
        if self.is_user:
            if self.user_identity is None:
                raise ValueError(
                    f"user-defined op {self.name!r} has no identity element"
                )
            return np.asarray(self.user_identity, dtype)
        return _IDENTITY[self.name](dtype)

    @property
    def is_logical(self):
        return not self.is_user and self.name in ("land", "lor", "lxor")

    @property
    def is_bitwise(self):
        return not self.is_user and self.name in ("band", "bor", "bxor")

    def __repr__(self):
        if self.is_user:
            return f"mpi4jax_tpu.Op.create({self.name!r})"
        return f"mpi4jax_tpu.{self.name.upper()}"


def _land(a, b):
    return jnp.logical_and(a, b)


def _lor(a, b):
    return jnp.logical_or(a, b)


_COMBINE = {
    "sum": jnp.add,
    "prod": jnp.multiply,
    "min": jnp.minimum,
    "max": jnp.maximum,
    "land": _land,
    "lor": _lor,
    "lxor": jnp.logical_xor,
    "band": jnp.bitwise_and,
    "bor": jnp.bitwise_or,
    "bxor": jnp.bitwise_xor,
}


def _dtype_min(dtype):
    dtype = np.dtype(dtype)
    if dtype.kind == "f":
        return np.array(-np.inf, dtype)
    return np.array(np.iinfo(dtype).min, dtype)


def _dtype_max(dtype):
    dtype = np.dtype(dtype)
    if dtype.kind == "f":
        return np.array(np.inf, dtype)
    return np.array(np.iinfo(dtype).max, dtype)


_IDENTITY = {
    "sum": lambda dt: np.zeros((), dt),
    "prod": lambda dt: np.ones((), dt),
    "min": _dtype_max,
    "max": _dtype_min,
    "land": lambda dt: np.array(True),
    "lor": lambda dt: np.array(False),
    "lxor": lambda dt: np.array(False),
    "band": lambda dt: np.array(-1).astype(dt),
    "bor": lambda dt: np.zeros((), dt),
    "bxor": lambda dt: np.zeros((), dt),
}

SUM = Op("sum")
PROD = Op("prod")
MIN = Op("min")
MAX = Op("max")
LAND = Op("land")
LOR = Op("lor")
LXOR = Op("lxor")
BAND = Op("band")
BOR = Op("bor")
BXOR = Op("bxor")

_BY_NAME = {
    op.name: op
    for op in (SUM, PROD, MIN, MAX, LAND, LOR, LXOR, BAND, BOR, BXOR)
}


def named_op(name):
    """Look up an :class:`Op` by name (case-insensitive)."""
    try:
        return _BY_NAME[name.lower()]
    except KeyError:
        raise ValueError(
            f"unknown reduction op {name!r}; valid: {sorted(_BY_NAME)}"
        ) from None


def rank_ordered_fold(rows, op, upto=None):
    """Left fold of per-rank operand rows (axis 0, in rank order) with
    ``op.combine`` — the one shared reduction kernel behind every
    backend's user-op (``Op.Create``) path and the non-native builtin
    fallbacks (the reference forwards user handles to libmpi, which
    applies the callback per reduction step; mpi4jax/_src/utils.py:77-96).

    Rank order makes ``commute=False`` safe.  ``upto`` folds only ranks
    ``[0, upto]`` (inclusive prefix for scan).  Combines must be
    shape-preserving (checked); a dtype-promoting combine is cast back
    to the buffer dtype, since MPI reductions preserve the datatype.
    """
    n = rows.shape[0] if upto is None else upto + 1
    acc = rows[0]
    for i in range(1, n):
        acc = op.combine(acc, rows[i])
    acc = jnp.asarray(acc)
    if acc.shape != rows.shape[1:]:
        raise ValueError(
            f"reduction op {op.name!r} combine changed the operand shape "
            f"{rows.shape[1:]} -> {acc.shape}; reduction combines must "
            "be shape-preserving"
        )
    return acc.astype(rows.dtype)


def group_psum(x, axes, groups=None):
    """psum across ``axes``, independently per subgroup when ``groups``
    is set (via grouped all_gather — shard_map's grouped psum is
    unimplemented in current JAX)."""
    if groups is None:
        return lax.psum(x, axes)
    gathered = lax.all_gather(
        x, axes, axis=0, tiled=False, axis_index_groups=groups
    )
    # accumulate in the input dtype: .sum() would promote sub-32-bit
    # ints (and bool) to int32, but psum/MPI_Allreduce preserve the
    # buffer type
    return gathered.sum(axis=0, dtype=x.dtype)


def mesh_allreduce(x, op, axes, groups=None):
    """Reduce ``x`` with ``op`` across the mesh axes, result on every device.

    Fast paths use native XLA collectives (data stays in HBM, rides ICI);
    operators with no native collective fall back to all_gather + local
    ``lax.reduce`` — semantically the reference's MPI_Allreduce with an
    arbitrary MPI.Op (mpi4jax/_src/collective_ops/allreduce.py:36-66).
    ``groups`` (from a split communicator) becomes XLA's
    axis_index_groups: one independent reduction per subgroup.
    """
    from mpi4jax_tpu.ops._core import promote_vma

    x = promote_vma(x, axes)
    dtype = x.dtype
    if op.is_user:
        # User-defined op (MPI.Op.Create analog): all_gather, then the
        # shared rank-ordered fold (commute=False safe).
        gathered = lax.all_gather(
            x, axes, axis=0, tiled=False, axis_index_groups=groups
        )
        return rank_ordered_fold(gathered, op)
    if op.name in ("sum", "lxor") and groups is not None:
        # shard_map's grouped psum is unimplemented in current JAX; the
        # grouped all_gather path is, so sum per subgroup via gather+add.
        gathered = lax.all_gather(
            x.astype(jnp.int32) if dtype == jnp.bool_ else x,
            axes,
            axis=0,
            tiled=False,
            axis_index_groups=groups,
        )
        # dtype= keeps the buffer type (psum semantics); .sum() alone
        # would promote sub-32-bit ints to int32
        total = gathered.sum(axis=0, dtype=gathered.dtype)
        if op.name == "lxor":
            return total % 2 != 0
        return total != 0 if dtype == jnp.bool_ else total
    if op.name == "sum":
        if dtype == jnp.bool_:
            return lax.psum(x.astype(jnp.int32), axes) != 0
        return lax.psum(x, axes)
    if op.name == "min":
        if dtype == jnp.bool_:
            return lax.pmin(x.astype(jnp.int8), axes, axis_index_groups=groups).astype(jnp.bool_)
        return lax.pmin(x, axes, axis_index_groups=groups)
    if op.name == "max":
        if dtype == jnp.bool_:
            return lax.pmax(x.astype(jnp.int8), axes, axis_index_groups=groups).astype(jnp.bool_)
        return lax.pmax(x, axes, axis_index_groups=groups)
    if op.name == "land":
        return lax.pmin(x.astype(jnp.int8), axes, axis_index_groups=groups).astype(jnp.bool_)
    if op.name == "lor":
        return lax.pmax(x.astype(jnp.int8), axes, axis_index_groups=groups).astype(jnp.bool_)
    if op.name == "lxor":
        return lax.psum(x.astype(jnp.int32), axes, axis_index_groups=groups) % 2 != 0
    # prod / band / bor / bxor: gather then reduce locally.
    gathered = lax.all_gather(x, axes, axis=0, tiled=False, axis_index_groups=groups)
    init = jnp.asarray(op.identity(dtype), dtype)
    return lax.reduce(gathered, init, op.combine, dimensions=(0,))
