"""allreduce — differentiable all-reduce over a communicator.

API contract follows the reference op
(mpi4jax/_src/collective_ops/allreduce.py:36-66) including its autodiff
convention (JVP at allreduce.py:164-179, transpose at :182-194):

* ``jvp(allreduce_SUM) = allreduce_SUM`` applied to the tangent,
  serialised on the primal's token chain;
* ``transpose(allreduce_SUM) = identity`` (the cotangent of a replicated
  result is already replicated), and a double transpose is a real
  allreduce again — implemented, as in the reference, with a ``transpose``
  primitive parameter that flips on every transposition and lowers to an
  identity when set (allreduce.py:77-79);
* non-SUM ops are not differentiable (NotImplementedError), matching
  allreduce.py:168-171.

This convention deliberately differs from ``lax.psum`` (whose transpose is
mathematically ``psum``), which is why allreduce is a custom JAX primitive
rather than a bare collective: the primitive owns its AD rules and lowers
via ``mlir.lower_fun`` to ``lax.psum``/``pmin``/``pmax`` inside the
enclosing ``shard_map``, so on TPU the data path is a single XLA
all-reduce over ICI that never leaves HBM.
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.extend.core import Primitive
from jax.interpreters import ad, batching, mlir

from mpi4jax_tpu.ops import reductions
from mpi4jax_tpu.ops._core import (
    Token,
    as_token,
    fence_in,
    fence_out,
    publishes_token,
)
from mpi4jax_tpu.utils.validation import check_comm, check_op

__all__ = ["allreduce", "BucketedGradSync"]

allreduce_p = Primitive("mpi4jax_tpu_allreduce")
allreduce_p.multiple_results = True


@publishes_token
def allreduce(x, op=reductions.SUM, *, comm=None, token=None):
    """All-reduce ``x`` with ``op`` across ``comm``.

    Returns ``(result, token)``.  Differentiable for ``op=SUM``.
    """
    op = check_op(op)
    comm = check_comm(comm)
    token = as_token(token)
    x = jnp.asarray(x)
    res, stamp = allreduce_p.bind(
        x, token.stamp, op=op, comm=comm, transpose=False
    )
    return res, token.with_stamp(stamp)


def _allreduce_impl(x, stamp, *, op, comm, transpose):
    if transpose:
        # Identity leg of the transpose pair (allreduce.py:77-79).
        return x, stamp
    tok = Token(stamp)
    if comm.backend == "self":
        tok, (x,) = fence_out(tok, x)
        return x, tok.stamp
    if comm.backend == "mesh":
        tok, (x,) = fence_in(tok, x)
        y = reductions.mesh_allreduce(x, op, comm.axes, comm.groups)
        tok, (y,) = fence_out(tok, y)
        return y, tok.stamp
    if comm.backend == "proc":
        from mpi4jax_tpu.ops import _proc

        return _proc.proc_allreduce(x, stamp, op, comm)
    raise NotImplementedError(
        f"allreduce not implemented for backend {comm.backend!r}"
    )


def _allreduce_abstract_eval(x, stamp, *, op, comm, transpose):
    return x, stamp


def _allreduce_jvp(primals, tangents, *, op, comm, transpose):
    # Reference semantics: tangent rides the same token chain as the
    # primal so the two collectives stay ordered (allreduce.py:164-179).
    if op.name != "sum" or op.is_user:
        raise NotImplementedError(
            "JVP of allreduce is only defined for op=SUM "
            "(reference: allreduce.py:168-171)"
        )
    x, stamp = primals
    xt, _ = tangents
    y, out_stamp = allreduce_p.bind(x, stamp, op=op, comm=comm, transpose=transpose)
    if type(xt) is ad.Zero:
        yt = ad.Zero(jax.typeof(y))
    else:
        # Tangent collective is serialised on the primal's token chain;
        # primal outputs stay independent of tangent inputs.
        yt, _ = allreduce_p.bind(
            xt, out_stamp, op=op, comm=comm, transpose=transpose
        )
    return (y, out_stamp), (yt, ad.Zero(jax.typeof(out_stamp)))


def _allreduce_transpose(cts, x, stamp, *, op, comm, transpose):
    if op.name != "sum" or op.is_user:
        raise NotImplementedError(
            "transpose of allreduce is only defined for op=SUM"
        )
    y_ct, _ = cts
    if type(y_ct) is ad.Zero:
        x_ct = ad.Zero(x.aval if ad.is_undefined_primal(x) else jax.typeof(x))
    else:
        fresh = jnp.zeros((), jnp.float32)
        x_ct, _ = allreduce_p.bind(
            y_ct, fresh, op=op, comm=comm, transpose=not transpose
        )
    stamp_ct = (
        ad.Zero(stamp.aval) if ad.is_undefined_primal(stamp) else None
    )
    return (
        x_ct if ad.is_undefined_primal(x) else None,
        stamp_ct,
    )


def _allreduce_batch(args, dims, *, op, comm, transpose):
    # The underlying collectives reduce over mesh axes, not array axes, so
    # batching is a pass-through (reference: allreduce.py:158-161).
    x, stamp = args
    xd, _ = dims
    y, out_stamp = allreduce_p.bind(x, stamp, op=op, comm=comm, transpose=transpose)
    return (y, out_stamp), (xd, batching.not_mapped)


allreduce_p.def_impl(_allreduce_impl)
allreduce_p.def_abstract_eval(_allreduce_abstract_eval)
ad.primitive_jvps[allreduce_p] = _allreduce_jvp
ad.primitive_transposes[allreduce_p] = _allreduce_transpose
batching.primitive_batchers[allreduce_p] = _allreduce_batch
mlir.register_lowering(
    allreduce_p, mlir.lower_fun(_allreduce_impl, multiple_results=True)
)


class BucketedGradSync:
    """DDP-style bucketed gradient synchronisation with compute/comm
    overlap (docs/async.md "gradient bucketing").

    Flattens a gradient pytree into buckets of about
    ``T4J_BUCKET_BYTES`` (grouped per dtype, greedy fill), launches one
    nonblocking :func:`~mpi4jax_tpu.iallreduce` per bucket, and waits
    every request at the end — the optimizer-step boundary.  Buckets
    are built in **reverse leaf order** by default because backprop
    produces the LAST layers' gradients first: submitting their bucket
    early lets the native progress engine run its wire phase while XLA
    is still computing the earlier layers' gradients, which is where
    the measured step-time win comes from
    (``benchmarks/transformer.py --overlap``).

    ``overlap=False`` keeps the exact same bucket layout but issues a
    blocking ``allreduce`` per bucket — the control arm of the
    interleaved on/off benchmark pairs, and the automatic fallback on
    backends without nonblocking support (mesh).

    Usage (pure data-parallel step)::

        sync = BucketedGradSync(comm_dp)
        grads, token = sync(grads, token=token)   # mean over comm_dp

    ``average=False`` returns sums instead of means.

    **Error feedback for compressed wire dtypes** (docs/performance.md
    "Compressed collectives"): when ``T4J_WIRE_DTYPE`` (or the
    calibrator) selects a low-precision wire dtype, pass a residual
    pytree through ``residuals`` and the sync quantises each f32
    bucket to the wire dtype BEFORE the allreduce, carrying the
    quantisation error into the next step::

        res = {}                                   # step 0: no carry
        grads, token, res = sync(grads, token=token, residuals=res)

    Per bucket: ``send = grad + residual_in``, ``q = upcast(downcast(
    send))``, ``residual_out = send - q`` and ``q`` is what travels —
    already wire-representable, so the native downcast is lossless on
    the first hop and the residual accounts for the whole local
    quantisation error (it is exactly zero when the stream is wire-
    representable, e.g. a constant integer-valued gradient).  Master
    weights and the returned gradients stay f32.  The residual dict is
    per-rank MUTABLE state: checkpoint it with the optimizer state.
    Residuals are world-stamped: every returned dict carries a
    ``"_world"`` key holding ``(epoch, alive_count)`` from the live
    membership view, and a sync that sees a residual dict stamped with
    a DIFFERENT epoch drops the carried residuals instead of folding a
    pre-resize quantisation error into the post-resize stream — the
    sharp bit docs/sharp-bits.md "error-feedback residuals are
    per-rank state" documents, now enforced here rather than left to
    caller discipline.  A per-bucket shape mismatch (the bucket layout
    changed under the carrier) likewise drops that bucket's residual
    rather than crashing the first post-resize step.  Without
    ``residuals`` the call keeps the classic 2-tuple signature and
    never quantises in Python (the native wire layer may still
    compress eligible comms).
    """

    def __init__(self, comm=None, bucket_bytes=None, average=True,
                 overlap=True, reverse=True):
        self.comm = check_comm(comm)
        if bucket_bytes is None:
            from mpi4jax_tpu.utils import config

            bucket_bytes = config.bucket_bytes()
        self.bucket_bytes = max(1, int(bucket_bytes))
        self.average = bool(average)
        # nonblocking requests are a proc-tier concept; the self
        # backend supports them trivially, the mesh backend does not
        # (ops/async_.py) — fall back to blocking buckets there
        self.overlap = bool(overlap) and self.comm.backend != "mesh"
        self.reverse = bool(reverse)

    def _buckets(self, leaves):
        """Greedy per-dtype grouping of leaf indices into byte-bounded
        buckets, in (optionally reversed) leaf order."""
        order = range(len(leaves) - 1, -1, -1) if self.reverse else range(
            len(leaves)
        )
        buckets = []
        open_by_dtype = {}
        for i in order:
            leaf = leaves[i]
            nbytes = leaf.size * jnp.dtype(leaf.dtype).itemsize
            key = str(leaf.dtype)
            cur = open_by_dtype.get(key)
            if cur is None or cur["bytes"] + nbytes > self.bucket_bytes:
                cur = {"dtype": key, "idx": [], "bytes": 0}
                open_by_dtype[key] = cur
                buckets.append(cur)
            cur["idx"].append(i)
            cur["bytes"] += nbytes
        return buckets

    def _wire_dtype(self):
        """The effective wire dtype as the Python layer sees it:
        ``"off"`` unless the comm is proc-tier and the native bridge
        reports a non-off mode (env knob or calibrator fit, applied at
        ``tuning.startup``).  Per-comm eligibility (same-host hops) is
        the native layer's business; quantising here when the wire
        happens to be exact is still correct — ``q`` is what every rank
        reduces, and the residual accounts for the error exactly."""
        if self.comm.backend != "proc":
            return "off"
        try:
            from mpi4jax_tpu.native import runtime

            info = runtime.wire_dtype_info()
        except Exception:
            info = None
        return (info or {}).get("wire_dtype", "off")

    def _world_stamp(self):
        """``(epoch, alive_count)`` from the live membership view, or
        ``None`` outside a proc-tier native job — the residual-dict
        validity stamp (a residual quantised against one membership's
        stream is stale in the next epoch)."""
        if self.comm.backend != "proc":
            return None
        try:
            from mpi4jax_tpu.native import runtime

            info = runtime.world_info()
        except Exception:
            info = None
        if not info:
            return None
        return (int(info["epoch"]), int(info["alive_count"]))

    @staticmethod
    def _wire_jnp_dtype(mode):
        if mode == "bf16":
            return jnp.bfloat16
        if mode == "fp8":
            # ml_dtypes e4m3fn, same wire format as the native cast
            # (overflow behaviour differs at |x| > 448: jax converts to
            # NaN where the wire saturates — gradients that large have
            # already left fp8's useful range)
            return getattr(jnp, "float8_e4m3fn", None)
        return None

    def sync(self, grads, *, token=None, residuals=None):
        """Return ``(synced_grads, token)`` — the same pytree with every
        leaf summed (or averaged) over the communicator.

        With ``residuals`` (a dict, ``{}`` on the first step) the
        return is ``(synced_grads, token, new_residuals)`` and each f32
        bucket is error-feedback quantised to the effective wire dtype
        (see the class docstring); non-f32 buckets and ``"off"`` mode
        pass through untouched (their residual keys are dropped)."""
        import jax as _jax

        from mpi4jax_tpu.ops._core import as_token
        from mpi4jax_tpu.ops.async_ import iallreduce, wait

        token = as_token(token)
        leaves, treedef = _jax.tree_util.tree_flatten(grads)
        if not leaves:
            if residuals is None:
                return grads, token
            return grads, token, {}
        leaves = [jnp.asarray(x) for x in leaves]
        ef = residuals is not None
        qdt = self._wire_jnp_dtype(self._wire_dtype()) if ef else None
        new_res = {} if ef else None
        carried = residuals if (ef and hasattr(residuals, "get")) else {}
        if ef:
            stamp = self._world_stamp()
            prev_stamp = carried.get("_world") if carried else None
            if (stamp is not None and prev_stamp is not None
                    and tuple(prev_stamp) != stamp):
                # resize-epoch commit: the carried residuals were
                # quantised against the old membership's stream — drop
                # them wholesale rather than fold stale error in
                carried = {}
            if stamp is not None:
                new_res["_world"] = stamp
        scale = 1.0 / float(self.comm.size) if self.average else None
        pending = []  # (bucket, request-or-reduced)
        for bi, bucket in enumerate(self._buckets(leaves)):
            flat = jnp.concatenate(
                [leaves[i].reshape(-1) for i in bucket["idx"]]
            )
            if qdt is not None and bucket["dtype"] == "float32":
                # error feedback: fold the carried residual in, send
                # the wire-representable rounding of the sum, keep the
                # rounding error for the next step.  Keyed by bucket
                # index — the greedy layout is deterministic for a
                # fixed pytree, so keys are stable across steps.
                prev = carried.get(bi) if carried else None
                if prev is not None:
                    prev = jnp.asarray(prev, flat.dtype)
                    if prev.shape != flat.shape:
                        # bucket layout changed under the carrier (a
                        # resized world re-shards the pytree): a
                        # wrong-shape residual is stale, not an error
                        prev = None
                if prev is not None:
                    flat = flat + prev
                q = flat.astype(qdt).astype(flat.dtype)
                new_res[bi] = flat - q
                flat = q
            if self.overlap:
                req, token = iallreduce(
                    flat, reductions.SUM, comm=self.comm, token=token
                )
                pending.append((bucket, req))
            else:
                red, token = allreduce(
                    flat, reductions.SUM, comm=self.comm, token=token
                )
                pending.append((bucket, red))
        out = list(leaves)
        for bucket, handle in pending:
            if self.overlap:
                red, token = wait(handle, token=token)
            else:
                red = handle
            if scale is not None:
                red = red * jnp.asarray(scale, red.dtype)
            off = 0
            for i in bucket["idx"]:
                n = leaves[i].size
                out[i] = red[off:off + n].reshape(leaves[i].shape)
                off += n
        synced = _jax.tree_util.tree_unflatten(treedef, out)
        if ef:
            return synced, token, new_res
        return synced, token

    __call__ = sync
