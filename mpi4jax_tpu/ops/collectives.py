"""Collective ops: allgather, alltoall, barrier, bcast, gather, reduce,
scan, scatter.

API surface mirrors the reference one-to-one
(mpi4jax/__init__.py:26-38); each docstring cites the matching reference
op.  On the mesh backend every op is a composition of XLA ICI collectives
(``all_gather`` / ``all_to_all`` / ``psum`` / ``ppermute``) inside the
enclosing ``shard_map`` — data never leaves HBM.  Autodiff falls out of
the underlying collectives' JAX rules, a superset of the reference (which
defines AD only for allreduce and sendrecv).

On the multi-process backend the native bridge picks the data plane
per call: same-host comms ride the shm arena, cross-host ones the
tree/segmented-ring TCP algorithms, and multi-host topologies with
several ranks per host the hierarchical shm-leaf + leader-ring plane
(selection knobs ``T4J_HIER`` / ``T4J_LEADER_RING_MIN_BYTES``;
docs/performance.md).  ``ops._proc.proc_topology`` exposes the
(host_id, local_rank, leader_rank) map the selection is built on.

SPMD note (the MPMD↔SPMD gap, SURVEY §7): the reference's rooted ops have
*rank-dependent output shapes* — e.g. gather returns ``(nproc, *shape)``
on root and the input unchanged elsewhere
(mpi4jax/_src/collective_ops/gather.py:74-87).  A single SPMD program must
have uniform shapes, so here rooted ops return the root's result on
*every* member: ``gather ≡ allgather``, ``reduce ≡ allreduce`` value-wise.
Off-root values are well-defined (not garbage); programs written against
the reference's root-only guarantees remain correct.  The multi-process
backend preserves exact MPMD shapes.
"""

from functools import partial

import numpy as np

import jax.numpy as jnp
from jax import lax

from mpi4jax_tpu.ops import reductions
from mpi4jax_tpu.ops._core import (
    as_token,
    fence_in,
    fence_out,
    promote_vma,
    publishes_token,
)
from mpi4jax_tpu.ops.allreduce import allreduce
from mpi4jax_tpu.utils.validation import check_comm, check_op, check_root

__all__ = [
    "allgather",
    "alltoall",
    "alltoall_multi",
    "barrier",
    "bcast",
    "gather",
    "reduce",
    "reduce_scatter",
    "scan",
    "scatter",
]


def _prologue(x, comm, token):
    comm = check_comm(comm)
    token = as_token(token)
    x = jnp.asarray(x) if x is not None else None
    return x, comm, token


def _unsupported(name, comm):
    return NotImplementedError(
        f"{name} not implemented for backend {comm.backend!r}"
    )


@publishes_token
def allgather(x, *, comm=None, token=None):
    """Gather ``x`` from every rank onto every rank.

    Output shape is ``(comm.size, *x.shape)`` on all ranks (reference:
    mpi4jax/_src/collective_ops/allgather.py:35-74, out shape at
    :167-174).
    """
    x, comm, token = _prologue(x, comm, token)
    if comm.backend == "self":
        y = x[None]
        token, (y,) = fence_out(token, y)
        return y, token
    if comm.backend == "mesh":
        token, (x,) = fence_in(token, x)
        x = promote_vma(x, comm.axes)
        y = lax.all_gather(
            x, comm.axes, axis=0, tiled=False, axis_index_groups=comm.groups
        )
        token, (y,) = fence_out(token, y)
        return y, token
    if comm.backend == "proc":
        from mpi4jax_tpu.ops import _proc

        y, stamp = _proc.proc_allgather(x, token.stamp, comm)
        return y, token.with_stamp(stamp)
    raise _unsupported("allgather", comm)


@publishes_token
def alltoall(x, *, comm=None, token=None):
    """All-to-all block exchange.

    ``x`` must have leading dimension ``comm.size`` (checked eagerly, as
    in the reference — mpi4jax/_src/collective_ops/alltoall.py:62-64);
    output row ``j`` is rank ``j``'s row ``rank``.
    """
    x, comm, token = _prologue(x, comm, token)
    if x.ndim == 0 or x.shape[0] != comm.size:
        raise ValueError(
            # wording matches the reference's check (alltoall.py:62-64
            # there; its own test suite asserts on the phrase)
            f"alltoall input must have shape (nproc, ...) with nproc == "
            f"comm.size={comm.size}, got shape {x.shape}"
        )
    if comm.backend == "self":
        token, (x,) = fence_out(token, x)
        return x, token
    if comm.backend == "mesh":
        token, (x,) = fence_in(token, x)
        x = promote_vma(x, comm.axes)
        y = lax.all_to_all(
            x, comm.axes, split_axis=0, concat_axis=0, tiled=True,
            axis_index_groups=comm.groups,
        )
        token, (y,) = fence_out(token, y)
        return y, token
    if comm.backend == "proc":
        from mpi4jax_tpu.ops import _proc

        y, stamp = _proc.proc_alltoall(x, token.stamp, comm)
        return y, token.with_stamp(stamp)
    raise _unsupported("alltoall", comm)


@publishes_token
def alltoall_multi(parts, *, comm=None, token=None, coalesce=None):
    """Several independent alltoalls at once — the coalescing entry
    point for per-expert dispatch (docs/performance.md "small-message
    coalescing"; ``parallel.moe.topk_moe`` with multiple experts per
    rank is the canonical caller).

    Semantically identical to one :func:`alltoall` per part
    (bit-identical outputs), but on the multi-process backend a small
    run travels as ONE fused frame per peer — carrying that peer's
    slice of every part — instead of ``len(parts)`` frames per peer.
    Fusion applies when the combined per-peer payload is at or below
    ``T4J_COALESCE_BYTES``; ``coalesce=True``/``False`` forces a side,
    ``T4J_COALESCE_BYTES=0`` restores the exact per-part wire
    behaviour.  Returns ``(outs, token)``.
    """
    comm = check_comm(comm)
    token = as_token(token)
    parts = [jnp.asarray(p) for p in parts]
    for p in parts:
        if p.ndim == 0 or p.shape[0] != comm.size:
            raise ValueError(
                f"alltoall input must have shape (nproc, ...) with "
                f"nproc == comm.size={comm.size}, got shape {p.shape}"
            )
    if not parts:
        return [], token
    if comm.backend == "proc" and len(parts) > 1:
        if isinstance(coalesce, bool):
            fuse = coalesce
        else:
            from mpi4jax_tpu import tuning

            per_peer = sum(
                int(p.size) * p.dtype.itemsize // comm.size
                for p in parts
            )
            fuse = tuning.coalesce_eligible(per_peer, len(parts))
        if fuse:
            from mpi4jax_tpu.ops import _proc

            outs, stamp = _proc.proc_alltoall_fused(
                parts, token.stamp, comm
            )
            return outs, token.with_stamp(stamp)
    outs = []
    for p in parts:
        y, token = alltoall(p, comm=comm, token=token)
        outs.append(y)
    return outs, token


@publishes_token
def barrier(*, comm=None, token=None):
    """Synchronisation barrier; returns only a token (reference:
    mpi4jax/_src/collective_ops/barrier.py:32-53).

    On the mesh backend this is a zero-payload ``psum`` chained into the
    token, forcing a cross-device rendezvous at this point in the program
    order.
    """
    comm = check_comm(comm)
    token = as_token(token)
    if comm.backend == "self":
        return token
    if comm.backend == "mesh":
        z = jnp.zeros((), jnp.int32)
        token, (z,) = fence_in(token, z)
        s = reductions.group_psum(z, comm.axes, comm.groups)
        token, _ = fence_out(token, s)
        return token
    if comm.backend == "proc":
        from mpi4jax_tpu.ops import _proc

        stamp = _proc.proc_barrier(token.stamp, comm)
        return token.with_stamp(stamp)
    raise _unsupported("barrier", comm)


def _bcast_schedule(size, nbytes):
    """Pick the bcast schedule.

    ``tree`` (binomial ppermute ladder) does ``ceil(log2 n)`` rounds —
    latency-optimal on high-latency fabrics, total traffic
    ``~payload*log2(n)``.  ``psum`` (masked all-reduce) costs one ring
    all-reduce, ``~2*(n-1)/n*payload``.  Measured on the 8-device
    virtual mesh (docs/performance.md "bcast schedule measurement")
    psum wins at every payload from 4 KB to 64 MB, so it is the
    default; override with MPI4JAX_TPU_BCAST=tree|psum.
    """
    import os

    del size, nbytes
    forced = os.environ.get("MPI4JAX_TPU_BCAST")
    if forced in ("tree", "psum"):
        return forced
    return "psum"


def _bcast_psum(xv, root, comm):
    """Masked all-reduce: non-root contributions zeroed, one psum
    delivers the root's value everywhere."""
    rank = comm.rank()
    masked = jnp.where(rank == root, xv, jnp.zeros_like(xv))
    return reductions.group_psum(masked, comm.axes, comm.groups)


def _bcast_tree(xv, root, comm):
    """Binomial-tree broadcast: round k ppermutes the payload from the
    first ``2**k`` (root-relative) ranks to the next ``2**k``."""
    size = comm.size
    rank = comm.rank()
    vrank = (rank - root) % size  # traced; perms below are static
    acc = jnp.where(rank == root, xv, jnp.zeros_like(xv))
    k = 1
    while k < size:
        pairs = [
            ((v + root) % size, (v + k + root) % size)
            for v in range(min(k, size - k))
        ]
        shifted = lax.ppermute(acc, comm.axes, comm.expand_perm(pairs))
        acc = jnp.where((vrank >= k) & (vrank < 2 * k), shifted, acc)
        k *= 2
    return acc


@publishes_token
def bcast(x, root, *, comm=None, token=None):
    """Broadcast ``x`` from ``root`` to every rank (reference:
    mpi4jax/_src/collective_ops/bcast.py:36-72).

    Two mesh schedules (selected by :func:`_bcast_schedule`): masked
    ``psum`` by default (measured fastest at every payload size), with
    a binomial ``ppermute`` tree available via ``MPI4JAX_TPU_BCAST=tree``
    for high-latency fabrics.
    """
    x, comm, token = _prologue(x, comm, token)
    root = check_root(root, comm)
    if comm.backend == "self":
        token, (x,) = fence_out(token, x)
        return x, token
    if comm.backend == "mesh":
        token, (x,) = fence_in(token, x)
        as_int = x.dtype == jnp.bool_
        xv = x.astype(jnp.int8) if as_int else x
        xv = promote_vma(xv, comm.axes)
        if _bcast_schedule(comm.size, xv.size * xv.dtype.itemsize) == "tree":
            y = _bcast_tree(xv, root, comm)
        else:
            y = _bcast_psum(xv, root, comm)
        if as_int:
            y = y.astype(jnp.bool_)
        token, (y,) = fence_out(token, y)
        return y, token
    if comm.backend == "proc":
        from mpi4jax_tpu.ops import _proc

        y, stamp = _proc.proc_bcast(x, token.stamp, comm, root)
        return y, token.with_stamp(stamp)
    raise _unsupported("bcast", comm)


@publishes_token
def gather(x, root, *, comm=None, token=None):
    """Gather ``x`` from every rank to ``root`` (reference:
    mpi4jax/_src/collective_ops/gather.py:36-87).

    Mesh backend: output is ``(comm.size, *x.shape)`` on every rank (SPMD
    uniform-shape note in the module docstring).
    """
    comm_r = check_comm(comm)
    root = check_root(root, comm_r)
    if comm_r.backend == "proc":
        from mpi4jax_tpu.ops import _proc

        x, comm_r, token = _prologue(x, comm_r, token)
        y, stamp = _proc.proc_gather(x, token.stamp, comm_r, root)
        token = token.with_stamp(stamp)
        if comm_r.rank() != root:
            # MPMD rank-dependent shape: unmodified input off-root
            return x, token
        return y, token
    del root  # value identical on every member under SPMD
    return allgather(x, comm=comm, token=token)


@publishes_token
def reduce(x, op, root, *, comm=None, token=None):
    """Reduce ``x`` with ``op`` to ``root`` (reference:
    mpi4jax/_src/collective_ops/reduce.py:37-71).

    Mesh backend: result is delivered on every rank (≡ allreduce).
    """
    op = check_op(op)
    comm_r = check_comm(comm)
    root = check_root(root, comm_r)
    if comm_r.backend == "proc":
        from mpi4jax_tpu.ops import _proc

        x, comm_r, token = _prologue(x, comm_r, token)
        y, stamp = _proc.proc_reduce(x, token.stamp, op, comm_r, root)
        return y, token.with_stamp(stamp)
    del root
    return allreduce(x, op, comm=comm, token=token)


@publishes_token
def reduce_scatter(x, op=reductions.SUM, *, comm=None, token=None):
    """Reduce ``x`` with ``op`` across ranks and scatter the result by
    row blocks (``MPI_Reduce_scatter_block``): rank ``r`` receives the
    reduction over all ranks of row ``r``.

    An **extension op** — not one of the reference's twelve (mpi4jax has
    no reduce_scatter; its MPI parent is standard) — included because it
    is the native TPU collective: with ``op=SUM`` it lowers to one
    ``lax.psum_scatter``, the ring reduce-scatter the ICI torus is
    optimised for, at O(payload) wire cost where ``allreduce`` of the
    same data costs ~2x.  Gradient sharding (ZeRO-style optimizer
    partitioning) is the canonical use — see
    ``models/train.py:make_global_zero_train_step``.

    ``x`` must have shape ``(comm.size, *rest)`` on every rank; the
    result has shape ``rest``.  Identity: ``reduce_scatter(x)`` on rank
    ``r`` equals ``allreduce(x)[r]``.  Differentiable for ``op=SUM``
    (the composition transposes to an ``all_gather``).  On the mesh
    backend, non-SUM and user-defined ops ride an ``all_to_all`` +
    rank-ordered local fold (correct for ``commute=False`` operators);
    on the proc backend every builtin op is a single native
    ``reduce_scatter`` over the DCN bridge — the segmented ring at
    large payloads, ``O((n-1)/n * payload)`` per link, and on
    multi-host topologies with several ranks per host the hierarchical
    shm-leaf + leader-ring plane, which cuts cross-host traffic by the
    local world size (docs/performance.md "TCP-tier algorithm
    selection" / "hierarchical collectives") — and only user-defined
    ops take the ``all_to_all`` + fold detour.
    """
    x, comm, token = _prologue(x, comm, token)
    op = check_op(op)
    if x.ndim == 0 or x.shape[0] != comm.size:
        raise ValueError(
            f"reduce_scatter input must have shape (nproc, ...) with "
            f"nproc == comm.size={comm.size}, got shape {x.shape}"
        )
    as_int = x.dtype == jnp.bool_
    # axis 0 is source-rank order after the exchange, so the shared
    # rank-ordered fold gives the commute=False contract
    fold_rows = partial(reductions.rank_ordered_fold, op=op)

    if comm.backend == "self":
        y = x[0]
        token, (y,) = fence_out(token, y)
        return y, token
    if comm.backend == "mesh":
        token, (x,) = fence_in(token, x)
        xv = promote_vma(x, comm.axes)
        if op.name == "sum" and not op.is_user:
            # bool rides the int8 psum_scatter like scatter does; the
            # final nonzero→True cast matches the general path's fold
            y = _scatter_sum(xv.astype(jnp.int8) if as_int else xv, comm)
            if as_int:
                y = y.astype(jnp.bool_)
        else:
            xv = xv.astype(jnp.int8) if as_int else xv
            rows = lax.all_to_all(
                xv, comm.axes, split_axis=0, concat_axis=0, tiled=True,
                axis_index_groups=comm.groups,
            )
            y = fold_rows(rows)
            if as_int:
                y = y.astype(jnp.bool_)
        token, (y,) = fence_out(token, y)
        return y, token
    if comm.backend == "proc":
        from mpi4jax_tpu.ops import _proc

        xv = x.astype(jnp.int8) if as_int else x
        if not op.is_user:
            # native segmented ring reduce-scatter (dcn.cc): the
            # scattered-gradient collective ZeRO wants, at
            # O((n-1)/n * payload) per link — the alltoall + fold
            # detour ships the same bytes but pays the fold on every
            # rank and a full staging pass
            y, stamp = _proc.proc_reduce_scatter(xv, token.stamp, op, comm)
        else:
            rows, stamp = _proc.proc_alltoall(xv, token.stamp, comm)
            y = fold_rows(rows)
        if as_int:
            y = y.astype(jnp.bool_)
        return y, token.with_stamp(stamp)
    raise _unsupported("reduce_scatter", comm)


@publishes_token
def scan(x, op, *, comm=None, token=None):
    """Inclusive prefix reduction over ranks (MPI_Scan; reference:
    mpi4jax/_src/collective_ops/scan.py:36-61).

    XLA has no native prefix collective (SURVEY §7 hard part 4); this is a
    Hillis–Steele ladder of ``ceil(log2(size))`` masked ``ppermute`` steps
    over ICI.
    """
    x, comm, token = _prologue(x, comm, token)
    op = check_op(op)
    if comm.backend == "self":
        token, (x,) = fence_out(token, x)
        return x, token
    if comm.backend == "mesh":
        size = comm.size
        token, (x,) = fence_in(token, x)
        rank = comm.rank()
        as_int = x.dtype == jnp.bool_
        acc = x.astype(jnp.int8) if as_int else x
        acc = promote_vma(acc, comm.axes)
        dist = 1
        while dist < size:
            perm = comm.expand_perm(
                [(r, r + dist) for r in range(size - dist)]
            )
            shifted = lax.ppermute(acc, comm.axes, perm)
            # lower-rank prefix on the left: correct for non-commutative
            # (user-defined, commute=False) operators
            combined = op.combine(shifted.astype(acc.dtype), acc)
            acc = jnp.where(rank >= dist, combined.astype(acc.dtype), acc)
            dist *= 2
        if as_int:
            acc = acc.astype(jnp.bool_)
        token, (acc,) = fence_out(token, acc)
        return acc, token
    if comm.backend == "proc":
        from mpi4jax_tpu.ops import _proc

        y, stamp = _proc.proc_scan(x, token.stamp, op, comm)
        return y, token.with_stamp(stamp)
    raise _unsupported("scan", comm)


@publishes_token
def scatter(x, root, *, comm=None, token=None):
    """Scatter rows of ``x`` from ``root`` (reference:
    mpi4jax/_src/collective_ops/scatter.py:36-92).

    ``x`` must have shape ``(comm.size, *rest)`` (the reference checks
    this on root, scatter.py:77-81; under SPMD every member passes the
    same template and only the root's values matter).  Returns the row at
    index ``rank``.
    """
    x, comm, token = _prologue(x, comm, token)
    root = check_root(root, comm)
    if comm.backend == "proc":
        from mpi4jax_tpu.ops import _proc

        if comm.rank() == root and (x.ndim == 0 or x.shape[0] != comm.size):
            raise ValueError(
                # reference wording (scatter.py:77-81 there)
                f"Scatter input must have shape (nproc, ...) with nproc "
                f"== comm.size={comm.size} on root, got shape {x.shape}"
            )
        y, stamp = _proc.proc_scatter(x, token.stamp, comm, root)
        return y, token.with_stamp(stamp)
    if x.ndim == 0 or x.shape[0] != comm.size:
        raise ValueError(
            # wording matches the reference's check (scatter.py:77-81
            # there; its own test suite asserts on the phrase)
            f"Scatter input must have shape (nproc, ...) with nproc == "
            f"comm.size={comm.size}, got shape {x.shape}"
        )
    if comm.backend == "self":
        y = x[0]
        token, (y,) = fence_out(token, y)
        return y, token
    if comm.backend == "mesh":
        token, (x,) = fence_in(token, x)
        rank = comm.rank()
        as_int = x.dtype == jnp.bool_
        xv = x.astype(jnp.int8) if as_int else x
        xv = promote_vma(xv, comm.axes)
        masked = jnp.where(rank == root, xv, jnp.zeros_like(xv))
        # reduce-scatter of the masked buffer: rank r receives
        # sum_over_ranks(row r) = root's row r.  O(payload) on the wire
        # (ring reduce-scatter), vs O(size*payload) for a full psum.
        y = _scatter_sum(masked, comm)
        if as_int:
            y = y.astype(jnp.bool_)
        token, (y,) = fence_out(token, y)
        return y, token
    raise _unsupported("scatter", comm)


def _scatter_sum(masked, comm):
    """``psum_scatter`` row ``rank`` of the summed buffer to each rank."""
    if comm.groups is None:
        return lax.psum_scatter(
            masked, comm.axes, scatter_dimension=0, tiled=False
        )
    return lax.psum_scatter(
        masked,
        comm.axes,
        scatter_dimension=0,
        axis_index_groups=comm.groups,
        tiled=False,
    )
