"""Point-to-point ops: send, recv, sendrecv (+ Status).

Reference API: mpi4jax/_src/collective_ops/send.py:37-60,
recv.py:39-84, sendrecv.py:41-103.

The MPMD→SPMD translation (SURVEY §7 hard part 1): the reference's p2p
ops are *per-rank* calls — rank 0 runs ``send`` while rank 1 runs
``recv`` in a different program.  A single SPMD program is uniform across
devices, so here a p2p pattern is specified *globally*:

* ``dest`` / ``source`` may be a **callable** ``rank -> partner`` (return
  ``None`` to sit out, the MPI_PROC_NULL analog) or an explicit list of
  ``(source_rank, dest_rank)`` pairs;
* a plain ``int`` is only meaningful on size-1 / multi-process backends
  — on a MeshComm it raises with guidance, since "every rank sends to
  rank k" is not a permutation.

``sendrecv`` lowers to one ``lax.ppermute`` over ICI.  Its transpose is
the inverse permutation — exactly the reference's transpose rule that
swaps source and dest so gradients travel the reverse network direction
(sendrecv.py:366-385) — and unlike the reference, forward-mode also works
(the reference hard-errors at sendrecv.py:128-133).

Lone ``send``/``recv`` pairs are matched **at trace time** through the
token: ``send`` stages its payload and pattern on the token's
pending-send queue, and the matching ``recv`` pops it and emits the fused
``ppermute``.  This reproduces MPI's eager-send/matching-recv semantics
(including tag matching and FIFO message order per pattern) with zero
runtime rendezvous cost.  The deadlock-freedom the reference must test
for (tests/collective_ops/test_send_and_recv.py:104-117) holds by
construction: a ppermute cannot deadlock.

Patterns that trace-time matching cannot express fall back to the
**host rendezvous** tier (ops/_rendezvous.py): a ``send`` whose ``dest``
is a traced (data-dependent) per-rank value posts its payload to the
in-process matching engine via ``io_callback``, and a wildcard ``recv``
with no trace-time match takes the earliest-arriving envelope match at
execution time — the reference's runtime ``ANY_SOURCE``/``ANY_TAG``
semantics (recv.py:39-47), with the Status reporting the true runtime
source.  Single-host scope (the engine is per-process); true
cross-process MPMD stays on the proc backend.
"""

import numpy as np

import jax.numpy as jnp
from jax import lax

from mpi4jax_tpu.ops._core import (
    ANY_SOURCE,
    ANY_TAG,
    PendingSendMeta,
    as_token,
    comm_key,
    fence_in,
    fence_out,
    publishes_token,
)
from mpi4jax_tpu.utils.validation import (
    check_comm,
    check_rank_range,
    check_static_int,
)

__all__ = [
    "send",
    "recv",
    "sendrecv",
    "sendrecv_multi",
    "Status",
    "ANY_SOURCE",
    "ANY_TAG",
]


class Status:
    """Output status for recv/sendrecv (MPI.Status analog).

    ``source`` and ``tag`` are filled on return; ``source`` may be a
    traced per-device value on the mesh backend.  The mpi4py accessor
    methods (``Get_source``/``Get_tag``/``Get_error``) are provided for
    call-compatibility with reference user code.
    """

    def __init__(self):
        self.source = None
        self.tag = None

    def Get_source(self):
        return self.source

    def Get_tag(self):
        return self.tag

    def Get_error(self):
        return 0


def _deliver_status(status, st):
    """Fill a Status object, working under jit too.

    Eager values are assigned synchronously (the old behaviour).  Under
    a trace, the reference bakes the MPI_Status struct's address into
    the executable and writes through it at execution time
    (sendrecv.py status out-param; utils.py:35-39 pointer plumbing);
    the JAX-native equivalent is a debug callback that receives the
    concrete envelope each run and mutates the object — read the status
    after the op's results are materialised (or ``jax.effects_barrier``)
    just as the reference requires the execution to have happened.
    """
    import jax

    if not isinstance(st, jax.core.Tracer):
        vals = np.asarray(st)
        status.source = int(vals[0])
        status.tag = int(vals[1])
        return

    def setter(vals):
        status.source = int(vals[0])
        status.tag = int(vals[1])

    jax.debug.callback(setter, st)


def _resolve_pairs(spec, size, role):
    """Normalise a p2p partner spec into (source, dest) pairs.

    ``role`` is "dest" (spec maps rank -> where its data goes) or
    "source" (spec maps rank -> where its data comes from).
    """
    if callable(spec):
        pairs = []
        for r in range(size):
            p = spec(r)
            if p is None:
                continue
            p = int(p)
            if not 0 <= p < size:
                raise ValueError(
                    f"{role} callable returned rank {p} for rank {r}, out "
                    f"of range for communicator of size {size}. Wrap "
                    f"explicitly (e.g. (r + 1) % size) for periodic "
                    f"patterns, or return None to sit out."
                )
            pairs.append((r, p) if role == "dest" else (p, r))
        return pairs
    if isinstance(spec, (list, tuple)) and all(
        isinstance(e, (list, tuple)) and len(e) == 2 for e in spec
    ):
        return [(int(s), int(d)) for s, d in spec]
    value = check_static_int(spec, role)
    if size == 1:
        if value != 0:
            raise ValueError(
                f"{role}={value} out of range for communicator of size 1"
            )
        return [(0, 0)]
    raise ValueError(
        f"{role}={value!r}: a bare integer rank is ambiguous under SPMD "
        f"(all {size} devices would target the same rank, which is not a "
        f"permutation). Pass a callable rank->partner (e.g. "
        f"lambda r: (r + 1) % size), an explicit list of (source, dest) "
        f"pairs, or use comm.shift_perm(axis, disp). Per-rank integer "
        f"addressing, as in MPI, works on the multi-process backend "
        f"(python -m mpi4jax_tpu.launch)."
    )


def _validate_perm(pairs, size, what):
    srcs = [s for s, _ in pairs]
    dsts = [d for _, d in pairs]
    if len(set(srcs)) != len(srcs) or len(set(dsts)) != len(dsts):
        raise ValueError(f"{what} pattern is not a permutation: {pairs}")
    for s, d in pairs:
        if not (0 <= s < size and 0 <= d < size):
            raise ValueError(f"{what} pattern rank out of range: {pairs}")
    return pairs


def _ppermute(x, axes, pairs):
    from mpi4jax_tpu.ops._core import promote_vma

    x = promote_vma(x, axes)
    if all(s == d for s, d in pairs):
        # pure self-sends (e.g. periodic wrap on a size-1 mesh axis): a
        # CollectivePermute would deliver x to every listed rank and 0
        # elsewhere, and callers mask non-destination ranks with the
        # recv template anyway (_recv_merge) — so the collective is an
        # identity with launch overhead.  Eliding it removes ~50 no-op
        # collectives per shallow-water step on a single chip.
        return x
    if x.dtype == jnp.bool_:
        return lax.ppermute(x.astype(jnp.int8), axes, pairs).astype(jnp.bool_)
    return lax.ppermute(x, axes, pairs)


def _recv_merge(permuted, template, pairs, comm):
    """Ranks with no inbound message keep their recv buffer (MPI leaves
    recvbuf untouched for MPI_PROC_NULL partners)."""
    size = comm.size
    if len(pairs) == size:
        return permuted
    has_msg = np.zeros(size, bool)
    for _, d in pairs:
        has_msg[d] = True
    mask = jnp.asarray(has_msg)[comm.rank()]
    return jnp.where(mask, permuted, template)


def _static_source_of(pairs, comm):
    src_of = np.full(comm.size, ANY_SOURCE, np.int32)
    for s, d in pairs:
        src_of[d] = s
    return jnp.asarray(src_of)[comm.rank()]


def _is_runtime_rank(spec):
    """A p2p partner given as a traced per-rank value (data-dependent
    routing) — only resolvable at execution time."""
    import jax

    return isinstance(spec, jax.core.Tracer)


def _is_static_rank_int(spec):
    """A partner given as a plain static int — bools are rejected on
    every rank-taking path (``check_static_int`` semantics), so they
    must not slip through to the rendezvous routes either."""
    return isinstance(spec, (int, np.integer)) and not isinstance(
        spec, (bool, np.bool_)
    )


def _check_tag(tag, rendezvous_ok):
    """Tags are static on the trace-time matching paths (matching keys on
    the value); the rendezvous tier accepts traced tags (they ride the
    io_callback operands).  ADVICE r3: a traced tag used to fall through
    to a generic concretization error."""
    if _is_runtime_rank(tag):
        if rendezvous_ok:
            return tag
        raise TypeError(
            "tag must be a static (trace-time) integer here: trace-time "
            "send/recv matching keys on the tag value. A traced "
            "(runtime-valued) tag is supported only on the mesh backend's "
            "rendezvous tier — send with an int or traced dest, or recv "
            "with an int or traced source or source=ANY_SOURCE (pattern-"
            "list partners stay trace-matched and need a static tag)."
        )
    return check_static_int(tag, "tag")


def _proc_partner(spec, comm, role):
    """Resolve a p2p partner spec to THIS process's partner rank on the
    multi-process backend.

    A plain int keeps the MPI per-rank addressing (each process passes
    its own value).  A callable or (source, dest) pair list — the
    mesh-backend pattern vocabulary, what ``shift_perm`` produces — is
    resolved against ``comm.rank()``; returns ``None`` when this rank
    has no partner in the pattern (the MPI_PROC_NULL analog: the op
    side simply drops out).  This is what lets grid-shaped code (halo
    exchanges over a :class:`~mpi4jax_tpu.parallel.proc.ProcGridComm`)
    run unchanged on OS-process worlds.
    """
    if _is_static_rank_int(spec):
        return check_rank_range(int(spec), role, comm.size)
    pairs = _resolve_pairs(spec, comm.size, role)
    me = int(comm.rank())
    if role == "dest":
        mine = [d for s, d in pairs if s == me]
    else:
        mine = [s for s, d in pairs if d == me]
    if not mine:
        return None
    if len(mine) > 1:
        raise ValueError(
            f"{role} pattern gives rank {me} {len(mine)} partners "
            f"({mine}); a p2p op takes exactly one — split the pattern "
            "into separate calls"
        )
    return mine[0]


def _rendezvous_send(x, dest, tag, comm, token):
    """Mesh send with a runtime destination: post the local shard to the
    host matching engine (ops/_rendezvous.py) via io_callback."""
    import jax
    from jax.experimental import io_callback

    from mpi4jax_tpu.ops._core import promote_vma
    from mpi4jax_tpu.ops._rendezvous import engine

    key = comm_key(comm)
    size = comm.size
    token, (x,) = fence_in(token, x)

    def post_cb(rank_v, dest_v, tag_v, payload, stamp):
        dest_i = int(dest_v)
        if not 0 <= dest_i < size:
            raise RuntimeError(
                f"rendezvous send: dest={dest_i} out of range for "
                f"communicator of size {size} (runtime-valued dest)"
            )
        tag_i = int(tag_v)
        if tag_i < 0:
            # a computed tag that lands on -1 would otherwise become the
            # ANY wildcard in the engine — silent mismatched delivery
            raise RuntimeError(
                f"rendezvous send: tag={tag_i} is negative (runtime-"
                "valued tags must be >= 0; wildcards are recv-only)"
            )
        engine().post(
            key, int(rank_v), dest_i, tag_i, np.asarray(payload).copy()
        )
        return np.asarray(stamp)

    # tag rides the operands, not the closure: a traced (runtime-valued)
    # tag is then just as legal as a runtime dest (ADVICE r3 — a closure
    # int(tag) on a tracer died with a generic concretization error)
    stamp = io_callback(
        post_cb,
        jax.ShapeDtypeStruct((), np.float32),
        comm.rank(), dest, jnp.int32(tag), x, token.stamp,
        ordered=False,
    )
    return token.with_stamp(promote_vma(stamp, comm.axes))


def _rendezvous_recv(x, source, tag, comm, token, status):
    """Mesh recv with runtime envelope matching: block in an
    io_callback until the engine has a message whose (source, tag)
    matches; Status reports the true runtime source."""
    import jax
    from jax.experimental import io_callback

    from mpi4jax_tpu.ops._core import promote_vma
    from mpi4jax_tpu.ops._rendezvous import engine

    key = comm_key(comm)
    if _is_runtime_rank(source):
        want = source
    else:
        # a static source reaches here either as the ANY_SOURCE wildcard
        # or as a specific rank paired with a traced tag (ADVICE r4) —
        # the engine matches both shapes at runtime
        want = jnp.int32(int(source))
    token, _ = fence_in(token)

    shape, dtype = tuple(x.shape), x.dtype

    tag_is_traced = _is_runtime_rank(tag)

    def take_cb(rank_v, want_v, tag_v, stamp):
        tag_i = int(tag_v)
        if tag_is_traced and tag_i < 0:
            # only the STATIC ANY_TAG constant may wildcard: a computed
            # traced tag that evaluates to -1 is a bug, not a wildcard
            raise RuntimeError(
                f"rendezvous recv on rank {int(rank_v)}: runtime-valued "
                f"tag={tag_i} is negative (pass the static ANY_TAG "
                "constant for a wildcard)"
            )
        payload, src, tg = engine().take(
            key, int(rank_v), int(want_v), tag_i
        )
        payload = np.asarray(payload)
        if payload.shape != shape or payload.dtype != np.dtype(dtype):
            raise RuntimeError(
                f"rendezvous recv on rank {int(rank_v)}: matched message "
                f"has shape/dtype {payload.shape}/{payload.dtype}, but "
                f"the recv template expects {shape}/{np.dtype(dtype)}"
            )
        return payload, np.int32(src), np.int32(tg), np.asarray(stamp)

    y, src, tg, stamp = io_callback(
        take_cb,
        (
            jax.ShapeDtypeStruct(shape, dtype),
            jax.ShapeDtypeStruct((), np.int32),
            jax.ShapeDtypeStruct((), np.int32),
            jax.ShapeDtypeStruct((), np.float32),
        ),
        comm.rank(), want, jnp.int32(tag), token.stamp,
        ordered=False,
    )
    y = promote_vma(y, comm.axes)
    token = token.with_stamp(promote_vma(stamp, comm.axes))
    if status is not None:
        # mesh-backend Status convention (class docstring): the fields
        # are per-device traced values — here the TRUE runtime envelope
        # as matched by the engine, not a trace-time reconstruction
        status.source = promote_vma(src, comm.axes)
        status.tag = promote_vma(tg, comm.axes)
    return y, token


@publishes_token
def send(x, dest, tag=0, *, comm=None, token=None):
    """Stage a send of ``x`` along the ``dest`` pattern; returns a token
    (reference: mpi4jax/_src/collective_ops/send.py:37-60 — returns token
    only, send.py:139-140).

    The payload rides the token until the matching :func:`recv` in the
    same trace consumes it.
    """
    comm = check_comm(comm)
    token = as_token(token)
    x = jnp.asarray(x)
    if comm.backend == "proc":
        from mpi4jax_tpu.ops import _proc

        tag = check_static_int(tag, "tag")
        dest = _proc_partner(dest, comm, "dest")
        if dest is None:
            return token  # no partner in the pattern (MPI_PROC_NULL)
        stamp = _proc.proc_send(x, token.stamp, comm, dest, tag)
        return token.with_stamp(stamp)
    if comm.backend == "mesh" and (
        _is_runtime_rank(dest)
        or (_is_runtime_rank(tag) and _is_static_rank_int(dest))
    ):
        # data-dependent destination (trace-time matching needs a static
        # pattern) or a traced tag on a single-rank dest (the matching
        # recv keys on the runtime tag value, so both sides must meet in
        # the engine; ADVICE r4) — route through the host rendezvous tier
        if not _is_runtime_rank(dest):
            dest = check_rank_range(dest, "dest", comm.size)
        return _rendezvous_send(x, dest, _check_tag(tag, True), comm, token)
    tag = _check_tag(tag, False)
    pairs = _resolve_pairs(dest, comm.size, "dest")
    _validate_perm(pairs, comm.size, "send dest")
    meta = PendingSendMeta(
        perm=tuple(sorted(pairs)),
        tag=tag,
        comm_key=comm_key(comm),
        shape=tuple(x.shape),
        dtype=str(x.dtype),
    )
    return token.push_send(x, meta)


@publishes_token
def recv(x, source=ANY_SOURCE, tag=ANY_TAG, *, comm=None, token=None, status=None):
    """Receive into the shape/dtype of template ``x`` (a template only —
    arrays are immutable; reference: mpi4jax/_src/collective_ops/
    recv.py:39-84, ANY defaults at recv.py:39-47).

    Matches the earliest staged :func:`send` on the token whose
    communicator, tag and pattern are compatible, and emits the fused
    ``ppermute``.
    """
    comm = check_comm(comm)
    token = as_token(token)
    x = jnp.asarray(x)
    if comm.backend == "proc":
        from mpi4jax_tpu.ops import _proc

        tag = check_static_int(tag, "tag")
        if _is_static_rank_int(source) and int(source) == ANY_SOURCE:
            source = ANY_SOURCE
        else:
            source = _proc_partner(source, comm, "source")
        if source is None:
            # no inbound message in the pattern: keep the recv buffer
            # (MPI_PROC_NULL semantics, matching the mesh merge path)
            if status is not None:
                status.source, status.tag = -1, -1
            return x, token
        y, stamp, st = _proc.proc_recv(x, token.stamp, comm, source, tag)
        if status is not None:
            _deliver_status(status, st)
        return y, token.with_stamp(stamp)
    source_is_any = (
        isinstance(source, (int, np.integer)) and int(source) == ANY_SOURCE
    )
    if comm.backend == "mesh" and (
        _is_runtime_rank(source)
        or (_is_runtime_rank(tag) and _is_static_rank_int(source))
    ):
        # runtime-valued source (no static pattern to match against) or
        # a traced tag (trace-time matching cannot key on it; the engine
        # matches any static-int or wildcard source at runtime, ADVICE
        # r4): match at execution time in the host engine
        if not _is_runtime_rank(source) and not source_is_any:
            source = check_rank_range(source, "source", comm.size)
        return _rendezvous_recv(
            x, source, _check_tag(tag, True), comm, token, status
        )
    tag = _check_tag(tag, False)
    want_pairs = None
    if not source_is_any:
        want_pairs = frozenset(
            _validate_perm(
                _resolve_pairs(source, comm.size, "source"), comm.size, "recv source"
            )
        )

    key = comm_key(comm)
    for i, meta in enumerate(token.pending_meta):
        if meta.comm_key != key:
            continue
        if tag != ANY_TAG and meta.tag != tag:
            continue
        if want_pairs is not None and frozenset(meta.perm) != want_pairs:
            continue
        if meta.shape != tuple(x.shape) or meta.dtype != str(x.dtype):
            raise ValueError(
                f"recv template shape/dtype {x.shape}/{x.dtype} does not "
                f"match staged send {meta.shape}/{meta.dtype}"
            )
        payload, meta, token = token.pop_send(i)
        pairs = list(meta.perm)
        if comm.backend == "self":
            token, (y,) = fence_out(token, payload)
        elif comm.backend == "mesh":
            token, (payload,) = fence_in(token, payload)
            y = _ppermute(payload, comm.axes, comm.expand_perm(pairs))
            y = _recv_merge(y, x, pairs, comm)
            token, (y,) = fence_out(token, y)
        else:
            raise NotImplementedError(
                f"recv not implemented for backend {comm.backend!r}"
            )
        if status is not None:
            if comm.backend == "self":
                status.source, status.tag = 0, meta.tag
            else:
                status.source = _static_source_of(pairs, comm)
                status.tag = meta.tag
        return y, token

    if comm.backend == "mesh" and source_is_any:
        # wildcard recv with no trace-time match: the message must be
        # coming from a runtime-routed send — match it at execution
        # time through the host engine (reference recv.py:39-47
        # semantics; Status reports the true runtime source)
        return _rendezvous_recv(x, source, tag, comm, token, status)
    staged = "; ".join(
        f"tag={meta.tag} perm={meta.perm} "
        f"{meta.dtype}[{'x'.join(map(str, meta.shape))}]"
        + ("" if meta.comm_key == key else " (different comm)")
        for meta in token.pending_meta
    )
    wanted = (
        f"tag={'ANY' if tag == ANY_TAG else tag}, source="
        f"{'ANY' if want_pairs is None else sorted(want_pairs)}"
    )
    raise RuntimeError(
        "recv found no matching in-trace send on this token. This recv "
        f"wants {wanted}; the token carries "
        + (f"staged send(s) [{staged}]" if staged else "no staged sends")
        + ". Under SPMD, "
        "send and recv must be paired within the same trace (the send "
        "stages its payload on the token; pass that token to recv). For "
        "true cross-process MPMD p2p use the multi-process backend."
    )


@publishes_token
def sendrecv(
    sendbuf,
    recvbuf,
    source,
    dest,
    sendtag=0,
    recvtag=ANY_TAG,
    *,
    comm=None,
    token=None,
    status=None,
):
    """Combined send+receive (reference: mpi4jax/_src/collective_ops/
    sendrecv.py:41-103).

    ``dest`` gives where each rank's ``sendbuf`` goes, ``source`` where
    its ``recvbuf`` comes from; the two views must describe the same
    global permutation.  Lowers to one ``lax.ppermute``; transposition
    reverses the permutation (reference transpose rule:
    sendrecv.py:366-385).
    """
    comm = check_comm(comm)
    token = as_token(token)
    check_static_int(sendtag, "sendtag")
    check_static_int(recvtag, "recvtag")
    sendbuf = jnp.asarray(sendbuf)
    recvbuf = jnp.asarray(recvbuf)
    if comm.backend == "proc":
        from mpi4jax_tpu.ops import _proc

        source = _proc_partner(source, comm, "source")
        dest = _proc_partner(dest, comm, "dest")
        if source is None and dest is None:
            if status is not None:
                status.source, status.tag = -1, -1
            return recvbuf, token
        if source is None:
            # send-only edge of a non-periodic pattern: the recv buffer
            # is returned unchanged (MPI_PROC_NULL recv side)
            stamp = _proc.proc_send(
                sendbuf, token.stamp, comm, dest, sendtag
            )
            if status is not None:
                status.source, status.tag = -1, -1
            return recvbuf, token.with_stamp(stamp)
        if dest is None:
            y, stamp, st = _proc.proc_recv(
                recvbuf, token.stamp, comm, source, recvtag
            )
            if status is not None:
                _deliver_status(status, st)
            return y, token.with_stamp(stamp)
        y, stamp, st = _proc.proc_sendrecv(
            sendbuf, recvbuf, token.stamp, comm, source, dest, sendtag,
            recvtag,
        )
        if status is not None:
            _deliver_status(status, st)
        return y, token.with_stamp(stamp)
    if comm.backend == "self":
        token, (y,) = fence_out(token, sendbuf)
        if status is not None:
            status.source, status.tag = 0, sendtag
        return y, token
    if comm.backend == "mesh":
        if tuple(sendbuf.shape) != tuple(recvbuf.shape) or sendbuf.dtype != recvbuf.dtype:
            raise ValueError(
                "mesh-backend sendrecv requires uniform send/recv "
                f"shapes and dtypes, got {sendbuf.shape}/{sendbuf.dtype} vs "
                f"{recvbuf.shape}/{recvbuf.dtype}"
            )
        dpairs = _validate_perm(
            _resolve_pairs(dest, comm.size, "dest"), comm.size, "sendrecv dest"
        )
        source_is_any = isinstance(source, (int, np.integer)) and int(source) == ANY_SOURCE
        if not source_is_any:
            spairs = _resolve_pairs(source, comm.size, "source")
            if frozenset(spairs) != frozenset(dpairs):
                raise ValueError(
                    "sendrecv source and dest views disagree: "
                    f"dest implies {sorted(dpairs)}, source implies "
                    f"{sorted(spairs)}. They must describe one global "
                    "permutation."
                )
        pairs_global = comm.expand_perm(dpairs)
        if all(s == d for s, d in pairs_global):
            # pure self-exchange (periodic wrap on a size-1 mesh axis):
            # no data crosses devices, so there is no cross-device
            # ordering to enforce — skip the token fences entirely.
            # This lets XLA fuse across the op: on a single chip the
            # whole solver step becomes a handful of fusions instead of
            # being cut at every (elided) exchange.
            y = _recv_merge(_ppermute(sendbuf, comm.axes, pairs_global),
                            recvbuf, dpairs, comm)
        else:
            token, (payload,) = fence_in(token, sendbuf)
            y = _ppermute(payload, comm.axes, pairs_global)
            y = _recv_merge(y, recvbuf, dpairs, comm)
            token, (y,) = fence_out(token, y)
        if status is not None:
            status.source = _static_source_of(dpairs, comm)
            status.tag = sendtag
        return y, token
    raise NotImplementedError(
        f"sendrecv not implemented for backend {comm.backend!r}"
    )


@publishes_token
def sendrecv_multi(
    sendbufs,
    recvbufs,
    source,
    dest,
    sendtag=0,
    recvtag=ANY_TAG,
    *,
    comm=None,
    token=None,
    status=None,
    coalesce=None,
):
    """Exchange several same-pattern messages at once — the coalescing
    entry point (docs/performance.md "small-message coalescing").

    Semantically identical to one :func:`sendrecv` per
    ``(sendbufs[i], recvbufs[i])`` pair along the same
    ``source``/``dest`` pattern (bit-identical results), but on the
    multi-process backend a small run travels as ONE fused wire frame
    — a single header + gathered payloads — instead of one frame per
    part.  Fusion applies when the combined payload is at or below
    ``T4J_COALESCE_BYTES`` (autotuner-calibrated; both sides derive
    the decision from the same knob).  ``coalesce=True``/``False``
    forces a side (benchmark plumbing); ``T4J_COALESCE_BYTES=0``
    restores the exact per-part wire behaviour.

    ``sendbufs`` and ``recvbufs`` are independent lists (they usually
    pair up, as in a halo exchange).  Returns ``(outs, token)`` with
    ``outs`` shaped like ``recvbufs``; ranks without an inbound
    partner in the pattern keep their recv buffers (MPI_PROC_NULL).
    """
    comm = check_comm(comm)
    token = as_token(token)
    check_static_int(sendtag, "sendtag")
    check_static_int(recvtag, "recvtag")
    sendbufs = [jnp.asarray(b) for b in sendbufs]
    recvbufs = [jnp.asarray(b) for b in recvbufs]
    if comm.backend == "proc":
        from mpi4jax_tpu.ops import _proc

        my_src = _proc_partner(source, comm, "source")
        my_dst = _proc_partner(dest, comm, "dest")
        sends = sendbufs if my_dst is not None else []
        recvs = recvbufs if my_src is not None else []
        if not sends and not recvs:
            if status is not None:
                status.source, status.tag = -1, -1
            return list(recvbufs), token

        # Fusion is decided PER WIRE DIRECTION: my send decision must
        # match my receiver's expectation, and it does because both
        # compute eligibility from the same part shapes (the program is
        # uniform across ranks) and the same T4J_COALESCE_BYTES — an
        # edge rank with only one side still agrees with its interior
        # peer about that one direction.
        def _eligible(bufs):
            if isinstance(coalesce, bool):
                return coalesce and len(bufs) >= 1
            from mpi4jax_tpu import tuning

            total = sum(int(b.size) * b.dtype.itemsize for b in bufs)
            return tuning.coalesce_eligible(total, len(bufs))

        fuse_send = bool(sends) and _eligible(sendbufs)
        fuse_recv = bool(recvs) and _eligible(recvbufs)
        outs = None
        st = None
        if fuse_send and fuse_recv:
            out = _proc.proc_sendrecv_fused(
                sends, recvs, token.stamp, comm, my_src, my_dst,
                sendtag, recvtag,
            )
            outs = list(out[:len(recvs)])
            token = token.with_stamp(out[len(recvs)])
            st = out[len(recvs) + 1]
        else:
            if fuse_send:
                out = _proc.proc_sendrecv_fused(
                    sends, [], token.stamp, comm, -1, my_dst, sendtag,
                    recvtag,
                )
                token = token.with_stamp(out[0])
            elif sends:
                # unfused: the exact pre-coalescing wire behaviour, one
                # frame per part (eager sends first — cannot deadlock)
                for sb in sends:
                    token = send(sb, my_dst, sendtag, comm=comm,
                                 token=token)
            if fuse_recv:
                out = _proc.proc_sendrecv_fused(
                    [], recvs, token.stamp, comm, my_src, -1, sendtag,
                    recvtag,
                )
                outs = list(out[:len(recvs)])
                token = token.with_stamp(out[len(recvs)])
                st = out[len(recvs) + 1]
            elif recvs:
                outs = []
                for rb in recvs:
                    y, token = recv(
                        rb, my_src, recvtag, comm=comm, token=token,
                        status=status,
                    )
                    outs.append(y)
        if status is not None:
            if st is not None:
                _deliver_status(status, st)
            elif not recvs:
                status.source, status.tag = -1, -1
        return (outs if recvs else list(recvbufs)), token
    if comm.backend == "self":
        outs = []
        for sb, rb in zip(sendbufs, recvbufs):
            y, token = sendrecv(
                sb, rb, source, dest, sendtag, recvtag, comm=comm,
                token=token, status=status,
            )
            outs.append(y)
        return outs, token
    if comm.backend == "mesh":
        # one ppermute per part; fusion is a wire-tier concept (the ICI
        # tier has no frame overhead to amortise — batching there is
        # the caller's jnp.stack, see halo_exchange_2d_batch)
        outs = []
        for sb, rb in zip(sendbufs, recvbufs):
            y, token = sendrecv(
                sb, rb, source, dest, sendtag, recvtag, comm=comm,
                token=token, status=status,
            )
            outs.append(y)
        return outs, token
    raise NotImplementedError(
        f"sendrecv_multi not implemented for backend {comm.backend!r}"
    )
