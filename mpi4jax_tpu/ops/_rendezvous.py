"""Host-side rendezvous matching for mesh-backend p2p with *runtime*
semantics (SURVEY §7 hard part 1).

The mesh backend matches ``send``/``recv`` pairs at trace time whenever
the pattern is static (ops/p2p.py) — zero runtime cost, deadlock-free
by construction.  What trace-time matching cannot express is the
reference's execution-time envelope matching
(mpi4jax/_src/collective_ops/recv.py:39-47, where libmpi matches
``ANY_SOURCE``/``ANY_TAG`` when the message actually arrives):

* a **data-dependent destination** — ``send(x, dest)`` where ``dest``
  is a traced per-rank value, unknowable at trace time;
* a **wildcard recv with no trace-time match** — the message will come
  from a send whose destination is itself runtime-valued.

Those ops route through this engine: an in-process mailbox with MPI
matching semantics (arrival order per destination; a recv takes the
EARLIEST-arrived message whose envelope matches its ``source``/``tag``
wants, wildcards matching anything).  Each device's op runs an
``io_callback`` — posts are non-blocking, takes block on a condition
variable until a matching envelope arrives (or a configurable timeout
diagnoses the deadlock).  Device-side ordering rides the token stamp
through the callbacks, the library's universal ordering model
(ops/_core.py).

This is the single-host analog of the DCN matching engine
(native/src/dcn.cc) that serves the multi-process backend; the proc
tier keeps serving true cross-process MPMD.
"""

import atexit
import os
import threading

ANY = -1  # matches ops._core.ANY_SOURCE / ANY_TAG

# Every diagnosed failure this module raises starts with one of these
# prefixes; the atexit hook absorbs ONLY failures carrying them, so an
# unrelated error whose text merely contains "rendezvous" still surfaces
# through jax's own drain (ADVICE r4).
_DIAG_MARKERS = (
    "rendezvous recv on rank",
    "rendezvous send:",
)


@atexit.register
def _absorb_failed_dispatches():
    """Exit-time hygiene for diagnosed rendezvous failures.

    A timeout raised inside a rendezvous ``io_callback`` is delivered to
    the consumer when it blocks on the op's result — but the poisoned
    XLA runtime token stays queued, and jax's own atexit drain
    (jax._src.dispatch.wait_for_tokens) re-raises it as an ``Exception
    ignored in atexit callback`` traceback after otherwise-clean runs
    (ADVICE r3).  This hook runs *before* jax's (atexit is LIFO and jax
    registers at jax-import time, which precedes package import) and
    absorbs failures that are ours and already diagnosed; anything else
    is left for jax's drain to surface normally.
    """
    try:
        from jax._src.dispatch import runtime_tokens
    except Exception:  # private API moved: fall back to jax's behavior
        return
    pending = list(runtime_tokens.current_tokens.values())
    pending += list(runtime_tokens.output_runtime_tokens.values())
    foreign_failure = False
    absorbed = 0
    for token in pending:
        try:
            token.block_until_ready()
        except Exception as e:  # noqa: BLE001 — classify, don't handle
            if any(m in str(e) for m in _DIAG_MARKERS):
                absorbed += 1
            else:
                foreign_failure = True  # not ours: keep jax's diagnostic
    if absorbed:
        # a fire-and-forget program (result never materialised) would
        # otherwise exit with NO trace of the failure: one concise line
        # preserves the diagnostic without the atexit traceback
        import sys

        print(
            f"mpi4jax_tpu: absorbed {absorbed} failed rendezvous "
            "dispatch(es) at exit (the diagnosis was raised on the op's "
            "results; see MPI4JAX_TPU_RENDEZVOUS_TIMEOUT docs)",
            file=sys.stderr,
        )
    if absorbed and not foreign_failure:
        # clear only when something WAS absorbed: a clean exit (or a
        # purely foreign failure) keeps jax's bookkeeping untouched
        runtime_tokens.clear()


def _timeout():
    try:
        return float(os.environ.get("MPI4JAX_TPU_RENDEZVOUS_TIMEOUT", "60"))
    except ValueError:
        return 60.0


class Engine:
    """Thread-safe mailbox with MPI envelope matching.

    Messages are keyed by ``(comm_key, dest_rank)``; within a mailbox
    they queue in arrival order.  ``take`` returns the earliest message
    whose ``(source, tag)`` envelope matches the caller's wants —
    exactly MPI's matching rule for a single-threaded receiver.
    """

    def __init__(self):
        self._cv = threading.Condition()
        self._boxes = {}  # (comm_key, dest) -> [(source, tag, payload)]
        # set when any take times out: wakes every other blocked take
        # promptly instead of letting each serve its own full timeout
        # (which would otherwise stall the process again at interpreter
        # exit while jax drains the still-blocked callbacks).  Cleared
        # automatically once the blocked cohort has drained, so a retry
        # in the same process starts clean.
        self._poisoned = False
        self._waiters = 0

    @staticmethod
    def _log(make_line):
        """DEBUG-gated engine trace, in the spirit of the wire-format op
        logging (ops/_core.py _debug_begin): one line per post/match so
        a runtime-matching bug is reconstructible from the transcript.
        Toggled by the same MPI4JAX_TPU_DEBUG switch.  Takes a LAZY
        line producer so the disabled path pays no formatting."""
        from mpi4jax_tpu.utils.config import debug_enabled

        if debug_enabled():
            print(make_line(), flush=True)

    def post(self, key, source, dest, tag, payload):
        with self._cv:
            self._boxes.setdefault((key, dest), []).append(
                (source, tag, payload)
            )
            self._cv.notify_all()
        self._log(
            lambda: f"r{source} | rendezvous | post -> r{dest} tag={tag} "
            f"({payload.size} items)"
        )

    def _match(self, box, want_source, want_tag):
        for i, (src, tag, _payload) in enumerate(box):
            if want_source != ANY and src != want_source:
                continue
            if want_tag != ANY and tag != want_tag:
                continue
            return i
        return None

    def take(self, key, rank, want_source, want_tag, timeout=None):
        timeout = _timeout() if timeout is None else timeout
        with self._cv:
            idx = None

            def ready():
                nonlocal idx
                box = self._boxes.get((key, rank))
                if box:
                    idx = self._match(box, want_source, want_tag)
                    if idx is not None:
                        return True  # a real match always wins
                return self._poisoned

            self._waiters += 1
            try:
                if not self._cv.wait_for(ready, timeout=timeout):
                    self._poisoned = True  # free the other blocked ranks
                    self._cv.notify_all()
                    raise RuntimeError(
                        f"rendezvous recv on rank {rank} timed out after "
                        f"{timeout:.0f}s waiting for a message matching "
                        f"source="
                        f"{'ANY' if want_source == ANY else want_source}, "
                        f"tag={'ANY' if want_tag == ANY else want_tag}. "
                        "Either the matching send never executes (deadlock "
                        "— check every rank posts its send before blocking "
                        "in recv, i.e. the ops share one token chain) or "
                        "it targets a different rank/tag. Raise "
                        "MPI4JAX_TPU_RENDEZVOUS_TIMEOUT if the send is "
                        "just slow."
                    )
                if idx is None:  # woken by poisoning, not by a match
                    raise RuntimeError(
                        f"rendezvous recv on rank {rank} aborted: another "
                        "rank's rendezvous recv timed out (deadlock "
                        "propagated — see that rank's error for the "
                        "diagnosis)"
                    )
                src, tag, payload = self._boxes[(key, rank)].pop(idx)
            finally:
                self._waiters -= 1
                if self._waiters == 0:
                    self._poisoned = False  # cohort drained: start clean
        self._log(
            lambda: f"r{rank} | rendezvous | matched <- r{src} tag={tag} "
            f"(wanted source="
            f"{'ANY' if want_source == ANY else want_source}, "
            f"tag={'ANY' if want_tag == ANY else want_tag})"
        )
        return payload, src, tag

    def reset(self):
        """Drop all queued messages and clear poisoning (new run /
        test isolation)."""
        with self._cv:
            self._boxes.clear()
            self._poisoned = False

    def pending_count(self):
        with self._cv:
            return sum(len(b) for b in self._boxes.values())


_engine = Engine()


def engine():
    return _engine


# ---------------------------------------------------------- topology map
#
# (host_id, local_rank, leader_rank) registry for the in-process tiers,
# the single-host counterpart of the native bridge's bootstrap topology
# (native/runtime.py topology()).  Mesh/self "ranks" are devices of one
# process on one host, so the published default is the trivial map —
# but MPMD-style harnesses that emulate several hosts in one process
# (tests, the rendezvous engine's own consumers) can publish a custom
# partition and the hierarchical-selection heuristics read one view
# regardless of backend (ops/_proc.py proc_topology).

_topo_lock = threading.Lock()
_topo = {}  # comm_key -> {rank: (host_id, local_rank, leader_rank)}


def publish_topology(key, rank, host_id, local_rank, leader_rank):
    """Publish one rank's (host_id, local_rank, leader_rank) entry for
    communicator ``key``; overwrites a prior entry for the rank."""
    with _topo_lock:
        _topo.setdefault(key, {})[rank] = (
            int(host_id), int(local_rank), int(leader_rank)
        )


def topology_map(key, size=None):
    """The published map for ``key``: {rank: (host_id, local_rank,
    leader_rank)}.  When nothing was published and ``size`` is given,
    returns the trivial single-host map (every rank local to host 0,
    rank 0 the leader) — the truth for the mesh/self tiers."""
    with _topo_lock:
        got = dict(_topo.get(key, {}))
    if got or size is None:
        return got
    return {r: (0, r, 0) for r in range(int(size))}


def reset_topology():
    with _topo_lock:
        _topo.clear()


# ------------------------------------------------------ value exchange
#
# Barrier-style in-process allgather of opaque byte payloads, the
# single-host counterpart of the native bridge's allgather wire: every
# participant posts its value for a generation of ``key`` and blocks
# until all ``size`` values arrived, then reads the full table.  The
# schedule-fingerprint pass (analysis/fingerprint.py) exchanges digests
# through here for in-process MPMD harnesses (thread-per-rank tests,
# the rendezvous engine's own consumers); proc-tier jobs use the native
# allgather instead.  The registry is reusable: a new round for the
# same key opens once the previous cohort has drained.

_xchg_cv = threading.Condition()
_xchg = {}  # key -> {"vals": {rank: bytes}, "readers": int}


def exchange(key, rank, size, payload, timeout=60.0):
    """Post ``payload`` (bytes) as ``rank``'s value for ``key`` and
    return the list of all ``size`` payloads, rank-ordered.  Raises
    RuntimeError on timeout (some participant never posted)."""
    rank, size = int(rank), int(size)
    with _xchg_cv:
        slot = _xchg.setdefault(key, {"vals": {}, "readers": 0})
        # a rank re-entering for the next round while the previous
        # cohort is still reading waits for the round to drain first
        if not _xchg_cv.wait_for(
            lambda: rank not in slot["vals"], timeout=timeout
        ):
            raise RuntimeError(
                f"exchange: rank {rank} re-posted for key {key!r} but "
                "the previous round never drained"
            )
        slot["vals"][rank] = bytes(payload)
        _xchg_cv.notify_all()
        if not _xchg_cv.wait_for(
            lambda: len(slot["vals"]) >= size, timeout=timeout
        ):
            missing = sorted(set(range(size)) - set(slot["vals"]))
            slot["vals"].pop(rank, None)
            _xchg_cv.notify_all()
            raise RuntimeError(
                f"exchange on key {key!r}: timed out after {timeout:.0f}s "
                f"waiting for rank(s) {missing} to post"
            )
        out = [slot["vals"][r] for r in range(size)]
        slot["readers"] += 1
        if slot["readers"] >= size:  # cohort drained: open a new round
            slot["vals"].clear()
            slot["readers"] = 0
            _xchg_cv.notify_all()
    return out


def reset_exchange():
    with _xchg_cv:
        _xchg.clear()
