"""Nonblocking collectives and p2p: request futures over the native
async progress engine (docs/async.md).

The reference substrate's real-world speed came from MPI's nonblocking
progress (``MPI_Isend``/``MPI_Irecv``/``MPI_Iallreduce``): submit
returns immediately, a progress engine drives the wire phase, and the
caller overlaps its own compute until ``MPI_Wait``.  This module is
that contract as JAX ops:

* :func:`iallreduce` / :func:`ireduce_scatter` / :func:`isend` /
  :func:`irecv` submit to the native progress engine
  (native/src/dcn.cc) and return a :class:`Request` immediately;
* :func:`wait` / :func:`waitall` / :func:`test` complete a request.

A :class:`Request` is a pytree whose leaves are the request id and an
ordering stamp, so it threads through ``jit`` as data: ``wait`` is a
**data dependency** on the submit that produced it — XLA cannot reorder
a wait before its submit, and compute placed between the two overlaps
the engine's wire phase.  Ordering between submits rides the same Token
machinery as every blocking op (ops/_core.py): each submit consumes and
returns a token, so the engine receives collectives in one well-defined
program order on every rank (the MPI requirement for nonblocking
collectives).

Request discipline (MPI semantics):

* every request must be consumed by ``wait``/``waitall`` (or ``test``
  returning done followed by ``wait``) **exactly once** — a second wait
  raises, and requests never waited are reported at finalize
  (``native/runtime.py``) and statically by ``t4j-lint`` rule T4J008
  (docs/static-analysis.md);
* the submitted operand is pinned host-side until completion (the
  runtime registry holds it), so donation/reuse of the JAX value is
  safe.

Backends: ``proc`` submits to the native engine (the point of the
subsystem).  ``self`` completes trivially at submit (the request
carries the value), so single-process programs and tests exercise the
full API surface.  The mesh backend raises ``NotImplementedError`` —
inside one XLA program the compiler already schedules collectives
asynchronously, and a host-side engine has nothing to add.
"""

from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp
from jax.tree_util import register_pytree_node

from mpi4jax_tpu.ops import reductions
from mpi4jax_tpu.ops._core import (
    ANY_SOURCE,
    ANY_TAG,
    as_token,
    publishes_token,
)
from mpi4jax_tpu.utils.validation import (
    check_comm,
    check_op,
    check_rank_range,
)

__all__ = [
    "Request",
    "iallreduce",
    "ireduce_scatter",
    "isend",
    "irecv",
    "wait",
    "waitall",
    "test",
    "assert_requests_drained",
]

_RID = jax.ShapeDtypeStruct((), np.uint64)
_STAMP = jax.ShapeDtypeStruct((), np.float32)
_STATUS = jax.ShapeDtypeStruct((2,), np.int32)


def _use_ffi():
    """In-jit fast path: submit/wait lower to native XLA custom calls
    (ffi.cc t4j_*_submit / t4j_async_wait) whenever arrays are already
    host-side — the host-callback detour and its per-call staging cost
    are what ate the overlap win (docs/async.md "measured overhead").
    Accelerator backends keep the staged io_callback path."""
    from mpi4jax_tpu.ops import _proc

    return not _proc._staged()


@dataclass(frozen=True)
class _RequestMeta:
    """Static half of a Request (pytree aux data)."""

    kind: str          # "iallreduce" | "ireduce_scatter" | "isend" | "irecv"
    backend: str       # "proc" | "self"
    shape: tuple       # result shape ("" for isend)
    dtype: str         # result dtype
    comm_key: tuple


class Request:
    """Handle for an in-flight nonblocking op.

    A pytree: the request id (or, on the ``self`` backend, the already-
    complete value) and the submit-time stamp are leaves, so a Request
    flows through ``jit``/``scan`` carries and ``wait`` inside the same
    trace is a data dependency on the submit.  Consume with
    :func:`wait`/:func:`waitall` exactly once.
    """

    def __init__(self, payload, stamp, meta):
        self.payload = payload  # rid array (proc) / result value (self)
        self.stamp = stamp
        self.meta = meta
        self._consumed = False

    def __repr__(self):
        return f"Request({self.meta.kind}, backend={self.meta.backend})"


def _request_flatten(req):
    return (req.payload, req.stamp), (req.meta, req._consumed)


def _request_unflatten(aux, children):
    req = Request(children[0], children[1], aux[0])
    req._consumed = aux[1]
    return req


register_pytree_node(Request, _request_flatten, _request_unflatten)


def _result_sds(meta):
    return jax.ShapeDtypeStruct(meta.shape, np.dtype(meta.dtype))


def _mark_consumed(req, what):
    if req._consumed:
        raise RuntimeError(
            f"{what} on an already-consumed request ({req.meta.kind}): a "
            "request may be waited exactly once (docs/async.md; t4j-lint "
            "rule T4J008)"
        )
    object.__setattr__(req, "_consumed", True)


def _io(cb, results, *operands):
    from mpi4jax_tpu.ops._proc import _io as proc_io

    return proc_io(cb, results, *operands)


def _check_async_backend(comm, opname):
    if comm.backend == "mesh":
        raise NotImplementedError(
            f"{opname} is not defined on the mesh backend: inside one "
            "XLA program the compiler already overlaps collectives; "
            "nonblocking requests are a proc-tier (multi-process) "
            "concept (docs/async.md)"
        )


# ---------------------------------------------------------------- submits


@publishes_token
def iallreduce(x, op=reductions.SUM, *, comm=None, token=None):
    """Nonblocking all-reduce: returns ``(request, token)`` immediately;
    the wire phase runs on the native progress engine while the caller
    keeps computing.  Complete with :func:`wait`, which returns the
    reduced array.  Builtin ops only (user-defined ops need the
    traceable fold of the blocking path)."""
    op = check_op(op)
    comm = check_comm(comm)
    _check_async_backend(comm, "iallreduce")
    token = as_token(token)
    x = jnp.asarray(x)
    meta = _RequestMeta(
        "iallreduce", comm.backend, tuple(jnp.shape(x)),
        str(jnp.result_type(x)), _comm_key(comm),
    )
    if comm.backend == "self":
        return Request(x, token.stamp, meta), token
    if getattr(op, "is_user", False):
        raise NotImplementedError(
            "iallreduce supports builtin reduction ops only; route "
            "user-defined ops through the blocking allreduce"
        )
    from mpi4jax_tpu.ops import _proc

    h = int(_proc._handle(comm))
    code = _proc._op_code(op)
    if _use_ffi():
        rid, stamp = _proc._call(
            "t4j_iallreduce_submit", (_RID, _STAMP), x, token.stamp,
            comm=np.int32(h), op=np.int32(code),
        )
        return Request(rid, stamp, meta), token.with_stamp(stamp)

    def cb(x_, stamp_):
        from mpi4jax_tpu.native import runtime

        rid = runtime.host_iallreduce(h, np.asarray(x_), code)
        return np.uint64(rid), stamp_

    rid, stamp = _io(cb, (_RID, _STAMP), x, token.stamp)
    return Request(rid, stamp, meta), token.with_stamp(stamp)


@publishes_token
def ireduce_scatter(x, op=reductions.SUM, *, comm=None, token=None):
    """Nonblocking ``MPI_Reduce_scatter_block``: ``x`` has shape
    ``(comm.size, *rest)``; :func:`wait` returns the reduction of row
    ``rank`` with shape ``rest``.  Builtin ops only."""
    op = check_op(op)
    comm = check_comm(comm)
    _check_async_backend(comm, "ireduce_scatter")
    token = as_token(token)
    x = jnp.asarray(x)
    shape = tuple(jnp.shape(x))
    if not shape or shape[0] != comm.size:
        raise ValueError(
            f"ireduce_scatter input must have shape (comm.size, ...) = "
            f"({comm.size}, ...), got {shape}"
        )
    meta = _RequestMeta(
        "ireduce_scatter", comm.backend, shape[1:],
        str(jnp.result_type(x)), _comm_key(comm),
    )
    if comm.backend == "self":
        return Request(x[0], token.stamp, meta), token
    if getattr(op, "is_user", False):
        raise NotImplementedError(
            "ireduce_scatter supports builtin reduction ops only"
        )
    from mpi4jax_tpu.ops import _proc

    h = int(_proc._handle(comm))
    code = _proc._op_code(op)
    if _use_ffi():
        rid, stamp = _proc._call(
            "t4j_ireduce_scatter_submit", (_RID, _STAMP), x, token.stamp,
            comm=np.int32(h), op=np.int32(code),
        )
        return Request(rid, stamp, meta), token.with_stamp(stamp)

    def cb(x_, stamp_):
        from mpi4jax_tpu.native import runtime

        rid = runtime.host_ireduce_scatter(h, np.asarray(x_), code)
        return np.uint64(rid), stamp_

    rid, stamp = _io(cb, (_RID, _STAMP), x, token.stamp)
    return Request(rid, stamp, meta), token.with_stamp(stamp)


@publishes_token
def isend(x, dest, tag=0, *, comm=None, token=None):
    """Nonblocking send: returns ``(request, token)`` immediately.  The
    matching receive is a peer's :func:`irecv` (or blocking ``recv``).
    ``wait`` on the request returns ``None`` — it marks the point after
    which the payload has left this rank's send path."""
    comm = check_comm(comm)
    _check_async_backend(comm, "isend")
    token = as_token(token)
    x = jnp.asarray(x)
    dest = check_rank_range(dest, "dest", comm.size)
    tag = int(tag)
    meta = _RequestMeta(
        "isend", comm.backend, (), str(jnp.result_type(x)),
        _comm_key(comm),
    )
    if comm.backend == "self":
        raise NotImplementedError(
            "isend on the self backend has no peer to receive; use the "
            "proc backend (a launched multi-process job)"
        )
    from mpi4jax_tpu.ops import _proc

    h = int(_proc._handle(comm))
    if _use_ffi():
        rid, stamp = _proc._call(
            "t4j_isend_submit", (_RID, _STAMP), x, token.stamp,
            comm=np.int32(h), dest=np.int32(dest), tag=np.int32(tag),
        )
        return Request(rid, stamp, meta), token.with_stamp(stamp)

    def cb(x_, stamp_):
        from mpi4jax_tpu.native import runtime

        rid = runtime.host_isend(h, np.asarray(x_), dest, tag)
        return np.uint64(rid), stamp_

    rid, stamp = _io(cb, (_RID, _STAMP), x, token.stamp)
    return Request(rid, stamp, meta), token.with_stamp(stamp)


@publishes_token
def irecv(x, source=ANY_SOURCE, tag=ANY_TAG, *, comm=None, token=None):
    """Nonblocking receive into the shape/dtype of template ``x``.

    The request parks in the progress engine until a matching message
    arrives — it never blocks the engine, so collectives submitted
    after it still make progress (MPI irecv semantics).  ``wait``
    returns the received array."""
    comm = check_comm(comm)
    _check_async_backend(comm, "irecv")
    token = as_token(token)
    if source != ANY_SOURCE:
        source = check_rank_range(source, "source", comm.size)
    tag = int(tag)
    meta = _RequestMeta(
        "irecv", comm.backend, tuple(jnp.shape(x)),
        str(jnp.result_type(x)), _comm_key(comm),
    )
    if comm.backend == "self":
        raise NotImplementedError(
            "irecv on the self backend has no peer to receive from; use "
            "the proc backend (a launched multi-process job)"
        )
    from mpi4jax_tpu.ops import _proc

    h = int(_proc._handle(comm))
    shape = tuple(jnp.shape(x))
    dtype = jnp.result_type(x)
    if _use_ffi():
        nbytes = int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
        rid, stamp = _proc._call(
            "t4j_irecv_submit", (_RID, _STAMP), token.stamp,
            comm=np.int32(h), source=np.int32(source),
            tag=np.int32(tag), nbytes=np.int64(nbytes),
        )
        return Request(rid, stamp, meta), token.with_stamp(stamp)

    def cb(stamp_):
        from mpi4jax_tpu.native import runtime

        rid = runtime.host_irecv(h, shape, dtype, source, tag)
        return np.uint64(rid), stamp_

    rid, stamp = _io(cb, (_RID, _STAMP), token.stamp)
    return Request(rid, stamp, meta), token.with_stamp(stamp)


# ---------------------------------------------------------------- waits


@publishes_token
def wait(req, *, token=None, status=None):
    """Complete a request: returns ``(result, token)``.

    ``result`` is the op's output (reduced array for iallreduce, the
    row block for ireduce_scatter, the received array for irecv) and
    ``None`` for isend.  For an ``irecv`` request, ``status`` (a
    :class:`~mpi4jax_tpu.ops.p2p.Status`) receives the matched
    ``(source, tag)`` envelope — the only way to learn the sender of an
    ``ANY_SOURCE`` receive, same out-param convention as blocking
    :func:`~mpi4jax_tpu.ops.p2p.recv`.  Inside ``jit`` the wait is a
    data dependency on the request id, so XLA keeps every submit before
    its wait and is free to schedule independent compute between the
    two — that window is the compute/comm overlap.  A request may be
    waited exactly once; requests never waited are reported at finalize
    and by t4j-lint rule T4J008."""
    if not isinstance(req, Request):
        raise TypeError(f"wait expects a Request, got {type(req)}")
    from mpi4jax_tpu.ops.p2p import _deliver_status

    _mark_consumed(req, "wait")
    token = as_token(token)
    meta = req.meta
    if meta.backend == "self":
        value = req.payload if meta.kind != "isend" else None
        return value, token
    if _use_ffi():
        from mpi4jax_tpu.ops import _proc

        # isend has no result payload: a 0-sized sink keeps one wait
        # handler for every kind (ffi.cc AsyncWaitImpl)
        out_sds = (jax.ShapeDtypeStruct((0,), np.uint8)
                   if meta.kind == "isend" else _result_sds(meta))
        out, stamp, st = _proc._call(
            "t4j_async_wait", (out_sds, _STAMP, _STATUS),
            req.payload, _merge(req, token),
        )
        if status is not None and meta.kind == "irecv":
            _deliver_status(status, st)
        if meta.kind == "isend":
            return None, token.with_stamp(stamp)
        return out, token.with_stamp(stamp)
    from mpi4jax_tpu.telemetry import recorder as _telrec

    if meta.kind == "isend":
        def cb(rid_, stamp_):
            from mpi4jax_tpu.native import runtime

            with _telrec.py_op("wait", 0):
                runtime.host_wait(int(rid_))
            return stamp_

        stamp = _io(cb, _STAMP, req.payload, _merge(req, token))
        return None, token.with_stamp(stamp)

    out_sds = _result_sds(meta)

    def cb(rid_, stamp_):
        from mpi4jax_tpu.native import runtime

        with _telrec.py_op("wait", 0):
            out, src_, tag_ = runtime.host_wait(int(rid_))
        return np.asarray(out), np.array([src_, tag_], np.int32), stamp_

    out, st, stamp = _io(cb, (out_sds, _STATUS, _STAMP), req.payload,
                         _merge(req, token))
    if status is not None and meta.kind == "irecv":
        _deliver_status(status, st)
    return out, token.with_stamp(stamp)


def _waitall(reqs, *, token=None):
    token = as_token(token)
    results = []
    for req in reqs:
        value, token = wait(req, token=token)
        results.append(value)
    return results, token


# The analyzer (analysis/record.py) binds the ORIGINAL call arguments
# when it records the op, so a generator argument would reach it
# exhausted and every request in it would lint as a T4J008 leak.
# Materialize in a plain outer wrapper so the instrumented function —
# and therefore the recorded event — always sees a tuple.
_waitall.__name__ = "waitall"
_waitall = publishes_token(_waitall)


def waitall(reqs, *, token=None):
    """Complete a sequence of requests (in order); returns
    ``(results, token)`` with one entry per request (``None`` for
    isends).  ``reqs`` may be any iterable of Requests."""
    return _waitall(tuple(reqs), token=token)


@publishes_token
def test(req, *, token=None):
    """Nonblocking completion probe: returns ``(done, token)`` with
    ``done`` a scalar bool array.  The request is NOT consumed — call
    :func:`wait` to fetch the result (it returns immediately once
    ``done`` is True)."""
    if not isinstance(req, Request):
        raise TypeError(f"test expects a Request, got {type(req)}")
    token = as_token(token)
    if req.meta.backend == "self":
        return jnp.asarray(True), token
    if _use_ffi():
        from mpi4jax_tpu.ops import _proc

        done, stamp = _proc._call(
            "t4j_async_test",
            (jax.ShapeDtypeStruct((), np.bool_), _STAMP),
            req.payload, _merge(req, token),
        )
        return done, token.with_stamp(stamp)

    def cb(rid_, stamp_):
        from mpi4jax_tpu.native import runtime

        return np.bool_(runtime.host_test(int(rid_))), stamp_

    done, stamp = _io(
        cb, (jax.ShapeDtypeStruct((), np.bool_), _STAMP),
        req.payload, _merge(req, token),
    )
    return done, token.with_stamp(stamp)


def assert_requests_drained():
    """Raise if this process holds async requests that were submitted
    but never waited (the runtime counterpart of
    ``Token.assert_drained``; t4j-lint reports the same statically as
    rule T4J008)."""
    from mpi4jax_tpu.native import runtime

    runtime.async_assert_drained()


def _merge(req, token):
    """Stamp that depends on BOTH the request's submit and the ambient
    token chain, so a wait is ordered after its submit and after any
    ops chained on the token since."""
    return req.stamp + 0 * token.stamp


def _comm_key(comm):
    from mpi4jax_tpu.ops._core import comm_key

    return comm_key(comm)
