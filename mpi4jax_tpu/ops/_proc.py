"""Multi-process (MPMD) backend: op implementations over the native DCN
bridge via XLA typed FFI.

This is the tier that preserves the reference's exact process model —
one OS process per rank, true per-rank control flow, rank-dependent
shapes — with the Cython/libmpi data plane replaced by the C++ socket
bridge (native/src/dcn.cc).  Each function here mirrors one CPU
custom-call encoder of the reference
(mpi4jax/_src/collective_ops/*.py "xla_encode_cpu" rules): static config
travels as FFI attributes, the array and an ordering stamp as operands,
and ``has_side_effect=True`` pins the call into the executable.

The sendrecv autodiff contract (transpose = swapped source/dest,
sendrecv.py:366-385) lives on a dedicated primitive below; allreduce
reuses the shared primitive in ops/allreduce.py whose impl dispatches
here for proc comms.
"""

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.extend.core import Primitive
from jax.interpreters import ad, batching, mlir

from mpi4jax_tpu.ops._core import ANY_SOURCE, ANY_TAG

_OP_CODES = {
    "sum": 0,
    "prod": 1,
    "min": 2,
    "max": 3,
    "land": 4,
    "lor": 5,
    "lxor": 6,
    "band": 7,
    "bor": 8,
    "bxor": 9,
}


def _op_code(op):
    """Wire code for a built-in reduction op.  User-defined ops never
    reach here: proc_allreduce/reduce/scan route them through the
    gather-wire + on-device fold path (:func:`_user_fold`) before any
    native op code is needed."""
    if getattr(op, "is_user", False):
        raise AssertionError(
            f"user-defined op {op.name!r} reached the native op table — "
            "it should have been routed through the _user_fold path"
        )
    return _OP_CODES[op.name]


def _user_fold(gathered, op, upto=None):
    """User-op fold on the proc tier: the operands ride the native
    allgather/gather wire and the combine — jax-traceable by the
    :meth:`Op.create` contract — lowers to on-device code through the
    shared rank-ordered fold (same kernel as the mesh tier; reference
    parity: mpi4jax/_src/utils.py:77-96, allreduce.py:36-66)."""
    from mpi4jax_tpu.ops.reductions import rank_ordered_fold

    return rank_ordered_fold(gathered, op, upto=upto)


def _handle(comm):
    from mpi4jax_tpu.native import runtime

    runtime.ensure_initialized()
    # fail fast once the bridge is faulted (a peer died, an op timed
    # out, or an abort broadcast arrived): dispatching another op onto
    # the dead transport would hang or abort, and the recorded fault
    # message is strictly more useful than either
    runtime.check_health()
    return np.int32(runtime.comm_handle(comm))


def proc_topology(comm):
    """(host_id, local_rank, local_size, leader_rank, n_hosts) map for
    a communicator, backend-agnostic.

    Proc comms read the native bridge's bootstrap topology (host
    fingerprints — the map the hierarchical collectives are built on);
    other backends read the rendezvous registry
    (ops/_rendezvous.py), which defaults to the trivial single-host
    map.  Benchmarks use this to label records with the local/leader
    world sizes."""
    if getattr(comm, "backend", None) == "proc":
        from mpi4jax_tpu.native import runtime

        runtime.ensure_initialized()
        topo = runtime.topology()
        if topo is not None:
            return topo
    from mpi4jax_tpu.ops import _rendezvous

    size = int(getattr(comm, "size", 1))
    rank = int(comm.rank()) if hasattr(comm, "rank") else 0
    tmap = _rendezvous.topology_map(
        getattr(comm, "context", 0), size=size
    )
    host, local, leader = tmap.get(rank, (0, rank, 0))
    return {
        "host_id": host,
        "local_rank": local,
        "local_size": sum(1 for h, _l, _r in tmap.values() if h == host),
        "leader_rank": leader,
        "n_hosts": len({h for h, _l, _r in tmap.values()}),
    }


def _staged():
    """True when arrays live on an accelerator: route ops through
    ``io_callback`` (device->host staging handled by JAX) instead of the
    CPU FFI custom call — the analog of the reference's GPU
    COPY_TO_HOST path (mpi_xla_bridge_gpu.pyx:211-251; there the bridge
    cudaMemcpys manually, here the runtime stages for us).

    Requires a runtime with host-callback support (standard libtpu has
    it; the experimental axon tunnel does not).
    ``MPI4JAX_TPU_FORCE_STAGED=1`` forces this path on CPU, for testing
    the staging tier without an accelerator.
    """
    import os

    from mpi4jax_tpu.utils.config import truthy

    if truthy(os.environ.get("MPI4JAX_TPU_FORCE_STAGED"), default=False):
        return True
    return jax.default_backend() != "cpu"


def _call(name, results, *operands, **attrs):
    from mpi4jax_tpu.native.runtime import _ffi_module

    fn = _ffi_module().ffi_call(name, results, has_side_effect=True)
    return fn(*operands, **attrs)


_hcb_state = {"supported": None}


def host_callback_supported():
    """Probe (once) whether the default backend can run host callbacks.

    Standard libtpu/CUDA PJRT can; the experimental axon tunnel raises
    UNIMPLEMENTED ("does not support host send/recv callbacks").  CPU
    always can.
    """
    if _hcb_state["supported"] is None:
        if jax.default_backend() == "cpu":
            _hcb_state["supported"] = True
        else:
            from jax.experimental import io_callback

            try:
                out = io_callback(
                    lambda v: np.asarray(v),
                    jax.ShapeDtypeStruct((), np.float32),
                    jnp.float32(0),
                )
                jax.block_until_ready(out)
                _hcb_state["supported"] = True
            except Exception:
                _hcb_state["supported"] = False
    return _hcb_state["supported"]


def _io(py_fn, results, *operands):
    if not host_callback_supported():
        return _eager_host_hop(py_fn, results, operands)
    from jax.experimental import io_callback

    # ordered=False: ordered IO effects need runtime token support some
    # experimental PJRT plugins lack (observed on axon). Ordering is
    # already guaranteed by data dependence — every op threads the stamp
    # through its callback — which is this library's ordering model
    # everywhere else (ops/_core.py docstring).
    return io_callback(py_fn, results, *operands, ordered=False)


def _eager_host_hop(py_fn, results, operands):
    """Explicit staging for runtimes with no host-callback support (the
    axon tunnel): device_get the operands, run the host collective,
    device_put the results back — the reference's COPY_TO_HOST hop
    (mpi_xla_bridge_gpu.pyx:211-251) done eagerly at the op boundary.

    Only possible outside jit: under a trace there is no way to reach
    the host mid-executable without callback support.
    """
    import jax.core

    if any(isinstance(o, jax.core.Tracer) for o in operands):
        raise NotImplementedError(
            "this accelerator runtime has no host-callback support, so "
            "multi-process (proc) collectives cannot run inside jit. "
            "Call the op eagerly (outside jit), or run the process on "
            "the CPU backend, or use a MeshComm for in-jit collectives."
        )
    host_ops = [np.asarray(jax.device_get(o)) for o in operands]
    out = py_fn(*host_ops)
    if isinstance(results, (tuple, list)):
        return tuple(jax.device_put(np.asarray(r)) for r in out)
    return jax.device_put(np.asarray(out))


def _staged_data(comm, out_sds, host_fn, x, stamp, name="op"):
    """Shared staged-tier shape for data-in/data-out ops: stages ``x``
    to host, runs ``host_fn(runtime, handle, np_x) -> np_out``, threads
    the stamp through for ordering.  The callback is bracketed with
    Python-level telemetry begin/end events (T4J_TELEMETRY=trace,
    docs/observability.md): under jit this is the execution-time span
    that encloses the native segment events — the trace-time bracket in
    ops/_core.py cannot see runtime from inside a compiled program."""
    from mpi4jax_tpu.native import runtime
    from mpi4jax_tpu.telemetry import recorder as _telrec

    h = int(_handle(comm))

    def cb(x_, stamp_):
        a = np.asarray(x_)
        with _telrec.py_op(f"staged_{name}", a.nbytes):
            return host_fn(runtime, h, a), stamp_

    return _io(cb, (out_sds, _STAMP), x, stamp)


def _sds(x):
    return jax.ShapeDtypeStruct(jnp.shape(x), jnp.result_type(x))


_STAMP = jax.ShapeDtypeStruct((), np.float32)
_STATUS = jax.ShapeDtypeStruct((2,), np.int32)


def proc_allreduce(x, stamp, op, comm):
    if getattr(op, "is_user", False):
        # Op.Create on the multi-process backend (VERDICT r3 missing #1):
        # operands cross the wire via the native allgather, the fold runs
        # on-device in rank order (commute=False safe)
        g, stamp = proc_allgather(x, stamp, comm)
        return _user_fold(g, op), stamp
    if _staged():
        code = _op_code(op)
        return _staged_data(
            comm, _sds(x),
            lambda rt, h, a: rt.host_allreduce(h, a, code), x, stamp,
            name="allreduce",
        )
    return _call(
        "t4j_allreduce",
        (_sds(x), _STAMP),
        x,
        stamp,
        comm=_handle(comm),
        op=np.int32(_op_code(op)),
    )


def proc_reduce(x, stamp, op, comm, root):
    if getattr(op, "is_user", False):
        # MPMD branch is a Python if: proc ranks are static ints
        g, stamp = proc_gather(x, stamp, comm, root)
        if int(comm.rank()) != int(root):
            return x, stamp  # off-root passthrough (wrapper contract)
        return _user_fold(g, op), stamp
    if _staged():
        code = _op_code(op)
        return _staged_data(
            comm, _sds(x),
            lambda rt, h, a: rt.host_reduce(h, a, code, root), x, stamp,
            name="reduce",
        )
    return _call(
        "t4j_reduce",
        (_sds(x), _STAMP),
        x,
        stamp,
        comm=_handle(comm),
        op=np.int32(_op_code(op)),
        root=np.int32(root),
    )


def proc_reduce_scatter(x, stamp, op, comm):
    """MPI_Reduce_scatter_block on the native bridge: ``x`` has shape
    ``(comm.size, *rest)``, the result is the reduction of row ``rank``
    with shape ``rest``.  Large payloads ride the segmented ring
    reduce-scatter directly — O((n-1)/n * payload) per link — instead
    of the alltoall + on-device fold detour.  Builtin ops only: callers
    route user-defined ops through the alltoall + rank-ordered-fold
    path (ops/collectives.py), which is the jax-traceable contract
    user combines require."""
    code = _op_code(op)
    out = jax.ShapeDtypeStruct(jnp.shape(x)[1:], jnp.result_type(x))
    if _staged():
        return _staged_data(
            comm, out,
            lambda rt, h, a: rt.host_reduce_scatter(h, a, code), x, stamp,
            name="reduce_scatter",
        )
    return _call(
        "t4j_reduce_scatter",
        (out, _STAMP),
        x,
        stamp,
        comm=_handle(comm),
        op=np.int32(code),
    )


def proc_scan(x, stamp, op, comm):
    if getattr(op, "is_user", False):
        g, stamp = proc_allgather(x, stamp, comm)
        return _user_fold(g, op, upto=int(comm.rank())), stamp
    if _staged():
        code = _op_code(op)
        return _staged_data(
            comm, _sds(x),
            lambda rt, h, a: rt.host_scan(h, a, code), x, stamp,
            name="scan",
        )
    return _call(
        "t4j_scan",
        (_sds(x), _STAMP),
        x,
        stamp,
        comm=_handle(comm),
        op=np.int32(_op_code(op)),
    )


def proc_barrier(stamp, comm):
    if _staged():
        from mpi4jax_tpu.native import runtime

        h = int(_handle(comm))

        def cb(stamp_):
            runtime.host_barrier(h)
            return stamp_

        return _io(cb, _STAMP, stamp)
    (out,) = _call("t4j_barrier", (_STAMP,), stamp, comm=_handle(comm))
    return out


def proc_bcast(x, stamp, comm, root):
    if _staged():
        return _staged_data(
            comm, _sds(x),
            lambda rt, h, a: rt.host_bcast(h, a, root), x, stamp,
            name="bcast",
        )
    return _call(
        "t4j_bcast",
        (_sds(x), _STAMP),
        x,
        stamp,
        comm=_handle(comm),
        root=np.int32(root),
    )


def proc_allgather(x, stamp, comm):
    out = jax.ShapeDtypeStruct((comm.size, *jnp.shape(x)), jnp.result_type(x))
    if _staged():
        return _staged_data(
            comm, out, lambda rt, h, a: rt.host_allgather(h, a), x, stamp,
            name="allgather",
        )
    return _call(
        "t4j_allgather", (out, _STAMP), x, stamp, comm=_handle(comm)
    )


def proc_gather(x, stamp, comm, root):
    out = jax.ShapeDtypeStruct((comm.size, *jnp.shape(x)), jnp.result_type(x))
    if _staged():
        return _staged_data(
            comm, out,
            lambda rt, h, a: rt.host_gather(h, a, root), x, stamp,
            name="gather",
        )
    return _call(
        "t4j_gather",
        (out, _STAMP),
        x,
        stamp,
        comm=_handle(comm),
        root=np.int32(root),
    )


def proc_scatter(x, stamp, comm, root):
    # MPMD shapes: the root passes (nproc, *rest) and receives (rest);
    # other ranks pass a (rest)-shaped template (scatter.py:52-58)
    shape = jnp.shape(x)[1:] if comm.rank() == root else jnp.shape(x)
    out = jax.ShapeDtypeStruct(shape, jnp.result_type(x))
    if _staged():
        return _staged_data(
            comm, out,
            lambda rt, h, a: rt.host_scatter(h, a, root), x, stamp,
            name="scatter",
        )
    return _call(
        "t4j_scatter",
        (out, _STAMP),
        x,
        stamp,
        comm=_handle(comm),
        root=np.int32(root),
    )


def proc_alltoall(x, stamp, comm):
    if _staged():
        return _staged_data(
            comm, _sds(x), lambda rt, h, a: rt.host_alltoall(h, a), x, stamp,
            name="alltoall",
        )
    return _call("t4j_alltoall", (_sds(x), _STAMP), x, stamp, comm=_handle(comm))


def proc_send(x, stamp, comm, dest, tag):
    if _staged():
        from mpi4jax_tpu.native import runtime

        h = int(_handle(comm))

        def cb(x_, stamp_):
            runtime.host_send(h, np.asarray(x_), dest, tag)
            return stamp_

        return _io(cb, _STAMP, x, stamp)
    (out,) = _call(
        "t4j_send",
        (_STAMP,),
        x,
        stamp,
        comm=_handle(comm),
        dest=np.int32(dest),
        tag=np.int32(tag),
    )
    return out


def proc_recv(template, stamp, comm, source, tag):
    """Returns (data, stamp, status[2])."""
    if _staged():
        from mpi4jax_tpu.native import runtime

        h = int(_handle(comm))
        shape = jnp.shape(template)
        dtype = jnp.result_type(template)

        def cb(stamp_):
            out, src, tg = runtime.host_recv(h, shape, dtype, source, tag)
            return out, stamp_, np.array([src, tg], np.int32)

        return _io(cb, (_sds(template), _STAMP, _STATUS), stamp)
    return _call(
        "t4j_recv",
        (_sds(template), _STAMP, _STATUS),
        stamp,
        comm=_handle(comm),
        source=np.int32(source),
        tag=np.int32(tag),
    )


# -- sendrecv primitive (AD: transpose swaps source and dest) -------------

sendrecv_p = Primitive("mpi4jax_tpu_proc_sendrecv")
sendrecv_p.multiple_results = True


def _sendrecv_impl(sendbuf, recvbuf, stamp, *, comm, source, dest, sendtag,
                   recvtag, _must_transpose):
    if _must_transpose:
        # only pure forward mode can leak a flipped marker to execution;
        # reverse mode transposes it back (the reference's scheme:
        # sendrecv.py:128-133 error, :320-361 jvp marker flip)
        raise RuntimeError(
            "forward-mode differentiation through sendrecv is not "
            "supported on the multi-process backend; use reverse mode"
        )
    if _staged():
        from mpi4jax_tpu.native import runtime

        h = int(_handle(comm))

        def cb(sendbuf_, recvbuf_, stamp_):
            out, src, tg = runtime.host_sendrecv(
                h, np.asarray(sendbuf_), np.asarray(recvbuf_), source, dest,
                sendtag, recvtag,
            )
            return out, stamp_, np.array([src, tg], np.int32)

        return _io(
            cb, (_sds(recvbuf), _STAMP, _STATUS), sendbuf, recvbuf, stamp
        )
    return _call(
        "t4j_sendrecv",
        (_sds(recvbuf), _STAMP, _STATUS),
        sendbuf,
        recvbuf,
        stamp,
        comm=_handle(comm),
        source=np.int32(source),
        dest=np.int32(dest),
        sendtag=np.int32(sendtag),
        recvtag=np.int32(recvtag),
    )


def _sendrecv_abstract(sendbuf, recvbuf, stamp, **kw):
    return (
        recvbuf,
        stamp,
        jax.core.ShapedArray((2,), np.int32),
    )


def _zero_like(x):
    if hasattr(ad.Zero, "from_primal_value"):
        return ad.Zero.from_primal_value(x)
    return ad.Zero.from_value(x)


def _sendrecv_jvp(primals, tangents, *, comm, source, dest, sendtag, recvtag,
                  _must_transpose):
    # the reference's rule (sendrecv.py:320-361): tangent exchange binds
    # with the _must_transpose marker flipped — executable only after a
    # transpose flips it back (reverse mode); pure forward mode then
    # errors at execution, exactly as the reference's lowering does
    sendbuf, recvbuf, stamp = primals
    st, rt, _ = tangents
    st = jnp.zeros_like(sendbuf) if type(st) is ad.Zero else st
    rt = jnp.zeros_like(recvbuf) if type(rt) is ad.Zero else rt
    val, stamp_out, status = sendrecv_p.bind(
        sendbuf, recvbuf, stamp, comm=comm, source=source, dest=dest,
        sendtag=sendtag, recvtag=recvtag, _must_transpose=_must_transpose,
    )
    jvp, jstamp, jstatus = sendrecv_p.bind(
        st, rt, stamp_out, comm=comm, source=source, dest=dest,
        sendtag=sendtag, recvtag=recvtag,
        _must_transpose=not _must_transpose,
    )
    return (
        (val, stamp_out, status),
        (jvp, _zero_like(jstamp), _zero_like(jstatus)),
    )


def _sendrecv_batch(args, dims, *, comm, source, dest, sendtag, recvtag,
                    _must_transpose):
    # one exchange of the whole batch (the reference's batch rule,
    # sendrecv.py:291-319)
    sendbuf, recvbuf, stamp = args
    bd_s, bd_r, bd_t = dims
    if bd_t is not None:
        raise NotImplementedError("batched tokens are not supported")
    if bd_s is None and bd_r is None:
        raise ValueError("sendrecv batch rule called without batched data")

    def tile(unbatched, axis, n):
        """Insert a batch dim of size n at ``axis`` (send/recv buffers
        may have different base shapes)."""
        shape = list(unbatched.shape)
        shape.insert(axis, n)
        return jnp.broadcast_to(jnp.expand_dims(unbatched, axis), shape)

    if bd_s is None:
        sendbuf = tile(sendbuf, bd_r, recvbuf.shape[bd_r])
        bd_s = bd_r
    if bd_r is None:
        recvbuf = tile(recvbuf, bd_s, sendbuf.shape[bd_s])
        bd_r = bd_s
    if bd_s != bd_r:
        sendbuf = jnp.moveaxis(sendbuf, bd_s, bd_r)
    out = sendrecv_p.bind(
        sendbuf, recvbuf, stamp, comm=comm, source=source, dest=dest,
        sendtag=sendtag, recvtag=recvtag, _must_transpose=_must_transpose,
    )
    return out, (bd_r, None, None)


def _sendrecv_transpose(cts, sendbuf, recvbuf, stamp, *, comm, source, dest,
                        sendtag, recvtag, _must_transpose):
    # gradients travel the reverse network direction (sendrecv.py:366-385)
    out_ct, _, _ = cts
    if type(out_ct) is ad.Zero:
        out_ct = jnp.zeros(recvbuf.aval.shape, recvbuf.aval.dtype)
    fresh = jnp.zeros((), np.float32)
    res, _, _ = sendrecv_p.bind(
        out_ct,
        out_ct,
        fresh,
        comm=comm,
        source=dest,
        dest=source,
        sendtag=sendtag,
        recvtag=recvtag,
        _must_transpose=not _must_transpose,
    )
    send_ct = res if ad.is_undefined_primal(sendbuf) else None
    recv_ct = (
        ad.Zero(recvbuf.aval) if ad.is_undefined_primal(recvbuf) else None
    )
    stamp_ct = (
        ad.Zero(stamp.aval) if ad.is_undefined_primal(stamp) else None
    )
    return send_ct, recv_ct, stamp_ct


sendrecv_p.def_impl(_sendrecv_impl)
sendrecv_p.def_abstract_eval(_sendrecv_abstract)
ad.primitive_jvps[sendrecv_p] = _sendrecv_jvp
ad.primitive_transposes[sendrecv_p] = _sendrecv_transpose
batching.primitive_batchers[sendrecv_p] = _sendrecv_batch
mlir.register_lowering(
    sendrecv_p, mlir.lower_fun(_sendrecv_impl, multiple_results=True)
)


def proc_sendrecv(sendbuf, recvbuf, stamp, comm, source, dest, sendtag,
                  recvtag):
    return sendrecv_p.bind(
        sendbuf,
        recvbuf,
        stamp,
        comm=comm,
        source=int(source),
        dest=int(dest),
        sendtag=int(sendtag),
        recvtag=int(recvtag),
        _must_transpose=False,
    )


# -- fused multi-part sendrecv (small-message coalescing) ------------------
#
# One wire frame for a run of small same-peer messages
# (docs/performance.md "small-message coalescing"): operands are the
# send parts (+ stamp), the recv parts come back as results, and the
# native layer gathers/scatters iovec-style — no packing copies on
# either side.  AD mirrors the single sendrecv primitive: the
# transpose swaps source and dest AND the send/recv part lists, so
# gradients travel the reverse network direction part-for-part.

sendrecv_fused_p = Primitive("mpi4jax_tpu_proc_sendrecv_fused")
sendrecv_fused_p.multiple_results = True


def _srf_split(args, n_send, n_recv):
    return (
        args[:n_send],
        args[n_send:n_send + n_recv],
        args[n_send + n_recv],
    )


def _srf_impl(*args, comm, source, dest, sendtag, recvtag, n_send,
              n_recv, _must_transpose):
    if _must_transpose:
        raise RuntimeError(
            "forward-mode differentiation through sendrecv_multi is not "
            "supported on the multi-process backend; use reverse mode"
        )
    sendbufs, recvbufs, stamp = _srf_split(args, n_send, n_recv)
    if _staged():
        from mpi4jax_tpu.native import runtime

        h = int(_handle(comm))
        templates = [
            jax.ShapeDtypeStruct(jnp.shape(r), jnp.result_type(r))
            for r in recvbufs
        ]

        def cb(*host_args):
            sends = [np.asarray(a) for a in host_args[:-1]]
            # templates pass through as ShapeDtypeStructs — the host
            # wrapper allocates the result buffers itself
            outs, src, tg = runtime.host_sendrecv_fused(
                h, sends, templates, source, dest, sendtag, recvtag,
            )
            return (*outs, host_args[-1], np.array([src, tg], np.int32))

        return _io(
            cb, (*[_sds(r) for r in recvbufs], _STAMP, _STATUS),
            *sendbufs, stamp,
        )
    return _call(
        "t4j_sendrecv_fused",
        (*[_sds(r) for r in recvbufs], _STAMP, _STATUS),
        *sendbufs,
        stamp,
        comm=_handle(comm),
        source=np.int32(source),
        dest=np.int32(dest),
        sendtag=np.int32(sendtag),
        recvtag=np.int32(recvtag),
        n_send=np.int32(n_send),
    )


def _srf_abstract(*args, n_send, n_recv, **kw):
    recvs = args[n_send:n_send + n_recv]
    stamp = args[n_send + n_recv]
    return (*recvs, stamp, jax.core.ShapedArray((2,), np.int32))


def _srf_jvp(primals, tangents, *, comm, source, dest, sendtag, recvtag,
             n_send, n_recv, _must_transpose):
    # the single-sendrecv scheme (sendrecv.py:320-361 in the
    # reference): the tangent exchange binds with the marker flipped —
    # executable only after a transpose flips it back
    sends, recvs, stamp = _srf_split(primals, n_send, n_recv)
    tsends = [
        jnp.zeros_like(p) if type(t) is ad.Zero else t
        for p, t in zip(sends, tangents[:n_send])
    ]
    trecvs = [
        jnp.zeros_like(p) if type(t) is ad.Zero else t
        for p, t in zip(recvs, tangents[n_send:n_send + n_recv])
    ]
    out = sendrecv_fused_p.bind(
        *sends, *recvs, stamp, comm=comm, source=source, dest=dest,
        sendtag=sendtag, recvtag=recvtag, n_send=n_send, n_recv=n_recv,
        _must_transpose=_must_transpose,
    )
    stamp_out = out[n_recv]
    jout = sendrecv_fused_p.bind(
        *tsends, *trecvs, stamp_out, comm=comm, source=source, dest=dest,
        sendtag=sendtag, recvtag=recvtag, n_send=n_send, n_recv=n_recv,
        _must_transpose=not _must_transpose,
    )
    return (
        out,
        (*jout[:n_recv], _zero_like(jout[n_recv]), _zero_like(jout[n_recv + 1])),
    )


def _srf_transpose(cts, *args, comm, source, dest, sendtag, recvtag,
                   n_send, n_recv, _must_transpose):
    # gradients travel the reverse network direction: the transposed
    # exchange SENDS the recv parts' cotangents back to `source` and
    # RECEIVES the send parts' cotangents from `dest`, part for part
    sends, recvs, stamp = _srf_split(args, n_send, n_recv)
    out_cts = [
        jnp.zeros(r.aval.shape, r.aval.dtype) if type(c) is ad.Zero else c
        for r, c in zip(recvs, cts[:n_recv])
    ]
    send_templates = [
        jnp.zeros(s.aval.shape, s.aval.dtype)
        if ad.is_undefined_primal(s) else jnp.zeros_like(s)
        for s in sends
    ]
    fresh = jnp.zeros((), np.float32)
    res = sendrecv_fused_p.bind(
        *out_cts, *send_templates, fresh, comm=comm, source=dest,
        dest=source, sendtag=sendtag, recvtag=recvtag, n_send=n_recv,
        n_recv=n_send, _must_transpose=not _must_transpose,
    )
    send_cts = [
        res[i] if ad.is_undefined_primal(s) else None
        for i, s in enumerate(sends)
    ]
    recv_cts = [
        ad.Zero(r.aval) if ad.is_undefined_primal(r) else None
        for r in recvs
    ]
    stamp_ct = (
        ad.Zero(stamp.aval) if ad.is_undefined_primal(stamp) else None
    )
    return (*send_cts, *recv_cts, stamp_ct)


sendrecv_fused_p.def_impl(_srf_impl)
sendrecv_fused_p.def_abstract_eval(_srf_abstract)
ad.primitive_jvps[sendrecv_fused_p] = _srf_jvp
ad.primitive_transposes[sendrecv_fused_p] = _srf_transpose
mlir.register_lowering(
    sendrecv_fused_p, mlir.lower_fun(_srf_impl, multiple_results=True)
)


def proc_sendrecv_fused(sendbufs, recvbufs, stamp, comm, source, dest,
                        sendtag, recvtag):
    """Returns ``(*recv_parts, stamp, status[2])``.  ``source`` /
    ``dest`` may be -1 (no send / no recv side) only when the matching
    part list is empty."""
    return sendrecv_fused_p.bind(
        *sendbufs,
        *recvbufs,
        stamp,
        comm=comm,
        source=int(source),
        dest=int(dest),
        sendtag=int(sendtag),
        recvtag=int(recvtag),
        n_send=len(sendbufs),
        n_recv=len(recvbufs),
        _must_transpose=False,
    )


def proc_alltoall_fused(parts, stamp, comm):
    """Fused multi-part alltoall: each peer receives ONE wire frame
    carrying its slice of every part (bit-identical to per-part
    alltoall; docs/performance.md "small-message coalescing").
    Returns ``(outs, stamp)``."""
    if _staged():
        from mpi4jax_tpu.native import runtime
        from mpi4jax_tpu.telemetry import recorder as _telrec

        h = int(_handle(comm))
        total = sum(int(np.prod(jnp.shape(p), dtype=np.int64))
                    for p in parts)

        def cb(*host_args):
            arrs = [np.asarray(a) for a in host_args[:-1]]
            with _telrec.py_op("staged_alltoall_fused", total):
                outs = runtime.host_alltoall_fused(h, arrs)
            return (*outs, host_args[-1])

        out = _io(cb, (*[_sds(p) for p in parts], _STAMP), *parts, stamp)
        return list(out[:-1]), out[-1]
    out = _call(
        "t4j_alltoall_fused",
        (*[_sds(p) for p in parts], _STAMP),
        *parts,
        stamp,
        comm=_handle(comm),
    )
    return list(out[:-1]), out[-1]
