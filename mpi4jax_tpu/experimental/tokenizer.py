"""auto_tokenize — automatic token threading for communication ops.

Reference counterpart: ``mpi4jax.experimental.auto_tokenize``
(mpi4jax/experimental/tokenizer.py:167-204), which re-interprets a traced
jaxpr and re-binds every mpi primitive with one threaded token
(tokenizer.py:108-156, register_overrides.py:18-125), recursing into
``scan`` / ``while`` / ``cond`` / nested ``jit`` sub-jaxprs.

TPU-native redesign — an *ambient token context* instead of a jaxpr
interpreter: inside ``auto_tokenize(f)``, every communication op called
with ``token=None`` resolves the current ambient token and commits its
output token back (see ``as_token`` / ``commit_token`` in
:mod:`mpi4jax_tpu.ops._core`).  Consecutive ops therefore chain on one
token exactly as if the user had threaded it by hand, which

* orders collectives on the mesh backend through data dependence, and
* lets bare ``send``/``recv`` pairs match through the shared token's
  pending-send queue (the property the reference's "hot potato" test
  guards, tests/experimental/test_auto_tokenize.py:76-127).

Control flow needs no special-casing: ops inside a ``lax.scan`` /
``while_loop`` / ``cond`` body chain with each other within the body
trace, and the chain restarts at the trace boundary (detected via a
tracer-liveness probe) — cross-boundary ordering is already guaranteed
by XLA's deterministic SPMD schedule and, on the multi-process backend,
by effectful-custom-call program order.  The reference instead had to
rewrite sub-jaxprs to carry the token (tokenizer.py:19-105); here the
same guarantee falls out of the backends' ordering models.
"""

import functools

from mpi4jax_tpu.ops._core import AmbientChain, _ambient_stack

__all__ = ["auto_tokenize", "ambient_token"]

# Interaction with the jit cache: ambient chaining happens at Python
# trace time, which jax.jit's cache key cannot observe, so a jitted
# function may be traced under one scope state and its cached executable
# reused under another.  Both directions are benign:
#
# * traced in-scope, called out-of-scope — the chained program is baked
#   into the executable and simply runs (the reference behaves the same:
#   its runtime ordering comes from the effect system whether or not
#   auto_tokenize re-threaded the tokens);
# * traced out-of-scope, called in-scope — only token=None *collectives*
#   can trace that way (a bare send/recv pair fails loudly at trace time
#   with "no matching in-trace send"), and their cross-device ordering
#   is still guaranteed without the chain: mesh-backend programs are
#   SPMD (every device compiles the identical module, so XLA's schedule
#   is consistent), and proc-backend ops are effectful FFI calls that
#   execute in program order.
#
# What is NOT preserved across a jit cache hit is the link between the
# inner ops and the *outer* ambient chain — the same trace-boundary
# reset that applies to scan/while/cond bodies (see AmbientChain).
#
# Both directions are pinned by tests (cache-hit asserted, not assumed):
# tests/experimental/test_auto_tokenize.py::
#   test_jit_cache_reuse_across_scope_is_benign   (traced in, called out)
#   test_jit_cache_reuse_into_scope_is_benign     (traced out, called in)


def auto_tokenize(fn=None):
    """Wrap ``fn`` so communication ops inside it auto-thread one token.

    Usable as ``auto_tokenize(f)`` or ``@auto_tokenize``; the wrapped
    function can run eagerly or under ``jax.jit`` (the reference requires
    the decorator *outside* jit; here both orders work, since the ambient
    context is consulted at trace time either way).
    """
    if fn is None:
        return auto_tokenize

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        stack = _ambient_stack()
        stack.append(AmbientChain())
        try:
            out = fn(*args, **kwargs)
            # surface unmatched sends staged at any still-live level
            stack[-1].resolve().assert_drained()
        finally:
            stack.pop()
        return out

    return wrapper


def ambient_token():
    """The current ambient token, or None outside auto_tokenize scopes.

    Escape hatch for mixing explicit- and auto-token code: ops that need
    the chain explicitly (e.g. to pass into a scan carry) can read it
    here; ops called with ``token=None`` keep chaining automatically.
    """
    stack = _ambient_stack()
    return stack[-1].resolve() if stack else None
