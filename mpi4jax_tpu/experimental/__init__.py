"""Experimental transforms (reference: mpi4jax/experimental/__init__.py:1-5
exports auto_tokenize only)."""

from mpi4jax_tpu.experimental.tokenizer import ambient_token, auto_tokenize

__all__ = ["auto_tokenize", "ambient_token"]
