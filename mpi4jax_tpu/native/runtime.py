"""Python side of the native DCN bridge: ctypes control plane + FFI
target registration.

Counterpart of the reference's bridge registration
(mpi4jax/_src/xla_bridge/__init__.py:26-31): loads the shared library,
hands the 12 typed-FFI handler symbols to XLA for the "cpu" platform,
and exposes the process-world control API (init/rank/size/comms) that
mpi4py provides in the reference.
"""

import atexit
import ctypes
import os

__all__ = [
    "available",
    "is_initialized",
    "ensure_initialized",
    "world_rank",
    "world_size",
    "comm_handle",
    "set_logging",
    "finalize",
    "check_health",
    "notify_abort",
    "last_error",
    "set_timeouts",
    "set_tuning",
    "set_wire",
    "wire_info",
    "set_wire_dtype",
    "wire_dtype_info",
    "set_wire_backend",
    "wire_backend_info",
    "set_coalesce",
    "coalesce_bytes",
    "set_hier",
    "set_resilience",
    "set_elastic",
    "world_info",
    "alive_ranks",
    "resize_wait",
    "refresh_after_resize",
    "WorldResized",
    "set_telemetry",
    "set_flight",
    "flight_info",
    "annotate_step",
    "telemetry_mode_name",
    "telemetry_drain",
    "telemetry_last",
    "telemetry_anchor",
    "telemetry_dropped",
    "metrics_snapshot",
    "link_stats",
    "topology",
    "hier_would_select",
    "hier_active",
    "host_iallreduce",
    "host_ireduce_scatter",
    "host_isend",
    "host_irecv",
    "host_wait",
    "host_test",
    "async_inflight",
    "async_pending",
    "async_assert_drained",
    "BridgeError",
    "HANDLER_NAMES",
]


class BridgeError(RuntimeError):
    """A DCN bridge call failed (transport error, deadline expiry, or a
    peer's abort broadcast).  The message carries rank/peer/op context
    from the native layer.  The bridge is faulted afterwards: every
    further proc-tier op raises until the job restarts."""


class WorldResized(RuntimeError):
    """The world membership changed under an elastic resize
    (docs/failure-semantics.md "elastic membership").

    Raised at the NEXT proc-tier op after a resize committed (and by
    :func:`check_health` directly).  Unlike :class:`BridgeError` this
    is recoverable: the transport is already rebuilt over the new
    membership — user code must drop its pre-resize communicators,
    rebuild them over ``new_world``, redistribute state (e.g. via
    ``utils/checkpoint.py``), and continue.  ``models/train.py``'s
    elastic loop does exactly that.

    Attributes:
        old_world: tuple of world ranks before the resize.
        new_world: tuple of world ranks after it.
        epoch: the committed world epoch (bumps by 1 per resize).
    """

    def __init__(self, old_world, new_world, epoch):
        self.old_world = tuple(old_world)
        self.new_world = tuple(new_world)
        self.epoch = int(epoch)
        joined = ",".join(str(r) for r in self.new_world)
        super().__init__(
            f"world resized at epoch {self.epoch}: "
            f"{len(self.old_world)} -> {len(self.new_world)} member(s) "
            f"(now [{joined}]) — rebuild communicators over the new "
            "world and redistribute state "
            "(docs/failure-semantics.md \"elastic membership\")"
        )

HANDLER_NAMES = [
    "t4j_allreduce",
    "t4j_hier_allreduce",
    "t4j_reduce",
    "t4j_reduce_scatter",
    "t4j_scan",
    "t4j_send",
    "t4j_recv",
    "t4j_sendrecv",
    "t4j_sendrecv_fused",
    "t4j_alltoall_fused",
    "t4j_barrier",
    "t4j_bcast",
    "t4j_allgather",
    "t4j_gather",
    "t4j_scatter",
    "t4j_alltoall",
    # async progress engine (docs/async.md): in-jit submit/wait fast
    # path — submits hand the operand to the engine's owned-buffer API
    # and return a u64 request id; wait/test consume it as data
    "t4j_iallreduce_submit",
    "t4j_ireduce_scatter_submit",
    "t4j_isend_submit",
    "t4j_irecv_submit",
    "t4j_async_wait",
    "t4j_async_test",
]

_state = {"lib": None, "registered": False, "comm_cache": {}}


def _load():
    if _state["lib"] is not None:
        return _state["lib"]
    from mpi4jax_tpu.native.build import ensure_built

    lib = ctypes.CDLL(str(ensure_built()))
    lib.t4j_init.restype = ctypes.c_int
    lib.t4j_initialized.restype = ctypes.c_int
    lib.t4j_world_rank.restype = ctypes.c_int
    lib.t4j_world_size.restype = ctypes.c_int
    lib.t4j_comm_create.restype = ctypes.c_int
    lib.t4j_comm_create.argtypes = [
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int32,
        ctypes.c_int32,
    ]
    lib.t4j_comm_rank.restype = ctypes.c_int
    lib.t4j_comm_rank.argtypes = [ctypes.c_int32]
    lib.t4j_comm_size.restype = ctypes.c_int
    lib.t4j_comm_size.argtypes = [ctypes.c_int32]
    lib.t4j_set_logging.argtypes = [ctypes.c_int]
    # robustness control surface (docs/failure-semantics.md)
    lib.t4j_last_error.restype = ctypes.c_char_p
    lib.t4j_health.restype = ctypes.c_int
    lib.t4j_fault_msg.restype = ctypes.c_char_p
    lib.t4j_set_timeouts.argtypes = [ctypes.c_double, ctypes.c_double]
    lib.t4j_set_tuning.argtypes = [ctypes.c_int64, ctypes.c_int64]
    lib.t4j_set_coalesce.argtypes = [ctypes.c_int64]
    lib.t4j_coalesce_bytes.restype = ctypes.c_int64
    lib.t4j_set_hier.argtypes = [ctypes.c_int32, ctypes.c_int64]
    lib.t4j_set_resilience.argtypes = [
        ctypes.c_int32, ctypes.c_double, ctypes.c_double, ctypes.c_int64,
    ]
    lib.t4j_set_elastic.argtypes = [
        ctypes.c_int32, ctypes.c_int32, ctypes.c_double,
    ]
    lib.t4j_world_info.argtypes = [
        ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.t4j_world_info.restype = ctypes.c_int32
    lib.t4j_resize_wait.argtypes = [ctypes.c_double]
    lib.t4j_resize_wait.restype = ctypes.c_int32
    lib.t4j_link_stats.argtypes = [
        ctypes.c_int32,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.t4j_link_stats.restype = ctypes.c_int32
    lib.t4j_link_stripe_stats.argtypes = [
        ctypes.c_int32,
        ctypes.c_int32,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.t4j_link_stripe_stats.restype = ctypes.c_int32
    lib.t4j_set_wire.argtypes = [
        ctypes.c_int32, ctypes.c_int64, ctypes.c_int32, ctypes.c_int64,
    ]
    lib.t4j_wire_info.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.t4j_wire_info.restype = ctypes.c_int32
    lib.t4j_set_wire_dtype.argtypes = [ctypes.c_int32]
    lib.t4j_wire_dtype_info.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.t4j_wire_dtype_info.restype = ctypes.c_int32
    lib.t4j_set_wire_backend.argtypes = [ctypes.c_int32]
    lib.t4j_wire_backend_info.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.t4j_wire_backend_info.restype = ctypes.c_int32
    lib.t4j_topo.argtypes = [ctypes.POINTER(ctypes.c_int32)] * 5
    lib.t4j_topo.restype = ctypes.c_int32
    lib.t4j_hier_would_select.argtypes = [ctypes.c_int32, ctypes.c_uint64]
    lib.t4j_hier_would_select.restype = ctypes.c_int32
    lib.t4j_hier_active.argtypes = [ctypes.c_int32]
    lib.t4j_hier_active.restype = ctypes.c_int32
    lib.t4j_abort_notify.argtypes = [ctypes.c_char_p]
    # telemetry surface (docs/observability.md)
    lib.t4j_set_telemetry.argtypes = [ctypes.c_int32, ctypes.c_int64]
    lib.t4j_telemetry_mode.restype = ctypes.c_int32
    lib.t4j_telemetry_drain.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.t4j_telemetry_drain.restype = ctypes.c_int64
    lib.t4j_telemetry_peek_last.argtypes = [
        ctypes.c_void_p, ctypes.c_int64,
    ]
    lib.t4j_telemetry_peek_last.restype = ctypes.c_int64
    lib.t4j_telemetry_dropped.restype = ctypes.c_uint64
    lib.t4j_set_flight.argtypes = [ctypes.c_int32, ctypes.c_char_p]
    lib.t4j_flight_info.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int32,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.t4j_flight_info.restype = ctypes.c_int32
    lib.t4j_telemetry_anchor.argtypes = [
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.t4j_telemetry_anchor.restype = ctypes.c_int32
    lib.t4j_metrics_snapshot.argtypes = [
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_int64,
    ]
    lib.t4j_metrics_snapshot.restype = ctypes.c_int64
    lib.t4j_annotate_step.argtypes = [ctypes.c_int64, ctypes.c_int32]
    # data plane for the host-callback tier (TPU staging path); every
    # call returns a status: 0 ok, nonzero = failed with t4j_last_error
    i32, u64, vp = ctypes.c_int32, ctypes.c_uint64, ctypes.c_void_p
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.t4j_c_hier_allreduce.argtypes = [i32, vp, vp, u64, i32, i32]
    lib.t4j_c_send.argtypes = [i32, vp, u64, i32, i32]
    lib.t4j_c_recv.argtypes = [i32, vp, u64, i32, i32, i32p, i32p]
    lib.t4j_c_sendrecv.argtypes = [i32, vp, u64, vp, u64, i32, i32, i32,
                                   i32, i32p, i32p]
    # fused multi-part p2p (small-message coalescing): pointer-array
    # iovec surface, sizes as u64[]
    vpp = ctypes.POINTER(ctypes.c_void_p)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.t4j_c_sendrecv_fused.argtypes = [
        i32, vpp, u64p, i32, vpp, u64p, i32, i32, i32, i32, i32, i32p,
        i32p,
    ]
    lib.t4j_c_alltoall_fused.argtypes = [i32, vpp, vpp, u64p, i32]
    lib.t4j_c_barrier.argtypes = [i32]
    lib.t4j_c_bcast.argtypes = [i32, vp, u64, i32]
    lib.t4j_c_allreduce.argtypes = [i32, vp, vp, u64, i32, i32]
    lib.t4j_c_reduce.argtypes = [i32, vp, vp, u64, i32, i32, i32]
    lib.t4j_c_scan.argtypes = [i32, vp, vp, u64, i32, i32]
    lib.t4j_c_reduce_scatter.argtypes = [i32, vp, vp, u64, i32, i32]
    lib.t4j_c_allgather.argtypes = [i32, vp, vp, u64]
    lib.t4j_c_gather.argtypes = [i32, vp, vp, u64, i32]
    lib.t4j_c_scatter.argtypes = [i32, vp, vp, u64, i32]
    lib.t4j_c_alltoall.argtypes = [i32, vp, vp, u64]
    # async progress engine (docs/async.md): nonblocking submits return
    # a request id (0 = failure, message via t4j_last_error)
    lib.t4j_iallreduce.argtypes = [i32, vp, vp, u64, i32, i32]
    lib.t4j_iallreduce.restype = u64
    lib.t4j_ireduce_scatter.argtypes = [i32, vp, vp, u64, i32, i32]
    lib.t4j_ireduce_scatter.restype = u64
    lib.t4j_isend.argtypes = [i32, vp, u64, i32, i32]
    lib.t4j_isend.restype = u64
    lib.t4j_irecv.argtypes = [i32, vp, u64, i32, i32]
    lib.t4j_irecv.restype = u64
    lib.t4j_wait.argtypes = [
        ctypes.c_uint64, ctypes.POINTER(i32), ctypes.POINTER(i32),
    ]
    lib.t4j_wait.restype = i32
    lib.t4j_test.argtypes = [
        ctypes.c_uint64, ctypes.POINTER(i32), ctypes.POINTER(i32),
        ctypes.POINTER(i32),
    ]
    lib.t4j_test.restype = i32
    lib.t4j_waitall.argtypes = [ctypes.POINTER(ctypes.c_uint64), i32]
    lib.t4j_waitall.restype = i32
    lib.t4j_async_inflight.restype = i32
    lib.t4j_async_pending.restype = i32
    for name in (
        "t4j_c_send", "t4j_c_recv", "t4j_c_sendrecv", "t4j_c_barrier",
        "t4j_c_bcast", "t4j_c_allreduce", "t4j_c_hier_allreduce",
        "t4j_c_reduce", "t4j_c_scan",
        "t4j_c_reduce_scatter", "t4j_c_allgather", "t4j_c_gather",
        "t4j_c_scatter", "t4j_c_alltoall", "t4j_c_sendrecv_fused",
        "t4j_c_alltoall_fused",
    ):
        getattr(lib, name).restype = ctypes.c_int32
    _state["lib"] = lib
    return lib


def last_error():
    """Contextual message of the last failed native call on this
    thread (empty string when nothing failed)."""
    lib = _state["lib"]
    if lib is None:
        return ""
    raw = lib.t4j_last_error()
    return raw.decode("utf-8", "replace") if raw else ""


def _check(status):
    """Map a native status code to BridgeError with the bridge's own
    rank/peer/op context."""
    if status:
        raise BridgeError(
            last_error() or "native bridge call failed (no detail)"
        )


def check_health():
    """Raise BridgeError if the bridge posted a fault (a peer died, an
    op timed out, or an abort broadcast arrived).  Called from the op
    tier before dispatch so post-fault calls fail fast instead of
    feeding a dead transport.  When the self-healing layer saw action
    before the fault, the message carries the reconnect/replay
    counters — a job that died AFTER surviving drops usually points at
    a flaky fabric, and the counters make that visible in the
    post-mortem."""
    lib = _state["lib"]
    if lib is None or not lib.t4j_initialized():
        return
    # elastic membership first: a committed resize surfaces as the
    # recoverable WorldResized (the transport is already rebuilt), not
    # as a fault — and an in-flight resize is waited out so the caller
    # sees the verdict
    _check_world_epoch(lib)
    if lib.t4j_health():
        raw = lib.t4j_fault_msg()
        msg = raw.decode("utf-8", "replace") if raw else "bridge faulted"
        stats = link_stats()
        if stats and stats["reconnects"]:
            msg += (
                " [self-healing before the fault: "
                f"{stats['reconnects']} reconnect(s), "
                f"{stats['replayed_frames']} frame(s) / "
                f"{stats['replayed_bytes']} bytes replayed — "
                "docs/failure-semantics.md]"
            )
        # the ring tail shows WHAT the rank was doing when it died
        # (T4J_TELEMETRY=counters records the control-plane events,
        # trace adds the op/frame context — docs/observability.md)
        try:
            tail = _format_recent_events(telemetry_last(8))
        except Exception:
            tail = ""
        if tail:
            msg += f" [last telemetry events: {tail}]"
        raise BridgeError(msg)


def _link_stats_one(lib, peer):
    rec = ctypes.c_uint64(0)
    frames = ctypes.c_uint64(0)
    nbytes = ctypes.c_uint64(0)
    txsc = ctypes.c_uint64(0)
    rxsc = ctypes.c_uint64(0)
    state = ctypes.c_int32(0)
    ok = lib.t4j_link_stats(
        int(peer),
        ctypes.byref(rec), ctypes.byref(frames), ctypes.byref(nbytes),
        ctypes.byref(txsc), ctypes.byref(rxsc),
        ctypes.byref(state),
    )
    if not ok:
        return None
    return {
        "reconnects": rec.value,
        "replayed_frames": frames.value,
        "replayed_bytes": nbytes.value,
        "tx_syscalls": txsc.value,
        "rx_syscalls": rxsc.value,
        "state": state.value,
    }


def _stripe_stats_one(lib, peer, stripe):
    rec = ctypes.c_uint64(0)
    frames = ctypes.c_uint64(0)
    nbytes = ctypes.c_uint64(0)
    txsc = ctypes.c_uint64(0)
    rxsc = ctypes.c_uint64(0)
    state = ctypes.c_int32(0)
    ok = lib.t4j_link_stripe_stats(
        int(peer), int(stripe),
        ctypes.byref(rec), ctypes.byref(frames), ctypes.byref(nbytes),
        ctypes.byref(txsc), ctypes.byref(rxsc),
        ctypes.byref(state),
    )
    if not ok:
        return None
    return {
        "reconnects": rec.value,
        "replayed_frames": frames.value,
        "replayed_bytes": nbytes.value,
        "tx_syscalls": txsc.value,
        "rx_syscalls": rxsc.value,
        "state": state.value,
    }


def set_wire(stripes=None, zerocopy_min_bytes=None, sendmsg_batch=None,
             emu_flow_bps=None):
    """Runtime override of the wire-path knobs (docs/performance.md
    "striped links and the zero-copy path").

    ``stripes`` sets the DEALING width (clamped to the built width
    after init); before init it also fixes the number of connections
    bootstrap builds per link.  ``None`` keeps each current value;
    ``zerocopy_min_bytes=0`` disables MSG_ZEROCOPY;
    ``emu_flow_bps=0`` disables the per-connection test throttle.
    Must be uniform across ranks (the launcher propagates
    ``T4J_STRIPES`` / ``T4J_ZEROCOPY_MIN_BYTES`` /
    ``T4J_SENDMSG_BATCH`` / ``T4J_EMU_FLOW_BPS``): both ends of a
    link must agree on the stripe count, and the receivers reorder by
    the same dealing discipline the senders use."""
    lib = _load()
    lib.t4j_set_wire(
        0 if stripes is None else int(stripes),
        -1 if zerocopy_min_bytes is None else int(zerocopy_min_bytes),
        0 if sendmsg_batch is None else int(sendmsg_batch),
        -1 if emu_flow_bps is None else int(emu_flow_bps),
    )


def wire_info():
    """Effective wire-path state: ``{"stripes_built",
    "stripes_active", "zerocopy_min_bytes", "sendmsg_batch",
    "emu_flow_bps", "zerocopy"}`` — ``zerocopy`` is True only when
    requested AND the kernel honours SO_ZEROCOPY.  ``None`` when the
    native library was never loaded."""
    lib = _state["lib"]
    if lib is None:
        return None
    sb = ctypes.c_int32(0)
    sa = ctypes.c_int32(0)
    zmin = ctypes.c_int64(0)
    batch = ctypes.c_int32(0)
    flow = ctypes.c_int64(0)
    zc = ctypes.c_int32(0)
    zc_done = ctypes.c_uint64(0)
    zc_copied = ctypes.c_uint64(0)
    lib.t4j_wire_info(
        ctypes.byref(sb), ctypes.byref(sa), ctypes.byref(zmin),
        ctypes.byref(batch), ctypes.byref(flow), ctypes.byref(zc),
        ctypes.byref(zc_done), ctypes.byref(zc_copied),
    )
    info = {
        "stripes_built": int(sb.value),
        "stripes_active": int(sa.value),
        "zerocopy_min_bytes": int(zmin.value),
        "sendmsg_batch": int(batch.value),
        "emu_flow_bps": int(flow.value),
        "zerocopy": bool(zc.value),
        # completion diagnostics: copied ~= completions means the
        # fabric (loopback always) fell back to copying — pin overhead
        # with no copy saved (docs/performance.md)
        "zc_completions": int(zc_done.value),
        "zc_copied": int(zc_copied.value),
    }
    info.update(wire_dtype_info() or {})
    info.update(wire_backend_info() or {})
    return info


WIRE_DTYPE_CODES = {"off": 0, "bf16": 1, "fp8": 2}
WIRE_DTYPE_NAMES = {v: k for k, v in WIRE_DTYPE_CODES.items()}


def set_wire_dtype(mode=None):
    """Runtime override of the compressed-collective wire dtype
    (docs/performance.md "Compressed collectives"): ``"off"`` /
    ``"bf16"`` / ``"fp8"`` or the native code 0/1/2; ``None`` keeps
    the current value.  Runtime-changeable like the dealing width (the
    calibrator and the interleaved benchmark arms A/B it inside one
    world), but must stay uniform across ranks — divergent wire
    dtypes exchange mismatched frame sizes and deadlock (t4j-lint rule
    T4J009 names the divergence)."""
    lib = _load()
    if mode is None:
        code = -1
    elif isinstance(mode, str):
        try:
            code = WIRE_DTYPE_CODES[mode.strip().lower()]
        except KeyError:
            raise ValueError(
                f"unknown wire dtype {mode!r} "
                f"(want {'|'.join(WIRE_DTYPE_CODES)})"
            ) from None
    else:
        code = int(mode)
    lib.t4j_set_wire_dtype(code)


def wire_dtype_info():
    """Effective compressed-collective state: ``{"wire_dtype",
    "wire_logical_bytes", "wire_bytes"}`` — the byte counters
    accumulate over the compressed send path only (0 while the mode is
    off), so ``wire_bytes / wire_logical_bytes`` is the provable wire
    saving.  ``None`` when the native library was never loaded."""
    lib = _state["lib"]
    if lib is None:
        return None
    mode = ctypes.c_int32(0)
    logical = ctypes.c_uint64(0)
    wire = ctypes.c_uint64(0)
    lib.t4j_wire_dtype_info(
        ctypes.byref(mode), ctypes.byref(logical), ctypes.byref(wire)
    )
    return {
        "wire_dtype": WIRE_DTYPE_NAMES.get(int(mode.value), "off"),
        "wire_logical_bytes": int(logical.value),
        "wire_bytes": int(wire.value),
    }


WIRE_BACKEND_CODES = {"sendmsg": 0, "uring": 1, "auto": 2}
WIRE_BACKEND_NAMES = {v: k for k, v in WIRE_BACKEND_CODES.items()}


def set_wire_backend(mode=None):
    """Runtime override of the wire data-plane backend
    (docs/performance.md "io_uring wire backend"): ``"sendmsg"`` /
    ``"uring"`` / ``"auto"`` or the native code 0/1/2; ``None`` keeps
    the current value.  Runtime-changeable between collectives (the
    calibrator and the interleaved benchmark arms A/B it inside one
    world) because both backends put identical bytes on the wire; it
    does NOT need to be uniform across ranks, but the launcher
    propagates ``T4J_WIRE_BACKEND`` so benchmarks compare like with
    like.  On a kernel without io_uring ``"uring"`` degrades loudly to
    sendmsg (one stderr line per process)."""
    lib = _load()
    if mode is None:
        code = -1
    elif isinstance(mode, str):
        try:
            code = WIRE_BACKEND_CODES[mode.strip().lower()]
        except KeyError:
            raise ValueError(
                f"unknown wire backend {mode!r} "
                f"(want {'|'.join(WIRE_BACKEND_CODES)})"
            ) from None
    else:
        code = int(mode)
    lib.t4j_set_wire_backend(code)


def wire_backend_info():
    """Effective wire-backend state: ``{"wire_backend",
    "uring_supported", "wire_backend_active"}`` — ``wire_backend`` is
    the requested mode, ``uring_supported`` whether the kernel's
    io_uring probe succeeded, ``wire_backend_active`` the backend the
    stripe threads actually use (``"uring"`` only when requested AND
    supported; ``"auto"`` resolves to sendmsg until the calibrator
    learns otherwise).  Valid pre-init — ``ensure_initialized`` uses
    it to reject an explicit uring request on a kernel without
    io_uring.  ``None`` when the native library was never loaded."""
    lib = _state["lib"]
    if lib is None:
        return None
    mode = ctypes.c_int32(0)
    supported = ctypes.c_int32(0)
    active = ctypes.c_int32(0)
    lib.t4j_wire_backend_info(
        ctypes.byref(mode), ctypes.byref(supported), ctypes.byref(active)
    )
    return {
        "wire_backend": WIRE_BACKEND_NAMES.get(int(mode.value), "auto"),
        "uring_supported": bool(supported.value),
        "wire_backend_active": "uring" if active.value else "sendmsg",
    }


def link_stats(peer=None):
    """Self-healing transport counters (docs/failure-semantics.md
    "self-healing transport"), or ``None`` before init.

    ``peer=None`` aggregates every link: ``{"reconnects",
    "replayed_frames", "replayed_bytes", "state"}`` with ``state`` the
    worst link state (0 up, 1 broken/repairing, 2 dead) — plus the
    per-peer MAXIMA (``"worst_peer"``, ``"max_reconnects"``,
    ``"max_replayed_frames"``, ``"max_replayed_bytes"``), because sums
    hide a single flaky link behind healthy ones and serving admission
    control sheds load by the WORST link, not the average
    (ROADMAP item 5).  ``worst_peer`` is the rank with the most
    reconnects (ties broken by replayed bytes, then by worse state);
    ``None`` when no link has any counter.  An integer ``peer``
    selects that world rank's link (``None`` for self or
    out-of-range)."""
    lib = _state["lib"]
    if lib is None or not lib.t4j_initialized():
        return None
    if peer is not None:
        s = _link_stats_one(lib, peer)
        if s is None:
            return None
        # per-stripe breakdown (docs/performance.md "striped links"):
        # one dict per stripe, so t4j-top and the proc tests can see
        # WHICH flow repaired/replayed instead of just the link sum
        stripes = []
        si = 0
        while True:
            ss = _stripe_stats_one(lib, peer, si)
            if ss is None:
                break
            stripes.append(ss)
            si += 1
        if stripes:
            s["stripes"] = stripes
        return s
    agg = _link_stats_one(lib, -1)
    if agg is None:
        return None
    agg.update(
        worst_peer=None,
        max_reconnects=0,
        max_replayed_frames=0,
        max_replayed_bytes=0,
    )
    worst_key = (0, 0, 0)
    for r in range(int(lib.t4j_world_size())):
        s = _link_stats_one(lib, r)
        if s is None:
            continue
        agg["max_reconnects"] = max(agg["max_reconnects"],
                                    s["reconnects"])
        agg["max_replayed_frames"] = max(agg["max_replayed_frames"],
                                         s["replayed_frames"])
        agg["max_replayed_bytes"] = max(agg["max_replayed_bytes"],
                                        s["replayed_bytes"])
        key = (s["reconnects"], s["replayed_bytes"], s["state"])
        if key > worst_key and any(key):
            worst_key = key
            agg["worst_peer"] = r
    return agg


def set_resilience(retry_max=None, backoff_base_s=None, backoff_max_s=None,
                   replay_bytes=None):
    """Runtime override of the self-healing transport knobs.

    ``None`` keeps the current value; ``retry_max=0`` disables
    self-healing (the first transport error fails the job).  Must be
    set before init and uniformly across ranks (the launcher
    propagates ``T4J_RETRY_MAX`` / ``T4J_BACKOFF_BASE`` /
    ``T4J_BACKOFF_MAX`` / ``T4J_REPLAY_BYTES``): the reconnect
    listener is wired at bootstrap, and one side healing while the
    other fail-stops would turn every transient drop into an abort."""
    lib = _load()
    lib.t4j_set_resilience(
        -1 if retry_max is None else int(retry_max),
        -1.0 if backoff_base_s is None else float(backoff_base_s),
        -1.0 if backoff_max_s is None else float(backoff_max_s),
        -1 if replay_bytes is None else int(replay_bytes),
    )


_ELASTIC_MODES = {"off": 0, "shrink": 1, "rejoin": 2}


def set_elastic(mode=None, min_world=None, resize_timeout_s=None):
    """Runtime override of the elastic-membership knobs
    (docs/failure-semantics.md "elastic membership").

    ``mode`` is ``"off"`` (a dead rank aborts the whole job, the
    default), ``"shrink"`` (survivors agree on a reduced world and
    continue) or ``"rejoin"`` (shrink, plus rank 0 keeps the bootstrap
    coordinator port open for relaunched replacements); ``None`` keeps
    the current setting.  Must be set before init and uniformly across
    ranks (the launcher propagates ``T4J_ELASTIC`` / ``T4J_MIN_WORLD``
    / ``T4J_RESIZE_TIMEOUT``)."""
    lib = _load()
    if mode is not None and str(mode) not in _ELASTIC_MODES:
        raise ValueError(
            f"cannot interpret elastic mode {mode!r} "
            "(want off|shrink|rejoin)"
        )
    code = -1 if mode is None else _ELASTIC_MODES[str(mode)]
    lib.t4j_set_elastic(
        code,
        0 if min_world is None else int(min_world),
        -1.0 if resize_timeout_s is None else float(resize_timeout_s),
    )


def world_info():
    """Live membership view, or ``None`` before init.

    Returns ``{"epoch", "boot_size", "alive_count", "alive_mask",
    "resizing", "stale_frames", "epoch_transitions"}`` — ``epoch`` 0
    is the bootstrap world and bumps once per committed elastic
    resize; ``alive_mask`` bit r means world rank r is a member;
    ``resizing`` is True while a membership agreement/rebuild is in
    flight; ``stale_frames`` counts frames dropped for carrying a
    pre-resize epoch (diagnostic); ``epoch_transitions`` counts the
    resize epochs THIS process has observed via the health path — the
    exporter's per-epoch transition counter (a rejoined replacement
    starts at 0 even though the world epoch it joins is higher)."""
    lib = _state["lib"]
    if lib is None or not lib.t4j_initialized():
        return None
    epoch = ctypes.c_uint32(0)
    alive = ctypes.c_int32(0)
    mask = ctypes.c_uint64(0)
    resizing = ctypes.c_int32(0)
    stale = ctypes.c_uint64(0)
    if not lib.t4j_world_info(
        ctypes.byref(epoch), ctypes.byref(alive), ctypes.byref(mask),
        ctypes.byref(resizing), ctypes.byref(stale),
    ):
        return None
    return {
        "epoch": int(epoch.value),
        "boot_size": int(lib.t4j_world_size()),
        "alive_count": int(alive.value),
        "alive_mask": int(mask.value),
        "resizing": bool(resizing.value),
        "stale_frames": int(stale.value),
        "epoch_transitions": int(_state.get("epoch_transitions", 0)),
    }


def _mask_ranks(mask, boot_size):
    if boot_size > 64:
        return tuple(range(boot_size))
    return tuple(r for r in range(boot_size) if (mask >> r) & 1)


def alive_ranks():
    """The current members as a sorted tuple of world ranks (the full
    bootstrap range before init or outside elastic jobs)."""
    info = world_info()
    if info is None:
        return None
    return _mask_ranks(info["alive_mask"], info["boot_size"])


def effective_world_size():
    """Current member count (= :func:`world_size` until a resize
    shrinks the membership).  The tuning layer keys its topology
    fingerprint off this, so a resize re-resolves the knobs."""
    info = world_info()
    if info is None:
        return world_size()
    return info["alive_count"]


def resize_wait(timeout_s=None):
    """Block until no elastic resize is in progress (True when
    settled).  ``None`` uses twice the configured T4J_RESIZE_TIMEOUT
    plus slack — a resize that cannot finish inside that posts a fault
    anyway."""
    lib = _state["lib"]
    if lib is None or not lib.t4j_initialized():
        return True
    if timeout_s is None:
        from mpi4jax_tpu.utils import config

        timeout_s = 2 * config.resize_timeout() + 10.0
    return bool(lib.t4j_resize_wait(float(timeout_s)))


def _check_world_epoch(lib):
    """Raise :class:`WorldResized` when the membership changed since
    the last check (clearing the stale comm-handle cache first); wait
    out an in-flight resize so the caller sees the verdict, not the
    turbulence."""
    info = world_info()
    if info is None:
        return
    if info["resizing"]:
        resize_wait()
        info = world_info()
        if info is None:
            return
    last = _state.get("world_view")
    if last is None:
        _state["world_view"] = info
        return
    if info["epoch"] != last["epoch"]:
        _state["world_view"] = info
        _state["comm_cache"].clear()  # pre-resize handles are stale
        _state["epoch_transitions"] = (
            _state.get("epoch_transitions", 0) + 1
        )
        raise WorldResized(
            _mask_ranks(last["alive_mask"], info["boot_size"]),
            _mask_ranks(info["alive_mask"], info["boot_size"]),
            info["epoch"],
        )


def refresh_after_resize(progress=None):
    """Re-resolve the substrate for the resized world: drop the stale
    comm-handle cache and re-run the tuning resolution against the NEW
    topology fingerprint (docs/performance.md "trace-guided
    autotuning").  COLLECTIVE — every surviving member must call it
    (the elastic training loop does, right after catching
    :class:`WorldResized`; a rejoined replacement runs the same
    resolution inside its own ``ensure_initialized``)."""
    _state["comm_cache"].clear()
    try:
        from mpi4jax_tpu import tuning

        return tuning.startup(progress=progress)
    except BridgeError:
        raise
    except Exception as e:  # noqa: BLE001 — cache trouble must not kill
        import sys as _sys

        print(
            "t4j: tuning re-resolution after resize skipped: "
            f"{type(e).__name__}: {e}",
            file=_sys.stderr,
            flush=True,
        )
        return None


_TEL_MODES = {"off": 0, "counters": 1, "trace": 2}
_TEL_MODE_NAMES = {v: k for k, v in _TEL_MODES.items()}


def set_telemetry(mode=None, ring_bytes=None):
    """Runtime override of the telemetry knobs (docs/observability.md).

    ``mode`` is ``"off"`` (zero-cost no-op, the default), ``"counters"``
    (metrics table + control-plane events) or ``"trace"`` (plus
    per-event records — the Perfetto feed); ``None`` keeps the current
    setting.  ``ring_bytes`` bounds the per-rank event ring.  Must be
    set before the first instrumented call: the ring is sized on first
    use and never re-sized."""
    lib = _load()
    code = -1 if mode is None else _TEL_MODES[str(mode)]
    lib.t4j_set_telemetry(
        code, -1 if ring_bytes is None else int(ring_bytes)
    )
    if mode is not None:
        # keep the Python-lane recorder in lockstep: it caches the env
        # mode on first use, and a runtime override that only reached
        # the native ring would silently drop the python timeline lane
        from mpi4jax_tpu.telemetry import recorder

        recorder.set_mode(str(mode))


def telemetry_mode_name():
    """The active telemetry mode as a string (``off`` before load)."""
    lib = _state["lib"]
    if lib is None:
        return "off"
    return _TEL_MODE_NAMES.get(int(lib.t4j_telemetry_mode()), "off")


def set_flight(enabled=None, directory=None):
    """Pre-init override of the flight-recorder knobs
    (docs/observability.md "flight recorder"): ``enabled`` True/False
    (None keeps), ``directory`` the file location (None keeps).  Must
    run before :func:`ensure_initialized` — the mmap'd arena is
    created once during bridge init."""
    lib = _load()
    code = -1 if enabled is None else (1 if enabled else 0)
    lib.t4j_set_flight(
        code, None if directory is None else str(directory).encode()
    )


def flight_info():
    """Live status of this rank's flight recorder, or ``None`` when it
    is off / the bridge never initialized: ``{"path", "file_bytes",
    "heartbeat_ns" (CLOCK_MONOTONIC), "heartbeat_count", "epoch",
    "heartbeat_age_s"}``."""
    lib = _state["lib"]
    if lib is None:
        return None
    path = ctypes.create_string_buffer(4096)
    fb = ctypes.c_uint64(0)
    hb = ctypes.c_uint64(0)
    hc = ctypes.c_uint64(0)
    ep = ctypes.c_uint64(0)
    if not lib.t4j_flight_info(path, len(path), ctypes.byref(fb),
                               ctypes.byref(hb), ctypes.byref(hc),
                               ctypes.byref(ep)):
        return None
    import time as _time

    now = _time.clock_gettime_ns(_time.CLOCK_MONOTONIC)
    return {
        "path": path.value.decode(errors="replace"),
        "file_bytes": int(fb.value),
        "heartbeat_ns": int(hb.value),
        "heartbeat_count": int(hc.value),
        "epoch": int(ep.value),
        "heartbeat_age_s": max(0.0, (now - int(hb.value)) / 1e9)
        if hb.value else None,
    }


def annotate_step(index, phase):
    """Emit a step-boundary event into the native ring (``phase`` 1 =
    begin, 2 = end; ``index`` is the caller-assigned step number).
    The public surface is :func:`mpi4jax_tpu.ops.step.annotate_step` —
    this is the plumbing.  No-op (returns False) when the native
    library was never loaded: single-process mesh/self jobs still get
    the python-lane step record from the recorder, they just have no
    native ring to mark.  Never loads or builds the library."""
    lib = _state["lib"]
    if lib is None:
        return False
    lib.t4j_annotate_step(int(index), int(phase))
    return True


def _decode_event_buffer(buf, nbytes):
    from mpi4jax_tpu.telemetry import schema as _schema

    return _schema.decode_events(bytes(buf[: int(nbytes)]))


def telemetry_drain(max_events=1 << 20):
    """Consume the native event ring (oldest first) into a list of
    :class:`telemetry.schema.Event`.  Empty list when telemetry is off
    or the library was never loaded.  The ring outlives finalize, so
    exit-path drains also carry teardown events."""
    lib = _state["lib"]
    if lib is None:
        return []
    out = []
    chunk = ctypes.create_string_buffer(32 * 4096)
    remaining = int(max_events)
    while remaining > 0:
        got = lib.t4j_telemetry_drain(
            chunk, min(remaining, 4096) * 32
        )
        if got <= 0:
            break
        events = _decode_event_buffer(chunk.raw, got)
        out.extend(events)
        remaining -= len(events)
    return out


def telemetry_last(n=16):
    """The newest ``n`` native events WITHOUT consuming them (the
    check_health post-mortem peek)."""
    lib = _state["lib"]
    if lib is None or n <= 0:
        return []
    buf = ctypes.create_string_buffer(32 * int(n))
    got = lib.t4j_telemetry_peek_last(buf, len(buf))
    return _decode_event_buffer(buf.raw, got)


def telemetry_dropped():
    lib = _state["lib"]
    return int(lib.t4j_telemetry_dropped()) if lib is not None else 0


def telemetry_anchor():
    """(mono_ns, unix_ns) clock anchor captured right after the
    bootstrap join barrier (docs/observability.md "clock alignment");
    captured lazily for single-process runs."""
    lib = _load()
    mono = ctypes.c_uint64(0)
    unix = ctypes.c_uint64(0)
    lib.t4j_telemetry_anchor(ctypes.byref(mono), ctypes.byref(unix))
    return mono.value, unix.value


def metrics_snapshot():
    """The native metrics table as a list of u64 words (parse with
    ``telemetry.schema.parse_snapshot`` / feed to
    ``telemetry.registry.MetricsRegistry.from_snapshot``).  Empty list
    when the library was never loaded or nothing was counted."""
    lib = _state["lib"]
    if lib is None:
        return []
    need = lib.t4j_metrics_snapshot(None, 0)
    if need <= 0:
        return []
    # sizing/fill race: a concurrent op can add a table row between
    # the two calls, making the fill call return the NEW required size
    # without writing (the native side never overruns the buffer).
    # Retry with the fresh size; the table has finitely many rows, so
    # this converges — the bound is just a backstop.
    for _ in range(4):
        buf = (ctypes.c_uint64 * int(need))()
        got = lib.t4j_metrics_snapshot(buf, need)
        if got <= need:
            return list(buf[: int(got)])
        need = got
    return []


def _format_recent_events(events):
    """Compact post-mortem rendering of the ring tail — delegates to
    the shared :func:`telemetry.schema.format_recent_events` so
    check_health, the launcher's first-failure report, and the
    exporter's one-shot export all render the tail identically."""
    from mpi4jax_tpu.telemetry import schema as _schema

    return _schema.format_recent_events(events)


def notify_abort(why):
    """Best-effort MPI_Abort analog: tell every peer this process is
    going down so their blocked collectives raise instead of hanging
    until the launcher's external kill."""
    lib = _state["lib"]
    if lib is not None and lib.t4j_initialized():
        lib.t4j_abort_notify(str(why).encode("utf-8", "replace"))


def set_tuning(ring_min_bytes=None, seg_bytes=None):
    """Runtime override of the TCP-tier collective tuning, in bytes.

    ``None`` keeps the current value; ``ring_min_bytes=0`` forces the
    segmented ring path for every message size.  Must be set uniformly
    across ranks (the launcher propagates ``T4J_RING_MIN_BYTES`` /
    ``T4J_SEG_BYTES``): ranks disagreeing on the switchover would run
    mismatched algorithms and deadlock."""
    lib = _load()
    lib.t4j_set_tuning(
        -1 if ring_min_bytes is None else int(ring_min_bytes),
        0 if seg_bytes is None else int(seg_bytes),
    )


def set_coalesce(bytes_threshold=None):
    """Runtime override of the small-message coalescing threshold
    (docs/performance.md "small-message coalescing"), in bytes.

    ``None`` keeps the current value; 0 disables fusion entirely (the
    exact pre-coalescing wire behaviour).  Must be uniform across
    ranks: both sides of a fused exchange must agree to fuse."""
    lib = _load()
    lib.t4j_set_coalesce(
        -1 if bytes_threshold is None else int(bytes_threshold)
    )


def coalesce_bytes():
    """The native layer's effective coalescing threshold in bytes."""
    lib = _load()
    return int(lib.t4j_coalesce_bytes())


_HIER_MODES = {"auto": 0, "on": 1, "off": 2}


def set_hier(mode=None, leader_ring_min_bytes=None):
    """Runtime override of the hierarchical-collective selection.

    ``mode`` is ``"auto"`` (size threshold), ``"on"`` (force wherever
    the topology allows) or ``"off"``; ``None`` keeps the current
    setting.  ``leader_ring_min_bytes`` is auto mode's switchover.
    Must be set uniformly across ranks (the launcher propagates
    ``T4J_HIER`` / ``T4J_LEADER_RING_MIN_BYTES``): ranks disagreeing
    on the selection would run mismatched algorithms and deadlock."""
    lib = _load()
    code = -1 if mode is None else _HIER_MODES[str(mode)]
    lib.t4j_set_hier(
        code,
        -1 if leader_ring_min_bytes is None else int(leader_ring_min_bytes),
    )


def topology():
    """Bootstrap topology of this rank, or ``None`` before init.

    Returns ``{"host_id", "local_rank", "local_size", "leader_rank",
    "n_hosts"}`` — host ordinals in first-occurrence order over world
    ranks, the leader being the lowest world rank on the host.  This
    is the map the hierarchical collectives are built on
    (docs/performance.md "hierarchical collectives")."""
    lib = _state["lib"]
    if lib is None or not lib.t4j_initialized():
        return None
    vals = [ctypes.c_int32(0) for _ in range(5)]
    if not lib.t4j_topo(*[ctypes.byref(v) for v in vals]):
        return None
    keys = ("host_id", "local_rank", "local_size", "leader_rank", "n_hosts")
    return dict(zip(keys, (v.value for v in vals)))


def hier_would_select(handle, total_bytes):
    """Would a collective of ``total_bytes`` on this comm handle take
    the hierarchical path right now?  Pure query — never communicates
    (benchmarks use it to label records)."""
    lib = _state["lib"]
    if lib is None or not lib.t4j_initialized():
        return False
    return lib.t4j_hier_would_select(int(handle), int(total_bytes)) == 1


def hier_active(handle):
    """True once the comm's hierarchical layer has been negotiated and
    is live (passive read)."""
    lib = _state["lib"]
    if lib is None or not lib.t4j_initialized():
        return False
    return lib.t4j_hier_active(int(handle)) == 1


def host_hier_allreduce(handle, x, opcode):
    """Explicitly hierarchical allreduce (raises when the topology is
    ineligible) — the auto-selected path is :func:`host_allreduce`."""
    import numpy as np

    x = _contig(x)
    out = np.empty_like(x)
    _check(_state["lib"].t4j_c_hier_allreduce(
        handle, _ptr(x), _ptr(out), x.size, dtype_code(x.dtype), opcode
    ))
    return out


def set_timeouts(op_s=None, connect_s=None):
    """Runtime override of the bridge deadlines, in seconds.

    ``None`` keeps the current value; ``op_s=0`` disables the per-op
    deadline.  Useful to arm a tight deadline only after warmup
    (startup skew and first-call compiles legitimately exceed
    sub-second deadlines)."""
    lib = _load()
    lib.t4j_set_timeouts(
        -1.0 if op_s is None else float(op_s),
        -1.0 if connect_s is None else float(connect_s),
    )


# numpy dtype -> native DType enum (dcn.h; the reference's 14-entry
# dtype table, mpi4jax/_src/utils.py:43-71, plus bf16)
_DTYPE_CODES = {
    "float32": 0,
    "float64": 1,
    "int8": 2,
    "int16": 3,
    "int32": 4,
    "int64": 5,
    "uint8": 6,
    "uint16": 7,
    "uint32": 8,
    "uint64": 9,
    "bool": 10,
    "complex64": 11,
    "complex128": 12,
    "float16": 13,
    "bfloat16": 14,
}


def dtype_code(np_dtype):
    name = str(np_dtype)
    try:
        return _DTYPE_CODES[name]
    except KeyError:
        raise ValueError(f"unsupported dtype for the native bridge: {name}")


def _ptr(arr):
    return arr.ctypes.data_as(ctypes.c_void_p)


def _contig(x):
    import numpy as np

    return np.ascontiguousarray(x)


# -- numpy-level op wrappers (host-callback data plane) --------------------


def host_allreduce(handle, x, opcode):
    import numpy as np

    x = _contig(x)
    out = np.empty_like(x)
    _check(_state["lib"].t4j_c_allreduce(
        handle, _ptr(x), _ptr(out), x.size, dtype_code(x.dtype), opcode
    ))
    return out


def host_reduce(handle, x, opcode, root):
    import numpy as np

    x = _contig(x)
    out = np.empty_like(x)
    _check(_state["lib"].t4j_c_reduce(
        handle, _ptr(x), _ptr(out), x.size, dtype_code(x.dtype), opcode, root
    ))
    if _state["lib"].t4j_comm_rank(handle) != root:
        return x  # off-root output is the input passthrough (wrapper contract)
    return out


def host_reduce_scatter(handle, x, opcode):
    """``x`` has shape ``(comm_size, *rest)``; returns the reduction of
    row ``rank`` (MPI_Reduce_scatter_block over the segmented ring)."""
    import numpy as np

    x = _contig(x)
    out = np.empty(x.shape[1:], x.dtype)
    _check(_state["lib"].t4j_c_reduce_scatter(
        handle, _ptr(x), _ptr(out), out.size, dtype_code(x.dtype), opcode
    ))
    return out


def host_scan(handle, x, opcode):
    import numpy as np

    x = _contig(x)
    out = np.empty_like(x)
    _check(_state["lib"].t4j_c_scan(
        handle, _ptr(x), _ptr(out), x.size, dtype_code(x.dtype), opcode
    ))
    return out


def host_barrier(handle):
    _check(_state["lib"].t4j_c_barrier(handle))


def host_bcast(handle, x, root):
    import numpy as np

    x = np.array(x, order="C")  # one writable contiguous copy
    _check(_state["lib"].t4j_c_bcast(handle, _ptr(x), x.nbytes, root))
    return x


def host_allgather(handle, x):
    import numpy as np

    x = _contig(x)
    n = _state["lib"].t4j_comm_size(handle)
    out = np.empty((n, *x.shape), x.dtype)
    _check(_state["lib"].t4j_c_allgather(handle, _ptr(x), _ptr(out), x.nbytes))
    return out


def host_gather(handle, x, root):
    import numpy as np

    x = _contig(x)
    n = _state["lib"].t4j_comm_size(handle)
    out = np.empty((n, *x.shape), x.dtype)
    _check(_state["lib"].t4j_c_gather(handle, _ptr(x), _ptr(out), x.nbytes, root))
    return out


def host_scatter(handle, x, root):
    import numpy as np

    x = _contig(x)
    lib = _state["lib"]
    if lib.t4j_comm_rank(handle) == root:
        out = np.empty(x.shape[1:], x.dtype)
        nbytes_each = out.nbytes
    else:
        out = np.empty(x.shape, x.dtype)
        nbytes_each = out.nbytes
    _check(lib.t4j_c_scatter(handle, _ptr(x), _ptr(out), nbytes_each, root))
    return out


def host_alltoall(handle, x):
    import numpy as np

    x = _contig(x)
    n = _state["lib"].t4j_comm_size(handle)
    out = np.empty_like(x)
    _check(_state["lib"].t4j_c_alltoall(handle, _ptr(x), _ptr(out), x.nbytes // n))
    return out


def host_send(handle, x, dest, tag):
    x = _contig(x)
    _check(_state["lib"].t4j_c_send(handle, _ptr(x), x.nbytes, dest, tag))


def host_recv(handle, shape, dtype, source, tag):
    import numpy as np

    out = np.empty(shape, dtype)
    src = ctypes.c_int32(0)
    tg = ctypes.c_int32(0)
    _check(_state["lib"].t4j_c_recv(
        handle, _ptr(out), out.nbytes, source, tag,
        ctypes.byref(src), ctypes.byref(tg),
    ))
    return out, np.int32(src.value), np.int32(tg.value)


def _ptr_array(arrays):
    arr = (ctypes.c_void_p * max(len(arrays), 1))()
    for i, a in enumerate(arrays):
        arr[i] = a.ctypes.data
    return arr


def _u64_array(sizes):
    return (ctypes.c_uint64 * max(len(sizes), 1))(*sizes)


def host_sendrecv_fused(handle, send_arrays, recv_templates, source, dest,
                        sendtag, recvtag):
    """Fused multi-part sendrecv (docs/performance.md "small-message
    coalescing"): every part in ``send_arrays`` travels in ONE wire
    frame to ``dest``, and one frame from ``source`` is scattered into
    arrays shaped like ``recv_templates`` (anything with ``.shape`` /
    ``.dtype`` — ShapeDtypeStructs included, so callers need not
    materialise template arrays).  Empty ``send_arrays`` /
    ``recv_templates`` select the one-sided halves.  Returns
    ``(outs, src, tag)``."""
    import numpy as np

    sends = [_contig(a) for a in send_arrays]
    outs = [np.empty(tuple(t.shape), t.dtype) for t in recv_templates]
    src = ctypes.c_int32(-1)
    tg = ctypes.c_int32(-1)
    _check(_state["lib"].t4j_c_sendrecv_fused(
        handle, _ptr_array(sends),
        _u64_array([a.nbytes for a in sends]), len(sends),
        _ptr_array(outs), _u64_array([o.nbytes for o in outs]),
        len(outs), source, dest, sendtag, recvtag,
        ctypes.byref(src), ctypes.byref(tg),
    ))
    return outs, np.int32(src.value), np.int32(tg.value)


def host_alltoall_fused(handle, parts):
    """Fused multi-part alltoall: part i has shape ``(comm_size,
    *rest_i)``; each peer receives ONE frame carrying its slice of
    every part (bit-identical to per-part ``host_alltoall``).  Returns
    the output parts."""
    import numpy as np

    parts = [_contig(p) for p in parts]
    outs = [np.empty_like(p) for p in parts]
    n = _state["lib"].t4j_comm_size(handle)
    _check(_state["lib"].t4j_c_alltoall_fused(
        handle, _ptr_array(parts), _ptr_array(outs),
        _u64_array([p.nbytes // n for p in parts]), len(parts),
    ))
    return outs


def host_sendrecv(handle, sendbuf, recvbuf, source, dest, sendtag, recvtag):
    import numpy as np

    sendbuf = _contig(sendbuf)
    out = np.empty(recvbuf.shape, recvbuf.dtype)
    src = ctypes.c_int32(0)
    tg = ctypes.c_int32(0)
    _check(_state["lib"].t4j_c_sendrecv(
        handle, _ptr(sendbuf), sendbuf.nbytes, _ptr(out), out.nbytes,
        source, dest, sendtag, recvtag, ctypes.byref(src), ctypes.byref(tg),
    ))
    return out, np.int32(src.value), np.int32(tg.value)


# -- async request layer (docs/async.md) ----------------------------------
#
# Nonblocking submits hand the native progress engine RAW buffer
# pointers, so the numpy arrays MUST outlive the request: the registry
# below pins (input, output) per request id until the matching
# host_wait/host_test-done consumes it.  Never-waited entries are the
# request leaks reported at finalize (and statically by t4j-lint rule
# T4J008, docs/static-analysis.md).

_async_reqs = {}  # rid -> {"kind", "out", "keep"}


def _async_submit(kind, rid, out, keep):
    if not rid:
        raise BridgeError(
            last_error() or f"native {kind} submit failed (no detail)"
        )
    _async_reqs[int(rid)] = {"kind": kind, "out": out, "keep": keep}
    return int(rid)


def host_iallreduce(handle, x, opcode):
    """Submit a nonblocking allreduce; returns the request id.  The
    result array is produced by :func:`host_wait` on that id."""
    import numpy as np

    x = _contig(x)
    out = np.empty_like(x)
    rid = _state["lib"].t4j_iallreduce(
        handle, _ptr(x), _ptr(out), x.size, dtype_code(x.dtype), opcode
    )
    return _async_submit("iallreduce", rid, out, (x,))


def host_ireduce_scatter(handle, x, opcode):
    """Nonblocking MPI_Reduce_scatter_block submit: ``x`` has shape
    ``(comm_size, *rest)``; wait returns the reduction of row rank."""
    import numpy as np

    x = _contig(x)
    out = np.empty(x.shape[1:], x.dtype)
    rid = _state["lib"].t4j_ireduce_scatter(
        handle, _ptr(x), _ptr(out), out.size, dtype_code(x.dtype), opcode
    )
    return _async_submit("ireduce_scatter", rid, out, (x,))


def host_isend(handle, x, dest, tag):
    x = _contig(x)
    rid = _state["lib"].t4j_isend(
        handle, _ptr(x), x.nbytes, int(dest), int(tag)
    )
    return _async_submit("isend", rid, None, (x,))


def host_irecv(handle, shape, dtype, source, tag):
    import numpy as np

    out = np.empty(shape, dtype)
    rid = _state["lib"].t4j_irecv(
        handle, _ptr(out), out.nbytes, int(source), int(tag)
    )
    return _async_submit("irecv", rid, out, ())


def host_wait(rid):
    """Block until request ``rid`` completes; consumes it.

    Returns ``(out, src, tag)`` — ``out`` is the result array (``None``
    for isend), ``src``/``tag`` the matched envelope for irecv (-1
    otherwise).  Raises BridgeError with the engine-side context when
    the op failed, and on a second wait of the same request."""
    rec = _async_reqs.pop(int(rid), None)
    src = ctypes.c_int32(-1)
    tag = ctypes.c_int32(-1)
    status = _state["lib"].t4j_wait(
        ctypes.c_uint64(int(rid)), ctypes.byref(src), ctypes.byref(tag)
    )
    if status:
        raise BridgeError(
            last_error() or "native wait failed (no detail)"
        )
    if rec is None:
        # the native layer accepted the wait (double-bookkeeping drift:
        # should be unreachable — native is the source of truth)
        return None, src.value, tag.value
    return rec["out"], src.value, tag.value


def host_test(rid):
    """Nonblocking completion probe: True when request ``rid`` is
    complete (it is NOT consumed — call :func:`host_wait` to fetch the
    result and release it).  A failed op raises here, consuming it."""
    done = ctypes.c_int32(0)
    status = _state["lib"].t4j_test(
        ctypes.c_uint64(int(rid)), ctypes.byref(done), None, None
    )
    if status:
        _async_reqs.pop(int(rid), None)
        raise BridgeError(last_error() or "native test failed (no detail)")
    return bool(done.value)


def async_inflight():
    """Progress-engine gauge: requests submitted but not yet complete
    (queued + running + parked).  0 when idle or before load."""
    lib = _state["lib"]
    return int(lib.t4j_async_inflight()) if lib is not None else 0


def async_pending():
    """Requests this process never consumed with wait (leak gauge).

    The native engine is authoritative: requests submitted through the
    in-jit FFI fast path never enter the Python-side registry, but
    every request (FFI or callback path) lives in the engine's inflight
    table until waited."""
    lib = _state["lib"]
    if lib is not None and lib.t4j_initialized():
        return int(lib.t4j_async_pending())
    return len(_async_reqs)


def async_assert_drained():
    """Raise if any async request was submitted but never waited — the
    runtime counterpart of ``Token.assert_drained`` (t4j-lint reports
    the same statically as rule T4J008)."""
    n = async_pending()
    if n:
        kinds = ", ".join(
            f"{rec['kind']} (req {rid})"
            for rid, rec in list(_async_reqs.items())[:8]
        ) or "submitted via the in-jit fast path"
        raise BridgeError(
            f"{n} async request(s) never waited: {kinds}"
            " — every iallreduce/isend/irecv must be completed by "
            "wait/waitall exactly once (docs/async.md)"
        )


def available():
    """True when this process is part of a multi-process job (launched
    via mpi4jax_tpu.launch or with T4J_RANK/T4J_SIZE set)."""
    return "T4J_RANK" in os.environ and "T4J_SIZE" in os.environ


def is_initialized():
    lib = _state["lib"]
    return bool(lib and lib.t4j_initialized())


def _ffi_module():
    """jax.ffi (jax>=0.7), or jax.extend.ffi on older lines — the
    latter keeps the ctypes control plane and the staged data plane
    usable from standalone harnesses on old-jax containers (same
    fallback as native/build.py)."""
    try:
        import jax.ffi as ffi
    except ImportError:
        from jax.extend import ffi
    return ffi


def _register_ffi_targets(lib):
    if _state["registered"]:
        return
    ffi = _ffi_module()

    for name in HANDLER_NAMES:
        fn = getattr(lib, name)
        ffi.register_ffi_target(name, ffi.pycapsule(fn), platform="cpu")
    _state["registered"] = True


def ensure_initialized():
    """Bootstrap the process world (idempotent).

    The analog of the reference's import-time ``from mpi4py import MPI``
    (mpi4jax/_src/__init__.py:3), made lazy/explicit: connects the TCP
    mesh, registers the XLA FFI targets, and installs the exit hook.
    """
    if is_initialized():
        return True
    if not available():
        return False
    # utils/config.py owns deadline validation: a bad T4J_OP_TIMEOUT /
    # T4J_CONNECT_TIMEOUT raises ValueError here, before the native
    # library is even built/loaded
    from mpi4jax_tpu.utils import config

    op_s, connect_s = config.op_timeout(), config.connect_timeout()
    ring_min, seg = config.ring_min_bytes(), config.seg_bytes()
    coalesce = config.coalesce_bytes()
    # wire-path knobs (docs/performance.md "striped links and the
    # zero-copy path"): validated loudly here, threaded before init —
    # the stripe count decides how many connections bootstrap builds.
    # "auto" stays native-default (one flow) until the tuning layer
    # resolves a calibrated width post-init.
    wire_stripes = config.stripes()
    zc_min = config.zerocopy_min_bytes()
    batch = config.sendmsg_batch()
    flow = config.emu_flow_bps()
    # compressed-collective wire dtype (docs/performance.md
    # "Compressed collectives"): a typo'd T4J_WIRE_DTYPE raises HERE,
    # before init — silently running uncompressed would fake the
    # benchmark the operator asked for.  Note the eligibility rule is
    # per-collective in the native layer (f32 SUM only; integer and
    # MIN/MAX payloads have no defined cast and always travel exact),
    # so fp8/bf16 is a policy cap, not a promise.
    wdtype = config.wire_dtype()
    # wire data-plane backend (docs/performance.md "io_uring wire
    # backend"): a typo'd T4J_WIRE_BACKEND raises HERE, before init.
    # An EXPLICIT uring request on a kernel whose io_uring probe fails
    # is also rejected below (after the library loads) — the managed
    # path fails loud rather than silently benchmarking sendmsg under
    # a uring label; standalone ctypes users get the native layer's
    # loud one-line degrade instead.
    wbackend = config.wire_backend()
    if zc_min > 0 and zc_min < 4096:
        raise ValueError(
            f"T4J_ZEROCOPY_MIN_BYTES={zc_min} is below the page floor "
            "(4096): MSG_ZEROCOPY pins whole pages per send, and "
            "sub-page frames pay the pin/completion round-trip for "
            "no copy saved — use 0 (off) or >= 4096 "
            "(docs/performance.md \"striped links and the zero-copy "
            "path\")"
        )
    config.autotune_enabled()  # loud validation; the flag acts post-init
    hier, hier_min = config.hier_mode(), config.leader_ring_min_bytes()
    retry = config.retry_max()
    boff_base, boff_max = config.backoff_base(), config.backoff_max()
    replay = config.replay_bytes()
    elastic = config.elastic_mode()
    world_floor = config.min_world()
    resize_s = config.resize_timeout()
    if elastic != "off" and retry == 0:
        raise ValueError(
            "T4J_ELASTIC="
            f"{elastic} requires T4J_RETRY_MAX > 0: the elastic rung "
            "triggers when the self-healing ladder's escalation "
            "declares a rank unrecoverable, and T4J_RETRY_MAX=0 "
            "disables that ladder entirely "
            "(docs/failure-semantics.md \"elastic membership\")"
        )
    # serving knobs (docs/serving.md): validated loudly here like the
    # deadlines — they act in the Python serving tier post-init
    serve_slo = config.slo_ms()
    config.max_batch()
    serve_admit = config.admit_mode()
    if serve_slo > 0 and serve_admit == "off":
        raise ValueError(
            f"T4J_SLO_MS={serve_slo:g} with T4J_ADMIT=off: an SLO "
            "with admission control off cannot be enforced, only "
            "missed — set T4J_ADMIT=on (shed to hold the deadline) "
            "or drop the SLO (docs/serving.md \"admission control\")"
        )
    autoscale = config.autoscale_mode()
    config.scale_up_windows()
    config.scale_down_occ()
    config.scale_down_windows()
    config.scale_cooldown_windows()
    if autoscale == "on" and elastic != "rejoin":
        raise ValueError(
            f"T4J_AUTOSCALE=on with T4J_ELASTIC={elastic}: growing "
            "the world admits a relaunched rank through the kept-open "
            "coordinator port, which only the rejoin mode provides — "
            "set T4J_ELASTIC=rejoin (docs/serving.md \"Autoscaling\")"
        )
    tel_mode, tel_bytes = config.telemetry_mode(), config.telemetry_bytes()
    tel_dir = config.telemetry_dir()
    flight = config.flight_enabled()
    fdir = config.flight_dir() or tel_dir
    lib = _load()
    lib.t4j_set_timeouts(op_s, connect_s)
    lib.t4j_set_tuning(ring_min, seg)
    lib.t4j_set_coalesce(coalesce)
    lib.t4j_set_wire(
        0 if wire_stripes == "auto" else int(wire_stripes),
        zc_min, batch, flow,
    )
    lib.t4j_set_wire_dtype(WIRE_DTYPE_CODES[wdtype])
    lib.t4j_set_wire_backend(WIRE_BACKEND_CODES[wbackend])
    if wbackend == "uring":
        binfo = wire_backend_info()
        if binfo is not None and not binfo["uring_supported"]:
            raise ValueError(
                "T4J_WIRE_BACKEND=uring but this kernel has no usable "
                "io_uring (the probe failed) — use auto (resolves to "
                "sendmsg here) or sendmsg (docs/performance.md "
                "\"io_uring wire backend\")"
            )
    lib.t4j_set_hier(_HIER_MODES[hier], hier_min)
    lib.t4j_set_resilience(retry, boff_base, boff_max, replay)
    lib.t4j_set_elastic(_ELASTIC_MODES[elastic], world_floor, resize_s)
    lib.t4j_set_telemetry(_TEL_MODES[tel_mode], tel_bytes)
    # crash-consistent flight recorder (docs/observability.md "flight
    # recorder"): must be decided before init — the mmap'd arena is
    # created inside t4j_init while the process is single-threaded
    lib.t4j_set_flight(
        1 if flight else 0, None if fdir is None else str(fdir).encode()
    )
    rc = lib.t4j_init()
    if rc != 0:
        detail = last_error()
        raise BridgeError(
            detail
            if detail
            else "native bridge init failed (check T4J_* env)"
        )
    _register_ffi_targets(lib)
    # membership baseline: a rejoined replacement starts at the
    # survivors' current epoch without a spurious WorldResized
    _state["world_view"] = world_info()
    # trace-guided tuning (docs/performance.md "trace-guided
    # autotuning"): load the fingerprint-keyed cache and thread it
    # through the same set_tuning/set_hier/set_coalesce plumbing;
    # explicit T4J_* env always wins, rank 0's resolution is broadcast
    # so divergent per-host cache files can never split the knob
    # vector.  T4J_AUTOTUNE calibrates first (collective) and writes
    # the cache.  A corrupt/stale cache degrades to env/defaults with
    # a warning rather than killing the job.
    try:
        from mpi4jax_tpu import tuning

        tuning.startup(progress=lambda m: print(m, flush=True))
    except BridgeError:
        raise  # a wedged collective during autotune is a real failure
    except Exception as e:  # noqa: BLE001 — cache trouble must not kill
        import sys as _sys

        print(
            f"t4j: tuning cache ignored: {type(e).__name__}: {e}",
            file=_sys.stderr,
            flush=True,
        )
    if tel_dir is not None:
        # registered BEFORE finalize: atexit runs LIFO, so the drain
        # happens after teardown and carries the exit-phase events too
        from mpi4jax_tpu.telemetry import dump

        dump.install_atexit(tel_dir)
    # live metrics exporter (docs/observability.md "live exporter"):
    # T4J_METRICS_PORT=P makes rank k serve its metrics snapshot +
    # link stats on 127.0.0.1:P+k as Prometheus text (/metrics) and
    # JSON (/metrics.json); the launcher's --metrics sets it and
    # aggregates the job view
    mport = config.metrics_port()
    if mport:
        try:
            from mpi4jax_tpu.telemetry import exporter

            srv = exporter.MetricsExporter(
                mport + int(lib.t4j_world_rank())
            )
            srv.start()
            _state["exporter"] = srv
        except Exception as e:  # noqa: BLE001 — metrics must not kill the job
            import sys as _sys

            print(
                f"t4j: metrics exporter failed to start: "
                f"{type(e).__name__}: {e}",
                file=_sys.stderr,
                flush=True,
            )
    atexit.register(finalize)
    return True


def finalize():
    srv = _state.pop("exporter", None)
    if srv is not None:
        try:
            srv.stop()
        except Exception:
            pass
    lib = _state["lib"]
    if lib and lib.t4j_initialized():
        # request-leak detection (docs/async.md): loud on stderr — the
        # native stop reports its own count too, but only this layer
        # knows the Python-level op kinds.  Not raised: finalize runs
        # from atexit, where an exception would mask the job's real
        # outcome; tests assert on the message instead.
        if _async_reqs:
            import sys as _sys

            kinds = ", ".join(
                rec["kind"] for rec in list(_async_reqs.values())[:8]
            )
            print(
                f"t4j: {len(_async_reqs)} async request(s) never waited "
                f"at finalize ({kinds}) — request leak; every "
                "iallreduce/isend/irecv must be completed by "
                "wait/waitall (docs/async.md)",
                file=_sys.stderr,
                flush=True,
            )
        # snapshot the teardown-sensitive telemetry state (per-link
        # counters, topology) while still initialized: the exit-time
        # rank-file drain deliberately runs AFTER this (atexit LIFO)
        # and would otherwise write link_stats {}
        try:
            from mpi4jax_tpu.utils import config

            if config.telemetry_dir() is not None:
                from mpi4jax_tpu.telemetry import dump

                dump.capture_runtime_state()
        except Exception:
            pass
        # flush pending XLA work before tearing down sockets — the
        # reference registers the same hygiene (decorators.py:11-24,
        # flush.py) to avoid the deadlock-on-exit class of bugs.
        # Skipped after a fault: pending work may itself be a wedged
        # collective, and native finalize already skips the exit
        # barrier then.
        if not lib.t4j_health():
            try:
                from mpi4jax_tpu.utils.runtime import drain
                import jax
                import jax.numpy as jnp

                drain(jnp.zeros(()) + 0)
            except Exception:
                pass
        lib.t4j_finalize()
        _async_reqs.clear()  # native reaped everything; release pins


def world_rank():
    ensure_initialized()
    return _state["lib"].t4j_world_rank()


def world_size():
    ensure_initialized()
    return _state["lib"].t4j_world_size()


def set_logging(enabled):
    lib = _load()
    lib.t4j_set_logging(1 if enabled else 0)


def _stable_ctx(ranks, context):
    """Deterministic 30-bit channel id for a communicator.

    Every member must derive the same wire context regardless of its
    local comm-creation order (MPMD processes create comms at different
    times), so the id is a pure function of the group + clone generation
    — FNV-1a over the rank list and context counter.  The world comm is
    pinned to ctx 0 natively.
    """
    h = 0x811C9DC5
    for v in (*ranks, 0x7FFFFFFF, context):
        h ^= (v + 1) & 0xFFFFFFFF
        h = (h * 0x01000193) & 0xFFFFFFFF
    ctx = h & 0x3FFFFFFF
    return ctx if ctx != 0 else 1


def comm_handle(comm):
    """Native handle for a ProcComm (cached per (ranks, context))."""
    ensure_initialized()
    key = (tuple(comm.ranks), comm.context)
    cached = _state["comm_cache"].get(key)
    if cached is not None:
        return cached
    lib = _state["lib"]
    if len(comm.ranks) == world_size() and comm.context == 0:
        handle = 0  # the pre-created world communicator
    else:
        arr = (ctypes.c_int32 * len(comm.ranks))(*comm.ranks)
        handle = lib.t4j_comm_create(
            arr, len(comm.ranks), _stable_ctx(comm.ranks, comm.context)
        )
    _state["comm_cache"][key] = handle
    return handle
