"""Native (C++) tier: the DCN host bridge for the multi-process backend.

Replaces the reference's Cython XLA bridge
(mpi4jax/_src/xla_bridge/mpi_xla_bridge*.pyx) with a C++ socket-based
collective backend exposed through XLA FFI.  Built by
``mpi4jax_tpu/native/build.py``; absent until built.
"""
