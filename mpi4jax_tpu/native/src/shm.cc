// Same-host shared-memory collective arena: see shm.h.

#include "shm.h"
#include "telemetry.h"

#include <fcntl.h>
#include <linux/futex.h>
#include <sched.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <time.h>
#include <unistd.h>

#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace t4j {
namespace shm {

namespace {

constexpr uint32_t kMagic = 0x7446a0AA;
constexpr int kMaxRanks = 64;
constexpr size_t kAlign = 64;

// Fold chunk: small enough that the accumulator segment stays cache-hot
// across the n-1 pairwise combines, so the effective fold traffic is
// ~(n+1) streams instead of 3*(n-1).
constexpr size_t kFoldChunkBytes = 256 << 10;

size_t slot_cap() {
  static size_t cap = [] {
    // T4J_SHM_SLOT_BYTES: byte-granular override (floor 4 KiB) so the
    // piece-boundary test matrix (tests/proc/test_shm_collectives.py)
    // can exercise the streaming gates without megabyte payloads.
    // T4J_SHM_SLOT_MB stays the production knob.
    const char* b = std::getenv("T4J_SHM_SLOT_BYTES");
    if (b && b[0]) {
      long v = std::atol(b);
      if (v < 4096) v = 4096;
      if (v > (256L << 20)) v = 256L << 20;
      return static_cast<size_t>(v);
    }
    const char* s = std::getenv("T4J_SHM_SLOT_MB");
    long mb = s ? std::atol(s) : 8;
    if (mb < 1) mb = 1;
    if (mb > 256) mb = 256;
    return static_cast<size_t>(mb) << 20;
  }();
  return cap;
}

struct Hdr {
  std::atomic<uint32_t> magic;
  std::atomic<uint32_t> progress;  // futex word: bumped on every update
  std::atomic<uint32_t> waiters;
  uint32_t n;
  uint64_t cap;
  // Monotone piece counters (never reset; all members execute the same
  // collective sequence, an MPI-contract invariant, so local piece
  // numbering agrees across ranks).
  std::atomic<uint64_t> staged[kMaxRanks];    // pieces staged into slot
  std::atomic<uint64_t> seg_done[kMaxRanks];  // pieces whose segment fold ran
  std::atomic<uint64_t> acked[kMaxRanks];     // pieces fully consumed
};

void futex_wait(std::atomic<uint32_t>* w, uint32_t val,
                double max_wait_s = 2.0) {
  // bounded: re-check the predicate (and the stop flag / deadline) at
  // least every max_wait_s
  if (max_wait_s <= 0 || max_wait_s > 2.0) max_wait_s = 2.0;
  if (max_wait_s < 0.01) max_wait_s = 0.01;
  timespec ts{static_cast<time_t>(max_wait_s),
              static_cast<long>((max_wait_s -
                                 static_cast<time_t>(max_wait_s)) * 1e9)};
  syscall(SYS_futex, reinterpret_cast<uint32_t*>(w), FUTEX_WAIT, val, &ts,
          nullptr, 0);
}

void futex_wake_all(std::atomic<uint32_t>* w) {
  syscall(SYS_futex, reinterpret_cast<uint32_t*>(w), FUTEX_WAKE, INT32_MAX,
          nullptr, nullptr, 0);
}

double now_s() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + 1e-9 * ts.tv_nsec;
}

// T4J_SHM_TIMEOUT (seconds) opts into fail-fast errors on a stalled
// collective; unset, the stall deadline falls back to the transport-
// wide T4J_OP_TIMEOUT so one knob bounds both tiers, and with neither
// set a stall WARNS once and keeps waiting — matching MPI, where a
// slow peer compiling a big program must not convert into a killed
// job.  A tripped deadline now raises BridgeError through the dcn
// fault path (abort broadcast + fault flag) instead of _exit(13).
// T4J_SHM_WARN (seconds, default 300) tunes when that one-time warning
// fires, for hosts where a legitimately slow first collective (large
// compile on a busy box) outlives the default (ADVICE r4).
double wait_warn_s() {
  static double lim = [] {
    const char* s = std::getenv("T4J_SHM_WARN");
    double v = s ? std::atof(s) : 0.0;
    return v > 0.0 ? v : 300.0;
  }();
  return lim;
}

double wait_abort_s() {
  static double env_lim = [] {
    const char* s = std::getenv("T4J_SHM_TIMEOUT");
    return s ? std::atof(s) : 0.0;  // 0 = defer to T4J_OP_TIMEOUT
  }();
  if (env_lim > 0) return env_lim;
  return detail::op_timeout_seconds();  // 0 = never abort
}

}  // namespace

constexpr size_t hdr_span() {
  return (sizeof(Hdr) + kAlign - 1) & ~(kAlign - 1);
}

struct Arena {
  Hdr* h = nullptr;
  uint8_t* base = nullptr;  // mmap base
  size_t total = 0;
  int n = 0;
  int me = 0;
  uint64_t pieces = 0;  // local count of pieces processed on this comm
  std::string name;
  bool creator = false;
  // T4J_SHM_PROF=1 phase accounting (printed at destroy)
  double t_gate = 0, t_stage = 0, t_wait_staged = 0, t_fold = 0,
         t_wait_folded = 0, t_out = 0;

  uint8_t* slot(int r) const {
    return base + hdr_span() + static_cast<size_t>(r) * h->cap;
  }
  uint8_t* result() const {
    return base + hdr_span() + static_cast<size_t>(n) * h->cap;
  }
};

namespace {

void bump(Hdr* h) {
  // seq_cst on both: a release-RMW followed by an acquire load would
  // let a weakly-ordered CPU hoist the waiters check above the bump
  // (and the data publish), losing a wakeup against a waiter that
  // registered in between — a 2s futex-timeout stall per occurrence
  h->progress.fetch_add(1, std::memory_order_seq_cst);
  if (h->waiters.load(std::memory_order_seq_cst) > 0)
    futex_wake_all(&h->progress);
}

template <class Pred>
void wait_for(Hdr* h, Pred ok) {
  // Single-core-friendly: spinning starves the peer that would satisfy
  // the predicate, so yield almost immediately and fall back to futex.
  for (int s = 0; s < 4; ++s) {
    if (ok()) return;
#if defined(__x86_64__) || defined(__i386__)
    __builtin_ia32_pause();
#endif
  }
  for (int s = 0; s < 16; ++s) {
    if (ok()) return;
    ::sched_yield();
  }
  double t0 = now_s();
  bool warned = false;
  for (;;) {
    // a rank wedged on the shm arena still ticks its flight-recorder
    // heartbeat (bounded by the futex timeout below), so postmortem
    // readers see "alive but stalled", not "dead"
    tel::flight_heartbeat();
    if (detail::stopped()) detail::raise_stop();
    uint32_t seen = h->progress.load(std::memory_order_acquire);
    if (ok()) return;
    double abort_s = wait_abort_s();
    h->waiters.fetch_add(1, std::memory_order_acq_rel);
    if (!ok() && !detail::stopped())
      // tick fast enough that a sub-second deadline actually fires
      // sub-second (the waker may be dead and never bump the futex)
      futex_wait(&h->progress, seen, abort_s > 0 ? abort_s / 4 : 2.0);
    h->waiters.fetch_sub(1, std::memory_order_acq_rel);
    if (ok()) return;
    double waited = now_s() - t0;
    if (!warned && waited > wait_warn_s()) {
      warned = true;
      std::fprintf(stderr,
                   "t4j shm arena: collective waiting > %.0fs for a peer "
                   "(slow rank or deadlock); still waiting — tune this "
                   "warning with T4J_SHM_WARN=<s>, or set "
                   "T4J_SHM_TIMEOUT=<s> for a fail-fast error\n",
                   wait_warn_s());
      std::fflush(stderr);
    }
    if (abort_s > 0 && waited > abort_s) {
      char buf[160];
      std::snprintf(buf, sizeof(buf),
                    "shm arena collective made no progress for %.2fs "
                    "(T4J_SHM_TIMEOUT/T4J_OP_TIMEOUT) — peer stalled "
                    "or dead",
                    waited);
      detail::fail_op(buf);  // abort broadcast + fault + BridgeError
    }
  }
}

uint64_t min_over(const std::atomic<uint64_t>* arr, int n) {
  uint64_t m = UINT64_MAX;
  for (int i = 0; i < n; ++i) {
    uint64_t v = arr[i].load(std::memory_order_acquire);
    if (v < m) m = v;
  }
  return m;
}

// Gate for reusing slots and the result buffer: everyone must have
// fully consumed piece p-1.
void wait_consumed(Hdr* h, uint64_t p) {
  wait_for(h, [&] { return min_over(h->acked, h->n) >= p - 1; });
}

void wait_staged(Hdr* h, uint64_t p) {
  wait_for(h, [&] { return min_over(h->staged, h->n) >= p; });
}

void wait_folded(Hdr* h, uint64_t p) {
  wait_for(h, [&] { return min_over(h->seg_done, h->n) >= p; });
}

// Segment split of `count` elements over n ranks (remainder spread over
// the first ranks), in elements.
void segment(size_t count, int n, int r, size_t* start, size_t* len) {
  size_t base = count / n, rem = count % n;
  *start = r * base + (static_cast<size_t>(r) < rem ? r : rem);
  *len = base + (static_cast<size_t>(r) < rem ? 1 : 0);
}

// The shared piece-iteration scaffold: every collective streams its
// payload in slot-capacity pieces, each piece gated on full consumption
// of the previous one (slot + result reuse fencing), with the
// zero-length case running exactly one synchronization piece so empty
// payloads still order like collectives.  The per-op body receives
// (done_units, piece_units, p) and must end by storing acked[me]=p.
bool prof_enabled() {
  static const bool on = [] {
    const char* s = std::getenv("T4J_SHM_PROF");
    return s && s[0] && std::strcmp(s, "0") != 0;
  }();
  return on;
}

template <class Body>
void for_pieces(Arena* a, size_t total_units, size_t cap_units, Body body) {
  for (size_t done = 0; done < total_units || done == 0;
       done += cap_units) {
    size_t left = total_units - done;
    size_t piece = left < cap_units ? left : cap_units;
    uint64_t p = ++a->pieces;
    double t0 = prof_enabled() ? now_s() : 0;
    wait_consumed(a->h, p);
    if (prof_enabled()) a->t_gate += now_s() - t0;
    body(done, piece, p);
    if (total_units == 0) break;
  }
}

// Pairwise fold of segment [start, start+len) elements across all n
// slots into dst, chunked so the accumulator stays cache-hot.
void fold_segment(Arena* a, size_t start_el, size_t len_el, DType dt,
                  ReduceOp op, uint8_t* dst) {
  size_t esz = dtype_size(dt);
  size_t chunk_el = kFoldChunkBytes / esz;
  if (chunk_el == 0) chunk_el = 1;
  for (size_t off = 0; off < len_el; off += chunk_el) {
    size_t m = len_el - off < chunk_el ? len_el - off : chunk_el;
    size_t byte_off = (start_el + off) * esz;
    uint8_t* acc = dst + off * esz;
    std::memcpy(acc, a->slot(0) + byte_off, m * esz);
    for (int k = 1; k < a->n; ++k)
      detail::combine(op, dt, a->slot(k) + byte_off, acc, m);
  }
}

}  // namespace

bool disabled() {
  const char* off = std::getenv("T4J_NO_SHM");
  return off && off[0] && std::strcmp(off, "0") != 0;
}

namespace {

void arena_name(char* buf, size_t bufsz, const char* job, int ctx) {
  std::snprintf(buf, bufsz, "/t4j_%s_c%d", job, ctx);
}

size_t arena_total(int n, size_t cap) {
  // hdr_span (not sizeof) so slot 0 and everything after start
  // cache-line-aligned
  return hdr_span() + (static_cast<size_t>(n) + 1) * cap;
}

Arena* map_arena(int fd, const char* name, int n, size_t total,
                 int my_index) {
  void* m = ::mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);
  if (m == MAP_FAILED) return nullptr;
#ifdef MADV_HUGEPAGE
  // best-effort THP: the arena is written 4KB-page-dense by big
  // memcpys, so 2MB mappings cut TLB pressure on every phase
  ::madvise(m, total, MADV_HUGEPAGE);
#endif
  Arena* a = new Arena;
  a->base = static_cast<uint8_t*>(m);
  a->h = reinterpret_cast<Hdr*>(a->base);
  a->total = total;
  a->n = n;
  a->me = my_index;
  a->name = name;
  a->creator = my_index == 0;
  return a;
}

}  // namespace

Arena* create(const char* job, int ctx, int n) {
  if (disabled() || n < 2 || n > kMaxRanks) return nullptr;
  char name[200];
  arena_name(name, sizeof(name), job, ctx);
  size_t cap = slot_cap();
  size_t total = arena_total(n, cap);

  // a crashed prior run with the same (job, ctx) — possible only for
  // hand-set T4J_* envs; the launcher's T4J_JOB is a fresh uuid — may
  // have left a stale segment whose counters would corrupt matching:
  // always start from a fresh inode (attachers open ONLY after the
  // agreement round that follows full initialisation, so they can
  // never see the unlinked one)
  ::shm_unlink(name);
  int fd = ::shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;  // no /dev/shm: fall back to TCP
  if (::ftruncate(fd, static_cast<off_t>(total)) != 0) {
    ::close(fd);
    ::shm_unlink(name);
    return nullptr;
  }
  Arena* a = map_arena(fd, name, n, total, 0);
  if (!a) {
    ::shm_unlink(name);
    return nullptr;
  }
  Hdr* h = a->h;
  h->n = static_cast<uint32_t>(n);
  h->cap = cap;
  h->progress.store(0, std::memory_order_relaxed);
  h->waiters.store(0, std::memory_order_relaxed);
  for (int i = 0; i < kMaxRanks; ++i) {
    h->staged[i].store(0, std::memory_order_relaxed);
    h->seg_done[i].store(0, std::memory_order_relaxed);
    h->acked[i].store(0, std::memory_order_relaxed);
  }
  h->magic.store(kMagic, std::memory_order_release);
  return a;
}

Arena* attach(const char* job, int ctx, int n, int my_index) {
  if (disabled() || n < 2 || n > kMaxRanks || my_index <= 0) return nullptr;
  char name[200];
  arena_name(name, sizeof(name), job, ctx);
  size_t cap = slot_cap();
  size_t total = arena_total(n, cap);

  // no O_CREAT: the creator fully initialised the segment before the
  // agreement round delivered us here, so it must exist and be sized
  int fd = ::shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (::fstat(fd, &st) != 0 || st.st_size < static_cast<off_t>(total)) {
    ::close(fd);
    return nullptr;
  }
  Arena* a = map_arena(fd, name, n, total, my_index);
  if (!a) return nullptr;
  if (a->h->magic.load(std::memory_order_acquire) != kMagic ||
      a->h->cap != cap || a->h->n != static_cast<uint32_t>(n)) {
    ::munmap(a->base, a->total);
    delete a;
    return nullptr;
  }
  return a;
}

void unlink_name(Arena* a) {
  if (a && a->creator && !a->name.empty()) {
    ::shm_unlink(a->name.c_str());
    a->name.clear();  // destroy() must not unlink a reused name
  }
}

void destroy(Arena* a) {
  if (!a) return;
  if (a->t_gate + a->t_stage + a->t_fold + a->t_out > 0) {
    std::fprintf(stderr,
                 "t4j shm prof r%d: gate %.1fms stage %.1fms wait_staged "
                 "%.1fms fold %.1fms wait_folded %.1fms out %.1fms\n",
                 a->me, a->t_gate * 1e3, a->t_stage * 1e3,
                 a->t_wait_staged * 1e3, a->t_fold * 1e3,
                 a->t_wait_folded * 1e3, a->t_out * 1e3);
  }
  unlink_name(a);  // normally already done right after the agreement
  ::munmap(a->base, a->total);
  delete a;
}

// ------------------------------------------------------------- collectives

void allreduce(Arena* a, const void* in, void* out, size_t count, DType dt,
               ReduceOp op) {
  Hdr* h = a->h;
  size_t esz = dtype_size(dt);
  const uint8_t* src = static_cast<const uint8_t*>(in);
  uint8_t* dst = static_cast<uint8_t*>(out);
  const bool prof = prof_enabled();
  for_pieces(a, count, h->cap / esz, [&](size_t done, size_t piece,
                                         uint64_t p) {
    double t1 = prof ? now_s() : 0;
    std::memcpy(a->slot(a->me), src + done * esz, piece * esz);
    h->staged[a->me].store(p, std::memory_order_release);
    bump(h);
    double t2 = prof ? now_s() : 0;
    wait_staged(h, p);
    double t3 = prof ? now_s() : 0;
    size_t seg_start, seg_len;
    segment(piece, a->n, a->me, &seg_start, &seg_len);
    if (seg_len)
      fold_segment(a, seg_start, seg_len, dt, op,
                   a->result() + seg_start * esz);
    h->seg_done[a->me].store(p, std::memory_order_release);
    bump(h);
    double t4 = prof ? now_s() : 0;
    wait_folded(h, p);
    double t5 = prof ? now_s() : 0;
    std::memcpy(dst + done * esz, a->result(), piece * esz);
    h->acked[a->me].store(p, std::memory_order_release);
    bump(h);
    if (prof) {
      double t6 = now_s();
      a->t_stage += t2 - t1;
      a->t_wait_staged += t3 - t2;
      a->t_fold += t4 - t3;
      a->t_wait_folded += t5 - t4;
      a->t_out += t6 - t5;
    }
  });
}

void reduce(Arena* a, const void* in, void* out, size_t count, DType dt,
            ReduceOp op, int root) {
  Hdr* h = a->h;
  size_t esz = dtype_size(dt);
  const uint8_t* src = static_cast<const uint8_t*>(in);
  uint8_t* dst = static_cast<uint8_t*>(out);
  for_pieces(a, count, h->cap / esz, [&](size_t done, size_t piece,
                                         uint64_t p) {
    std::memcpy(a->slot(a->me), src + done * esz, piece * esz);
    h->staged[a->me].store(p, std::memory_order_release);
    bump(h);
    wait_staged(h, p);
    size_t seg_start, seg_len;
    segment(piece, a->n, a->me, &seg_start, &seg_len);
    if (seg_len)
      fold_segment(a, seg_start, seg_len, dt, op,
                   a->result() + seg_start * esz);
    h->seg_done[a->me].store(p, std::memory_order_release);
    bump(h);
    if (a->me == root) {
      wait_folded(h, p);
      std::memcpy(dst + done * esz, a->result(), piece * esz);
    }
    h->acked[a->me].store(p, std::memory_order_release);
    bump(h);
  });
}

uint64_t reduce_stage(Arena* a, const void* in, size_t nbytes) {
  Hdr* h = a->h;
  uint64_t p = ++a->pieces;
  wait_consumed(h, p);
  std::memcpy(a->slot(a->me), in, nbytes);
  h->staged[a->me].store(p, std::memory_order_release);
  bump(h);
  tel::trace_event(tel::kShmStage, tel::kInstant, tel::kPlaneShm, -1,
                   -1, nbytes);
  return p;
}

void reduce_finish(Arena* a, uint64_t p, void* out, size_t count,
                   DType dt, ReduceOp op, int root) {
  Hdr* h = a->h;
  wait_staged(h, p);
  size_t seg_start, seg_len;
  segment(count, a->n, a->me, &seg_start, &seg_len);
  size_t esz = dtype_size(dt);
  if (seg_len)
    fold_segment(a, seg_start, seg_len, dt, op,
                 a->result() + seg_start * esz);
  h->seg_done[a->me].store(p, std::memory_order_release);
  bump(h);
  if (a->me == root) {
    wait_folded(h, p);
    std::memcpy(out, a->result(), count * esz);
  }
  h->acked[a->me].store(p, std::memory_order_release);
  bump(h);
  tel::trace_event(tel::kShmFold, tel::kInstant, tel::kPlaneShm, -1,
                   -1, count * esz);
}

size_t slot_bytes() { return slot_cap(); }

void scan(Arena* a, const void* in, void* out, size_t count, DType dt,
          ReduceOp op) {
  // Inclusive prefix: rank r folds slots[0..r].  O(n^2) total combine
  // work across ranks, but each rank's pass is one cache-chunked sweep.
  Hdr* h = a->h;
  size_t esz = dtype_size(dt);
  for_pieces(a, count, h->cap / esz, [&](size_t done, size_t piece,
                                         uint64_t p) {
    const uint8_t* src = static_cast<const uint8_t*>(in);
    uint8_t* dst = static_cast<uint8_t*>(out);
    std::memcpy(a->slot(a->me), src + done * esz, piece * esz);
    h->staged[a->me].store(p, std::memory_order_release);
    bump(h);
    // need slots 0..me staged; waiting for all keeps the gates uniform
    wait_staged(h, p);
    size_t chunk_el = kFoldChunkBytes / esz;
    if (chunk_el == 0) chunk_el = 1;
    for (size_t off = 0; off < piece; off += chunk_el) {
      size_t m = piece - off < chunk_el ? piece - off : chunk_el;
      uint8_t* acc = dst + (done + off) * esz;
      std::memcpy(acc, a->slot(0) + off * esz, m * esz);
      for (int k = 1; k <= a->me; ++k)
        detail::combine(op, dt, a->slot(k) + off * esz, acc, m);
    }
    h->seg_done[a->me].store(p, std::memory_order_release);
    h->acked[a->me].store(p, std::memory_order_release);
    bump(h);
  });
}

void bcast(Arena* a, void* buf, size_t nbytes, int root) {
  Hdr* h = a->h;
  uint8_t* b = static_cast<uint8_t*>(buf);
  for_pieces(a, nbytes, h->cap, [&](size_t done, size_t piece, uint64_t p) {
    if (a->me == root) {
      std::memcpy(a->result(), b + done, piece);
      h->staged[a->me].store(p, std::memory_order_release);
      bump(h);
    } else {
      wait_for(h, [&] {
        return h->staged[root].load(std::memory_order_acquire) >= p;
      });
      std::memcpy(b + done, a->result(), piece);
      h->staged[a->me].store(p, std::memory_order_release);
    }
    h->seg_done[a->me].store(p, std::memory_order_release);
    h->acked[a->me].store(p, std::memory_order_release);
    bump(h);
  });
}

void allgather(Arena* a, const void* in, void* out, size_t nbytes_each) {
  Hdr* h = a->h;
  const uint8_t* src = static_cast<const uint8_t*>(in);
  uint8_t* dst = static_cast<uint8_t*>(out);
  for_pieces(a, nbytes_each, h->cap, [&](size_t done, size_t piece,
                                         uint64_t p) {
    std::memcpy(a->slot(a->me), src + done, piece);
    h->staged[a->me].store(p, std::memory_order_release);
    bump(h);
    wait_staged(h, p);
    for (int k = 0; k < a->n; ++k)
      std::memcpy(dst + k * nbytes_each + done, a->slot(k), piece);
    h->seg_done[a->me].store(p, std::memory_order_release);
    h->acked[a->me].store(p, std::memory_order_release);
    bump(h);
  });
}

void gather(Arena* a, const void* in, void* out, size_t nbytes_each,
            int root) {
  Hdr* h = a->h;
  const uint8_t* src = static_cast<const uint8_t*>(in);
  uint8_t* dst = static_cast<uint8_t*>(out);
  for_pieces(a, nbytes_each, h->cap, [&](size_t done, size_t piece,
                                         uint64_t p) {
    std::memcpy(a->slot(a->me), src + done, piece);
    h->staged[a->me].store(p, std::memory_order_release);
    bump(h);
    if (a->me == root) {
      wait_staged(h, p);
      for (int k = 0; k < a->n; ++k)
        std::memcpy(dst + k * nbytes_each + done, a->slot(k), piece);
    }
    h->seg_done[a->me].store(p, std::memory_order_release);
    h->acked[a->me].store(p, std::memory_order_release);
    bump(h);
  });
}

void scatter(Arena* a, const void* in, void* out, size_t nbytes_each,
             int root) {
  Hdr* h = a->h;
  // root's input is n blocks of nbytes_each; stream block-piece-wise so
  // a block piece always fits the (shared) result buffer
  const uint8_t* src = static_cast<const uint8_t*>(in);
  uint8_t* dst = static_cast<uint8_t*>(out);
  size_t blk_cap = h->cap / static_cast<size_t>(a->n);
  if (blk_cap == 0) blk_cap = 1;
  for_pieces(a, nbytes_each, blk_cap, [&](size_t done, size_t piece,
                                          uint64_t p) {
    if (a->me == root) {
      uint8_t* r = a->result();
      for (int k = 0; k < a->n; ++k)
        std::memcpy(r + k * piece, src + k * nbytes_each + done, piece);
      std::memcpy(dst + done, src + root * nbytes_each + done, piece);
      h->staged[a->me].store(p, std::memory_order_release);
      bump(h);
    } else {
      wait_for(h, [&] {
        return h->staged[root].load(std::memory_order_acquire) >= p;
      });
      std::memcpy(dst + done, a->result() + a->me * piece, piece);
      h->staged[a->me].store(p, std::memory_order_release);
    }
    h->seg_done[a->me].store(p, std::memory_order_release);
    h->acked[a->me].store(p, std::memory_order_release);
    bump(h);
  });
}

void alltoall(Arena* a, const void* in, void* out, size_t nbytes_each) {
  Hdr* h = a->h;
  const uint8_t* src = static_cast<const uint8_t*>(in);
  uint8_t* dst = static_cast<uint8_t*>(out);
  size_t blk_cap = h->cap / static_cast<size_t>(a->n);
  if (blk_cap == 0) blk_cap = 1;
  for_pieces(a, nbytes_each, blk_cap, [&](size_t done, size_t piece,
                                          uint64_t p) {
    uint8_t* s = a->slot(a->me);
    for (int k = 0; k < a->n; ++k)
      std::memcpy(s + k * piece, src + k * nbytes_each + done, piece);
    h->staged[a->me].store(p, std::memory_order_release);
    bump(h);
    wait_staged(h, p);
    for (int k = 0; k < a->n; ++k)
      std::memcpy(dst + k * nbytes_each + done, a->slot(k) + a->me * piece,
                  piece);
    h->seg_done[a->me].store(p, std::memory_order_release);
    h->acked[a->me].store(p, std::memory_order_release);
    bump(h);
  });
}

void barrier(Arena* a) {
  Hdr* h = a->h;
  for_pieces(a, 0, 1, [&](size_t, size_t, uint64_t p) {
    h->staged[a->me].store(p, std::memory_order_release);
    bump(h);
    wait_staged(h, p);
    h->seg_done[a->me].store(p, std::memory_order_release);
    h->acked[a->me].store(p, std::memory_order_release);
    bump(h);
  });
}


// ------------------------------------------------------- p2p byte pipes

namespace {

constexpr uint32_t kPipeMagic = 0x7446a0BB;

size_t pipe_cap() {
  static size_t cap = [] {
    const char* s = std::getenv("T4J_SHM_PIPE_MB");
    long mb = s ? std::atol(s) : 4;
    if (mb < 1) mb = 1;
    if (mb > 64) mb = 64;
    return static_cast<size_t>(mb) << 20;
  }();
  return cap;
}

struct PipeHdr {
  // producer-written line: the consumer reads it, but each side's
  // STORES stay on its own cache line (no false-sharing ping-pong on
  // the data path)
  std::atomic<uint64_t> head;       // bytes ever written
  std::atomic<uint32_t> prod_bell;  // futex: bumped by producer
  std::atomic<uint32_t> prod_waiters;
  uint8_t pad0[48];
  // consumer-written line
  std::atomic<uint64_t> tail;       // bytes ever read
  std::atomic<uint32_t> cons_bell;  // futex: bumped by consumer
  std::atomic<uint32_t> cons_waiters;
  uint8_t pad1[48];
};
static_assert(sizeof(PipeHdr) == 128, "PipeHdr: two cache lines");

struct SegHdr {
  std::atomic<uint32_t> magic;
  uint32_t n;
  uint64_t cap;
};

size_t seg_span() {
  return (sizeof(SegHdr) + kAlign - 1) & ~(kAlign - 1);
}

size_t pipe_stride(size_t cap) {
  return (sizeof(PipeHdr) + cap + kAlign - 1) & ~(kAlign - 1);
}

size_t pipes_total(int n, size_t cap) {
  return seg_span() + static_cast<size_t>(n) * pipe_stride(cap);
}

void pipes_name(char* buf, size_t bufsz, const char* job, int rank) {
  std::snprintf(buf, bufsz, "/t4j_%s_p2p_r%d", job, rank);
}

}  // namespace

struct Pipe {
  PipeHdr* h = nullptr;
  uint8_t* buf = nullptr;
  size_t cap = 0;
  // set only on sender-attached views (owns the mapping)
  uint8_t* owned_base = nullptr;
  size_t owned_total = 0;
};

struct PipeSeg {
  uint8_t* base = nullptr;
  size_t total = 0;
  int n = 0;
  std::string name;
  std::vector<Pipe> pipes;
};

namespace {

void pipe_fill(PipeSeg* seg) {
  SegHdr* sh = reinterpret_cast<SegHdr*>(seg->base);
  size_t cap = sh->cap;
  size_t stride = pipe_stride(cap);
  seg->pipes.resize(seg->n);
  uint8_t* p = seg->base + seg_span();
  for (int i = 0; i < seg->n; ++i) {
    seg->pipes[i].h = reinterpret_cast<PipeHdr*>(p);
    seg->pipes[i].buf = p + sizeof(PipeHdr);
    seg->pipes[i].cap = cap;
    p += stride;
  }
}

}  // namespace

PipeSeg* pipes_create(const char* job, int my_rank, int n_sources) {
  if (disabled() || n_sources < 1) return nullptr;
  char name[200];
  pipes_name(name, sizeof(name), job, my_rank);
  size_t cap = pipe_cap();
  size_t total = pipes_total(n_sources, cap);
  ::shm_unlink(name);
  int fd = ::shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (::ftruncate(fd, static_cast<off_t>(total)) != 0) {
    ::close(fd);
    ::shm_unlink(name);
    return nullptr;
  }
  void* m = ::mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);
  if (m == MAP_FAILED) {
    ::shm_unlink(name);
    return nullptr;
  }
#ifdef MADV_HUGEPAGE
  ::madvise(m, total, MADV_HUGEPAGE);
#endif
  PipeSeg* seg = new PipeSeg;
  seg->base = static_cast<uint8_t*>(m);
  seg->total = total;
  seg->n = n_sources;
  seg->name = name;
  SegHdr* sh = reinterpret_cast<SegHdr*>(seg->base);
  sh->n = static_cast<uint32_t>(n_sources);
  sh->cap = cap;
  pipe_fill(seg);
  for (auto& p : seg->pipes) {
    p.h->head.store(0, std::memory_order_relaxed);
    p.h->tail.store(0, std::memory_order_relaxed);
    p.h->prod_bell.store(0, std::memory_order_relaxed);
    p.h->cons_bell.store(0, std::memory_order_relaxed);
    p.h->prod_waiters.store(0, std::memory_order_relaxed);
    p.h->cons_waiters.store(0, std::memory_order_relaxed);
  }
  sh->magic.store(kPipeMagic, std::memory_order_release);
  return seg;
}

Pipe* pipe_of(PipeSeg* seg, int slot) {
  if (!seg || slot < 0 || slot >= seg->n) return nullptr;
  return &seg->pipes[slot];
}

Pipe* pipe_attach(const char* job, int dest_rank, int slot, int n_sources) {
  if (disabled() || slot < 0 || slot >= n_sources) return nullptr;
  char name[200];
  pipes_name(name, sizeof(name), job, dest_rank);
  size_t cap = pipe_cap();
  size_t total = pipes_total(n_sources, cap);
  // no retry needed: the caller's agreement round confirmed every
  // owner's pipes_create (which publishes the magic before returning)
  // completed before anyone attaches
  int fd = ::shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (::fstat(fd, &st) != 0 || st.st_size < static_cast<off_t>(total)) {
    ::close(fd);
    return nullptr;
  }
  void* m = ::mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);
  if (m == MAP_FAILED) return nullptr;
  SegHdr* sh = reinterpret_cast<SegHdr*>(m);
  if (sh->magic.load(std::memory_order_acquire) != kPipeMagic ||
      sh->cap != cap || sh->n != static_cast<uint32_t>(n_sources)) {
    ::munmap(m, total);
    return nullptr;
  }
  PipeSeg tmp;
  tmp.base = static_cast<uint8_t*>(m);
  tmp.n = n_sources;
  pipe_fill(&tmp);
  Pipe* p = new Pipe(tmp.pipes[slot]);
  p->owned_base = static_cast<uint8_t*>(m);
  p->owned_total = total;
  return p;
}

namespace {

// Wait until pred() or shutdown; bell is the futex word the OTHER side
// bumps, waiters the counter it checks before the wake syscall.
template <class Pred>
bool pipe_wait(std::atomic<uint32_t>* bell, std::atomic<uint32_t>* waiters,
               const std::atomic<bool>& shutdown, Pred pred) {
  for (int s = 0; s < 64; ++s) {
    if (pred()) return true;
    if (shutdown.load(std::memory_order_acquire)) return false;
#if defined(__x86_64__) || defined(__i386__)
    __builtin_ia32_pause();
#endif
  }
  for (int s = 0; s < 16; ++s) {
    if (pred()) return true;
    if (shutdown.load(std::memory_order_acquire)) return false;
    ::sched_yield();
  }
  for (;;) {
    uint32_t seen = bell->load(std::memory_order_acquire);
    if (pred()) return true;
    if (shutdown.load(std::memory_order_acquire)) return false;
    waiters->fetch_add(1, std::memory_order_acq_rel);
    if (!pred() && !shutdown.load(std::memory_order_acquire))
      futex_wait(bell, seen);
    waiters->fetch_sub(1, std::memory_order_acq_rel);
  }
}

void pipe_bump(std::atomic<uint32_t>* bell, std::atomic<uint32_t>* waiters) {
  // seq_cst pair: see bump() — prevents the lost-wakeup reordering on
  // weakly-ordered CPUs
  bell->fetch_add(1, std::memory_order_seq_cst);
  if (waiters->load(std::memory_order_seq_cst) > 0) futex_wake_all(bell);
}

}  // namespace

bool pipe_write(Pipe* p, const void* data, size_t n,
                const std::atomic<bool>& shutdown) {
  const uint8_t* src = static_cast<const uint8_t*>(data);
  PipeHdr* h = p->h;
  size_t cap = p->cap;
  while (n > 0) {
    uint64_t head = h->head.load(std::memory_order_relaxed);
    uint64_t tail = h->tail.load(std::memory_order_acquire);
    size_t free = cap - static_cast<size_t>(head - tail);
    if (free == 0) {
      if (!pipe_wait(&h->cons_bell, &h->prod_waiters, shutdown, [&] {
            return cap - static_cast<size_t>(
                             h->head.load(std::memory_order_relaxed) -
                             h->tail.load(std::memory_order_acquire)) > 0;
          }))
        return false;
      continue;
    }
    size_t chunk = n < free ? n : free;
    size_t off = static_cast<size_t>(head % cap);
    size_t first = chunk < cap - off ? chunk : cap - off;
    std::memcpy(p->buf + off, src, first);
    if (chunk > first) std::memcpy(p->buf, src + first, chunk - first);
    h->head.store(head + chunk, std::memory_order_release);
    pipe_bump(&h->prod_bell, &h->cons_waiters);
    src += chunk;
    n -= chunk;
  }
  return true;
}

bool pipe_read(Pipe* p, void* data, size_t n,
               const std::atomic<bool>& shutdown) {
  uint8_t* dst = static_cast<uint8_t*>(data);
  PipeHdr* h = p->h;
  size_t cap = p->cap;
  while (n > 0) {
    uint64_t tail = h->tail.load(std::memory_order_relaxed);
    uint64_t head = h->head.load(std::memory_order_acquire);
    size_t avail = static_cast<size_t>(head - tail);
    if (avail == 0) {
      if (!pipe_wait(&h->prod_bell, &h->cons_waiters, shutdown, [&] {
            return h->head.load(std::memory_order_acquire) !=
                   h->tail.load(std::memory_order_relaxed);
          }))
        return false;
      continue;
    }
    size_t chunk = n < avail ? n : avail;
    size_t off = static_cast<size_t>(tail % cap);
    size_t first = chunk < cap - off ? chunk : cap - off;
    std::memcpy(dst, p->buf + off, first);
    if (chunk > first) std::memcpy(dst + first, p->buf, chunk - first);
    h->tail.store(tail + chunk, std::memory_order_release);
    pipe_bump(&h->cons_bell, &h->prod_waiters);
    dst += chunk;
    n -= chunk;
  }
  return true;
}

void pipe_wake(Pipe* p) {
  if (!p) return;
  // bump BEFORE waking: a waiter that just validated the old bell
  // value must fail the kernel's futex value check instead of sleeping
  // through the wake (it would only recover via the 2s timeout)
  p->h->prod_bell.fetch_add(1, std::memory_order_release);
  p->h->cons_bell.fetch_add(1, std::memory_order_release);
  futex_wake_all(&p->h->prod_bell);
  futex_wake_all(&p->h->cons_bell);
}

void pipes_unlink(PipeSeg* seg) {
  if (seg && !seg->name.empty()) {
    ::shm_unlink(seg->name.c_str());
    seg->name.clear();
  }
}

void pipes_destroy(PipeSeg* seg) {
  if (!seg) return;
  pipes_unlink(seg);
  ::munmap(seg->base, seg->total);
  delete seg;
}

void pipe_close(Pipe* p) {
  if (!p) return;
  if (p->owned_base) ::munmap(p->owned_base, p->owned_total);
  delete p;
}

}  // namespace shm
}  // namespace t4j
