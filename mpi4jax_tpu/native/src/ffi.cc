// XLA FFI entry points for the DCN bridge.
//
// The native replacement for the reference's CPU custom-call targets
// (mpi4jax/_src/xla_bridge/mpi_xla_bridge_cpu.pyx:20-189): one typed-FFI
// handler per op, registered from Python via jax.ffi.register_ffi_target.
// Where the reference decodes positional scalar operands, handlers here
// take static FFI attributes (comm handle, op code, root, tags) plus the
// data buffer and a f32[] ordering stamp that threads the token chain
// through the compiled program.
//
// Failure propagation: bridge calls throw t4j::BridgeError with
// rank/peer/op context.  FFI handlers translate that into a non-OK
// ffi::Error (surfacing in Python as XlaRuntimeError with the message
// intact); the plain-C control API returns a nonzero status and parks
// the message in a thread-local retrieved via t4j_last_error().  The
// process is never aborted from here — the reference's MPI_Abort
// fail-fast is replaced by the abort broadcast (dcn.cc) plus the
// launcher's job-level fail-fast.
//
// Also exports the plain-C control API consumed through ctypes
// (mpi4jax_tpu/native/runtime.py).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "dcn.h"
#include "telemetry.h"
#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

namespace {

// last failure message for the ctypes tier (per thread: the Python
// caller reads it right after the failing call on the same thread)
thread_local std::string g_tls_err;

template <typename F>
ffi::Error guarded(F&& f) {
  try {
    f();
    return ffi::Error::Success();
  } catch (const t4j::BridgeError& e) {
    return ffi::Error(ffi::ErrorCode::kAborted, e.what());
  } catch (const std::exception& e) {
    return ffi::Error(ffi::ErrorCode::kInternal, e.what());
  }
}

template <typename F>
int32_t c_guard(F&& f) {
  try {
    f();
    return 0;
  } catch (const t4j::BridgeError& e) {
    g_tls_err = e.what();
    return 1;
  } catch (const std::exception& e) {
    g_tls_err = e.what();
    return 2;
  }
}

t4j::DType to_dtype(ffi::DataType dt) {
  switch (dt) {
    case ffi::F32:
      return t4j::DType::kF32;
    case ffi::F64:
      return t4j::DType::kF64;
    case ffi::S8:
      return t4j::DType::kI8;
    case ffi::S16:
      return t4j::DType::kI16;
    case ffi::S32:
      return t4j::DType::kI32;
    case ffi::S64:
      return t4j::DType::kI64;
    case ffi::U8:
      return t4j::DType::kU8;
    case ffi::U16:
      return t4j::DType::kU16;
    case ffi::U32:
      return t4j::DType::kU32;
    case ffi::U64:
      return t4j::DType::kU64;
    case ffi::PRED:
      return t4j::DType::kBool;
    case ffi::C64:
      return t4j::DType::kC64;
    case ffi::C128:
      return t4j::DType::kC128;
    case ffi::F16:
      return t4j::DType::kF16;
    case ffi::BF16:
      return t4j::DType::kBF16;
    default:
      throw t4j::BridgeError("unsupported dtype in FFI call");
  }
}

void touch_stamp(ffi::AnyBuffer& stamp, ffi::Result<ffi::AnyBuffer>& out) {
  if (out->size_bytes() && stamp.size_bytes())
    std::memcpy(out->untyped_data(), stamp.untyped_data(),
                out->size_bytes());
}

// ---- allreduce / reduce / scan -----------------------------------------

ffi::Error AllreduceImpl(ffi::AnyBuffer x, ffi::AnyBuffer stamp,
                         ffi::Result<ffi::AnyBuffer> y,
                         ffi::Result<ffi::AnyBuffer> stamp_out,
                         int32_t comm, int32_t op) {
  return guarded([&] {
    t4j::allreduce(comm, x.untyped_data(), y->untyped_data(),
                   x.element_count(), to_dtype(x.element_type()),
                   static_cast<t4j::ReduceOp>(op));
    touch_stamp(stamp, stamp_out);
  });
}

ffi::Error ReduceImpl(ffi::AnyBuffer x, ffi::AnyBuffer stamp,
                      ffi::Result<ffi::AnyBuffer> y,
                      ffi::Result<ffi::AnyBuffer> stamp_out, int32_t comm,
                      int32_t op, int32_t root) {
  return guarded([&] {
    // non-root outputs mirror the input (the Python wrapper returns the
    // input unchanged off-root, reference reduce.py:66-71)
    std::memcpy(y->untyped_data(), x.untyped_data(), x.size_bytes());
    t4j::reduce(comm, x.untyped_data(), y->untyped_data(),
                x.element_count(), to_dtype(x.element_type()),
                static_cast<t4j::ReduceOp>(op), root);
    touch_stamp(stamp, stamp_out);
  });
}

ffi::Error ReduceScatterImpl(ffi::AnyBuffer x, ffi::AnyBuffer stamp,
                             ffi::Result<ffi::AnyBuffer> y,
                             ffi::Result<ffi::AnyBuffer> stamp_out,
                             int32_t comm, int32_t op) {
  return guarded([&] {
    // y's element count is the per-rank block (x = comm_size blocks)
    t4j::reduce_scatter(comm, x.untyped_data(), y->untyped_data(),
                        y->element_count(), to_dtype(x.element_type()),
                        static_cast<t4j::ReduceOp>(op));
    touch_stamp(stamp, stamp_out);
  });
}

// Explicitly hierarchical allreduce (shm leaf reduce -> leader ring ->
// shm bcast): errors instead of falling back when the topology is
// ineligible.  The auto-selected path lives inside t4j_allreduce.
ffi::Error HierAllreduceImpl(ffi::AnyBuffer x, ffi::AnyBuffer stamp,
                             ffi::Result<ffi::AnyBuffer> y,
                             ffi::Result<ffi::AnyBuffer> stamp_out,
                             int32_t comm, int32_t op) {
  return guarded([&] {
    t4j::hier_allreduce(comm, x.untyped_data(), y->untyped_data(),
                        x.element_count(), to_dtype(x.element_type()),
                        static_cast<t4j::ReduceOp>(op));
    touch_stamp(stamp, stamp_out);
  });
}

ffi::Error ScanImpl(ffi::AnyBuffer x, ffi::AnyBuffer stamp,
                    ffi::Result<ffi::AnyBuffer> y,
                    ffi::Result<ffi::AnyBuffer> stamp_out, int32_t comm,
                    int32_t op) {
  return guarded([&] {
    t4j::scan(comm, x.untyped_data(), y->untyped_data(), x.element_count(),
              to_dtype(x.element_type()), static_cast<t4j::ReduceOp>(op));
    touch_stamp(stamp, stamp_out);
  });
}

// ---- p2p ----------------------------------------------------------------

ffi::Error SendImpl(ffi::AnyBuffer x, ffi::AnyBuffer stamp,
                    ffi::Result<ffi::AnyBuffer> stamp_out, int32_t comm,
                    int32_t dest, int32_t tag) {
  return guarded([&] {
    t4j::send(comm, x.untyped_data(), x.size_bytes(), dest, tag);
    touch_stamp(stamp, stamp_out);
  });
}

ffi::Error RecvImpl(ffi::AnyBuffer stamp, ffi::Result<ffi::AnyBuffer> y,
                    ffi::Result<ffi::AnyBuffer> stamp_out,
                    ffi::Result<ffi::AnyBuffer> status, int32_t comm,
                    int32_t source, int32_t tag) {
  return guarded([&] {
    int src = 0, got_tag = 0;
    t4j::recv(comm, y->untyped_data(), y->size_bytes(), source, tag, &src,
              &got_tag);
    auto* st = static_cast<int32_t*>(status->untyped_data());
    st[0] = src;
    st[1] = got_tag;
    touch_stamp(stamp, stamp_out);
  });
}

ffi::Error SendrecvImpl(ffi::AnyBuffer sendbuf, ffi::AnyBuffer recvbuf,
                        ffi::AnyBuffer stamp, ffi::Result<ffi::AnyBuffer> y,
                        ffi::Result<ffi::AnyBuffer> stamp_out,
                        ffi::Result<ffi::AnyBuffer> status, int32_t comm,
                        int32_t source, int32_t dest, int32_t sendtag,
                        int32_t recvtag) {
  return guarded([&] {
    (void)recvbuf;
    int src = 0, got_tag = 0;
    t4j::sendrecv(comm, sendbuf.untyped_data(), sendbuf.size_bytes(),
                  y->untyped_data(), y->size_bytes(), source, dest, sendtag,
                  recvtag, &src, &got_tag);
    auto* st = static_cast<int32_t*>(status->untyped_data());
    st[0] = src;
    st[1] = got_tag;
    touch_stamp(stamp, stamp_out);
  });
}

// ---- rooted / gather family --------------------------------------------

ffi::Error BarrierImpl(ffi::AnyBuffer stamp,
                       ffi::Result<ffi::AnyBuffer> stamp_out, int32_t comm) {
  return guarded([&] {
    t4j::barrier(comm);
    touch_stamp(stamp, stamp_out);
  });
}

ffi::Error BcastImpl(ffi::AnyBuffer x, ffi::AnyBuffer stamp,
                     ffi::Result<ffi::AnyBuffer> y,
                     ffi::Result<ffi::AnyBuffer> stamp_out, int32_t comm,
                     int32_t root) {
  return guarded([&] {
    std::memcpy(y->untyped_data(), x.untyped_data(), x.size_bytes());
    t4j::bcast(comm, y->untyped_data(), y->size_bytes(), root);
    touch_stamp(stamp, stamp_out);
  });
}

ffi::Error AllgatherImpl(ffi::AnyBuffer x, ffi::AnyBuffer stamp,
                         ffi::Result<ffi::AnyBuffer> y,
                         ffi::Result<ffi::AnyBuffer> stamp_out,
                         int32_t comm) {
  return guarded([&] {
    t4j::allgather(comm, x.untyped_data(), y->untyped_data(),
                   x.size_bytes());
    touch_stamp(stamp, stamp_out);
  });
}

ffi::Error GatherImpl(ffi::AnyBuffer x, ffi::AnyBuffer stamp,
                      ffi::Result<ffi::AnyBuffer> y,
                      ffi::Result<ffi::AnyBuffer> stamp_out, int32_t comm,
                      int32_t root) {
  return guarded([&] {
    t4j::gather(comm, x.untyped_data(), y->untyped_data(), x.size_bytes(),
                root);
    touch_stamp(stamp, stamp_out);
  });
}

ffi::Error ScatterImpl(ffi::AnyBuffer x, ffi::AnyBuffer stamp,
                       ffi::Result<ffi::AnyBuffer> y,
                       ffi::Result<ffi::AnyBuffer> stamp_out, int32_t comm,
                       int32_t root) {
  return guarded([&] {
    t4j::scatter(comm, x.untyped_data(), y->untyped_data(),
                 y->size_bytes(), root);
    touch_stamp(stamp, stamp_out);
  });
}

ffi::Error AlltoallImpl(ffi::AnyBuffer x, ffi::AnyBuffer stamp,
                        ffi::Result<ffi::AnyBuffer> y,
                        ffi::Result<ffi::AnyBuffer> stamp_out, int32_t comm) {
  return guarded([&] {
    int n = t4j::comm_size(comm);
    t4j::alltoall(comm, x.untyped_data(), y->untyped_data(),
                  x.size_bytes() / static_cast<size_t>(n));
    touch_stamp(stamp, stamp_out);
  });
}

// ---- fused multi-part p2p (small-message coalescing) --------------------
//
// Variadic handlers: the operand list is [send_0 .. send_{n_send-1},
// stamp] and the result list [recv_0 .. recv_{n_recv-1}, stamp_out,
// status], decoded through RemainingArgs/RemainingRets so one handler
// serves every part count — a true iovec gather/scatter, no Python-
// side packing copies.  n_send travels as an attribute; n_recv is
// implied by the result arity.

ffi::Error SendrecvFusedImpl(ffi::RemainingArgs args,
                             ffi::RemainingRets rets, int32_t comm,
                             int32_t source, int32_t dest, int32_t sendtag,
                             int32_t recvtag, int32_t n_send) {
  return guarded([&] {
    if (args.size() < 1 || rets.size() < 2 ||
        static_cast<size_t>(n_send) + 1 != args.size())
      throw t4j::BridgeError("fused sendrecv: malformed call arity");
    int n_recv = static_cast<int>(rets.size()) - 2;
    std::vector<const void*> sp(n_send);
    std::vector<size_t> sb(n_send);
    for (int i = 0; i < n_send; ++i) {
      auto b = args.get<ffi::AnyBuffer>(i);
      if (!b.has_value())
        throw t4j::BridgeError("fused sendrecv: bad send operand");
      sp[i] = b->untyped_data();
      sb[i] = b->size_bytes();
    }
    std::vector<void*> rp(n_recv);
    std::vector<size_t> rb(n_recv);
    for (int i = 0; i < n_recv; ++i) {
      auto r = rets.get<ffi::AnyBuffer>(i);
      if (!r.has_value())
        throw t4j::BridgeError("fused sendrecv: bad recv result");
      rp[i] = (*r)->untyped_data();
      rb[i] = (*r)->size_bytes();
    }
    int src = -1, tag = -1;
    t4j::sendrecv_fused(comm, sp.data(), sb.data(), n_send, rp.data(),
                        rb.data(), n_recv, source, dest, sendtag, recvtag,
                        &src, &tag);
    auto status = rets.get<ffi::AnyBuffer>(rets.size() - 1);
    if (status.has_value()) {
      auto* st = static_cast<int32_t*>((*status)->untyped_data());
      st[0] = src;
      st[1] = tag;
    }
    auto stamp = args.get<ffi::AnyBuffer>(args.size() - 1);
    auto stamp_out = rets.get<ffi::AnyBuffer>(rets.size() - 2);
    if (stamp.has_value() && stamp_out.has_value() &&
        (*stamp_out)->size_bytes() && stamp->size_bytes())
      std::memcpy((*stamp_out)->untyped_data(), stamp->untyped_data(),
                  (*stamp_out)->size_bytes());
  });
}

// Operands [part_0 .. part_{np-1}, stamp], results [out_0 ..
// out_{np-1}, stamp_out]; part count implied by the arity.
ffi::Error AlltoallFusedImpl(ffi::RemainingArgs args,
                             ffi::RemainingRets rets, int32_t comm) {
  return guarded([&] {
    // operands [part_0.., stamp] and results [out_0.., stamp_out]
    // have the SAME arity: one buffer per part plus the stamp
    if (args.size() < 2 || rets.size() != args.size())
      throw t4j::BridgeError("fused alltoall: malformed call arity");
    int np = static_cast<int>(rets.size()) - 1;
    int n = t4j::comm_size(comm);
    std::vector<const void*> parts(np);
    std::vector<void*> outs(np);
    std::vector<size_t> each(np);
    for (int i = 0; i < np; ++i) {
      auto b = args.get<ffi::AnyBuffer>(i);
      auto r = rets.get<ffi::AnyBuffer>(i);
      if (!b.has_value() || !r.has_value())
        throw t4j::BridgeError("fused alltoall: bad part buffer");
      parts[i] = b->untyped_data();
      outs[i] = (*r)->untyped_data();
      each[i] = b->size_bytes() / static_cast<size_t>(n);
    }
    t4j::alltoall_fused(comm, parts.data(), outs.data(), each.data(), np);
    auto stamp = args.get<ffi::AnyBuffer>(args.size() - 1);
    auto stamp_out = rets.get<ffi::AnyBuffer>(rets.size() - 1);
    if (stamp.has_value() && stamp_out.has_value() &&
        (*stamp_out)->size_bytes() && stamp->size_bytes())
      std::memcpy((*stamp_out)->untyped_data(), stamp->untyped_data(),
                  (*stamp_out)->size_bytes());
  });
}

// ---- async submit / wait (docs/async.md) --------------------------------
//
// The in-jit fast path for ops/async_.py: a submit handler hands the
// operand to the progress engine's owned-buffer API (custom-call
// operands are reused the moment the handler returns) and writes the
// request id into a u64 scalar output that ``wait``/``test`` consume
// as an ordinary data dependency — the host-callback detour and its
// per-call staging cost never enter the compiled program.

void put_req(ffi::Result<ffi::AnyBuffer>& req, uint64_t rid) {
  *static_cast<uint64_t*>(req->untyped_data()) = rid;
}

uint64_t get_req(const ffi::AnyBuffer& req) {
  return *static_cast<const uint64_t*>(req.untyped_data());
}

ffi::Error IallreduceSubmitImpl(ffi::AnyBuffer x, ffi::AnyBuffer stamp,
                                ffi::Result<ffi::AnyBuffer> req,
                                ffi::Result<ffi::AnyBuffer> stamp_out,
                                int32_t comm, int32_t op) {
  return guarded([&] {
    put_req(req, t4j::iallreduce_owned(comm, x.untyped_data(),
                                       x.element_count(),
                                       to_dtype(x.element_type()),
                                       static_cast<t4j::ReduceOp>(op)));
    touch_stamp(stamp, stamp_out);
  });
}

ffi::Error IreduceScatterSubmitImpl(ffi::AnyBuffer x, ffi::AnyBuffer stamp,
                                    ffi::Result<ffi::AnyBuffer> req,
                                    ffi::Result<ffi::AnyBuffer> stamp_out,
                                    int32_t comm, int32_t op) {
  return guarded([&] {
    int n = t4j::comm_size(comm);
    put_req(req, t4j::ireduce_scatter_owned(
                     comm, x.untyped_data(),
                     x.element_count() / static_cast<size_t>(n),
                     to_dtype(x.element_type()),
                     static_cast<t4j::ReduceOp>(op)));
    touch_stamp(stamp, stamp_out);
  });
}

ffi::Error IsendSubmitImpl(ffi::AnyBuffer x, ffi::AnyBuffer stamp,
                           ffi::Result<ffi::AnyBuffer> req,
                           ffi::Result<ffi::AnyBuffer> stamp_out,
                           int32_t comm, int32_t dest, int32_t tag) {
  return guarded([&] {
    put_req(req, t4j::isend_owned(comm, x.untyped_data(), x.size_bytes(),
                                  dest, tag));
    touch_stamp(stamp, stamp_out);
  });
}

ffi::Error IrecvSubmitImpl(ffi::AnyBuffer stamp,
                           ffi::Result<ffi::AnyBuffer> req,
                           ffi::Result<ffi::AnyBuffer> stamp_out,
                           int32_t comm, int32_t source, int32_t tag,
                           int64_t nbytes) {
  return guarded([&] {
    put_req(req, t4j::irecv_owned(comm, static_cast<size_t>(nbytes),
                                  source, tag));
    touch_stamp(stamp, stamp_out);
  });
}

// y is the result payload (0-sized for isend); status carries the
// matched (source, tag) envelope for irecv, (-1, -1) otherwise.
ffi::Error AsyncWaitImpl(ffi::AnyBuffer req, ffi::AnyBuffer stamp,
                         ffi::Result<ffi::AnyBuffer> y,
                         ffi::Result<ffi::AnyBuffer> stamp_out,
                         ffi::Result<ffi::AnyBuffer> status) {
  return guarded([&] {
    int src = -1, tag = -1;
    t4j::wait_into(get_req(req), y->untyped_data(), y->size_bytes(),
                   &src, &tag);
    auto* st = static_cast<int32_t*>(status->untyped_data());
    st[0] = src;
    st[1] = tag;
    touch_stamp(stamp, stamp_out);
  });
}

ffi::Error AsyncTestImpl(ffi::AnyBuffer req, ffi::AnyBuffer stamp,
                         ffi::Result<ffi::AnyBuffer> done,
                         ffi::Result<ffi::AnyBuffer> stamp_out) {
  return guarded([&] {
    bool d = t4j::test(get_req(req), nullptr, nullptr);
    *static_cast<int8_t*>(done->untyped_data()) = d ? 1 : 0;
    touch_stamp(stamp, stamp_out);
  });
}

}  // namespace

// ---- handler symbol definitions ----------------------------------------

#define T4J_BUF ffi::Ffi::Bind().Arg<ffi::AnyBuffer>()

XLA_FFI_DEFINE_HANDLER_SYMBOL(t4j_allreduce, AllreduceImpl,
                              T4J_BUF.Arg<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>()
                                  .Attr<int32_t>("comm")
                                  .Attr<int32_t>("op"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(t4j_reduce, ReduceImpl,
                              T4J_BUF.Arg<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>()
                                  .Attr<int32_t>("comm")
                                  .Attr<int32_t>("op")
                                  .Attr<int32_t>("root"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(t4j_reduce_scatter, ReduceScatterImpl,
                              T4J_BUF.Arg<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>()
                                  .Attr<int32_t>("comm")
                                  .Attr<int32_t>("op"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(t4j_hier_allreduce, HierAllreduceImpl,
                              T4J_BUF.Arg<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>()
                                  .Attr<int32_t>("comm")
                                  .Attr<int32_t>("op"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(t4j_scan, ScanImpl,
                              T4J_BUF.Arg<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>()
                                  .Attr<int32_t>("comm")
                                  .Attr<int32_t>("op"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(t4j_send, SendImpl,
                              T4J_BUF.Arg<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>()
                                  .Attr<int32_t>("comm")
                                  .Attr<int32_t>("dest")
                                  .Attr<int32_t>("tag"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(t4j_recv, RecvImpl,
                              T4J_BUF.Ret<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>()
                                  .Attr<int32_t>("comm")
                                  .Attr<int32_t>("source")
                                  .Attr<int32_t>("tag"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(t4j_sendrecv, SendrecvImpl,
                              T4J_BUF.Arg<ffi::AnyBuffer>()
                                  .Arg<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>()
                                  .Attr<int32_t>("comm")
                                  .Attr<int32_t>("source")
                                  .Attr<int32_t>("dest")
                                  .Attr<int32_t>("sendtag")
                                  .Attr<int32_t>("recvtag"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(t4j_barrier, BarrierImpl,
                              T4J_BUF.Ret<ffi::AnyBuffer>().Attr<int32_t>(
                                  "comm"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(t4j_bcast, BcastImpl,
                              T4J_BUF.Arg<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>()
                                  .Attr<int32_t>("comm")
                                  .Attr<int32_t>("root"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(t4j_allgather, AllgatherImpl,
                              T4J_BUF.Arg<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>()
                                  .Attr<int32_t>("comm"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(t4j_gather, GatherImpl,
                              T4J_BUF.Arg<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>()
                                  .Attr<int32_t>("comm")
                                  .Attr<int32_t>("root"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(t4j_scatter, ScatterImpl,
                              T4J_BUF.Arg<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>()
                                  .Attr<int32_t>("comm")
                                  .Attr<int32_t>("root"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(t4j_alltoall, AlltoallImpl,
                              T4J_BUF.Arg<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>()
                                  .Attr<int32_t>("comm"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(t4j_sendrecv_fused, SendrecvFusedImpl,
                              ffi::Ffi::Bind()
                                  .RemainingArgs()
                                  .RemainingRets()
                                  .Attr<int32_t>("comm")
                                  .Attr<int32_t>("source")
                                  .Attr<int32_t>("dest")
                                  .Attr<int32_t>("sendtag")
                                  .Attr<int32_t>("recvtag")
                                  .Attr<int32_t>("n_send"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(t4j_alltoall_fused, AlltoallFusedImpl,
                              ffi::Ffi::Bind()
                                  .RemainingArgs()
                                  .RemainingRets()
                                  .Attr<int32_t>("comm"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(t4j_iallreduce_submit, IallreduceSubmitImpl,
                              T4J_BUF.Arg<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>()
                                  .Attr<int32_t>("comm")
                                  .Attr<int32_t>("op"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(t4j_ireduce_scatter_submit,
                              IreduceScatterSubmitImpl,
                              T4J_BUF.Arg<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>()
                                  .Attr<int32_t>("comm")
                                  .Attr<int32_t>("op"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(t4j_isend_submit, IsendSubmitImpl,
                              T4J_BUF.Arg<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>()
                                  .Attr<int32_t>("comm")
                                  .Attr<int32_t>("dest")
                                  .Attr<int32_t>("tag"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(t4j_irecv_submit, IrecvSubmitImpl,
                              T4J_BUF.Ret<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>()
                                  .Attr<int32_t>("comm")
                                  .Attr<int32_t>("source")
                                  .Attr<int32_t>("tag")
                                  .Attr<int64_t>("nbytes"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(t4j_async_wait, AsyncWaitImpl,
                              T4J_BUF.Arg<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>());

XLA_FFI_DEFINE_HANDLER_SYMBOL(t4j_async_test, AsyncTestImpl,
                              T4J_BUF.Arg<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>());

// ---- plain-C control API (ctypes) --------------------------------------
//
// Data-plane entry points return 0 on success; nonzero means the call
// failed and t4j_last_error() (same thread) holds the contextual
// message.  Python raises it as BridgeError (native/runtime.py).

extern "C" {

int t4j_init() {
  try {
    return t4j::init_from_env();  // 0 ok, 1 not a multi-process job
  } catch (const std::exception& e) {
    g_tls_err = e.what();
    return 2;  // bootstrap failed; message via t4j_last_error()
  }
}
void t4j_finalize() { t4j::finalize(); }
int t4j_initialized() { return t4j::initialized() ? 1 : 0; }
int t4j_world_rank() { return t4j::world_rank(); }
int t4j_world_size() { return t4j::world_size(); }
void t4j_set_logging(int enabled) { t4j::set_logging(enabled != 0); }
const char* t4j_last_error() { return g_tls_err.c_str(); }

// fault surface: 0 = healthy, 1 = a bridge failure was posted (every
// further call on any thread fails fast with t4j_fault_msg())
int t4j_health() { return t4j::faulted() ? 1 : 0; }
const char* t4j_fault_msg() {
  thread_local std::string msg;
  msg = t4j::fault_message();
  return msg.c_str();
}
void t4j_set_timeouts(double op_s, double connect_s) {
  t4j::set_timeouts(op_s, connect_s);
}
void t4j_set_tuning(int64_t ring_min_bytes, int64_t seg_bytes) {
  t4j::set_tuning(ring_min_bytes, seg_bytes);
}
void t4j_set_hier(int32_t mode, int64_t min_bytes) {
  t4j::set_hier(mode, min_bytes);
}
// Small-message coalescing threshold (docs/performance.md
// "small-message coalescing"): bytes < 0 keeps, 0 disables fusion,
// > 0 sets.  Must be uniform across ranks like the other data-plane
// knobs.
void t4j_set_coalesce(int64_t bytes) { t4j::set_coalesce(bytes); }
int64_t t4j_coalesce_bytes() { return t4j::coalesce_threshold(); }
// Self-healing transport knobs (docs/failure-semantics.md
// "self-healing transport"); must be set before t4j_init and
// uniformly across ranks.  retry_max < 0 keeps, 0 disables; backoffs
// <= 0 keep; replay_bytes < 0 keeps.
void t4j_set_resilience(int32_t retry_max, double backoff_base_s,
                        double backoff_max_s, int64_t replay_bytes) {
  t4j::set_resilience(retry_max, backoff_base_s, backoff_max_s,
                      replay_bytes);
}
// Wire-path knobs (docs/performance.md "striped links and the
// zero-copy path"): stripes >= 1 sets the dealing width (pre-init it
// also fixes the connections bootstrap builds per link), <= 0 keeps;
// zc_min < 0 keeps, 0 disables MSG_ZEROCOPY, > 0 sets the opt-in
// floor; batch >= 1 sets the frames-per-sendmsg gather cap; emu_flow
// < 0 keeps, 0 disables the per-connection test throttle, > 0 sets it
// (bytes/second).  Must be uniform across ranks; utils/config.py owns
// validation.
void t4j_set_wire(int32_t stripes, int64_t zc_min, int32_t batch,
                  int64_t emu_flow_bps) {
  t4j::set_wire(stripes, zc_min, batch, emu_flow_bps);
}
// Effective wire-path state: built/active stripe width, zerocopy
// floor + whether the kernel honours it, sendmsg batch, throttle,
// and the zerocopy completion diagnostics (completions reaped /
// kernel-copied-anyway — loopback reports copied~completions).
// Returns 1 always (pre-init it reports the requested values).
int32_t t4j_wire_info(int32_t* stripes_built, int32_t* stripes_active,
                      int64_t* zc_min, int32_t* batch,
                      int64_t* emu_flow_bps, int32_t* zerocopy,
                      uint64_t* zc_completions, uint64_t* zc_copied) {
  t4j::WireInfo w;
  t4j::wire_info(&w);
  if (stripes_built) *stripes_built = w.stripes_built;
  if (stripes_active) *stripes_active = w.stripes_active;
  if (zc_min) *zc_min = w.zc_min_bytes;
  if (batch) *batch = w.sendmsg_batch;
  if (emu_flow_bps) *emu_flow_bps = w.emu_flow_bps;
  if (zerocopy) *zerocopy = w.zerocopy ? 1 : 0;
  if (zc_completions) *zc_completions = w.zc_completions;
  if (zc_copied) *zc_copied = w.zc_copied;
  return 1;
}
// Compressed-collective wire dtype (docs/performance.md "Compressed
// collectives"): mode 0 off, 1 bf16, 2 fp8(e4m3); < 0 keeps.
// Runtime-changeable (the calibrator A/Bs it); must be uniform across
// ranks.  utils/config.py owns env validation.
void t4j_set_wire_dtype(int32_t mode) { t4j::set_wire_dtype(mode); }
// Wire backend (docs/performance.md "io_uring wire backend"): mode
// 0 sendmsg, 1 io_uring, 2 auto (< 0 keeps, > 2 clamps to auto).
// Runtime-changeable between collectives (the calibrator A/Bs it);
// must be uniform across ranks.  utils/config.py owns env validation.
void t4j_set_wire_backend(int32_t mode) { t4j::set_wire_backend(mode); }
// Requested mode, whether the kernel's io_uring probe succeeded, and
// whether the uring data plane is actually in effect (mode == uring
// AND supported).  Valid pre-init so ensure_initialized can reject an
// explicit uring request on a kernel without io_uring before sockets
// exist.  Returns 1 always.
int32_t t4j_wire_backend_info(int32_t* mode, int32_t* supported,
                              int32_t* active) {
  int m = 0, s = 0, a = 0;
  t4j::wire_backend_info(&m, &s, &a);
  if (mode) *mode = m;
  if (supported) *supported = s;
  if (active) *active = a;
  return 1;
}
// Effective wire dtype plus the cumulative logical (f32) vs wire
// (compressed) byte counters over the compressed send path — the
// provable byte saving.  Returns 1 always (pre-init it reports the
// requested mode and zero counters).
int32_t t4j_wire_dtype_info(int32_t* mode, uint64_t* logical_bytes,
                            uint64_t* wire_bytes) {
  int m = 0;
  unsigned long long lb = 0, wb = 0;
  t4j::wire_dtype_info(&m, &lb, &wb);
  if (mode) *mode = m;
  if (logical_bytes) *logical_bytes = lb;
  if (wire_bytes) *wire_bytes = wb;
  return 1;
}
// Elastic membership knobs (docs/failure-semantics.md "elastic
// membership"): mode 0 off, 1 shrink, 2 rejoin (other values keep);
// min_world >= 1 sets; resize_timeout_s > 0 sets.  Must be set before
// t4j_init and uniformly across ranks; utils/config.py owns
// validation (including rejecting elastic with T4J_RETRY_MAX=0).
void t4j_set_elastic(int32_t mode, int32_t min_world,
                     double resize_timeout_s) {
  t4j::set_elastic(mode, min_world, resize_timeout_s);
}
// Live membership: world epoch (0 = bootstrap), current member count,
// alive bitmask (bit r = world rank r is a member), whether a resize
// is in progress, and the stale-epoch frame drop counter (diagnostic).
// Returns 1 when filled, 0 before init.
int32_t t4j_world_info(uint32_t* epoch, int32_t* alive_count,
                       uint64_t* alive_mask, int32_t* resizing,
                       uint64_t* stale_frames) {
  t4j::WorldInfo w;
  if (!t4j::world_info(&w)) return 0;
  if (epoch) *epoch = w.epoch;
  if (alive_count) *alive_count = w.alive_count;
  if (alive_mask) *alive_mask = w.alive_mask;
  if (resizing) *resizing = w.resizing ? 1 : 0;
  if (stale_frames) *stale_frames = w.stale_frames;
  return 1;
}
// Block until no resize is in progress (bounded by timeout_s; <= 0 =
// one nonblocking check).  Returns 1 when settled, 0 on timeout.
int32_t t4j_resize_wait(double timeout_s) {
  return t4j::resize_wait(timeout_s) ? 1 : 0;
}
// Per-peer reconnect/replay/syscall counters.  peer >= 0 selects one
// link; peer < 0 aggregates every link (state = worst: 0 up, 1 broken,
// 2 dead).  tx/rx_syscalls count kernel crossings made by the wire
// threads (sendmsg/recv/poll or io_uring_enter) — the syscalls-per-
// frame metric reads these, never a hand-derived estimate.  Returns 1
// when the outputs were filled, 0 before init or for an invalid peer.
int32_t t4j_link_stats(int32_t peer, uint64_t* reconnects,
                       uint64_t* replayed_frames,
                       uint64_t* replayed_bytes, uint64_t* tx_syscalls,
                       uint64_t* rx_syscalls, int32_t* state) {
  t4j::LinkStats s;
  if (!t4j::link_stats(peer, &s)) return 0;
  if (reconnects) *reconnects = s.reconnects;
  if (replayed_frames) *replayed_frames = s.replayed_frames;
  if (replayed_bytes) *replayed_bytes = s.replayed_bytes;
  if (tx_syscalls) *tx_syscalls = s.tx_syscalls;
  if (rx_syscalls) *rx_syscalls = s.rx_syscalls;
  if (state) *state = s.state;
  return 1;
}
// One stripe's reconnect/replay/syscall counters + state (0 up,
// 1 broken, 2 dead).  Returns 1 when filled, 0 before init or for an
// invalid peer/stripe index (docs/performance.md "striped links").
int32_t t4j_link_stripe_stats(int32_t peer, int32_t stripe,
                              uint64_t* reconnects,
                              uint64_t* replayed_frames,
                              uint64_t* replayed_bytes,
                              uint64_t* tx_syscalls,
                              uint64_t* rx_syscalls, int32_t* state) {
  t4j::LinkStats s;
  if (!t4j::link_stripe_stats(peer, stripe, &s)) return 0;
  if (reconnects) *reconnects = s.reconnects;
  if (replayed_frames) *replayed_frames = s.replayed_frames;
  if (replayed_bytes) *replayed_bytes = s.replayed_bytes;
  if (tx_syscalls) *tx_syscalls = s.tx_syscalls;
  if (rx_syscalls) *rx_syscalls = s.rx_syscalls;
  if (state) *state = s.state;
  return 1;
}
// Bootstrap topology (host_id, local_rank, local_size, leader_rank,
// n_hosts); returns 0 and leaves the outputs untouched before init.
int32_t t4j_topo(int32_t* host_id, int32_t* local_rank,
                 int32_t* local_size, int32_t* leader_rank,
                 int32_t* n_hosts) {
  t4j::TopoInfo t;
  if (!t4j::topology(&t)) return 0;
  if (host_id) *host_id = t.host_id;
  if (local_rank) *local_rank = t.local_rank;
  if (local_size) *local_size = t.local_size;
  if (leader_rank) *leader_rank = t.leader_rank;
  if (n_hosts) *n_hosts = t.n_hosts;
  return 1;
}
// Pure selection query (never communicates): would a collective of
// total_bytes on this comm take the hierarchical path right now?
int32_t t4j_hier_would_select(int32_t comm, uint64_t total_bytes) {
  try {
    return t4j::hier_would_select(comm, total_bytes) ? 1 : 0;
  } catch (const std::exception& e) {
    g_tls_err = e.what();
    return -1;
  }
}
int32_t t4j_hier_active(int32_t comm) {
  try {
    return t4j::hier_active(comm) ? 1 : 0;
  } catch (const std::exception& e) {
    g_tls_err = e.what();
    return -1;
  }
}
void t4j_abort_notify(const char* why) { t4j::abort_notify(why); }

// ---- telemetry control surface (docs/observability.md) ------------------
//
// mode: 0 off, 1 counters, 2 trace (< 0 keeps); ring_bytes sizes the
// per-rank event ring (< 0 keeps; clamped to a small floor).  Must be
// set before the first instrumented call — the ring is sized on first
// use and never re-sized.  utils/config.py owns validation
// (T4J_TELEMETRY / T4J_TELEMETRY_BYTES); the env parse in telemetry.h
// is the fallback for hand-run processes.
void t4j_set_telemetry(int32_t mode, int64_t ring_bytes) {
  t4j::tel::set(mode, ring_bytes);
}
int32_t t4j_telemetry_mode() { return t4j::tel::mode(); }
// Consume up to max_bytes/32 ring events (oldest first) into `out` as
// packed 32-byte records (telemetry/schema.py mirrors the layout);
// returns bytes written.  Call repeatedly until 0.
int64_t t4j_telemetry_drain(void* out, int64_t max_bytes) {
  if (!out || max_bytes < 0) return 0;
  return static_cast<int64_t>(
      t4j::tel::drain(out, static_cast<size_t>(max_bytes)));
}
// Copy the NEWEST events without consuming (the check_health
// post-mortem peek); same record format, returns bytes written.
int64_t t4j_telemetry_peek_last(void* out, int64_t max_bytes) {
  if (!out || max_bytes < 0) return 0;
  return static_cast<int64_t>(
      t4j::tel::peek_last(out, static_cast<size_t>(max_bytes)));
}
uint64_t t4j_telemetry_dropped() { return t4j::tel::dropped(); }
// Clock anchor: one (monotonic, realtime) pair captured right after
// the bootstrap join barrier (or lazily now for single-process jobs).
// Returns 1 when a bootstrap anchor existed, 0 when it was captured
// lazily by this call.
int32_t t4j_telemetry_anchor(uint64_t* mono_ns, uint64_t* unix_ns) {
  return t4j::tel::anchor(mono_ns, unix_ns) ? 1 : 0;
}
// Metrics-table snapshot as u64 words (header + nonzero rows; layout
// in telemetry.h / telemetry/schema.py).  out == null returns the
// word count required.
int64_t t4j_metrics_snapshot(uint64_t* out, int64_t max_words) {
  return static_cast<int64_t>(t4j::tel::metrics_snapshot(
      out, max_words < 0 ? 0 : static_cast<size_t>(max_words)));
}
// Step marker (ops.step.annotate_step / step_scope): emit a step-
// boundary event — phase 1 begin, 2 end — with the caller-assigned
// step index.  No-op below counters mode; never fails.
void t4j_annotate_step(int64_t index, int32_t phase) {
  t4j::tel::step_event(
      phase == 2 ? t4j::tel::kEnd : t4j::tel::kBegin,
      index < 0 ? 0 : static_cast<uint64_t>(index));
}
// Flight recorder (docs/observability.md "flight recorder"): on < 0
// keeps, dir null/empty keeps.  Must run before t4j_init — the mmap'd
// arena is created once during init, while still single-threaded.
// utils/config.py owns validation (T4J_FLIGHT / T4J_FLIGHT_DIR); the
// env parse in telemetry.h is the fallback for hand-run processes.
void t4j_set_flight(int32_t on, const char* dir) {
  t4j::tel::set_flight(on, dir);
}
// Live status of this rank's flight recorder: returns 1 and fills the
// out-params when active, 0 when off/unmapped.  heartbeat_ns is the
// recorder's CLOCK_MONOTONIC heartbeat (compare against the anchor to
// translate to wall time).
int32_t t4j_flight_info(char* path_out, int32_t path_cap,
                        uint64_t* file_bytes, uint64_t* heartbeat_ns,
                        uint64_t* heartbeat_count, uint64_t* epoch) {
  std::string path;
  uint64_t fb = 0, hb = 0, hc = 0, ep = 0;
  if (!t4j::tel::flight_info(&path, &fb, &hb, &hc, &ep)) return 0;
  if (path_out && path_cap > 0) {
    std::snprintf(path_out, static_cast<size_t>(path_cap), "%s",
                  path.c_str());
  }
  if (file_bytes) *file_bytes = fb;
  if (heartbeat_ns) *heartbeat_ns = hb;
  if (heartbeat_count) *heartbeat_count = hc;
  if (epoch) *epoch = ep;
  return 1;
}

// ---- async progress engine (docs/async.md) ------------------------------
//
// Nonblocking submits return a request id (> 0) or 0 on failure (the
// message is in t4j_last_error on this thread).  Buffers must stay
// valid until the request completes; every request must be consumed
// by wait/waitall (or test returning done) exactly once — leaks are
// reported at finalize.

uint64_t t4j_iallreduce(int32_t comm, const void* in, void* out,
                        uint64_t count, int32_t dt, int32_t op) {
  try {
    return t4j::iallreduce(comm, in, out, count,
                           static_cast<t4j::DType>(dt),
                           static_cast<t4j::ReduceOp>(op));
  } catch (const std::exception& e) {
    g_tls_err = e.what();
    return 0;
  }
}
uint64_t t4j_ireduce_scatter(int32_t comm, const void* in, void* out,
                             uint64_t count_each, int32_t dt, int32_t op) {
  try {
    return t4j::ireduce_scatter(comm, in, out, count_each,
                                static_cast<t4j::DType>(dt),
                                static_cast<t4j::ReduceOp>(op));
  } catch (const std::exception& e) {
    g_tls_err = e.what();
    return 0;
  }
}
uint64_t t4j_isend(int32_t comm, const void* buf, uint64_t nbytes,
                   int32_t dest, int32_t tag) {
  try {
    return t4j::isend(comm, buf, nbytes, dest, tag);
  } catch (const std::exception& e) {
    g_tls_err = e.what();
    return 0;
  }
}
uint64_t t4j_irecv(int32_t comm, void* buf, uint64_t nbytes,
                   int32_t source, int32_t tag) {
  try {
    return t4j::irecv(comm, buf, nbytes, source, tag);
  } catch (const std::exception& e) {
    g_tls_err = e.what();
    return 0;
  }
}
// Blocks until the request completes and consumes it; src_out/tag_out
// carry the matched envelope for irecv (null ok).
int32_t t4j_wait(uint64_t req, int32_t* src_out, int32_t* tag_out) {
  return c_guard([&] {
    int s = -1, t = -1;
    t4j::wait(req, &s, &t);
    if (src_out) *src_out = s;
    if (tag_out) *tag_out = t;
  });
}
// Nonblocking probe: *done = 1 when complete (request NOT consumed —
// a later wait reaps it); a failed op returns nonzero and consumes.
int32_t t4j_test(uint64_t req, int32_t* done, int32_t* src_out,
                 int32_t* tag_out) {
  return c_guard([&] {
    int s = -1, t = -1;
    bool d = t4j::test(req, &s, &t);
    if (done) *done = d ? 1 : 0;
    if (d) {
      if (src_out) *src_out = s;
      if (tag_out) *tag_out = t;
    }
  });
}
int32_t t4j_waitall(const uint64_t* reqs, int32_t n) {
  return c_guard([&] { t4j::waitall(reqs, n); });
}
// In-flight-depth gauge (submitted, not yet complete) and the
// never-consumed request count (the finalize leak check's input).
int32_t t4j_async_inflight() { return t4j::async_inflight(); }
int32_t t4j_async_pending() { return t4j::async_pending(); }

int t4j_comm_create(const int32_t* ranks, int32_t n, int32_t ctx) {
  try {
    return t4j::comm_create(reinterpret_cast<const int*>(ranks),
                            static_cast<int>(n), static_cast<int>(ctx));
  } catch (const std::exception& e) {
    g_tls_err = e.what();
    return -1;
  }
}
int t4j_comm_rank(int32_t comm) {
  try {
    return t4j::comm_rank(comm);
  } catch (const std::exception& e) {
    g_tls_err = e.what();
    return -1;
  }
}
int t4j_comm_size(int32_t comm) {
  try {
    return t4j::comm_size(comm);
  } catch (const std::exception& e) {
    g_tls_err = e.what();
    return -1;
  }
}
void t4j_abort(int32_t code) { t4j::abort_job(code, "user abort"); }

// ctypes data plane: used by the host-callback tier (TPU jits stage
// HBM->host via jax io_callback, then these run the wire ops — the
// analog of the reference's GPU COPY_TO_HOST staging path,
// mpi_xla_bridge_gpu.pyx:211-251).

int32_t t4j_c_send(int32_t comm, const void* buf, uint64_t nbytes,
                   int32_t dest, int32_t tag) {
  return c_guard([&] { t4j::send(comm, buf, nbytes, dest, tag); });
}
int32_t t4j_c_recv(int32_t comm, void* buf, uint64_t nbytes, int32_t source,
                   int32_t tag, int32_t* src_out, int32_t* tag_out) {
  return c_guard([&] {
    int s = 0, t = 0;
    t4j::recv(comm, buf, nbytes, source, tag, &s, &t);
    if (src_out) *src_out = s;
    if (tag_out) *tag_out = t;
  });
}
int32_t t4j_c_sendrecv(int32_t comm, const void* sendbuf,
                       uint64_t send_nbytes, void* recvbuf,
                       uint64_t recv_nbytes, int32_t source, int32_t dest,
                       int32_t sendtag, int32_t recvtag, int32_t* src_out,
                       int32_t* tag_out) {
  return c_guard([&] {
    int s = 0, t = 0;
    t4j::sendrecv(comm, sendbuf, send_nbytes, recvbuf, recv_nbytes, source,
                  dest, sendtag, recvtag, &s, &t);
    if (src_out) *src_out = s;
    if (tag_out) *tag_out = t;
  });
}
int32_t t4j_c_barrier(int32_t comm) {
  return c_guard([&] { t4j::barrier(comm); });
}
int32_t t4j_c_bcast(int32_t comm, void* buf, uint64_t nbytes, int32_t root) {
  return c_guard([&] { t4j::bcast(comm, buf, nbytes, root); });
}
int32_t t4j_c_allreduce(int32_t comm, const void* in, void* out,
                        uint64_t count, int32_t dt, int32_t op) {
  return c_guard([&] {
    t4j::allreduce(comm, in, out, count, static_cast<t4j::DType>(dt),
                   static_cast<t4j::ReduceOp>(op));
  });
}
int32_t t4j_c_reduce(int32_t comm, const void* in, void* out, uint64_t count,
                     int32_t dt, int32_t op, int32_t root) {
  return c_guard([&] {
    t4j::reduce(comm, in, out, count, static_cast<t4j::DType>(dt),
                static_cast<t4j::ReduceOp>(op), root);
  });
}
int32_t t4j_c_scan(int32_t comm, const void* in, void* out, uint64_t count,
                   int32_t dt, int32_t op) {
  return c_guard([&] {
    t4j::scan(comm, in, out, count, static_cast<t4j::DType>(dt),
              static_cast<t4j::ReduceOp>(op));
  });
}
int32_t t4j_c_hier_allreduce(int32_t comm, const void* in, void* out,
                             uint64_t count, int32_t dt, int32_t op) {
  return c_guard([&] {
    t4j::hier_allreduce(comm, in, out, count, static_cast<t4j::DType>(dt),
                        static_cast<t4j::ReduceOp>(op));
  });
}
int32_t t4j_c_reduce_scatter(int32_t comm, const void* in, void* out,
                             uint64_t count_each, int32_t dt, int32_t op) {
  return c_guard([&] {
    t4j::reduce_scatter(comm, in, out, count_each,
                        static_cast<t4j::DType>(dt),
                        static_cast<t4j::ReduceOp>(op));
  });
}
int32_t t4j_c_allgather(int32_t comm, const void* in, void* out,
                        uint64_t nbytes_each) {
  return c_guard([&] { t4j::allgather(comm, in, out, nbytes_each); });
}
int32_t t4j_c_gather(int32_t comm, const void* in, void* out,
                     uint64_t nbytes_each, int32_t root) {
  return c_guard([&] { t4j::gather(comm, in, out, nbytes_each, root); });
}
int32_t t4j_c_scatter(int32_t comm, const void* in, void* out,
                      uint64_t nbytes_each, int32_t root) {
  return c_guard([&] { t4j::scatter(comm, in, out, nbytes_each, root); });
}
int32_t t4j_c_alltoall(int32_t comm, const void* in, void* out,
                       uint64_t nbytes_each) {
  return c_guard([&] { t4j::alltoall(comm, in, out, nbytes_each); });
}
// Fused multi-part p2p (small-message coalescing): pointer-array
// iovec surface for the staged/host-callback tier and standalone
// harnesses.  Part sizes travel as u64 so ctypes callers never deal
// with platform size_t.
int32_t t4j_c_sendrecv_fused(int32_t comm, void* const* send_parts,
                             const uint64_t* send_nbytes, int32_t n_send,
                             void* const* recv_parts,
                             const uint64_t* recv_nbytes, int32_t n_recv,
                             int32_t source, int32_t dest, int32_t sendtag,
                             int32_t recvtag, int32_t* src_out,
                             int32_t* tag_out) {
  return c_guard([&] {
    std::vector<size_t> sb(send_nbytes, send_nbytes + n_send);
    std::vector<size_t> rb(recv_nbytes, recv_nbytes + n_recv);
    int s = -1, t = -1;
    t4j::sendrecv_fused(comm, const_cast<const void* const*>(send_parts),
                        sb.data(), n_send, recv_parts, rb.data(), n_recv,
                        source, dest, sendtag, recvtag, &s, &t);
    if (src_out) *src_out = s;
    if (tag_out) *tag_out = t;
  });
}
int32_t t4j_c_alltoall_fused(int32_t comm, void* const* parts,
                             void* const* outs,
                             const uint64_t* nbytes_each, int32_t nparts) {
  return c_guard([&] {
    std::vector<size_t> each(nbytes_each, nbytes_each + nparts);
    t4j::alltoall_fused(comm, const_cast<const void* const*>(parts), outs,
                        each.data(), nparts);
  });
}

}  // extern "C"
