// DCN bridge: TCP-based multi-process communication backend.
//
// Native replacement tier for the reference's Cython->libmpi bridge
// (mpi4jax/_src/xla_bridge/mpi_xla_bridge.pyx): same responsibilities --
// tagged point-to-point messaging with ANY_SOURCE/ANY_TAG matching,
// collectives, abort-on-error semantics and the per-call debug log wire
// format (mpi_xla_bridge.pyx:35-60) -- implemented over the hosts'
// data-center network (TCP sockets) instead of libmpi, since the TPU
// runtime environment ships no MPI.
//
// Process model: one OS process per rank (the reference's model,
// SURVEY §7 "one JAX process per TPU host").  Bootstrap via environment:
//   T4J_RANK, T4J_SIZE, T4J_COORD=host:port (rank 0 listens there).
//
// Failure semantics (docs/failure-semantics.md): transport errors no
// longer abort the process.  They raise BridgeError with rank/peer/op
// context, post a process-wide fault (every subsequent bridge call then
// fails fast), and broadcast an abort control frame so peers blocked in
// a matching collective raise too instead of hanging.  Deadlines:
//   T4J_OP_TIMEOUT      per-call progress deadline, seconds (0 = wait
//                       forever, the default — matching MPI)
//   T4J_CONNECT_TIMEOUT bootstrap connect/accept deadline (default 30s)
// Deterministic fault injection for tests (T4J_FAULT_MODE=refuse|
// close_after|delay|die_after|flaky|drop_conn gated on T4J_FAULT_RANK)
// is compiled in; see init_from_env.
//
// Self-healing transport (docs/failure-semantics.md "self-healing
// transport"): each TCP peer link carries a connection epoch and
// sequence-numbered frames backed by a bounded replay ring, so a
// transient connection drop no longer kills the job.  The escalation
// ladder is retry -> reconnect+replay -> abort: the surviving sides
// re-dial with exponential backoff + jitter, handshake (incarnation
// token, epoch, last-acked seq) and replay only the unacked tail —
// in-flight segmented/hierarchical collectives resume from the last
// completed segment instead of restarting.  Exhausted retries, an
// evicted replay tail, or a re-dial from a RESTARTED process (stale
// bootstrap fingerprint) escalate to the abort broadcast above, so
// fail-stop remains the backstop.  Knobs (validated in
// utils/config.py):
//   T4J_RETRY_MAX     reconnect attempts per break (default 3;
//                     0 disables self-healing entirely)
//   T4J_BACKOFF_BASE  first re-dial delay, seconds (default 0.05)
//   T4J_BACKOFF_MAX   backoff cap, seconds (default 2)
//   T4J_REPLAY_BYTES  per-peer replay-ring cap (default 32 MiB; see
//                     docs/performance.md for the memory cost)
//
// Data-plane algorithm selection (docs/performance.md "TCP-tier
// algorithm selection"): large-message allreduce/allgather/
// reduce_scatter run as segmented ring collectives (each link carries
// ~2*(n-1)/n of the payload instead of the trees' full payload per
// level), pipelined at T4J_SEG_BYTES granularity; small messages keep
// the latency-optimal trees.  Knobs (validated in utils/config.py):
//   T4J_RING_MIN_BYTES  total message size at or above which the ring
//                       path is used (default 256 KiB, the measured
//                       crossover; 0 = always ring)
//   T4J_SEG_BYTES       ring segment size (default 1 MiB)
//
// Hierarchical collectives (docs/performance.md "hierarchical
// collectives"): when a communicator spans several hosts and at least
// one host holds more than one member, large allreduce/reduce/bcast/
// allgather/reduce_scatter compose the two native tiers NCCL-style —
// same-host members reduce (or gather) into their host leader through
// the shm arena, leaders run the segmented ring over the DCN TCP tier
// among themselves, and results fan back out through the arena.
// Cross-host traffic shrinks by the local world size; the intra- and
// inter-node phases pipeline at T4J_SEG_BYTES granularity (the leader
// rings chunk k while its locals are still combining chunk k+1).
// Knobs (validated in utils/config.py):
//   T4J_HIER                  auto (default) | on (force, any size) |
//                             off (never)
//   T4J_LEADER_RING_MIN_BYTES total message size at or above which
//                             auto mode picks the hierarchical path
//                             (default 256 KiB)
//   T4J_EMU_LOCAL=k           testing: fold rank/k into the host
//                             fingerprint so one box emulates
//                             ceil(size/k) nodes of k local ranks each
//                             (same-host shm stays within an emulated
//                             node; cross-node traffic rides real TCP)
// Every phase keeps the deadline/abort contract above — a dead or
// stalled local rank (leader or not) surfaces on every survivor as a
// contextual BridgeError within the op deadline.
//
// Striped multi-connection links (docs/performance.md "striped links
// and the zero-copy path"): each TCP peer link is backed by N parallel
// connections ("stripes").  Frames — the existing segment/pipelining
// unit — are dealt round-robin across the stripes under one per-link
// sequence counter; the receiver delivers them back into per-link
// order through a reorder stage, so MPI matching semantics are
// untouched.  Self-healing is per stripe: each stripe keeps its own
// replay ring and reconnect cycle, so one dropped flow repairs and
// replays alone while its siblings keep moving; a stripe that
// exhausts its retry budget migrates its unacked tail onto a live
// sibling, and the LINK is dead only when every stripe is.  Syscalls
// batch: runs of small frames to one stripe ride a single
// sendmsg/iovec gather (T4J_SENDMSG_BATCH frames per call) and the
// readers drain through a scatter buffer; large frames additionally
// opt into MSG_ZEROCOPY (completion-queue reaping bounds replay-arena
// reuse), making the replay-arena copy the only copy on the large
// path — and no copy at all with T4J_RETRY_MAX=0.  Knobs (validated
// in utils/config.py; uniform across ranks):
//   T4J_STRIPES             connections per link (default auto = 1
//                           until the calibrator learns better; the
//                           built width is fixed at bootstrap, the
//                           DEALING width can be lowered/raised up to
//                           it at runtime via t4j_set_wire)
//   T4J_ZEROCOPY_MIN_BYTES  frames at or above this use MSG_ZEROCOPY
//                           (0 = off, the default; degrades loudly to
//                           the copy path on kernels without
//                           SO_ZEROCOPY)
//   T4J_SENDMSG_BATCH       max frames gathered into one sendmsg
//                           (default 8)
//   T4J_EMU_FLOW_BPS        testing: per-connection token-bucket
//                           throttle, bytes/second (0 = off) — lets a
//                           loopback box demonstrate the multi-flow
//                           busbw step real fabrics get from multiple
//                           NIC queues
//
// Async progress engine (docs/async.md): nonblocking
// iallreduce/isend/irecv/ireduce_scatter return a request handle
// immediately; a dedicated progress thread (grown out of the PR-5
// accept-thread model) drains a submission queue and drives each
// operation's segments off the caller's thread, composing with the
// replay-ring self-healing and the per-segment deadlines unchanged
// (the op bodies are the SAME code, just executed on the engine
// thread).  Blocking allreduce/reduce_scatter/send/recv are routed
// through the engine too (blocking = submit + wait), so there is
// exactly one wire path and the deadline/abort contract lives in one
// place.  MPI semantics apply: buffers passed to a nonblocking op must
// stay valid and unmodified (send side) until the request completes,
// and every rank must submit collectives on one communicator in the
// same order.  Requests that are never waited are reported at
// finalize (request-leak detection; t4j-lint rule T4J008 catches the
// same statically).

#pragma once

#include <cstddef>
#include <cstdint>
#include <stdexcept>
#include <string>

namespace t4j {

constexpr int kAnySource = -1;
constexpr int kAnyTag = -1;

// Raised (not abort) on transport failures, deadline expiry, matching
// errors and invalid arguments.  The message carries rank, peer and op
// context ("r2 | t4j: MPI_Recv ...") so pod post-mortems are
// attributable.  Crosses the FFI boundary as ffi::Error (ffi.cc) and
// the ctypes boundary as a nonzero status + t4j_last_error().
struct BridgeError : std::runtime_error {
  using std::runtime_error::runtime_error;
};

enum class ReduceOp : int32_t {
  kSum = 0,
  kProd = 1,
  kMin = 2,
  kMax = 3,
  kLand = 4,
  kLor = 5,
  kLxor = 6,
  kBand = 7,
  kBor = 8,
  kBxor = 9,
};

// Element dtypes, mirroring the reference's 14-entry dtype table
// (mpi4jax/_src/utils.py:43-71).
enum class DType : int32_t {
  kF32 = 0,
  kF64 = 1,
  kI8 = 2,
  kI16 = 3,
  kI32 = 4,
  kI64 = 5,
  kU8 = 6,
  kU16 = 7,
  kU32 = 8,
  kU64 = 9,
  kBool = 10,
  kC64 = 11,
  kC128 = 12,
  kF16 = 13,
  kBF16 = 14,
};

size_t dtype_size(DType dt);

// -- runtime lifecycle ----------------------------------------------------
// All communication functions throw BridgeError (with an MPI_Abort-style
// contextual message, mpi_xla_bridge.pyx:67-91) on transport errors and
// deadline expiry; after the first failure the bridge is faulted and
// every further call fails fast.

bool initialized();
int init_from_env();  // 0 ok; 1 not a multi-process job; 2 bootstrap failed
void finalize();
int world_rank();
int world_size();
void set_logging(bool enabled);
void abort_job(int code, const char* why);

// Override the env-derived deadlines (seconds).  op_s: < 0 keeps the
// current value, 0 disables the per-op deadline, > 0 sets it.
// connect_s: <= 0 keeps the current value (a connect deadline cannot
// be disabled).  Called from Python (native/runtime.py) before init so
// utils/config.py owns validation.
void set_timeouts(double op_s, double connect_s);

// Override the env-derived data-plane tuning.  ring_min: < 0 keeps the
// current value, 0 = always use the ring path, > 0 sets the tree->ring
// switchover in bytes.  seg: < 1 keeps, >= 1 sets the ring segment
// size in bytes.  Must be uniform across ranks (divergent values would
// run mismatched algorithms and deadlock); utils/config.py owns
// validation, native/runtime.py threads the values through before init.
void set_tuning(long long ring_min, long long seg);

// Override the env-derived hierarchical-collective selection.  mode:
// 0 = auto (size threshold), 1 = on (force wherever the topology
// allows), 2 = off, any other value keeps the current setting.
// min_bytes: < 0 keeps, >= 0 sets the auto-mode switchover.  Must be
// uniform across ranks (divergent values would run mismatched
// algorithms and deadlock); utils/config.py owns validation.
void set_hier(int mode, long long min_bytes);

// Override the env-derived self-healing knobs.  retry: < 0 keeps,
// 0 disables (fail-stop on the first transport error, the pre-PR-5
// behaviour), > 0 caps reconnect attempts per break.  base_s / max_s:
// <= 0 keeps.  replay: < 0 keeps, >= 0 sets the per-peer replay-ring
// byte cap.  Must be called before init and uniformly across ranks;
// utils/config.py owns validation.
void set_resilience(int retry, double base_s, double max_s,
                    long long replay);

// Override the env-derived wire-path knobs (striping / syscall
// batching / zerocopy; header comment above).  stripes: >= 1 sets the
// dealing width (clamped to the built width after init, and to
// kMaxStripes always), <= 0 keeps.  Before init it also sets the
// number of connections bootstrap builds per link.  zc_min: < 0
// keeps, 0 disables MSG_ZEROCOPY, > 0 sets the opt-in floor.  batch:
// >= 1 sets the frames-per-sendmsg gather cap, <= 0 keeps.
// emu_flow_bps: < 0 keeps, 0 disables the per-connection throttle,
// > 0 sets it (bytes/second).  Must be uniform across ranks
// (utils/config.py owns validation).
void set_wire(int stripes, long long zc_min, int batch,
              long long emu_flow_bps);

// Effective wire-path state for introspection/benchmark labels.
struct WireInfo {
  int stripes_built;    // connections per link (fixed at bootstrap)
  int stripes_active;   // current dealing width (<= built)
  long long zc_min_bytes;
  int sendmsg_batch;
  long long emu_flow_bps;
  bool zerocopy;        // requested AND the kernel honours SO_ZEROCOPY
  // completion diagnostics: how many MSG_ZEROCOPY sends completed,
  // and how many of those the kernel reported as COPIED anyway
  // (SO_EE_CODE_ZEROCOPY_COPIED — loopback always; real NIC paths
  // should not, and a copied~completions ratio near 1 means the
  // fabric pays pin overhead for no copy saved)
  unsigned long long zc_completions;
  unsigned long long zc_copied;
};
void wire_info(WireInfo* out);

// Wire backend selection (docs/performance.md "io_uring wire
// backend").  mode: 0 = sendmsg (the classic gather-write/recv data
// plane, byte-stable vs every prior release), 1 = io_uring (SQ-ring
// submission of send/recv chains with registered buffers over the
// replay arena), 2 = auto (uring when the kernel supports it and the
// calibrator found it profitable, else sendmsg); < 0 keeps.  An
// explicit uring request on a kernel without io_uring degrades
// LOUDLY to sendmsg at init (the knob is a perf opt-in, not a
// correctness contract — Python additionally rejects it before init
// when the probe fails).  Frame bytes on the wire are identical
// across backends: only the syscall shape changes.  Must be uniform
// across ranks only by convention (mixed backends interoperate — the
// wire protocol is unchanged — but benchmark labels assume
// uniformity).  utils/config.py owns env validation
// (T4J_WIRE_BACKEND=auto|sendmsg|uring).
void set_wire_backend(int mode);

// Effective wire-backend state: requested mode (0 sendmsg / 1 uring /
// 2 auto), whether the running kernel supports io_uring (probed once,
// cheap, valid before init), and the ACTIVE backend after resolution
// (0 sendmsg / 1 uring).
void wire_backend_info(int* mode, int* supported, int* active);

// Wire dtype for compressed collectives (docs/performance.md
// "Compressed collectives").  mode: 0 = off (payloads travel f32,
// bit-identical to the uncompressed build), 1 = bf16 (round-to-
// nearest-even), 2 = fp8 e4m3 (saturating, max 448); < 0 keeps the
// current value.  Compression applies per-segment inside the ring /
// hierarchical-leader loops, and only to f32 SUM payloads on comms
// whose EVERY ring hop crosses hosts — a single shm/pipe hop disables
// it for the whole comm so all ranks of a collective see identical
// result bytes regardless of their position on the ring.  Must be
// uniform across ranks (divergent wire dtypes would exchange
// mismatched frame sizes and deadlock; t4j-lint rule T4J009 catches
// it statically).  utils/config.py owns env validation
// (T4J_WIRE_DTYPE=off|bf16|fp8).
void set_wire_dtype(int mode);

// Effective wire-dtype state: mode (0 off / 1 bf16 / 2 fp8) plus the
// cumulative logical (f32) vs wire (compressed) byte counters over the
// compressed send path — the counters are the provable byte saving
// (telemetry/dump.py records both; they stay 0 while mode is off).
void wire_dtype_info(int* mode, unsigned long long* logical_bytes,
                     unsigned long long* wire_bytes);

// -- elastic world membership (docs/failure-semantics.md "elastic
// membership") --------------------------------------------------------------
// When a rank is declared unrecoverable (its link exhausted the
// retry/replay budget), T4J_ELASTIC decides what happens next:
//   off    — today's exact behaviour: abort broadcast, whole job dies.
//   shrink — survivors agree on a reduced world (suspected-dead sets
//            flooded over the surviving mesh, lowest surviving rank
//            arbitrates), in-flight ops drain with a ResizeInterrupted
//            status, ring/hier/shm topology is rebuilt over the
//            survivors under a bumped world epoch (stamped into every
//            wire frame so stale-epoch traffic is rejected), and the
//            job continues at the reduced size.  The Python tier
//            surfaces WorldResized at the next op.
//   rejoin — shrink, plus rank 0 keeps the bootstrap coordinator port
//            open: a relaunched replacement process (T4J_REJOIN=1)
//            re-bootstraps through it with a fresh incarnation token
//            and joins at the next epoch fence (grow resize).
// Floors and bounds:
//   T4J_MIN_WORLD       below this many survivors the legacy abort
//                       fires instead of a shrink (default 1).
//   T4J_RESIZE_TIMEOUT  per-phase bound on the membership agreement /
//                       rebuild (seconds, default 30).
// Elastic requires self-healing on (T4J_RETRY_MAX > 0 — escalation is
// what triggers it; utils/config.py rejects the combination) and a
// bootstrap world of at most 64 ranks (the agreement floods a u64
// membership mask).
// mode: 0 off, 1 shrink, 2 rejoin (other values keep).  min_world:
// >= 1 sets, else keeps.  resize_timeout_s: > 0 sets, else keeps.
// Must be set before init and uniformly across ranks.
void set_elastic(int mode, int min_world, double resize_timeout_s);

// Live membership view.  epoch 0 = the bootstrap world; every
// completed resize bumps it.  alive_mask bit r = world rank r is a
// member.  Returns false before init.
struct WorldInfo {
  uint32_t epoch;
  int boot_size;    // T4J_SIZE at bootstrap (rank ids keep this space)
  int alive_count;  // current members
  uint64_t alive_mask;
  bool resizing;    // a membership agreement/rebuild is in progress
  // frames dropped for carrying a stale world epoch (diagnostic: the
  // drop is belt-and-braces — post-resize links are fresh — so a
  // nonzero count in a post-mortem flags pre-resize traffic arriving
  // where it never should)
  uint64_t stale_frames;
};
bool world_info(WorldInfo* out);

// Block until no resize is in progress (bounded by timeout_s; <= 0 =
// one nonblocking check).  Returns true when settled.
bool resize_wait(double timeout_s);

// Per-peer self-healing counters (t4j_link_stats / runtime.link_stats):
// how many times the link reconnected and how much it replayed.
// state: 0 = up, 1 = broken (repair in progress), 2 = dead.
struct LinkStats {
  uint64_t reconnects;
  uint64_t replayed_frames;
  uint64_t replayed_bytes;
  // Data-plane syscall counters (docs/performance.md "io_uring wire
  // backend"): every kernel crossing the send/recv paths make on this
  // link — sendmsg/recv/read/poll on the classic backend,
  // io_uring_enter on the uring one.  The syscalls-per-frame ratio
  // these give against the frame counters is the acceptance metric
  // for the uring backend; it is counted at the syscall sites, never
  // hand-derived.
  uint64_t tx_syscalls;
  uint64_t rx_syscalls;
  int state;
};
// peer >= 0: that link's counters (false for self/out-of-range).
// peer < 0: aggregate over every link, state = worst.  False before
// init.  With striping a LINK's counters are the sum over its
// stripes, and its state derives stripe-wise: dead only when EVERY
// stripe is dead, broken when any stripe is down.
bool link_stats(int peer, LinkStats* out);
// One stripe's counters/state (docs/performance.md "striped links"):
// false for self/out-of-range peer or stripe index, or before init.
bool link_stripe_stats(int peer, int stripe, LinkStats* out);

// World-level topology discovered at bootstrap (host fingerprints).
// host_id is the ordinal of this rank's host in first-occurrence
// order over world ranks; leader_rank the lowest world rank sharing
// the host.  Returns false before init (fields untouched).
struct TopoInfo {
  int host_id;
  int local_rank;
  int local_size;
  int leader_rank;
  int n_hosts;
};
bool topology(TopoInfo* out);

// Pure selection query (no communication): would a collective of
// total_bytes on this communicator take the hierarchical path, given
// the current T4J_HIER mode, threshold and bootstrap topology?
// Assumes the local arenas negotiate successfully (they are queried
// lazily on first real use).
bool hier_would_select(int comm, size_t total_bytes);
// True once the communicator's hierarchical layer has actually been
// negotiated and is live (passive read; never communicates).
bool hier_active(int comm);

// Explicitly hierarchical allreduce: throws BridgeError when the
// topology is ineligible or the negotiation failed, instead of
// falling back.  The auto-selected path is the plain allreduce().
void hier_allreduce(int comm, const void* in, void* out, size_t count,
                    DType dt, ReduceOp op);

// Fault surface: after any bridge call fails, faulted() is true and
// fault_message() describes the first failure.
bool faulted();
std::string fault_message();

// Best-effort MPI_Abort analog: broadcast an abort control frame to
// every connected peer (their blocked ops raise `why` within their
// deadline) without touching this process's own control flow.  Used by
// the launcher's child wrapper when user code dies so survivors don't
// hang until the external kill.
void abort_notify(const char* why);

// -- communicators --------------------------------------------------------
// A communicator is a subset of world ranks plus a context id that
// namespaces its traffic (the clone/firewall semantics of the
// reference's comm.py:4-11).
int comm_create(const int* world_ranks, int n, int ctx);  // returns handle
int comm_rank(int comm);                         // my rank within comm
int comm_size(int comm);

// -- point to point -------------------------------------------------------
void send(int comm, const void* buf, size_t nbytes, int dest, int tag);
// Blocks until a matching message arrives; fills *src/*tag_out with the
// matched envelope. nbytes must match the message size exactly.
void recv(int comm, void* buf, size_t nbytes, int source, int tag,
          int* src_out, int* tag_out);
// Send and receive sizes are independent, as in MPI_Sendrecv (the
// reference allows differing buffer shapes, sendrecv.py:41-103).
void sendrecv(int comm, const void* sendbuf, size_t send_nbytes,
              void* recvbuf, size_t recv_nbytes, int source, int dest,
              int sendtag, int recvtag, int* src_out, int* tag_out);

// -- collectives ----------------------------------------------------------
void barrier(int comm);
void bcast(int comm, void* buf, size_t nbytes, int root);
void allreduce(int comm, const void* in, void* out, size_t count, DType dt,
               ReduceOp op);
// MPI_Reduce_scatter_block: `in` holds comm_size blocks of count_each
// elements; member r receives the reduction of block r in `out`.
// Large messages ride the segmented ring reduce-scatter directly —
// O((n-1)/n * payload) per link, the collective ZeRO-style scattered
// gradients want — instead of paying full allreduce (or alltoall)
// cost.
void reduce_scatter(int comm, const void* in, void* out, size_t count_each,
                    DType dt, ReduceOp op);
void reduce(int comm, const void* in, void* out, size_t count, DType dt,
            ReduceOp op, int root);
void scan(int comm, const void* in, void* out, size_t count, DType dt,
          ReduceOp op);
void allgather(int comm, const void* in, void* out, size_t nbytes_each);
void gather(int comm, const void* in, void* out, size_t nbytes_each,
            int root);
void scatter(int comm, const void* in, void* out, size_t nbytes_each,
             int root);
void alltoall(int comm, const void* in, void* out, size_t nbytes_each);

// -- small-message coalescing (docs/performance.md "small-message
// coalescing") -------------------------------------------------------------
// Fused multi-part p2p: every part of a fused call travels in ONE wire
// frame — a single WireHeader followed by a fused sub-header (magic,
// part count, per-part sizes) and the concatenated payloads — instead
// of one frame (header + syscall + telemetry event) per part.  The
// frame rides the normal p2p channel, so the replay ring, the shm
// pipes, per-op deadlines and telemetry all apply unchanged; both
// sides must agree on the part list (sizes are validated against the
// sub-header, a mismatch is an attributable fail_op).  The FUSION
// DECISION lives in the Python op layer, gated by T4J_COALESCE_BYTES
// (mpi4jax_tpu/tuning/ calibrates it); the knob is mirrored here so
// standalone harnesses and introspection see the effective value.
//   T4J_COALESCE_BYTES  fuse runs of small same-peer messages whose
//                       combined payload is at or below this many
//                       bytes (default 16 KiB; 0 disables fusion —
//                       the exact pre-coalescing wire behaviour).

// bytes < 0 keeps the current value; 0 disables; > 0 sets.  Like the
// other data-plane knobs it must be uniform across ranks (both sides
// of a fused exchange must agree to fuse).
void set_coalesce(long long bytes);
long long coalesce_threshold();

// Fused sendrecv: gather-send `n_send` parts as one frame to `dest`,
// then scatter-recv `n_recv` parts from one frame from `source`
// (eager send-first order, like sendrecv).  n_send == 0 makes it a
// pure scatter-recv, n_recv == 0 a pure gather-send — the one-sided
// halves a non-periodic halo edge rank needs.  src_out/tag_out carry
// the matched envelope when n_recv > 0 (null ok).
void sendrecv_fused(int comm, const void* const* send_parts,
                    const size_t* send_nbytes, int n_send,
                    void* const* recv_parts, const size_t* recv_nbytes,
                    int n_recv, int source, int dest, int sendtag,
                    int recvtag, int* src_out, int* tag_out);

// Fused alltoall over `nparts` independent block arrays: part i holds
// comm_size blocks of nbytes_each[i]; outs[i] receives block `rank`
// of every member's part i.  Equivalent to nparts separate alltoall
// calls (bit-identical outputs), but each peer receives ONE fused
// frame carrying its slice of every part instead of nparts frames —
// the MoE per-expert dispatch path (parallel/moe.py).  Same-host
// arena communicators run the parts through the arena individually
// (no wire frames to fuse there).
void alltoall_fused(int comm, const void* const* parts, void* const* outs,
                    const size_t* nbytes_each, int nparts);

// -- async progress engine (docs/async.md) --------------------------------
// Nonblocking ops: submit returns a request id (> 0) immediately; the
// progress thread executes the wire phase.  Contract (MPI_I* model):
//   * `in` / `buf` must stay valid and unmodified, and `out` valid,
//     until the request completes (wait/test-done);
//   * collectives must be submitted in the same order on every member
//     rank (the engine executes them in submission order);
//   * every request must be completed by wait/waitall (or test
//     returning done) exactly once — a second wait throws, and
//     requests still pending at finalize are reported as leaks.
// Argument errors (bad comm/rank/dtype) throw at submit time on the
// caller's thread; transport failures during execution surface from
// wait/test as BridgeError with the engine-side context, after the
// usual fault posting + abort broadcast.
uint64_t iallreduce(int comm, const void* in, void* out, size_t count,
                    DType dt, ReduceOp op);
uint64_t ireduce_scatter(int comm, const void* in, void* out,
                         size_t count_each, DType dt, ReduceOp op);
uint64_t isend(int comm, const void* buf, size_t nbytes, int dest, int tag);
// irecv parks in the engine until a matching frame arrives (it never
// blocks the progress thread); source/tag may be ANY.  The matched
// envelope is returned by wait/test via src_out/tag_out.
uint64_t irecv(int comm, void* buf, size_t nbytes, int source, int tag);
// Block until the request completes; fills *src_out/*tag_out for
// irecv (untouched otherwise; null ok).  Consumes the request.
void wait(uint64_t req, int* src_out, int* tag_out);
// Nonblocking completion probe: returns true when the request is
// complete (outputs filled like wait) WITHOUT consuming it — a later
// wait reaps it.  Throws if the op failed (consuming the request).
bool test(uint64_t req, int* src_out, int* tag_out);
void waitall(const uint64_t* reqs, int n);
// Owned-buffer variants for callers whose buffers do NOT outlive the
// submit call (the XLA FFI handlers: custom-call operands are reused
// the moment the handler returns).  The engine copies the input into a
// request-owned buffer at submit and allocates the result buffer
// itself; wait_into copies the completed result out.  One extra
// memcpy per direction versus the zero-copy API above — still far
// below the host-callback path these exist to replace.
uint64_t iallreduce_owned(int comm, const void* in, size_t count,
                          DType dt, ReduceOp op);
uint64_t ireduce_scatter_owned(int comm, const void* in,
                               size_t count_each, DType dt, ReduceOp op);
uint64_t isend_owned(int comm, const void* buf, size_t nbytes, int dest,
                     int tag);
uint64_t irecv_owned(int comm, size_t nbytes, int source, int tag);
// Wait for an owned-buffer request and copy its result into dst
// (exactly nbytes; dst/nbytes ignored for isend).  Fills
// *src_out/*tag_out for irecv.  Consumes the request.
void wait_into(uint64_t req, void* dst, size_t nbytes, int* src_out,
               int* tag_out);

// Gauge: requests submitted but not yet complete (queued + running +
// parked).  0 before init / when idle.
int async_inflight();
// Requests never consumed by wait/test-done (includes completed ones
// nobody reaped) — the finalize leak check reads this.
int async_pending();

// -- internal hooks shared with the shm tier (shm.cc) ---------------------
namespace detail {
// True once a fault was posted or shutdown began: blocked shm waiters
// must bail out instead of waiting for a peer that will never come.
bool stopped();
// Throw the posted fault (or a generic stop message) as BridgeError.
[[noreturn]] void raise_stop();
// Effective per-op progress deadline in seconds (0 = unbounded).
double op_timeout_seconds();
// Op-context failure: posts the fault, broadcasts the abort control
// frame to peers, throws BridgeError.  `what` is appended to the
// "r<rank> | t4j: <current op>: " prefix.
[[noreturn]] void fail_op(const std::string& what);
}  // namespace detail

}  // namespace t4j
