// DCN bridge: TCP-based multi-process communication backend.
//
// Native replacement tier for the reference's Cython->libmpi bridge
// (mpi4jax/_src/xla_bridge/mpi_xla_bridge.pyx): same responsibilities --
// tagged point-to-point messaging with ANY_SOURCE/ANY_TAG matching,
// collectives, abort-on-error semantics and the per-call debug log wire
// format (mpi_xla_bridge.pyx:35-60) -- implemented over the hosts'
// data-center network (TCP sockets) instead of libmpi, since the TPU
// runtime environment ships no MPI.
//
// Process model: one OS process per rank (the reference's model,
// SURVEY §7 "one JAX process per TPU host").  Bootstrap via environment:
//   T4J_RANK, T4J_SIZE, T4J_COORD=host:port (rank 0 listens there).

#pragma once

#include <cstddef>
#include <cstdint>

namespace t4j {

constexpr int kAnySource = -1;
constexpr int kAnyTag = -1;

enum class ReduceOp : int32_t {
  kSum = 0,
  kProd = 1,
  kMin = 2,
  kMax = 3,
  kLand = 4,
  kLor = 5,
  kLxor = 6,
  kBand = 7,
  kBor = 8,
  kBxor = 9,
};

// Element dtypes, mirroring the reference's 14-entry dtype table
// (mpi4jax/_src/utils.py:43-71).
enum class DType : int32_t {
  kF32 = 0,
  kF64 = 1,
  kI8 = 2,
  kI16 = 3,
  kI32 = 4,
  kI64 = 5,
  kU8 = 6,
  kU16 = 7,
  kU32 = 8,
  kU64 = 9,
  kBool = 10,
  kC64 = 11,
  kC128 = 12,
  kF16 = 13,
  kBF16 = 14,
};

size_t dtype_size(DType dt);

// -- runtime lifecycle ----------------------------------------------------
// All functions abort the process (after printing an MPI_Abort-style
// message, mpi_xla_bridge.pyx:67-91) on unrecoverable transport errors.

bool initialized();
int init_from_env();  // returns 0 on success
void finalize();
int world_rank();
int world_size();
void set_logging(bool enabled);
void abort_job(int code, const char* why);

// -- communicators --------------------------------------------------------
// A communicator is a subset of world ranks plus a context id that
// namespaces its traffic (the clone/firewall semantics of the
// reference's comm.py:4-11).
int comm_create(const int* world_ranks, int n, int ctx);  // returns handle
int comm_rank(int comm);                         // my rank within comm
int comm_size(int comm);

// -- point to point -------------------------------------------------------
void send(int comm, const void* buf, size_t nbytes, int dest, int tag);
// Blocks until a matching message arrives; fills *src/*tag_out with the
// matched envelope. nbytes must match the message size exactly.
void recv(int comm, void* buf, size_t nbytes, int source, int tag,
          int* src_out, int* tag_out);
// Send and receive sizes are independent, as in MPI_Sendrecv (the
// reference allows differing buffer shapes, sendrecv.py:41-103).
void sendrecv(int comm, const void* sendbuf, size_t send_nbytes,
              void* recvbuf, size_t recv_nbytes, int source, int dest,
              int sendtag, int recvtag, int* src_out, int* tag_out);

// -- collectives ----------------------------------------------------------
void barrier(int comm);
void bcast(int comm, void* buf, size_t nbytes, int root);
void allreduce(int comm, const void* in, void* out, size_t count, DType dt,
               ReduceOp op);
void reduce(int comm, const void* in, void* out, size_t count, DType dt,
            ReduceOp op, int root);
void scan(int comm, const void* in, void* out, size_t count, DType dt,
          ReduceOp op);
void allgather(int comm, const void* in, void* out, size_t nbytes_each);
void gather(int comm, const void* in, void* out, size_t nbytes_each,
            int root);
void scatter(int comm, const void* in, void* out, size_t nbytes_each,
             int root);
void alltoall(int comm, const void* in, void* out, size_t nbytes_each);

}  // namespace t4j
