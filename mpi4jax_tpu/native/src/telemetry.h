// Native telemetry core: per-rank lock-free event ring + metrics table
// (internal; docs/observability.md).
//
// The reference's observability story stops at per-call debug log lines
// (mpi_xla_bridge.pyx:35-60) — string formatting on the hot path, off
// by default, unparseable at scale.  This header is the measurement
// substrate under docs/observability.md: every instrumented site in
// dcn.cc / shm.cc appends a fixed 32-byte binary record to a per-rank
// lock-free ring buffer and/or bumps a fixed-shape atomic counter
// table, drained from Python (ffi.cc exports, native/runtime.py,
// mpi4jax_tpu/telemetry/) into per-rank snapshot files and merged
// cross-rank Perfetto timelines.
//
// Modes (T4J_TELEMETRY, validated loudly in utils/config.py; the env
// parse here is the fallback for hand-run processes):
//   off       — zero-cost: every instrumented site is one relaxed
//               atomic load + compare (measured within noise of the
//               un-instrumented build, docs/observability.md
//               "overhead").
//   counters  — the metrics table only (per comm x op x plane count /
//               bytes / latency + size histograms), plus the rare
//               control-plane events (link break / reconnect / replay
//               escalation / fault) in the ring — those are the
//               post-mortem payload runtime.check_health() reports.
//   trace     — counters plus per-event records for ops, wire frames
//               (= ring/hier segments) and shm arena stages: the
//               Perfetto timeline feed.
//
// T4J_TELEMETRY_BYTES bounds the ring (default 1 MiB = 32Ki events);
// when writers lap the drain cursor the oldest events are dropped and
// counted (t4j_telemetry_dropped), never blocking a data-plane thread.
//
// Concurrency: writers reserve a slot with one fetch_add and publish
// with a per-slot ticket (release store of index+1); the drain side
// (Python, serialised by a mutex) copies a slot and re-checks its
// ticket, discarding records a lapping writer tore mid-copy — a
// per-slot seqlock.  No instrumented path ever takes a lock.
//
// The event layout is mirrored byte-for-byte by
// mpi4jax_tpu/telemetry/schema.py (struct format "<QHBBiiIQ"); bump
// kSchemaVersion when changing either.
//
// Flight recorder (T4J_FLIGHT=on, docs/observability.md "flight
// recorder"): the ring slots, the metrics table, and a fixed header
// (magic / schema / rank / boot incarnation / world epoch / clock
// anchor / heartbeat) live in a per-rank mmap'd file instead of the
// heap.  mmap(MAP_SHARED) makes the page cache the storage: a rank
// killed by SIGKILL / segfault / OOM loses NOTHING it had published —
// the seqlock ticket discipline that already detects torn reads on
// the drain path makes every slot independently validatable by an
// offline reader (telemetry/postmortem.py), so the dying rank's last
// events survive without any cooperative drain.  The heartbeat word
// is bumped by the progress-engine thread and the io poll loops so a
// reader can distinguish "process dead" (heartbeat frozen) from
// "alive but wedged" (heartbeat fresh, no op progress).  The file
// layout is mirrored by telemetry/schema.py (FLIGHT_HEADER_STRUCT);
// bump kFlightVersion when changing either.

#pragma once

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <atomic>
#include <cerrno>
#include <chrono>
#include <cstddef>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <memory>
#include <mutex>
#include <new>
#include <string>
#include <thread>

namespace t4j {
namespace tel {

// v2: frame_tx/frame_rx and the link control events (break/reconnect/
// replay/link_dead) carry the STRIPE index in the previously unused
// `comm` field (-1 = unstriped/unknown; docs/performance.md "striped
// links").  The 32-byte record layout itself is unchanged.
constexpr uint32_t kSchemaVersion = 2;

enum Mode : int { kOff = 0, kCounters = 1, kTrace = 2 };

// Stable wire ids, mirrored by telemetry/schema.py KIND_NAMES.
enum Kind : uint16_t {
  kKindNone = 0,
  // op-level (metrics rows + trace begin/end pairs)
  kSend = 1,
  kRecv = 2,
  kSendrecv = 3,
  kBarrier = 4,
  kBcast = 5,
  kReduce = 6,
  kAllreduce = 7,
  kReduceScatter = 8,
  kScan = 9,
  kAllgather = 10,
  kGather = 11,
  kScatter = 12,
  kAlltoall = 13,
  kHierAllreduce = 14,
  // data plane (trace instants): one frame = one wire segment
  kFrameTx = 20,
  kFrameRx = 21,
  // control plane (recorded from counters mode up: rare and vital)
  kLinkBreak = 30,
  kReconnect = 31,
  kReplay = 32,
  kLinkDead = 33,
  kFault = 34,
  // shm arena stages (trace instants)
  kShmStage = 40,
  kShmFold = 41,
  // async progress engine (trace instants; docs/async.md).  The
  // 32-byte record has no spare field, so these three overload two:
  // `peer` carries the in-flight-depth gauge (the engine has no wire
  // peer), and while kOpQueued/kOpProgress put the payload size in
  // `bytes`, kOpComplete's `bytes` is the op's EXECUTION duration in
  // ns — t4j-top derives queue depth and the engine overlap ratio
  // from these without needing per-event request ids.
  kOpQueued = 50,
  kOpProgress = 51,
  kOpComplete = 52,
  // caller-side blocked wait (trace mode): a begin/end pair on the
  // CALLER's lane around reap_request's blocked region.  Every op
  // body executes on the progress-engine thread, so its OpScope lands
  // on the ENGINE lane — without this bracket a trace cannot tell a
  // caller that sat inside wait() (blocking submit+wait included)
  // from one that computed while the engine ran.  telemetry/
  // diagnose.py builds caller-blocked time from these + caller-lane
  // op scopes, and engine wire time from the engine lane.
  kWait = 53,
  // step markers (docs/observability.md "step markers"): user-declared
  // iteration boundaries emitted through ops.step.annotate_step /
  // step_scope via t4j_annotate_step.  `bytes` carries the step INDEX
  // (monotone per rank, assigned by the Python side so every rank's
  // step k is the same user-level iteration); begin/end phases pair up
  // like op scopes.  Recorded from counters mode up — they are rare
  // (one pair per training step) and they are the ground truth every
  // per-step aggregation in telemetry/diagnose.py anchors on, so a
  // counters-mode post-mortem still knows which step it died in.
  kStep = 60,
  // elastic world membership (docs/failure-semantics.md "elastic
  // membership"): control instants recorded from counters mode up.
  // kResizeBegin/kResizeDone carry the forming/committed world epoch
  // in `bytes` (done additionally carries the new alive count in
  // `peer`); kRankDead marks a rank leaving the membership (`peer` =
  // the departed world rank, `bytes` = the epoch that removed it) —
  // distinct from kLinkDead, which is one LINK's terminal verdict.
  kResizeBegin = 61,
  kResizeDone = 62,
  kRankDead = 63,
};

enum Phase : uint8_t { kInstant = 0, kBegin = 1, kEnd = 2 };

// Data-plane attribution, mirrored by telemetry/schema.py PLANE_NAMES.
enum Plane : uint8_t {
  kPlaneNone = 0,
  kPlaneTree = 1,
  kPlaneRing = 2,
  kPlaneHier = 3,
  kPlaneShm = 4,
  kPlaneCtrl = 5,
};

// 32-byte packed record; `seq` carries a 32-bit hash of the emitting
// thread id so the exporter can lane events per native thread (begin/
// end pairs nest correctly per lane).
struct Event {
  uint64_t t_ns;  // monotonic (CLOCK_MONOTONIC via steady_clock)
  uint16_t kind;
  uint8_t phase;
  uint8_t plane;
  int32_t comm;  // comm handle, -1 when unknown (shm arena stages)
  int32_t peer;  // world rank of the peer/root, -1 when n/a
  uint32_t seq;  // emitting-thread lane id
  uint64_t bytes;
};
static_assert(sizeof(Event) == 32, "telemetry event layout");

inline uint64_t now_ns() {
  return static_cast<uint64_t>(
      std::chrono::duration_cast<std::chrono::nanoseconds>(
          std::chrono::steady_clock::now().time_since_epoch())
          .count());
}

inline uint32_t thread_lane() {
  static thread_local uint32_t lane = [] {
    size_t h = std::hash<std::thread::id>{}(std::this_thread::get_id());
    uint32_t v = static_cast<uint32_t>(h ^ (h >> 32));
    return v ? v : 1u;
  }();
  return lane;
}

// ---- flight-recorder header ---------------------------------------------
//
// The first 160 bytes of a rank's flight file (rank<k>-<boot>.t4jflight).
// Every mutable word is a lock-free atomic living IN the mapping, so
// the on-disk view is always within one store of the live view; the
// offline reader (telemetry/schema.py read_flight_file) needs no
// cooperation from the writer, dead or alive.  Mirrored by
// FLIGHT_HEADER_STRUCT — keep the offsets pinned by the asserts below.

constexpr uint32_t kFlightVersion = 1;
constexpr char kFlightMagic[8] = {'T', '4', 'J', 'F', 'L', 'T', '1', 0};
constexpr uint32_t kFlightFinalized = 1;  // flags: clean finalize ran

struct FlightHeader {
  char magic[8];
  uint32_t version;  // kFlightVersion (file layout)
  uint32_t schema;   // kSchemaVersion (event record layout)
  int32_t rank;
  int32_t world;
  std::atomic<uint32_t> world_epoch;  // elastic membership epoch
  std::atomic<uint32_t> mode;         // telemetry mode at last set()
  uint64_t boot_unix_ns;              // process boot incarnation (time)
  std::atomic<uint64_t> boot_token;   // bootstrap incarnation token
  std::atomic<uint64_t> anchor_mono_ns;
  std::atomic<uint64_t> anchor_unix_ns;
  uint64_t nslots;
  std::atomic<uint64_t> widx;     // the LIVE ring write cursor
  std::atomic<uint64_t> dropped;  // the LIVE overflow counter
  std::atomic<uint64_t> heartbeat_ns;     // mono; engine/poll threads bump
  std::atomic<uint64_t> heartbeat_count;
  std::atomic<uint32_t> flags;  // kFlightFinalized on clean exit
  uint32_t pad;
  uint64_t slots_off;      // byte offset of the Slot array
  uint64_t metrics_off;    // byte offset of the raw metrics Table
  uint64_t metrics_bytes;  // sizeof(Table)
  uint64_t reserved[3];
};
static_assert(sizeof(FlightHeader) == 160, "flight header layout");
static_assert(offsetof(FlightHeader, boot_unix_ns) == 32, "flight layout");
static_assert(offsetof(FlightHeader, widx) == 72, "flight layout");
static_assert(offsetof(FlightHeader, flags) == 104, "flight layout");
static_assert(offsetof(FlightHeader, slots_off) == 112, "flight layout");
static_assert(std::atomic<uint64_t>::is_always_lock_free,
              "flight mapping needs lock-free u64 atomics");

struct FlightState {
  std::atomic<FlightHeader*> header{nullptr};
  void* base = nullptr;
  size_t map_bytes = 0;
  std::string path;  // set before header is published, then immutable
};

inline FlightState& flight_state() {
  static FlightState& s = *new FlightState;  // leaked: see ring()
  return s;
}

inline FlightHeader* flight_header() {
  return flight_state().header.load(std::memory_order_acquire);
}

// One relaxed store + add when the recorder is on, one relaxed load
// when it is off: cheap enough for the io poll loops.
inline void flight_heartbeat() {
  FlightHeader* h = flight_header();
  if (!h) return;
  h->heartbeat_ns.store(now_ns(), std::memory_order_relaxed);
  h->heartbeat_count.fetch_add(1, std::memory_order_relaxed);
}

inline void flight_set_epoch(uint32_t epoch) {
  FlightHeader* h = flight_header();
  if (h) h->world_epoch.store(epoch, std::memory_order_relaxed);
}

inline void flight_set_token(uint64_t token) {
  FlightHeader* h = flight_header();
  if (h) h->boot_token.store(token, std::memory_order_relaxed);
}

inline void flight_set_mode_word(uint32_t m) {
  FlightHeader* h = flight_header();
  if (h) h->mode.store(m, std::memory_order_relaxed);
}

inline void flight_anchor_sync(uint64_t mono, uint64_t unix_ns) {
  FlightHeader* h = flight_header();
  if (!h) return;
  h->anchor_mono_ns.store(mono, std::memory_order_relaxed);
  h->anchor_unix_ns.store(unix_ns, std::memory_order_relaxed);
}

// Clean-finalize mark: a reader finding it knows the rank exited
// cooperatively (its drained rank file is the richer artifact); a
// flight file WITHOUT it is a hard death or a still-running rank —
// the heartbeat age tells those apart.
inline void flight_mark_finalized() {
  FlightState& s = flight_state();
  FlightHeader* h = s.header.load(std::memory_order_acquire);
  if (!h) return;
  h->flags.fetch_or(kFlightFinalized, std::memory_order_relaxed);
  ::msync(s.base, s.map_bytes, MS_ASYNC);
}

// ---- knobs --------------------------------------------------------------

inline std::atomic<int>& mode_cell() {
  static std::atomic<int> v{-1};
  return v;
}

inline std::atomic<long long>& ring_bytes_cell() {
  static std::atomic<long long> v{-1};
  return v;
}

constexpr long long kDefaultRingBytes = 1 << 20;  // 32Ki events
constexpr long long kMinRingBytes = 4 << 10;      // 128 events

inline int mode() {
  int v = mode_cell().load(std::memory_order_relaxed);
  if (v < 0) {
    const char* s = std::getenv("T4J_TELEMETRY");
    v = kOff;
    if (s && s[0]) {
      if (!std::strcmp(s, "counters")) v = kCounters;
      else if (!std::strcmp(s, "trace")) v = kTrace;
      // anything else keeps off; utils/config.py rejects loudly
    }
    mode_cell().store(v, std::memory_order_relaxed);
  }
  return v;
}

inline long long ring_bytes() {
  long long v = ring_bytes_cell().load(std::memory_order_relaxed);
  if (v < 0) {
    v = kDefaultRingBytes;
    const char* s = std::getenv("T4J_TELEMETRY_BYTES");
    if (s && s[0]) {
      char* end = nullptr;
      long long got = std::strtoll(s, &end, 10);
      if (end != s && got >= 0) {
        if (*end == 'k' || *end == 'K') { got <<= 10; ++end; }
        else if (*end == 'm' || *end == 'M') { got <<= 20; ++end; }
        else if (*end == 'g' || *end == 'G') { got <<= 30; ++end; }
        if (*end == '\0') v = got;  // Python is the loud validator
      }
    }
    if (v < kMinRingBytes) v = kMinRingBytes;
    ring_bytes_cell().store(v, std::memory_order_relaxed);
  }
  return v;
}

// set_telemetry(mode, ring_bytes): mode < 0 or ring < 0 keeps the
// current value.  Must be called before the first event is recorded
// (native/runtime.py threads it through before t4j_init; the ring is
// sized on first use and never re-sized).
inline void set(int m, long long ring) {
  if (m >= kOff && m <= kTrace) {
    mode_cell().store(m, std::memory_order_relaxed);
    flight_set_mode_word(static_cast<uint32_t>(m));
  }
  if (ring >= 0) {
    if (ring < kMinRingBytes) ring = kMinRingBytes;
    ring_bytes_cell().store(ring, std::memory_order_relaxed);
  }
}

// ---- flight-recorder knobs ----------------------------------------------
//
// T4J_FLIGHT truthy turns the recorder on; T4J_FLIGHT_DIR names the
// directory (falling back to T4J_TELEMETRY_DIR, then ".").  Both can
// be overridden pre-init via t4j_set_flight (utils/config.py is the
// loud validator, this parse is the hand-run fallback).  The file is
// sized by T4J_TELEMETRY_BYTES — the same knob that bounds the heap
// ring, since the slots ARE the ring.

inline std::atomic<int>& flight_on_cell() {
  static std::atomic<int> v{-1};
  return v;
}

inline std::string& flight_dir_cell() {
  static std::string& s = *new std::string;  // set pre-init only
  return s;
}

inline bool flight_on() {
  int v = flight_on_cell().load(std::memory_order_relaxed);
  if (v < 0) {
    const char* s = std::getenv("T4J_FLIGHT");
    v = 0;
    if (s && s[0] && std::strcmp(s, "0") != 0 &&
        std::strcmp(s, "off") != 0 && std::strcmp(s, "false") != 0 &&
        std::strcmp(s, "no") != 0)
      v = 1;
    flight_on_cell().store(v, std::memory_order_relaxed);
  }
  return v > 0;
}

inline std::string flight_dir() {
  if (!flight_dir_cell().empty()) return flight_dir_cell();
  const char* s = std::getenv("T4J_FLIGHT_DIR");
  if (s && s[0]) return s;
  s = std::getenv("T4J_TELEMETRY_DIR");
  if (s && s[0]) return s;
  return ".";
}

// t4j_set_flight(on, dir): on < 0 keeps, dir null/empty keeps.  Must
// run before t4j_init (single-threaded; the mapping is created once).
inline void set_flight(int on, const char* dir) {
  if (on >= 0)
    flight_on_cell().store(on ? 1 : 0, std::memory_order_relaxed);
  if (dir && dir[0]) flight_dir_cell() = dir;
}

// ---- clock anchor -------------------------------------------------------
//
// Event timestamps are monotonic (immune to NTP steps mid-run); the
// cross-rank merge needs each rank's monotonic clock pinned to a
// shared timeline.  The anchor is one (monotonic, realtime) pair
// captured at bridge bootstrap: per-rank files carry it, and the
// merger maps t_unix = t_mono - anchor_mono + anchor_unix.  Same-host
// ranks then align exactly; across hosts the residual is the hosts'
// wall-clock skew (NTP-bounded), which the merger additionally
// tightens by pinning every rank's bootstrap-barrier instant to the
// same tick (docs/observability.md "clock alignment").

struct Anchor {
  std::atomic<uint64_t> mono_ns{0};
  std::atomic<uint64_t> unix_ns{0};
};

inline Anchor& anchor_cell() {
  static Anchor a;
  return a;
}

inline void capture_anchor() {
  timespec ts{};
  clock_gettime(CLOCK_REALTIME, &ts);
  uint64_t real = static_cast<uint64_t>(ts.tv_sec) * 1000000000ull +
                  static_cast<uint64_t>(ts.tv_nsec);
  uint64_t mono = now_ns();
  anchor_cell().mono_ns.store(mono, std::memory_order_relaxed);
  anchor_cell().unix_ns.store(real, std::memory_order_relaxed);
  flight_anchor_sync(mono, real);  // the offline reader's copy
}

// Returns false (and captures now) when no bootstrap anchor was taken
// yet — a single-process job is its own timeline.
inline bool anchor(uint64_t* mono, uint64_t* unix_out) {
  bool had = anchor_cell().mono_ns.load(std::memory_order_relaxed) != 0;
  if (!had) capture_anchor();
  if (mono) *mono = anchor_cell().mono_ns.load(std::memory_order_relaxed);
  if (unix_out)
    *unix_out = anchor_cell().unix_ns.load(std::memory_order_relaxed);
  return had;
}

// ---- event ring ---------------------------------------------------------

struct Slot {
  std::atomic<uint64_t> ticket{0};  // index+1 once the payload is valid
  Event ev;
};
// The flight file stores Slots verbatim; telemetry/schema.py mirrors
// this 40-byte layout (ticket u64 + the 32-byte Event).
static_assert(sizeof(Slot) == 40, "flight slot layout");

// The slot array and write cursor sit behind pointers so flight_init
// can retarget them into the mmap'd file (done once, pre-bootstrap,
// while the process is still single-threaded — the bridge's reader/
// engine/repair threads all spawn later, and thread creation
// publishes the swapped pointers to them).
struct Ring {
  Slot* slots = nullptr;
  std::unique_ptr<Slot[]> heap;  // owns the storage when not mapped
  size_t nslots = 0;             // power of two
  size_t mask = 0;
  std::atomic<uint64_t>* widx = nullptr;
  std::atomic<uint64_t>* dropped = nullptr;
  std::atomic<uint64_t> widx_own{0};
  std::atomic<uint64_t> dropped_own{0};
  uint64_t ridx = 0;  // guarded by drain_mu
  std::mutex drain_mu;
};

// Leaked on purpose, like every global detached threads touch (see the
// g_fault_mu comment in dcn.cc): reader/repair threads emit events
// until the instant the process exits.
inline Ring& ring() {
  static Ring& r = *[] {
    Ring* rr = new Ring;
    size_t want = static_cast<size_t>(ring_bytes()) / sizeof(Event);
    size_t n = 1;
    while (n * 2 <= want) n *= 2;
    rr->heap.reset(new Slot[n]);
    rr->slots = rr->heap.get();
    rr->nslots = n;
    rr->mask = n - 1;
    rr->widx = &rr->widx_own;
    rr->dropped = &rr->dropped_own;
    return rr;
  }();
  return r;
}

inline void emit(Kind kind, Phase phase, Plane plane, int comm, int peer,
                 uint64_t bytes) {
  Ring& r = ring();
  uint64_t idx = r.widx->fetch_add(1, std::memory_order_relaxed);
  Slot& s = r.slots[idx & r.mask];
  // invalidate first so a concurrent drain of a lapped slot never
  // reads a half-written payload with a stale valid ticket; the full
  // fence keeps the payload stores below from becoming visible BEFORE
  // the invalidation on weakly-ordered CPUs (classical seqlock writer
  // — the paired reader fence is in drain/peek_last)
  s.ticket.store(0, std::memory_order_relaxed);
  std::atomic_thread_fence(std::memory_order_seq_cst);
  s.ev.t_ns = now_ns();
  s.ev.kind = static_cast<uint16_t>(kind);
  s.ev.phase = phase;
  s.ev.plane = plane;
  s.ev.comm = comm;
  s.ev.peer = peer;
  s.ev.seq = thread_lane();
  s.ev.bytes = bytes;
  s.ticket.store(idx + 1, std::memory_order_release);
}

// Data-plane record: trace mode only.
inline void trace_event(Kind kind, Phase phase, Plane plane, int comm,
                        int peer, uint64_t bytes) {
  if (mode() < kTrace) return;
  emit(kind, phase, plane, comm, peer, bytes);
}

// Control-plane record (link break/reconnect/replay/fault): rare and
// vital, recorded from counters mode up so post-mortems always carry
// them (runtime.check_health reports the tail of the ring).  `stripe`
// rides the comm field for the per-link events (schema v2; -1 =
// unstriped/unknown) so t4j-diagnose can attribute a repair window to
// ONE slow stripe instead of blaming the whole link.
inline void control_event(Kind kind, int peer, uint64_t bytes,
                          int stripe = -1) {
  if (mode() < kCounters) return;
  emit(kind, kInstant, kPlaneCtrl, stripe, peer, bytes);
}

// Step-boundary record (ops.step.annotate_step via t4j_annotate_step):
// one begin/end pair per user-declared step, the step index in
// `bytes`.  Counters mode up, like control events — rare and the
// anchor of every per-step aggregation (telemetry/diagnose.py).
inline void step_event(Phase phase, uint64_t index) {
  if (mode() < kCounters) return;
  emit(kStep, phase, kPlaneCtrl, -1, -1, index);
}

// Drain up to max_bytes/32 events in ring order (oldest first),
// consuming them; returns bytes written.  Lapped (overflowed) events
// are counted in `dropped`; an *in-flight* slot — reserved by a
// writer that has not published yet, which is the only way a ticket
// can mismatch inside the [w - nslots, w) window — stops the drain
// there, leaving the cursor on it: the writer finishes within a few
// instructions and the next drain picks it up, so no published event
// is ever lost.  Serialised: one consumer at a time.
inline size_t drain(void* out, size_t max_bytes) {
  Ring& r = ring();
  std::lock_guard<std::mutex> lk(r.drain_mu);
  uint64_t w = r.widx->load(std::memory_order_acquire);
  uint64_t start = r.ridx;
  if (w > r.nslots && start < w - r.nslots) {
    r.dropped->fetch_add((w - r.nslots) - start,
                         std::memory_order_relaxed);
    start = w - r.nslots;
  }
  Event* dst = static_cast<Event*>(out);
  size_t cap = max_bytes / sizeof(Event);
  size_t n = 0;
  uint64_t i = start;
  for (; i < w && n < cap; ++i) {
    Slot& s = r.slots[i & r.mask];
    if (s.ticket.load(std::memory_order_acquire) != i + 1)
      break;  // in-flight writer: resume here next drain
    Event copy = s.ev;
    // seqlock read validation: the fence orders the payload loads
    // above before the ticket re-check (paired with emit's fence)
    std::atomic_thread_fence(std::memory_order_acquire);
    if (s.ticket.load(std::memory_order_relaxed) != i + 1)
      break;  // a writer claimed this slot mid-copy
    dst[n++] = copy;
  }
  r.ridx = i;
  return n * sizeof(Event);
}

// Copy the NEWEST events (up to max_bytes/32, oldest-of-the-tail
// first) WITHOUT consuming: the post-mortem peek check_health uses.
inline size_t peek_last(void* out, size_t max_bytes) {
  Ring& r = ring();
  std::lock_guard<std::mutex> lk(r.drain_mu);
  uint64_t w = r.widx->load(std::memory_order_acquire);
  size_t cap = max_bytes / sizeof(Event);
  uint64_t lo = 0;
  if (w > cap) lo = w - cap;
  if (w > r.nslots && lo < w - r.nslots) lo = w - r.nslots;
  Event* dst = static_cast<Event*>(out);
  size_t n = 0;
  for (uint64_t i = lo; i < w && n < cap; ++i) {
    Slot& s = r.slots[i & r.mask];
    if (s.ticket.load(std::memory_order_acquire) != i + 1) continue;
    Event copy = s.ev;
    std::atomic_thread_fence(std::memory_order_acquire);
    if (s.ticket.load(std::memory_order_relaxed) != i + 1) continue;
    dst[n++] = copy;
  }
  return n * sizeof(Event);
}

inline uint64_t dropped() {
  return ring().dropped->load(std::memory_order_relaxed);
}

// ---- metrics table ------------------------------------------------------
//
// Fixed-shape atomic counters per (comm, op kind, plane): count, bytes,
// sum/min/max latency, a log2 latency histogram (1 us .. ~8.6 s) and a
// log2 size histogram (64 B .. >=32 MB).  Fixed shape keeps the update
// path allocation- and lock-free; Python (telemetry/registry.py)
// derives p50/p99 from the buckets.  Comm handles >= kMaxComm-1 fold
// into the last row (real programs use a handful of comms; the fold
// loses per-comm attribution, never counts).

constexpr int kMaxComm = 8;
constexpr int kMaxKind = 16;  // op kinds 0..15 (kSend..kHierAllreduce)
constexpr int kMaxPlane = 6;
constexpr int kLatBuckets = 24;     // bucket i: [2^(10+i), 2^(11+i)) ns
constexpr int kLatBaseLog2 = 10;    // 1.024 us
constexpr int kSizeBuckets = 20;    // bucket i: [2^(6+i), 2^(7+i)) bytes
constexpr int kSizeBaseLog2 = 6;    // 64 B

struct Row {
  std::atomic<uint64_t> count{0};
  std::atomic<uint64_t> bytes{0};
  std::atomic<uint64_t> sum_ns{0};
  std::atomic<uint64_t> min_ns{0};  // 0 = unset
  std::atomic<uint64_t> max_ns{0};
  std::atomic<uint64_t> lat[kLatBuckets];
  std::atomic<uint64_t> size[kSizeBuckets];
};

struct Table {
  Row rows[kMaxComm][kMaxKind][kMaxPlane];
};
// The flight file stores the Table verbatim; telemetry/schema.py
// mirrors this fixed shape (49 u64 words per row, comm-major order).
static_assert(sizeof(Row) == (5 + kLatBuckets + kSizeBuckets) * 8,
              "flight metrics row layout");
static_assert(sizeof(Table) ==
                  sizeof(Row) * kMaxComm * kMaxKind * kMaxPlane,
              "flight metrics table layout");

// Behind an atomic pointer so flight_init can retarget the table into
// the mmap'd file (same single-threaded-swap discipline as the ring).
inline std::atomic<Table*>& table_cell() {
  static std::atomic<Table*> p{nullptr};
  return p;
}

inline Table& table() {
  Table* t = table_cell().load(std::memory_order_acquire);
  if (!t) {
    static Table* heap = new Table;  // leaked: see ring()
    Table* expected = nullptr;
    table_cell().compare_exchange_strong(expected, heap,
                                         std::memory_order_acq_rel);
    t = table_cell().load(std::memory_order_acquire);
  }
  return *t;
}

inline int log2_bucket(uint64_t v, int base, int nbuckets) {
  if (v >> base == 0) return 0;
  int b = 0;
  uint64_t x = v >> base;
  while (x > 1 && b < nbuckets - 1) {
    x >>= 1;
    ++b;
  }
  return b;
}

inline void count_op(int comm, Kind kind, Plane plane, uint64_t bytes,
                     uint64_t dur_ns) {
  if (comm < 0) comm = 0;
  if (comm >= kMaxComm) comm = kMaxComm - 1;
  int k = static_cast<int>(kind);
  if (k < 0 || k >= kMaxKind) return;
  int p = static_cast<int>(plane);
  if (p < 0 || p >= kMaxPlane) p = 0;
  Row& r = table().rows[comm][k][p];
  r.count.fetch_add(1, std::memory_order_relaxed);
  r.bytes.fetch_add(bytes, std::memory_order_relaxed);
  r.sum_ns.fetch_add(dur_ns, std::memory_order_relaxed);
  uint64_t cur = r.min_ns.load(std::memory_order_relaxed);
  while ((cur == 0 || dur_ns < cur) &&
         !r.min_ns.compare_exchange_weak(cur, dur_ns,
                                         std::memory_order_relaxed)) {
  }
  cur = r.max_ns.load(std::memory_order_relaxed);
  while (dur_ns > cur &&
         !r.max_ns.compare_exchange_weak(cur, dur_ns,
                                         std::memory_order_relaxed)) {
  }
  r.lat[log2_bucket(dur_ns, kLatBaseLog2, kLatBuckets)].fetch_add(
      1, std::memory_order_relaxed);
  r.size[log2_bucket(bytes, kSizeBaseLog2, kSizeBuckets)].fetch_add(
      1, std::memory_order_relaxed);
}

// Snapshot layout (u64 words), mirrored by telemetry/schema.py
// parse_snapshot:
//   header: [version, n_rows, row_words, lat_buckets, lat_base_log2,
//            size_buckets, size_base_log2, mode]
//   row:    [comm, kind, plane, count, bytes, sum_ns, min_ns, max_ns,
//            lat..., size...]
// Only rows with count > 0 are emitted.  Returns words written; when
// out is null (or too small) returns the words REQUIRED — callers size
// a buffer with a null call first.
constexpr int kSnapHeader = 8;
constexpr int kRowWords = 8 + kLatBuckets + kSizeBuckets;

inline size_t metrics_snapshot(uint64_t* out, size_t max_words) {
  Table& t = table();
  size_t nrows = 0;
  for (int c = 0; c < kMaxComm; ++c)
    for (int k = 0; k < kMaxKind; ++k)
      for (int p = 0; p < kMaxPlane; ++p)
        if (t.rows[c][k][p].count.load(std::memory_order_relaxed))
          ++nrows;
  size_t need = kSnapHeader + nrows * kRowWords;
  if (!out || max_words < need) return need;
  uint64_t* w = out;
  uint64_t emitted = 0;
  *w++ = kSchemaVersion;
  *w++ = nrows;
  *w++ = kRowWords;
  *w++ = kLatBuckets;
  *w++ = kLatBaseLog2;
  *w++ = kSizeBuckets;
  *w++ = kSizeBaseLog2;
  *w++ = static_cast<uint64_t>(mode());
  for (int c = 0; c < kMaxComm; ++c)
    for (int k = 0; k < kMaxKind; ++k)
      for (int p = 0; p < kMaxPlane; ++p) {
        Row& r = t.rows[c][k][p];
        uint64_t cnt = r.count.load(std::memory_order_relaxed);
        if (!cnt) continue;
        // a row can flip nonzero between the sizing pass and this
        // one (concurrent OpScope): never write past the caller's
        // buffer — the skipped row shows up in the next snapshot
        if (static_cast<size_t>(w - out) + kRowWords > max_words)
          goto done;
        ++emitted;
        *w++ = static_cast<uint64_t>(c);
        *w++ = static_cast<uint64_t>(k);
        *w++ = static_cast<uint64_t>(p);
        *w++ = cnt;
        *w++ = r.bytes.load(std::memory_order_relaxed);
        *w++ = r.sum_ns.load(std::memory_order_relaxed);
        *w++ = r.min_ns.load(std::memory_order_relaxed);
        *w++ = r.max_ns.load(std::memory_order_relaxed);
        for (int i = 0; i < kLatBuckets; ++i)
          *w++ = r.lat[i].load(std::memory_order_relaxed);
        for (int i = 0; i < kSizeBuckets; ++i)
          *w++ = r.size[i].load(std::memory_order_relaxed);
      }
done:
  out[1] = emitted;  // the rows actually written, not the sizing count
  return static_cast<size_t>(w - out);
}

// ---- flight-recorder arena ----------------------------------------------
//
// Layout: [FlightHeader | Slot[nslots] | Table].  Called ONCE from
// init_from_env, BEFORE the bootstrap spawns any bridge thread, so the
// pointer swaps below are single-threaded; events already in the heap
// ring (pre-init emits, if any) migrate into the mapping.  Any failure
// warns on stderr and leaves the heap ring in place — the recorder
// must never take a job down.

inline size_t flight_file_bytes_for(size_t nslots) {
  return sizeof(FlightHeader) + nslots * sizeof(Slot) + sizeof(Table);
}

inline bool flight_init(int rank, int world, uint32_t epoch) {
  if (!flight_on()) return false;
  FlightState& s = flight_state();
  if (s.header.load(std::memory_order_relaxed)) return true;  // once
  Ring& r = ring();  // forces heap creation; fixes nslots
  std::string dir = flight_dir();
  ::mkdir(dir.c_str(), 0777);  // best-effort single level
  timespec ts{};
  clock_gettime(CLOCK_REALTIME, &ts);
  uint64_t boot = static_cast<uint64_t>(ts.tv_sec) * 1000000000ull +
                  static_cast<uint64_t>(ts.tv_nsec);
  // the boot incarnation in the name keeps a rejoined replacement (or
  // a --restarts relaunch) from truncating its dead predecessor's
  // evidence — the postmortem reads every incarnation
  std::string path = dir + "/rank" + std::to_string(rank) + "-" +
                     std::to_string(boot) + ".t4jflight";
  size_t bytes = flight_file_bytes_for(r.nslots);
  int fd = ::open(path.c_str(), O_RDWR | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) {
    std::fprintf(stderr,
                 "t4j: flight recorder disabled: cannot create %s "
                 "(errno %d)\n",
                 path.c_str(), errno);
    return false;
  }
  void* base = MAP_FAILED;
  if (::ftruncate(fd, static_cast<off_t>(bytes)) == 0)
    base = ::mmap(nullptr, bytes, PROT_READ | PROT_WRITE, MAP_SHARED,
                  fd, 0);
  ::close(fd);
  if (base == MAP_FAILED) {
    std::fprintf(stderr,
                 "t4j: flight recorder disabled: cannot mmap %zu bytes "
                 "of %s (errno %d)\n",
                 bytes, path.c_str(), errno);
    ::unlink(path.c_str());
    return false;
  }
  auto* h = new (base) FlightHeader();
  // slot-by-slot placement new: the array form may prepend a count
  // cookie, which would shift the layout the offline reader mirrors
  Slot* slots = reinterpret_cast<Slot*>(static_cast<char*>(base) +
                                        sizeof(FlightHeader));
  for (size_t i = 0; i < r.nslots; ++i) new (&slots[i]) Slot();
  auto* tbl = new (static_cast<char*>(base) + sizeof(FlightHeader) +
                   r.nslots * sizeof(Slot)) Table();
  std::memcpy(h->magic, kFlightMagic, sizeof(h->magic));
  h->version = kFlightVersion;
  h->schema = kSchemaVersion;
  h->rank = rank;
  h->world = world;
  h->world_epoch.store(epoch, std::memory_order_relaxed);
  h->mode.store(static_cast<uint32_t>(mode()), std::memory_order_relaxed);
  h->boot_unix_ns = boot;
  h->anchor_mono_ns.store(
      anchor_cell().mono_ns.load(std::memory_order_relaxed),
      std::memory_order_relaxed);
  h->anchor_unix_ns.store(
      anchor_cell().unix_ns.load(std::memory_order_relaxed),
      std::memory_order_relaxed);
  h->nslots = r.nslots;
  h->slots_off = sizeof(FlightHeader);
  h->metrics_off = sizeof(FlightHeader) + r.nslots * sizeof(Slot);
  h->metrics_bytes = sizeof(Table);
  h->heartbeat_ns.store(now_ns(), std::memory_order_relaxed);
  // migrate anything already recorded (single-threaded: no writer can
  // race these copies)
  uint64_t w = r.widx->load(std::memory_order_relaxed);
  uint64_t lo = w > r.nslots ? w - r.nslots : 0;
  for (uint64_t i = lo; i < w; ++i) {
    Slot& src = r.slots[i & r.mask];
    Slot& dst = slots[i & r.mask];
    dst.ev = src.ev;
    dst.ticket.store(src.ticket.load(std::memory_order_relaxed),
                     std::memory_order_relaxed);
  }
  h->widx.store(w, std::memory_order_relaxed);
  h->dropped.store(r.dropped->load(std::memory_order_relaxed),
                   std::memory_order_relaxed);
  Table& old = table();
  for (int c = 0; c < kMaxComm; ++c)
    for (int k = 0; k < kMaxKind; ++k)
      for (int p = 0; p < kMaxPlane; ++p) {
        Row& a = old.rows[c][k][p];
        Row& b = tbl->rows[c][k][p];
        b.count.store(a.count.load(std::memory_order_relaxed),
                      std::memory_order_relaxed);
        b.bytes.store(a.bytes.load(std::memory_order_relaxed),
                      std::memory_order_relaxed);
        b.sum_ns.store(a.sum_ns.load(std::memory_order_relaxed),
                       std::memory_order_relaxed);
        b.min_ns.store(a.min_ns.load(std::memory_order_relaxed),
                       std::memory_order_relaxed);
        b.max_ns.store(a.max_ns.load(std::memory_order_relaxed),
                       std::memory_order_relaxed);
        for (int i = 0; i < kLatBuckets; ++i)
          b.lat[i].store(a.lat[i].load(std::memory_order_relaxed),
                         std::memory_order_relaxed);
        for (int i = 0; i < kSizeBuckets; ++i)
          b.size[i].store(a.size[i].load(std::memory_order_relaxed),
                          std::memory_order_relaxed);
      }
  // retarget the live paths into the mapping (single-threaded; later
  // thread creation publishes the new pointers)
  r.slots = slots;
  r.widx = &h->widx;
  r.dropped = &h->dropped;
  table_cell().store(tbl, std::memory_order_release);
  s.base = base;
  s.map_bytes = bytes;
  s.path = path;
  s.header.store(h, std::memory_order_release);
  return true;
}

// Status query for runtime.flight_info / t4j-top: returns true when
// the recorder is active.
inline bool flight_info(std::string* path, uint64_t* file_bytes,
                        uint64_t* heartbeat_ns, uint64_t* heartbeat_count,
                        uint64_t* epoch) {
  FlightState& s = flight_state();
  FlightHeader* h = s.header.load(std::memory_order_acquire);
  if (!h) return false;
  if (path) *path = s.path;
  if (file_bytes) *file_bytes = s.map_bytes;
  if (heartbeat_ns)
    *heartbeat_ns = h->heartbeat_ns.load(std::memory_order_relaxed);
  if (heartbeat_count)
    *heartbeat_count = h->heartbeat_count.load(std::memory_order_relaxed);
  if (epoch) *epoch = h->world_epoch.load(std::memory_order_relaxed);
  return true;
}

// ---- op scope -----------------------------------------------------------
//
// RAII bracket for the public op entry points (dcn.cc): one metrics
// update per op (counters mode up) and a begin/end event pair (trace
// mode).  The op body sets `plane` once path selection has happened —
// the destructor records the plane that actually served the call.
//
// Composed ops nest (tree allreduce = reduce + bcast through the
// public entry points; hier phases call reduce on the leader comm):
// the nested scopes still emit trace begin/end pairs — nested
// timeline slices are exactly what Perfetto should show — but only
// the OUTERMOST scope updates the metrics table, so per-op counts
// and per-plane byte totals count each user-visible call once (the
// same count-once convention the analyzer's publishes_token
// reentrancy guard enforces on the Python side).

inline int& op_depth() {
  static thread_local int depth = 0;
  return depth;
}

struct OpScope {
  Kind kind;
  int comm;
  int peer;
  uint64_t bytes;
  Plane plane = kPlaneNone;
  uint64_t t0 = 0;
  bool counting = false;
  bool outermost = false;

  OpScope(Kind kind_, int comm_, uint64_t bytes_, int peer_ = -1)
      : kind(kind_), comm(comm_), peer(peer_), bytes(bytes_) {
    if (mode() < kCounters) return;
    counting = true;
    outermost = op_depth()++ == 0;
    t0 = now_ns();
    if (mode() >= kTrace)
      emit(kind, kBegin, plane, comm, peer, bytes);
  }
  ~OpScope() {
    if (!counting) return;
    --op_depth();
    if (outermost) count_op(comm, kind, plane, bytes, now_ns() - t0);
    if (mode() >= kTrace) emit(kind, kEnd, plane, comm, peer, bytes);
  }
};

}  // namespace tel
}  // namespace t4j
